"""Benchmark entry point — prints ONE JSON line for the driver.

Current flagship metric: GF(2⁸) Reed–Solomon parity encode throughput on
device (the broadcast hot op, BASELINE.json config 4 "RS-as-matmul") vs the
numpy host codec baseline.  As the TPU crypto stack lands this will switch
to the north-star metric (HBBFT epochs/sec at N=100,f=33).
"""

import json
import time

import numpy as np


def bench_rs_encode() -> dict:
    import jax
    import jax.numpy as jnp

    from hbbft_tpu.crypto.erasure import RSCodec
    from hbbft_tpu.ops.gf256 import JaxRSCodec

    k, m = 34, 66  # N=100, f=33 broadcast shape: k = N-2f data, 2f parity
    L = 1 << 16  # bytes per shard
    rng = np.random.default_rng(0)
    mat = rng.integers(0, 256, size=(k, L), dtype=np.uint8)

    dev = JaxRSCodec(k, m)
    fn = jax.jit(dev.encode_matrix_fn())
    x = jnp.asarray(mat)
    fn(x).block_until_ready()  # compile
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    out.block_until_ready()
    dev_s = (time.perf_counter() - t0) / iters

    host = RSCodec(k, m)
    from hbbft_tpu.crypto.erasure import gf256

    gf = gf256()
    t0 = time.perf_counter()
    gf.matmul(host.encode_matrix, mat)
    host_s = time.perf_counter() - t0

    mb = k * L / 1e6
    return {
        "metric": "rs_encode_throughput",
        "value": round(mb / dev_s, 2),
        "unit": "MB/s",
        "vs_baseline": round(host_s / dev_s, 2),
    }


if __name__ == "__main__":
    print(json.dumps(bench_rs_encode()))
