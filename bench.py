"""Benchmark entry point — prints ONE JSON line for the driver.

Flagship metric: **threshold-share verifications/sec** on device — each
item is a full BLS12-381 pairing-equation check e(a1,b1)==e(a2,b2) done as
two Miller loops + one shared (fast) final exponentiation, batched over the
work-item axis (BASELINE.json: "threshold-decrypt shares verified/sec/chip"
is the operative micro-metric; the O(N²) such checks per epoch are the
whole HBBFT performance story, SURVEY.md §3.2).

``vs_baseline`` compares against 1_000 checks/sec — the order-of-magnitude
single-core CPU pairing throughput BASELINE.md's cost model assigns the
Rust reference (its `threshold_crypto` crate; the repo itself publishes no
numbers).

The benched graph is `hbbft_tpu.ops.pairing.product2_fast` — the SAME
kernel the TpuBackend dispatches, so the number is the framework's real
verification path, not a proxy.

Set BENCH_BATCH / BENCH_ITERS to override batch size and timing loops.
"""

import json
import os
import time

CPU_BASELINE_CHECKS_PER_SEC = 1_000.0


def bench_share_verify() -> dict:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    from hbbft_tpu.utils.jax_config import enable_compile_cache

    enable_compile_cache()
    import jax
    import numpy as np

    from hbbft_tpu.ops import pairing

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))

    import jax.numpy as jnp

    args = pairing.example_verify_batch(batch)
    fn = jax.jit(pairing.product2_fast)
    jax.block_until_ready(fn(*args))  # compile

    def fresh(a):
        # New device buffers each call: the remote (axon) execution layer
        # memoizes repeat dispatches on identical buffers, which would turn
        # the timing loop into a no-op and report absurd throughput.
        return jax.tree_util.tree_map(
            lambda c: jnp.asarray(np.asarray(c).copy()), a
        )

    copies = [fresh(args) for _ in range(iters)]
    t0 = time.perf_counter()
    for c in copies:
        out = fn(*c)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters

    # Spot-check correctness of the benched computation.
    f_host = jax.tree_util.tree_map(np.asarray, out)
    assert pairing.is_one_host(f_host, 0), "benched verification is wrong"

    checks_per_sec = batch / dt
    return {
        "metric": "share_verify_throughput",
        "value": round(checks_per_sec, 2),
        "unit": "checks/s",
        "vs_baseline": round(checks_per_sec / CPU_BASELINE_CHECKS_PER_SEC, 3),
    }


if __name__ == "__main__":
    print(json.dumps(bench_share_verify()))
