"""Benchmark entry point — prints one JSON line PER METRIC for the driver.

Rows print in SUPPORT-FIRST order (flagship crypto rows and macro rows
last, north-star ``array_epochs_per_sec_n100`` as the final line) because
the driver records a stdout tail; the FULL row set is also written to
``BENCH_rows.json`` after every row so truncation can't lose evidence.
Under a TIME BUDGET (``BENCH_BUDGET`` seconds; defaulted to 3000 for
driver-style full runs on real TPU) the order flips to FLAGSHIP-FIRST
and benches that no longer fit are skipped with labeled rows — round 4's
driver run was timeout-killed before the support-first ordering reached
a single flagship row (verdict Weak #3).

Flagship micro-metric: ``rlc_dec_verify_throughput`` —
**threshold-decrypt shares verified/sec/chip**, BASELINE.json's operative
micro-metric, measured through the REAL backend kernel (grouped
random-linear-combination verification at the config-1 shape: 64
ciphertext groups × 16 shares).  The O(N²) such checks per epoch are the
whole HBBFT performance story (SURVEY.md §3.2).

Further metrics (one JSON line each; the LAST line printed is the
north-star array_epochs_per_sec_n100 row):

* ``share_verify_throughput``    — full BLS12-381 pairing-equation checks
  e(a1,b1)==e(a2,b2) (two Miller loops + one shared fast final
  exponentiation per item): the general path used where shares check
  against distinct documents (and rounds 1-6's flagship line, kept for
  continuity).
* ``rlc_sig_verify_throughput``  — grouped sig-share verification at the
  common-coin shape (config 2: N=64-ish coin instances × shares each).
* ``g2_sign_throughput``         — batched 254-bit G2 ladders (the sign op
  behind "10k coin flips vmapped", config 2).
* ``coin_flips_per_sec``         — config 2 END TO END: batched sign →
  grouped-RLC verify → batched Lagrange combine → parity, per flip.
* ``rlc_dec_verify_adversarial`` — the flagship shape with 1-5% forged
  shares through the bisecting fallback (adversarial throughput).
* ``glv_ladder_ab``              — GLV joint-table vs w2 ladder A/B on the
  backend g1_mul_batch path: per-ladder field-mul counts (2368 vs 3810
  target) + wall ladders/s both arms (BENCH_ONLY=glv_ladder).
* ``fq_mul_throughput``          — raw field-multiply kernel, RNS vs limb
  (subprocess A/B; BENCH_FQ=0 skips).
* ``rs_encode_throughput``       — GF(2⁸) Reed–Solomon parity as an MXU
  bit-matmul at the N=100 broadcast shape (HBBFT_TPU_GF_DOT=bf16 A/B).
* ``hbbft_epochs_per_sec_n4``    — BASELINE config 0 (N=4 f=1, object
  runtime, mock backend: protocol-layer row) plus
  ``hbbft_epochs_per_sec_n4_realcrypto`` (CpuBackend by default — the
  honest single-core real-crypto anchor, in the artifact every run).
* ``hbbft_epochs_per_sec_n100``  — the north-star shape through the
  per-message OBJECT runtime (labeled correctness-harness; the
  throughput row is the array engine's).
* ``array_epochs_per_sec_*``     — lockstep array-engine macro rows:
  n16 real-crypto, n64 with real coin rounds, n256 soak (10 epochs),
  n100 dedup, and the 100-epoch n100 row with one timed mid-run era
  change (churn_epochs/era_change_seconds fields).

``vs_baseline`` on the flagship compares against 1_000 checks/sec — the
order-of-magnitude single-core CPU pairing throughput BASELINE.md's cost
model assigns the Rust reference (its `threshold_crypto` crate; the repo
publishes no numbers, so the baseline is an ESTIMATE — flagged in the
JSON).

The benched graphs are the SAME kernels TpuBackend dispatches, so the
numbers are the framework's real paths, not proxies.

Set BENCH_BATCH / BENCH_ITERS to override batch size and timing loops.
"""

import json
import os
import sys
import time

CPU_BASELINE_CHECKS_PER_SEC = 1_000.0
ARRAY_N16_METRIC = "array_epochs_per_sec_n16_realcrypto"
#: bench names that execute the Fq facade (device field arithmetic)
_FQ_ROWS = frozenset(
    {
        "rlc_dec",
        "share_verify",
        "rlc_sig",
        "g2_sign",
        "coin_e2e",
        "rlc_dec_adversarial",
        "array_n16_tpu",
        "glv_ladder",
    }
)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def _fresh(args):
    """New device buffers each call: the remote (axon) execution layer may
    memoize repeat dispatches on identical buffers (defensively assumed —
    naive round-2 timing without fresh buffers + fences reported
    physically impossible throughput), which would turn the timing loop
    into a no-op."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    return jax.tree_util.tree_map(lambda c: jnp.asarray(np.asarray(c).copy()), args)


def _touch(copies) -> None:
    """Force device materialization of freshly staged argument buffers.

    On the tunneled accelerator the first dispatch touching a new buffer
    pays ~45-80 ms of relay staging (measured; PERF.md "axon timing"),
    which would otherwise be billed to the kernel — understating cheap
    kernels up to ~10×.  Materialize with a trivial jitted reduce per
    copy rather than pre-running the benched fn, so the timed loop's
    (fn, buffers) dispatches stay first-time pairs (repeat dispatches on
    identical buffers may be memoized by the remote layer)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    probe = jax.jit(
        lambda *ls: jnp.stack([l.ravel()[0].astype(jnp.float32) for l in ls])
    )
    for c in copies:
        np.asarray(probe(*jax.tree_util.tree_leaves(c)))


def _time_fn(fn, args, iters: int) -> float:
    """Steady-state timing: compile once, stage `iters` fresh copies
    (device-materialized untimed, see _touch), then time one full pass
    with a host-fetch fence (block_until_ready alone can return early on
    the tunneled accelerator)."""
    import numpy as np

    import jax

    jax.block_until_ready(fn(*args))  # compile
    copies = [_fresh(args) for _ in range(iters)]
    _touch(copies)
    t0 = time.perf_counter()
    out = None
    for c in copies:
        out = fn(*c)
    # slice on DEVICE, then fetch one element: fencing with a whole-leaf
    # transfer would bill a ~MB device→host copy to the kernel
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters


def bench_share_verify() -> dict:
    from hbbft_tpu.ops import pairing
    import jax

    batch = _env_int("BENCH_BATCH", 256)
    iters = _env_int("BENCH_ITERS", 3)
    args = pairing.example_verify_batch(batch)
    fn = jax.jit(pairing.product2_fast)
    dt = _time_fn(fn, args, iters)

    # Spot-check correctness of the benched computation.
    import numpy as np

    out = jax.tree_util.tree_map(np.asarray, fn(*args))
    assert pairing.is_one_host(out, 0), "benched verification is wrong"

    checks_per_sec = batch / dt
    return {
        "metric": "share_verify_throughput",
        "value": round(checks_per_sec, 2),
        "unit": "checks/s",
        "vs_baseline": round(checks_per_sec / CPU_BASELINE_CHECKS_PER_SEC, 3),
        "baseline": "estimated",
        "batch": batch,
    }


def _synthetic_share_groups(g: int, k: int, seed: int = 7):
    """Valid (σ_i, PK_i) groups without host goldens: σ_i = s_i·H, PK_i =
    s_i·G1 for random s_i, so e(G1, Σrσ_i) == e(ΣrPK_i, H) holds exactly.
    Built with the device ladders themselves (fast)."""
    import random

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hbbft_tpu.crypto import bls381 as gold
    from hbbft_tpu.crypto.field import R
    from hbbft_tpu.ops import curve, pairing

    rng = random.Random(seed)
    n = g * k
    scalars = [rng.randrange(1, R) for _ in range(n)]
    safe = [curve.safe_scalar(s) for s in scalars]
    bits = jnp.asarray(curve.scalars_to_bits([s for s, _ in safe]))
    negs = jnp.asarray(np.array([neg for _, neg in safe]))

    G1 = curve.g1_to_device([gold.G1_GEN] * n)
    H2 = curve.g2_to_device([gold.G2_GEN] * n)

    @jax.jit
    def build(G1, H2, bits, negs):
        pk = curve.g1_scalar_mul_batch(G1, bits)
        pk = curve.jac_select(curve._F1, negs, curve.jac_neg(curve._F1, pk), pk)
        sig = curve.g2_scalar_mul_batch(H2, bits)
        sig = curve.jac_select(curve._F2, negs, curve.jac_neg(curve._F2, sig), sig)
        return pk, sig

    pk, sig = build(G1, H2, bits, negs)

    def group(dev):
        return jax.tree_util.tree_map(
            lambda c: c.reshape((g, k) + c.shape[1:]), dev
        )

    neg_g1 = pairing.g1_affine_to_device([gold.ec_neg(gold.FQ, gold.G1_GEN)] * g)
    H_aff = pairing.g2_affine_to_device([gold.G2_GEN] * g)
    return group(sig), group(pk), neg_g1, H_aff


def bench_rlc_sig() -> dict:
    """Grouped coin-share verification: the common-coin hot loop shape."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from hbbft_tpu.ops import curve, pairing
    from hbbft_tpu.ops.backend import TpuBackend, _jitted_rlc_sig

    g = _env_int("BENCH_RLC_GROUPS", 64)
    k = _env_int("BENCH_RLC_K", 32)
    iters = _env_int("BENCH_ITERS", 3)
    S, PK, negG1, H = _synthetic_share_groups(g, k)
    rs = [
        [1 + i * 7919 + j for j in range(k)] for i in range(g)
    ]  # fixed nonzero coefficients (timing, not security)
    rbits = jnp.asarray(
        np.stack([curve.scalars_to_bits(row, TpuBackend._rlc_bits()) for row in rs])
    )
    fn = _jitted_rlc_sig()
    dt = _time_fn(fn, (S, PK, rbits, negG1, H), iters)

    out = jax.tree_util.tree_map(np.asarray, fn(S, PK, rbits, negG1, H))
    assert pairing.is_one_host(out, 0), "rlc sig group check is wrong"

    items = g * k
    return {
        "metric": "rlc_sig_verify_throughput",
        "value": round(items / dt, 2),
        "unit": "shares/s",
        "vs_baseline": round(items / dt / CPU_BASELINE_CHECKS_PER_SEC, 3),
        "baseline": "estimated",
        "batch": items,
        "groups": g,
    }


def bench_rlc_dec() -> dict:
    """Grouped dec-share verification at the 1k-ciphertext batch shape."""
    import random

    import numpy as np
    import jax
    import jax.numpy as jnp

    from hbbft_tpu.crypto import bls381 as gold
    from hbbft_tpu.crypto.field import R
    from hbbft_tpu.ops import curve, pairing
    from hbbft_tpu.ops.backend import TpuBackend, _jitted_rlc_dec

    g = _env_int("BENCH_DEC_GROUPS", 64)  # ciphertext groups
    k = _env_int("BENCH_DEC_K", 16)  # shares each (config 1: N=16)
    iters = _env_int("BENCH_ITERS", 3)

    # Valid shape: D_i = s_i·G1, PK_i = s_i·G1, H = W → e(D,H)==e(PK,W).
    rng = random.Random(11)
    n = g * k
    scalars = [rng.randrange(1, R) for _ in range(n)]
    safe = [curve.safe_scalar(s) for s in scalars]
    bits = jnp.asarray(curve.scalars_to_bits([s for s, _ in safe]))
    negs = jnp.asarray(np.array([neg for _, neg in safe]))
    G1 = curve.g1_to_device([gold.G1_GEN] * n)

    @jax.jit
    def build(G1, bits, negs):
        d = curve.g1_scalar_mul_batch(G1, bits)
        d = curve.jac_select(curve._F1, negs, curve.jac_neg(curve._F1, d), d)
        return d

    D = build(G1, bits, negs)
    group = lambda dev: jax.tree_util.tree_map(  # noqa: E731
        lambda c: c.reshape((g, k) + c.shape[1:]), dev
    )
    D = group(D)
    H = pairing.g2_affine_to_device([gold.G2_GEN] * g)
    rs = [[1 + i * 104729 + j for j in range(k)] for i in range(g)]
    rbits = jnp.asarray(
        np.stack([curve.scalars_to_bits(row, TpuBackend._rlc_bits()) for row in rs])
    )
    fn = _jitted_rlc_dec()
    dt = _time_fn(fn, (D, D, rbits, H, H), iters)

    out = jax.tree_util.tree_map(np.asarray, fn(D, D, rbits, H, H))
    assert pairing.is_one_host(out, 0), "rlc dec group check is wrong"

    items = g * k
    return {
        "metric": "rlc_dec_verify_throughput",
        "value": round(items / dt, 2),
        "unit": "shares/s",
        "vs_baseline": round(items / dt / CPU_BASELINE_CHECKS_PER_SEC, 3),
        "baseline": "estimated",
        "batch": items,
        "groups": g,
    }


def bench_coin_e2e() -> dict:
    """BASELINE config 2 end to end: the common coin as one pipeline —
    batched G2 sign → grouped-RLC share verify → batched G2 Lagrange
    combine → hash → coin bit — at N=64 f=21 (threshold_sign.py flow,
    SURVEY.md §3.2 HOTTEST loop).  Work is the dedup'd network-wide flow
    per flip: N signs, N share verifies (one RLC group), one combine of
    f+1 shares, one parity.  (The per-receiver duplication rides the
    array-engine coin macro row instead.)  Flip 0's bit is asserted
    against the host golden combine.  BENCH_COIN_FLIPS scales (config 2
    names 10k)."""
    import random

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hbbft_tpu.crypto import bls381 as gold
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto.keys import SecretKeySet, Signature, SignatureShare
    from hbbft_tpu.ops import curve, pairing
    from hbbft_tpu.ops.backend import (
        TpuBackend,
        _jitted_combine_g2_batch,
        _jitted_rlc_sig,
        _squeeze_point,
    )
    from hbbft_tpu.crypto.field import lagrange_coeffs_at_zero

    n = _env_int("BENCH_COIN_N", 64)
    f = (n - 1) // 3  # 21
    flips = _env_int("BENCH_COIN_FLIPS", 128)
    iters = _env_int("BENCH_COIN_ITERS", 1)
    # Fixed per-dispatch chunk: the sign graph holds chunk*n G2 ladder
    # lanes, so a 10k-flip run (config 2 at size) must NOT compile a
    # 640k-lane graph — the relay's compile helper 500s on it (observed
    # 2026-08-01).  500 flips * 64 = 32k lanes = the device lane cap;
    # larger totals loop the same compiled chunk.
    chunk = min(flips, _env_int("BENCH_COIN_CHUNK", 500))
    n_chunks = -(-flips // chunk)  # ceil: never under-run the request
    flips_total = chunk * n_chunks  # rounded UP to whole chunks; reported

    g = CpuBackend().group
    rng = random.Random(21)
    sk_set = SecretKeySet.random(g, f, rng)
    pk_set = sk_set.public_keys()
    shares_sk = [sk_set.secret_key_share(i) for i in range(n)]
    pk_els = [pk_set.public_key_share(i).el for i in range(n)]
    safe = [curve.safe_scalar(sk.x) for sk in shares_sk]
    sk_bits_1 = curve.scalars_to_bits([s for s, _ in safe])
    sk_negs_1 = np.array([neg for _, neg in safe])

    k = f + 1  # combine width
    lam = lagrange_coeffs_at_zero(list(range(1, k + 1)))
    lsafe = [curve.safe_scalar(l) for l in lam]
    lam_bits = curve.scalars_to_bits([s for s, _ in lsafe])
    lam_negs = np.array([negq for _, negq in lsafe])

    sign_fn = jax.jit(curve.g2_scalar_mul_signed)
    rlc_fn = _jitted_rlc_sig()
    comb_fn = _jitted_combine_g2_batch()
    neg_g1 = pairing.g1_affine_to_device(
        [gold.ec_neg(gold.FQ, gold.G1_GEN)] * chunk
    )
    PK_jac = curve.g1_to_device(pk_els * chunk)
    PK_jac = jax.tree_util.tree_map(
        lambda c: c.reshape((chunk, n) + c.shape[1:]), PK_jac
    )

    def flip_chunk(epoch_tag: int):
        # one distinct doc per flip (the real coin's per-instance nonce);
        # host hash-to-G2 is part of the honest pipeline cost.
        docs = [
            b"coin:%d:%d" % (epoch_tag, i) for i in range(chunk)
        ]
        H = [g.hash_to_g2(d) for d in docs]
        H_rep = [h for h in H for _ in range(n)]  # sign points, flip-major
        bits = np.tile(sk_bits_1, (chunk, 1))
        negs = np.tile(sk_negs_1, chunk)
        S = sign_fn(
            curve.g2_to_device(H_rep), jnp.asarray(bits), jnp.asarray(negs)
        )  # (chunk*n,) signature shares, Jacobian
        S_g = jax.tree_util.tree_map(
            lambda c: c.reshape((chunk, n) + c.shape[1:]), S
        )
        # grouped-RLC verify: one group per flip
        rs = [TpuBackend._rlc_scalars(n) for _ in range(chunk)]
        rbits = jnp.asarray(
            np.stack(
                [curve.scalars_to_bits(r, TpuBackend._rlc_bits()) for r in rs]
            )
        )
        H_aff = pairing.g2_affine_to_device(H)
        fvals = rlc_fn(S_g, PK_jac, rbits, neg_g1, H_aff)
        fvals = jax.tree_util.tree_map(np.asarray, fvals)
        # combine f+1 shares per flip (lowest indices), then parity
        S_k = jax.tree_util.tree_map(lambda c: c[:, :k], S_g)
        cb = jnp.asarray(np.tile(lam_bits, (chunk, 1, 1)))
        cn = jnp.asarray(np.tile(lam_negs, (chunk, 1)))
        combined = comb_fn(S_k, cb, cn)
        els = curve.g2_from_device(_squeeze_point(combined))
        bits_out = []
        for i in range(chunk):
            assert pairing.is_one_host(fvals, i), "coin share group failed"
            bits_out.append(Signature(g, els[i]).parity())
        return docs, bits_out

    docs, bits_out = flip_chunk(0)  # warm + correctness
    # golden: host combine of flip 0 must yield the same coin bit
    gold_shares = {
        i: SignatureShare(g, g.g2_mul(shares_sk[i].x, g.hash_to_g2(docs[0])))
        for i in range(k)
    }
    assert (
        pk_set.combine_signatures(gold_shares).parity() == bits_out[0]
    ), "coin bit mismatch vs host golden"

    t0 = time.perf_counter()
    for it in range(iters):
        for c in range(n_chunks):
            flip_chunk(1 + it * n_chunks + c)
    dt = (time.perf_counter() - t0) / iters

    # single-core estimate: N G2 signs (~1.5ms) + N pairing verifies
    # (~1ms) + combine ≈ 0.16 s/flip ≈ 6 flips/s.
    fps = flips_total / dt
    return {
        "metric": "coin_flips_per_sec",
        "value": round(fps, 2),
        "unit": "flips/s",
        "vs_baseline": round(fps / 6.0, 3),
        "baseline": "estimated",
        "flips": flips_total,
        "chunk": chunk,
        "n": n,
        "signs_per_flip": n,
        "verifies_per_flip": n,
        "combine_width": k,
    }


def bench_rlc_dec_adversarial() -> dict:
    """Grouped dec-share verification with 1-5% forged shares through the
    REAL backend path (verify_dec_shares): group mismatch → bisection →
    exact leaf pairings (ops/backend.py _grouped_rlc).  Measures the
    adversarial-DoS resistance the per-item fallback lacked."""
    import random

    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto.keys import SecretKeySet
    from hbbft_tpu.ops.backend import TpuBackend

    gct = _env_int("BENCH_ADV_GROUPS", 32)  # ciphertext groups
    k = _env_int("BENCH_ADV_K", 16)  # shares each
    frac = float(os.environ.get("BENCH_ADV_FRAC", "0.03"))

    backend = TpuBackend()
    g = backend.group
    rng = random.Random(5)
    sk_set = SecretKeySet.random(g, 5, rng)
    pk_set = sk_set.public_keys()
    sks = [sk_set.secret_key_share(i) for i in range(k)]
    cts = [pk_set.encrypt(b"adv-%d" % i, rng) for i in range(gct)]
    gen = backend.decrypt_shares_batch(
        [(sks[s], cts[ci]) for ci in range(gct) for s in range(k)]
    )
    items = []
    want = []
    n_items = gct * k
    n_bad = max(1, int(frac * n_items))
    bad_at = set(rng.sample(range(n_items), n_bad))
    pos = 0
    for ci in range(gct):
        for s in range(k):
            share = gen[pos]
            good = pos not in bad_at
            if not good:  # forged: another sender's share for the same ct
                share = gen[ci * k + (s + 1) % k]
            items.append((pk_set.public_key_share(s), cts[ci], share))
            want.append(good)
            pos += 1

    # warm (compiles the bisection shapes) + correctness
    got = backend.verify_dec_shares(items)
    assert got == want, "adversarial attribution wrong"
    iters = _env_int("BENCH_ADV_ITERS", 2)
    t0 = time.perf_counter()
    for _ in range(iters):
        backend.verify_dec_shares(items)
    dt = (time.perf_counter() - t0) / iters
    tput = n_items / dt
    return {
        "metric": "rlc_dec_verify_adversarial",
        "value": round(tput, 2),
        "unit": "shares/s",
        "vs_baseline": round(tput / CPU_BASELINE_CHECKS_PER_SEC, 3),
        "baseline": "estimated",
        "batch": n_items,
        "groups": gct,
        "contaminated": n_bad,
        "contamination_frac": round(n_bad / n_items, 4),
    }


def _adv_contaminated_items(backend, gct: int, k: int, frac: float, seed: int = 5):
    """(items, want) for a dec-share verify batch with ``frac`` of the
    shares swapped for another sender's share of the same ciphertext
    (well-typed, fails the pairing) — the bench_rlc_dec_adversarial
    construction, parameterized over the contamination rate."""
    import random

    from hbbft_tpu.crypto.keys import SecretKeySet

    g = backend.group
    rng = random.Random(seed)
    sk_set = SecretKeySet.random(g, 5, rng)
    pk_set = sk_set.public_keys()
    sks = [sk_set.secret_key_share(i) for i in range(k)]
    cts = [pk_set.encrypt(b"advm-%d" % i, rng) for i in range(gct)]
    gen = backend.decrypt_shares_batch(
        [(sks[s], cts[ci]) for ci in range(gct) for s in range(k)]
    )
    items, want = [], []
    n_items = gct * k
    n_bad = max(1, int(frac * n_items)) if frac > 0 else 0
    bad_at = set(rng.sample(range(n_items), n_bad)) if n_bad else set()
    pos = 0
    for ci in range(gct):
        for s in range(k):
            share = gen[pos]
            good = pos not in bad_at
            if not good:
                share = gen[ci * k + (s + 1) % k]
            items.append((pk_set.public_key_share(s), cts[ci], share))
            want.append(good)
            pos += 1
    return items, want


def bench_adv_matrix() -> dict:
    """Contamination sweep {0, 1.6, 5, 15}% through the REAL grouped-RLC
    verify path, adaptive group sizing vs the HBBFT_TPU_NO_ADAPTIVE_RLC=1
    fixed arm.  The r01 adversarial row measured 2× degradation at 1.6%
    contamination with fixed whole-document groups; this row turns that
    cliff into a measured curve and records whether the
    contamination-adaptive sizing (ops/backend.py _rlc_adaptive_cap,
    blst's playbook) beats fixed sizing where it should (≥5%).

    ``epochs_per_s_est`` is the derived proxy: measured verify
    throughput / N² distinct dec-share verifies per epoch at the
    north-star N=100 dedup shape (10 000 per epoch)."""
    import os as _os

    from hbbft_tpu.ops.backend import TpuBackend

    gct = _env_int("BENCH_ADVM_GROUPS", 8)
    k = _env_int("BENCH_ADVM_K", 32)
    iters = _env_int("BENCH_ADVM_ITERS", 2)
    fracs = [
        float(x)
        for x in os.environ.get("BENCH_ADVM_FRACS", "0,0.016,0.05,0.15").split(",")
    ]
    shares_per_epoch_n100 = 100 * 100

    curve_rows = []
    kill = "HBBFT_TPU_NO_ADAPTIVE_RLC"
    saved = _os.environ.get(kill)
    try:
        for frac in fracs:
            per_arm = {}
            for arm in ("adaptive", "fixed"):
                _os.environ[kill] = "0" if arm == "adaptive" else "1"
                backend = TpuBackend()
                items, want = _adv_contaminated_items(backend, gct, k, frac)
                got = backend.verify_dec_shares(items)  # warm + train + check
                assert got == want, f"adv_matrix attribution wrong ({arm}, {frac})"
                lf0 = backend.counters.ladder_field_muls
                d0 = backend.counters.device_dispatches
                s0 = backend.counters.rlc_adaptive_splits
                t0 = time.perf_counter()
                for _ in range(iters):
                    got = backend.verify_dec_shares(items)
                dt = (time.perf_counter() - t0) / iters
                assert got == want, f"adv_matrix attribution wrong ({arm}, {frac})"
                tput = len(items) / dt
                per_arm[arm] = {
                    "shares_per_sec": round(tput, 2),
                    "epochs_per_s_est": round(tput / shares_per_epoch_n100, 4),
                    "ladder_field_muls": (
                        backend.counters.ladder_field_muls - lf0
                    ) // iters,
                    "device_dispatches": (
                        backend.counters.device_dispatches - d0
                    ) // iters,
                    # per-iteration like the two deltas above (the warm/
                    # training pass is excluded from all three)
                    "adaptive_splits": (
                        backend.counters.rlc_adaptive_splits - s0
                    ) // iters,
                }
            ratio = (
                per_arm["adaptive"]["shares_per_sec"]
                / per_arm["fixed"]["shares_per_sec"]
                if per_arm["fixed"]["shares_per_sec"]
                else None
            )
            curve_rows.append(
                {
                    "contamination_frac": frac,
                    "adaptive": per_arm["adaptive"],
                    "fixed": per_arm["fixed"],
                    "adaptive_over_fixed": round(ratio, 3) if ratio else None,
                }
            )
    finally:
        if saved is None:
            _os.environ.pop(kill, None)
        else:
            _os.environ[kill] = saved

    at5 = next(
        (r["adaptive_over_fixed"] for r in curve_rows
         if abs(r["contamination_frac"] - 0.05) < 1e-9),
        None,
    )
    return {
        "metric": "adv_matrix",
        # headline: adaptive-over-fixed wall ratio at the 5% point
        "value": at5 if at5 is not None else 0.0,
        "unit": "x (adaptive/fixed @5%)",
        "vs_baseline": at5 if at5 is not None else 0.0,
        "baseline": "fixed sizing",
        "batch": gct * k,
        "groups": gct,
        "curve": curve_rows,
        "adaptive_beats_fixed_at_5pct": bool(at5 and at5 > 1.0),
    }


def bench_scenario_matrix() -> dict:
    """The adversary × network-schedule liveness matrix (net/scenarios.py)
    at the fast shape (N∈{4,7}, all attacks × 2 schedules, MockBackend):
    every cell must commit identical Batches on all honest nodes with the
    expected fault kinds attributed.  The row's ``fault_kinds`` aggregate
    feeds tools/trace_report.py --faults (fault-kind count drift between
    captures)."""
    from hbbft_tpu.net.scenarios import run_matrix

    ns = [int(x) for x in os.environ.get("BENCH_SCEN_NS", "4,7").split(",")]
    schedules = os.environ.get("BENCH_SCEN_SCHEDULES", "uniform,partition_heal")
    t0 = time.perf_counter()
    results = run_matrix(ns=ns, schedules=tuple(schedules.split(",")), epochs=1)
    dt = time.perf_counter() - t0
    n_ok = sum(1 for r in results if r.ok)
    fault_kinds: dict = {}
    for r in results:
        for kind, cnt in r.fault_kinds.items():
            fault_kinds[kind] = fault_kinds.get(kind, 0) + cnt
    failed = [
        {"attack": r.attack, "schedule": r.schedule, "n": r.n, "error": r.error}
        for r in results
        if not r.ok
    ]
    return {
        "metric": "scenario_matrix",
        "value": round(len(results) / dt, 2),
        "unit": "cells/s",
        "vs_baseline": 1.0,
        "baseline": "estimated",
        "cells": len(results),
        "cells_ok": n_ok,
        "all_ok": n_ok == len(results),
        "fault_kinds": dict(sorted(fault_kinds.items())),
        "failed_cells": failed,
    }


def bench_crash_matrix() -> dict:
    """The composed gauntlet (net/scenarios.py Cell runner): attack ×
    net-schedule × churn × crash+restart × traffic soaks with the full
    verdict set — honest Batches bit-identical, every fault attributed,
    restarted nodes recommitted within the gate, stable seeded-replay
    fingerprints.  The ``fault_kinds`` aggregate (including the crash:*
    recovery kinds when a recovery fails) feeds tools/trace_report.py
    --faults.  Knobs: BENCH_CRASH_N / BENCH_CRASH_EPOCHS / BENCH_CRASH_CELLS
    (cell specs, comma-separated) / BENCH_CRASH_BACKEND (mock|cpu|tpu:
    real crypto routes the restored node's replay re-verifies through
    the device)."""
    from examples.simulation import make_backend
    from hbbft_tpu.net.scenarios import Cell, run_cell

    backend_name = os.environ.get("BENCH_CRASH_BACKEND", "mock")
    n = int(os.environ.get("BENCH_CRASH_N", "5"))
    epochs = int(os.environ.get("BENCH_CRASH_EPOCHS", "12"))
    specs = os.environ.get(
        "BENCH_CRASH_CELLS",
        "equivocate:partition_heal:era_flip:one_restart:one_x,"
        "crafted_shares:wan:era_flip:two_restarts:two_x,"
        "replay_flood:lan:none:one_restart:half_x",
    ).split(",")
    t0 = time.perf_counter()
    results = []
    for spec in specs:
        parts = (spec.split(":") + ["none"] * 5)[:5]
        cell = Cell(
            attack=parts[0], schedule=parts[1], churn=parts[2],
            crash=parts[3], traffic=parts[4], n=n, epochs=epochs, seed=1,
        )
        results.append(run_cell(cell, backend=make_backend(backend_name)))
    dt = time.perf_counter() - t0
    n_ok = sum(1 for r in results if r.ok)
    fault_kinds: dict = {}
    recovery_cranks = []
    for r in results:
        for kind, cnt in r.fault_kinds.items():
            fault_kinds[kind] = fault_kinds.get(kind, 0) + cnt
        recovery_cranks.extend(
            rec.get("down_cranks", 0) for rec in r.recoveries
        )
    failed = [
        {"cell": r.cell.cell_id(), "error": r.error,
         "missing": r.missing_expected, "misattributed": r.misattributed[:4]}
        for r in results
        if not r.ok
    ]
    return {
        "metric": "crash_matrix",
        "value": round(len(results) / dt, 2),
        "unit": "cells/s",
        "vs_baseline": 1.0,
        "baseline": "estimated",
        "cells": len(results),
        "cells_ok": n_ok,
        "all_ok": n_ok == len(results),
        "crashes": sum(r.crashes for r in results),
        "restarts": sum(r.restarts for r in results),
        "recovered_in_time": all(r.recovered_in_time for r in results),
        "recovery_cranks": _histogram_summary(recovery_cranks),
        "tx_committed": sum(r.tx_committed for r in results),
        "commit_p99_max": max((r.commit_p99 for r in results), default=0.0),
        "fault_kinds": dict(sorted(fault_kinds.items())),
        "failed_cells": failed,
        "backend": backend_name,
    }


def _histogram_summary(values: list) -> dict:
    if not values:
        return {"count": 0}
    s = sorted(values)
    return {
        "count": len(s),
        "min": s[0],
        "max": s[-1],
        "mean": round(sum(s) / len(s), 1),
    }


def bench_qhb_traffic() -> dict:
    """The QueueingHoneyBadger batch-size × arrival-rate curve — the
    traffic subsystem's bench row (hbbft_tpu/traffic/): open-loop Poisson
    client load (Zipf population, BENCH_QHB_CLIENTS) against per-node
    bounded mempools, QHB-style random samples driven through
    ArrayHoneyBadgerNet lockstep epochs, per-tx commit latency tracked
    end to end.  Each grid cell records sustained committed tx/s (wall),
    tx/epoch, p50/p90/p99 commit latency in EPOCH units, mempool depth,
    and admission drops; arrival rates are fractions of the nominal
    per-epoch proposal capacity N·batch_size, so >1.0 cells measure the
    OVERLOAD regime — the acceptance claim is that the bounded mempool
    keeps memory flat and committed tx/s within ~10% of the saturation
    plateau (``overload`` summary fields).  One N=100 f=33 point rides
    along (fanout="one": each client submits to one node, bounding the
    admission cost at the north-star shape).  ``vs_baseline`` compares
    wall tx/s against the single-core reference committing the same
    tx/epoch at the estimated 0.25 epochs/s (N=16 real-crypto anchor).

    The batch-size knob is HoneyBadgerBFT's central throughput/latency
    trade (CCS 2016 §5): bigger batches amortize the O(N²·λ) crypto per
    epoch over more transactions but each epoch takes longer — this row
    turns that trade from prose into data."""
    import random as _random

    from examples.simulation import make_backend
    from hbbft_tpu.engine import ArrayHoneyBadgerNet
    from hbbft_tpu.obs import Tracer
    from hbbft_tpu.traffic import (
        ArrayTrafficDriver,
        OpenLoopSource,
        PayloadSizes,
        ZipfPopulation,
    )

    n = _env_int("BENCH_QHB_N", 16)
    epochs = _env_int("BENCH_QHB_EPOCHS", 4)
    clients = _env_int("BENCH_QHB_CLIENTS", 10_000)
    batches = [
        int(x)
        for x in os.environ.get("BENCH_QHB_BATCHES", "16,64,256").split(",")
    ]
    fracs = [
        float(x)
        for x in os.environ.get("BENCH_QHB_RATES", "0.5,1.0,2.0").split(",")
    ]
    backend_name = os.environ.get("BENCH_QHB_BACKEND", "mock")
    backend_label = backend_name  # refined to backend.name by the first cell

    def cell(n_, batch_size, frac, epochs_, fanout):
        nonlocal backend_label
        backend = make_backend(backend_name)
        backend_label = backend.name
        tracer = Tracer(spans=False)
        backend.tracer = tracer
        net = ArrayHoneyBadgerNet(
            range(n_), backend=backend, seed=0, dynamic=True, tracer=tracer
        )
        rate = frac * n_ * batch_size
        # capacity ~2 epochs of offered load at saturation: small enough
        # that the overload cells actually exercise the bound
        cap = max(64, 2 * n_ * batch_size)
        src = OpenLoopSource(
            rate, ZipfPopulation(clients, 1.1), PayloadSizes("fixed", 32)
        )
        drv = ArrayTrafficDriver(
            net, src, _random.Random(1234), batch_size=batch_size,
            mempool_capacity=cap, fanout=fanout, tracer=tracer,
        )
        t0 = time.perf_counter()
        rep = drv.run(epochs_)
        dt = time.perf_counter() - t0
        lat = rep["tracker"]["commit_latency"]
        return {
            "n": n_,
            "batch_size": batch_size,
            "rate_frac": frac,
            "rate_per_epoch": round(rate, 1),
            "epochs": epochs_,
            "committed": rep["committed"],
            "tx_per_epoch": rep["tx_per_epoch"],
            "tx_per_s": round(rep["committed"] / dt, 2) if dt > 0 else 0.0,
            "epochs_per_s": round(epochs_ / dt, 4) if dt > 0 else 0.0,
            "latency_p50": lat.get("p50", 0.0),
            "latency_p90": lat.get("p90", 0.0),
            "latency_p99": lat.get("p99", 0.0),
            "mempool_capacity": cap,
            "mempool_peak_depth": rep["mempool_peak_depth"],
            "dropped": rep["mempool_dropped"],
            "backpressure_epochs": rep["backpressure_epochs"],
            "state": rep["status"]["state"],
            "fanout": fanout,
        }

    curve = [
        cell(n, b, frac, epochs, "all") for b in batches for frac in fracs
    ]

    # overload acceptance summary: per batch size, committed tx/epoch at
    # every rate above saturation vs the frac==1.0 plateau, and whether
    # the mempool bound held (peak depth never exceeded capacity)
    overload = []
    for b in batches:
        plateau = next(
            (
                c["tx_per_epoch"]
                for c in curve
                if c["batch_size"] == b and abs(c["rate_frac"] - 1.0) < 1e-9
            ),
            None,
        )
        for c in curve:
            if c["batch_size"] != b or c["rate_frac"] <= 1.0 or not plateau:
                continue
            overload.append(
                {
                    "batch_size": b,
                    "rate_frac": c["rate_frac"],
                    "plateau_ratio": round(c["tx_per_epoch"] / plateau, 3),
                    "bounded": c["mempool_peak_depth"] <= c["mempool_capacity"],
                    "named_saturated": c["state"] == "saturated",
                }
            )

    n100_cell = None
    if os.environ.get("BENCH_QHB_N100", "1") == "1":
        n100_cell = cell(
            _env_int("BENCH_QHB_N100_N", 100),
            _env_int("BENCH_QHB_N100_BATCH", 128),
            1.0,
            _env_int("BENCH_QHB_N100_EPOCHS", 2),
            "one",
        )

    best = max(curve, key=lambda c: c["tx_per_s"])
    baseline_tx_per_s = 0.25 * best["tx_per_epoch"]  # single-core est.
    row = {
        "metric": "qhb_traffic",
        "value": best["tx_per_s"],
        "unit": "tx/s",
        "vs_baseline": (
            round(best["tx_per_s"] / baseline_tx_per_s, 3)
            if baseline_tx_per_s
            else 0.0
        ),
        "baseline": "estimated",
        "backend": backend_label,
        "n": n,
        "epochs": epochs,
        "clients": clients,
        "best_cell": {
            "batch_size": best["batch_size"],
            "rate_frac": best["rate_frac"],
            "latency_p99": best["latency_p99"],
        },
        "curve": curve,
        "overload": overload,
        # null, not False, when no >1.0x cell had a plateau reference —
        # "no data" must not read as "bound violated"
        "overload_bounded": (
            all(o["bounded"] for o in overload) if overload else None
        ),
        "overload_plateau_held": (
            all(o["plateau_ratio"] >= 0.9 for o in overload)
            if overload
            else None
        ),
    }
    if n100_cell is not None:
        row["n100"] = n100_cell
    return row


def bench_slo_traffic() -> dict:
    """The control-plane flagship row: SLO-driven adaptive batch sizing
    vs every fixed-B cell under the 10×-swing load trace (ROADMAP item
    3's closed loop; hbbft_tpu/control/).

    One declared SLO (p99 commit latency ≤ BENCH_SLO_P99 epochs), one
    replayable trace (BENCH_SLO_TRACE, default swing10x: half the period
    at the base rate, half at 10×), a 10⁶-client Zipf population over
    sharded mempools — and per cell either a fixed batch size from
    BENCH_SLO_BATCHES or the AdaptiveBatchController walking the ladder.
    The acceptance claim recorded on the row: the controller holds the
    SLO while every fixed-B cell either violates it (small B drowns in
    the swing's high phase) or sustains lower wall tx/s (large B
    over-samples the drained pool in the low phase — N decorrelated
    proposals of a small mempool are ~N× redundant bytes).  A final
    kill-switch arm re-runs the controller cell under
    ``HBBFT_TPU_NO_ADAPTIVE_B=1`` and asserts bit-identical batch
    digests + tracker fingerprint vs the fixed cell at the controller's
    initial B (``killswitch_identical``).

    ``vs_baseline`` is controller tx/s over the best SLO-compliant
    fixed cell's tx/s — the number >1.0 IS the claim."""
    import hashlib as _hashlib
    import random as _random

    from examples.simulation import make_backend
    from hbbft_tpu.control import SLO, AdaptiveBatchController, make_trace
    from hbbft_tpu.engine import ArrayHoneyBadgerNet
    from hbbft_tpu.obs import Tracer
    from hbbft_tpu.traffic import (
        ArrayTrafficDriver,
        OpenLoopSource,
        PayloadSizes,
        ZipfPopulation,
    )

    n = _env_int("BENCH_SLO_N", 16)
    epochs = _env_int("BENCH_SLO_EPOCHS", 24)
    clients = _env_int("BENCH_SLO_CLIENTS", 1_000_000)
    shards = _env_int("BENCH_SLO_SHARDS", 16)
    b0 = _env_int("BENCH_SLO_B0", 32)
    p99_target = float(os.environ.get("BENCH_SLO_P99", "4.0"))
    trace_name = os.environ.get("BENCH_SLO_TRACE", "swing10x")
    rate = float(os.environ.get("BENCH_SLO_RATE", str(6.25 * n)))
    batches = [
        int(x)
        for x in os.environ.get("BENCH_SLO_BATCHES", "8,32,128,512").split(",")
    ]
    backend_name = os.environ.get("BENCH_SLO_BACKEND", "mock")
    backend_label = backend_name
    slo = SLO(p99_epochs=p99_target)
    # one capacity for EVERY cell (fairness): ~4 epochs of peak load
    trace = make_trace(trace_name)
    cap = max(256, int(4 * rate * trace.peak()))

    def cell(batch_size, adaptive):
        nonlocal backend_label
        backend = make_backend(backend_name)
        backend_label = backend.name
        tracer = Tracer(spans=False)
        backend.tracer = tracer
        net = ArrayHoneyBadgerNet(
            range(n), backend=backend, seed=0, dynamic=True, tracer=tracer
        )
        src = OpenLoopSource(
            rate,
            ZipfPopulation(clients, 1.1),
            PayloadSizes("fixed", 32),
            trace=make_trace(trace_name),
        )
        controller = (
            AdaptiveBatchController(slo, initial_b=batch_size)
            if adaptive
            else None
        )
        drv = ArrayTrafficDriver(
            net, src, _random.Random(1234), batch_size=batch_size,
            mempool_capacity=cap, fanout="all", tracer=tracer,
            controller=controller, mempool_shards=shards,
        )
        digest = _hashlib.sha256()

        def on_batches(batches_map):
            b = batches_map[net.ids[0]]
            for p in net.ids:
                digest.update(bytes(b.contributions[p]))

        net.batch_listeners.append(on_batches)
        t0 = time.perf_counter()
        rep = drv.run(epochs)
        dt = time.perf_counter() - t0
        lat = rep["tracker"]["commit_latency"]
        p99 = lat.get("p99", 0.0)
        out = {
            "n": n,
            "batch_size": "adaptive" if adaptive else batch_size,
            "epochs": epochs,
            "committed": rep["committed"],
            "tx_per_epoch": rep["tx_per_epoch"],
            "tx_per_s": round(rep["committed"] / dt, 2) if dt > 0 else 0.0,
            "epochs_per_s": round(epochs / dt, 4) if dt > 0 else 0.0,
            "latency_p50": lat.get("p50", 0.0),
            "latency_p99": p99,
            "slo_compliant": bool(slo.compliant(p99 or None)),
            "mempool_peak_depth": rep["mempool_peak_depth"],
            "dropped": rep["mempool_dropped"],
            "state": rep["status"]["state"],
            "batch_digest": digest.hexdigest(),
            "tracker_fingerprint": _hashlib.sha256(
                repr(sorted(drv.tracker.fingerprint().items())).encode()
            ).hexdigest(),
        }
        if adaptive:
            out["b_trace"] = rep["controller"]["b_trace"]
            out["steps_up"] = rep["controller"]["steps_up"]
            out["steps_down"] = rep["controller"]["steps_down"]
        return out

    fixed_cells = [cell(b, adaptive=False) for b in batches]
    adaptive_cell = cell(b0, adaptive=True)

    # kill-switch arm: the controller cell pinned to its initial rung
    # must be bit-identical to the fixed-B0 cell (digest + fingerprint)
    saved = os.environ.get("HBBFT_TPU_NO_ADAPTIVE_B")
    os.environ["HBBFT_TPU_NO_ADAPTIVE_B"] = "1"
    try:
        killswitch_cell = cell(b0, adaptive=True)
    finally:
        if saved is None:
            os.environ.pop("HBBFT_TPU_NO_ADAPTIVE_B", None)
        else:
            os.environ["HBBFT_TPU_NO_ADAPTIVE_B"] = saved
    fixed_b0 = next(
        (c for c in fixed_cells if c["batch_size"] == b0), None
    )
    if fixed_b0 is None:
        fixed_b0 = cell(b0, adaptive=False)
    killswitch_identical = (
        killswitch_cell["batch_digest"] == fixed_b0["batch_digest"]
        and killswitch_cell["tracker_fingerprint"]
        == fixed_b0["tracker_fingerprint"]
    )

    compliant_fixed = [c for c in fixed_cells if c["slo_compliant"]]
    best_fixed_compliant = max(
        (c["tx_per_s"] for c in compliant_fixed), default=0.0
    )
    beats = all(
        (not c["slo_compliant"]) or c["tx_per_s"] < adaptive_cell["tx_per_s"]
        for c in fixed_cells
    )
    return {
        "metric": "slo_traffic",
        "value": adaptive_cell["tx_per_s"],
        "unit": "tx/s",
        "vs_baseline": (
            round(adaptive_cell["tx_per_s"] / best_fixed_compliant, 3)
            if best_fixed_compliant
            else 0.0
        ),
        "baseline": "best SLO-compliant fixed-B cell",
        "backend": backend_label,
        "n": n,
        "epochs": epochs,
        "clients": clients,
        "mempool_shards": shards,
        "mempool_capacity": cap,
        "rate_per_epoch": rate,
        "trace": trace.describe(),
        "slo": slo.describe(),
        "initial_b": b0,
        "curve": fixed_cells + [adaptive_cell],
        "controller_compliant": adaptive_cell["slo_compliant"],
        "controller_beats_fixed": beats,
        "killswitch_identical": killswitch_identical,
    }


def bench_g2_sign() -> dict:
    """Batched 254-bit G2 ladders — the sign op of vmapped coin flips."""
    import random

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hbbft_tpu.crypto import bls381 as gold
    from hbbft_tpu.crypto.field import R
    from hbbft_tpu.ops import curve

    batch = _env_int("BENCH_SIGN_BATCH", 1024)
    iters = _env_int("BENCH_ITERS", 3)
    rng = random.Random(3)
    scalars = [curve.safe_scalar(rng.randrange(1, R))[0] for _ in range(batch)]
    bits = jnp.asarray(curve.scalars_to_bits(scalars))
    H = curve.g2_to_device([gold.G2_GEN] * batch)
    fn = jax.jit(curve.g2_scalar_mul_batch)
    dt = _time_fn(fn, (H, bits), iters)

    # Spot check one lane against the golden ladder.
    out = fn(H, bits)
    got = curve.g2_from_device(
        jax.tree_util.tree_map(lambda c: np.asarray(c)[:1], out)
    )[0]
    want = gold.ec_mul(gold.FQ2, scalars[0], gold.G2_GEN)
    assert got == want, "g2 ladder wrong"

    # A single-core CPU G2 mult is ~1-2ms (est.): baseline ~700 signs/s.
    return {
        "metric": "g2_sign_throughput",
        "value": round(batch / dt, 2),
        "unit": "signs/s",
        "vs_baseline": round(batch / dt / 700.0, 3),
        "baseline": "estimated",
        "batch": batch,
    }


def bench_glv_ladder() -> dict:
    """GLV joint-table vs w2 ladder A/B on the REAL backend G1 mul path
    (``glv_ladder_ab``): per-ladder field-mul counts read off the
    ladder_field_muls counter — the measurable 2368-vs-3810 prediction
    from PERF.md's round-5 addendum — plus wall-clock ladders/s for both
    arms.  In-process A/B: HBBFT_TPU_NO_GLV is read per batch, and the
    two arms' bit-matrix shapes compile distinct graphs.  Fresh random
    scalars per timed iteration (fresh-buffer discipline — the axon
    relay memoizes repeat dispatches on identical buffers), and each
    g1_mul_batch call ends in a host readback, which doubles as the
    fence.  Dispatches are kinded glv_ab so the row's device seconds
    never pollute real DKG attribution."""
    import random

    from hbbft_tpu.crypto.field import R
    from hbbft_tpu.ops.backend import TpuBackend

    # below the combine threshold the backend takes the host-golden path
    # and no ladder is measured at all (the counters would divide by 0)
    batch = max(
        _env_int("BENCH_GLV_BATCH", 256), TpuBackend.device_combine_threshold
    )
    iters = max(1, _env_int("BENCH_GLV_ITERS", 3))

    def arm(no_glv: bool):
        saved = os.environ.pop("HBBFT_TPU_NO_GLV", None)
        # an ambient binary-ladder A/B knob would silently disable GLV in
        # BOTH arms and publish a vacuous reduction of 1.0
        saved_bin = os.environ.pop("HBBFT_TPU_LADDER_BINARY", None)
        if no_glv:
            os.environ["HBBFT_TPU_NO_GLV"] = "1"
        try:
            rng = random.Random(407)
            be = TpuBackend()
            g1 = be.group.g1()
            pts = [g1] * batch
            scal = [rng.randrange(R) for _ in range(batch)]
            be.g1_mul_batch(scal, pts, kind="glv_ab")  # compile + warm
            c = be.counters
            muls0, tbl0 = c.ladder_field_muls, c.glv_table_field_muls
            t0 = time.perf_counter()
            for _ in range(iters):
                scal = [rng.randrange(R) for _ in range(batch)]
                out = be.g1_mul_batch(scal, pts, kind="glv_ab")
            dt = time.perf_counter() - t0
            # spot-check the last iteration against the host golden
            i = rng.randrange(batch)
            assert out[i] == be.group.g1_mul(scal[i], g1), "A/B arm is wrong"
            n = iters * batch
            return {
                "muls_per_ladder": (c.ladder_field_muls - muls0) / n,
                "table_muls_per_ladder": (c.glv_table_field_muls - tbl0) / n,
                "ladders_per_sec": n / dt,
                "decompositions": c.glv_decompositions,
            }
        finally:
            if saved is None:
                os.environ.pop("HBBFT_TPU_NO_GLV", None)
            else:
                os.environ["HBBFT_TPU_NO_GLV"] = saved
            if saved_bin is not None:
                os.environ["HBBFT_TPU_LADDER_BINARY"] = saved_bin

    glv = arm(no_glv=False)
    w2 = arm(no_glv=True)
    assert glv["decompositions"] > 0, "GLV arm never decomposed — vacuous A/B"
    assert w2["decompositions"] == 0, "kill switch leaked into the w2 arm"
    return {
        "metric": "glv_ladder_ab",
        "value": round(glv["ladders_per_sec"], 2),
        "unit": "ladders/s",
        "batch": batch,
        "field_muls_per_ladder_glv": round(glv["muls_per_ladder"], 1),
        "field_muls_per_ladder_w2": round(w2["muls_per_ladder"], 1),
        "field_mul_reduction": round(
            w2["muls_per_ladder"] / glv["muls_per_ladder"], 3
        ),
        "table_muls_per_ladder": round(glv["table_muls_per_ladder"], 1),
        "w2_ladders_per_sec": round(w2["ladders_per_sec"], 2),
        "glv_vs_w2": round(
            glv["ladders_per_sec"] / w2["ladders_per_sec"], 3
        ),
    }


def bench_fq_kernel() -> dict:
    """Raw Fq-multiply throughput, limb vs RNS, via tools/kernel_bench.py
    in SUBPROCESSES (HBBFT_TPU_FQ_IMPL is read at import, so an in-process
    A/B is impossible).  The value is the RNS rate — the round-3 MXU
    reformulation this row exists to track; the limb rate rides along for
    the A/B.  vs_baseline is against the round-2 on-chip limb asymptote
    (217M muls/s)."""
    import re
    import subprocess

    lanes = os.environ.get("BENCH_FQ_LANES", "65536")
    chain = os.environ.get("BENCH_FQ_CHAIN", "200")

    import jax

    parent_backend = jax.default_backend()

    def run(impl: str) -> float:
        env = dict(os.environ)
        env["HBBFT_TPU_FQ_IMPL"] = impl
        env["KB_LANES"] = lanes
        env["KB_CHAIN"] = chain
        env["KB_NO_ROOFLINE"] = "1"  # probe is step-independent, full-size
        # this row is the UNFUSED fq.mul A/B: without this the rns arm's
        # fused-chain sweep would print last and m[-1] would silently
        # record the fused rate against limb's unfused one
        env["KB_FUSED"] = "0"
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "kernel_bench.py")],
            capture_output=True,
            text=True,
            timeout=1500,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        m = re.findall(r"([0-9.]+) M muls/s", proc.stdout)
        if not m:
            raise RuntimeError(
                f"kernel_bench[{impl}] produced no rate:\n"
                f"{proc.stdout[-800:]}\n{proc.stderr[-800:]}"
            )
        child = re.search(r"backend=(\S+)", proc.stdout)
        child_backend = child.group(1) if child else "unknown"
        if child_backend != parent_backend:
            # a silent CPU fallback in the child must not be recorded
            # under the parent's platform stamp (poisoned TPU artifact)
            raise RuntimeError(
                f"kernel_bench[{impl}] ran on {child_backend!r}, parent "
                f"is {parent_backend!r} — refusing to record"
            )
        return float(m[-1])

    rns = run("rns")
    limb = run("limb")
    return {
        "metric": "fq_mul_throughput",
        "value": round(rns * 1e6, 0),
        "unit": "muls/s",
        "vs_baseline": round(rns * 1e6 / 217e6, 3),
        "baseline": "round-2 on-chip limb asymptote",
        "impl": "rns",
        "limb_muls_per_sec": round(limb * 1e6, 0),
        "rns_vs_limb": round(rns / limb, 2) if limb else None,
        "lanes": lanes,
    }


def bench_rs_encode() -> dict:
    """GF(2⁸) RS parity at the N=100 broadcast shape (34 data, 66 parity)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hbbft_tpu.ops.gf256 import JaxRSCodec

    data, parity = 34, 66  # N=100, f=33: N-2f data + 2f parity
    shard = _env_int("BENCH_RS_SHARD", 16384)
    # cheap kernel: more iters amortize residual relay noise; the
    # metric-specific knob wins over the global BENCH_ITERS
    iters = _env_int("BENCH_RS_ITERS", _env_int("BENCH_ITERS", 20))
    codec = JaxRSCodec(data, parity)
    enc = jax.jit(codec.encode_matrix_fn())
    rng = np.random.default_rng(0)
    mat = jnp.asarray(rng.integers(0, 256, size=(data, shard), dtype=np.uint8))
    dt = _time_fn(enc, (mat,), iters)

    # Golden spot check against the host codec.
    from hbbft_tpu.crypto.erasure import RSCodec

    host = RSCodec(data, parity)
    got = np.asarray(enc(mat))
    want = host._parity(np.asarray(mat))
    assert np.array_equal(got, want), "device RS parity wrong"

    mb = data * shard / 1e6
    return {
        "metric": "rs_encode_throughput",
        "value": round(mb / dt, 2),
        "unit": "MB/s",
        "vs_baseline": round(mb / dt / 500.0, 3),  # ~500 MB/s single-core est.
        "baseline": "estimated",
        "batch": shard,
    }


def bench_rs_host() -> dict:
    """RS parity through the codec the PROTOCOL STACK actually routes to
    (crypto/erasure.RSCodec → native AVX2 GF(2⁸) kernel): broadcast.py
    always uses the host codec; JaxRSCodec is the staged device
    alternative the rs_encode_throughput row A/Bs.  This row exists so
    the artifact reflects the path users get (round-3 verdict Weak #4's
    'host-AVX2 routing for the protocol path' — that IS the routing)."""
    import numpy as np

    from hbbft_tpu.crypto.erasure import RSCodec

    data, parity = 34, 66  # N=100 broadcast shape
    shard = _env_int("BENCH_RS_SHARD", 16384)
    iters = _env_int("BENCH_RS_ITERS", _env_int("BENCH_ITERS", 20))
    codec = RSCodec(data, parity)
    rng = np.random.default_rng(0)
    mat = rng.integers(0, 256, size=(data, shard), dtype=np.uint8)
    codec._parity(mat)  # warm (builds tables / loads the native kernel)
    t0 = time.perf_counter()
    for _ in range(iters):
        codec._parity(mat)
    dt = (time.perf_counter() - t0) / iters
    mb = data * shard / 1e6
    return {
        "metric": "rs_encode_host_throughput",
        "value": round(mb / dt, 2),
        "unit": "MB/s",
        "vs_baseline": round(mb / dt / 500.0, 3),
        "baseline": "estimated",
        "batch": shard,
        "engine": "native-simd",
        "role": "protocol path",
    }


def bench_rs_plane_ab() -> dict:
    """Device erasure/hash plane vs host codec A/B (``rs_plane_ab``):
    the full per-proposer RS+Merkle workload — batched encode, tree
    build, all-N² proof verifies, and an m-erasure reconstruct — through
    ``TpuBackend``'s plane methods, at the N=16 shape (k=6, m=10) and
    the N=100 f=33 broadcast shape (k=34, m=66).  In-process A/B:
    HBBFT_TPU_NO_DEVICE_RS is read per call, so the host arm runs the
    byte-for-byte protocol codec path through the SAME entry points.
    Fresh random blocks per timed iteration (fresh-buffer discipline),
    golden spot-checks against the host codec + hashlib trees in BOTH
    arms, and kill-switch non-leak asserts in both directions via the
    rs_enc/merkle dispatch-kind counters."""
    import random

    from hbbft_tpu.crypto.erasure import RSCodec
    from hbbft_tpu.crypto.merkle import MerkleTree, PackedProofs
    from hbbft_tpu.ops.backend import TpuBackend

    shapes = [("n16", 6, 10), ("n100_f33", 34, 66)]
    block = _env_int("BENCH_RS_PLANE_BLOCK", 4096)
    iters = max(1, _env_int("BENCH_RS_PLANE_ITERS", 3))

    def arm(no_device: bool) -> dict:
        saved = os.environ.pop("HBBFT_TPU_NO_DEVICE_RS", None)
        if no_device:
            os.environ["HBBFT_TPU_NO_DEVICE_RS"] = "1"
        try:
            rng = random.Random(419)
            be = TpuBackend()
            out: dict = {}

            def workload(codec, n, datas):
                sh = be.rs_encode_batch(codec, datas)
                trees = be.merkle_build_batch(sh)
                packed = PackedProofs.from_trees(trees, n, device=not no_device)
                verdicts = (
                    be.merkle_verify_batch(packed)
                    if packed is not None
                    else [
                        t.proof(i).validate(n)
                        for t in trees
                        for i in range(n)
                    ]
                )
                holes = [list(s) for s in sh]
                for h in holes:
                    for j in rng.sample(range(n), codec.m):
                        h[j] = None
                rec = be.rs_reconstruct_batch(codec, holes)
                return sh, trees, verdicts, rec

            for label, k, m in shapes:
                codec = RSCodec(k, m)
                n = k + m
                datas = [
                    rng.randbytes(block) for _ in range(n)
                ]
                workload(codec, n, datas)  # compile + warm
                t0 = time.perf_counter()
                for _ in range(iters):
                    datas = [rng.randbytes(block) for _ in range(n)]
                    sh, trees, verdicts, rec = workload(codec, n, datas)
                dt = time.perf_counter() - t0
                # golden spot check, last iteration, both arms
                i = rng.randrange(n)
                assert sh[i] == codec.encode(datas[i]), "A/B arm encode wrong"
                assert (
                    trees[i].root_hash == MerkleTree(sh[i]).root_hash
                ), "A/B arm tree wrong"
                assert all(verdicts), "A/B arm verify wrong"
                assert rec[i] == sh[i], "A/B arm reconstruct wrong"
                out[label] = iters * n / dt
            c = be.counters
            out["plane_seconds"] = (
                c.device_seconds_rs_enc
                + c.device_seconds_rs_dec
                + c.device_seconds_merkle
            )
            return out
        finally:
            if saved is None:
                os.environ.pop("HBBFT_TPU_NO_DEVICE_RS", None)
            else:
                os.environ["HBBFT_TPU_NO_DEVICE_RS"] = saved

    dev = arm(no_device=False)
    host = arm(no_device=True)
    assert dev["plane_seconds"] > 0, "device arm never dispatched — vacuous A/B"
    assert host["plane_seconds"] == 0, "kill switch leaked into the host arm"
    return {
        "metric": "rs_plane_ab",
        "value": round(dev["n100_f33"], 2),
        "unit": "blocks/s",
        "batch": block,
        "host_blocks_per_sec": round(host["n100_f33"], 2),
        "device_vs_host": round(dev["n100_f33"] / host["n100_f33"], 3),
        "n16_blocks_per_sec": round(dev["n16"], 2),
        "n16_host_blocks_per_sec": round(host["n16"], 2),
        "n16_device_vs_host": round(dev["n16"] / host["n16"], 3),
    }


def bench_fused_chain_ab() -> dict:
    """VMEM-resident fused tower chain vs stacked kernels (``fused_chain_ab``,
    PR 20): the grouped rlc_sig verification graph — the rlc_dec/rlc_sig
    chain shape the ≥2G field-muls/s target is stated against — through
    ``_jitted_rlc_sig(mode)`` in both compositions.  Steady-state
    ``_time_fn`` discipline (compile untimed, fresh staged copies,
    ``_touch``, host-fetch fence); bit-identical canonical readback
    between arms asserted on a spot-checked group, plus env-ladder
    routing in both directions (the kill switch must resolve to the
    stacked graph).  The analytic launch/mul model (pairing_chain) turns
    the fused wall into field-muls/s — the row's value — and reports the
    per-verification Pallas-launch drop the ISSUE-20 ≥3× bar reads."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from hbbft_tpu.ops import curve, pairing, pairing_chain, tower
    from hbbft_tpu.ops.backend import TpuBackend, _jitted_rlc_sig

    g = _env_int("BENCH_FUSED_GROUPS", 64)
    k = _env_int("BENCH_RLC_K", 32)
    iters = _env_int("BENCH_ITERS", 3)

    fused_mode = "native" if jax.default_backend() == "tpu" else "interpret"
    saved = {
        v: os.environ.pop(v, None)
        for v in ("HBBFT_TPU_FUSED_TOWER", "HBBFT_TPU_NO_FUSED_TOWER")
    }
    try:
        # env-ladder routing, both directions
        os.environ["HBBFT_TPU_FUSED_TOWER"] = fused_mode
        assert pairing_chain.fused_tower_mode() == fused_mode
        os.environ["HBBFT_TPU_NO_FUSED_TOWER"] = "1"
        assert pairing_chain.fused_tower_mode() is None, "kill switch leaked"
        del os.environ["HBBFT_TPU_NO_FUSED_TOWER"]

        S, PK, negG1, H = _synthetic_share_groups(g, k, seed=11)
        rs = [[1 + i * 6007 + j for j in range(k)] for i in range(g)]
        rbits = jnp.asarray(
            np.stack(
                [curve.scalars_to_bits(row, TpuBackend._rlc_bits()) for row in rs]
            )
        )
        args = (S, PK, rbits, negG1, H)

        fused_fn = _jitted_rlc_sig(fused_mode)
        stacked_fn = _jitted_rlc_sig(None)
        dt_fused = _time_fn(fused_fn, args, iters)
        dt_stacked = _time_fn(stacked_fn, args, iters)

        # bit-identical represented values between arms + real verdicts
        out_f = jax.tree_util.tree_map(np.asarray, fused_fn(*args))
        out_s = jax.tree_util.tree_map(np.asarray, stacked_fn(*args))
        assert tower.fq12_to_ints_batch(out_f, g) == tower.fq12_to_ints_batch(
            out_s, g
        ), "fused arm diverged from stacked graph"
        assert all(pairing.is_one_host_batch(out_f, g)), "verification wrong"

        muls = pairing_chain.analytic_chain_field_muls(g)
        launches_fused = pairing_chain.analytic_pallas_calls(2, fused=True)
        launches_stacked = pairing_chain.analytic_pallas_calls(2, fused=False)
        return {
            "metric": "fused_chain_ab",
            "value": round(muls / dt_fused, 2),
            "unit": "field_muls/s",
            "batch": g * k,
            "groups": g,
            "mode": fused_mode,
            "shares_per_sec": round(g * k / dt_fused, 2),
            "stacked_shares_per_sec": round(g * k / dt_stacked, 2),
            "fused_vs_stacked": round(dt_stacked / dt_fused, 3),
            "launch_drop": round(launches_stacked / launches_fused, 2),
        }
    finally:
        for v, val in saved.items():
            if val is None:
                os.environ.pop(v, None)
            else:
                os.environ[v] = val


def bench_epochs_n100() -> dict:
    """North-star macro shape: N=100 f=33 QHB epochs/sec, end to end.

    Wall-clock here is dominated by the host protocol layer (pure-Python
    message handling) — this measures the whole framework, not the device
    kernel.  BENCH_N100_BACKEND=tpu routes the crypto through the device.

    BASELINE.md (round-5 corrected): the whole-network simulation does
    ~990k pairing verifies per epoch (N²·(N−f), the measured count) at
    the ~10³ pairings/s/core anchor → ~0.001 epochs/s single-core.  The
    earlier 0.1 figure took the per-NODE O(N²)≈10k count for the whole
    network — 100x too generous to the reference."""
    return _bench_object_runtime(
        "hbbft_epochs_per_sec_n100",
        n=100,
        f=33,
        env_prefix="BENCH_N100",
        default_epochs=1,
        default_txns=200,
        baseline_eps=0.001,
        # This row measures the per-message OBJECT runtime — the
        # correctness/adversarial harness.  The throughput story at this
        # shape is array_epochs_per_sec_n100 (lockstep array engine).
        extra_fields={"role": "correctness-harness"},
    )


def _bench_object_runtime(
    metric: str,
    n: int,
    f: int,
    env_prefix: str,
    default_epochs: int,
    default_txns: int,
    baseline_eps: float,
    extra_fields: dict,
    default_backend: str = "mock",
) -> dict:
    """Shared body of the object-runtime rows (configs 0 and 3): build a
    Simulation at the given shape and time its epochs."""
    import random

    from examples.simulation import Simulation, make_backend

    class A:  # argparse stand-in
        num_nodes = n
        num_faulty = f
        batch_size = _env_int(f"{env_prefix}_BATCH", 100)
        tx_size = 10
        txns = _env_int(f"{env_prefix}_TXNS", default_txns)
        epochs = _env_int(f"{env_prefix}_EPOCHS", default_epochs)
        lam = 100.0
        bandwidth = 2000.0
        cpu_factor = 1.0
        crypto_window = _env_int(f"{env_prefix}_WINDOW", 256)
        seed = 0

    backend = make_backend(os.environ.get(f"{env_prefix}_BACKEND", default_backend))
    sim = Simulation(A, backend, random.Random(0))
    t0 = time.perf_counter()
    rows = sim.run()
    dt = time.perf_counter() - t0
    eps = len(rows) / dt if dt > 0 else 0.0
    return {
        "metric": metric,
        "value": round(eps, 4),
        "unit": "epochs/s",
        "vs_baseline": round(eps / baseline_eps, 3),
        "baseline": "estimated",
        "epochs_measured": len(rows),
        "backend": backend.name,
        "runtime": "object",
        **extra_fields,
    }


def bench_epochs_n4() -> dict:
    """BASELINE config 0 shape: HoneyBadger N=4 f=1, 10 epochs, 100
    txns/batch — the CPU-reference configuration, run through the OBJECT
    runtime (the per-message semantics the reference measures).
    Mock (default) measures the protocol layer; the honest single-core
    real-crypto anchor is its own row (bench_epochs_n4_realcrypto).
    BENCH_N4_TXNS must scale with BENCH_N4_EPOCHS (~25 consumed per node
    per epoch) or the queue drains early — epochs_measured reports what
    actually ran."""
    # single-core Rust at N=4: ~128 pairings/epoch at ~1k/s ≈ 7 epochs/s
    epochs = _env_int("BENCH_N4_EPOCHS", 10)
    return _bench_object_runtime(
        "hbbft_epochs_per_sec_n4",
        n=4,
        f=1,
        env_prefix="BENCH_N4",
        default_epochs=epochs,
        default_txns=40 * epochs,
        baseline_eps=7.0,
        extra_fields={},
    )


def bench_epochs_n4_realcrypto() -> dict:
    """BASELINE config 0's honest single-core anchor: N=4 f=1 through the
    object runtime with the REAL host crypto (CpuBackend — golden
    BLS12-381, every pairing actually computed on one core).  This is the
    apples-to-apples point the config exists for (round-3 verdict Missing
    #5): the mock n4 row measures only the protocol layer.  ~128
    pairings/epoch at the measured ~0.5 s/host-pairing ≈ 60-70 s/epoch, so
    the default horizon is small (BENCH_N4RC_EPOCHS)."""
    epochs = _env_int("BENCH_N4RC_EPOCHS", 2)
    return _bench_object_runtime(
        "hbbft_epochs_per_sec_n4_realcrypto",
        n=4,
        f=1,
        env_prefix="BENCH_N4RC",
        default_epochs=epochs,
        default_txns=40 * epochs,
        baseline_eps=7.0,
        extra_fields={"role": "single-core real-crypto anchor"},
        default_backend="cpu",
    )


def _bench_array_engine(
    metric: str,
    n: int,
    epochs: int,
    baseline_eps: float,
    dedup: bool,
    dynamic: bool,
    backend_env: str = "BENCH_ARRAY_BACKEND",
    backend_default: str = "mock",
    coin_rounds: int = 0,
    churn_epochs: int = 0,
) -> dict:
    """Shared array-engine macro bench: warm one epoch (compile/caches),
    then time ``epochs`` full-workload lockstep epochs at network size n.

    ``churn_epochs`` > 0 inserts that many mid-run era changes (vote →
    SyncKeyGen DKG → new keys; SURVEY.md §3.4) at evenly spaced epochs;
    the era-change cost is timed separately (``era_change_seconds``) and
    excluded from epochs/s so the steady-state metric stays comparable
    round over round.  Post-turnover epochs run under the NEW keys — the
    engine's decrypt-equality asserts are the correctness check."""
    from examples.simulation import make_backend
    from hbbft_tpu.engine import ArrayHoneyBadgerNet
    from hbbft_tpu.obs import Tracer

    backend = make_backend(os.environ.get(backend_env, backend_default))
    # histogram-only tracer (spans off: no event-list growth on
    # million-dispatch epochs): dispatch batch sizes + RLC group sizes
    # ride the row as p50/p90/p99 summaries
    tracer = Tracer(spans=False)
    backend.tracer = tracer
    net = ArrayHoneyBadgerNet(
        range(n), backend=backend, seed=0, dedup_verifies=dedup,
        dynamic=dynamic, coin_rounds=coin_rounds, tracer=tracer,
    )
    net.run_epochs(1, payload_size=64)  # warm: compile/caches
    # per-epoch telemetry series (PR 13): BENCH_SERIES=<path.jsonl>
    # attaches a MetricsLog AFTER the warm epoch (so the series covers
    # exactly the timed steady-state epochs) with timing fields opted
    # back in — benches are wall-clock evidence, not replay artifacts
    series_path = os.environ.get("BENCH_SERIES")
    if series_path:
        from hbbft_tpu.obs.timeseries import MetricsLog

        net.metrics_log = MetricsLog(include_timing=True)
    counters = getattr(backend, "counters", None)
    ctr0 = counters.snapshot() if counters is not None else {}
    # post-warm baselines so the row's counters/histograms cover exactly
    # the timed epochs (the warm epoch includes JIT compilation, which
    # would skew the attribution the *_per_epoch fields exclude)
    merged0 = net.counters.merged_with(backend.counters)
    tracer.histograms.clear()
    from hbbft_tpu.obs.hostbuckets import HOST_BUCKETS

    churn_ctr = {
        "device_seconds": 0.0,
        "hash_g2_seconds": 0.0,
        # pipelined-dispatch attribution (PR 3): host staging cost and
        # the host time hidden under device execution, both excluded
        # from steady-state per-epoch fields like churn_time is
        "host_assembly_seconds": 0.0,
        "overlap_seconds": 0.0,
        # host-bucket attribution (PR 5): total attributable host wall
        # and its named split, excluded from steady-state per-epoch
        # fields the same way
        "host_seconds": 0.0,
        "fetch_blocked_seconds": 0.0,
        **{f"host_bucket_{b}": 0.0 for b in HOST_BUCKETS},
        # per-kind split (r4 verdict task 7): rows elide zero-valued kinds
        "device_seconds_pairing": 0.0,
        "device_seconds_rlc_sig": 0.0,
        "device_seconds_rlc_dec": 0.0,
        "device_seconds_combine": 0.0,
        "device_seconds_sign": 0.0,
        "device_seconds_decrypt": 0.0,
        "device_seconds_dkg": 0.0,
        "device_seconds_encrypt": 0.0,
        # device erasure/hash plane (PR 19)
        "device_seconds_rs_enc": 0.0,
        "device_seconds_rs_dec": 0.0,
        "device_seconds_merkle": 0.0,
    }
    # mid-run only: era changes need a preceding and a following epoch, so
    # indices clamp to [1, epochs-1] and dedupe (epochs < 2 → no churn; the
    # row's churn_epochs field reports what actually ran).
    churn_at = (
        sorted(
            {
                min(epochs - 1, max(1, (i + 1) * epochs // (churn_epochs + 1)))
                for i in range(churn_epochs)
            }
        )
        if churn_epochs and epochs >= 2
        else []
    )
    churn_time = 0.0
    churn_merged = {}
    t0 = time.perf_counter()
    done = 0
    for e in range(epochs):
        if e in churn_at:
            t_ch = time.perf_counter()
            pre = counters.snapshot() if counters is not None else {}
            pre_merged = net.counters.merged_with(backend.counters)
            net.era_change()
            if counters is not None:
                d = counters.diff(pre)
                for k in churn_ctr:  # excluded like churn_time is
                    churn_ctr[k] += d.get(k, 0.0)
            for k, v in net.counters.merged_with(backend.counters).items():
                dv = v - pre_merged.get(k, 0)
                if dv:
                    churn_merged[k] = churn_merged.get(k, 0) + dv
            churn_time += time.perf_counter() - t_ch
        net.run_epochs(1, payload_size=64)
        done += 1
    dt = (time.perf_counter() - t0) - churn_time
    eps = done / dt if dt > 0 else 0.0
    rep = net.reports[-1]  # warm epoch guarantees one report even if epochs=0
    row = {
        "metric": metric,
        "value": round(eps, 5),
        "unit": "epochs/s",
        "vs_baseline": round(eps / baseline_eps, 3),
        "baseline": "estimated",
        "runtime": "array",
        "n": n,
        "backend": backend.name,
        "dedup": dedup,
        "dynamic": dynamic,
        "epochs": epochs,
        "churn_epochs": len(net.churn_reports),
        "messages_per_epoch": rep.messages_delivered,
        "dec_share_verifies_per_epoch": rep.dec_shares_verified,
    }
    if counters is not None and done:
        # host/device attribution for the timed epochs (verdict task 8):
        # device_seconds = dispatch+fetch wall of the dominant jitted
        # calls, hash_g2_seconds = host EC hashing — both per
        # steady-state epoch (era-change work excluded, like churn_time).
        delta = counters.diff(ctr0)
        skip_keys = {
            "host_assembly_seconds", "overlap_seconds", "host_seconds",
            "fetch_blocked_seconds",
        }
        for key in churn_ctr:
            if key in skip_keys or key.startswith("host_bucket_"):
                continue  # emitted below under their canonical names
            val = delta.get(key, 0.0) - churn_ctr[key]
            if val > 0:
                row[f"{key}_per_epoch"] = round(val / done, 4)
        # host/device split without a trace attached (PR 5):
        # host_seconds_per_epoch is the TOTAL host wall inside the timed
        # epochs minus device-fetch-blocked time (the engine's epoch
        # region, obs/hostbuckets.py — before PR 5 this field carried
        # only the staging slice), host_buckets is its named exclusive
        # split, and host_unattributed_fraction is the residual "other"
        # share the <10% acceptance bar tracks.  overlap_fraction: the
        # fraction of device dispatch wall during which the host was
        # doing OTHER work (assembly of the next chunk) instead of
        # blocking on the fetch; sync mode (HBBFT_TPU_NO_PIPELINE=1)
        # reads 0.
        host = delta.get("host_seconds", 0.0) - churn_ctr["host_seconds"]
        row["host_seconds_per_epoch"] = round(max(host, 0.0) / done, 4)
        blocked = delta.get("fetch_blocked_seconds", 0.0) - churn_ctr[
            "fetch_blocked_seconds"
        ]
        if blocked > 0:
            row["fetch_blocked_seconds_per_epoch"] = round(blocked / done, 4)
        buckets = {}
        for b in HOST_BUCKETS:
            key = f"host_bucket_{b}"
            val = delta.get(key, 0.0) - churn_ctr[key]
            if val > 0:
                buckets[b] = round(val / done, 4)
        if buckets:
            row["host_buckets"] = buckets
        if host > 0:
            row["host_unattributed_fraction"] = round(
                max(
                    delta.get("host_bucket_other", 0.0)
                    - churn_ctr["host_bucket_other"],
                    0.0,
                )
                / host,
                4,
            )
        dev = delta.get("device_seconds", 0.0) - churn_ctr["device_seconds"]
        ovl = delta.get("overlap_seconds", 0.0) - churn_ctr["overlap_seconds"]
        row["overlap_fraction"] = round(ovl / dev, 4) if dev > 0 else 0.0
    if coin_rounds:
        row["coin_rounds_per_ba"] = coin_rounds
        row["coin_signs_per_epoch"] = rep.coin_signs
        row["sig_share_verifies_per_epoch"] = rep.sig_shares_verified
        row["sig_combines_per_epoch"] = rep.sig_combines
    if net.churn_reports:
        crep = net.churn_reports[0]
        row["era_change_seconds"] = round(churn_time / len(net.churn_reports), 3)
        row["era_change_kg_acks"] = crep.kg_acks_handled
        row["era"] = net.era
    hists = tracer.hist_summary()
    if hists:
        row["histograms"] = hists
    # merged counters delta (engine + crypto, nonzero keys) over the
    # TIMED steady-state epochs only — era-change work subtracted, like
    # churn_time and the *_per_epoch fields — so driver artifacts carry
    # full attribution without a re-run.  (The histograms above still
    # include era-change dispatches on dynamic configs: distributions
    # are not subtractable.)
    merged1 = net.counters.merged_with(backend.counters)
    row["counters"] = {
        k: v
        for k in merged1
        if (
            v := round(
                merged1[k] - merged0.get(k, 0) - churn_merged.get(k, 0), 4
            )
        )
    }
    if series_path and net.metrics_log is not None:
        from hbbft_tpu.obs.critpath import gating_from_series

        net.metrics_log.to_jsonl(series_path)
        gating = gating_from_series(net.metrics_log.rows_list())
        if gating:
            row["gating"] = gating
        row["series"] = series_path
    return row


def bench_array_engine_n100() -> dict:
    """North-star macro config through the ARRAY ENGINE: N=100 f=33
    epochs/sec, whole-network lockstep execution with the full per-receiver
    workload (6.94M messages, ~10.7M hashes, ~10⁶ share verifies per epoch
    — identical counts to the object runtime, see
    hbbft_tpu/engine/array_engine.py).

    BENCH_ARRAY_BACKEND=tpu routes crypto through the device backend; the
    memoizing-simulation variant has its own row
    (array_epochs_per_sec_n100_dedup) so this one is always the full
    per-receiver workload.  BASELINE config 3 names DynamicHoneyBadger,
    so the DHB flavor is the default.  Estimated single-core reference
    ≈ 0.1 epochs/s (BASELINE.md cost model).

    BASELINE config 3 defines this at 1k epochs; the default here runs
    100 (BENCH_ARRAY_EPOCHS raises it — CPU-fallback mode shrinks to 2)
    with ONE mid-run era change (vote → DKG → era; BENCH_ARRAY_CHURN),
    timed separately in era_change_seconds.

    Baseline (round-5 correction): the whole-NETWORK simulation on one
    core performs ~990k pairing verifies per epoch (the measured
    dec_share count) at the BASELINE.md ~10^3 pairings/s anchor →
    ~0.001 epochs/s.  Rounds 1-4 used 0.1 (the per-NODE cost misread
    as whole-network, 100x too generous to the reference) — archived
    artifacts keep their recorded ratios; PERF.md documents the rebase.
    Scales with the actual N (BENCH_ARRAY_N) as N^2·(N-f)."""
    n_cfg = _env_int("BENCH_ARRAY_N", 100)
    f_cfg = (n_cfg - 1) // 3
    pairings_per_epoch = n_cfg * n_cfg * (n_cfg - f_cfg)
    return _bench_array_engine(
        "array_epochs_per_sec_n100",
        n=n_cfg,
        epochs=_env_int("BENCH_ARRAY_EPOCHS", 100),
        baseline_eps=1000.0 / pairings_per_epoch,
        dedup=False,
        dynamic=os.environ.get("BENCH_ARRAY_DYNAMIC", "1") == "1",
        churn_epochs=_env_int("BENCH_ARRAY_CHURN", 1),
    )


def bench_array_engine_n100_dedup() -> dict:
    """The N=100 macro in MEMOIZING-SIMULATION mode: identical per-receiver
    verifications collapse to one representative each (every receiver
    checks the same share against the same public key, so one truth value
    serves all N).  Message/threshold accounting is unchanged; only
    redundant crypto work is deduplicated.  Labeled distinctly from the
    full-workload row — the reference's simulation would NOT memoize.
    Baseline: same whole-network ~0.001 eps anchor as the full row."""
    return _bench_array_engine(
        "array_epochs_per_sec_n100_dedup",
        n=_env_int("BENCH_ARRAY_N", 100),
        epochs=_env_int("BENCH_ARRAY_EPOCHS", 100),
        baseline_eps=0.001,
        dedup=True,
        dynamic=os.environ.get("BENCH_ARRAY_DYNAMIC", "1") == "1",
    )


def bench_array_engine_n16_tpu() -> dict:
    """Real-crypto end-to-end macro: N=16 f=5 lockstep epochs with the
    DEVICE backend — every Merkle proof, RS code, threshold encryption,
    grouped-RLC share verification, batched share generation, and Lagrange
    combine on the real BLS12-381 device path (config-1 network size, run
    as whole epochs rather than the rlc_dec micro-shape).  Per-epoch full
    workload at N=16: ~3.8k dec-share verifies, 256 combines, 28k
    messages.  Estimated single-core reference ≈ 0.25 epochs/s (n²(n−1) ≈
    3.8k pairings/epoch at ~1k/s).  BENCH_ARRAY16_BACKEND overrides the
    backend (tpu default here)."""
    return _bench_array_engine(
        ARRAY_N16_METRIC,
        n=16,
        epochs=_env_int("BENCH_ARRAY16_EPOCHS", 2),
        baseline_eps=0.25,
        dedup=False,
        dynamic=True,
        backend_env="BENCH_ARRAY16_BACKEND",
        backend_default="tpu",
    )


def bench_array_engine_n256_soak() -> dict:
    """BASELINE config 5 (QHB N=256 f=85 sustained) through the array
    engine: full-workload lockstep epochs — 117M delivered messages, 16.7M
    dec-share verifies, 185M hashes each — as a sustained-throughput soak
    point.  Default horizon 10 epochs (config 5 says "sustained";
    CPU-fallback mode shrinks to 1).  Baseline: the corrected N=100
    whole-network model (~0.001 eps) scaled by (256/100)³ ≈ 16.8× →
    ≈ 6e-05 epochs/s single-core."""
    return _bench_array_engine(
        "array_epochs_per_sec_n256_soak",
        n=256,
        epochs=_env_int("BENCH_SOAK_EPOCHS", 10),
        baseline_eps=6e-05,
        dedup=False,
        dynamic=True,
    )


def bench_array_engine_n64_coin() -> dict:
    """BASELINE config 2 as a MACRO config: N=64 f=21 lockstep epochs with
    one REAL common-coin round per BA instance (split-input schedule, so
    ThresholdSign traffic actually executes: batched G2 signs, grouped-RLC
    share verifies, per-receiver f+1 Lagrange combines, parity agreement
    asserted across receivers — engine/_coin_round).  Full per-receiver
    workload; mock backend by default so the row measures the engine +
    accounting (BENCH_COIN_MACRO_BACKEND=tpu for the device path).
    Baseline: N=64 epoch ≈ 260k pairing-verifies (dec + coin) at ~1k/s
    ≈ 0.004 epochs/s single-core."""
    return _bench_array_engine(
        "array_epochs_per_sec_n64_coin",
        n=64,
        epochs=_env_int("BENCH_COIN_MACRO_EPOCHS", 2),
        baseline_eps=0.004,
        dedup=False,
        dynamic=True,
        backend_env="BENCH_COIN_MACRO_BACKEND",
        backend_default="mock",
        coin_rounds=_env_int("BENCH_COIN_ROUNDS", 1),
    )


def _ensure_live_accelerator() -> None:
    """Fall back to CPU if the ambient accelerator hangs.

    The remote-TPU tunnel can die mid-session (observed: a wedged relay
    makes the first device op hang forever while `import jax` still
    succeeds).  Probe device liveness in a SUBPROCESS with a timeout; on
    failure re-exec this benchmark on the CPU platform so every metric
    still produces a (labeled) number instead of the whole run hanging.
    """
    import subprocess

    if os.environ.get("BENCH_PLATFORM_CHECKED"):
        return
    # Fast path: tools/tpu_watch.sh probes the tunnel every 180 s and
    # maintains /tmp/tpu_alive (touched on success, removed on failure)
    # plus /tmp/tpu_status.log.  A fresh watcher DEAD verdict skips the
    # 180 s probe entirely — a dead-tunnel bench run reaches its first
    # row in seconds, not minutes (round-3 verdict Weak #6).  A fresh
    # ALIVE verdict does NOT skip the probe (the tunnel may have died
    # since the watcher's last touch, and the first device op on a dead
    # tunnel hangs forever) — it only shortens the probe timeout.
    # BENCH_PROBE=force always pays the full probe.
    probe_timeout = _env_int("BENCH_PROBE_TIMEOUT", 180)
    if os.environ.get("BENCH_PROBE", "") != "force":
        stale_after = float(os.environ.get("BENCH_WATCH_STALE", "400"))
        now = time.time()
        flag, log = "/tmp/tpu_alive", "/tmp/tpu_status.log"
        try:
            if os.path.exists(flag) and now - os.path.getmtime(flag) < stale_after:
                probe_timeout = _env_int("BENCH_PROBE_FAST_TIMEOUT", 45)
            elif (
                os.path.exists(log)
                and now - os.path.getmtime(log) < stale_after
                and _last_log_line_dead(log)
            ):
                # last line must actually SAY dead: a watcher that was
                # just restarted rm -f's the alive flag before its first
                # probe, and an ALIVE tail must not trigger CPU fallback
                _reexec_on_cpu("watcher-confirmed dead tunnel")
                return  # unreachable (execve), keeps control flow obvious
        except OSError:
            pass  # racing watcher update — fall through to the probe
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax, numpy as np, jax.numpy as jnp;"
                "print(float(np.asarray(jnp.ones((2, 2)) @ jnp.ones((2, 2)))[0][0]))",  # = 2.0
            ],
            capture_output=True,
            text=True,
            timeout=probe_timeout,
        )
        alive = proc.returncode == 0 and "2.0" in proc.stdout
    except subprocess.TimeoutExpired:
        alive = False
    if alive:
        os.environ["BENCH_PLATFORM_CHECKED"] = "1"
        return
    _reexec_on_cpu("accelerator unreachable; re-running on CPU")


def _last_log_line_dead(log: str) -> bool:
    try:
        with open(log, "rb") as f:
            f.seek(0, 2)
            f.seek(max(0, f.tell() - 256))
            lines = f.read().decode("utf-8", "replace").strip().splitlines()
        return bool(lines) and lines[-1].rstrip().endswith("dead")
    except OSError:
        return False


def _reexec_on_cpu(reason: str) -> None:
    print(
        json.dumps({"metric": "bench_note", "error": reason}),
        flush=True,
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_PLATFORM_CHECKED"] = "1"
    env["BENCH_CPU_FALLBACK"] = "1"  # marks rows/shapes as degraded-mode
    # RNS is the global default since the round-4 on-chip A/B settled
    # promotion (rlc_dec 6.0x, CPU kernel 16.7x); the setdefault is kept
    # so degraded re-exec preserves an explicit caller override.
    env.setdefault("HBBFT_TPU_FQ_IMPL", "rns")
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _clear_kernel_caches() -> None:
    """Drop every functools cache holding a jitted/pallas callable so a
    changed HBBFT_TPU_* env var takes effect on the next call."""
    import importlib

    for modname in (
        "hbbft_tpu.ops.backend",
        "hbbft_tpu.ops.fq_pallas",
        "hbbft_tpu.ops.fq_rns_pallas",
        "hbbft_tpu.ops.pairing",
        "hbbft_tpu.ops.curve",
    ):
        try:
            mod = importlib.import_module(modname)
        except Exception:
            continue
        for v in vars(mod).values():
            clear = getattr(v, "cache_clear", None)
            if callable(clear):
                clear()


def _compile_like(e: BaseException) -> bool:
    """Heuristic: does this look like a kernel compile/lowering failure
    (worth retrying on a more conservative path) rather than a
    deterministic bench bug?"""
    if isinstance(e, (AssertionError, KeyError, AttributeError, IndexError)):
        return False
    mod = type(e).__module__ or ""
    if mod.startswith("jax") or mod.startswith("jaxlib"):
        return True
    text = f"{type(e).__name__}: {e}"
    needles = (
        "Mosaic", "mosaic", "pallas", "Pallas", "lowering", "XLA",
        "xla", "INTERNAL", "UNIMPLEMENTED", "RESOURCE_EXHAUSTED",
        "Unsupported", "compil",
    )
    return any(n in text for n in needles)


def _with_fallback(fn):
    """Run a bench metric; on failure retry on progressively more
    conservative kernel paths.

    The Pallas kernels are golden-tested in interpret mode but a first
    Mosaic compile on new hardware can still fail; without this, one
    rejected kernel turns the flagship metric into an error row.  Fallback
    ladder: requested path (stacked kernels + fused pow-chain) →
    HBBFT_TPU_NO_FUSED (forces the fused pow-chain kernel off) →
    HBBFT_TPU_NO_MERGE
    (also unstack the k-pair Miller merge) → pure XLA
    (HBBFT_TPU_NO_PALLAS).  The env is restored afterwards so every
    metric independently attempts (and is labeled with) its own path;
    rungs whose variable was already set on entry are skipped as no-ops."""
    saved = {
        var: os.environ.get(var)
        for var in (
            "HBBFT_TPU_NO_FUSED",
            "HBBFT_TPU_NO_MERGE",
            "HBBFT_TPU_NO_PALLAS",
        )
    }
    changed = False
    try:
        try:
            return fn()
        except Exception as first:
            if not _compile_like(first):
                raise  # a deterministic bench bug; don't triple the cost
            errors = [first]
            for var in saved:
                if saved[var]:
                    continue  # this rung is the config that just failed
                os.environ[var] = "1"
                changed = True
                _clear_kernel_caches()
                try:
                    row = fn()
                    row["fallback"] = var
                    row["fallback_reason"] = repr(first)[:160]
                    return row
                except Exception as e:
                    errors.append(e)
            if len(errors) > 1:
                raise ExceptionGroup("all kernel paths failed", errors)
            raise first
    finally:
        if changed:
            for var, val in saved.items():
                if val is None:
                    os.environ.pop(var, None)
                else:
                    os.environ[var] = val
            _clear_kernel_caches()


class _RowSink:
    """Emit each metric row to stdout AND persist the cumulative row set
    to BENCH_rows.json at the repo root.

    The driver's artifact is a TAIL of stdout; in round 3 that truncated
    8 of 15 rows — including every flagship crypto row — out of the
    official record (verdict Weak #1).  The side file is rewritten after
    every row (crash-safe: a killed run still leaves everything emitted
    so far) and is self-describing: platform, fallback mode, fq impl,
    and a wall-clock stamp per run."""

    PATH = os.environ.get(
        "BENCH_ROWS_PATH",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_rows.json"),
    )

    def __init__(self, platform: str) -> None:
        self.rows = []
        self.meta = {
            "platform": platform,
            "cpu_fallback": bool(os.environ.get("BENCH_CPU_FALLBACK")),
            "fq_impl": os.environ.get("HBBFT_TPU_FQ_IMPL", "rns"),
            "started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "bench_only": os.environ.get("BENCH_ONLY") or None,
        }

    def emit(self, row: dict) -> None:
        print(json.dumps(row), flush=True)
        self.rows.append(row)
        try:
            with open(self.PATH + ".tmp", "w") as f:
                json.dump({"meta": self.meta, "rows": self.rows}, f, indent=1)
            os.replace(self.PATH + ".tmp", self.PATH)
        except OSError:
            pass  # a read-only checkout must not kill the bench


def bench_array_engine_n100_tpu() -> dict:
    """The NORTH STAR at its defined shape inside a DRIVER run: N=100 f=33
    real-crypto (TpuBackend) epochs + one era change.  The window runbook
    runs the same shape via BENCH_ONLY=array_n100 + env; this entry exists
    so the driver-visible artifact itself carries a real-crypto N=100 row
    (round-4 verdict Missing #5 / task 8) — epochs default small (3) so the
    row lands inside the driver's timeout; BENCH_N100_TPU_EPOCHS raises it.
    Skipped off-TPU (XLA:CPU measured ~55 min/epoch at N=64)."""
    overrides = {
        "BENCH_ARRAY_BACKEND": "tpu",
        "BENCH_ARRAY_EPOCHS": os.environ.get("BENCH_N100_TPU_EPOCHS", "3"),
        "BENCH_ARRAY_CHURN": os.environ.get("BENCH_N100_TPU_CHURN", "1"),
    }
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        return bench_array_engine_n100()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def bench_mesh_scaling() -> dict:
    """PR 18 scale-out row: the per-device pipelined dispatcher
    (parallel/shardpipe.py) across virtual CPU meshes of 1/2/4/8 devices.
    Each mesh size runs in its own subprocess (tools/mesh_probe.py — the
    XLA host-platform device count is fixed at JAX init) and reports
    STRUCTURAL facts: deterministic round-robin placements, balanced
    per-device dispatch tallies, imbalance 1.0 for divisible chunk
    counts, bit-correct results.  Virtual-mesh chunks/s is NOT a
    scale-out measurement (host devices share the physical cores —
    PERF.md round 14); the real-mesh number comes from the window
    runbook's mesh_scaling step.  Knobs: BENCH_MESH_SIZES /
    BENCH_MESH_CHUNKS / BENCH_MESH_LANES."""
    import subprocess

    sizes = [
        int(x)
        for x in os.environ.get("BENCH_MESH_SIZES", "1,2,4,8").split(",")
    ]
    chunks = int(os.environ.get("BENCH_MESH_CHUNKS", "64"))
    probe = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools", "mesh_probe.py"
    )
    t0 = time.perf_counter()
    meshes = []
    failed = []
    for k in sizes:
        env = dict(os.environ)
        env["BENCH_MESH_DEVICES"] = str(k)
        env["BENCH_MESH_CHUNKS"] = str(chunks)
        # the probe pins its own device count and forces JAX_PLATFORMS=cpu
        env.pop("BENCH_ONLY", None)
        proc = subprocess.run(
            [sys.executable, probe],
            env=env,
            capture_output=True,
            text=True,
            timeout=300,
        )
        if proc.returncode != 0 or not proc.stdout.strip():
            failed.append({"devices": k, "error": proc.stderr[-500:]})
            continue
        meshes.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    dt = time.perf_counter() - t0
    structural_ok = bool(meshes) and not failed and all(
        m["placements_ok"] and m["balanced"] and m["results_ok"]
        for m in meshes
    )
    widest = meshes[-1] if meshes else {}
    return {
        "metric": "mesh_scaling",
        "value": widest.get("chunks_per_s", 0.0),
        "unit": f"chunks/s@{widest.get('devices', 0)}dev",
        "vs_baseline": 1.0,
        "baseline": "estimated",
        "virtual_mesh": True,  # structural only — never a scale-out claim
        "chunks": chunks,
        "meshes": meshes,
        "all_ok": structural_ok,
        "imbalance_max": max((m["imbalance"] for m in meshes), default=0.0),
        "failed": failed,
        "wall_s": round(dt, 2),
    }


# Rough per-bench wall-cost estimates on TPU, seconds (measured: round-4
# window logs — step 2's seven rows took ~17 min incl. compiles; n100
# real-crypto per-epoch from the round-5 step-4 capture).  Used only by
# budget mode to decide what still fits; deliberately pessimistic.
_BENCH_EST_S = {
    "rlc_dec": 180, "share_verify": 150, "rlc_sig": 150, "g2_sign": 150,
    "coin_e2e": 240, "rlc_dec_adversarial": 150, "array_n16_tpu": 420,
    "array_n100_tpu": 1200, "rs_encode": 120, "rs_host": 60,
    "rs_plane_ab": 180,
    "fused_chain_ab": 240,
    "fq_kernel": 240, "n4": 60, "n4_realcrypto": 300, "n100": 420,
    "array_n256_soak": 300, "array_n100_dedup": 120, "array_n64_coin": 240,
    "array_n100": 300, "glv_ladder": 180, "adv_matrix": 600,
    "scenario_matrix": 60, "qhb_traffic": 420, "crash_matrix": 120,
    "slo_traffic": 420, "mesh_scaling": 120,
}


def _plan_benches(only, platform: str, budget: float) -> list:
    """Ordered (name, fn) bench list.

    No budget → SUPPORT-FIRST order: the driver records a tail of stdout,
    which in round 3 truncated the flagship crypto rows out of
    BENCH_r03.json (verdict Weak #1); flagship rows print last.

    Budget set → FLAGSHIP-FIRST order: round 4's driver run hit its
    timeout (rc=124) before the support-first ordering reached the
    flagship rows, so the artifact carried none of them (verdict Weak #3).
    Under a budget the valuable rows run FIRST (BENCH_rows.json preserves
    them whatever happens to stdout), and the runner skips any later bench
    whose cost estimate no longer fits.
    """
    arrays = os.environ.get("BENCH_ARRAY", "1") != "0"
    n4 = os.environ.get("BENCH_N4", "1") != "0"
    n100 = os.environ.get("BENCH_N100", "1") != "0"
    soak = os.environ.get("BENCH_SOAK", "1") != "0"
    fqk = os.environ.get("BENCH_FQ", "1") != "0"

    if budget:
        plan = [
            ("rlc_dec", bench_rlc_dec),
            ("share_verify", bench_share_verify),
            ("rlc_sig", bench_rlc_sig),
            ("g2_sign", bench_g2_sign),
            ("coin_e2e", bench_coin_e2e),
            ("rlc_dec_adversarial", bench_rlc_dec_adversarial),
            ("adv_matrix", bench_adv_matrix),
        ]
        if arrays:
            plan.append(("array_n16_tpu", bench_array_engine_n16_tpu))
            if platform == "tpu":
                plan.append(("array_n100_tpu", bench_array_engine_n100_tpu))
        # diagnostic A/B rows — after the flagship prefix, before support
        # (fused_chain_ab is the PR-20 device-chain A/B: it must survive
        # a budget timeout, so it rides directly behind the flagships)
        plan.append(("fused_chain_ab", bench_fused_chain_ab))
        plan.append(("glv_ladder", bench_glv_ladder))
        plan.append(("scenario_matrix", bench_scenario_matrix))
        plan.append(("crash_matrix", bench_crash_matrix))
        # per-device dispatcher structure row (PR 18) — cheap, ahead of
        # the traffic curves so a timeout still captures it
        plan.append(("mesh_scaling", bench_mesh_scaling))
        # traffic curve: new measured axis, ahead of the support rows
        plan.append(("qhb_traffic", bench_qhb_traffic))
        # control plane: the adaptive-vs-fixed-B SLO row rides with it
        plan.append(("slo_traffic", bench_slo_traffic))
        plan += [
            ("rs_encode", bench_rs_encode),
            ("rs_host", bench_rs_host),
            ("rs_plane_ab", bench_rs_plane_ab),
        ]
        if fqk:
            plan.append(("fq_kernel", bench_fq_kernel))
        if n4:
            plan.append(("n4", bench_epochs_n4))
            plan.append(("n4_realcrypto", bench_epochs_n4_realcrypto))
        if n100:
            plan.append(("n100", bench_epochs_n100))
        if soak:
            plan.append(("array_n256_soak", bench_array_engine_n256_soak))
        if arrays:
            plan.append(("array_n100_dedup", bench_array_engine_n100_dedup))
            plan.append(("array_n64_coin", bench_array_engine_n64_coin))
            plan.append(("array_n100", bench_array_engine_n100))
    else:
        # Legacy support-first print order, identical to rounds 1-4
        # (flagships last, mock north star at the very end).
        plan = [
            ("rs_encode", bench_rs_encode),
            ("rs_host", bench_rs_host),
            ("rs_plane_ab", bench_rs_plane_ab),
            ("fused_chain_ab", bench_fused_chain_ab),
            ("share_verify", bench_share_verify),
        ]
        if n4:
            plan.append(("n4", bench_epochs_n4))
            plan.append(("n4_realcrypto", bench_epochs_n4_realcrypto))
        if n100:
            plan.append(("n100", bench_epochs_n100))
        if soak:
            plan.append(("array_n256_soak", bench_array_engine_n256_soak))
        if arrays:
            plan.append(("array_n100_dedup", bench_array_engine_n100_dedup))
        plan += [
            ("rlc_sig", bench_rlc_sig),
            ("g2_sign", bench_g2_sign),
            ("coin_e2e", bench_coin_e2e),
            ("rlc_dec_adversarial", bench_rlc_dec_adversarial),
            ("adv_matrix", bench_adv_matrix),
            ("scenario_matrix", bench_scenario_matrix),
            ("crash_matrix", bench_crash_matrix),
            ("mesh_scaling", bench_mesh_scaling),
            ("qhb_traffic", bench_qhb_traffic),
            ("slo_traffic", bench_slo_traffic),
            ("glv_ladder", bench_glv_ladder),
        ]
        if fqk:
            plan.append(("fq_kernel", bench_fq_kernel))
        plan.append(("rlc_dec", bench_rlc_dec))
        if arrays:
            plan.append(("array_n16_tpu", bench_array_engine_n16_tpu))
            plan.append(("array_n64_coin", bench_array_engine_n64_coin))
            plan.append(("array_n100", bench_array_engine_n100))
        # array_n100_tpu is reachable without a budget only by request
        plan.append(("array_n100_tpu", bench_array_engine_n100_tpu))
    if only is not None:
        # an explicit request overrides the platform gate (budget branch
        # only adds the row on tpu)
        if "array_n100_tpu" in only and "array_n100_tpu" not in {
            n for n, _ in plan
        }:
            plan.append(("array_n100_tpu", bench_array_engine_n100_tpu))
        plan = [(n, f) for (n, f) in plan if n in only]
    else:
        plan = [(n, f) for (n, f) in plan if n != "array_n100_tpu" or budget]
    return plan


def main() -> None:
    _ensure_live_accelerator()
    if os.environ.get("BENCH_ONLY"):
        only = set(os.environ["BENCH_ONLY"].split(","))
    else:
        only = None

    from hbbft_tpu.utils.jax_config import enable_compile_cache, raise_stack_limit

    enable_compile_cache()
    raise_stack_limit()  # XLA:CPU LLVM recursion vs the 8 MB default stack

    import jax

    platform = jax.default_backend()
    cpu_fallback = bool(os.environ.get("BENCH_CPU_FALLBACK"))
    # Time budget (verdict r4 Weak #3): BENCH_BUDGET=<seconds> switches to
    # flagship-first ordering and skips benches that no longer fit.  A
    # driver-style run (full row set on real TPU, no BENCH_ONLY) gets a
    # DEFAULT budget — round 4's driver bench was rc=124-killed with zero
    # flagship rows in the artifact; never again.  BENCH_BUDGET=0 disables.
    budget_env = os.environ.get("BENCH_BUDGET")
    if budget_env is not None:
        budget = float(budget_env)
    elif only is None and platform == "tpu" and not cpu_fallback:
        budget = 3000.0
    else:
        budget = 0.0
    t_start = time.monotonic()
    sink = _RowSink(platform)
    if budget:
        sink.meta["budget_seconds"] = budget
    if os.environ.get("BENCH_ARRAY_DEDUP"):
        sink.emit(
            {
                "metric": "bench_note",
                "note": "BENCH_ARRAY_DEDUP no longer affects "
                "array_epochs_per_sec_n100; the memoizing variant is "
                "its own row (array_epochs_per_sec_n100_dedup)",
            }
        )
    if cpu_fallback:
        # Accelerator unreachable (dead tunnel → _ensure_live_accelerator
        # re-exec'd us on CPU): shrink shapes/iters so every metric still
        # reports a labeled number without half-hour XLA:CPU compiles —
        # and without the big-graph XLA:CPU segfault risk (PERF.md).
        # Deliberate CPU runs (user-set JAX_PLATFORMS=cpu) keep full
        # shapes; rows below embed batch/groups so shrinkage is visible.
        for var, val in (
            ("BENCH_ITERS", "1"),
            ("BENCH_RS_ITERS", "2"),
            ("BENCH_BATCH", "32"),
            ("BENCH_RLC_GROUPS", "8"),
            ("BENCH_RLC_K", "8"),
            ("BENCH_DEC_GROUPS", "8"),
            ("BENCH_SIGN_BATCH", "64"),
            ("BENCH_RS_SHARD", "4096"),
            ("BENCH_COIN_FLIPS", "8"),
            ("BENCH_COIN_N", "16"),
            ("BENCH_ADV_GROUPS", "8"),
            ("BENCH_ADV_K", "8"),
            ("BENCH_N4RC_EPOCHS", "1"),
            ("BENCH_ARRAY_EPOCHS", "2"),
            ("BENCH_SOAK_EPOCHS", "1"),
            ("BENCH_COIN_MACRO_EPOCHS", "1"),
            ("BENCH_ARRAY_CHURN", "0"),
            ("BENCH_FQ_LANES", "4096"),
            ("BENCH_FQ_CHAIN", "50"),
            ("BENCH_QHB_EPOCHS", "2"),
            ("BENCH_QHB_BATCHES", "8,32"),
            ("BENCH_QHB_N100", "0"),
        ):
            os.environ.setdefault(var, val)
    for name, fn in _plan_benches(only, platform, budget):
        elapsed = time.monotonic() - t_start
        if budget and name == "array_n100_tpu":
            # Adaptive epoch count instead of the generic estimate skip:
            # fill ~70% of what's left (compile + warm epoch eat the
            # rest), floor 1, cap at the env/default.  Per-epoch cost
            # from the round-5 step-4 on-chip capture (_BENCH_EST_S).
            per_epoch = float(os.environ.get("BENCH_N100_TPU_EPOCH_EST", "250"))
            fit = int((budget - elapsed) * 0.7 / per_epoch)
            if fit < 1:
                sink.emit(
                    {
                        "metric": name,
                        "skipped": "budget exhausted "
                        f"({elapsed:.0f}s elapsed of {budget:.0f}s; "
                        f"needs ~{per_epoch * 1.5:.0f}s for one epoch)",
                        "platform": platform,
                    }
                )
                continue
            want = _env_int("BENCH_N100_TPU_EPOCHS", 3)
            os.environ["BENCH_N100_TPU_EPOCHS"] = str(max(1, min(want, fit)))
        elif budget and elapsed + _BENCH_EST_S.get(name, 120) > budget:
            sink.emit(
                {
                    "metric": name,
                    "skipped": "budget exhausted "
                    f"({elapsed:.0f}s elapsed of {budget:.0f}s; "
                    f"estimate {_BENCH_EST_S.get(name, 120)}s)",
                    "platform": platform,
                }
            )
            continue
        if (
            name == "array_n16_tpu"
            and platform == "cpu"
            and not os.environ.get("BENCH_ARRAY16_BACKEND")
        ):
            # TpuBackend on XLA:CPU costs ~50 min of compiles cold (3 min
            # with a warm persistent cache — measured 2026-08-01); too
            # risky for the driver's budget, so the degraded-mode row is
            # captured out-of-band instead: see
            # artifacts/BENCH_cpu_n16_realcrypto_r04.json (0.0755
            # epochs/s real crypto, device 11.6 s/epoch of the 13.2).
            # Set BENCH_ARRAY16_BACKEND=tpu to force the attempt.
            sink.emit(
                {
                    "metric": ARRAY_N16_METRIC,
                    "skipped": "accelerator unavailable"
                    " (CPU measurement: artifacts/"
                    "BENCH_cpu_n16_realcrypto_r04.json)",
                    "platform": platform,
                }
            )
            continue
        try:
            t_row = time.monotonic()
            row = _with_fallback(fn)
            row["row_seconds"] = round(time.monotonic() - t_row, 1)
            row["platform"] = platform
            fq_impl = os.environ.get("HBBFT_TPU_FQ_IMPL", "rns")
            # label every row whose bench executes the Fq facade (mock
            # macros and the GF(2^8) RS row never touch field code) —
            # including the limb arm, so A/B artifacts are per-row
            # self-describing (ADVICE r4 low #3)
            backend_name = str(row.get("backend", ""))
            uses_fq = (
                name in _FQ_ROWS
                or backend_name == "TpuBackend"
                or backend_name.startswith("MeshBackend")
            )
            if uses_fq:
                row["fq_impl"] = fq_impl
            if backend_name == "MockBackend" and "vs_baseline" in row:
                # the estimated baselines are real-crypto cost models; a
                # mock-backend macro beating them measures no crypto
                # (round-3 verdict Weak #2) — keep the ratio for trend
                # tracking but under a name no skimming reader mistakes
                row["vs_baseline_mock_runtime"] = row.pop("vs_baseline")
                row["baseline_comparable"] = False
            sink.emit(row)
        except Exception as e:  # one dead bench must not kill the others
            sink.emit({"metric": name, "error": repr(e)[:200]})


if __name__ == "__main__":
    main()
