#!/usr/bin/env python
"""Composed-gauntlet soak CLI (net/scenarios.py Cell runner).

Runs multi-epoch deterministic soaks over the full cell product —
attack × net-schedule × churn-schedule × crash-schedule × traffic-source
— and gates each cell on the gauntlet verdicts: honest Batches
bit-identical, every fault attributed to a faulty node, restarted nodes
recommitted within the gate, why_stalled naming the dominant cause in
every stalled cell, and p99 commit latency bounded vs the clean cell.

Usage::

    python tools/soak.py                         # default composed suite
    python tools/soak.py --smoke                 # ~2 s deterministic cell (CI)
    python tools/soak.py --flagship              # N=16 x 200-epoch acceptance
                                                 # cell, two seeds (slow)
    python tools/soak.py --cells equivocate:partition_heal:era_flip:one_restart:one_x \
        --n 16 --epochs 200 --seeds 1,2
    python tools/soak.py --json /tmp/soak.json --fail-dir /tmp/failed
    python tools/soak.py --replay /tmp/failed/<cell>.json   # reproduce a
                                                 # failed cell from its
                                                 # record (cell + seed +
                                                 # fingerprint) alone
    python tools/soak.py --race-cex /tmp/cx.json # fold a race-explorer
                                                 # minimized counterexample
                                                 # in as a first-class cell

Cell syntax: ``attack:schedule[:churn[:crash[:traffic]]]`` with names
from the net/scenarios.py registries (missing axes default to "none").

Exit status: 0 when every cell passed its verdict; 1 when any failed
(failed cells are written to --fail-dir as replayable records); 2 when a
--replay did not reproduce the recorded fingerprint.

Pure CPU / no JAX: cells run MockBackend protocol math.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from hbbft_tpu.net.scenarios import (  # noqa: E402
    ATTACKS,
    CHURNS,
    CRASHES,
    SCHEDULES,
    TRAFFICS,
    Cell,
    run_cell,
)

#: p99 bound vs the clean cell: composed conditions (a 30·N²-crank
#: partition, an outage, overload) legitimately stretch the tail; beyond
#: this multiple the degradation is no longer "bounded" and the cell
#: fails.  Calibration: the N=16 flagship cell sits ~8x its clean
#: baseline (partition dominates); 12x leaves headroom without letting
#: an unbounded tail pass.
P99_MULT = 12.0

#: the default composed suite (fast shapes; the flagship arm is opt-in)
DEFAULT_SUITE = (
    "equivocate:partition_heal:era_flip:one_restart:one_x",
    "crafted_shares:wan:era_flip:two_restarts:two_x",
    "replay_flood:lan:none:one_restart:half_x",
    "withhold_shares:uniform:era_flip:one_restart:one_x",
    "withhold_echo:lossy:none:one_restart:none",
    # control plane in the loop (PR 12): the SLO-driven adaptive batch
    # controller under the 10x-swing trace composed with churn + a
    # crash/restart — B updates are input-borne, so the restarted
    # node's WAL replay reproduces the exact B history (the b_trace is
    # folded into the cell fingerprint)
    "equivocate:uniform:era_flip:one_restart:swing_adaptive",
)

#: the acceptance-criteria cell (ISSUE 11): equivocator x partition-heal
#: x churn x one crash+restart x 1x traffic at N=16, >=200 epochs
FLAGSHIP = "equivocate:partition_heal:era_flip:one_restart:one_x"

#: --smoke-fail crank budget: the smoke cell (n=5, 12 epochs, seed 3)
#: runs ~9000 cranks with its injected restart at crank ~3770; cutting
#: at 4200 kills the run deterministically just AFTER the restart, so
#: the auto-dumped forensics bundle's window names the recovery.
SMOKE_FAIL_CRANKS = 4200


def parse_cell_spec(spec: str, n: int, epochs: int, seed: int,
                    batch_size: int) -> Cell:
    parts = spec.split(":")
    if not 2 <= len(parts) <= 5:
        raise SystemExit(f"bad cell spec {spec!r} (attack:schedule[:churn[:crash[:traffic]]])")
    parts = parts + ["none"] * (5 - len(parts))
    attack, schedule, churn, crash, traffic = parts
    for name, registry, label in (
        (attack, ATTACKS, "attack"),
        (schedule, SCHEDULES, "schedule"),
        (churn, CHURNS, "churn"),
        (crash, CRASHES, "crash"),
        (traffic, TRAFFICS, "traffic"),
    ):
        if name not in registry:
            raise SystemExit(
                f"unknown {label} {name!r}; known: {sorted(registry)}"
            )
    return Cell(
        attack=attack, schedule=schedule, churn=churn, crash=crash,
        traffic=traffic, n=n, epochs=epochs, seed=seed,
        batch_size=batch_size,
    )


def clean_cell_for(cell: Cell) -> Cell:
    """The p99 baseline: same shape and traffic, every hostile axis off."""
    return Cell(
        attack="passive", schedule="uniform", churn="none", crash="none",
        traffic=cell.traffic, n=cell.n, epochs=cell.epochs, seed=cell.seed,
        batch_size=cell.batch_size,
    )


def run_one(cell: Cell, clean_p99: dict, crank_limit: int) -> dict:
    """Run a cell (and lazily its clean baseline for the p99 gate)."""
    t0 = time.perf_counter()
    r = run_cell(cell, crank_limit=crank_limit)
    row = r.row()
    if r.forensics is not None:
        # hidden key: write_failed writes it next to the replay record;
        # stripped before any JSON row dump
        row["_forensics"] = r.forensics
    row["wall_s"] = round(time.perf_counter() - t0, 3)
    row["p99_ok"] = True
    if r.commit_p99 and cell.traffic != "none":
        key = (cell.traffic, cell.n, cell.epochs, cell.seed)
        if key not in clean_p99:
            base = run_cell(clean_cell_for(cell), crank_limit=crank_limit)
            clean_p99[key] = base.commit_p99 or 0.0
        base_p99 = clean_p99[key]
        row["clean_p99"] = base_p99
        row["p99_ok"] = (not base_p99) or r.commit_p99 <= base_p99 * P99_MULT
    row["ok"] = bool(row["ok"] and row["p99_ok"])
    return row


def run_race_cex(path: str) -> dict:
    """A race-explorer minimized counterexample as a first-class cell:
    the cell passes when the seams no longer diverge on the recorded
    schedule (a reproduced divergence is a deterministic, still-open
    failure — reported with the recorded vs observed fingerprints)."""
    from hbbft_tpu.analysis import schedules

    t0 = time.perf_counter()
    rep = schedules.replay_counterexample(path)
    return {
        "cell": f"race-cex:{Path(path).name}",
        "kind": "race_counterexample",
        "ok": not rep["diverged"],
        "diverged": rep["diverged"],
        "reproduced": rep["reproduced"],
        "first_divergence": rep["first_divergence"],
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def write_failed(fail_dir: str, cell: Cell, row: dict):
    """A replayable failed-cell record: the cell (with its seed) + the
    observed fingerprint — everything --replay needs.  When the run
    auto-dumped a forensics bundle (row["_forensics"], from the flight
    recorder) it lands next to the record as <cell>.forensics.json.
    Returns (record_path, bundle_path_or_None)."""
    from hbbft_tpu.obs.flight import write_bundle

    p = Path(fail_dir)
    p.mkdir(parents=True, exist_ok=True)
    bundle = row.pop("_forensics", None)
    out = p / f"{cell.cell_id()}.json"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(
            {"version": 1, "cell": cell.to_dict(), "fingerprint": row["fingerprint"], "row": row},
            f, indent=2, sort_keys=True, default=repr,
        )
        f.write("\n")
    bpath = None
    if bundle is not None:
        bpath = str(p / f"{cell.cell_id()}.forensics.json")
        write_bundle(bundle, bpath)
    return str(out), bpath


def replay_record(path: str, crank_limit: int) -> int:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    cell = Cell.from_dict(doc["cell"])
    r = run_cell(cell, crank_limit=crank_limit)
    fp = r.fingerprint()
    match = fp == doc["fingerprint"]
    print(
        f"replay: {cell.cell_id()} ok={r.ok} "
        f"fingerprint={'REPRODUCED' if match else 'DIVERGED'}"
    )
    if not match:
        print(f"  recorded {doc['fingerprint']}")
        print(f"  observed {fp}")
    return 0 if match else 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cells", nargs="*", default=None,
                    help="cell specs attack:schedule[:churn[:crash[:traffic]]]")
    ap.add_argument("--smoke", action="store_true",
                    help="one fast composed cell, run twice, fingerprint-stable (CI)")
    ap.add_argument("--smoke-fail", action="store_true",
                    help="kill the smoke cell mid-flight (deterministic crank cut) "
                         "and gate on the auto-dumped forensics bundle (CI)")
    ap.add_argument("--flagship", action="store_true",
                    help="the N=16 x 200-epoch acceptance cell, two seeds (slow)")
    ap.add_argument("--n", type=int, default=5)
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=3)
    ap.add_argument("--seeds", default="1",
                    help="comma-separated seeds (each cell runs per seed)")
    ap.add_argument("--crank-limit", type=int, default=50_000_000)
    ap.add_argument("--json", help="write all cell rows here")
    ap.add_argument("--fail-dir", default="/tmp/hbbft_soak_failed",
                    help="replayable records of failed cells land here")
    ap.add_argument("--replay", help="re-run a failed-cell record; exit 2 on fingerprint mismatch")
    ap.add_argument("--race-cex", nargs="*", default=(),
                    help="race-explorer counterexample files to fold in as cells")
    args = ap.parse_args(argv)

    if args.replay:
        return replay_record(args.replay, args.crank_limit)

    if args.smoke_fail:
        # the forensics round-trip smoke: a seeded cell dies at a pinned
        # crank (after its injected restart), must auto-emit a valid
        # bundle, and the record+bundle land in --fail-dir.  Transcript
        # is deterministic (no wall times) — ci.sh asserts on it.
        from hbbft_tpu.obs.flight import validate_bundle

        cell = parse_cell_spec(FLAGSHIP, n=5, epochs=12, seed=3, batch_size=3)
        r = run_cell(cell, crank_limit=SMOKE_FAIL_CRANKS)
        row = r.row()
        if r.forensics is not None:
            row["_forensics"] = r.forensics
        errs = (
            validate_bundle(r.forensics)
            if r.forensics is not None
            else ["no forensics bundle emitted"]
        )
        rec, bpath = write_failed(args.fail_dir, cell, row)
        gate = (r.forensics or {}).get("critical_path", {}).get("gate")
        print(
            f"soak: smoke-fail {cell.cell_id()} failed={not r.ok} "
            f"bundle={'valid' if not errs else 'INVALID'} gate={gate!r}"
        )
        print(f"soak:      replay record -> {rec}")
        if bpath:
            print(f"soak:      forensics bundle -> {bpath}")
        for e in errs:
            print(f"soak:      bundle error: {e}")
        return 0 if (not r.ok and bpath and not errs) else 1

    rows = []
    rc = 0
    clean_p99: dict = {}

    if args.smoke:
        cell = parse_cell_spec(FLAGSHIP, n=5, epochs=12, seed=3, batch_size=3)
        row = run_one(cell, clean_p99, args.crank_limit)
        again = run_cell(cell, crank_limit=args.crank_limit)
        row["fingerprint_stable"] = again.fingerprint() == row["fingerprint"]
        row["ok"] = bool(row["ok"] and row["fingerprint_stable"])
        rows.append(row)
    elif args.flagship:
        for seed in (int(s) for s in args.seeds.split(",")):
            cell = parse_cell_spec(
                FLAGSHIP, n=16, epochs=max(args.epochs, 200), seed=seed,
                batch_size=args.batch_size,
            )
            row = run_one(cell, clean_p99, args.crank_limit)
            again = run_cell(cell, crank_limit=args.crank_limit)
            row["fingerprint_stable"] = again.fingerprint() == row["fingerprint"]
            row["ok"] = bool(row["ok"] and row["fingerprint_stable"])
            rows.append(row)
    else:
        specs = args.cells if args.cells else list(DEFAULT_SUITE)
        for spec in specs:
            for seed in (int(s) for s in args.seeds.split(",")):
                cell = parse_cell_spec(
                    spec, n=args.n, epochs=args.epochs, seed=seed,
                    batch_size=args.batch_size,
                )
                rows.append(run_one(cell, clean_p99, args.crank_limit))

    for path in args.race_cex:
        rows.append(run_race_cex(path))

    for row in rows:
        ok = row["ok"]
        name = row["cell"]
        extra = ""
        if row.get("kind") == "race_counterexample":
            extra = f" diverged={row['diverged']} reproduced={row['reproduced']}"
        else:
            extra = (
                f" epochs={row.get('epochs_committed')}"
                f" eras={row.get('eras')}"
                f" crashes={row.get('crashes')}/{row.get('restarts')}"
                f" tx={row.get('tx_committed')} p99={row.get('commit_p99')}"
            )
            if not row.get("p99_ok", True):
                extra += f" P99-UNBOUNDED(clean={row.get('clean_p99')})"
            if "fingerprint_stable" in row:
                extra += f" stable={row['fingerprint_stable']}"
            if row.get("error"):
                extra += f" error={row['error']!r}"
        # --smoke feeds tools/ci.sh, whose transcript is asserted
        # identical across runs — wall time stays in the JSON rows only
        wall = "" if args.smoke else f" ({row['wall_s']}s)"
        print(f"soak: {'ok  ' if ok else 'FAIL'} {name}{extra}{wall}")
        if not ok:
            rc = 1
            if "fingerprint" in row:
                cell = Cell.from_dict({k: row[k] for k in Cell.__dataclass_fields__ if k in row})
                rec, bpath = write_failed(args.fail_dir, cell, row)
                print(f"soak:      replay record -> {rec}")
                if bpath:
                    print(f"soak:      forensics bundle -> {bpath}")

    if args.json:
        # hidden evidence keys (full forensics bundles) stay out of the
        # row dump — they live as standalone .forensics.json files
        slim = [{k: v for k, v in row.items() if not k.startswith("_")} for row in rows]
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump({"rows": slim}, f, indent=2, sort_keys=True, default=repr)
            f.write("\n")
    print(f"soak: {sum(1 for r in rows if r['ok'])}/{len(rows)} cells ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
