"""Compile a TPU-window snapshot directory into readable tables.

The runbook (tools/tpu_window.sh) copies BENCH_rows.json into
``rows_after_<step>.json`` after every step; this tool turns that
directory into (a) a step-by-step metric table and (b) the cross-impl
matrix (metrics x fq_impl with per-trial values) — the analysis the
round-5 PERF.md sections were built from, automated for round 6.

    python tools/analyze_window.py [tpu_window_r05]
"""

from __future__ import annotations

import json
import os
import sys
from collections import defaultdict


def load_rows(art_dir: str):
    out = []
    for name in sorted(os.listdir(art_dir)):
        if not (name.startswith("rows_after_") and name.endswith(".json")):
            continue
        step = name[len("rows_after_") : -len(".json")]
        try:
            with open(os.path.join(art_dir, name)) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"!! {name}: unreadable ({e})")
            continue
        for row in data.get("rows", []):
            out.append((step, data.get("meta", {}), row))
    return out


def main() -> None:
    art = sys.argv[1] if len(sys.argv) > 1 else "tpu_window_r05"
    rows = load_rows(art)
    if not rows:
        print(f"no snapshots under {art}/")
        return

    print(f"== {art}: step-by-step ==")
    for step, meta, row in rows:
        metric = row.get("metric", "?")
        if "value" in row:
            extras = " ".join(
                f"{k}={row[k]}"
                for k in ("n", "epochs", "churn_epochs", "flips", "fq_impl",
                          "backend", "era_change_seconds", "row_seconds")
                if k in row
            )
            print(f"{step:22s} {metric:38s} {row['value']:>12} "
                  f"{row.get('unit', ''):12s} {extras}")
        else:
            why = row.get("error") or row.get("skipped") or "?"
            print(f"{step:22s} {metric:38s} {'—':>12} FAILED: {str(why)[:60]}")

    # cross-impl matrix over the matrix_* trials
    matrix = defaultdict(dict)  # metric -> trial -> value
    for step, meta, row in rows:
        if step.startswith("matrix_") and "value" in row:
            matrix[row["metric"]][step[len("matrix_"):]] = row["value"]
    if matrix:
        trials = sorted({t for m in matrix.values() for t in m})
        print(f"\n== cross-impl matrix ==")
        print(f"{'metric':38s}" + "".join(f"{t:>12s}" for t in trials))
        for metric, per in sorted(matrix.items()):
            print(f"{metric:38s}" + "".join(
                f"{per.get(t, float('nan')):>12.1f}" for t in trials))

    # device-time attribution for macro rows that carry it
    print("\n== macro attribution (s/epoch) ==")
    for step, meta, row in rows:
        if "device_seconds_per_epoch" not in row:
            continue
        kinds = {
            k[len("device_seconds_"):-len("_per_epoch")]: v
            for k, v in row.items()
            if k.startswith("device_seconds_") and k.endswith("_per_epoch")
            and k != "device_seconds_per_epoch"
        }
        total = 1.0 / row["value"] if row.get("value") else float("nan")
        print(f"{step}: n={row.get('n')} total={total:.1f} "
              f"device={row['device_seconds_per_epoch']} "
              f"hash={row.get('hash_g2_seconds_per_epoch', 0)} "
              f"kinds={kinds}")


if __name__ == "__main__":
    main()
