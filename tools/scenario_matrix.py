"""scenario_matrix — sweep the adversary × network-schedule matrix.

Runs every cell of the attack × schedule × N matrix (net/scenarios.py)
over MockBackend, printing a PASS/FAIL table with per-cell fault-kind
counts, and optionally writing the full row set as JSON.  A failed cell
prints its why-stalled one-liner (the attack + partition the stall
reporter named).

    python tools/scenario_matrix.py                    # N in {4, 7, 16}
    python tools/scenario_matrix.py --ns 4,7 --epochs 2
    python tools/scenario_matrix.py --attacks equivocate,crafted_shares \
        --schedules uniform,partition_heal --json matrix.json
    python tools/scenario_matrix.py --n100   # the slow N=100/f=33 arm

Exit code 1 when any cell fails — usable as a CI gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.net.scenarios import (  # noqa: E402
    MATRIX_ATTACKS,
    MATRIX_SCHEDULES,
    run_matrix,
    run_scenario,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--ns", default="4,7,16", help="comma-separated N values")
    p.add_argument(
        "--attacks", default=",".join(MATRIX_ATTACKS),
        help="comma-separated attack names",
    )
    p.add_argument(
        "--schedules", default=",".join(MATRIX_SCHEDULES),
        help="comma-separated schedule names",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument(
        "--scheduler", default="random", choices=("random", "first"),
        help="VirtualNet delivery scheduler",
    )
    p.add_argument(
        "--n100", action="store_true",
        help="run the slow N=100/f=33 arm (crafted_shares + equivocate "
        "under partition_heal) instead of the matrix",
    )
    p.add_argument("--json", default=None, help="write rows to this path")
    p.add_argument(
        "--fail-dir", default=None,
        help="write each failed cell's forensics bundle (flight-recorder "
        "dump) here as <attack>x<schedule>@n<N>.forensics.json",
    )
    args = p.parse_args(argv)

    if args.n100:
        # uniform delivery: the schedule layer is cheap per message
        # (heap ops + rng draws) but the N=100 epoch moves ~4M messages
        # through an already ~16-minute cell; network-condition coverage
        # at width lives in the N=16 matrix
        results = [
            run_scenario(
                attack, "uniform", 100, f=33,
                seed=args.seed, epochs=1, scheduler=args.scheduler,
                crank_limit=50_000_000,
            )
            for attack in ("crafted_shares", "equivocate")
        ]
    else:
        results = run_matrix(
            ns=tuple(int(x) for x in args.ns.split(",")),
            attacks=tuple(args.attacks.split(",")),
            schedules=tuple(args.schedules.split(",")),
            seed=args.seed,
            epochs=args.epochs,
            scheduler=args.scheduler,
        )

    wide = max(len(r.attack) for r in results)
    print(
        f"{'attack':>{wide}} {'schedule':>15} {'n':>4} {'ok':>4} "
        f"{'epochs':>6} {'faults':>7} {'cranks':>9} {'dropped':>8}"
    )
    failed = 0
    for r in results:
        ok = "PASS" if r.ok else "FAIL"
        print(
            f"{r.attack:>{wide}} {r.schedule:>15} {r.n:>4} {ok:>4} "
            f"{r.epochs_committed:>6} {sum(r.fault_kinds.values()):>7} "
            f"{r.cranks:>9} {r.schedule_dropped:>8}"
        )
        if not r.ok:
            failed += 1
            if r.error:
                print(f"    stall: {r.error}")
            if r.why and r.why.get("summary"):
                print(f"    why:   {r.why['summary'][0]}")
            if r.missing_expected:
                print(f"    missing expected faults: {r.missing_expected}")
            if r.misattributed:
                print(f"    misattributed: {r.misattributed[:5]}")
            if args.fail_dir and r.forensics is not None:
                from hbbft_tpu.obs.flight import write_bundle

                os.makedirs(args.fail_dir, exist_ok=True)
                bpath = os.path.join(
                    args.fail_dir,
                    f"{r.attack}x{r.schedule}@n{r.n}.forensics.json",
                )
                write_bundle(r.forensics, bpath)
                print(f"    forensics bundle -> {bpath}")
    kinds: dict = {}
    for r in results:
        for k, c in r.fault_kinds.items():
            kinds[k] = kinds.get(k, 0) + c
    print(f"\n{len(results) - failed}/{len(results)} cells passed")
    for k in sorted(kinds):
        print(f"  {k}: {kinds[k]}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"rows": [r.row() for r in results]}, f, indent=2)
        print(f"rows written to {args.json}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
