#!/bin/bash
# Background TPU tunnel liveness watcher. Appends one line per probe to
# /tmp/tpu_status.log; writes /tmp/tpu_alive when a probe succeeds so the
# build session can grab a bench window immediately (VERDICT r2 Missing #1).
# Success = a small device matmul completes and fetches within the timeout
# (same discipline as __graft_entry__._accelerator_alive: only a hang
# counts as dead; the platform may report "tpu" or "axon").
while true; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 120 python -c "
import jax, numpy as np, jax.numpy as jnp
v = float(np.asarray(jnp.ones((64,64)) @ jnp.ones((64,64)))[0][0])
plat = jax.devices()[0].platform
assert plat in ('tpu', 'axon'), plat  # a CPU fallback is NOT alive
print('OK', plat, v)
" 2>/dev/null | grep '^OK' | head -1)
  if [ -n "$out" ]; then
    echo "$ts ALIVE $out" >> /tmp/tpu_status.log
    touch /tmp/tpu_alive
  else
    echo "$ts dead" >> /tmp/tpu_status.log
    rm -f /tmp/tpu_alive
  fi
  sleep 180
done
