#!/bin/bash
# Background TPU tunnel liveness watcher. Appends one line per probe to
# /tmp/tpu_status.log; writes /tmp/tpu_alive when a probe succeeds so the
# build session can grab a bench window immediately (VERDICT r2 Missing #1).
# Success = a small device matmul completes and fetches within the timeout
# (same discipline as __graft_entry__._accelerator_alive: only a hang
# counts as dead; the platform may report "tpu" or "axon").
#
# On a dead→alive TRANSITION the watcher AUTO-LAUNCHES the window
# capture runbook (tools/tpu_window.sh) in the background, once per
# window (lockfile): rounds 2-3 lost every window to timing, so capture
# must not depend on a human/agent noticing the flag.  Disable with
# TPU_WATCH_NO_CAPTURE=1 (e.g. while driving the window manually).
cd "$(dirname "$0")/.."
LOCK=/tmp/tpu_window_running
# a stale flag from a previous watcher run would make the first ALIVE
# probe read as "no transition" and skip that window's capture
rm -f /tmp/tpu_alive
while true; do
  # reap a stale lock (capture killed before its rmdir): no live
  # tpu_window.sh process → the lock cannot be protecting anything
  if [ -d "$LOCK" ] && ! pgrep -f "bash tools/tpu_window.sh" >/dev/null; then
    echo "$(date -u +%H:%M:%S) reaping stale capture lock" >> /tmp/tpu_status.log
    rmdir "$LOCK" 2>/dev/null || true
  fi
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 120 python -c "
import jax, numpy as np, jax.numpy as jnp
v = float(np.asarray(jnp.ones((64,64)) @ jnp.ones((64,64)))[0][0])
plat = jax.devices()[0].platform
assert plat in ('tpu', 'axon'), plat  # a CPU fallback is NOT alive
print('OK', plat, v)
" 2>/dev/null | grep '^OK' | head -1)
  if [ -n "$out" ]; then
    was_dead=1
    [ -f /tmp/tpu_alive ] && was_dead=0
    echo "$ts ALIVE $out" >> /tmp/tpu_status.log
    touch /tmp/tpu_alive
    if [ "$was_dead" = 1 ] && [ -z "${TPU_WATCH_NO_CAPTURE:-}" ] \
        && mkdir "$LOCK" 2>/dev/null; then
      echo "$ts auto-launching tpu_window.sh" >> /tmp/tpu_status.log
      ( bash tools/tpu_window.sh >> /tmp/tpu_window.log 2>&1; \
        rmdir "$LOCK" ) &
    fi
  else
    echo "$ts dead" >> /tmp/tpu_status.log
    rm -f /tmp/tpu_alive
  fi
  sleep 180
done
