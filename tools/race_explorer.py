#!/usr/bin/env python
"""Schedule-space race explorer CLI (analysis/schedules.py).

Systematically explores non-equivalent resolution/crank schedules of the
async seams (pipeline chunk resolution, deferred verify, traffic hooks,
VirtualNet delivery order) and asserts the run fingerprint — Batch
sha256, fault log, integer counters, device_dispatches — is identical
across all of them.

Usage::

    python tools/race_explorer.py                          # smoke sweep
    python tools/race_explorer.py --smoke                  # same, explicit
    python tools/race_explorer.py --full                   # slow sweep (N=4,7)
    python tools/race_explorer.py --target pipeline --n 4 --max-runs 200
    python tools/race_explorer.py --target mutant:accum --counterexample /tmp/cx.json
    python tools/race_explorer.py --replay /tmp/cx.json    # reproduce a divergence

Exit status: 0 when every explored schedule agreed (or a --replay
reproduced its recorded divergence exactly); 1 when a divergence was
found (the counterexample file is written if --counterexample was
given); 2 when a --replay failed to reproduce.

Pure CPU / no JAX: every target runs MockBackend protocol math, so a
sweep costs milliseconds per schedule.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from hbbft_tpu.analysis import schedules  # noqa: E402
from hbbft_tpu.analysis.schedules import FULL_PLAN, SMOKE_PLAN  # noqa: E402


def run_plan(plan, seed: int, counterexample) -> int:
    total_classes = 0
    total_runs = 0
    total_pruned = 0
    rc = 0
    for target, n, max_runs in plan:
        ex = schedules.explore(target, n, seed=seed, max_runs=max_runs)
        total_classes += ex.classes
        total_runs += ex.runs
        total_pruned += ex.pruned
        s = ex.summary()
        print(
            f"explorer: {target} n={n} runs={s['runs']} "
            f"classes={s['non_equivalent_schedules']} "
            f"pruned={s['dpor_pruned']} ok={s['ok']}"
        )
        if not ex.ok:
            rc = 1
            print(
                "explorer: DIVERGENCE "
                + json.dumps(
                    ex.divergence["first_divergence"], sort_keys=True
                )
            )
            if counterexample:
                schedules.write_counterexample(counterexample, ex)
                print(f"explorer: counterexample -> {counterexample}")
                return rc
    print(
        f"explorer: total runs={total_runs} "
        f"non-equivalent schedules={total_classes} pruned={total_pruned}"
    )
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--target", help="one target (pipeline/traffic/virtualnet/mutant:*)")
    ap.add_argument("--n", type=int, default=4, help="network size (default 4)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-runs", type=int, default=120)
    ap.add_argument("--smoke", action="store_true", help="tier-1 smoke plan")
    ap.add_argument("--full", action="store_true", help="slow full sweep plan")
    ap.add_argument(
        "--counterexample",
        type=Path,
        help="write a minimized replayable counterexample here on divergence",
    )
    ap.add_argument(
        "--replay",
        type=Path,
        help="re-run a counterexample file; exit 0 iff it reproduces exactly",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable summary")
    args = ap.parse_args(argv)

    if args.replay:
        rep = schedules.replay_counterexample(args.replay)
        if args.json:
            print(json.dumps(rep, indent=2, sort_keys=True, default=repr))
        else:
            print(
                f"replay: diverged={rep['diverged']} "
                f"reproduced={rep['reproduced']} "
                f"first_divergence={json.dumps(rep['first_divergence'])}"
            )
        return 0 if rep["reproduced"] else 2

    if args.target:
        ex = schedules.explore(
            args.target, args.n, seed=args.seed, max_runs=args.max_runs
        )
        if args.json:
            print(json.dumps(ex.summary(), indent=2, sort_keys=True, default=repr))
        else:
            s = ex.summary()
            print(
                f"explorer: {args.target} n={args.n} runs={s['runs']} "
                f"classes={s['non_equivalent_schedules']} "
                f"pruned={s['dpor_pruned']} ok={s['ok']}"
            )
        if not ex.ok:
            if args.counterexample:
                schedules.write_counterexample(args.counterexample, ex)
                print(f"explorer: counterexample -> {args.counterexample}")
            else:
                print(
                    "explorer: DIVERGENCE "
                    + json.dumps(ex.divergence["first_divergence"], sort_keys=True)
                )
            return 1
        return 0

    plan = FULL_PLAN if args.full else SMOKE_PLAN
    return run_plan(plan, args.seed, args.counterexample)


if __name__ == "__main__":
    sys.exit(main())
