#!/usr/bin/env python
"""Protocol-invariant linter CLI (hbbft_tpu/analysis rule engine).

Usage::

    python tools/lint.py                 # full run over hbbft_tpu/
    python tools/lint.py --diff          # only files changed vs git HEAD
    python tools/lint.py --baseline      # rewrite the grandfathered baseline
    python tools/lint.py --ci            # ruff (if installed) + custom rules
    python tools/lint.py --json OUT      # machine-readable findings ("-": stdout)
    python tools/lint.py path/a.py ...   # explicit file list

Exit status is non-zero iff there are findings beyond the checked-in
baseline (``tools/lint_baseline.json``).  Output is deterministically
sorted by (path, line, col, rule, message).

Suppression syntax (must carry a reason)::

    x = f(s)  # lint: allow[determinism] ordering provably irrelevant: <why>

The linter never imports the code under analysis — a full run is pure AST
work and finishes in seconds on CPU (no JAX import).
"""

from __future__ import annotations

import argparse
import json
import shutil
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from hbbft_tpu.analysis.engine import (  # noqa: E402
    Baseline,
    Finding,
    iter_python_files,
    run_lint,
)

BASELINE_PATH = REPO_ROOT / "tools" / "lint_baseline.json"

#: schema identifier pinned by tests/test_lint.py — bump only with a
#: matching consumer update (tools/ci.sh parses this, not the human text)
JSON_SCHEMA = "hbbft-tpu-lint/1"


def findings_document(new, grandfathered: int) -> dict:
    """Machine-readable findings: stable sort, schema-pinned shape."""
    return {
        "schema": JSON_SCHEMA,
        "new": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
            }
            for f in sorted(new, key=Finding.sort_key)
        ],
        "grandfathered": grandfathered,
    }


def _git_changed_files() -> list:
    """Changed + untracked .py files under hbbft_tpu/ (repo-relative)."""
    out = subprocess.run(
        ["git", "status", "--porcelain", "--untracked-files=all"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        check=True,
    ).stdout
    paths = []
    for line in out.splitlines():
        rel = line[3:].split(" -> ")[-1].strip().strip('"')
        p = REPO_ROOT / rel
        if rel.endswith(".py") and rel.startswith("hbbft_tpu/") and p.exists():
            paths.append(p)
    return sorted(set(paths))


def _run_ruff() -> int:
    """Run ruff if the binary is available; 0 when absent (gated dep)."""
    ruff = shutil.which("ruff")
    if ruff is None:
        print("lint: ruff not installed; skipping ruff pass", file=sys.stderr)
        return 0
    proc = subprocess.run(
        [ruff, "check", "hbbft_tpu", "tools", "tests"], cwd=REPO_ROOT
    )
    return proc.returncode


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*", help="explicit files (default: hbbft_tpu/)")
    ap.add_argument(
        "--diff", action="store_true", help="lint only files changed vs git"
    )
    ap.add_argument(
        "--baseline",
        action="store_true",
        help="rewrite tools/lint_baseline.json from the current full run",
    )
    ap.add_argument(
        "--baseline-file",
        type=Path,
        default=BASELINE_PATH,
        help="baseline location (default tools/lint_baseline.json)",
    )
    ap.add_argument(
        "--ci",
        action="store_true",
        help="also run ruff (if installed); exit codes are merged",
    )
    ap.add_argument(
        "--json",
        metavar="OUT",
        default=None,
        help="write machine-readable findings (schema "
        f"{JSON_SCHEMA!r}) to OUT; '-' writes JSON to stdout and moves "
        "the human summary to stderr",
    )
    args = ap.parse_args(argv)

    if args.files:
        paths = [Path(f).resolve() for f in args.files]
    elif args.diff:
        paths = _git_changed_files()
        if not paths:
            print("lint: no changed files under hbbft_tpu/")
            return _run_ruff() if args.ci else 0
    else:
        paths = iter_python_files(REPO_ROOT / "hbbft_tpu")

    findings = run_lint(REPO_ROOT, paths)

    if args.baseline:
        if args.diff or args.files:
            print("lint: --baseline requires a full run", file=sys.stderr)
            return 2
        old = Baseline.load(args.baseline_file)
        new_baseline = Baseline.from_findings(findings)
        # prune report: grandfathered entries whose findings no longer
        # occur (fixed code keeps the baseline honest automatically)
        pruned = sum(
            max(0, n - new_baseline.counts.get(key, 0))
            for key, n in old.counts.items()
        )
        added = sum(
            max(0, n - old.counts.get(key, 0))
            for key, n in new_baseline.counts.items()
        )
        new_baseline.save(args.baseline_file)
        print(
            f"lint: baseline rewritten with {len(findings)} grandfathered "
            f"finding(s) -> {args.baseline_file} "
            f"({pruned} pruned, {added} added)"
        )
        return 0

    baseline = Baseline.load(args.baseline_file)
    new = baseline.new_findings(findings)
    grandfathered = len(findings) - len(new)

    human_out = sys.stdout
    if args.json is not None:
        doc = json.dumps(findings_document(new, grandfathered), indent=2)
        if args.json == "-":
            print(doc)
            human_out = sys.stderr
        else:
            Path(args.json).write_text(doc + "\n", encoding="utf-8")

    for f in new:
        print(f.render(), file=human_out)
    summary = f"lint: {len(new)} new finding(s)"
    if grandfathered:
        summary += f", {grandfathered} grandfathered"
    print(summary, file=human_out)

    rc = 1 if new else 0
    if args.ci:
        rc = max(rc, _run_ruff())
    return rc


if __name__ == "__main__":
    sys.exit(main())
