"""One virtual-CPU-mesh probe for bench.py's ``mesh_scaling`` row (PR 18).

Runs in a fresh subprocess so the XLA host-platform device count can be
forced per mesh size (it is fixed at JAX init).  Drives the per-device
pipelined dispatcher (parallel/shardpipe.py) with ``BENCH_MESH_CHUNKS``
committed-placement dispatches of a trivial jitted reduction and prints
one JSON line of structural facts: placement determinism, balanced
per-device dispatch tallies, imbalance, and chunk throughput.  The
throughput number is STRUCTURAL ONLY — host-platform "devices" share the
physical cores, so it must never be read as a scale-out measurement
(PERF.md round 14); the real-mesh number comes from the window runbook.

Env: BENCH_MESH_DEVICES (mesh size), BENCH_MESH_CHUNKS (default 64),
BENCH_MESH_LANES (elements per chunk, default 4096).
"""

import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def main() -> int:
    k = int(os.environ.get("BENCH_MESH_DEVICES", "1"))
    chunks = int(os.environ.get("BENCH_MESH_CHUNKS", "64"))
    lanes = int(os.environ.get("BENCH_MESH_LANES", "4096"))
    native = os.environ.get("BENCH_MESH_PLATFORM") == "native"
    if not native:
        # the device count must be pinned before JAX initializes; drop
        # any inherited pin (e.g. the test conftest's 8) so ours wins
        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={k}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
        os.environ["JAX_PLATFORMS"] = "cpu"  # virtual mesh = CPU

    import jax
    import jax.numpy as jnp

    from hbbft_tpu.parallel.shardpipe import ShardedDispatchPipeline

    devices = jax.devices()[:k]
    if len(devices) != k:
        print(json.dumps({"error": f"got {len(devices)} devices, want {k}"}))
        return 1
    fn = jax.jit(lambda x: (x * 2.0 + 1.0).sum())
    base = jnp.arange(lanes, dtype=jnp.float32)
    for d in devices:  # compile + warm every device before timing
        fn(jax.device_put(base, d)).block_until_ready()

    pipe = ShardedDispatchPipeline(k)
    out = []
    t0 = time.perf_counter()
    for i in range(chunks):
        d = pipe.reserve_device()
        staged = jax.device_put(base, devices[d])
        pipe.submit(
            lambda staged=staged: fn(staged),
            fetch=float,
            kind=f"c{i}",
            items=lanes,
            on_result=out.append,
        )
    pipe.flush()
    dt = time.perf_counter() - t0

    expect = float(fn(base))
    print(
        json.dumps(
            {
                "devices": k,
                "chunks": chunks,
                "chunks_per_s": round(chunks / dt, 2),
                "wall_s": round(dt, 4),
                "dev_dispatches": pipe.dev_dispatches,
                "placements_ok": pipe.placements
                == [i % k for i in range(chunks)],
                "balanced": max(pipe.dev_dispatches)
                - min(pipe.dev_dispatches)
                <= (1 if chunks % k else 0),
                "imbalance": round(pipe.imbalance(), 4),
                "results_ok": len(out) == chunks
                and all(abs(v - expect) < 1e-3 * abs(expect) for v in out),
                "platform": devices[0].platform,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
