"""trace_report — summarize obs traces; diff BENCH_rows.json artifacts.

Two subcommands on one small CLI:

* ``python tools/trace_report.py TRACE`` — load a Chrome-trace-event
  ``trace.json`` (or raw event ``.jsonl``) written by
  :class:`hbbft_tpu.obs.tracer.Tracer`, validate it against the
  trace-event schema (required keys, monotonic ``ts``, matched B/E
  pairs), and print the per-kind time table: span category, span count,
  total seconds, share — the device rows reproduce the
  ``device_seconds_*`` counter split from the trace alone.
* ``python tools/trace_report.py --diff OLD NEW`` — compare two
  ``BENCH_rows.json`` files metric by metric and flag regressions where
  the new value dropped more than ``--tol`` (default 10%) below the old
  (all bench metrics are higher-is-better rates).  Exit code 1 when any
  regression is flagged, so the check can gate CI.
* ``python tools/trace_report.py --faults OLD NEW`` — diff the
  fault-kind counts carried by rows with a ``fault_kinds`` field
  (scenario_matrix / adv_matrix captures): count changes print, and a
  kind that VANISHED while its row persists (an attack that stopped
  being detected) exits 1.
* ``python tools/trace_report.py --traffic OLD NEW`` — diff the
  ``qhb_traffic``/``slo_traffic`` throughput/latency curves cell by
  cell: a sustained tx/s drop beyond ``--tol`` (default 10%) OR a p99
  commit-latency increase beyond it is a regression (exit 1) — latency
  is lower-is-better, unlike every other bench metric, so the generic
  ``--diff`` mode cannot gate it.  ``slo_traffic`` cells additionally
  gate SLO COMPLIANCE: a cell (the controller's ``adaptive`` cell
  above all) that held the declared SLO in OLD and lost it in NEW
  exits 1 whatever the ratios.
* ``python tools/trace_report.py --critical-path PATH [PATH2]`` — print
  the run-level gating histogram (fraction of epochs each phase gated,
  obs/critpath.py) from any gating evidence: a per-epoch series
  ``.jsonl`` (rows carry ``gate.phase``), a forensics bundle
  (``critical_path.gating``), a BENCH capture whose rows carry a
  ``gating`` field, or a raw Chrome trace (epoch windows re-gated from
  the phase span categories).  With two paths, diff them: any phase
  whose gating share shifted more than ``--tol`` absolute share points
  (default 0.10) exits 1 — the commit-latency-attribution regression
  gate.
* ``python tools/trace_report.py --forensics BUNDLE...`` — validate
  each flight-recorder forensics bundle (required keys, monotonic frame
  epochs, gating shares sane, phase names inside the critpath registry)
  and print its summary (reason, cell, gate one-liner, gating table,
  fault kinds).  Exit 1 when any bundle is invalid.

The validation helpers are imported by the test suite
(tests/test_obs_tracer.py, tests/test_trace_smoke.py) — keep them
dependency-free.  The critpath phase vocabulary below is a deliberate
inline COPY of hbbft_tpu/obs/critpath.py (this tool must not import the
package); tests/test_phase_labels.py pins the two against each other.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

#: keys every span event must carry (Chrome trace-event format)
REQUIRED_KEYS = ("name", "ph", "ts", "pid", "tid")

#: inline copy of hbbft_tpu/obs/critpath.py PHASES — the closed critpath
#: phase vocabulary (tests/test_phase_labels.py pins the two lists)
CRITPATH_PHASES = (
    "rbc.output",
    "ba.decide",
    "coin.reveal",
    "decrypt.combine",
    "epoch.commit",
    "crank",
    "crash:recovery",
)

#: tracer span category -> critpath phase (inverse of critpath
#: PHASE_SPAN_CATS; same guard test pins it) — how a raw Chrome trace's
#: spans re-derive per-epoch gating without importing the package
SPAN_CAT_PHASES = {
    "rbc": "rbc.output",
    "ba": "ba.decide",
    "coin": "coin.reveal",
    "decrypt": "decrypt.combine",
    "epoch": "epoch.commit",
    "crank": "crank",
    "crash": "crash:recovery",
}

#: inline copy of hbbft_tpu/obs/flight.py REQUIRED_BUNDLE_KEYS
REQUIRED_FORENSICS_KEYS = ("version", "kind", "reason", "frames", "critical_path")


def load_events(path: str) -> List[Dict[str, Any]]:
    """Events from a Chrome trace (``{"traceEvents": [...]}``) or JSONL."""
    with open(path) as f:
        if path.endswith(".jsonl"):
            return [json.loads(line) for line in f if line.strip()]
        doc = json.load(f)
    if isinstance(doc, dict) and "traceEvents" in doc:
        return doc["traceEvents"]
    raise ValueError(f"{path}: not a Chrome trace or event JSONL")


def validate_chrome_trace(events: List[Dict[str, Any]]) -> List[str]:
    """Schema errors (empty list = valid).

    Checks: every event carries the required keys; ``ts`` is monotonic
    non-decreasing in file order; on each (pid, tid) the B/E events form
    a properly nested stack with matching names; no span left open.
    """
    errors: List[str] = []
    last_ts = None
    stacks: Dict[Tuple[Any, Any], List[str]] = {}
    for i, ev in enumerate(events):
        for k in REQUIRED_KEYS:
            if k not in ev:
                errors.append(f"event {i}: missing key {k!r}")
        ph = ev.get("ph")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"event {i}: bad ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            errors.append(f"event {i}: ts {ts} < previous {last_ts}")
        last_ts = ts
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(key, []).append(ev.get("name", ""))
        elif ph == "E":
            stack = stacks.get(key)
            if not stack:
                errors.append(f"event {i}: E with no open B on tid {key[1]}")
            elif stack[-1] != ev.get("name", ""):
                errors.append(
                    f"event {i}: E {ev.get('name')!r} closes "
                    f"open B {stack[-1]!r} on tid {key[1]}"
                )
                stack.pop()
            else:
                stack.pop()
        elif ph not in ("M",):
            errors.append(f"event {i}: unknown ph {ph!r}")
    for key, stack in stacks.items():
        for name in stack:
            errors.append(f"unclosed span {name!r} on tid {key[1]}")
    return errors


def span_durations(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Closed spans as {name, cat, tid, dur_us, device, args}."""
    out: List[Dict[str, Any]] = []
    stacks: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = {}
    for ev in events:
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(key, []).append(ev)
        elif ph == "E":
            stack = stacks.get(key)
            if stack:
                b = stack.pop()
                args = b.get("args", {})
                out.append(
                    {
                        "name": b.get("name", ""),
                        "cat": b.get("cat", ""),
                        "tid": key[1],
                        "dur_us": ev["ts"] - b["ts"],
                        "device": bool(args.get("device")),
                        "args": args,
                    }
                )
    return out


def device_span_seconds(events: List[Dict[str, Any]]) -> float:
    """Total wall seconds of device=True dispatch spans — should agree
    with counters.device_seconds (±5%; both bill the same dispatch+fetch
    interval).  Under pipelined dispatch the spans OVERLAP in wall time
    (each in-flight slot spans its own ``device/<slot>`` track), but the
    per-span durations still sum to the counter, because both bill the
    same per-dispatch dispatch→fetch interval."""
    return sum(
        s["dur_us"] for s in span_durations(events) if s["device"]
    ) / 1e6


def check_device_seconds(
    events: List[Dict[str, Any]], expected: float, tol: float = 0.05
) -> Tuple[bool, float]:
    """Acceptance check: Σ device=True span durations == ``expected``
    (counters.device_seconds, or device_seconds_per_epoch × epochs from
    a bench row) within ``tol`` relative.  Returns (ok, measured).

    This is the invariant that keeps the pipeline honest: deferring
    fetches must not lose or double-bill device time — overlapped spans
    still sum to the counter, so a traced run validates the attribution
    without hardware-side profiling."""
    got = device_span_seconds(events)
    if expected <= 0:
        return (got == 0.0, got)
    return (abs(got - expected) <= tol * expected, got)


def track_name_map(events: List[Dict[str, Any]]) -> Dict[Any, str]:
    """tid -> tracer track name, from the ``thread_name`` metadata
    events obs/tracer.py emits for every track."""
    out: Dict[Any, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            out[ev.get("tid")] = (ev.get("args") or {}).get("name", "")
    return out


def per_device_span_seconds(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """``device=True`` span seconds grouped by tracer track — the shard
    pipeline's per-device attribution surface (parallel/shardpipe.py).

    Sharded dispatches span ``device/<n>`` (their device's track);
    unreserved async entries span ``device/q<slot>`` and sync entries
    the classic ``device`` track, so the values sum to the SAME total as
    :func:`device_span_seconds` however the run was routed."""
    names = track_name_map(events)
    out: Dict[str, float] = {}
    for s in span_durations(events):
        if not s["device"]:
            continue
        track = names.get(s["tid"], str(s["tid"]))
        out[track] = out.get(track, 0.0) + s["dur_us"] / 1e6
    return out


def check_per_device_seconds(
    events: List[Dict[str, Any]], expected: float, tol: float = 0.05
) -> Tuple[bool, Dict[str, float]]:
    """Acceptance check (PR 18): the per-device span partition must sum
    to ``expected`` (counters.device_seconds) within ``tol`` relative —
    per-device attribution may not lose or double-bill device time
    relative to the global counter.  Returns (ok, per-track seconds)."""
    per = per_device_span_seconds(events)
    got = sum(per.values())
    if expected <= 0:
        return (got == 0.0, per)
    return (abs(got - expected) <= tol * expected, per)


def host_bucket_seconds(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """Per-bucket host seconds from the ``host=True`` region spans.

    Regions NEST (dispatch ⊃ staging), so summing span durations would
    double-count; each span instead carries its EXCLUSIVE seconds in
    ``args.exclusive_s`` (obs/hostbuckets.py), and those reproduce the
    ``host_bucket_*`` counter partition from the trace alone."""
    out: Dict[str, float] = {}
    for s in span_durations(events):
        args = s["args"]
        if not args.get("host"):
            continue
        bucket = args.get("bucket", s["name"])
        sec = args.get("exclusive_s")
        if not isinstance(sec, (int, float)):
            sec = s["dur_us"] / 1e6
        out[bucket] = out.get(bucket, 0.0) + float(sec)
    return out


def check_host_buckets(
    events: List[Dict[str, Any]],
    expected: float,
    tol: float = 0.05,
    max_unattributed: float = 0.10,
    folded_device_seconds: float = 0.0,
) -> Tuple[bool, Dict[str, float]]:
    """Acceptance check for the host attribution (PR 5): the traced
    bucket partition must sum to ``expected`` (counters.host_seconds, or
    host_seconds_per_epoch × epochs from a bench row) within ``tol``
    relative, AND the residual ``other`` bucket must stay under
    ``max_unattributed`` of the total — i.e. the named buckets cover
    ≥ 1 − max_unattributed of the epoch's host time.

    ``folded_device_seconds`` (PR 19) is the device time of work that
    used to be host buckets — with the device erasure/hash plane on,
    encode/rs_merkle legitimately fall to ~0 and host_seconds shrinks by
    the folded amount, which would inflate every remaining bucket's
    SHARE; the unattributed bound is therefore judged against the
    pre-fold scale (expected + folded).  The sum check is unchanged:
    the buckets must still account for the host time that remains.
    Returns (ok, buckets)."""
    buckets = host_bucket_seconds(events)
    total = sum(buckets.values())
    if expected <= 0:
        return (total == 0.0, buckets)
    ok = (
        abs(total - expected) <= tol * expected
        and buckets.get("other", 0.0)
        <= max_unattributed * (expected + max(0.0, folded_device_seconds))
    )
    return ok, buckets


def kind_table(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-category totals, device dispatches split from protocol spans.

    ``share`` is each category's total against the trace's WALL duration
    (first B to last E), not the sum of all span durations — spans nest
    (epoch ⊃ subset ⊃ rbc) and the lockstep engine replicates per-proposer
    instance spans across tracks, so a duration-sum denominator would
    understate enclosing spans.  Shares can therefore sum past 100%: an
    ``epoch`` row near 100% wall is the expected reading."""
    agg: Dict[Tuple[str, bool], Dict[str, float]] = {}
    for s in span_durations(events):
        key = (s["cat"] or "span", s["device"])
        a = agg.setdefault(key, {"count": 0, "us": 0.0})
        a["count"] += 1
        a["us"] += s["dur_us"]
    ts = [e["ts"] for e in events if e.get("ph") in ("B", "E")]
    wall_us = (max(ts) - min(ts)) if ts else 0.0
    rows = [
        {
            "cat": cat,
            "device": device,
            "count": int(a["count"]),
            "seconds": a["us"] / 1e6,
            "share": a["us"] / wall_us if wall_us else 0.0,
        }
        for (cat, device), a in agg.items()
    ]
    rows.sort(key=lambda r: -r["seconds"])
    return rows


def report(
    path: str,
    device_seconds: Optional[float] = None,
    tol: float = 0.05,
    host_buckets: Optional[float] = None,
    host_unattributed_max: float = 0.10,
    host_folded_device: float = 0.0,
) -> int:
    events = load_events(path)
    errors = validate_chrome_trace(events)
    if errors:
        print(f"{path}: INVALID ({len(errors)} schema errors)")
        for e in errors[:20]:
            print("  " + e)
        return 1
    spans = [e for e in events if e.get("ph") in ("B", "E")]
    print(f"{path}: valid; {len(spans) // 2} spans")
    dev = device_span_seconds(events)
    print(f"device dispatch time (device=True spans): {dev:.4f} s")
    print(f"{'cat':>12} {'where':>7} {'count':>8} {'seconds':>10} {'wall%':>7}")
    for r in kind_table(events):
        where = "device" if r["device"] else "host"
        print(
            f"{r['cat']:>12} {where:>7} {r['count']:>8} "
            f"{r['seconds']:>10.4f} {r['share']:>6.1%}"
        )
    per = per_device_span_seconds(events)
    if any(t.startswith("device/") for t in per):
        print(f"{'device track':>14} {'seconds':>10}")
        for track in sorted(per):
            print(f"{track:>14} {per[track]:>10.4f}")
    if device_seconds is not None:
        ok, got = check_device_seconds(events, device_seconds, tol)
        verdict = "OK" if ok else "MISMATCH"
        print(
            f"device-seconds check: spans {got:.4f} s vs counter "
            f"{device_seconds:.4f} s (±{tol:.0%}) — {verdict}"
        )
        if not ok:
            return 1
        ok, per = check_per_device_seconds(events, device_seconds, tol)
        verdict = "OK" if ok else "MISMATCH"
        print(
            f"per-device check: {len(per)} track(s) sum "
            f"{sum(per.values()):.4f} s vs counter {device_seconds:.4f} s "
            f"(±{tol:.0%}) — {verdict}"
        )
        if not ok:
            return 1
    if host_buckets is not None:
        ok, buckets = check_host_buckets(
            events, host_buckets, tol, host_unattributed_max,
            host_folded_device,
        )
        total = sum(buckets.values())
        print(f"{'host bucket':>12} {'seconds':>10} {'share':>7}")
        for name, sec in sorted(buckets.items(), key=lambda kv: -kv[1]):
            share = sec / host_buckets if host_buckets else 0.0
            print(f"{name:>12} {sec:>10.4f} {share:>6.1%}")
        verdict = "OK" if ok else "MISMATCH"
        folded = (
            f", folded device {host_folded_device:.4f} s"
            if host_folded_device
            else ""
        )
        print(
            f"host-buckets check: buckets {total:.4f} s vs counter "
            f"{host_buckets:.4f} s (±{tol:.0%}), unattributed "
            f"{buckets.get('other', 0.0):.4f} s "
            f"(max {host_unattributed_max:.0%}{folded}) — {verdict}"
        )
        if not ok:
            return 1
    return 0


# ---------------------------------------------------------------------------
# BENCH_rows.json diff
# ---------------------------------------------------------------------------


def _rows_by_metric(path: str) -> Dict[str, Dict[str, Any]]:
    with open(path) as f:
        doc = json.load(f)
    rows = doc["rows"] if isinstance(doc, dict) else doc
    return {
        r["metric"]: r
        for r in rows
        if isinstance(r.get("value"), (int, float))
    }


#: A/B rows whose secondary arm / ratio must diff alongside the headline
#: value — the rs_plane_ab row's ``value`` is the device-plane rate, so
#: without these sub-metrics a host-arm collapse (or the device-vs-host
#: ratio sliding under 1.0) would pass the diff unnoticed (PR 19)
_AB_SUBMETRICS: Dict[str, Tuple[str, ...]] = {
    "rs_plane_ab": ("host_blocks_per_sec", "device_vs_host"),
}


def diff_rows(
    old_path: str, new_path: str, tol: float = 0.10
) -> List[Dict[str, Any]]:
    """Per-metric comparison; ``regression`` flags a >tol drop (all bench
    metrics are higher-is-better rates)."""
    old, new = _rows_by_metric(old_path), _rows_by_metric(new_path)
    out = []
    for metric in sorted(set(old) | set(new)):
        o, n = old.get(metric), new.get(metric)
        entry: Dict[str, Any] = {"metric": metric}
        if o is None or n is None:
            entry["status"] = "only_in_new" if o is None else "only_in_old"
            entry["regression"] = False
            out.append(entry)
            continue
        entry["old"] = o["value"]
        entry["new"] = n["value"]
        entry["ratio"] = n["value"] / o["value"] if o["value"] else None
        entry["regression"] = bool(
            o["value"] and n["value"] < o["value"] * (1.0 - tol)
        )
        out.append(entry)
        for field in _AB_SUBMETRICS.get(metric, ()):
            ov, nv = o.get(field), n.get(field)
            if not isinstance(ov, (int, float)) or not isinstance(
                nv, (int, float)
            ):
                continue
            out.append(
                {
                    "metric": f"{metric}.{field}",
                    "old": ov,
                    "new": nv,
                    "ratio": nv / ov if ov else None,
                    "regression": bool(ov and nv < ov * (1.0 - tol)),
                }
            )
    return out


def _fault_rows(path: str) -> Dict[str, Dict[str, int]]:
    """metric -> fault-kind counts for every row carrying a
    ``fault_kinds`` field (scenario_matrix, adv_matrix captures)."""
    with open(path) as f:
        doc = json.load(f)
    rows = doc["rows"] if isinstance(doc, dict) else doc
    out: Dict[str, Dict[str, int]] = {}
    for r in rows:
        if not isinstance(r.get("fault_kinds"), dict):
            continue
        kinds = dict(r["fault_kinds"])
        # crash-axis rows (crash_matrix): the injected-crash and
        # completed-restart counts ride as synthetic kinds, so the axis
        # silently ceasing to crash/recover (counts -> 0 while the row
        # persists) is a detection loss exactly like a vanished
        # crash:recovery_failed / crash:replay_divergence fault count
        for key, pseudo in (
            ("crashes", "axis:crashes_injected"),
            ("restarts", "axis:restarts_completed"),
        ):
            if isinstance(r.get(key), int) and r[key]:
                kinds[pseudo] = r[key]
        out[r["metric"]] = kinds
    return out


def diff_faults(old_path: str, new_path: str) -> List[Dict[str, Any]]:
    """Per-metric fault-kind count deltas between two BENCH_rows files.

    A changed count is not automatically a regression (more epochs plant
    more faults) but a kind that DISAPPEARS while its metric persists
    means an attack stopped being detected — that is flagged."""
    old, new = _fault_rows(old_path), _fault_rows(new_path)
    out: List[Dict[str, Any]] = []
    for metric in sorted(set(old) | set(new)):
        o, n = old.get(metric, {}), new.get(metric, {})
        for kind in sorted(set(o) | set(n)):
            oc, nc = o.get(kind, 0), n.get(kind, 0)
            if oc == nc:
                continue
            out.append(
                {
                    "metric": metric,
                    "kind": kind,
                    "old": oc,
                    "new": nc,
                    # detection loss: the kind vanished while the metric row
                    # still exists in the new capture
                    "lost": bool(oc and not nc and metric in new),
                }
            )
    return out


def report_faults(old_path: str, new_path: str) -> int:
    entries = diff_faults(old_path, new_path)
    if not entries:
        print("fault-kind counts identical")
        return 0
    lost = [e for e in entries if e["lost"]]
    for e in entries:
        flag = "  LOST" if e["lost"] else ""
        print(
            f"{e['metric']:>20} {e['kind']:>45} "
            f"{e['old']:>6} -> {e['new']:>6}{flag}"
        )
    print(
        f"{len(entries)} fault-kind count change(s), "
        f"{len(lost)} detection loss(es)"
    )
    return 1 if lost else 0


def _traffic_cells(path: str) -> Dict[Tuple, Dict[str, Any]]:
    """(metric, n, batch_size, rate_frac) -> cell for every traffic-curve
    row (a ``curve`` list of cells plus the optional ``n100`` cell)."""
    with open(path) as f:
        doc = json.load(f)
    rows = doc["rows"] if isinstance(doc, dict) else doc
    out: Dict[Tuple, Dict[str, Any]] = {}
    for r in rows:
        if not isinstance(r.get("curve"), list):
            continue
        cells = list(r["curve"])
        if isinstance(r.get("n100"), dict):
            cells.append(r["n100"])
        for c in cells:
            if not isinstance(c, dict) or "batch_size" not in c:
                continue
            key = (
                r["metric"],
                c.get("n"),
                c["batch_size"],
                c.get("rate_frac"),
            )
            out[key] = c
    return out


def diff_traffic(
    old_path: str, new_path: str, tol: float = 0.10
) -> List[Dict[str, Any]]:
    """Cell-by-cell comparison of traffic curves.  Two regression axes,
    because latency is lower-is-better: sustained tx/s dropping more than
    ``tol``, or p99 commit latency rising more than ``tol``."""
    old, new = _traffic_cells(old_path), _traffic_cells(new_path)
    out: List[Dict[str, Any]] = []
    for key in sorted(set(old) | set(new), key=repr):
        o, n = old.get(key), new.get(key)
        entry: Dict[str, Any] = {
            "cell": {
                "metric": key[0], "n": key[1],
                "batch_size": key[2], "rate_frac": key[3],
            }
        }
        if o is None or n is None:
            entry["status"] = "only_in_new" if o is None else "only_in_old"
            entry["regression"] = False
            out.append(entry)
            continue
        o_tx, n_tx = o.get("tx_per_s", 0), n.get("tx_per_s", 0)
        o_p99, n_p99 = o.get("latency_p99", 0), n.get("latency_p99", 0)
        entry["old_tx_per_s"], entry["new_tx_per_s"] = o_tx, n_tx
        entry["old_p99"], entry["new_p99"] = o_p99, n_p99
        entry["tx_regression"] = bool(o_tx and n_tx < o_tx * (1.0 - tol))
        entry["p99_regression"] = bool(o_p99 and n_p99 > o_p99 * (1.0 + tol))
        # SLO-compliance gate (slo_traffic cells carry slo_compliant):
        # a cell — above all the controller's "adaptive" cell — that
        # held the declared SLO in the old capture and lost it in the
        # new one is a regression regardless of throughput ratios
        entry["slo_regression"] = bool(
            o.get("slo_compliant") and n.get("slo_compliant") is False
        )
        entry["regression"] = (
            entry["tx_regression"]
            or entry["p99_regression"]
            or entry["slo_regression"]
        )
        out.append(entry)
    return out


def report_traffic(old_path: str, new_path: str, tol: float) -> int:
    entries = diff_traffic(old_path, new_path, tol)
    if not entries:
        print("no traffic-curve rows found in either capture")
        return 0
    regressed = [e for e in entries if e["regression"]]
    for e in entries:
        c = e["cell"]
        label = f"{c['metric']} n={c['n']} B={c['batch_size']} r={c['rate_frac']}"
        if "status" in e:
            print(f"{label:>44} {e['status']}")
            continue
        flags = "".join(
            f"  {name}" for name, hit in (
                ("TX-REGRESSION", e["tx_regression"]),
                ("P99-REGRESSION", e["p99_regression"]),
                ("SLO-REGRESSION", e.get("slo_regression", False)),
            ) if hit
        )
        print(
            f"{label:>44} tx/s {e['old_tx_per_s']:>10} -> {e['new_tx_per_s']:>10}"
            f"  p99 {e['old_p99']:>7} -> {e['new_p99']:>7}{flags}"
        )
    print(
        f"{len(regressed)} traffic regression(s) beyond {tol:.0%} "
        f"across {len(entries)} cells"
    )
    return 1 if regressed else 0


def report_diff(old_path: str, new_path: str, tol: float) -> int:
    entries = diff_rows(old_path, new_path, tol)
    regressed = [e for e in entries if e["regression"]]
    for e in entries:
        if "ratio" in e:
            flag = "  REGRESSION" if e["regression"] else ""
            ratio = f"{e['ratio']:.3f}x" if e["ratio"] is not None else "n/a"
            print(f"{e['metric']:>40} {e['old']:>12} -> {e['new']:>12} {ratio}{flag}")
        else:
            print(f"{e['metric']:>40} {e['status']}")
    print(
        f"{len(regressed)} regression(s) beyond {tol:.0%} "
        f"across {len(entries)} metrics"
    )
    return 1 if regressed else 0


# ---------------------------------------------------------------------------
# critical-path gating (obs/critpath.py evidence, read dependency-free)
# ---------------------------------------------------------------------------


def _gating_from_gate_rows(rows: List[Dict[str, Any]]) -> Dict[str, float]:
    """Gating histogram from per-epoch series rows (their ``gate.phase``
    field, obs/timeseries.py) — one count per committed epoch."""
    counts: Dict[str, int] = {}
    for r in rows:
        if not isinstance(r, dict):
            continue
        g = r.get("gate")
        if isinstance(g, dict) and g.get("phase"):
            counts[g["phase"]] = counts.get(g["phase"], 0) + 1
    total = sum(counts[k] for k in sorted(counts))
    if not total:
        return {}
    return {k: round(counts[k] / total, 4) for k in sorted(counts)}


def gating_from_trace(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """Per-epoch gating re-derived from a raw Chrome trace: each
    ``cat="epoch"`` span is an epoch window; the phase category (rbc /
    ba / coin / decrypt / crash) with the largest summed duration inside
    the window gates that epoch — the trace-side mirror of
    ``critpath.path_from_phase_seconds``.  ``epoch``/``crank`` spans are
    containers, not phases, so they never gate."""
    spans: List[Dict[str, Any]] = []
    stacks: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = {}
    for ev in events:
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(key, []).append(ev)
        elif ph == "E":
            stack = stacks.get(key)
            if stack:
                b = stack.pop()
                spans.append({"cat": b.get("cat", ""), "b": b["ts"], "e": ev["ts"]})
    windows = sorted(
        (s["b"], s["e"]) for s in spans if s["cat"] == "epoch"
    )
    counts: Dict[str, int] = {}
    for wb, we in windows:
        durs: Dict[str, float] = {}
        for s in spans:
            phase = SPAN_CAT_PHASES.get(s["cat"])
            if phase is None or phase in ("epoch.commit", "crank"):
                continue
            if wb <= s["b"] <= we:
                durs[phase] = durs.get(phase, 0.0) + (s["e"] - s["b"])
        if not durs:
            continue
        gate, best = "epoch.commit", -1.0
        for phase in sorted(durs):
            if durs[phase] > best:
                best = durs[phase]
                gate = phase
        counts[gate] = counts.get(gate, 0) + 1
    total = sum(counts[k] for k in sorted(counts))
    if not total:
        return {}
    return {k: round(counts[k] / total, 4) for k in sorted(counts)}


def load_gating(path: str) -> Dict[str, float]:
    """The gating histogram from whichever evidence ``path`` holds:
    a per-epoch series ``.jsonl`` (rows carry ``gate``), a forensics
    bundle (``critical_path.gating``), a BENCH/soak capture whose rows
    carry a ``gating`` field (averaged across rows), or a raw Chrome
    trace (re-gated from span categories)."""
    if path.endswith(".jsonl"):
        with open(path) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        return _gating_from_gate_rows(rows)
    with open(path) as f:
        doc = json.load(f)
    rows: Any = None
    if isinstance(doc, dict):
        if doc.get("kind") == "forensics":
            cp = doc.get("critical_path") or {}
            return dict(cp.get("gating") or {})
        if "traceEvents" in doc:
            return gating_from_trace(doc["traceEvents"])
        rows = doc.get("rows")
    elif isinstance(doc, list):
        rows = doc
    if isinstance(rows, list):
        g = _gating_from_gate_rows(rows)
        if g:
            return g
        per_row = [
            r["gating"]
            for r in rows
            if isinstance(r, dict) and isinstance(r.get("gating"), dict) and r["gating"]
        ]
        if per_row:
            acc: Dict[str, float] = {}
            for g in per_row:
                for phase in sorted(g):
                    acc[phase] = acc.get(phase, 0.0) + g[phase]
            return {phase: round(acc[phase] / len(per_row), 4) for phase in sorted(acc)}
    raise ValueError(
        f"{path}: no gating evidence (expected series .jsonl, forensics "
        "bundle, rows with a 'gating' field, or a Chrome trace)"
    )


def gating_shifts(
    old: Dict[str, float], new: Dict[str, float], tol: float = 0.10
) -> List[Dict[str, Any]]:
    """Phase-share shifts beyond ``tol`` ABSOLUTE share points between
    two gating histograms (inline twin of ``critpath.diff_gating``) —
    'coin went from gating 20% of epochs to 45%' is a >tol shift even
    though both captures pass every throughput gate."""
    out: List[Dict[str, Any]] = []
    for phase in sorted(set(old) | set(new)):
        a, b = old.get(phase, 0.0), new.get(phase, 0.0)
        if abs(b - a) > tol:
            out.append(
                {
                    "phase": phase,
                    "old": round(a, 4),
                    "new": round(b, 4),
                    "shift": round(b - a, 4),
                }
            )
    return out


def report_critical_path(paths: List[str], tol: float) -> int:
    if len(paths) == 1:
        gating = load_gating(paths[0])
        if not gating:
            print(f"{paths[0]}: no gated epochs")
            return 0
        print(f"{'gating phase':>18} {'share':>7}")
        for phase in sorted(gating, key=lambda p: (-gating[p], p)):
            print(f"{phase:>18} {gating[phase]:>6.1%}")
        return 0
    old, new = load_gating(paths[0]), load_gating(paths[1])
    shifts = gating_shifts(old, new, tol)
    shifted = {s["phase"] for s in shifts}
    print(f"{'gating phase':>18} {'old':>7} {'new':>7} {'shift':>8}")
    for phase in sorted(set(old) | set(new)):
        a, b = old.get(phase, 0.0), new.get(phase, 0.0)
        flag = "  SHIFT" if phase in shifted else ""
        print(f"{phase:>18} {a:>6.1%} {b:>6.1%} {b - a:>+7.1%}{flag}")
    print(
        f"{len(shifts)} gating shift(s) beyond {tol:.0%} share points "
        f"across {len(set(old) | set(new))} phases"
    )
    return 1 if shifts else 0


# ---------------------------------------------------------------------------
# forensics bundles (obs/flight.py dumps, validated dependency-free)
# ---------------------------------------------------------------------------


def validate_forensics(doc: Any) -> List[str]:
    """Structural checks on a flight-recorder bundle (inline twin of
    ``obs/flight.validate_bundle``): required keys, version/kind,
    monotonic frame epochs, gating shares in range and summing to 1,
    every phase name inside the critpath registry."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return ["bundle is not a JSON object"]
    for k in REQUIRED_FORENSICS_KEYS:
        if k not in doc:
            errors.append(f"missing key {k!r}")
    if errors:
        return errors
    if doc["version"] != 1:
        errors.append(f"unknown version {doc['version']!r}")
    if doc["kind"] != "forensics":
        errors.append(f"kind is {doc['kind']!r}, not 'forensics'")
    frames = doc["frames"]
    if not isinstance(frames, list) or not frames:
        errors.append("frames must be a non-empty list")
        return errors
    prev = None
    for i, fr in enumerate(frames):
        ep = fr.get("epoch") if isinstance(fr, dict) else None
        if not isinstance(ep, int):
            errors.append(f"frame {i} has no integer epoch")
            continue
        if prev is not None and ep < prev:
            errors.append(f"frame epochs not monotonic at index {i} ({ep} < {prev})")
        prev = ep
    cp = doc["critical_path"]
    if not isinstance(cp, dict) or "gating" not in cp or "paths" not in cp:
        errors.append("critical_path must hold 'gating' and 'paths'")
        return errors
    share_sum = 0.0
    for phase in sorted(cp["gating"]):
        share = cp["gating"][phase]
        if phase not in CRITPATH_PHASES:
            errors.append(f"gating phase {phase!r} not in the critpath registry")
        if not 0.0 <= share <= 1.0001:
            errors.append(f"gating share out of range for {phase!r}: {share}")
        share_sum += share
    if cp["gating"] and not 0.99 <= share_sum <= 1.01:
        errors.append(f"gating shares sum to {share_sum:.4f}, not 1")
    for j, p in enumerate(cp["paths"]):
        if p.get("gate_phase") not in CRITPATH_PHASES:
            errors.append(f"path {j} gate_phase {p.get('gate_phase')!r} unknown")
    return errors


def summarize_forensics(doc: Dict[str, Any]) -> List[str]:
    """Human summary lines for a valid bundle (mirrors
    ``obs/flight.summarize_bundle``)."""
    frames = doc.get("frames", [])
    epochs = [fr.get("epoch") for fr in frames if isinstance(fr.get("epoch"), int)]
    span = f"epochs {min(epochs)}..{max(epochs)}" if epochs else "no epochs"
    lines = [
        f"forensics: reason={doc.get('reason')!r} frames={len(frames)} ({span})",
    ]
    ctx = doc.get("context") or {}
    cell = ctx.get("cell") if isinstance(ctx, dict) else None
    if isinstance(cell, dict):
        axes = "x".join(
            str(cell.get(k))
            for k in ("attack", "schedule", "churn", "crash", "traffic")
        )
        lines.append(f"  cell: {axes} n={cell.get('n')} seed={cell.get('seed')}")
    cp = doc.get("critical_path") or {}
    if cp.get("gate"):
        lines.append(f"  gate: {cp['gate']}")
    gating = cp.get("gating") or {}
    for phase in sorted(gating, key=lambda p: (-gating[p], p)):
        lines.append(f"  gating {phase}: {gating[phase] * 100:.1f}%")
    why = doc.get("why") or {}
    summary = why.get("summary") if isinstance(why, dict) else None
    if summary:
        lines.append(f"  why: {summary[0]}")
    faults = doc.get("faults") or []
    kinds: Dict[str, int] = {}
    for t in faults:
        kind = t[2] if isinstance(t, (list, tuple)) and len(t) == 3 else repr(t)
        kinds[kind] = kinds.get(kind, 0) + 1
    for kind in sorted(kinds):
        lines.append(f"  fault {kind}: {kinds[kind]}")
    return lines


def report_forensics(paths: List[str]) -> int:
    rc = 0
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"{path}: UNREADABLE ({e})")
            rc = 1
            continue
        errors = validate_forensics(doc)
        if errors:
            print(f"{path}: INVALID ({len(errors)} error(s))")
            for e in errors[:20]:
                print("  " + e)
            rc = 1
            continue
        lines = summarize_forensics(doc)
        print(f"{path}: valid")
        for line in lines:
            print("  " + line)
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="+", help="TRACE, or OLD NEW with --diff")
    p.add_argument(
        "--diff", action="store_true",
        help="treat the two paths as BENCH_rows.json files to compare",
    )
    p.add_argument(
        "--faults", action="store_true",
        help="diff fault-kind counts between two BENCH_rows.json files "
        "(rows carrying a fault_kinds field, e.g. scenario_matrix); "
        "exit 1 when a previously-detected kind vanished",
    )
    p.add_argument(
        "--traffic", action="store_true",
        help="diff qhb_traffic throughput/latency curves cell by cell "
        "between two BENCH_rows.json files; a >tol tx/s drop or >tol "
        "p99 commit-latency rise exits 1",
    )
    p.add_argument(
        "--critical-path", action="store_true",
        help="print the run-level gating histogram from gating evidence "
        "(series .jsonl / forensics bundle / rows with 'gating' / Chrome "
        "trace); with two paths, diff them — a phase share shifting more "
        "than --tol absolute points exits 1",
    )
    p.add_argument(
        "--forensics", action="store_true",
        help="validate each flight-recorder forensics bundle and print "
        "its summary; exit 1 when any bundle is invalid",
    )
    p.add_argument(
        "--tol", type=float, default=0.10,
        help="relative drop flagged as a regression (default 0.10)",
    )
    p.add_argument(
        "--device-seconds", type=float, default=None,
        help="validate that the trace's device=True spans sum to this "
        "counter value within --device-tol (exit 1 on mismatch) — the "
        "pipelined-dispatch acceptance check",
    )
    p.add_argument(
        "--device-tol", type=float, default=0.05,
        help="relative tolerance for --device-seconds (default 0.05)",
    )
    p.add_argument(
        "--host-buckets", type=float, default=None,
        help="validate that the trace's host=True bucket spans sum to "
        "this counter value (counters.host_seconds) within --device-tol "
        "AND that the 'other' (unattributed) bucket stays under "
        "--host-unattributed-max of it; exit 1 on mismatch — the host-"
        "attribution acceptance check",
    )
    p.add_argument(
        "--host-unattributed-max", type=float, default=0.10,
        help="max unattributed ('other') share for --host-buckets "
        "(default 0.10)",
    )
    p.add_argument(
        "--host-folded-device", type=float, default=0.0,
        help="device seconds of work folded OUT of the host buckets by "
        "the device erasure/hash plane (counters.device_seconds_rs_enc "
        "+ _rs_dec + _merkle); the --host-buckets unattributed bound is "
        "judged against host_seconds + this, so a run with encode/"
        "rs_merkle legitimately ~0 does not trip the gate",
    )
    args = p.parse_args(argv)
    if args.forensics:
        return report_forensics(args.paths)
    if args.critical_path:
        if len(args.paths) not in (1, 2):
            p.error("--critical-path takes one path (report) or two (diff)")
        return report_critical_path(args.paths, args.tol)
    if args.traffic:
        if len(args.paths) != 2:
            p.error("--traffic needs exactly two BENCH_rows.json paths")
        rc = report_traffic(args.paths[0], args.paths[1], args.tol)
        if args.diff:
            rc = max(rc, report_diff(args.paths[0], args.paths[1], args.tol))
        return rc
    if args.faults:
        if len(args.paths) != 2:
            p.error("--faults needs exactly two BENCH_rows.json paths")
        rc = report_faults(args.paths[0], args.paths[1])
        if args.diff:
            rc = max(rc, report_diff(args.paths[0], args.paths[1], args.tol))
        return rc
    if args.diff:
        if len(args.paths) != 2:
            p.error("--diff needs exactly two BENCH_rows.json paths")
        return report_diff(args.paths[0], args.paths[1], args.tol)
    if len(args.paths) != 1:
        p.error("exactly one trace path (or --diff OLD NEW)")
    return report(
        args.paths[0], args.device_seconds, args.device_tol,
        args.host_buckets, args.host_unattributed_max,
        args.host_folded_device,
    )


if __name__ == "__main__":
    sys.exit(main())
