"""Microbenchmark: raw Fq limb-multiply throughput on the current backend.

Timing methodology (important): the tunneled TPU platform ("axon") both
memoizes identical dispatches AND returns from ``block_until_ready``
before the computation has really finished, so naive timing reports
physically impossible numbers (hundreds of Tflop/s).  The only reliable
sync is a host transfer.  Every measurement here therefore (a) chains N
data-dependent multiplies inside one jitted scan so the work cannot be
elided or overlapped, (b) uses fresh input buffers per call, and (c)
fetches one element to host as the fence.  The scan makes the fetch
round-trip amortize to latency/N per multiply.

    python tools/kernel_bench.py
    HBBFT_TPU_CONV_MODE=concat python tools/kernel_bench.py
    HBBFT_TPU_NO_PALLAS=1 python tools/kernel_bench.py
"""

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from hbbft_tpu.ops import fq

CHAIN = int(os.environ.get("KB_CHAIN", "400"))  # muls per timed dispatch
LANES = [
    int(x) for x in os.environ.get("KB_LANES", "4096,16384,65536,262144").split(",")
]


@functools.partial(jax.jit, static_argnums=2)
def _mul_chain(a, b, n):
    def step(x, _):
        return fq.mul(x, b), None
    out, _ = jax.lax.scan(step, a, None, length=n)
    return out


def _rand_limbs(rng, lanes):
    return jnp.asarray(
        rng.integers(0, fq.BASE, size=(lanes, fq.NLIMBS)).astype(fq.NP_DTYPE)
    )


def _fence(x):
    """Host-fetch fence: returns only when the device really finished."""
    return np.asarray(x[0, :1])


def measure_mul(rng, lanes, reps=2):
    b = _rand_limbs(rng, lanes)
    _fence(_mul_chain(_rand_limbs(rng, lanes), b, CHAIN))  # compile+warm
    best = float("inf")
    for _ in range(reps):
        a = _rand_limbs(rng, lanes)
        _fence(a)  # materialize input before timing
        t0 = time.perf_counter()
        _fence(_mul_chain(a, b, CHAIN))
        best = min(best, (time.perf_counter() - t0) / CHAIN)
    return best


def main():
    rng = np.random.default_rng(0)
    impl = os.environ.get("HBBFT_TPU_FQ_IMPL", "rns")
    limb_only = (
        f" BITS={fq.BITS}"
        f" conv_mode={os.environ.get('HBBFT_TPU_CONV_MODE', 'scratch')}"
        f" no_pallas={bool(os.environ.get('HBBFT_TPU_NO_PALLAS'))}"
        if impl == "limb"
        else ""
    )
    print(
        f"backend={jax.default_backend()} impl={impl} "
        f"width={fq.NLIMBS}{limb_only}"
    )
    # Under impl=rns the random inputs are valid residue VECTORS (every
    # lane in range); the represented values are arbitrary, which is fine
    # for throughput — the pipeline is branch-free and data-independent.
    for lanes in LANES:
        dt = measure_mul(rng, lanes)
        print(
            f"lanes={lanes:7d}  fq.mul: {dt*1e3:8.4f} ms  "
            f"{lanes/dt/1e6:8.2f} M muls/s"
        )

    if os.environ.get("KB_NO_ROOFLINE"):
        return  # bench.py's subprocess A/B skips the fixed-size probe

    # Fused whole-chain RNS kernel (ops/fq_rns_pallas.mul_chain): the
    # entire Montgomery pipeline resident in VMEM, n muls per launch —
    # the compute-ceiling probe for the ≥2G muls/s target (round-3
    # verdict task 2).  TPU only (interpret mode would measure Python);
    # KB_FUSED=interpret forces a tiny interpret-mode sanity run.
    kb_fused = os.environ.get("KB_FUSED", "auto")
    on_tpu = jax.default_backend() == "tpu"
    if impl == "rns" and kb_fused != "0" and (on_tpu or kb_fused == "interpret"):
        from hbbft_tpu.ops import fq_rns_pallas

        interp = not on_tpu
        chain = CHAIN if on_tpu else min(CHAIN, 4)
        for lanes in LANES if on_tpu else [512]:
            b = _rand_limbs(rng, lanes)
            run = lambda aa: _fence(  # noqa: E731
                fq_rns_pallas.mul_chain(aa, b, chain, interpret=interp)
            )
            run(_rand_limbs(rng, lanes))  # compile+warm
            best = float("inf")
            for _ in range(2):
                a = _rand_limbs(rng, lanes)
                _fence(a)
                t0 = time.perf_counter()
                run(a)
                best = min(best, (time.perf_counter() - t0) / chain)
            print(
                f"lanes={lanes:7d}  fused-chain: {best*1e3:8.4f} ms  "
                f"{lanes/best/1e6:8.2f} M muls/s (fq_rns_pallas)"
            )

    # VPU roofline probe: same chain+fence discipline, pure FMA body.
    lanes = 262144
    rows = 50
    y = jnp.asarray(rng.random((rows, lanes)), jnp.float32)

    @functools.partial(jax.jit, static_argnums=1)
    def fma_chain(x, n):
        def step(acc, _):
            # 50 dependent FMAs over a (50, lanes) tile ~ one conv's flops
            for _ in range(rows):
                acc = acc * 1.0000001 + y
            return acc, None
        out, _ = jax.lax.scan(step, x, None, length=n)
        return out

    x = jnp.asarray(rng.random((rows, lanes)), jnp.float32)
    _ = np.asarray(fma_chain(x, 50)[0, :1])
    t0 = time.perf_counter()
    _ = np.asarray(fma_chain(x + 1.0, 50)[0, :1])
    dt = (time.perf_counter() - t0) / 50
    flops = 2 * rows * rows * lanes
    print(
        f"VPU FMA roofline probe: {dt*1e3:.4f} ms/step  "
        f"{flops/dt/1e12:.3f} Tflop/s "
        f"(= {flops/2/2500/dt/1e6:.1f} M conv-equiv muls/s, "
        f"= {flops/2/5000/dt/1e6:.1f} M rns-fused-equiv at ~5k "
        f"lane-ops/mul — the measured-ceiling yardstick for the fused "
        f"chain)"
    )


if __name__ == "__main__":
    main()
