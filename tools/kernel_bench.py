"""Microbenchmark: raw Fq limb-multiply throughput on the current backend.

Timing methodology (important): the tunneled TPU platform ("axon") both
memoizes identical dispatches AND returns from ``block_until_ready``
before the computation has really finished, so naive timing reports
physically impossible numbers (hundreds of Tflop/s).  The only reliable
sync is a host transfer.  Every measurement here therefore (a) chains N
data-dependent multiplies inside one jitted scan so the work cannot be
elided or overlapped, (b) uses fresh input buffers per call, and (c)
fetches one element to host as the fence.  The scan makes the fetch
round-trip amortize to latency/N per multiply.

    python tools/kernel_bench.py
    HBBFT_TPU_CONV_MODE=concat python tools/kernel_bench.py
    HBBFT_TPU_NO_PALLAS=1 python tools/kernel_bench.py
"""

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from hbbft_tpu.ops import fq

CHAIN = int(os.environ.get("KB_CHAIN", "400"))  # muls per timed dispatch
LANES = [
    int(x) for x in os.environ.get("KB_LANES", "4096,16384,65536,262144").split(",")
]


@functools.partial(jax.jit, static_argnums=2)
def _mul_chain(a, b, n):
    def step(x, _):
        return fq.mul(x, b), None
    out, _ = jax.lax.scan(step, a, None, length=n)
    return out


def _rand_limbs(rng, lanes):
    return jnp.asarray(
        rng.integers(0, fq.BASE, size=(lanes, fq.NLIMBS)).astype(fq.NP_DTYPE)
    )


def _fence(x):
    """Host-fetch fence: returns only when the device really finished."""
    return np.asarray(x[0, :1])


def measure_mul(rng, lanes, reps=2):
    b = _rand_limbs(rng, lanes)
    _fence(_mul_chain(_rand_limbs(rng, lanes), b, CHAIN))  # compile+warm
    best = float("inf")
    for _ in range(reps):
        a = _rand_limbs(rng, lanes)
        _fence(a)  # materialize input before timing
        t0 = time.perf_counter()
        _fence(_mul_chain(a, b, CHAIN))
        best = min(best, (time.perf_counter() - t0) / CHAIN)
    return best


def main():
    rng = np.random.default_rng(0)
    impl = os.environ.get("HBBFT_TPU_FQ_IMPL", "rns")
    limb_only = (
        f" BITS={fq.BITS}"
        f" conv_mode={os.environ.get('HBBFT_TPU_CONV_MODE', 'scratch')}"
        f" no_pallas={bool(os.environ.get('HBBFT_TPU_NO_PALLAS'))}"
        if impl == "limb"
        else ""
    )
    print(
        f"backend={jax.default_backend()} impl={impl} "
        f"width={fq.NLIMBS}{limb_only}"
    )
    # Under impl=rns the random inputs are valid residue VECTORS (every
    # lane in range); the represented values are arbitrary, which is fine
    # for throughput — the pipeline is branch-free and data-independent.
    for lanes in LANES:
        dt = measure_mul(rng, lanes)
        print(
            f"lanes={lanes:7d}  fq.mul: {dt*1e3:8.4f} ms  "
            f"{lanes/dt/1e6:8.2f} M muls/s"
        )

    # Fused whole-chain RNS kernel (ops/fq_rns_pallas.mul_chain): the
    # entire Montgomery pipeline resident in VMEM, n muls per launch —
    # the compute-ceiling probe for the ≥2G muls/s target (round-3
    # verdict task 2).  TPU only (interpret mode would measure Python);
    # KB_FUSED=interpret forces a tiny interpret-mode sanity run.
    kb_fused = os.environ.get("KB_FUSED", "auto")
    on_tpu = jax.default_backend() == "tpu"
    if impl == "rns" and kb_fused != "0" and (on_tpu or kb_fused == "interpret"):
        from hbbft_tpu.ops import fq_rns_pallas

        interp = not on_tpu
        chain = CHAIN if on_tpu else min(CHAIN, 4)
        for lanes in LANES if on_tpu else [512]:
            b = _rand_limbs(rng, lanes)
            run = lambda aa: _fence(  # noqa: E731
                fq_rns_pallas.mul_chain(aa, b, chain, interpret=interp)
            )
            run(_rand_limbs(rng, lanes))  # compile+warm
            best = float("inf")
            for _ in range(2):
                a = _rand_limbs(rng, lanes)
                _fence(a)
                t0 = time.perf_counter()
                run(a)
                best = min(best, (time.perf_counter() - t0) / chain)
            print(
                f"lanes={lanes:7d}  fused-chain: {best*1e3:8.4f} ms  "
                f"{lanes/best/1e6:8.2f} M muls/s (fq_rns_pallas)"
            )

    if os.environ.get("KB_NO_ROOFLINE"):
        # skip the probes only: the fused-chain sweep above still runs, so
        # HBBFT_TPU_RNS_TILE / EXT A/Bs measure the kernel they target
        # (code-review r5 finding 1)
        return

    # -- corrected roofline (round-4 verdict Weak #2) -----------------------
    #
    # The old probe chained 50 *serially dependent* HBM-resident FMAs per
    # step; measured against its own printout it was HBM-bandwidth-bound
    # (52 MB read+write per unfused op at ~477 GB/s), so its "ceiling" of
    # ~0.1 Tflop/s sat 33x BELOW the fused kernel it claimed to bound.
    # The replacement measures where the fused kernel actually runs:
    #
    #   (1) a Pallas kernel with a VMEM-resident (80, TILE) tile running
    #       8 INDEPENDENT FMA chains (no latency serialization, no HBM in
    #       the loop) — the f32 VPU throughput the fused kernel's
    #       pointwise stages draw on;
    #   (2) the REAL fused chain vs the same chain with its two
    #       base-extension _split_dot stages stubbed to a pointwise op —
    #       the difference attributes per-mul time to the MXU/extension
    #       stage vs the VPU stages (itemization, not analogy).
    #
    # Per-mul op counts for the yardstick, from fq_rns_pallas._mul_core
    # (reduced=True steady state, per lane): pointwise stages touch the
    # (80,) product + mod_loose (~6 ops/row), sigma/xi mod_lanes halves
    # (~8 ops/40 rows each), split-plane prep + three mod_lanes per
    # _split_dot (~25 ops/40 rows x 2), r2r/r1 folds (~7 ops/40 rows)
    # ≈ 3.6k VPU lane-ops per mul; the four bf16 (40,40)@(40,T) dots per
    # _split_dot are 2 x 12.8k MXU MACs per mul.
    _VPU_OPS_PER_MUL = 3600.0
    if jax.default_backend() == "tpu":
        from jax.experimental import pallas as pl

        probe_rows, probe_tile, probe_iters, probe_chains = 80, 512, 2000, 8

        def _vpu_probe_kernel(x_ref, o_ref):
            x = x_ref[:]
            accs = [x * (1.0 + 1e-6 * i) for i in range(probe_chains)]

            def body(_, accs):
                # 8 independent FMA chains: throughput-form, not latency
                return [a * 1.0000001 + x for a in accs]

            accs = jax.lax.fori_loop(0, probe_iters, body, accs)
            out = accs[0]
            for a in accs[1:]:
                out = out + a
            o_ref[:] = out

        probe = pl.pallas_call(
            _vpu_probe_kernel,
            out_shape=jax.ShapeDtypeStruct((probe_rows, probe_tile), jnp.float32),
        )
        xp = jnp.asarray(rng.random((probe_rows, probe_tile)), jnp.float32)
        _fence(probe(xp))  # compile+warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            _fence(probe(xp))
            best = min(best, time.perf_counter() - t0)
        flops = 2.0 * probe_chains * probe_iters * probe_rows * probe_tile
        vpu_tops = flops / best / 1e12
        print(
            f"VPU probe (VMEM-resident, {probe_chains} independent chains): "
            f"{best*1e3:.4f} ms  {vpu_tops:.3f} Tflop/s f32 "
            f"=> fused-kernel VPU-stage ceiling ~"
            f"{vpu_tops*1e12/_VPU_OPS_PER_MUL/1e6:.0f} M muls/s at "
            f"{_VPU_OPS_PER_MUL:.0f} lane-ops/mul"
        )

        if impl == "rns" and kb_fused != "0":
            # stage itemization: full fused chain vs ext-stubbed chain
            from hbbft_tpu.ops import fq_rns_pallas as RP

            lanes_it = 262144
            chain_it = 200

            def timed_chain() -> float:
                b = _rand_limbs(rng, lanes_it)
                a = _rand_limbs(rng, lanes_it)
                _fence(RP.mul_chain(a, b, chain_it))  # compile+warm
                best = float("inf")
                for _ in range(2):
                    a2 = _rand_limbs(rng, lanes_it)
                    _fence(a2)
                    t0 = time.perf_counter()
                    _fence(RP.mul_chain(a2, b, chain_it))
                    best = min(best, (time.perf_counter() - t0) / chain_it)
                return best

            t_full = timed_chain()
            orig_split = RP._split_dot
            orig_cache = RP._chain_call
            try:
                RP._split_dot = lambda elo, ehi, v, p, invp: RP._mod_lanes(
                    v * 1.0000001, p, invp
                )
                RP._chain_call.cache_clear()  # force retrace with the stub
                t_stub = timed_chain()
            finally:
                RP._split_dot = orig_split
                orig_cache.cache_clear()  # drop stubbed traces
            ext = max(t_full - t_stub, 0.0)
            print(
                f"fused-chain stage split @ {lanes_it} lanes: "
                f"full {t_full*1e6:.2f} us/mul = "
                f"VPU-stages {t_stub*1e6:.2f} us "
                f"+ ext/MXU {ext*1e6:.2f} us "
                f"({100*ext/max(t_full,1e-12):.0f}% extension) "
                f"=> zero-ext ceiling {lanes_it/t_stub/1e6:.0f} M muls/s"
            )


if __name__ == "__main__":
    main()
