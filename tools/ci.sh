#!/usr/bin/env bash
# Single CI entry point for the static + concurrency gates (PR 9).
#
#   bash tools/ci.sh
#
# Runs, in order:
#   1. tools/lint.py --ci   — the custom AST rule families (seam-race,
#      byzantine-input, determinism, handler-exhaustiveness,
#      tracer-safety, deferred-fetch, glv-table-order, snapshot-coverage,
#      replay-purity, hook-detachment, plus the stale-suppression pass)
#      against tools/lint_baseline.json, and ruff when the binary is
#      installed (skipped cleanly otherwise — no dependency is
#      downloaded).  Findings are also written as schema-pinned JSON
#      (--json) and the CI annotations below are emitted from that
#      document, never by parsing the human text.
#   2. tools/race_explorer.py --smoke — the schedule-space smoke sweep
#      over the pipeline / traffic-hook / virtualnet seams.
#   3. tools/soak.py --smoke — one composed-gauntlet cell (equivocator x
#      partition-heal x churn x crash+restart x 1x traffic), run twice,
#      fingerprint-stable, ~2 s deterministic.
#   4. the forensics round-trip — tools/soak.py --smoke-fail starves the
#      smoke cell's crank budget so it fails mid-gauntlet just after the
#      crash/restart, asserts the flight recorder auto-dumped a valid
#      bundle naming the injected fault's phase, then
#      tools/trace_report.py --forensics re-validates the written bundle
#      dependency-free (the two validators are inline twins; the guard
#      test pins them against each other).
#
# Output is deterministic (lint findings are sorted; the explorer's
# run/class/prune counts and the soak cell's fingerprint are seeded), so
# CI diffs are meaningful.  Exit status is nonzero iff any stage found a
# new finding, a schedule divergence, or a failed soak verdict.  Budget:
# the whole script is a few seconds on one CPU core (no JAX import on
# any path) — tests/test_race_explorer.py pins it under 60 s in tier-1.

set -u -o pipefail
cd "$(dirname "$0")/.."

PY="${PYTHON:-python}"
rc=0

echo "== ci: lint (custom rule families + ruff if installed) =="
LINT_JSON="${TMPDIR:-/tmp}/hbbft_ci_lint.json"
"$PY" tools/lint.py --ci --json "$LINT_JSON" || rc=1
# CI annotations come from the schema-pinned JSON document, not from the
# human-readable transcript above (stable even if the text format moves)
"$PY" - "$LINT_JSON" <<'ANNOTATE'
import json, sys
doc = json.load(open(sys.argv[1], encoding="utf-8"))
assert doc["schema"] == "hbbft-tpu-lint/1", doc["schema"]
for f in doc["new"]:
    print(
        "::error file={path},line={line},col={col},title=lint:{rule}::{message}".format(**f)
    )
ANNOTATE

echo "== ci: schedule-space race explorer (smoke sweep) =="
"$PY" tools/race_explorer.py --smoke || rc=1

echo "== ci: composed-gauntlet soak (smoke cell) =="
"$PY" tools/soak.py --smoke || rc=1

echo "== ci: forensics round-trip (flight-recorder dump + re-validate) =="
FDIR="${TMPDIR:-/tmp}/hbbft_ci_forensics"
rm -rf "$FDIR"
"$PY" tools/soak.py --smoke-fail --fail-dir "$FDIR" || rc=1
"$PY" tools/trace_report.py --forensics "$FDIR"/*.forensics.json || rc=1

if [ "$rc" -ne 0 ]; then
    echo "ci: FAILED"
else
    echo "ci: ok"
fi
exit "$rc"
