#!/bin/bash
# TPU-window runbook: run this THE MOMENT /tmp/tpu_alive exists (the
# tunnel died for all of rounds 2-3; treat every live window as
# preemptible — capture in strict priority order, flush after each step).
#
#   bash tools/tpu_window.sh | tee -a /tmp/tpu_window.log
#
# Round-4 priority order (VERDICT r3 "Next round" tasks 1-5):
#   1. limb-vs-RNS kernel A/B on-chip (decides RNS default promotion)
#      + the fused-chain VMEM-ceiling probe (fq_rns_pallas, task 2)
#   2. flagship crypto rows + n16 real-crypto macro under RNS
#   3. the same flagship subset under limb (graph-level A/B)
#   4. N=100 f=33 real-crypto epochs (>=10, one era change) — the
#      north star at its defined shape (task 3)
#   5. config 2 at size: 10k coin flips, N=64 (task 5)
#   6. full driver bench (fills every remaining row on TPU)
#   7. RS encode int8-vs-bf16 dot A/B (task 4)
#   8. per-mul fused RNS A/B (HBBFT_TPU_RNS_FUSED=all vs pow)
#   9. extension-matmul strategy A/B (HBBFT_TPU_RNS_EXT highest/bf16/int8)
# Each bench.py run OVERWRITES BENCH_rows.json with its own row set, so
# a snapshot is copied to tpu_window_r04/ after every step — the
# archive is the snapshot directory, and a dying tunnel can only lose
# the CURRENT step.
set -u
cd "$(dirname "$0")/.."
TS() { date -u +%H:%M:%S; }
ART=tpu_window_r04
mkdir -p "$ART"
SNAP() { cp -f BENCH_rows.json "$ART/rows_after_$1.json" 2>/dev/null || true; }
# Abort between steps when the tunnel has died: the remaining steps
# would silently run (and record) CPU fallback instead, overwriting
# BENCH_rows.json with cpu rows and burning the wall clock.  A FRESH
# watcher flag (<400s, the bench.py staleness bound) short-circuits;
# otherwise — flag missing (watcher restarting?) or stale (watcher
# dead?) — one direct probe decides, so neither case misfires.
ALIVE() {
  if [ -f /tmp/tpu_alive ]; then
    age=$(( $(date +%s) - $(stat -c %Y /tmp/tpu_alive 2>/dev/null || echo 0) ))
    [ "$age" -lt 400 ] && return 0
  fi
  out=$(timeout 120 python -c "
import jax, numpy as np, jax.numpy as jnp
v = float(np.asarray(jnp.ones((8,8)) @ jnp.ones((8,8)))[0][0])
assert jax.devices()[0].platform in ('tpu', 'axon')
print('OK')" 2>/dev/null | grep -c '^OK')
  if [ "$out" != "1" ]; then
    echo "=== $(TS) tunnel died — aborting window capture ==="
    exit 1
  fi
}

echo "=== $(TS) step 1: kernel A/B limb vs rns (+fused-chain probe) ==="
timeout 1200 python tools/kernel_bench.py 2>&1 | tee "$ART/kernel_limb.log"
HBBFT_TPU_FQ_IMPL=rns timeout 1800 python tools/kernel_bench.py 2>&1 \
  | tee "$ART/kernel_rns.log"

ALIVE
echo "=== $(TS) step 2: flagship rows + n16 real-crypto under rns ==="
HBBFT_TPU_FQ_IMPL=rns \
  BENCH_ONLY=rlc_dec,rlc_sig,coin_e2e,g2_sign,share_verify,rlc_dec_adversarial,array_n16_tpu \
  timeout 3600 python bench.py
SNAP step2_rns

ALIVE
echo "=== $(TS) step 3: rlc_dec + coin under limb (graph A/B) ==="
BENCH_ONLY=rlc_dec,coin_e2e timeout 1800 python bench.py
SNAP step3_limb

ALIVE
echo "=== $(TS) step 4: N=100 real-crypto epochs + era change ==="
HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n100 BENCH_ARRAY_BACKEND=tpu \
  BENCH_ARRAY_EPOCHS=10 BENCH_ARRAY_CHURN=1 \
  timeout 5400 python bench.py
SNAP step4_n100

ALIVE
echo "=== $(TS) step 5: config 2 at size (10k flips; n64 coin macro) ==="
HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=coin_e2e BENCH_COIN_FLIPS=10000 \
  timeout 3600 python bench.py
SNAP step5_flips
HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n64_coin BENCH_COIN_MACRO_BACKEND=tpu \
  timeout 1800 python bench.py
SNAP step5_macro

ALIVE
echo "=== $(TS) step 6: full driver bench (tpu; fq A/B inside) ==="
HBBFT_TPU_FQ_IMPL=rns timeout 5400 python bench.py
cp -f BENCH_rows.json "$ART/rows_full_rns.json" 2>/dev/null || true

ALIVE
echo "=== $(TS) step 7: RS encode (int8 vs bf16 dot A/B) ==="
BENCH_ONLY=rs_encode timeout 900 python bench.py
BENCH_ONLY=rs_encode HBBFT_TPU_GF_DOT=bf16 timeout 900 python bench.py
BENCH_ONLY=rs_encode HBBFT_TPU_GF_DOT=bf16 BENCH_RS_SHARD=65536 \
  timeout 900 python bench.py
SNAP step7_rs

ALIVE
echo "=== $(TS) step 8: per-mul fused RNS A/B on the flagship row ==="
HBBFT_TPU_FQ_IMPL=rns HBBFT_TPU_RNS_FUSED=all BENCH_ONLY=rlc_dec \
  timeout 1800 python bench.py
SNAP step8_fused_all

ALIVE
echo "=== $(TS) step 9: extension-matmul strategy A/B (single size) ==="
# HIGHEST (6 MXU passes) vs explicit bf16 planes (4) vs int8 MXU
HBBFT_TPU_FQ_IMPL=rns HBBFT_TPU_RNS_EXT=bf16 KB_FUSED=0 KB_NO_ROOFLINE=1 \
  KB_LANES=65536 timeout 900 python tools/kernel_bench.py 2>&1 \
  | tee "$ART/kernel_rns_bf16.log"
HBBFT_TPU_FQ_IMPL=rns HBBFT_TPU_RNS_EXT=int8 KB_FUSED=0 KB_NO_ROOFLINE=1 \
  KB_LANES=65536 timeout 900 python tools/kernel_bench.py 2>&1 \
  | tee "$ART/kernel_rns_int8.log"
# if either wins on the rlc_dec graph too, promote via env default:
HBBFT_TPU_FQ_IMPL=rns HBBFT_TPU_RNS_EXT=int8 BENCH_ONLY=rlc_dec \
  timeout 1200 python bench.py
SNAP step9_ext_ab

echo "=== $(TS) done — snapshots in $ART/ ==="
