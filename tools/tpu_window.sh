#!/bin/bash
# TPU-window runbook: run this THE MOMENT /tmp/tpu_alive exists (the
# tunnel died repeatedly in rounds 2-3; treat every live window as
# preemptible — capture in strict priority order, flush after each step).
#
#   bash tools/tpu_window.sh | tee -a /tmp/tpu_window.log
#
# Priority order (round-2 verdict Missing #1 / round-3 plan):
#   1. full driver bench -> the official BENCH artifact rows, platform=tpu
#      (includes the new coin_flips_per_sec, rlc_dec_verify_adversarial,
#      100-epoch n100 macro with era change, 10-epoch n256 soak)
#   2. kernel A/B limb vs RNS (tools/kernel_bench.py both impls)
#   3. rlc_dec + coin rows under HBBFT_TPU_FQ_IMPL=rns (promotion A/B)
#   4. N=100 real-crypto epoch (replaces PERF.md's "expected 180-200s")
#   5. RS-encode profile (verdict Weak #6)
set -u
cd "$(dirname "$0")/.."
TS() { date -u +%H:%M:%S; }

echo "=== $(TS) step 1: full driver bench (tpu) ==="
# BENCH_FQ=0: step 2 runs the kernel A/B dedicated; keep step 1's budget
# for the macro rows it exists to capture.
BENCH_FQ=0 timeout 3600 python bench.py

echo "=== $(TS) step 2: kernel A/B limb vs rns ==="
timeout 1200 python tools/kernel_bench.py
HBBFT_TPU_FQ_IMPL=rns timeout 1200 python tools/kernel_bench.py

echo "=== $(TS) step 3: backend rows under rns ==="
HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=rlc_dec,rlc_sig,coin_e2e,g2_sign,share_verify,rlc_dec_adversarial \
  timeout 2400 python bench.py

echo "=== $(TS) step 4: N=100 real-crypto array epoch ==="
BENCH_ONLY=array_n100 BENCH_ARRAY_BACKEND=tpu BENCH_ARRAY_EPOCHS=1 BENCH_ARRAY_CHURN=0 \
  timeout 3600 python bench.py

echo "=== $(TS) step 5: RS encode (int8 vs bf16 dot A/B) ==="
BENCH_ONLY=rs_encode timeout 900 python bench.py
BENCH_ONLY=rs_encode HBBFT_TPU_GF_DOT=bf16 timeout 900 python bench.py
BENCH_ONLY=rs_encode HBBFT_TPU_GF_DOT=bf16 BENCH_RS_SHARD=65536 timeout 900 python bench.py

echo "=== $(TS) done ==="
