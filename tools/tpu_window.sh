#!/bin/bash
# TPU-window runbook, round 5 — RESUMABLE. Run the moment /tmp/tpu_alive
# exists (the tunnel died for all of rounds 2-3 and round 4's window died
# after step 3 of 9; treat every live window as preemptible).
#
#   bash tools/tpu_window.sh | tee -a /tmp/tpu_window.log
#   bash tools/tpu_window.sh --list     # show skip/pending, run nothing
#
# Round-4 postmortem (VERDICT r4 Weak #4): the runbook ran strictly
# top-to-bottom and re-measured already-recorded steps while the
# highest-value uncaptured step (the N=100 north star) waited; the window
# died first. This version CONTENT-CHECKS each step's snapshot and runs
# only missing steps, highest priority first — so a resumed window's
# first minute goes to the top uncaptured item.
#
# Round-5 priority (VERDICT r4 "Next round" tasks):
#   n100          north star: N=100 f=33 real-crypto >=10 epochs + era
#                 change on TpuBackend (task 1)
#   matrix_*      cross-impl flagship matrix, INTERLEAVED trials
#                 (rns/limb/rns/limb) for the variance note (task 3,
#                 Weak #1/#7): share_verify, rlc_sig, g2_sign, rlc_dec,
#                 coin under both impls from one window
#   flips10k      config 2 at size: 10k coin flips N=64 (task 5)
#   n64coin       n64 real-coin macro on TpuBackend (task 5b)
#   rs_ab         RS encode dot-strategy A/B + shard sweep (task 6)
#   kernel_levers TILE sweep / RNS_FUSED=all / EXT strategies with the
#                 corrected throughput roofline (task 4)
#   driver_budget full flagship-first BENCH_BUDGET bench — exactly what
#                 the driver will run, validated on-chip (task 2b/8)
#
# Each bench.py run OVERWRITES BENCH_rows.json, so a snapshot is copied
# into $ART after every step; the archive is the snapshot directory and a
# dying tunnel can only lose the CURRENT step.
set -u
cd "$(dirname "$0")/.."
TS() { date -u +%H:%M:%S; }
ART=${TPU_WINDOW_ART:-tpu_window_r05}
mkdir -p "$ART"
SNAP() { cp -f BENCH_rows.json "$ART/rows_after_$1.json" 2>/dev/null || true; }

# has_row FILE METRIC [key=value ...] — true when FILE has a completed row
# for METRIC matching every key=value (content check, not existence: a
# crashed step leaves a snapshot without its row and must re-run).
has_row() {
  python - "$@" <<'PY'
import json, sys
path, metric = sys.argv[1], sys.argv[2]
want = dict(kv.split("=", 1) for kv in sys.argv[3:])
try:
    rows = json.load(open(path)).get("rows", [])
except Exception:
    sys.exit(1)
for r in rows:
    if r.get("metric") != metric or "value" not in r:
        continue
    if all(str(r.get(k)) == v for k, v in want.items()):
        sys.exit(0)
sys.exit(1)
PY
}

# Abort when the tunnel has died: the remaining steps would silently run
# (and record) CPU fallback instead. A FRESH watcher flag (<400s, the
# bench.py staleness bound) short-circuits; otherwise one direct probe
# decides.
ALIVE() {
  if [ -f /tmp/tpu_alive ]; then
    age=$(( $(date +%s) - $(stat -c %Y /tmp/tpu_alive 2>/dev/null || echo 0) ))
    [ "$age" -lt 400 ] && return 0
  fi
  out=$(timeout 120 python -c "
import jax, numpy as np, jax.numpy as jnp
v = float(np.asarray(jnp.ones((8,8)) @ jnp.ones((8,8)))[0][0])
assert jax.devices()[0].platform in ('tpu', 'axon')
print('OK')" 2>/dev/null | grep -c '^OK')
  if [ "$out" != "1" ]; then
    echo "=== $(TS) tunnel died — aborting window capture ==="
    exit 1
  fi
}

MATRIX_ONLY=share_verify,rlc_sig,g2_sign,rlc_dec,coin_e2e

# --- step done-checks (content-verified) -----------------------------------
done_n100() {
  has_row "$ART/rows_after_n100.json" array_epochs_per_sec_n100 backend=TpuBackend
}
done_matrix_rns_a() {
  has_row "$ART/rows_after_matrix_rns_a.json" rlc_dec_verify_throughput fq_impl=rns
}
done_matrix_limb_a() {
  has_row "$ART/rows_after_matrix_limb_a.json" rlc_dec_verify_throughput fq_impl=limb
}
done_matrix_rns_b() {
  has_row "$ART/rows_after_matrix_rns_b.json" rlc_dec_verify_throughput fq_impl=rns
}
done_matrix_limb_b() {
  has_row "$ART/rows_after_matrix_limb_b.json" rlc_dec_verify_throughput fq_impl=limb
}
done_glv_ab() {
  has_row "$ART/rows_after_glv_ab.json" glv_ladder_ab
}
done_host_ab() {
  has_row "$ART/rows_after_host_ab.json" array_epochs_per_sec_n100 backend=TpuBackend \
    && has_row "$ART/rows_after_host_ab_off.json" array_epochs_per_sec_n100 backend=TpuBackend
}
done_flips10k() {
  has_row "$ART/rows_after_flips10k.json" coin_flips_per_sec flips=10000
}
done_n64coin() {
  has_row "$ART/rows_after_n64coin.json" array_epochs_per_sec_n64_coin backend=TpuBackend
}
done_rs_ab() {
  has_row "$ART/rows_after_rs_ab.json" rs_encode_throughput
}
done_rs_plane() {
  has_row "$ART/rows_after_rs_plane.json" rs_plane_ab
}
done_fused_chain() {
  has_row "$ART/rows_after_fused_chain.json" fused_chain_ab
}
done_kernel_levers() {
  # completion marker written at the END of the step: a mid-step death
  # must re-run it (the first sub-command already prints fused-chain
  # lines, so grepping those would mark a dead step complete)
  grep -q "KERNEL_LEVERS_COMPLETE" "$ART/kernel_levers.log" 2>/dev/null
}
done_driver_budget() {
  has_row "$ART/rows_after_driver_budget.json" rlc_dec_verify_throughput platform=tpu
}

# --- step bodies ------------------------------------------------------------
do_n100() {
  # churn=0 here: this step banks the epochs/s record first.  Churn
  # evidence comes from n16_churn / n32_churn (batched DKG + native
  # hash kernel), and the FULL 10-epoch+churn shape runs LAST as
  # n100_churn (~1.5 h era change since the native hash landed).
  # BENCH_SERIES (PR 13): per-epoch telemetry rows land next to the
  # snapshot so post-window analysis can re-gate the epochs
  # (tools/trace_report.py --critical-path "$ART/series_n100.jsonl")
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n100 BENCH_ARRAY_BACKEND=tpu \
    BENCH_ARRAY_EPOCHS=10 BENCH_ARRAY_CHURN=0 \
    BENCH_SERIES="$ART/series_n100.jsonl" \
    timeout 7200 python bench.py
}
do_matrix_rns_a()  { HBBFT_TPU_FQ_IMPL=rns  BENCH_ONLY=$MATRIX_ONLY timeout 1800 python bench.py; }
do_matrix_limb_a() { HBBFT_TPU_FQ_IMPL=limb BENCH_ONLY=$MATRIX_ONLY timeout 1800 python bench.py; }
do_matrix_rns_b()  { HBBFT_TPU_FQ_IMPL=rns  BENCH_ONLY=$MATRIX_ONLY timeout 1800 python bench.py; }
do_matrix_limb_b() { HBBFT_TPU_FQ_IMPL=limb BENCH_ONLY=$MATRIX_ONLY timeout 1800 python bench.py; }
do_glv_ab() {
  # GLV joint-table vs w2 ladder A/B (PR 4): ON-CHIP capture of the
  # 2368-vs-3810 field-mul prediction and the wall-clock delta at a real
  # dispatch shape.  In-process A/B (HBBFT_TPU_NO_GLV read per batch);
  # cheap — runs early so no window death can lose it.
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=glv_ladder BENCH_GLV_BATCH=1024 \
    timeout 1800 python bench.py
}
do_host_ab() {
  # Host-half A/B (PR 5): the N=100 shape with and without the host
  # pipeline (vectorized assembly + cross-round overlap) AND the
  # dispatch pipeline — the kill-switch arm is the strictly serial
  # pre-PR host.  3 epochs per arm keeps both inside one short window;
  # the per-bucket host split lands on each row (host_buckets field).
  # HBBFT_TPU_NO_DEVICE_RS=1 pins BOTH arms to the host codec (PR 19):
  # this step isolates the HOSTPIPE axis, and its bucket series stays
  # comparable with pre-PR-19 rounds; the device erasure/hash plane has
  # its own A/B step (rs_plane below).
  HBBFT_TPU_NO_DEVICE_RS=1 \
    HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n100 BENCH_ARRAY_BACKEND=tpu \
    BENCH_ARRAY_EPOCHS=3 BENCH_ARRAY_CHURN=0 \
    BENCH_SERIES="$ART/series_host_ab.jsonl" \
    timeout 7200 python bench.py
  SNAP host_ab
  ALIVE
  HBBFT_TPU_NO_HOSTPIPE=1 HBBFT_TPU_NO_PIPELINE=1 HBBFT_TPU_NO_DEVICE_RS=1 \
    HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n100 BENCH_ARRAY_BACKEND=tpu \
    BENCH_ARRAY_EPOCHS=3 BENCH_ARRAY_CHURN=0 \
    BENCH_SERIES="$ART/series_host_ab_off.jsonl" \
    timeout 10800 python bench.py
  cp -f BENCH_rows.json "$ART/rows_after_host_ab_off.json" 2>/dev/null || true
  # side-by-side per-bucket host split (driver-readable in the log)
  python - "$ART/rows_after_host_ab.json" "$ART/rows_after_host_ab_off.json" <<'PY'
import json, sys
def row(path):
    for r in json.load(open(path)).get("rows", []):
        if r.get("metric") == "array_epochs_per_sec_n100":
            return r
    return {}
on, off = row(sys.argv[1]), row(sys.argv[2])
print(f"{'bucket':>12} {'pipelined':>12} {'kill-switch':>12}")
names = sorted(set(on.get("host_buckets", {})) | set(off.get("host_buckets", {})))
for b in names:
    print(f"{b:>12} {on.get('host_buckets', {}).get(b, 0):>12} "
          f"{off.get('host_buckets', {}).get(b, 0):>12}")
for k in ("host_seconds_per_epoch", "host_unattributed_fraction",
          "overlap_fraction", "value"):
    print(f"{k:>28} {on.get(k, 0):>12} {off.get(k, 0):>12}")
PY
}
do_flips10k() {
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=coin_e2e BENCH_COIN_FLIPS=10000 \
    timeout 3600 python bench.py
}
do_n64coin() {
  # epochs=1 for the first-ever on-chip capture: the 2-epoch default ran
  # >30 min into the 13:03 tunnel death (n64 coin macro is host-heavy on
  # this 1-core box); widen the timeout for the retry
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n64_coin BENCH_COIN_MACRO_BACKEND=tpu \
    BENCH_COIN_MACRO_EPOCHS=1 BENCH_SERIES="$ART/series_n64coin.jsonl" \
    timeout 3600 python bench.py
}
do_rs_ab() {
  BENCH_ONLY=rs_encode timeout 900 python bench.py
  SNAP rs_default
  BENCH_ONLY=rs_encode HBBFT_TPU_GF_DOT=bf16 timeout 900 python bench.py
  SNAP rs_bf16
  BENCH_ONLY=rs_encode HBBFT_TPU_GF_DOT=bf16 BENCH_RS_SHARD=65536 \
    timeout 900 python bench.py
}
do_rs_plane() {
  # Device erasure/hash plane A/B (PR 19): batched RS encode/reconstruct
  # bit-matmuls + device SHA-256 Merkle build/verify through the
  # TpuBackend plane entry points, vs the host codec kill switch
  # (HBBFT_TPU_NO_DEVICE_RS read per call, in-process A/B) — at the
  # N=16 and the N=100 f=33 shapes.  Cheap kernel row; the measurement
  # protocol (bucket-fold acceptance) is PERF.md round 15.
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=rs_plane_ab timeout 1800 python bench.py
}
do_fused_chain() {
  # VMEM-resident fused tower chain A/B (PR 20): the grouped rlc_sig
  # verification graph on the fused Miller/hard-exp kernels vs the
  # stacked composition (_jitted_rlc_sig(mode), in-process A/B with
  # bit-identical readback asserted).  The row's value is analytic
  # field-muls/s inside the fused kernels — the ≥2G north-star reads
  # off it directly; measurement protocol is PERF.md round 16.
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=fused_chain_ab timeout 1800 python bench.py
}
do_kernel_levers() {
  # body runs under -e/pipefail so a failed sub-command (timeout rc=124,
  # crashed sweep) aborts the step and the completion marker is NOT
  # written — partial logs stay, the next pass re-runs the step
  ( set -e -o pipefail
  : > "$ART/kernel_levers.log"
  # corrected roofline + default fused chain (rns)
  HBBFT_TPU_FQ_IMPL=rns timeout 1200 python tools/kernel_bench.py 2>&1 \
    | tee -a "$ART/kernel_levers.log"
  # TILE sweep on the fused chain
  for tile in 128 256 512 1024; do
    HBBFT_TPU_FQ_IMPL=rns HBBFT_TPU_RNS_TILE=$tile KB_NO_ROOFLINE=1 \
      KB_LANES=262144 timeout 900 python tools/kernel_bench.py 2>&1 \
      | tee -a "$ART/kernel_levers.log"
  done
  # extension-matmul strategy A/B at one size
  for ext in bf16 int8; do
    HBBFT_TPU_FQ_IMPL=rns HBBFT_TPU_RNS_EXT=$ext KB_FUSED=0 KB_NO_ROOFLINE=1 \
      KB_LANES=65536 timeout 900 python tools/kernel_bench.py 2>&1 \
      | tee -a "$ART/kernel_levers.log"
  done
  # per-mul fused RNS on the flagship graph row
  HBBFT_TPU_FQ_IMPL=rns HBBFT_TPU_RNS_FUSED=all BENCH_ONLY=rlc_dec \
    timeout 1800 python bench.py
  SNAP fused_all
  ) && echo "KERNEL_LEVERS_COMPLETE" >> "$ART/kernel_levers.log"
}
do_driver_budget() {
  HBBFT_TPU_FQ_IMPL=rns BENCH_BUDGET=3000 timeout 3600 python bench.py
}
done_adv_matrix() {
  has_row "$ART/rows_after_adv_matrix.json" adv_matrix
}
do_adv_matrix() {
  # Contamination sweep 0/1.6/5/15% at the N=100 dec-share shape
  # (100 ciphertext groups x 100 shares), adaptive RLC sizing vs the
  # HBBFT_TPU_NO_ADAPTIVE_RLC=1 fixed arm — both arms run inside the one
  # bench (the kill switch is read per batch).  Banks the on-chip
  # contamination-vs-throughput curve the r01 2x-at-1.6% cliff row
  # lacked; the PERF.md round-8 acceptance is adaptive>fixed at >=5%.
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=adv_matrix \
    BENCH_ADVM_GROUPS=100 BENCH_ADVM_K=100 BENCH_ADVM_ITERS=2 \
    timeout 7200 python bench.py
}
done_qhb_traffic() {
  has_row "$ART/rows_after_qhb_traffic.json" qhb_traffic
}
do_qhb_traffic() {
  # QHB traffic curve ON DEVICE: batch-size x arrival-rate grid at N=16
  # real crypto (every epoch's shares/pairings/combines through
  # TpuBackend) + the N=100 f=33 point — sustained tx/s and p50/p99
  # commit latency as first-class rows next to epochs/s.  Short grid: 2
  # epochs/cell; the mock-backend curve in the driver bench already
  # charts the full shape, this step banks the real-crypto anchor.
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=qhb_traffic BENCH_QHB_BACKEND=tpu \
    BENCH_QHB_EPOCHS=2 BENCH_QHB_BATCHES=16,64 BENCH_QHB_RATES=0.5,1.0,2.0 \
    BENCH_QHB_N100=0 timeout 7200 python bench.py
}
done_slo_traffic() {
  has_row "$ART/rows_after_slo_traffic.json" slo_traffic
}
do_slo_traffic() {
  # SLO-driven adaptive batch control ON DEVICE at the north-star shape:
  # N=100 f=33 real crypto under the 10x-swing trace, controller vs a
  # short fixed-B grid + the kill-switch identity arm (all in-process).
  # Short run (12 epochs/cell): the verdict fields (controller_compliant
  # / controller_beats_fixed / killswitch_identical) and the real-crypto
  # tx/s anchor are what this step banks; the full curve shape is
  # already charted by the CPU capture (artifacts/, PERF.md round 12).
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=slo_traffic BENCH_SLO_BACKEND=tpu \
    BENCH_SLO_N=100 BENCH_SLO_EPOCHS=12 BENCH_SLO_BATCHES=32,128 \
    BENCH_SLO_B0=32 BENCH_SLO_CLIENTS=1000000 \
    timeout 10800 python bench.py
}
done_crash_matrix() {
  has_row "$ART/rows_after_crash_matrix.json" crash_matrix
}
do_crash_matrix() {
  # composed gauntlet ON DEVICE: attack x schedule x churn x
  # crash+restart x traffic soak cells with real crypto through
  # TpuBackend (checkpoint/restore + WAL replay run against live device
  # state — the restored node's re-verifies dispatch to the chip).
  # Small shapes: the cell verdicts (bit-identical Batches, attributed
  # faults, recovery gate) are what this step banks, not throughput.
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=crash_matrix BENCH_CRASH_BACKEND=tpu \
    BENCH_CRASH_N=5 BENCH_CRASH_EPOCHS=8 \
    timeout 3600 python bench.py
}
done_mesh_scaling() {
  has_row "$ART/rows_after_mesh_scaling.json" mesh_scaling
}
do_mesh_scaling() {
  # per-device pipelined dispatch (PR 18) ON THE REAL MESH: the only
  # capture where mesh_scaling's chunks/s is a scale-out number — the
  # driver bench's virtual-CPU row is structural (devices share cores).
  # native mode sizes the sweep by what the chip actually exposes.
  BENCH_ONLY=mesh_scaling BENCH_MESH_PLATFORM=native \
    BENCH_MESH_SIZES=1,2,4,8 BENCH_MESH_CHUNKS=256 \
    timeout 1800 python bench.py
}
done_n32_churn() {
  has_row "$ART/rows_after_n32_churn.json" array_epochs_per_sec_n100 \
    backend=TpuBackend n=32
}
do_n32_churn() {
  # real-crypto era change ON DEVICE via the batched DKG
  # (engine/dkg_batch.py).  N=32 f=10: ~15 min host hash-to-G2 (the
  # measured 13.65 ms/doc wall, 2x32^3 docs) + batched device
  # ladders/pairings.  N=100 churn at full fidelity is ~7.7 h of host
  # hashing — itemized in PERF.md, native hash kernel is the next lever.
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n100 BENCH_ARRAY_BACKEND=tpu \
    BENCH_ARRAY_N=32 BENCH_ARRAY_EPOCHS=3 BENCH_ARRAY_CHURN=1 \
    BENCH_SERIES="$ART/series_n32_churn.jsonl" \
    timeout 5400 python bench.py
}
done_n16_churn() {
  has_row "$ART/rows_after_n16_churn.json" array_epochs_per_sec_n100 \
    backend=TpuBackend n=16
}
do_n16_churn() {
  # quick churn row: batched-DKG era change at the config-1 size
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n100 BENCH_ARRAY_BACKEND=tpu \
    BENCH_ARRAY_N=16 BENCH_ARRAY_EPOCHS=3 BENCH_ARRAY_CHURN=1 \
    BENCH_SERIES="$ART/series_n16_churn.jsonl" \
    timeout 3600 python bench.py
}

done_n100_churn() {
  has_row "$ART/rows_after_n100_churn.json" array_epochs_per_sec_n100 \
    backend=TpuBackend n=100 churn_epochs=1
}
do_n100_churn() {
  # the FULL north-star shape (VERDICT r4 task 1): >=10 epochs + one era
  # change in ONE row.  Feasible only since the native hash-to-G2 kernel
  # (1.8 ms/doc) + batched DKG: era change ~1.5 h + 10 epochs — run LAST
  # so a dying window costs nothing already captured.
  HBBFT_TPU_FQ_IMPL=rns BENCH_ONLY=array_n100 BENCH_ARRAY_BACKEND=tpu \
    BENCH_ARRAY_EPOCHS=10 BENCH_ARRAY_CHURN=1 \
    BENCH_SERIES="$ART/series_n100_churn.jsonl" \
    timeout 18000 python bench.py
}

STEPS="n100 matrix_rns_a matrix_limb_a matrix_rns_b matrix_limb_b glv_ab host_ab adv_matrix qhb_traffic slo_traffic crash_matrix mesh_scaling n16_churn flips10k kernel_levers driver_budget rs_ab rs_plane fused_chain n32_churn n64coin n100_churn"

for s in $STEPS; do
  if "done_$s"; then
    echo "=== $(TS) skip $s (snapshot verified) ==="
    continue
  fi
  if [ "${1:-}" = "--list" ]; then
    echo "pending: $s"
    continue
  fi
  ALIVE
  echo "=== $(TS) step $s ==="
  "do_$s"
  SNAP "$s"
done
echo "=== $(TS) runbook pass complete — snapshots in $ART/ ==="
