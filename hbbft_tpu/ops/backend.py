"""TpuBackend — batched device crypto behind the CryptoBackend seam.

This is BASELINE.json's north star made concrete: protocols (and the
VirtualNet crank loop) hand whole *batches* of pairing checks and share
combinations to this backend, which resolves them in a handful of jitted
device dispatches instead of per-share host loops (SURVEY.md §3.2: the
O(N²) pairing verifies per node per epoch are the entire performance
story).

Every verification equation in the framework has the shape
``e(a1, b1) == e(a2, b2)``, i.e. ``FE(ML(a1, b1)·ML(−a2, b2)) == 1``:

* sig share:    e(G1, σ_i)  == e(PK_i, H2(doc))      (keys.py conventions)
* full sig:     e(G1, σ)    == e(PK, H2(msg))
* dec share:    e(D_i, H)   == e(PK_i, W)
* ciphertext:   e(G1, W)    == e(U, H)

So ONE jitted kernel — two batched Miller loops + one shared final
exponentiation — serves all four batch-verify entry points.  Batches are
padded to power-of-two buckets with trivially-true items (e(G,H)==e(G,H))
so XLA compiles a handful of shapes, once each.

Hash-to-curve and canonical equality run host-side: hashing is not the
dominant cost (SURVEY.md §2.2) and host comparison removes every
sequential carry chain from the device graph (ops/fq.py).

Combines (Lagrange in the exponent) run on device above a batch-size
threshold via the fixed-ladder MSM in ops/curve.py, else on the host
golden path — share counts are small at small N and the 254-step ladder
only pays for itself in bulk.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.crypto.bls381 import BLS381Group
from hbbft_tpu.crypto.field import lagrange_coeffs_at_zero
from hbbft_tpu.crypto.keys import (
    Ciphertext,
    CryptoError,
    DecryptionShare,
    PublicKeySet,
    PublicKeyShare,
    Signature,
    SignatureShare,
)
from hbbft_tpu.ops import curve, pairing, tower

_MIN_BUCKET = 4


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


@functools.lru_cache(maxsize=None)
def _jitted_product2():
    """(P1, Q1, P2, Q2) → fq12 limbs of FE_fast(ML(P1,Q1)·ML(P2,Q2))."""
    return jax.jit(pairing.product2_fast)


@functools.lru_cache(maxsize=None)
def _jitted_combine_g1():
    return jax.jit(curve.linear_combine_g1)


@functools.lru_cache(maxsize=None)
def _jitted_combine_g2():
    return jax.jit(curve.linear_combine_g2)


class TpuBackend(CryptoBackend):
    """JAX/TPU batched BLS12-381 backend.

    Protocol-visible semantics are identical to CpuBackend (same golden
    group for key material, hashing and serialization); only the batch
    verify/combine paths move to the device.
    """

    #: combine on device only when at least this many shares are batched
    device_combine_threshold = 8

    def __init__(self) -> None:
        super().__init__(BLS381Group())
        self._h2_cache: Dict[bytes, Any] = {}

    # -- internals -----------------------------------------------------------

    def _hash_g2(self, doc: bytes):
        h = self._h2_cache.get(doc)
        if h is None:
            h = self.group.hash_to_g2(doc)
            if len(self._h2_cache) > 4096:
                self._h2_cache.clear()
            self._h2_cache[doc] = h
        return h

    def _check_batch(self, quads) -> List[bool]:
        """quads: list of (a1, b1, a2, b2) affine tuples checking
        e(a1,b1) == e(a2,b2).  Returns per-item booleans."""
        n = len(quads)
        if n == 0:
            return []
        g1 = self.group.g1()
        g2 = self.group.g2()
        pad = (g1, g2, g1, g2)  # trivially true
        b = _bucket(n)
        quads = list(quads) + [pad] * (b - n)

        neg = self.group.g1_neg
        P1 = pairing.g1_affine_to_device([q[0] for q in quads])
        Q1 = pairing.g2_affine_to_device([q[1] for q in quads])
        P2 = pairing.g1_affine_to_device(
            [neg(q[2]) if q[2] is not None else None for q in quads]
        )
        Q2 = pairing.g2_affine_to_device([q[3] for q in quads])

        f = _jitted_product2()(P1, Q1, P2, Q2)
        f = jax.tree_util.tree_map(np.asarray, f)
        return [pairing.is_one_host(f, i) for i in range(n)]

    # -- batched verification ------------------------------------------------

    def verify_sig_shares(
        self, items: Sequence[Tuple[PublicKeyShare, bytes, SignatureShare]]
    ) -> List[bool]:
        g1 = self.group.g1()
        quads = [
            (g1, share.el, pk.el, self._hash_g2(doc))
            for pk, doc, share in items
        ]
        return self._check_batch(quads)

    def verify_signatures(
        self, items: Sequence[Tuple[Any, bytes, Signature]]
    ) -> List[bool]:
        g1 = self.group.g1()
        quads = [
            (g1, sig.el, pk.el, self._hash_g2(msg)) for pk, msg, sig in items
        ]
        return self._check_batch(quads)

    def verify_dec_shares(
        self, items: Sequence[Tuple[PublicKeyShare, Ciphertext, DecryptionShare]]
    ) -> List[bool]:
        quads = []
        for pk, ct, share in items:
            h = self._hash_g2(self.group.g1_to_bytes(ct.u) + ct.v)
            quads.append((share.el, h, pk.el, ct.w))
        return self._check_batch(quads)

    def verify_ciphertexts(self, items: Sequence[Ciphertext]) -> List[bool]:
        g1 = self.group.g1()
        quads = []
        for ct in items:
            h = self._hash_g2(self.group.g1_to_bytes(ct.u) + ct.v)
            quads.append((g1, ct.w, ct.u, h))
        return self._check_batch(quads)

    # -- combination ---------------------------------------------------------

    def _lagrange_device(
        self, pts: List[Tuple[int, Any]], to_device, from_device, jitted
    ):
        """Shared padding/bucketing for device Lagrange combines.

        Pads with infinity points and zero scalars (0·∞ contributes the
        identity) up to a power-of-two bucket so XLA compiles few shapes.
        """
        lam = lagrange_coeffs_at_zero([x for x, _ in pts])
        safe = [curve.safe_scalar(l) for l in lam]
        b = _bucket(len(pts))
        points = [el for _, el in pts] + [None] * (b - len(pts))
        bits = curve.scalars_to_bits(
            [s for s, _ in safe] + [0] * (b - len(pts))
        )
        negs = np.array([n for _, n in safe] + [False] * (b - len(pts)))
        combined = jitted(to_device(points), bits, negs)
        return from_device(combined)[0]

    def _lagrange_device_g2(self, pts: List[Tuple[int, Any]]):
        return self._lagrange_device(
            pts, curve.g2_to_device, curve.g2_from_device, _jitted_combine_g2()
        )

    def _lagrange_device_g1(self, pts: List[Tuple[int, Any]]):
        return self._lagrange_device(
            pts, curve.g1_to_device, curve.g1_from_device, _jitted_combine_g1()
        )

    def combine_signatures(
        self, pk_set: PublicKeySet, shares: Dict[int, SignatureShare]
    ) -> Signature:
        if len(shares) <= pk_set.threshold():
            raise CryptoError(
                f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
            )
        if len(shares) < self.device_combine_threshold:
            return pk_set.combine_signatures(shares)
        pts = [(i + 1, s.el) for i, s in sorted(shares.items())]
        return Signature(self.group, self._lagrange_device_g2(pts))

    def combine_decryption_shares(
        self, pk_set: PublicKeySet, shares: Dict[int, DecryptionShare], ct: Ciphertext
    ) -> bytes:
        if len(shares) <= pk_set.threshold():
            raise CryptoError(
                f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
            )
        if len(shares) < self.device_combine_threshold:
            return pk_set.combine_decryption_shares(shares, ct)
        pts = [(i + 1, s.el) for i, s in sorted(shares.items())]
        combined = self._lagrange_device_g1(pts)
        g = self.group
        pad = g.hash_bytes(g.g1_to_bytes(combined), len(ct.v))
        return bytes(a ^ b for a, b in zip(ct.v, pad))
