"""TpuBackend — batched device crypto behind the CryptoBackend seam.

This is BASELINE.json's north star made concrete: protocols (and the
VirtualNet crank loop) hand whole *batches* of pairing checks and share
combinations to this backend, which resolves them in a handful of jitted
device dispatches instead of per-share host loops (SURVEY.md §3.2: the
O(N²) pairing verifies per node per epoch are the entire performance
story).

Every verification equation in the framework has the shape
``e(a1, b1) == e(a2, b2)``, i.e. ``FE(ML(a1, b1)·ML(−a2, b2)) == 1``:

* sig share:    e(G1, σ_i)  == e(PK_i, H2(doc))      (keys.py conventions)
* full sig:     e(G1, σ)    == e(PK, H2(msg))
* dec share:    e(D_i, H)   == e(PK_i, W)
* ciphertext:   e(G1, W)    == e(U, H)

So ONE jitted kernel — two batched Miller loops + one shared final
exponentiation — serves all four batch-verify entry points.  Batches are
padded to power-of-two buckets with trivially-true items (e(G,H)==e(G,H))
so XLA compiles a handful of shapes, once each.

Hash-to-curve and canonical equality run host-side: hashing is not the
dominant cost (SURVEY.md §2.2) and host comparison removes every
sequential carry chain from the device graph (ops/fq.py).

Combines (Lagrange in the exponent) run on device above a batch-size
threshold via the fixed-ladder MSM in ops/curve.py, else on the host
golden path — share counts are small at small N and the 254-step ladder
only pays for itself in bulk.

Dispatches are **pipelined** (ops/pipeline.py): every lane-capped chunk
loop assembles chunk k+1 on host while chunk k executes on device,
behind a bounded in-flight queue, and repeated key material stages
through the value-keyed limb-row cache (ops/staging.py) instead of
re-running the bigint conversion per dispatch.  ``HBBFT_TPU_NO_PIPELINE
=1`` restores strictly synchronous dispatch+fetch; outputs are
bit-identical either way and dispatch counts do not change.
"""

from __future__ import annotations

import functools
import os
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.crypto.bls381 import BLS381Group
from hbbft_tpu.crypto.field import lagrange_coeffs_at_zero
from hbbft_tpu.crypto.keys import (
    Ciphertext,
    CryptoError,
    DecryptionShare,
    PublicKeySet,
    PublicKeyShare,
    Signature,
    SignatureShare,
)
from hbbft_tpu.crypto.merkle import MerkleTree
from hbbft_tpu.ops import curve, pairing, tower
from hbbft_tpu.ops import sha256 as sha256_device
from hbbft_tpu.ops.gf256 import DecodeMatrixCache, expand_gf_matrix, gf256_matmul
from hbbft_tpu.ops.pipeline import (
    DispatchPipeline,
    device_rs_enabled,
    fetch_to_host,
    hostpipe_enabled,
)
from hbbft_tpu.ops.staging import StagingCache

_MIN_BUCKET = 4


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def _pad_zero_rows(bits, negs, pad: int):
    """Pad prepared ladder rows with zero-scalar lanes (0·∞ contributes
    the identity).  A zero scalar's bit rows and sign flags are all-zero
    in BOTH the classic and the GLV-decomposed forms, so zero-fill is
    exactly equivalent to decomposing the padding scalars — without
    billing phantom Babai decompositions to the GLV counters."""
    if pad <= 0:
        return bits, negs
    bits = np.concatenate(
        [bits, np.zeros((pad,) + bits.shape[1:], dtype=bits.dtype)]
    )
    negs = np.concatenate(
        [negs, np.zeros((pad,) + negs.shape[1:], dtype=negs.dtype)]
    )
    return bits, negs


@functools.lru_cache(maxsize=None)
def _jitted_product2(fused=None):
    """(P1, Q1, P2, Q2) → fq12 limbs of FE_fast(ML(P1,Q1)·ML(P2,Q2)).

    ``fused`` is the RESOLVED pairing_chain mode (None = stacked graph,
    "native"/"interpret" = fused tower kernels); the jit cache is keyed
    on it, so call sites that re-read the env ladder per call (the kill
    switch HBBFT_TPU_NO_FUSED_TOWER) always hit the matching graph —
    env flips can never serve a stale trace."""
    return jax.jit(
        functools.partial(pairing.product2_fast, fused=fused or False)
    )


@functools.lru_cache(maxsize=None)
def _jitted_combine_g1():
    return jax.jit(curve.linear_combine_g1)


@functools.lru_cache(maxsize=None)
def _jitted_combine_g1_batch():
    """vmap of the G1 Lagrange combine over an item axis: (B, k) points ×
    (B, k, 254) bit matrices → B combined points in one dispatch."""
    return jax.jit(jax.vmap(curve.linear_combine_g1, in_axes=(0, 0, 0)))


@functools.lru_cache(maxsize=None)
def _jitted_combine_g2():
    return jax.jit(curve.linear_combine_g2)


@functools.lru_cache(maxsize=None)
def _jitted_g1_mul_batch():
    """Batched independent G1 ladders: (B,) points × (B, 254) bit rows →
    (B,) Jacobian products (the decrypt-share generation shape)."""
    return jax.jit(curve.g1_scalar_mul_signed)


@functools.lru_cache(maxsize=None)
def _jitted_g2_mul_batch():
    """Batched independent G2 ladders: the coin-sign generation shape
    (x_i·H2(doc) per item; SURVEY.md §3.2 hottest loop)."""
    return jax.jit(curve.g2_scalar_mul_signed)


@functools.lru_cache(maxsize=None)
def _jitted_combine_g2_batch():
    """vmap of the G2 Lagrange combine over an item axis — the batched
    signature-combination shape (B items × k shares each)."""
    return jax.jit(jax.vmap(curve.linear_combine_g2, in_axes=(0, 0, 0)))


def _squeeze_point(P):
    """(G, 1, ...) Jacobian from a vmapped combine → (G, ...)."""
    return jax.tree_util.tree_map(lambda c: c[:, 0], P)


@functools.lru_cache(maxsize=None)
def _jitted_rlc_sig(fused=None):
    """Grouped sig-share check: e(G1, Σr·σ_i) == e(Σr·PK_i, H) per group.

    Inputs: S (G,k) G2 Jacobian shares, PK (G,k) G1 Jacobian key shares,
    rbits (G,k,RLC_BITS), negG1 (G,) affine −G1, H (G,) affine G2 points.
    Returns fq12 limbs; host checks == 1 per group.  ``fused`` keys the
    cache on the resolved pairing-chain mode (see _jitted_product2).
    """

    def f(S, PK, rbits, negG1, H):
        zeros = jnp.zeros(rbits.shape[:2], dtype=bool)
        comb_s = jax.vmap(curve.linear_combine_g2)(S, rbits, zeros)
        comb_pk = jax.vmap(curve.linear_combine_g1)(PK, rbits, zeros)
        s_aff = curve.jac_to_affine_g2(_squeeze_point(comb_s))
        pk_aff = curve.jac_to_affine_g1(_squeeze_point(comb_pk))
        return pairing.product2_fast(
            negG1, s_aff, pk_aff, H, fused=fused or False
        )

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def _jitted_rlc_dec(fused=None):
    """Grouped dec-share check: e(Σr·D_i, H) == e(Σr·PK_i, W) per group.

    D and PK both live in G1; H, W are per-group affine G2 points.
    ``fused`` keys the cache on the resolved pairing-chain mode.
    """

    def f(D, PK, rbits, H, W):
        zeros = jnp.zeros(rbits.shape[:2], dtype=bool)
        comb_d = jax.vmap(curve.linear_combine_g1)(D, rbits, zeros)
        comb_pk = jax.vmap(curve.linear_combine_g1)(PK, rbits, zeros)
        d_aff = curve.jac_to_affine_g1(_squeeze_point(comb_d))
        pk_aff = curve.jac_to_affine_g1(_squeeze_point(comb_pk))
        neg_pk = (pk_aff[0], jnp.negative(pk_aff[1]), pk_aff[2])
        return pairing.product2_fast(d_aff, H, neg_pk, W, fused=fused or False)

    return jax.jit(f)


class TpuBackend(CryptoBackend):
    """JAX/TPU batched BLS12-381 backend.

    Protocol-visible semantics are identical to CpuBackend (same golden
    group for key material, hashing and serialization); only the batch
    verify/combine paths move to the device.
    """

    #: the erasure/hash plane (RS bit-matmuls + device SHA-256 Merkle)
    #: rides the dispatch seam here (see the plane methods at the end of
    #: the class); crypto/merkle.py's PackedProofs packing keys off this
    device_rs_plane = True
    #: combine on device only when at least this many shares are batched
    device_combine_threshold = 8
    #: Max ladder lanes (items × shares) per combine/generation dispatch.
    #: One graph holding the whole batch's Jacobian ladder state scales
    #: HBM linearly with lanes: 557k lanes (10k combines × k=34 at N=100)
    #: needed 16.4 GB against the v5e's 15.75 GB.  32k lanes ≈ 1 GB.
    device_lane_cap = 1 << 15

    def __init__(self) -> None:
        super().__init__(BLS381Group())
        self._h2_cache: Dict[bytes, Any] = {}
        # the deferred-fetch pipeline (bounded in-flight queue) and the
        # value-keyed limb-row staging cache.  The tracer is attached
        # after construction, so the pipeline reads it via a closure.
        self._pipe = DispatchPipeline(
            counters=self.counters, tracer_ref=lambda: self.tracer
        )
        self._stage = StagingCache(counters=self.counters)
        # Contamination-adaptive RLC sizing (blst's playbook): a decayed
        # observation window of (items seen, items rejected) across the
        # grouped verifies drives the NEXT batch's initial group size —
        # see _rlc_adaptive_cap.  Plain floats, no entropy.
        self._rlc_obs_items = 0.0
        self._rlc_obs_rejects = 0.0
        # Device erasure/hash plane constants: per-(k, m) bit-expanded
        # encode matrices (a handful of codec shapes per process) and the
        # erasure-pattern-keyed LRU of decode matrices (bounded — distinct
        # patterns are combinatorially many; see ops/gf256.py).
        self._rs_enc_bits: Dict[Tuple[int, int], Any] = {}
        self._rs_dec_cache = DecodeMatrixCache()
        # Lagrange-combine prep memo: the engine's N² combines per epoch
        # all interpolate over the SAME share indices (the lowest f+1),
        # and the (bits, negs) ladder form is a pure function of those
        # indices and the GLV arm — one Babai decomposition + bit packing
        # serves the whole epoch.  Keyed (prep fn, index tuple, glv) so
        # the in-process A/B arms can never alias; bounded like the
        # lagrange coefficient cache it sits on top of.
        self._prep_memo: Dict[Any, Tuple[np.ndarray, np.ndarray]] = {}

    def flush(self) -> None:
        """Resolve every pending (dispatched-but-unfetched) chunk.  All
        public batch entry points flush before returning, so this is a
        no-op unless called mid-batch from a callback."""
        self._pipe.flush()

    def new_era(self, era: int) -> None:
        """Era turnover: drop staged limb rows for the dead key material
        (value-keyed entries are never *wrong*, only dead weight — this
        reclaims them promptly instead of waiting out the LRU)."""
        self._stage.clear()

    @contextmanager
    def _host_assembly(self):
        """Time one host staging block (limb packing, scalars_to_bits,
        point conversion, placement) into counters.host_assembly_seconds
        — the quantity the pipeline overlaps with device execution.
        Not nested: each dispatch site wraps exactly its own staging.

        Also a ``staging`` host-bucket region (obs/hostbuckets.py): when
        the engine's epoch wraps this call, the block's exclusive time
        lands in ``host_bucket_staging`` instead of the enclosing
        ``dispatch`` bucket."""
        t0 = time.perf_counter()
        with self.buckets.region("staging"):
            try:
                yield
            finally:
                self.counters.host_assembly_seconds += (
                    time.perf_counter() - t0
                )

    def _pad_bucket(self, n: int) -> int:
        """Bucket size for a batch/group axis.  MeshBackend widens this
        to a multiple of the mesh so the axis shards evenly."""
        return _bucket(n)

    def _prep_scalars(self, prep, scalars):
        """Run a curve.prep_g*_scalars host prep under the GLV accounting
        counters: decomposition+packing wall time (the host share of the
        joint-table path) bills to glv_table_build_seconds, and the
        decomposition tally to glv_decompositions."""
        t0 = time.perf_counter()
        bits, negs = prep(scalars)
        if curve.glv_enabled():
            c = self.counters
            c.glv_table_build_seconds += time.perf_counter() - t0
            c.glv_decompositions += len(scalars)
        return bits, negs

    def _count_ladder(
        self, bits, lanes: int, glv: bool, ladders_per_lane: int = 1
    ) -> None:
        """Analytic ladder accounting: `ladder_field_muls` accumulates the
        per-lane scan cost of the prepared bit matrix × lanes (Fq muls on
        G1 shapes, Fq2 muls on G2 — documented in utils/metrics.py), plus
        the per-lane joint-table build under GLV.  ``glv`` is passed
        explicitly by the call site (a decomposed bit matrix and an RLC
        (groups, k, 64) matrix can be shape-identical at k ∈ {2, 4}).
        ``ladders_per_lane`` is for graphs that run several ladders over
        one bit row (the RLC group check ladders both the share and the
        key combination)."""
        c = self.counters
        c.ladder_field_muls += (
            curve.ladder_scan_field_muls(bits, glv) * lanes * ladders_per_lane
        )
        if glv:
            c.glv_table_field_muls += (
                curve.glv_table_field_muls(bits) * lanes * ladders_per_lane
            )

    def _place(self, tree, pipelined: bool = False):
        """Placement hook for jitted-call inputs.  ``pipelined=True``
        marks a chunk headed for a DEFERRED-fetch dispatch — MeshBackend
        lands such chunks whole on one reserved device (per-device
        pipelining, parallel/shardpipe.py) and shards only sync
        dispatches SPMD; the single-chip backend ignores both."""
        return tree

    def _to_device_gather(self, points, to_device, transform=None):
        """Stage ``points`` with identity-deduplicated conversion.

        The engine's per-receiver workload replicates the SAME Python
        point objects N−1 times per lane (index arithmetic over the flat
        batch), so the limb conversion + cache lookups need only run per
        DISTINCT object — the full-width arrays are rebuilt with one
        numpy-index gather per coordinate plane.  ``transform`` (e.g.
        g1_neg) is applied per distinct point, after the dedup, so
        derived tuples cannot defeat it.  Value-identical points that
        are distinct objects simply miss the shortcut and fall through
        to the staging cache's value dedup — results are identical.
        The expansion happens host-side (numpy fancy indexing inside the
        converters' ``gather=``) — an unjitted device gather would
        trigger a per-shape XLA compile and dwarf the saving.  Legacy
        arm (``HBBFT_TPU_NO_HOSTPIPE=1``): straight per-lane conversion,
        as before this PR."""
        def conv(pts, gather=None):
            if transform is not None:
                pts = [transform(p) if p is not None else None for p in pts]
            return to_device(pts, cache=self._stage, gather=gather)

        if not hostpipe_enabled() or len(points) <= 1:
            return conv(list(points))
        index: Dict[Any, int] = {}
        order: List[Any] = []
        idx = np.empty(len(points), dtype=np.int64)
        for j, p in enumerate(points):
            key = None if p is None else id(p)
            pos = index.get(key)
            if pos is None:
                pos = index[key] = len(order)
                order.append(p)
            idx[j] = pos
        if len(order) == len(points):
            return conv(order)
        return conv(order, gather=idx)

    # -- internals -----------------------------------------------------------

    def _hash_g2(self, doc: bytes):
        h = self._h2_cache.get(doc)
        if h is None:
            t0 = time.perf_counter()
            h = self.group.hash_to_g2(doc)
            self.counters.hash_g2_seconds += time.perf_counter() - t0
            while len(self._h2_cache) >= 4096:
                # bounded LRU, not a wholesale clear(): sign_shares_batch
                # hashes every doc up front and the lane-cap recursion
                # re-hashes per chunk, so one >4096-doc batch under
                # clear() would thrash and re-run host hash-to-G2
                self._h2_cache.pop(next(iter(self._h2_cache)))
        else:
            del self._h2_cache[doc]  # re-insert → most-recently-used
        self._h2_cache[doc] = h
        return h

    #: Max pairing checks per device dispatch.  The Miller-loop graph
    #: carries fq12 state (12 x 79 f32 lanes) per item plus staged
    #: intermediates — far heavier per lane than the scalar ladders, so
    #: the cap sits well below device_lane_cap.  The batched DKG feeds
    #: N³-sized ciphertext batches through here (engine/dkg_batch.py);
    #: without the cap a single 32k+-lane pairing dispatch OOMs HBM.
    pairing_lane_cap = int(os.environ.get("HBBFT_TPU_PAIR_CAP", "2048"))

    def _check_batch(self, quads) -> List[bool]:
        """quads: list of (a1, b1, a2, b2) affine tuples checking
        e(a1,b1) == e(a2,b2).  Returns per-item booleans."""
        quads = list(quads)
        results: List[Optional[bool]] = [None] * len(quads)
        self._check_batch_async(quads, results.__setitem__)
        self._pipe.flush()
        return [bool(r) for r in results]

    def _check_batch_async(self, quads, write) -> None:
        """Submit pairing checks in pipelined lane-capped chunks: chunk
        k+1's host staging runs while chunk k executes on device.  Per-
        item booleans are delivered as ``write(index, ok)`` from each
        chunk's deferred fetch — the caller must flush the pipeline (or
        issue a sync dispatch) before reading them."""
        quads = list(quads)
        for lo in range(0, len(quads), self.pairing_lane_cap):
            self._submit_check_chunk(
                quads[lo : lo + self.pairing_lane_cap], lo, write
            )

    def _bill_chain(self, mode, lanes: int) -> None:
        """Fused-chain accounting for one verification dispatch of
        ``lanes`` pairing lanes: tally the analytic per-verification
        kernel-launch counts of whichever composition is routing (the
        ≥3× dispatch-drop A/B reads fused_chain_pallas_calls vs
        stacked_chain_pallas_calls directly) and, on the fused arm, the
        analytic Fq-mul count executed inside the fused kernels (the
        muls/s numerator of the fused_chain_ab bench row)."""
        from hbbft_tpu.ops import pairing_chain

        c = self.counters
        if mode:
            c.fused_tower_calls += 1
            c.fused_chain_field_muls += pairing_chain.analytic_chain_field_muls(
                lanes
            )
            c.fused_chain_pallas_calls += pairing_chain.analytic_pallas_calls(
                2, fused=True
            )
        else:
            c.stacked_chain_pallas_calls += pairing_chain.analytic_pallas_calls(
                2, fused=False
            )

    def _submit_check_chunk(self, chunk, base: int, write) -> None:
        from hbbft_tpu.ops import pairing_chain

        n = len(chunk)
        if n == 0:
            return
        self.counters.pairing_checks += n
        self.counters.device_dispatches += 1
        # per-call routing resolve: the jit cache is keyed on the mode,
        # so flipping HBBFT_TPU_NO_FUSED_TOWER mid-process restores the
        # stacked graph exactly (no stale traces)
        mode = pairing_chain.fused_tower_mode()
        g1 = self.group.g1()
        g2 = self.group.g2()
        pad = (g1, g2, g1, g2)  # trivially true
        b = self._pad_bucket(n)
        chunk = chunk + [pad] * (b - n)
        neg = self.group.g1_neg
        with self._host_assembly():
            P1 = self._to_device_gather(
                [q[0] for q in chunk], pairing.g1_affine_to_device
            )
            Q1 = self._to_device_gather(
                [q[1] for q in chunk], pairing.g2_affine_to_device
            )
            # negation runs per DISTINCT point, after the dedup — a
            # per-lane neg() would mint fresh tuples and defeat it
            P2 = self._to_device_gather(
                [q[2] for q in chunk], pairing.g1_affine_to_device,
                transform=neg,
            )
            Q2 = self._to_device_gather(
                [q[3] for q in chunk], pairing.g2_affine_to_device
            )
            placed = self._place((P1, Q1, P2, Q2), pipelined=True)

        def deliver(f, base=base, n=n):
            if hostpipe_enabled():
                for i, ok in enumerate(pairing.is_one_host_batch(f, n)):
                    write(base + i, ok)
            else:  # legacy per-lane CRT readback (A/B kill-switch arm)
                for i in range(n):
                    write(base + i, pairing.is_one_host(f, i))

        self._bill_chain(mode, b)
        self._dispatch_async(
            _jitted_product2(mode), placed,
            kind="fused_chain" if mode else "pairing", items=n,
            on_result=deliver,
        )

    def _dispatch_fetch(self, jitted, args, kind: str = "", items: int = 0):
        """Dispatch one jitted call and fetch the result to host
        SYNCHRONOUSLY (draining any pending pipelined chunks first, in
        FIFO order), billing the dispatch→fetch wall clock to
        counters.device_seconds (task-8 attribution — includes any queued
        device work this fetch must wait for) and, when ``kind`` is
        given, to ``device_seconds_<kind>`` so macro rows can break an
        epoch's device time down by op kind (r4 task 7).

        With a tracer attached, the identical [t0, t1] interval becomes a
        ``device=True`` dispatch span on the ``device`` track — traced
        device time and counter attribution agree exactly by construction
        (the acceptance check in tools/trace_report.py relies on this).
        Used where control flow needs the result immediately (RLC
        bisection rounds, single combines)."""
        return self._pipe.submit(
            lambda: jitted(*args), fetch_to_host, kind=kind, items=items,
            sync=True,
        ).value

    def _dispatch_async(
        self, jitted, args, kind: str = "", items: int = 0, on_result=None
    ):
        """Dispatch one jitted call with a DEFERRED fetch behind the
        bounded in-flight queue (ops/pipeline.py): the billing/tracer
        contract is identical to :meth:`_dispatch_fetch` per dispatch —
        same [dispatch, fetch] interval to the same counters and span
        kind — but intervals of in-flight chunks overlap in wall time
        (each slot spans its own ``device/<slot>`` track).
        ``HBBFT_TPU_NO_PIPELINE=1`` makes this exactly
        :meth:`_dispatch_fetch`."""
        return self._pipe.submit(
            lambda: jitted(*args), fetch_to_host, kind=kind, items=items,
            on_result=on_result,
        )

    # -- grouped (random-linear-combination) verification --------------------
    #
    # For k same-document shares, ONE check e(G1, Σr_iσ_i) == e(Σr_iPK_i, H)
    # with unpredictable RLC_BITS-wide r_i replaces k pairing checks: a forged
    # share survives only if Σ r_i·δ_i = 0 for its discrepancy δ — probability
    # 2^-RLC_BITS over r.  Cost per item falls from 2 Miller loops + FE to two
    # RLC_BITS-wide ladder lanes.  Groups that fail fall back to per-item
    # checks, preserving exact fault attribution.  (This is the classic BLS batch
    # verification; the common-coin workload — N shares per coin instance,
    # SURVEY.md §3.2 — is exactly this shape.)

    rlc_min_group = 3
    #: Random-coefficient width.  64 bits is the standard batch-verification
    #: choice (blst's mult-verify and Ethereum consensus clients use 64-bit
    #: random multipliers): a forged share survives a group check with
    #: probability 2⁻⁶⁴ per attempt, and a failing group still falls back to
    #: exact per-item checks, so soundness of fault ATTRIBUTION is never
    #: probabilistic.  Halving the width halves the dominant per-share
    #: device cost (the coefficient ladder).  HBBFT_TPU_RLC_BITS overrides
    #: (e.g. 128 for the belt-and-braces setting) and is re-read per batch
    #: so in-process A/Bs (bench fallback ladder) take effect immediately.

    @classmethod
    def _rlc_bits(cls) -> int:
        return int(os.environ.get("HBBFT_TPU_RLC_BITS", "64"))

    # -- contamination-adaptive group sizing ---------------------------------
    #
    # The r01 adversarial row measured 2× degradation at just 1.6% forged
    # shares: with whole-document groups (k = N at the coin shape) a single
    # forged share costs ~2·log₂k extra bisection rounds of group lanes.
    # blst's batch-verify playbook adapts: when contamination is OBSERVED,
    # start the next batch with smaller groups so a contaminated group
    # wastes less work.  Expected per-item lane cost with group size k and
    # contamination c is ≈ 1/k + c·log₂k; minimizing gives k* = ln2/c ≈
    # 0.7/c — at 1.6% that's k*≈43, at 5% k*≈14, at 15% k*≈4.  The
    # observation window decays by half each batch, so a burst of forgeries
    # shrinks groups within one round and an honest stretch re-grows them.
    #
    # Soundness is UNCHANGED: splitting only re-partitions the group
    # structure; False still only ever comes from the exact per-item
    # pairing fallback.  At an observed rate of 0 the cap is None and the
    # group structure is IDENTICAL to the fixed path, which is what makes
    # the HBBFT_TPU_NO_ADAPTIVE_RLC=1 A/B bit-identical on honest traffic.

    #: observed-rejection rate below which groups are left at full size
    rlc_adapt_min_rate = 0.005

    @staticmethod
    def _adaptive_rlc_enabled() -> bool:
        return os.environ.get("HBBFT_TPU_NO_ADAPTIVE_RLC", "0") != "1"

    def _rlc_observed_rate(self) -> float:
        # lint: allow[seam-race] the observation window only sizes the NEXT
        # batch's groups, never verdicts (False comes solely from exact
        # per-item pairing); c=0 bit-identity vs fixed groups is
        # tier-1-asserted and tools/race_explorer.py sweeps the deferred seam
        if self._rlc_obs_items <= 0:
            return 0.0
        # lint: allow[seam-race] same window invariant as above: sizing-only
        return self._rlc_obs_rejects / self._rlc_obs_items

    def _rlc_adaptive_cap(self) -> Optional[int]:
        """Max initial group size for the next batch, or None for
        unlimited (honest regime)."""
        if not self._adaptive_rlc_enabled():
            return None
        rate = self._rlc_observed_rate()
        if rate < self.rlc_adapt_min_rate:
            return None
        return max(self.rlc_min_group, round(0.7 / rate))

    def _rlc_observe(self, indices: List[int], results: List) -> None:
        """Fold one finished grouped verify into the decayed observation
        window (called after the batch's results are final)."""
        if not indices:
            return
        rejects = sum(1 for idx in indices if results[idx] is False)
        self._rlc_obs_items = self._rlc_obs_items * 0.5 + len(indices)
        self._rlc_obs_rejects = self._rlc_obs_rejects * 0.5 + rejects

    def _rlc_apply_cap(self, groups: List[List[int]]) -> List[List[int]]:
        """Split groups to the adaptive cap (contiguous slices — the
        deterministic re-partition).  Slicing never strands a sub-minimum
        tail: the last two slices are rebalanced when the tail would drop
        below rlc_min_group."""
        cap = self._rlc_adaptive_cap()
        if cap is None:
            return groups
        out: List[List[int]] = []
        split = False
        for grp in groups:
            if len(grp) <= cap:
                out.append(grp)
                continue
            split = True
            for lo in range(0, len(grp), cap):
                piece = grp[lo : lo + cap]
                if len(piece) < self.rlc_min_group and out and split:
                    out[-1].extend(piece)  # rebalance the short tail
                else:
                    out.append(list(piece))
        if split:
            self.counters.rlc_adaptive_splits += 1
        return out

    @staticmethod
    def _rlc_scalars(k: int) -> List[int]:
        bits = TpuBackend._rlc_bits()
        top = (1 << bits) - 1
        nbytes = (bits + 7) // 8
        return [
            1 + int.from_bytes(os.urandom(nbytes), "big") % top
            for _ in range(k)
        ]

    @staticmethod
    def _reshape_groups(dev, g: int, k: int):
        return jax.tree_util.tree_map(
            lambda c: c.reshape((g, k) + c.shape[1:]), dev
        )

    def _grouped_rlc(
        self,
        groups: List[List[int]],
        items: Sequence,
        build_group_arrays,
        jitted,
        results: List,
        direct_quad,
        kind: str = "",
        deferred: bool = False,
    ):
        """Run RLC group checks; write per-item booleans into `results`.

        `build_group_arrays(flat_padded_groups, g, k, group_keys) -> args`
        constructs the jitted fn's inputs; padding inside each group uses
        (None point, scalar 0) lanes that contribute the identity.
        `direct_quad(item)` builds the per-item pairing quad for the exact
        fallback on contaminated leaves (passed explicitly so concurrent
        sig/dec verifications on one backend can't cross wires).

        A failing group is BISECTED: each half re-enters the next round's
        single batched RLC dispatch with fresh coefficients, until halves
        would drop below rlc_min_group — those leaves get exact per-item
        pairing checks (one batched dispatch at the end).  Attribution
        cost for c contaminated items among k is O(c·log k) group lanes +
        O(c) exact pairings instead of k pairings, so a 1-5%-garbage
        batch can no longer collapse verification to per-item cost (the
        adversarial-DoS amplifier the round-2 verdict flagged).  Fault
        attribution stays exact: False is only ever written by the
        per-item pairing check.

        Pipelining: each round's group check is a SYNC dispatch (the
        bisection's control flow needs the verdicts), but contaminated
        leaves submit their exact per-item checks asynchronously the
        round they appear — the leaf pairing executes on device while
        the next bisection round's arrays assemble on host.

        ``deferred=True`` (the engine's cross-round overlap) instead
        submits the FIRST round behind the in-flight queue and returns a
        zero-arg resumer; calling it processes the verdicts and runs any
        remaining bisection rounds synchronously.  Returns None in sync
        mode.
        """
        pending = self._rlc_apply_cap([list(grp) for grp in groups if grp])
        grouped_idx = [i for grp in pending for i in grp]
        tr = self.tracer
        if tr is not None:
            h = tr.hist("rlc_group_size")
            for grp in pending:
                h.record(len(grp))
        if deferred and pending:
            # Cross-round overlap (PR 5): submit the FIRST round's group
            # check behind the bounded queue and hand back a resolver —
            # the engine assembles the next round's item lists while the
            # check executes.  Bisection (a failing group) resumes
            # synchronously inside the resolver; the honest path is one
            # deferred dispatch.  Dispatch counts are identical to the
            # sync loop: same rounds, same chunks, only the first fetch
            # is deferred.
            placed, n_items = self._rlc_round_stage(
                pending, build_group_arrays, pipelined=True
            )
            holder: List[Any] = []
            self.counters.device_dispatches += 1
            self._dispatch_async(
                jitted, placed, kind=kind, items=n_items,
                on_result=holder.append,
            )

            def resume():
                if not holder:
                    self._pipe.flush()
                nxt = self._rlc_process_round(
                    pending, holder[0], items, results, direct_quad
                )
                self._rlc_rounds(
                    nxt, items, build_group_arrays, jitted, results,
                    direct_quad, kind,
                )
                self._rlc_observe(grouped_idx, results)

            return resume
        self._rlc_rounds(
            pending, items, build_group_arrays, jitted, results,
            direct_quad, kind,
        )
        self._rlc_observe(grouped_idx, results)
        return None

    def _rlc_rounds(
        self, pending, items, build_group_arrays, jitted, results,
        direct_quad, kind,
    ) -> None:
        """The synchronous bisection loop: one SYNC group dispatch per
        round (control flow needs the verdicts) until no group remains."""
        while pending:
            placed, n_items = self._rlc_round_stage(pending, build_group_arrays)
            self.counters.device_dispatches += 1
            f = self._dispatch_fetch(
                jitted, placed, kind=kind, items=n_items,
            )
            pending = self._rlc_process_round(
                pending, f, items, results, direct_quad
            )
        self._pipe.flush()

    def _rlc_round_stage(self, pending, build_group_arrays,
                         pipelined: bool = False):
        """Stage one bisection round's arrays: pad groups, draw fresh RLC
        coefficients (one flattened ``scalars_to_bits`` call for the
        whole (g, k) matrix), build the group point arrays, place.
        ``pipelined`` marks the deferred first round (PR 5) so the mesh
        backend can land it whole on one device."""
        with self._host_assembly():
            k = _bucket(max(len(grp) for grp in pending))
            g = self._pad_bucket(len(pending))
            pad_group = [None] * k
            padded: List[List[Optional[int]]] = [
                list(grp) + [None] * (k - len(grp)) for grp in pending
            ] + [pad_group] * (g - len(pending))

            if hostpipe_enabled():
                # one entropy draw for the whole round's (g, k) matrix —
                # the per-coefficient urandom syscall was a visible slice
                # of the legacy dispatch bucket at N³ verify shapes
                bits = self._rlc_bits()
                top = (1 << bits) - 1
                nb = (bits + 7) // 8
                buf = os.urandom(nb * k * len(padded))
                flat_rs = [
                    1 + int.from_bytes(buf[o : o + nb], "big") % top
                    for o in range(0, len(buf), nb)
                ]
                scalars = [
                    flat_rs[gi * k + j] if idx is not None else 0
                    for gi, grp in enumerate(padded)
                    for j, idx in enumerate(grp)
                ]
            else:
                scalars = []
                for grp in padded:
                    rs = self._rlc_scalars(k)
                    scalars.extend(
                        r if idx is not None else 0 for r, idx in zip(rs, grp)
                    )
            rbits = curve.scalars_to_bits(
                scalars, self._rlc_bits()
            ).reshape(g, k, -1)

            args = build_group_arrays(padded, g, k)
            placed = self._place(
                tuple(args) + (jnp.asarray(rbits),), pipelined=pipelined
            )
        # two RLC_BITS-wide w2 ladders per lane (share + key combine);
        # the 64-bit coefficients stay on the classic path — GLV
        # decomposition has nothing to split below 2^127
        n_items = sum(len(grp) for grp in pending)
        self._count_ladder(rbits, n_items, glv=False, ladders_per_lane=2)
        self.counters.rlc_groups += len(pending)
        return placed, n_items

    def _rlc_process_round(self, pending, f, items, results, direct_quad):
        """Apply one round's group verdicts: passing groups write True,
        small contaminated groups drop to async exact per-item checks,
        larger ones bisect into the next round."""
        next_pending: List[List[int]] = []
        new_leaves: List[int] = []
        if hostpipe_enabled():
            group_ok = pairing.is_one_host_batch(f, len(pending))
        else:
            group_ok = [
                pairing.is_one_host(f, gi) for gi in range(len(pending))
            ]
        for gi, grp in enumerate(pending):
            if group_ok[gi]:
                for idx in grp:
                    results[idx] = True
            elif len(grp) < 2 * self.rlc_min_group:
                new_leaves.extend(grp)
            else:
                mid = len(grp) // 2
                next_pending.append(grp[:mid])
                next_pending.append(grp[mid:])
        if new_leaves:
            self._check_batch_async(
                [direct_quad(items[idx]) for idx in new_leaves],
                lambda j, ok, leaves=tuple(new_leaves): results.__setitem__(
                    leaves[j], ok
                ),
            )
        return next_pending

    # -- batched verification ------------------------------------------------

    def _finish_verify(self, results, cont, deferred):
        """Shared tail of the verify entry points: sync mode flushes and
        returns the booleans; deferred mode returns a resolver that
        flushes (materializing the deferred first-round check and any
        async leaf checks), resumes bisection if needed, and returns the
        same booleans the sync path would."""
        if not deferred:
            self._pipe.flush()
            return [bool(r) for r in results]

        def resolve():
            self._pipe.flush()
            if cont is not None:
                cont()
            return [bool(r) for r in results]

        return resolve

    def verify_sig_shares(
        self, items: Sequence[Tuple[PublicKeyShare, bytes, SignatureShare]]
    ) -> List[bool]:
        return self._verify_sig_shares_impl(items, deferred=False)

    def verify_sig_shares_deferred(
        self, items: Sequence[Tuple[PublicKeyShare, bytes, SignatureShare]]
    ):
        """Submit the batch now (direct checks + first RLC round behind
        the in-flight queue); the returned resolver yields the same
        booleans ``verify_sig_shares`` would, with identical dispatch
        counts — the engine assembles the next round's work in between."""
        return self._verify_sig_shares_impl(items, deferred=True)

    def _verify_sig_shares_impl(self, items, deferred: bool):
        g1 = self.group.g1()

        def direct(item):
            pk, doc, share = item
            return (g1, share.el, pk.el, self._hash_g2(doc))

        self.counters.sig_shares_verified += len(items)
        n = len(items)
        results: List[Optional[bool]] = [None] * n

        by_doc: Dict[bytes, List[int]] = {}
        for i, (pk, doc, share) in enumerate(items):
            by_doc.setdefault(doc, []).append(i)

        rlc_groups = [g for g in by_doc.values() if len(g) >= self.rlc_min_group]
        direct_idx = [
            i for g in by_doc.values() if len(g) < self.rlc_min_group for i in g
        ]

        if direct_idx:
            # pipelined: the direct pairing checks execute on device
            # while the RLC group arrays below assemble on host
            self._check_batch_async(
                [direct(items[i]) for i in direct_idx],
                lambda j, ok, idx=tuple(direct_idx): results.__setitem__(
                    idx[j], ok
                ),
            )

        def build(padded, g, k):
            flat = [i for grp in padded for i in grp]
            cache = self._stage
            # Jacobian form (Z=1) for the ladder lanes; per-receiver
            # replication gathers from the distinct staged rows.
            S_jac = self._reshape_groups(
                self._to_device_gather(
                    [items[i][2].el if i is not None else None for i in flat],
                    curve.g2_to_device,
                ),
                g,
                k,
            )
            PK_jac = self._reshape_groups(
                self._to_device_gather(
                    [items[i][0].el if i is not None else None for i in flat],
                    curve.g1_to_device,
                ),
                g,
                k,
            )
            neg_g1 = pairing.g1_affine_to_device(
                [self.group.g1_neg(g1)] * g, cache=cache
            )
            hs = []
            for gi in range(g):
                grp = padded[gi]
                first = next((i for i in grp if i is not None), None)
                hs.append(
                    self._hash_g2(items[first][1]) if first is not None else None
                )
            H = pairing.g2_affine_to_device(hs, cache=cache)
            return (S_jac, PK_jac, neg_g1, H)

        def jitted(S_jac, PK_jac, neg_g1, H, rbits):
            # per-dispatch routing resolve + fused-chain accounting; the
            # dispatch KIND stays rlc_sig (the RLC bucket split is by
            # workload, the fused/unfused split reads off the counters)
            from hbbft_tpu.ops import pairing_chain

            mode = pairing_chain.fused_tower_mode()
            self._bill_chain(mode, rbits.shape[0])
            return _jitted_rlc_sig(mode)(S_jac, PK_jac, rbits, neg_g1, H)

        cont = self._grouped_rlc(
            rlc_groups, items, build, jitted, results, direct,
            kind="rlc_sig", deferred=deferred,
        )
        return self._finish_verify(results, cont, deferred)

    def verify_signatures(
        self, items: Sequence[Tuple[Any, bytes, Signature]]
    ) -> List[bool]:
        self.counters.signatures_verified += len(items)
        g1 = self.group.g1()
        quads = [
            (g1, sig.el, pk.el, self._hash_g2(msg)) for pk, msg, sig in items
        ]
        return self._check_batch(quads)

    def verify_dec_shares(
        self, items: Sequence[Tuple[PublicKeyShare, Ciphertext, DecryptionShare]]
    ) -> List[bool]:
        return self._verify_dec_shares_impl(items, deferred=False)

    def verify_dec_shares_deferred(
        self, items: Sequence[Tuple[PublicKeyShare, Ciphertext, DecryptionShare]]
    ):
        """Deferred twin of ``verify_dec_shares`` (contract as for
        ``verify_sig_shares_deferred``)."""
        return self._verify_dec_shares_impl(items, deferred=True)

    def _verify_dec_shares_impl(self, items, deferred: bool):
        def direct(item):
            pk, ct, share = item
            h = self._hash_g2(self.group.g1_to_bytes(ct.u) + ct.v)
            return (share.el, h, pk.el, ct.w)

        self.counters.dec_shares_verified += len(items)
        n = len(items)
        results: List[Optional[bool]] = [None] * n

        by_ct: Dict[bytes, List[int]] = {}
        for i, (pk, ct, share) in enumerate(items):
            by_ct.setdefault(ct.digest(), []).append(i)

        rlc_groups = [g for g in by_ct.values() if len(g) >= self.rlc_min_group]
        direct_idx = [
            i for g in by_ct.values() if len(g) < self.rlc_min_group for i in g
        ]

        if direct_idx:
            self._check_batch_async(
                [direct(items[i]) for i in direct_idx],
                lambda j, ok, idx=tuple(direct_idx): results.__setitem__(
                    idx[j], ok
                ),
            )

        def build(padded, g, k):
            flat = [i for grp in padded for i in grp]
            cache = self._stage
            D_jac = self._reshape_groups(
                self._to_device_gather(
                    [items[i][2].el if i is not None else None for i in flat],
                    curve.g1_to_device,
                ),
                g,
                k,
            )
            PK_jac = self._reshape_groups(
                self._to_device_gather(
                    [items[i][0].el if i is not None else None for i in flat],
                    curve.g1_to_device,
                ),
                g,
                k,
            )
            hs, ws = [], []
            for gi in range(g):
                grp = padded[gi]
                first = next((i for i in grp if i is not None), None)
                if first is None:
                    hs.append(None)
                    ws.append(None)
                else:
                    ct = items[first][1]
                    hs.append(self._hash_g2(self.group.g1_to_bytes(ct.u) + ct.v))
                    ws.append(ct.w)
            H = pairing.g2_affine_to_device(hs, cache=cache)
            W = pairing.g2_affine_to_device(ws, cache=cache)
            return (D_jac, PK_jac, H, W)

        def jitted(D_jac, PK_jac, H, W, rbits):
            from hbbft_tpu.ops import pairing_chain

            mode = pairing_chain.fused_tower_mode()
            self._bill_chain(mode, rbits.shape[0])
            return _jitted_rlc_dec(mode)(D_jac, PK_jac, rbits, H, W)

        cont = self._grouped_rlc(
            rlc_groups, items, build, jitted, results, direct,
            kind="rlc_dec", deferred=deferred,
        )
        return self._finish_verify(results, cont, deferred)

    def verify_ciphertexts(self, items: Sequence[Ciphertext]) -> List[bool]:
        self.counters.ciphertexts_verified += len(items)
        return self._check_batch(self._ct_quads(items))

    def verify_ciphertexts_deferred(self, items: Sequence[Ciphertext]):
        """Deferred twin of ``verify_ciphertexts``: the pairing chunks
        execute behind the in-flight queue while the engine assembles the
        decrypt-share round; the resolver flushes and returns the same
        booleans with identical dispatch counts."""
        self.counters.ciphertexts_verified += len(items)
        results: List[Optional[bool]] = [None] * len(items)
        self._check_batch_async(self._ct_quads(items), results.__setitem__)
        return self._finish_verify(results, None, deferred=True)

    def _ct_quads(self, items: Sequence[Ciphertext]):
        g1 = self.group.g1()
        quads = []
        for ct in items:
            h = self._hash_g2(self.group.g1_to_bytes(ct.u) + ct.v)
            quads.append((g1, ct.w, ct.u, h))
        return quads

    # -- combination ---------------------------------------------------------

    def _lagrange_device(
        self, pts: List[Tuple[int, Any]], to_device, from_device, jitted, prep
    ):
        """Shared padding/bucketing for device Lagrange combines.

        Pads with infinity points and zero scalars (0·∞ contributes the
        identity; a zero scalar decomposes to all-zero GLV halves) up to
        a power-of-two bucket so XLA compiles few shapes.  ``prep`` is
        the group's curve.prep_g*_scalars — it picks the GLV joint-table
        or classic safe_scalar ladder form.
        """
        with self._host_assembly():
            lam = lagrange_coeffs_at_zero([x for x, _ in pts])
            args = self._stage_combine(
                lam, [el for _, el in pts], to_device, prep
            )
        combined = self._dispatch_fetch(
            jitted, args, kind="combine", items=len(pts),
        )
        return from_device(combined)[0]

    def _stage_combine(self, coeffs, point_els, to_device, prep):
        """Shared staging core of the single-combine and MSM-chunk
        dispatches: bucket-pad points with ∞ and coefficients with
        zero-scalar rows (identity contributions), prep through the
        GLV/classic seam, and bill the ladder accounting — ONE place so
        pad rules and counters cannot drift between the paths."""
        b = _bucket(len(point_els))
        pts = list(point_els) + [None] * (b - len(point_els))
        bits, negs = self._prep_scalars(prep, list(coeffs))
        bits, negs = _pad_zero_rows(bits, negs, b - len(point_els))
        self._count_ladder(bits, len(point_els), glv=bits.ndim == 3)
        return (to_device(pts, cache=self._stage), bits, negs)

    def _lagrange_device_g2(self, pts: List[Tuple[int, Any]]):
        return self._lagrange_device(
            pts, curve.g2_to_device, curve.g2_from_device,
            _jitted_combine_g2(), curve.prep_g2_scalars,
        )

    def _lagrange_device_g1(self, pts: List[Tuple[int, Any]]):
        return self._lagrange_device(
            pts, curve.g1_to_device, curve.g1_from_device,
            _jitted_combine_g1(), curve.prep_g1_scalars,
        )

    def combine_signatures(
        self,
        pk_set: PublicKeySet,
        shares: Dict[int, SignatureShare],
        doc: Optional[bytes] = None,
    ) -> Signature:
        if len(shares) <= pk_set.threshold():
            raise CryptoError(
                f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
            )
        self.counters.sig_shares_combined += len(shares)
        if len(shares) < self.device_combine_threshold:
            return pk_set.combine_signatures(shares)
        pts = [(i + 1, s.el) for i, s in sorted(shares.items())]
        self.counters.device_dispatches += 1
        sig = Signature(self.group, self._lagrange_device_g2(pts))
        if doc is not None:
            # Defense in depth for the device ladder (see ops/curve.py
            # docstring): one batched device pairing check of the combined
            # signature against the master public key.  On mismatch fall
            # back to the host golden combine — correctness over speed.
            pk = pk_set.public_key()
            ok = self._check_batch([(self.group.g1(), sig.el, pk.el, self._hash_g2(doc))])
            if not ok[0]:
                return pk_set.combine_signatures(shares)
        return sig

    def combine_decryption_shares(
        self, pk_set: PublicKeySet, shares: Dict[int, DecryptionShare], ct: Ciphertext
    ) -> bytes:
        if len(shares) <= pk_set.threshold():
            raise CryptoError(
                f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
            )
        self.counters.dec_shares_combined += len(shares)
        if len(shares) < self.device_combine_threshold:
            return pk_set.combine_decryption_shares(shares, ct)
        pts = [(i + 1, s.el) for i, s in sorted(shares.items())]
        self.counters.device_dispatches += 1
        return self._plaintext_from_combined(self._lagrange_device_g1(pts), ct)

    def _plaintext_from_combined(self, combined, ct: Ciphertext) -> bytes:
        """Shared tail of threshold decryption: pad = H(s·PK), v ⊕ pad."""
        g = self.group
        pad = g.hash_bytes(g.g1_to_bytes(combined), len(ct.v))
        return bytes(a ^ b for a, b in zip(ct.v, pad))

    def combine_dec_shares_batch(
        self,
        pk_set: PublicKeySet,
        items: Sequence[Tuple[Dict[int, DecryptionShare], Ciphertext]],
    ) -> List[bytes]:
        """All combines in ONE device dispatch per share-count group.

        The array engine emits N² combines per epoch (N proposers × N
        receivers, each over the same f+1 verified share set) — per-item
        device round-trips would dominate.  Items are grouped by share
        count k, vmapped over the item axis of a (B, k) Lagrange linear
        combination, and padded to power-of-two B buckets so XLA compiles
        a handful of shapes.
        """
        out: List[Optional[bytes]] = [None] * len(items)
        by_k: Dict[int, List[int]] = {}
        for idx, (shares, _ct) in enumerate(items):
            if len(shares) <= pk_set.threshold():
                raise CryptoError(
                    f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
                )
            by_k.setdefault(len(shares), []).append(idx)
        for k, all_idxs in by_k.items():
            self.counters.dec_shares_combined += k * len(all_idxs)
            # Gate on TOTAL ladder lanes (k shares × batch items), not the
            # per-item share count: at N=16 every item has k=f+1=6 shares
            # and a per-item gate would push 256-item batches through the
            # host loop one combine at a time (measured 14.5 s/epoch).
            if k * len(all_idxs) < self.device_combine_threshold:
                for idx in all_idxs:
                    shares, ct = items[idx]
                    out[idx] = pk_set.combine_decryption_shares(shares, ct)
                continue
            step = self._lane_capped_step(k)
            for lo in range(0, len(all_idxs), step):
                self._combine_dec_chunk(
                    pk_set, items, all_idxs[lo : lo + step], k, out
                )
        self._pipe.flush()
        return out  # type: ignore[return-value]

    def _combine_dec_chunk(self, pk_set, items, idxs, k, out) -> None:
        def deliver(combined, idxs=tuple(idxs)):
            els = curve.g1_from_device(_squeeze_point(combined))
            for idx, el in zip(idxs, els[: len(idxs)]):
                out[idx] = self._plaintext_from_combined(el, items[idx][1])

        self._lagrange_chunk(
            [items[idx][0] for idx in idxs],
            k,
            curve.g1_to_device,
            _jitted_combine_g1_batch(),
            deliver,
            curve.prep_g1_scalars,
        )

    def _ladder_batch(self, scalars, points, host_fn, to_device,
                      from_device, jitted, prep, kind=""):
        """Shared body of the batched independent-ladder dispatches
        (decrypt-share generation in G1, coin-share signing in G2):
        threshold gate → lane-capped pipelined chunk loop → bucket pad →
        deferred-fetch dispatch per chunk → unwrap.

        ``prep`` (curve.prep_g1_scalars / prep_g2_scalars) turns the
        chunk's scalars into the ladder bit form — GLV/GLS-decomposed
        joint-table windows by default, classic safe_scalar bits under
        ``HBBFT_TPU_NO_GLV=1``; outputs are bit-identical either way.
        ``host_fn(i)`` is the per-item host golden below the threshold;
        it also serves a trailing chunk that falls below the threshold
        (n == cap + small tail), exactly as the pre-pipeline recursion
        did.  Chunk k+1's staging (decomposition + bit packing + point
        conversion) overlaps chunk k's device execution; each chunk's
        deferred fetch delivers into its own slice of ``out``."""
        n = len(scalars)
        if n < self.device_combine_threshold:
            return [host_fn(i) for i in range(n)]
        out: List[Any] = [None] * n
        cap = self.device_lane_cap  # lane-capped chunks (HBM bound)
        for lo in range(0, n, cap):
            hi = min(n, lo + cap)
            if hi - lo < self.device_combine_threshold:
                for i in range(lo, hi):
                    out[i] = host_fn(i)
                continue
            self._submit_ladder_chunk(
                scalars[lo:hi], points[lo:hi], lo, out,
                to_device, from_device, jitted, prep, kind,
            )
        self._pipe.flush()
        return out

    def _submit_ladder_chunk(self, scalars, points, base, out,
                             to_device, from_device, jitted, prep,
                             kind) -> None:
        n = len(scalars)
        with self._host_assembly():
            b = self._pad_bucket(n)
            bits, negs = self._prep_scalars(prep, list(scalars))
            pts = list(points)
            if b > n:
                bits = np.concatenate([bits, np.repeat(bits[:1], b - n, axis=0)])
                negs = np.concatenate([negs, np.repeat(negs[:1], b - n, axis=0)])
                pts = pts + [pts[0]] * (b - n)
            P = self._to_device_gather(pts, to_device)
            placed = self._place(
                (P, jnp.asarray(bits), jnp.asarray(negs)), pipelined=True
            )
        self._count_ladder(bits, n, glv=bits.ndim == 3)
        self.counters.device_dispatches += 1

        def deliver(fetched, base=base, n=n):
            # from_device's per-lane host affine conversion runs on
            # fetched numpy arrays — host work, deliberately NOT billed
            # as device; under pipelining it overlaps the next chunk's
            # device execution.
            out[base : base + n] = from_device(fetched)[:n]

        self._dispatch_async(
            jitted, placed, kind=kind, items=n, on_result=deliver,
        )

    def sign_shares_batch(
        self, items: Sequence[Tuple[Any, bytes]]
    ) -> List[SignatureShare]:
        """All coin-share generations (x_i·H2(doc)) in one batched G2
        ladder dispatch — the sign side of BASELINE config 2 (N signs per
        coin instance, N instances per epoch at the macro shapes).

        H2(doc) has order r by construction (hash_to_g2 clears the
        cofactor), satisfying the device ladder's precondition."""
        els = self._ladder_batch(
            [sk.x for sk, _ in items],
            [self._hash_g2(doc) for _, doc in items],
            lambda i: items[i][0].sign_share(items[i][1]),
            curve.g2_to_device,
            curve.g2_from_device,
            _jitted_g2_mul_batch(),
            curve.prep_g2_scalars,
            kind="sign",
        )
        return [
            el if isinstance(el, SignatureShare) else SignatureShare(self.group, el)
            for el in els
        ]

    def combine_sig_shares_batch(
        self,
        pk_set: PublicKeySet,
        items: Sequence[Tuple[Dict[int, SignatureShare], Optional[bytes]]],
    ) -> List[Signature]:
        """All signature combines in ONE device dispatch per share-count
        group — the combine side of BASELINE config 2 (every receiver
        combines f+1 verified coin shares per instance).  Mirrors
        combine_dec_shares_batch's grouping/lane-capping; items whose doc
        is not None get a batched combined-signature re-verify against the
        master public key, with host-golden fallback on mismatch (same
        defense-in-depth contract as combine_signatures)."""
        out: List[Optional[Signature]] = [None] * len(items)
        by_k: Dict[int, List[int]] = {}
        for idx, (shares, _doc) in enumerate(items):
            if len(shares) <= pk_set.threshold():
                raise CryptoError(
                    f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
                )
            by_k.setdefault(len(shares), []).append(idx)
        device_idxs: List[int] = []
        for k, all_idxs in by_k.items():
            self.counters.sig_shares_combined += k * len(all_idxs)
            if k * len(all_idxs) < self.device_combine_threshold:
                for idx in all_idxs:
                    shares, doc = items[idx]
                    out[idx] = pk_set.combine_signatures(shares)
                continue
            device_idxs.extend(all_idxs)
            step = self._lane_capped_step(k)
            for lo in range(0, len(all_idxs), step):
                self._combine_sig_chunk(
                    pk_set, items, all_idxs[lo : lo + step], k, out
                )
        self._pipe.flush()  # materialize deferred combine chunks
        # Batched defense-in-depth for DEVICE-combined items only (the
        # host path IS the golden combine — re-verifying it would just
        # recompute itself on mismatch): one pairing per doc-carrying item.
        check_idx = [i for i in device_idxs if items[i][1] is not None]
        if check_idx:
            g1 = self.group.g1()
            pk = pk_set.public_key()
            quads = [
                (g1, out[i].el, pk.el, self._hash_g2(items[i][1]))
                for i in check_idx
            ]
            ok = self._check_batch(quads)
            for i, good in zip(check_idx, ok):
                if not good:
                    out[i] = pk_set.combine_signatures(items[i][0])
        return out  # type: ignore[return-value]

    def _combine_prep(self, idxs: Tuple[int, ...], prep):
        """Memoized (bits, negs) ladder form of the Lagrange coefficients
        over share indices ``idxs`` (0-based) — a pure function of the
        indices and the GLV arm.  ``HBBFT_TPU_NO_HOSTPIPE=1`` bypasses
        the memo so the kill-switch arm measures the legacy per-item
        decomposition cost (and bills the legacy GLV counters)."""
        if not hostpipe_enabled():
            lam = lagrange_coeffs_at_zero([i + 1 for i in idxs])
            return self._prep_scalars(prep, lam)
        key = (prep, idxs, curve.glv_enabled())
        hit = self._prep_memo.get(key)
        if hit is None:
            lam = lagrange_coeffs_at_zero([i + 1 for i in idxs])
            hit = self._prep_scalars(prep, lam)
            if len(self._prep_memo) >= 4096:
                self._prep_memo.clear()
            self._prep_memo[key] = hit
        return hit

    def _lane_capped_step(self, k: int) -> int:
        """Items per combine chunk: lane-capped (one oversized graph OOMs
        HBM — see device_lane_cap), rounded down to a power of two so
        _pad_bucket's round-up can't overshoot the cap or waste lanes on
        padding.

        Chunk-boundary fix (PR 3): _pad_bucket has a FLOOR — _bucket
        never returns less than _MIN_BUCKET (and MeshBackend widens to
        the mesh lcm) — so a step below that floor still dispatches
        floor·k padded lanes per chunk.  When cap // k lands under the
        floor, clamping the step UP to the floor dispatches the same
        lanes per chunk with zero padding waste and fewer chunks (the
        old rounded-down step of 1-2 items burned up to 75% of each
        dispatch on pad lanes)."""
        step = max(1, self.device_lane_cap // k)
        if step & (step - 1):
            step = 1 << (step.bit_length() - 1)
        floor = self._pad_bucket(1)
        if step < floor:
            step = floor
        return step

    def _lagrange_chunk(self, share_dicts, k, to_device, jitted, on_result,
                        prep):
        """Shared chunk body for the batched Lagrange combines: (B, k)
        point tree + per-item coefficient bit/neg rows (GLV-decomposed by
        default — ``prep`` picks the form), padded with copies of the
        first item (discarded) to a power-of-two item bucket.

        The dispatch is pipelined: ``on_result(fetched)`` is called from
        the deferred fetch while later chunks assemble; the caller
        flushes the pipeline before reading its output slots."""
        with self._host_assembly():
            b = self._pad_bucket(len(share_dicts))
            flat_pts: List[Any] = []
            bits_rows = []
            negs_rows = []
            for shares in share_dicts:
                srt = sorted(shares.items())
                flat_pts.extend(s.el for _, s in srt)
                row_bits, row_negs = self._combine_prep(
                    tuple(i for i, _ in srt), prep
                )
                bits_rows.append(row_bits)
                negs_rows.append(row_negs)
            pad = b - len(share_dicts)
            flat_pts.extend(flat_pts[:k] * pad)
            bits_rows.extend([bits_rows[0]] * pad)
            negs_rows.extend([negs_rows[0]] * pad)
            P = self._to_device_gather(flat_pts, to_device)
            P = jax.tree_util.tree_map(
                lambda c: jnp.reshape(c, (b, k) + c.shape[1:]), P
            )
            bits = jnp.asarray(np.stack(bits_rows))
            negs = jnp.asarray(np.stack(negs_rows))
            placed = self._place((P, bits, negs), pipelined=True)
        # bits_rows[0] is the host numpy prep output — shape/ndim only
        self._count_ladder(
            bits_rows[0], len(share_dicts) * k, glv=bits_rows[0].ndim == 3,
        )
        self.counters.device_dispatches += 1
        return self._dispatch_async(
            jitted, placed, kind="combine", items=len(share_dicts),
            on_result=on_result,
        )

    def _combine_sig_chunk(self, pk_set, items, idxs, k, out) -> None:
        def deliver(combined, idxs=tuple(idxs)):
            els = curve.g2_from_device(_squeeze_point(combined))
            for idx, el in zip(idxs, els[: len(idxs)]):
                out[idx] = Signature(self.group, el)

        self._lagrange_chunk(
            [items[idx][0] for idx in idxs],
            k,
            curve.g2_to_device,
            _jitted_combine_g2_batch(),
            deliver,
            curve.prep_g2_scalars,
        )

    def decrypt_shares_batch(
        self, items: Sequence[Tuple[Any, Ciphertext]]
    ) -> List[DecryptionShare]:
        """All N² decrypt-share generations (x_i·U_p) in one batched G1
        ladder dispatch — the whole-network simulation's round-7 workload
        (host golden: ~9 ms per scalar mult; measured 4.4 s/epoch at N=16
        before batching).

        Precondition: every ct.u has order r.  The device ladder's
        unequal-add safety argument (ops/curve.py) holds only for order-r
        points; this is guaranteed because encrypt() constructs u = rG1
        and network-deserialized points pass the subgroup check in
        bls381.g1_from_bytes (g1_in_subgroup)."""
        els = self._ladder_batch(
            [sk.x for sk, _ in items],
            [ct.u for _, ct in items],
            lambda i: items[i][0].decrypt_share_unchecked(items[i][1]),
            curve.g1_to_device,
            curve.g1_from_device,
            _jitted_g1_mul_batch(),
            curve.prep_g1_scalars,
            kind="decrypt",
        )
        return [
            el if isinstance(el, DecryptionShare) else DecryptionShare(self.group, el)
            for el in els
        ]

    def g1_mul_batch(
        self, scalars: Sequence[int], points: Sequence[Any], kind: str = "dkg"
    ) -> List[Any]:
        """Batched independent G1 ladders s_i·P_i for the batched DKG and
        batched threshold encryption: commitment coefficient muls,
        ciphertext U/shared components, row/value decrypt ladders.
        ``kind`` picks the device-time attribution bucket.

        Precondition (as for decrypt_shares_batch): points have order r —
        the DKG feeds generator multiples and honestly-encrypted U values.
        """
        return self._ladder_batch(
            list(scalars),
            list(points),
            lambda i: self.group.g1_mul(scalars[i], points[i]),
            curve.g1_to_device,
            curve.g1_from_device,
            _jitted_g1_mul_batch(),
            curve.prep_g1_scalars,
            kind=kind,
        )

    def g1_lincomb(self, scalars: Sequence[int], points: Sequence[Any]) -> Any:
        """One device MSM Σ s_i·P_i — the aggregated side of the batched
        DKG's RLC commitment cross-checks and era-change consistency
        checks (engine/dkg_batch.py feeds N²-sized point sets here).

        Above the combine threshold this is a single linear_combine_g1
        dispatch per lane-capped chunk riding the GLV joint-table ladder
        (the base class falls back to batched muls + host fold, which
        costs a per-point host add and a full readback).  Chunks are
        PIPELINED like every other lane-capped loop here: chunk k+1's
        decomposition + staging overlaps chunk k's device execution, and
        the ≤ n/device_lane_cap partial sums fold on host after the
        flush.

        Precondition (as for g1_mul_batch): points have order r."""
        n = len(scalars)
        if n < self.device_combine_threshold:
            return super().g1_lincomb(scalars, points)
        cap = self.device_lane_cap
        partials: List[Any] = [None] * ((n + cap - 1) // cap)
        for ci, lo in enumerate(range(0, n, cap)):
            chunk_s = list(scalars[lo : lo + cap])
            chunk_p = list(points[lo : lo + cap])
            if len(chunk_s) < self.device_combine_threshold:
                # sub-threshold tail chunk: host fold, as _ladder_batch
                # does — a device round-trip for a couple of scalars
                # costs more than it saves
                acc_h = None
                for s, p in zip(chunk_s, chunk_p):
                    acc_h = self.group.g1_add(acc_h, self.group.g1_mul(s, p))
                partials[ci] = acc_h
                continue
            with self._host_assembly():
                args = self._stage_combine(
                    chunk_s, chunk_p, curve.g1_to_device,
                    curve.prep_g1_scalars,
                )
            self.counters.device_dispatches += 1

            def deliver(fetched, ci=ci):
                partials[ci] = curve.g1_from_device(fetched)[0]

            self._dispatch_async(
                _jitted_combine_g1(), args, kind="dkg", items=len(chunk_s),
                on_result=deliver,
            )
        self._pipe.flush()
        acc = partials[0]
        for el in partials[1:]:
            acc = self.group.g1_add(acc, el)
        return acc

    def g2_mul_batch(
        self, scalars: Sequence[int], points: Sequence[Any], kind: str = "dkg"
    ) -> List[Any]:
        """Batched independent G2 ladders (ciphertext W = s·H2(U‖V))."""
        return self._ladder_batch(
            list(scalars),
            list(points),
            lambda i: self.group.g2_mul(scalars[i], points[i]),
            curve.g2_to_device,
            curve.g2_from_device,
            _jitted_g2_mul_batch(),
            curve.prep_g2_scalars,
            kind=kind,
        )

    # -- device erasure/hash plane (PR 19) -----------------------------------
    #
    # RS encode/reconstruct as GF(2⁸) F₂ bit-matmuls (ops/gf256.py) and
    # Merkle build/verify as batched device SHA-256 (ops/sha256.py), routed
    # through the same DispatchPipeline seam as the pairing/ladder chunks —
    # _place(pipelined=True) means these chunks also ride MeshBackend's
    # per-device queues (parallel/shardpipe.py) with no extra code.  Every
    # method is bit-identical to the CryptoBackend host default (asserted
    # by the parity fuzz in tests/test_device_rs.py), and
    # HBBFT_TPU_NO_DEVICE_RS=1 routes straight to it.  The explicit
    # CryptoBackend.<method>(self, ...) calls (instead of super()) keep the
    # fallback usable from test hybrids that borrow these methods unbound.

    def rs_encode_batch(
        self, codec, datas: Sequence[bytes]
    ) -> List[List[bytes]]:
        """All blocks' parity in one batched bit-matmul per shard length.

        The N per-proposer encodes of an epoch share one (k, m) codec and
        (near-always) one framed length, so they collapse into a single
        (8m × 8k) @ (8k × N·L) MXU product — the "N parallel encodes
        become one matmul" plank of the north star."""
        if not device_rs_enabled() or not datas or codec.m == 0:
            return CryptoBackend.rs_encode_batch(self, codec, datas)
        key = (codec.k, codec.m)
        bits = self._rs_enc_bits.get(key)
        if bits is None:
            bits = self._rs_enc_bits[key] = jnp.asarray(
                expand_gf_matrix(codec.encode_matrix)
            )
        out: List[Optional[List[bytes]]] = [None] * len(datas)
        by_len: Dict[int, List[int]] = {}
        for i, d in enumerate(datas):
            by_len.setdefault(codec.shard_length(len(d)), []).append(i)
        k, m = codec.k, codec.m
        for shard_len, idxs in by_len.items():
            with self._host_assembly():
                stack = np.empty((len(idxs), k, shard_len), dtype=np.uint8)
                for row, i in enumerate(idxs):
                    padded = datas[i].ljust(shard_len * k, b"\0")
                    stack[row] = np.frombuffer(
                        padded, dtype=np.uint8
                    ).reshape(k, shard_len)
                # (G, k, L) → (k, G·L): per-block columns concatenate, so
                # the whole group is ONE matmul against the shared matrix
                mat = np.ascontiguousarray(stack.transpose(1, 0, 2)).reshape(
                    k, len(idxs) * shard_len
                )
                placed = self._place(
                    (bits, jnp.asarray(mat)), pipelined=True
                )
            self.counters.device_dispatches += 1

            def deliver(parity, idxs=tuple(idxs), stack=stack, L=shard_len):
                par = parity.reshape(m, len(idxs), L)
                for row, i in enumerate(idxs):
                    out[i] = [stack[row, j].tobytes() for j in range(k)] + [
                        par[j, row].tobytes() for j in range(m)
                    ]

            self._dispatch_async(
                gf256_matmul, placed, kind="rs_enc", items=len(idxs),
                on_result=deliver,
            )
        self._pipe.flush()
        return out  # type: ignore[return-value]

    def rs_reconstruct_batch(
        self, codec, shard_lists: Sequence[Sequence[Optional[bytes]]]
    ) -> List[List[bytes]]:
        """All erasure repairs in one batched decode matmul per pattern.

        Items are grouped by (present-k indices, missing indices, shard
        length) — the decode matrix is a constant per such pattern, served
        from the bounded LRU.  Error cases (wrong slot count, too few
        shards) and the zero-math all-present case run the host codec
        inline, in item order, so raises and results match the host loop
        exactly."""
        if not device_rs_enabled() or not shard_lists:
            return CryptoBackend.rs_reconstruct_batch(self, codec, shard_lists)
        sls = [list(s) for s in shard_lists]
        out: List[Optional[List[bytes]]] = [None] * len(sls)
        groups: Dict[Tuple, List[int]] = {}
        for i, shards in enumerate(sls):
            present = [j for j, s in enumerate(shards) if s is not None]
            if len(shards) != codec.n or len(present) < codec.k:
                out[i] = codec.reconstruct(shards)  # the exact host raise
                continue
            missing = tuple(j for j, s in enumerate(shards) if s is None)
            if not missing:
                # all-present: zero GF math on the host path too
                out[i] = codec.reconstruct(shards)
                continue
            xs = tuple(present[: codec.k])
            lens = {len(shards[j]) for j in xs}
            if len(lens) != 1:
                # ragged present shards: host np.stack raises — keep it
                out[i] = codec.reconstruct(shards)
                continue
            groups.setdefault((xs, missing, lens.pop()), []).append(i)
        for (xs, missing, shard_len), idxs in groups.items():
            with self._host_assembly():
                bits = self._rs_dec_cache.get(xs, missing)
                stack = np.empty(
                    (len(idxs), codec.k, shard_len), dtype=np.uint8
                )
                for row, i in enumerate(idxs):
                    for col, j in enumerate(xs):
                        stack[row, col] = np.frombuffer(sls[i][j], dtype=np.uint8)
                mat = np.ascontiguousarray(stack.transpose(1, 0, 2)).reshape(
                    codec.k, len(idxs) * shard_len
                )
                placed = self._place(
                    (bits, jnp.asarray(mat)), pipelined=True
                )
            self.counters.device_dispatches += 1

            def deliver(rec, idxs=tuple(idxs), missing=missing, L=shard_len):
                r = rec.reshape(len(missing), len(idxs), L)
                for row, i in enumerate(idxs):
                    full = list(sls[i])
                    for mrow, midx in enumerate(missing):
                        full[midx] = r[mrow, row].tobytes()
                    out[i] = full

            self._dispatch_async(
                gf256_matmul, placed, kind="rs_dec", items=len(idxs),
                on_result=deliver,
            )
        self._pipe.flush()
        return out  # type: ignore[return-value]

    def merkle_build_batch(self, shard_lists: Sequence[Sequence[bytes]]) -> List[Any]:
        """All proposers' Merkle trees in one batched SHA-256 dispatch.

        Requires the rectangular case (uniform leaf count + length across
        the batch — the epoch's trees, all over one codec's shards); any
        other shape falls back to the host loop, including its empty-tree
        raise.  The fetched levels are adopted without re-hashing
        (MerkleTree.from_levels), so the trees are bit-identical to
        host-built ones."""
        sls = [list(sl) for sl in shard_lists]
        if not device_rs_enabled() or not sls or not sls[0]:
            return CryptoBackend.merkle_build_batch(self, sls)
        n = len(sls[0])
        leaf_len = len(sls[0][0])
        if any(
            len(sl) != n or any(len(v) != leaf_len for v in sl) for sl in sls
        ):
            return CryptoBackend.merkle_build_batch(self, sls)
        with self._host_assembly():
            leaves = np.frombuffer(
                b"".join(b"".join(sl) for sl in sls), dtype=np.uint8
            ).reshape(len(sls), n, leaf_len)
            placed = self._place((jnp.asarray(leaves),), pipelined=True)
        self.counters.device_dispatches += 1
        trees: List[Any] = [None] * len(sls)

        def deliver(levels):
            for ti, sl in enumerate(sls):
                trees[ti] = MerkleTree.from_levels(
                    sl,
                    [
                        [lvl[ti, j].tobytes() for j in range(lvl.shape[1])]
                        for lvl in levels
                    ],
                )

        self._dispatch_async(
            sha256_device.tree_levels, placed, kind="merkle",
            items=len(sls), on_result=deliver,
        )
        self._pipe.flush()
        return trees

    def merkle_verify_batch(self, packed, reps: int = 1) -> List[bool]:
        """All N² packed proofs walked on device, ``reps`` times.

        The repetition contract (one hash workload per simulated
        receiver) is preserved as ``reps`` SEPARATE dispatches over the
        same placed arrays — a reps-times loop inside one jit would be
        CSE'd to a single walk and under-measure the plane.  Verdicts are
        delivered from the first repetition."""
        if not device_rs_enabled() or not len(packed):
            return CryptoBackend.merkle_verify_batch(self, packed, reps=reps)
        with self._host_assembly():
            placed = self._place(
                (
                    jnp.asarray(packed.leaves),
                    jnp.asarray(packed.paths),
                    jnp.asarray(packed.indices),
                    jnp.asarray(packed.roots),
                ),
                pipelined=True,
            )
        verdicts: List[Any] = []
        for rep in range(max(1, int(reps))):
            self.counters.device_dispatches += 1
            self._dispatch_async(
                sha256_device.verify_proofs, placed, kind="merkle",
                items=len(packed),
                on_result=verdicts.append if rep == 0 else None,
            )
        self._pipe.flush()
        return [bool(v) for v in verdicts[0]]

