"""TpuBackend — batched device crypto behind the CryptoBackend seam.

This is BASELINE.json's north star made concrete: protocols (and the
VirtualNet crank loop) hand whole *batches* of pairing checks and share
combinations to this backend, which resolves them in a handful of jitted
device dispatches instead of per-share host loops (SURVEY.md §3.2: the
O(N²) pairing verifies per node per epoch are the entire performance
story).

Every verification equation in the framework has the shape
``e(a1, b1) == e(a2, b2)``, i.e. ``FE(ML(a1, b1)·ML(−a2, b2)) == 1``:

* sig share:    e(G1, σ_i)  == e(PK_i, H2(doc))      (keys.py conventions)
* full sig:     e(G1, σ)    == e(PK, H2(msg))
* dec share:    e(D_i, H)   == e(PK_i, W)
* ciphertext:   e(G1, W)    == e(U, H)

So ONE jitted kernel — two batched Miller loops + one shared final
exponentiation — serves all four batch-verify entry points.  Batches are
padded to power-of-two buckets with trivially-true items (e(G,H)==e(G,H))
so XLA compiles a handful of shapes, once each.

Hash-to-curve and canonical equality run host-side: hashing is not the
dominant cost (SURVEY.md §2.2) and host comparison removes every
sequential carry chain from the device graph (ops/fq.py).

Combines (Lagrange in the exponent) run on device above a batch-size
threshold via the fixed-ladder MSM in ops/curve.py, else on the host
golden path — share counts are small at small N and the 254-step ladder
only pays for itself in bulk.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.crypto.bls381 import BLS381Group
from hbbft_tpu.crypto.field import lagrange_coeffs_at_zero
from hbbft_tpu.crypto.keys import (
    Ciphertext,
    CryptoError,
    DecryptionShare,
    PublicKeySet,
    PublicKeyShare,
    Signature,
    SignatureShare,
)
from hbbft_tpu.ops import curve, pairing, tower

_MIN_BUCKET = 4


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


@functools.lru_cache(maxsize=None)
def _jitted_product2():
    """(P1, Q1, P2, Q2) → fq12 limbs of FE_fast(ML(P1,Q1)·ML(P2,Q2))."""
    return jax.jit(pairing.product2_fast)


@functools.lru_cache(maxsize=None)
def _jitted_combine_g1():
    return jax.jit(curve.linear_combine_g1)


@functools.lru_cache(maxsize=None)
def _jitted_combine_g1_batch():
    """vmap of the G1 Lagrange combine over an item axis: (B, k) points ×
    (B, k, 254) bit matrices → B combined points in one dispatch."""
    return jax.jit(jax.vmap(curve.linear_combine_g1, in_axes=(0, 0, 0)))


@functools.lru_cache(maxsize=None)
def _jitted_combine_g2():
    return jax.jit(curve.linear_combine_g2)


@functools.lru_cache(maxsize=None)
def _jitted_g1_mul_batch():
    """Batched independent G1 ladders: (B,) points × (B, 254) bit rows →
    (B,) Jacobian products (the decrypt-share generation shape)."""
    return jax.jit(curve.g1_scalar_mul_signed)


@functools.lru_cache(maxsize=None)
def _jitted_g2_mul_batch():
    """Batched independent G2 ladders: the coin-sign generation shape
    (x_i·H2(doc) per item; SURVEY.md §3.2 hottest loop)."""
    return jax.jit(curve.g2_scalar_mul_signed)


@functools.lru_cache(maxsize=None)
def _jitted_combine_g2_batch():
    """vmap of the G2 Lagrange combine over an item axis — the batched
    signature-combination shape (B items × k shares each)."""
    return jax.jit(jax.vmap(curve.linear_combine_g2, in_axes=(0, 0, 0)))


def _squeeze_point(P):
    """(G, 1, ...) Jacobian from a vmapped combine → (G, ...)."""
    return jax.tree_util.tree_map(lambda c: c[:, 0], P)


@functools.lru_cache(maxsize=None)
def _jitted_rlc_sig():
    """Grouped sig-share check: e(G1, Σr·σ_i) == e(Σr·PK_i, H) per group.

    Inputs: S (G,k) G2 Jacobian shares, PK (G,k) G1 Jacobian key shares,
    rbits (G,k,RLC_BITS), negG1 (G,) affine −G1, H (G,) affine G2 points.
    Returns fq12 limbs; host checks == 1 per group.
    """

    def f(S, PK, rbits, negG1, H):
        zeros = jnp.zeros(rbits.shape[:2], dtype=bool)
        comb_s = jax.vmap(curve.linear_combine_g2)(S, rbits, zeros)
        comb_pk = jax.vmap(curve.linear_combine_g1)(PK, rbits, zeros)
        s_aff = curve.jac_to_affine_g2(_squeeze_point(comb_s))
        pk_aff = curve.jac_to_affine_g1(_squeeze_point(comb_pk))
        return pairing.product2_fast(negG1, s_aff, pk_aff, H)

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def _jitted_rlc_dec():
    """Grouped dec-share check: e(Σr·D_i, H) == e(Σr·PK_i, W) per group.

    D and PK both live in G1; H, W are per-group affine G2 points.
    """

    def f(D, PK, rbits, H, W):
        zeros = jnp.zeros(rbits.shape[:2], dtype=bool)
        comb_d = jax.vmap(curve.linear_combine_g1)(D, rbits, zeros)
        comb_pk = jax.vmap(curve.linear_combine_g1)(PK, rbits, zeros)
        d_aff = curve.jac_to_affine_g1(_squeeze_point(comb_d))
        pk_aff = curve.jac_to_affine_g1(_squeeze_point(comb_pk))
        neg_pk = (pk_aff[0], jnp.negative(pk_aff[1]), pk_aff[2])
        return pairing.product2_fast(d_aff, H, neg_pk, W)

    return jax.jit(f)


class TpuBackend(CryptoBackend):
    """JAX/TPU batched BLS12-381 backend.

    Protocol-visible semantics are identical to CpuBackend (same golden
    group for key material, hashing and serialization); only the batch
    verify/combine paths move to the device.
    """

    #: combine on device only when at least this many shares are batched
    device_combine_threshold = 8
    #: Max ladder lanes (items × shares) per combine/generation dispatch.
    #: One graph holding the whole batch's Jacobian ladder state scales
    #: HBM linearly with lanes: 557k lanes (10k combines × k=34 at N=100)
    #: needed 16.4 GB against the v5e's 15.75 GB.  32k lanes ≈ 1 GB.
    device_lane_cap = 1 << 15

    def __init__(self) -> None:
        super().__init__(BLS381Group())
        self._h2_cache: Dict[bytes, Any] = {}

    def _pad_bucket(self, n: int) -> int:
        """Bucket size for a batch/group axis.  MeshBackend widens this
        to a multiple of the mesh so the axis shards evenly."""
        return _bucket(n)

    def _place(self, tree):
        """Placement hook for jitted-call inputs (MeshBackend shards)."""
        return tree

    # -- internals -----------------------------------------------------------

    def _hash_g2(self, doc: bytes):
        h = self._h2_cache.get(doc)
        if h is None:
            t0 = time.perf_counter()
            h = self.group.hash_to_g2(doc)
            self.counters.hash_g2_seconds += time.perf_counter() - t0
            while len(self._h2_cache) >= 4096:
                # bounded LRU, not a wholesale clear(): sign_shares_batch
                # hashes every doc up front and the lane-cap recursion
                # re-hashes per chunk, so one >4096-doc batch under
                # clear() would thrash and re-run host hash-to-G2
                self._h2_cache.pop(next(iter(self._h2_cache)))
        else:
            del self._h2_cache[doc]  # re-insert → most-recently-used
        self._h2_cache[doc] = h
        return h

    #: Max pairing checks per device dispatch.  The Miller-loop graph
    #: carries fq12 state (12 x 79 f32 lanes) per item plus staged
    #: intermediates — far heavier per lane than the scalar ladders, so
    #: the cap sits well below device_lane_cap.  The batched DKG feeds
    #: N³-sized ciphertext batches through here (engine/dkg_batch.py);
    #: without the cap a single 32k+-lane pairing dispatch OOMs HBM.
    pairing_lane_cap = int(os.environ.get("HBBFT_TPU_PAIR_CAP", "2048"))

    def _check_batch(self, quads) -> List[bool]:
        """quads: list of (a1, b1, a2, b2) affine tuples checking
        e(a1,b1) == e(a2,b2).  Returns per-item booleans."""
        quads = list(quads)
        n = len(quads)
        if n == 0:
            return []
        if n > self.pairing_lane_cap:
            out: List[bool] = []
            for lo in range(0, n, self.pairing_lane_cap):
                out.extend(self._check_batch(quads[lo : lo + self.pairing_lane_cap]))
            return out
        self.counters.pairing_checks += n
        self.counters.device_dispatches += 1
        g1 = self.group.g1()
        g2 = self.group.g2()
        pad = (g1, g2, g1, g2)  # trivially true
        b = self._pad_bucket(n)
        quads = quads + [pad] * (b - n)

        neg = self.group.g1_neg
        P1 = pairing.g1_affine_to_device([q[0] for q in quads])
        Q1 = pairing.g2_affine_to_device([q[1] for q in quads])
        P2 = pairing.g1_affine_to_device(
            [neg(q[2]) if q[2] is not None else None for q in quads]
        )
        Q2 = pairing.g2_affine_to_device([q[3] for q in quads])

        f = self._dispatch_fetch(
            _jitted_product2(), self._place((P1, Q1, P2, Q2)), kind="pairing",
            items=n,
        )
        return [pairing.is_one_host(f, i) for i in range(n)]

    def _dispatch_fetch(self, jitted, args, kind: str = "", items: int = 0):
        """Dispatch one jitted call and fetch the result to host, billing
        the wall clock to counters.device_seconds (task-8 attribution —
        includes any queued device work this fetch must wait for) and,
        when ``kind`` is given, to ``device_seconds_<kind>`` so macro rows
        can break an epoch's device time down by op kind (r4 task 7).

        With a tracer attached, the identical [t0, t1] interval becomes a
        ``device=True`` dispatch span on the ``device`` track — traced
        device time and counter attribution agree exactly by construction
        (the acceptance check in tools/trace_report.py relies on this)."""
        t0 = time.perf_counter()
        out = jitted(*args)
        out = jax.tree_util.tree_map(np.asarray, out)
        t1 = time.perf_counter()
        dt = t1 - t0
        self.counters.device_seconds += dt
        if kind:
            name = "device_seconds_" + kind
            setattr(self.counters, name, getattr(self.counters, name) + dt)
        tr = self.tracer
        if tr is not None:
            tr.complete(
                f"dispatch:{kind or 'unkinded'}", t0, t1,
                cat=kind or "unkinded", track="device", items=items,
                device=True,
            )
            if items:
                tr.hist("dispatch_batch_items").record(items)
        return out

    # -- grouped (random-linear-combination) verification --------------------
    #
    # For k same-document shares, ONE check e(G1, Σr_iσ_i) == e(Σr_iPK_i, H)
    # with unpredictable RLC_BITS-wide r_i replaces k pairing checks: a forged
    # share survives only if Σ r_i·δ_i = 0 for its discrepancy δ — probability
    # 2^-RLC_BITS over r.  Cost per item falls from 2 Miller loops + FE to two
    # RLC_BITS-wide ladder lanes.  Groups that fail fall back to per-item
    # checks, preserving exact fault attribution.  (This is the classic BLS batch
    # verification; the common-coin workload — N shares per coin instance,
    # SURVEY.md §3.2 — is exactly this shape.)

    rlc_min_group = 3
    #: Random-coefficient width.  64 bits is the standard batch-verification
    #: choice (blst's mult-verify and Ethereum consensus clients use 64-bit
    #: random multipliers): a forged share survives a group check with
    #: probability 2⁻⁶⁴ per attempt, and a failing group still falls back to
    #: exact per-item checks, so soundness of fault ATTRIBUTION is never
    #: probabilistic.  Halving the width halves the dominant per-share
    #: device cost (the coefficient ladder).  HBBFT_TPU_RLC_BITS overrides
    #: (e.g. 128 for the belt-and-braces setting) and is re-read per batch
    #: so in-process A/Bs (bench fallback ladder) take effect immediately.

    @classmethod
    def _rlc_bits(cls) -> int:
        return int(os.environ.get("HBBFT_TPU_RLC_BITS", "64"))

    @staticmethod
    def _rlc_scalars(k: int) -> List[int]:
        bits = TpuBackend._rlc_bits()
        top = (1 << bits) - 1
        nbytes = (bits + 7) // 8
        return [
            1 + int.from_bytes(os.urandom(nbytes), "big") % top
            for _ in range(k)
        ]

    @staticmethod
    def _reshape_groups(dev, g: int, k: int):
        return jax.tree_util.tree_map(
            lambda c: c.reshape((g, k) + c.shape[1:]), dev
        )

    def _grouped_rlc(
        self,
        groups: List[List[int]],
        items: Sequence,
        build_group_arrays,
        jitted,
        results: List,
        direct_quad,
        kind: str = "",
    ) -> None:
        """Run RLC group checks; write per-item booleans into `results`.

        `build_group_arrays(flat_padded_groups, g, k, group_keys) -> args`
        constructs the jitted fn's inputs; padding inside each group uses
        (None point, scalar 0) lanes that contribute the identity.
        `direct_quad(item)` builds the per-item pairing quad for the exact
        fallback on contaminated leaves (passed explicitly so concurrent
        sig/dec verifications on one backend can't cross wires).

        A failing group is BISECTED: each half re-enters the next round's
        single batched RLC dispatch with fresh coefficients, until halves
        would drop below rlc_min_group — those leaves get exact per-item
        pairing checks (one batched dispatch at the end).  Attribution
        cost for c contaminated items among k is O(c·log k) group lanes +
        O(c) exact pairings instead of k pairings, so a 1-5%-garbage
        batch can no longer collapse verification to per-item cost (the
        adversarial-DoS amplifier the round-2 verdict flagged).  Fault
        attribution stays exact: False is only ever written by the
        per-item pairing check.
        """
        pending = [list(grp) for grp in groups if grp]
        tr = self.tracer
        if tr is not None:
            h = tr.hist("rlc_group_size")
            for grp in pending:
                h.record(len(grp))
        direct_leaf: List[int] = []
        while pending:
            k = _bucket(max(len(grp) for grp in pending))
            g = self._pad_bucket(len(pending))
            pad_group = [None] * k
            padded: List[List[Optional[int]]] = [
                list(grp) + [None] * (k - len(grp)) for grp in pending
            ] + [pad_group] * (g - len(pending))

            scalars = []
            for grp in padded:
                rs = self._rlc_scalars(k)
                scalars.append(
                    [r if idx is not None else 0 for r, idx in zip(rs, grp)]
                )
            rbits = np.stack(
                [curve.scalars_to_bits(row, self._rlc_bits()) for row in scalars]
            )

            self.counters.rlc_groups += len(pending)
            self.counters.device_dispatches += 1
            args = build_group_arrays(padded, g, k)
            placed = self._place(tuple(args) + (jnp.asarray(rbits),))
            f = self._dispatch_fetch(
                jitted, placed, kind=kind,
                items=sum(len(grp) for grp in pending),
            )
            next_pending: List[List[int]] = []
            for gi, grp in enumerate(pending):
                if pairing.is_one_host(f, gi):
                    for idx in grp:
                        results[idx] = True
                elif len(grp) < 2 * self.rlc_min_group:
                    direct_leaf.extend(grp)
                else:
                    mid = len(grp) // 2
                    next_pending.append(grp[:mid])
                    next_pending.append(grp[mid:])
            pending = next_pending
        if direct_leaf:
            sub = self._check_batch(
                [direct_quad(items[idx]) for idx in direct_leaf]
            )
            for idx, ok in zip(direct_leaf, sub):
                results[idx] = ok

    # -- batched verification ------------------------------------------------

    def verify_sig_shares(
        self, items: Sequence[Tuple[PublicKeyShare, bytes, SignatureShare]]
    ) -> List[bool]:
        g1 = self.group.g1()

        def direct(item):
            pk, doc, share = item
            return (g1, share.el, pk.el, self._hash_g2(doc))

        self.counters.sig_shares_verified += len(items)
        n = len(items)
        results: List[Optional[bool]] = [None] * n

        by_doc: Dict[bytes, List[int]] = {}
        for i, (pk, doc, share) in enumerate(items):
            by_doc.setdefault(doc, []).append(i)

        rlc_groups = [g for g in by_doc.values() if len(g) >= self.rlc_min_group]
        direct_idx = [
            i for g in by_doc.values() if len(g) < self.rlc_min_group for i in g
        ]

        if direct_idx:
            sub = self._check_batch([direct(items[i]) for i in direct_idx])
            for i, ok in zip(direct_idx, sub):
                results[i] = ok

        def build(padded, g, k):
            flat = [i for grp in padded for i in grp]
            # Jacobian form (Z=1) for the ladder lanes.
            S_jac = self._reshape_groups(
                curve.g2_to_device(
                    [items[i][2].el if i is not None else None for i in flat]
                ),
                g,
                k,
            )
            PK_jac = self._reshape_groups(
                curve.g1_to_device(
                    [items[i][0].el if i is not None else None for i in flat]
                ),
                g,
                k,
            )
            neg_g1 = pairing.g1_affine_to_device(
                [self.group.g1_neg(g1)] * g
            )
            hs = []
            for gi in range(g):
                grp = padded[gi]
                first = next((i for i in grp if i is not None), None)
                hs.append(
                    self._hash_g2(items[first][1]) if first is not None else None
                )
            H = pairing.g2_affine_to_device(hs)
            return (S_jac, PK_jac, neg_g1, H)

        def jitted(S_jac, PK_jac, neg_g1, H, rbits):
            return _jitted_rlc_sig()(S_jac, PK_jac, rbits, neg_g1, H)

        self._grouped_rlc(
            rlc_groups, items, build, jitted, results, direct, kind="rlc_sig"
        )
        return [bool(r) for r in results]

    def verify_signatures(
        self, items: Sequence[Tuple[Any, bytes, Signature]]
    ) -> List[bool]:
        self.counters.signatures_verified += len(items)
        g1 = self.group.g1()
        quads = [
            (g1, sig.el, pk.el, self._hash_g2(msg)) for pk, msg, sig in items
        ]
        return self._check_batch(quads)

    def verify_dec_shares(
        self, items: Sequence[Tuple[PublicKeyShare, Ciphertext, DecryptionShare]]
    ) -> List[bool]:
        def direct(item):
            pk, ct, share = item
            h = self._hash_g2(self.group.g1_to_bytes(ct.u) + ct.v)
            return (share.el, h, pk.el, ct.w)

        self.counters.dec_shares_verified += len(items)
        n = len(items)
        results: List[Optional[bool]] = [None] * n

        by_ct: Dict[bytes, List[int]] = {}
        for i, (pk, ct, share) in enumerate(items):
            by_ct.setdefault(ct.digest(), []).append(i)

        rlc_groups = [g for g in by_ct.values() if len(g) >= self.rlc_min_group]
        direct_idx = [
            i for g in by_ct.values() if len(g) < self.rlc_min_group for i in g
        ]

        if direct_idx:
            sub = self._check_batch([direct(items[i]) for i in direct_idx])
            for i, ok in zip(direct_idx, sub):
                results[i] = ok

        def build(padded, g, k):
            flat = [i for grp in padded for i in grp]
            D_jac = self._reshape_groups(
                curve.g1_to_device(
                    [items[i][2].el if i is not None else None for i in flat]
                ),
                g,
                k,
            )
            PK_jac = self._reshape_groups(
                curve.g1_to_device(
                    [items[i][0].el if i is not None else None for i in flat]
                ),
                g,
                k,
            )
            hs, ws = [], []
            for gi in range(g):
                grp = padded[gi]
                first = next((i for i in grp if i is not None), None)
                if first is None:
                    hs.append(None)
                    ws.append(None)
                else:
                    ct = items[first][1]
                    hs.append(self._hash_g2(self.group.g1_to_bytes(ct.u) + ct.v))
                    ws.append(ct.w)
            H = pairing.g2_affine_to_device(hs)
            W = pairing.g2_affine_to_device(ws)
            return (D_jac, PK_jac, H, W)

        def jitted(D_jac, PK_jac, H, W, rbits):
            return _jitted_rlc_dec()(D_jac, PK_jac, rbits, H, W)

        self._grouped_rlc(
            rlc_groups, items, build, jitted, results, direct, kind="rlc_dec"
        )
        return [bool(r) for r in results]

    def verify_ciphertexts(self, items: Sequence[Ciphertext]) -> List[bool]:
        self.counters.ciphertexts_verified += len(items)
        g1 = self.group.g1()
        quads = []
        for ct in items:
            h = self._hash_g2(self.group.g1_to_bytes(ct.u) + ct.v)
            quads.append((g1, ct.w, ct.u, h))
        return self._check_batch(quads)

    # -- combination ---------------------------------------------------------

    def _lagrange_device(
        self, pts: List[Tuple[int, Any]], to_device, from_device, jitted
    ):
        """Shared padding/bucketing for device Lagrange combines.

        Pads with infinity points and zero scalars (0·∞ contributes the
        identity) up to a power-of-two bucket so XLA compiles few shapes.
        """
        lam = lagrange_coeffs_at_zero([x for x, _ in pts])
        safe = [curve.safe_scalar(l) for l in lam]
        b = _bucket(len(pts))
        points = [el for _, el in pts] + [None] * (b - len(pts))
        bits = curve.scalars_to_bits(
            [s for s, _ in safe] + [0] * (b - len(pts))
        )
        negs = np.array([n for _, n in safe] + [False] * (b - len(pts)))
        combined = self._dispatch_fetch(
            jitted, (to_device(points), bits, negs), kind="combine",
            items=len(pts),
        )
        return from_device(combined)[0]

    def _lagrange_device_g2(self, pts: List[Tuple[int, Any]]):
        return self._lagrange_device(
            pts, curve.g2_to_device, curve.g2_from_device, _jitted_combine_g2()
        )

    def _lagrange_device_g1(self, pts: List[Tuple[int, Any]]):
        return self._lagrange_device(
            pts, curve.g1_to_device, curve.g1_from_device, _jitted_combine_g1()
        )

    def combine_signatures(
        self,
        pk_set: PublicKeySet,
        shares: Dict[int, SignatureShare],
        doc: Optional[bytes] = None,
    ) -> Signature:
        if len(shares) <= pk_set.threshold():
            raise CryptoError(
                f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
            )
        self.counters.sig_shares_combined += len(shares)
        if len(shares) < self.device_combine_threshold:
            return pk_set.combine_signatures(shares)
        pts = [(i + 1, s.el) for i, s in sorted(shares.items())]
        self.counters.device_dispatches += 1
        sig = Signature(self.group, self._lagrange_device_g2(pts))
        if doc is not None:
            # Defense in depth for the device ladder (see ops/curve.py
            # docstring): one batched device pairing check of the combined
            # signature against the master public key.  On mismatch fall
            # back to the host golden combine — correctness over speed.
            pk = pk_set.public_key()
            ok = self._check_batch([(self.group.g1(), sig.el, pk.el, self._hash_g2(doc))])
            if not ok[0]:
                return pk_set.combine_signatures(shares)
        return sig

    def combine_decryption_shares(
        self, pk_set: PublicKeySet, shares: Dict[int, DecryptionShare], ct: Ciphertext
    ) -> bytes:
        if len(shares) <= pk_set.threshold():
            raise CryptoError(
                f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
            )
        self.counters.dec_shares_combined += len(shares)
        if len(shares) < self.device_combine_threshold:
            return pk_set.combine_decryption_shares(shares, ct)
        pts = [(i + 1, s.el) for i, s in sorted(shares.items())]
        self.counters.device_dispatches += 1
        return self._plaintext_from_combined(self._lagrange_device_g1(pts), ct)

    def _plaintext_from_combined(self, combined, ct: Ciphertext) -> bytes:
        """Shared tail of threshold decryption: pad = H(s·PK), v ⊕ pad."""
        g = self.group
        pad = g.hash_bytes(g.g1_to_bytes(combined), len(ct.v))
        return bytes(a ^ b for a, b in zip(ct.v, pad))

    def combine_dec_shares_batch(
        self,
        pk_set: PublicKeySet,
        items: Sequence[Tuple[Dict[int, DecryptionShare], Ciphertext]],
    ) -> List[bytes]:
        """All combines in ONE device dispatch per share-count group.

        The array engine emits N² combines per epoch (N proposers × N
        receivers, each over the same f+1 verified share set) — per-item
        device round-trips would dominate.  Items are grouped by share
        count k, vmapped over the item axis of a (B, k) Lagrange linear
        combination, and padded to power-of-two B buckets so XLA compiles
        a handful of shapes.
        """
        out: List[Optional[bytes]] = [None] * len(items)
        by_k: Dict[int, List[int]] = {}
        for idx, (shares, _ct) in enumerate(items):
            if len(shares) <= pk_set.threshold():
                raise CryptoError(
                    f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
                )
            by_k.setdefault(len(shares), []).append(idx)
        for k, all_idxs in by_k.items():
            self.counters.dec_shares_combined += k * len(all_idxs)
            # Gate on TOTAL ladder lanes (k shares × batch items), not the
            # per-item share count: at N=16 every item has k=f+1=6 shares
            # and a per-item gate would push 256-item batches through the
            # host loop one combine at a time (measured 14.5 s/epoch).
            if k * len(all_idxs) < self.device_combine_threshold:
                for idx in all_idxs:
                    shares, ct = items[idx]
                    out[idx] = pk_set.combine_decryption_shares(shares, ct)
                continue
            step = self._lane_capped_step(k)
            for lo in range(0, len(all_idxs), step):
                self._combine_dec_chunk(
                    pk_set, items, all_idxs[lo : lo + step], k, out
                )
        return out  # type: ignore[return-value]

    def _combine_dec_chunk(self, pk_set, items, idxs, k, out) -> None:
        combined = self._lagrange_chunk(
            [items[idx][0] for idx in idxs],
            k,
            curve.g1_to_device,
            _jitted_combine_g1_batch(),
        )
        els = curve.g1_from_device(_squeeze_point(combined))
        for idx, el in zip(idxs, els[: len(idxs)]):
            out[idx] = self._plaintext_from_combined(el, items[idx][1])

    def _ladder_batch(self, scalars, points, host_fn, chunk_self, to_device,
                      from_device, jitted, kind=""):
        """Shared body of the batched independent-ladder dispatches
        (decrypt-share generation in G1, coin-share signing in G2):
        threshold gate → lane-capped chunk recursion → bucket pad →
        one device dispatch → unwrap.

        ``host_fn(i)`` is the per-item host golden below the threshold;
        ``chunk_self(sub_range)`` recurses on a lane-capped slice."""
        n = len(scalars)
        if n < self.device_combine_threshold:
            return [host_fn(i) for i in range(n)]
        if n > self.device_lane_cap:  # lane-capped chunks (HBM bound)
            out = []
            for lo in range(0, n, self.device_lane_cap):
                out.extend(chunk_self(slice(lo, lo + self.device_lane_cap)))
            return out
        b = self._pad_bucket(n)
        safe = [curve.safe_scalar(s) for s in scalars]
        bits = curve.scalars_to_bits([s for s, _ in safe])
        negs = np.array([neg for _, neg in safe])
        pts = list(points)
        if b > n:
            bits = np.concatenate([bits, np.repeat(bits[:1], b - n, axis=0)])
            negs = np.concatenate([negs, np.repeat(negs[:1], b - n)])
            pts = pts + [pts[0]] * (b - n)
        P = to_device(pts)
        self.counters.device_dispatches += 1
        out = self._dispatch_fetch(
            jitted, self._place((P, jnp.asarray(bits), jnp.asarray(negs))),
            kind=kind, items=n,
        )
        # from_device's per-lane host affine conversion runs on fetched
        # numpy arrays — host work, deliberately NOT billed as device
        return from_device(out)[:n]

    def sign_shares_batch(
        self, items: Sequence[Tuple[Any, bytes]]
    ) -> List[SignatureShare]:
        """All coin-share generations (x_i·H2(doc)) in one batched G2
        ladder dispatch — the sign side of BASELINE config 2 (N signs per
        coin instance, N instances per epoch at the macro shapes).

        H2(doc) has order r by construction (hash_to_g2 clears the
        cofactor), satisfying the device ladder's precondition."""
        els = self._ladder_batch(
            [sk.x for sk, _ in items],
            [self._hash_g2(doc) for _, doc in items],
            lambda i: items[i][0].sign_share(items[i][1]),
            lambda sub: self.sign_shares_batch(items[sub]),
            curve.g2_to_device,
            curve.g2_from_device,
            _jitted_g2_mul_batch(),
            kind="sign",
        )
        return [
            el if isinstance(el, SignatureShare) else SignatureShare(self.group, el)
            for el in els
        ]

    def combine_sig_shares_batch(
        self,
        pk_set: PublicKeySet,
        items: Sequence[Tuple[Dict[int, SignatureShare], Optional[bytes]]],
    ) -> List[Signature]:
        """All signature combines in ONE device dispatch per share-count
        group — the combine side of BASELINE config 2 (every receiver
        combines f+1 verified coin shares per instance).  Mirrors
        combine_dec_shares_batch's grouping/lane-capping; items whose doc
        is not None get a batched combined-signature re-verify against the
        master public key, with host-golden fallback on mismatch (same
        defense-in-depth contract as combine_signatures)."""
        out: List[Optional[Signature]] = [None] * len(items)
        by_k: Dict[int, List[int]] = {}
        for idx, (shares, _doc) in enumerate(items):
            if len(shares) <= pk_set.threshold():
                raise CryptoError(
                    f"need {pk_set.threshold() + 1} shares, got {len(shares)}"
                )
            by_k.setdefault(len(shares), []).append(idx)
        device_idxs: List[int] = []
        for k, all_idxs in by_k.items():
            self.counters.sig_shares_combined += k * len(all_idxs)
            if k * len(all_idxs) < self.device_combine_threshold:
                for idx in all_idxs:
                    shares, doc = items[idx]
                    out[idx] = pk_set.combine_signatures(shares)
                continue
            device_idxs.extend(all_idxs)
            step = self._lane_capped_step(k)
            for lo in range(0, len(all_idxs), step):
                self._combine_sig_chunk(
                    pk_set, items, all_idxs[lo : lo + step], k, out
                )
        # Batched defense-in-depth for DEVICE-combined items only (the
        # host path IS the golden combine — re-verifying it would just
        # recompute itself on mismatch): one pairing per doc-carrying item.
        check_idx = [i for i in device_idxs if items[i][1] is not None]
        if check_idx:
            g1 = self.group.g1()
            pk = pk_set.public_key()
            quads = [
                (g1, out[i].el, pk.el, self._hash_g2(items[i][1]))
                for i in check_idx
            ]
            ok = self._check_batch(quads)
            for i, good in zip(check_idx, ok):
                if not good:
                    out[i] = pk_set.combine_signatures(items[i][0])
        return out  # type: ignore[return-value]

    def _lane_capped_step(self, k: int) -> int:
        """Items per combine chunk: lane-capped (one oversized graph OOMs
        HBM — see device_lane_cap), rounded down to a power of two so
        _pad_bucket's round-up can't overshoot the cap or waste lanes on
        padding."""
        step = max(1, self.device_lane_cap // k)
        if step & (step - 1):
            step = 1 << (step.bit_length() - 1)
        return step

    def _lagrange_chunk(self, share_dicts, k, to_device, jitted):
        """Shared chunk body for the batched Lagrange combines: (B, k)
        point tree + per-item coefficient bit/neg rows, padded with copies
        of the first item (discarded) to a power-of-two item bucket."""
        b = self._pad_bucket(len(share_dicts))
        flat_pts: List[Any] = []
        bits_rows = []
        negs_rows = []
        for shares in share_dicts:
            srt = sorted(shares.items())
            lam = lagrange_coeffs_at_zero([i + 1 for i, _ in srt])
            safe = [curve.safe_scalar(l) for l in lam]
            flat_pts.extend(s.el for _, s in srt)
            bits_rows.append(curve.scalars_to_bits([s for s, _ in safe]))
            negs_rows.append([n for _, n in safe])
        pad = b - len(share_dicts)
        flat_pts.extend(flat_pts[:k] * pad)
        bits_rows.extend([bits_rows[0]] * pad)
        negs_rows.extend([negs_rows[0]] * pad)
        P = to_device(flat_pts)
        P = jax.tree_util.tree_map(
            lambda c: jnp.reshape(c, (b, k) + c.shape[1:]), P
        )
        bits = jnp.asarray(np.stack(bits_rows))
        negs = jnp.asarray(np.array(negs_rows))
        self.counters.device_dispatches += 1
        return self._dispatch_fetch(
            jitted, self._place((P, bits, negs)), kind="combine",
            items=len(share_dicts),
        )

    def _combine_sig_chunk(self, pk_set, items, idxs, k, out) -> None:
        combined = self._lagrange_chunk(
            [items[idx][0] for idx in idxs],
            k,
            curve.g2_to_device,
            _jitted_combine_g2_batch(),
        )
        els = curve.g2_from_device(_squeeze_point(combined))
        for idx, el in zip(idxs, els[: len(idxs)]):
            out[idx] = Signature(self.group, el)

    def decrypt_shares_batch(
        self, items: Sequence[Tuple[Any, Ciphertext]]
    ) -> List[DecryptionShare]:
        """All N² decrypt-share generations (x_i·U_p) in one batched G1
        ladder dispatch — the whole-network simulation's round-7 workload
        (host golden: ~9 ms per scalar mult; measured 4.4 s/epoch at N=16
        before batching).

        Precondition: every ct.u has order r.  The device ladder's
        unequal-add safety argument (ops/curve.py) holds only for order-r
        points; this is guaranteed because encrypt() constructs u = rG1
        and network-deserialized points pass the subgroup check in
        bls381.g1_from_bytes (g1_in_subgroup)."""
        els = self._ladder_batch(
            [sk.x for sk, _ in items],
            [ct.u for _, ct in items],
            lambda i: items[i][0].decrypt_share_unchecked(items[i][1]),
            lambda sub: self.decrypt_shares_batch(items[sub]),
            curve.g1_to_device,
            curve.g1_from_device,
            _jitted_g1_mul_batch(),
            kind="decrypt",
        )
        return [
            el if isinstance(el, DecryptionShare) else DecryptionShare(self.group, el)
            for el in els
        ]

    def g1_mul_batch(
        self, scalars: Sequence[int], points: Sequence[Any], kind: str = "dkg"
    ) -> List[Any]:
        """Batched independent G1 ladders s_i·P_i for the batched DKG and
        batched threshold encryption: commitment coefficient muls,
        ciphertext U/shared components, row/value decrypt ladders.
        ``kind`` picks the device-time attribution bucket.

        Precondition (as for decrypt_shares_batch): points have order r —
        the DKG feeds generator multiples and honestly-encrypted U values.
        """
        return self._ladder_batch(
            list(scalars),
            list(points),
            lambda i: self.group.g1_mul(scalars[i], points[i]),
            lambda sub: self.g1_mul_batch(scalars[sub], list(points)[sub], kind),
            curve.g1_to_device,
            curve.g1_from_device,
            _jitted_g1_mul_batch(),
            kind=kind,
        )

    def g2_mul_batch(
        self, scalars: Sequence[int], points: Sequence[Any], kind: str = "dkg"
    ) -> List[Any]:
        """Batched independent G2 ladders (ciphertext W = s·H2(U‖V))."""
        return self._ladder_batch(
            list(scalars),
            list(points),
            lambda i: self.group.g2_mul(scalars[i], points[i]),
            lambda sub: self.g2_mul_batch(scalars[sub], list(points)[sub], kind),
            curve.g2_to_device,
            curve.g2_from_device,
            _jitted_g2_mul_batch(),
            kind=kind,
        )

