"""Batched G1/G2 elliptic-curve arithmetic in Jacobian coordinates.

One generic implementation serves both groups: G1 over Fq and G2 over Fq2,
parameterized by a tiny field-ops namespace (the same move the golden
reference makes with its `_Fld` vtable — crypto/bls381.py).

Design constraints from the TPU mapping (SURVEY.md §7):

* **No in-graph zero tests.**  Infinity is an explicit boolean lane carried
  next to (X, Y, Z); all formulas are total and results are `select`ed.
* **Fixed control flow.**  Scalar multiplication is a 254-iteration
  MSB-first double-and-add-always ladder under `lax.scan` — one compiled
  graph for every scalar, batch-friendly, constant-time by construction.
* **Unequal-add only.**  The Jacobian add assumes P ≠ ±Q for finite
  operands.  Inside the ladder acc = 2m·P meets ±P only when 2m ≡ ±1
  (mod r), which is impossible for scalars < 2^254 (see `safe_scalar`) —
  the degenerate case is structurally excluded, not probabilistically.
  For share combination the added points are distinct verified shares whose
  discrete logs were fixed before the (public) Lagrange coefficients were
  known, so an accidental ±collision has cryptographically negligible
  probability; signature combines are additionally re-verified against the
  master public key by the backend (defense in depth with CPU fallback).

Reference analogue: group ops inside `threshold_crypto`'s `pairing` crate
(SURVEY.md §2.2) — serial Rust there, batched limb vectors here.
"""

from __future__ import annotations

import os
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.field import R
from hbbft_tpu.ops import fq, tower

SCALAR_BITS = 254  # scalars are screened to < 2^254 (see safe_scalar)


# ---------------------------------------------------------------------------
# Field-ops namespaces
# ---------------------------------------------------------------------------


class _F1:
    """Fq ops (G1 coordinate field)."""

    add = staticmethod(fq.add)
    sub = staticmethod(fq.sub)
    neg = staticmethod(fq.neg)
    mul = staticmethod(fq.mul)
    sqr = staticmethod(fq.sqr)
    mul_many = staticmethod(fq.mul_n)
    select = staticmethod(fq.select)

    @staticmethod
    def zeros_like(x):
        return jnp.zeros_like(jnp.asarray(x))

    @staticmethod
    def one_like(x):
        x = jnp.asarray(x)
        return jnp.broadcast_to(jnp.asarray(fq.ONE), x.shape)


class _F2:
    """Fq2 ops (G2 coordinate field)."""

    add = staticmethod(tower.fq2_add)
    sub = staticmethod(tower.fq2_sub)
    neg = staticmethod(tower.fq2_neg)
    mul = staticmethod(tower.fq2_mul)
    sqr = staticmethod(tower.fq2_sqr)
    mul_many = staticmethod(tower.fq2_mul_many)
    select = staticmethod(tower.fq2_select)

    @staticmethod
    def zeros_like(x):
        return tuple(jnp.zeros_like(jnp.asarray(c)) for c in x)

    @staticmethod
    def one_like(x):
        return tuple(
            jnp.broadcast_to(jnp.asarray(c), jnp.asarray(x[0]).shape)
            for c in tower.FQ2_ONE
        )


# ---------------------------------------------------------------------------
# Jacobian point ops.  A point is (X, Y, Z, inf) with inf a bool array over
# the batch shape.  (X : Y : Z) is valid only where ~inf.
# ---------------------------------------------------------------------------


def jac_double(F, P):
    # Staged so every stage's independent products share one stacked multiply
    # (compile-time: 3 dots instead of 7 — see fq.mul_n).
    X, Y, Z, inf = P
    A, B, YZ = F.mul_many([(X, X), (Y, Y), (Y, Z)])
    E = F.add(F.add(A, A), A)  # 3A
    C, t, Fv = F.mul_many([(B, B), (F.add(X, B), F.add(X, B)), (E, E)])
    D = F.add(F.sub(F.sub(t, A), C), F.sub(F.sub(t, A), C))  # 2((X+B)²−A−C)
    X3 = F.sub(Fv, F.add(D, D))
    C4 = F.add(F.add(C, C), F.add(C, C))
    C8 = F.add(C4, C4)
    (EDX3,) = F.mul_many([(E, F.sub(D, X3))])
    Y3 = F.sub(EDX3, C8)
    Z3 = F.add(YZ, YZ)
    return (X3, Y3, Z3, inf)


def jac_add(F, P, Qp):
    """Unequal add (P ≠ ±Q where both finite); infinity handled by select."""
    X1, Y1, Z1, inf1 = P
    X2, Y2, Z2, inf2 = Qp
    Z1Z1, Z2Z2, Y1Z2, Y2Z1, Z1Z2 = F.mul_many(
        [(Z1, Z1), (Z2, Z2), (Y1, Z2), (Y2, Z1), (Z1, Z2)]
    )
    U1, U2, S1, S2 = F.mul_many(
        [(X1, Z2Z2), (X2, Z1Z1), (Y1Z2, Z2Z2), (Y2Z1, Z1Z1)]
    )
    H = F.sub(U2, U1)
    Rr = F.sub(S2, S1)
    H2, Z3 = F.mul_many([(H, H), (Z1Z2, H)])
    H3, U1H2, R2 = F.mul_many([(H, H2), (U1, H2), (Rr, Rr)])
    X3 = F.sub(F.sub(R2, H3), F.add(U1H2, U1H2))
    RY, S1H3 = F.mul_many([(Rr, F.sub(U1H2, X3)), (S1, H3)])
    Y3 = F.sub(RY, S1H3)

    # inf1 → Q ; inf2 → P ; both → inf
    X3 = F.select(inf1, X2, F.select(inf2, X1, X3))
    Y3 = F.select(inf1, Y2, F.select(inf2, Y1, Y3))
    Z3 = F.select(inf1, Z2, F.select(inf2, Z1, Z3))
    return (X3, Y3, Z3, inf1 & inf2)


def jac_neg(F, P):
    X, Y, Z, inf = P
    return (X, F.neg(Y), Z, inf)


def infinity_like(F, P):
    X, Y, Z, inf = P
    return (
        F.zeros_like(X),
        F.one_like(Y),
        F.zeros_like(Z),
        jnp.ones_like(inf),
    )


def jac_select(F, cond, P, Qp):
    return (
        F.select(cond, P[0], Qp[0]),
        F.select(cond, P[1], Qp[1]),
        F.select(cond, P[2], Qp[2]),
        jnp.where(cond, P[3], Qp[3]),
    )


def scalar_mul(F, bits: jnp.ndarray, P):
    """MSB-first ladder: bits shape (..., SCALAR_BITS) over batch shape.

    Scalars must be pre-screened by `safe_scalar` (< 2^254, no ±1 prefix).

    (The round-2 fused whole-ladder Pallas kernel was deleted after its
    on-chip A/B loss — PERF.md "Round-2 sixth pass": this scan form won
    every RLC metric, g2_sign 7,001/s vs the fused path trailing.)
    """
    if jnp.shape(bits)[-1] % 2 == 0 and not os.environ.get(
        "HBBFT_TPU_LADDER_BINARY"
    ):
        return _scalar_mul_w2(F, bits, P)

    acc = infinity_like(F, P)

    def step(acc, bit):
        acc = jac_double(F, acc)
        cand = jac_add(F, acc, P)
        cond = bit.astype(bool)
        return jac_select(F, cond, cand, acc), None

    # scan over the bit axis: move it to the front.
    xs = jnp.moveaxis(bits, -1, 0)
    acc, _ = jax.lax.scan(step, acc, xs)
    return acc


def _scalar_mul_w2(F, bits: jnp.ndarray, P):
    """2-bit windowed MSB-first ladder: acc ← 4·acc + w·P per window,
    w = 2·b_hi + b_lo ∈ {0..3} selected from precomputed {P, 2P, 3P}.

    Halves the sequential scan length and replaces 2 conditional adds
    with 1 per 2 bits: ~25% fewer point-ops than the binary ladder AND
    half the per-step scan overhead (the dominant cost at RLC widths).

    Unequal-add safety (same style as safe_scalar's argument): before a
    window the accumulator is 4m·P with prefix m < 2^252 (a safe_scalar
    input has < 2^254 bits, so the prefix before the last window is at
    most 2^252−1).  A degenerate add needs 4m ≡ ±w (mod r) for the
    selected w ∈ {1,2,3}: 4m = w is impossible (4 ∤ w, and m = 0 is the
    explicit-infinity lane jac_add handles), and 4m = r−w needs
    m ≥ (r−3)/4 > 2^252.8 — out of range.  The w = 0 lane executes a
    dummy add of P whose (possibly degenerate) result is discarded by
    the select; degenerate lanes are finite residues, never NaN/Inf.
    Precompute: 3P = 2P + P is safe since 2 ≢ ±1 (mod r).
    """
    P2 = jac_double(F, P)
    P3 = jac_add(F, P2, P)
    acc = infinity_like(F, P)

    def step(acc, bw):
        hi, lo = bw
        acc = jac_double(F, jac_double(F, acc))
        T = jac_select(
            F,
            hi.astype(bool),
            jac_select(F, lo.astype(bool), P3, P2),
            P,
        )
        cand = jac_add(F, acc, T)
        nz = (hi | lo).astype(bool)
        return jac_select(F, nz, cand, acc), None

    hi = jnp.moveaxis(bits[..., 0::2], -1, 0)
    lo = jnp.moveaxis(bits[..., 1::2], -1, 0)
    acc, _ = jax.lax.scan(step, acc, (hi, lo))
    return acc


# ---------------------------------------------------------------------------
# Host-side scalar preparation
# ---------------------------------------------------------------------------


def safe_scalar(s: int) -> Tuple[int, bool]:
    """Return (s', negate) with s ≡ ±s' (mod r) and s' < 2^254.

    Why that bound makes the ladder safe: a selected add step computes
    acc + P with acc = 2m·P, where the pre-step prefix m has ≤ 253 bits.
    The unequal-add degenerate case needs 2m ≡ ±1 (mod r); but
    2m < 2^254 < r − 1, so 2m can be neither 1 (it's even and > 0 when it
    matters) nor r − 1.  Since r > 2^254.8, at least one of s, r − s is
    always < 2^254.
    """
    s %= R
    if not (s >> SCALAR_BITS):
        return (s, False)
    return (R - s, True)


def scalars_to_bits(scalars: Sequence[int], width: int = SCALAR_BITS) -> np.ndarray:
    """(B, width) MSB-first bit matrix (host; vectorized via unpackbits).

    A narrower width (e.g. 64 for random-linear-combination coefficients)
    shortens the device ladder proportionally; any scalar < 2^width < 2^254
    is automatically ladder-safe (see safe_scalar).
    """
    if not scalars:
        return np.zeros((0, width), dtype=np.int32)
    nbytes = (width + 7) // 8
    rows = []
    for s in scalars:
        if s >> width:
            raise ValueError("scalar too large for bit width")
        rows.append(int(s).to_bytes(nbytes, "big"))
    buf = np.frombuffer(b"".join(rows), dtype=np.uint8).reshape(
        len(scalars), nbytes
    )
    bits = np.unpackbits(buf, axis=1)[:, 8 * nbytes - width :]
    return bits.astype(np.int32)


# ---------------------------------------------------------------------------
# Host <-> device point conversion
# ---------------------------------------------------------------------------


def g1_to_device(points: Sequence[Optional[Tuple[int, int]]], cache=None):
    """Affine G1 points (golden-ref (x, y) ints or None) → batched Jacobian.

    ``cache`` (an ops/staging.StagingCache) serves repeated coordinate
    values from the cross-call limb-row cache instead of re-running the
    bigint conversion per dispatch."""
    conv = cache.rows if cache is not None else fq.from_ints
    xs = conv([(p[0] if p else 0) for p in points])
    ys = conv([(p[1] if p else 1) for p in points])
    inf = np.array([p is None for p in points])
    zs = np.where(
        inf[:, None], np.asarray(fq.ZERO), np.asarray(fq.ONE)
    ).astype(np.asarray(fq.ONE).dtype)
    return (jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs), jnp.asarray(inf))


def g2_to_device(points, cache=None):
    """Affine G2 points (((x0,x1),(y0,y1)) or None) → batched Jacobian."""
    conv = cache.rows if cache is not None else fq.from_ints
    X = (
        conv([(p[0][0] if p else 0) for p in points]),
        conv([(p[0][1] if p else 0) for p in points]),
    )
    Y = (
        conv([(p[1][0] if p else 1) for p in points]),
        conv([(p[1][1] if p else 0) for p in points]),
    )
    Z = (
        conv([(1 if p is not None else 0) for p in points]),
        conv([0 for _ in points]),
    )
    inf = np.array([p is None for p in points])
    return (
        tuple(jnp.asarray(c) for c in X),
        tuple(jnp.asarray(c) for c in Y),
        tuple(jnp.asarray(c) for c in Z),
        jnp.asarray(inf),
    )


def _batch_modinv(vals: List[int], q: int) -> List[int]:
    """Montgomery batch inversion: one pow(·, -1, q) + 3 bigint muls per
    element instead of one pow per element.  Zero entries pass through as
    zero (callers treat them as infinity)."""
    prefix: List[int] = []
    acc = 1
    for v in vals:
        prefix.append(acc)
        if v:
            acc = acc * v % q
    inv = pow(acc, -1, q)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        if vals[i]:
            out[i] = prefix[i] * inv % q
            inv = inv * vals[i] % q
    return out


def g1_from_device(P) -> List[Optional[Tuple[int, int]]]:
    """Batched Jacobian G1 → affine int tuples (host; exact).

    Round-5 vectorization: ONE batched residue readback per coordinate
    plane (fq.to_ints) and ONE batch inversion for every lane's Z — the
    per-lane to_int/pow loop was the dominant host cost of macro runs
    (PERF.md round-5 north-star attribution)."""
    X, Y, Z, inf = P
    X, Y, Z = np.asarray(X), np.asarray(Y), np.asarray(Z)
    inf = np.asarray(inf)
    from hbbft_tpu.crypto.field import Q

    xs = fq.to_ints(X)
    ys = fq.to_ints(Y)
    zs = fq.to_ints(Z)
    zis = _batch_modinv(zs, Q)
    out: List[Optional[Tuple[int, int]]] = []
    for i in range(X.shape[0]):
        if inf[i] or zs[i] == 0:
            out.append(None)
            continue
        zi = zis[i]
        zi2 = zi * zi % Q
        out.append((xs[i] * zi2 % Q, ys[i] * zi2 * zi % Q))
    return out


def g2_from_device(P):
    """Batched Jacobian G2 → affine ((x0,x1),(y0,y1)) tuples (host; exact).

    Same vectorization as g1_from_device; the Fq2 inversion uses the
    conjugate/norm identity so the batch inversion runs over Fq norms."""
    from hbbft_tpu.crypto.field import Q

    X, Y, Z, inf = P
    inf = np.asarray(inf)
    n = np.asarray(X[0]).shape[0]
    coords = {}
    for name, pair in (("x", X), ("y", Y), ("z", Z)):
        coords[name + "0"] = fq.to_ints(np.asarray(pair[0]))
        coords[name + "1"] = fq.to_ints(np.asarray(pair[1]))
    # 1/(z0 + z1·u) = (z0 - z1·u) / (z0² + z1²): batch-invert the norms
    norms = [
        (coords["z0"][i] * coords["z0"][i] + coords["z1"][i] * coords["z1"][i]) % Q
        for i in range(n)
    ]
    ninvs = _batch_modinv(norms, Q)
    out = []
    for i in range(n):
        z0, z1 = coords["z0"][i], coords["z1"][i]
        if inf[i] or (z0 == 0 and z1 == 0):
            out.append(None)
            continue
        ni = ninvs[i]
        zi = (z0 * ni % Q, (-z1 * ni) % Q)
        zi2 = ((zi[0] * zi[0] - zi[1] * zi[1]) % Q, 2 * zi[0] * zi[1] % Q)
        zi3 = (
            (zi2[0] * zi[0] - zi2[1] * zi[1]) % Q,
            (zi2[0] * zi[1] + zi2[1] * zi[0]) % Q,
        )
        x0, x1 = coords["x0"][i], coords["x1"][i]
        y0, y1 = coords["y0"][i], coords["y1"][i]
        out.append(
            (
                (
                    (x0 * zi2[0] - x1 * zi2[1]) % Q,
                    (x0 * zi2[1] + x1 * zi2[0]) % Q,
                ),
                (
                    (y0 * zi3[0] - y1 * zi3[1]) % Q,
                    (y0 * zi3[1] + y1 * zi3[0]) % Q,
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# Batched scalar-mul + linear combination (the share-combine kernel)
# ---------------------------------------------------------------------------


def g1_scalar_mul_batch(points, bits):
    """points: batched Jacobian G1 (B, ...), bits (B, 254) → batched m·P."""
    return scalar_mul(_F1, jnp.asarray(bits), points)


def g2_scalar_mul_batch(points, bits):
    return scalar_mul(_F2, jnp.asarray(bits), points)


def _tree_sum(F, P, axis_len: int):
    """Reduce a batch of points (leading axis) to their sum by halving."""
    n = axis_len
    while n > 1:
        half = n // 2
        a = tuple(
            jax.tree_util.tree_map(lambda c: c[:half], P[k]) for k in range(3)
        ) + (P[3][:half],)
        b = tuple(
            jax.tree_util.tree_map(lambda c: c[half : 2 * half], P[k])
            for k in range(3)
        ) + (P[3][half : 2 * half],)
        summed = jac_add(F, a, b)
        if n % 2:
            tail = tuple(
                jax.tree_util.tree_map(lambda c: c[-1:], P[k]) for k in range(3)
            ) + (P[3][-1:],)
            summed = (
                tuple(
                    jax.tree_util.tree_map(
                        lambda s, t: jnp.concatenate([s, t], axis=0), summed[k], tail[k]
                    )
                    for k in range(3)
                )
                + (jnp.concatenate([summed[3], tail[3]], axis=0),)
            )
            n = half + 1
        else:
            n = half
        P = summed
    return P


def jac_to_affine_g1(P):
    """Batched Jacobian → affine (x, y, inf) — one Fermat inverse total.

    Infinity lanes get garbage coordinates masked to (0, 1) with inf=True;
    the Miller loop neutralizes them by flag, never by value.
    """
    X, Y, Z, inf = P
    # Avoid 0-division garbage polluting the batch product: substitute 1.
    Zsafe = fq.select(inf, _F1.one_like(Z), Z)
    zinv = fq.batch_inv(Zsafe)
    zinv2, zinv3 = fq.mul_n([(zinv, zinv), (fq.mul(zinv, zinv), zinv)])
    x, y = fq.mul_n([(X, zinv2), (Y, zinv3)])
    x = fq.select(inf, _F1.zeros_like(x), x)
    y = fq.select(inf, _F1.one_like(y), y)
    return (x, y, inf)


def jac_to_affine_g2(P):
    X, Y, Z, inf = P
    Zsafe = tower.fq2_select(inf, _F2.one_like(Z), Z)
    zinv = tower.batch_inv_fq2(Zsafe)
    (zinv2,) = tower.fq2_mul_many([(zinv, zinv)])
    (zinv3,) = tower.fq2_mul_many([(zinv2, zinv)])
    x, y = tower.fq2_mul_many([(X, zinv2), (Y, zinv3)])
    x = tower.fq2_select(inf, _F2.zeros_like(x), x)
    y = tower.fq2_select(inf, _F2.one_like(y), y)
    return (x, y, inf)


def g1_scalar_mul_signed(points, bits, negs):
    """Batched ±(bits_i · P_i) ladders: the shared signed-ladder prologue
    (`negs` is the (B,) bool safe_scalar negation mask)."""
    prods = g1_scalar_mul_batch(points, bits)
    return jac_select(_F1, jnp.asarray(negs), jac_neg(_F1, prods), prods)


def g2_scalar_mul_signed(points, bits, negs):
    prods = g2_scalar_mul_batch(points, bits)
    return jac_select(_F2, jnp.asarray(negs), jac_neg(_F2, prods), prods)


def linear_combine_g1(points, bits, negs):
    """Σ ±(bits_i · P_i) over the leading axis → single Jacobian point.

    `negs` is a (B,) bool array applying the safe_scalar negation.
    """
    prods = g1_scalar_mul_signed(points, bits, negs)
    return _tree_sum(_F1, prods, jnp.shape(bits)[0])


def linear_combine_g2(points, bits, negs):
    prods = g2_scalar_mul_signed(points, bits, negs)
    return _tree_sum(_F2, prods, jnp.shape(bits)[0])
