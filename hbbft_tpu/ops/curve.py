"""Batched G1/G2 elliptic-curve arithmetic in Jacobian coordinates.

One generic implementation serves both groups: G1 over Fq and G2 over Fq2,
parameterized by a tiny field-ops namespace (the same move the golden
reference makes with its `_Fld` vtable — crypto/bls381.py).

Design constraints from the TPU mapping (SURVEY.md §7):

* **No in-graph zero tests.**  Infinity is an explicit boolean lane carried
  next to (X, Y, Z); all formulas are total and results are `select`ed.
* **Fixed control flow.**  Scalar multiplication is a 254-iteration
  MSB-first double-and-add-always ladder under `lax.scan` — one compiled
  graph for every scalar, batch-friendly, constant-time by construction.
* **Unequal-add on the classic ladders, complete add on the table paths.**
  The plain Jacobian add assumes P ≠ ±Q for finite operands; the binary
  and w2 ladders carry structural proofs that the degenerate case cannot
  occur (see `safe_scalar` / `_scalar_mul_w2`).  The GLV/GLS joint-table
  ladders (`_scalar_mul_joint`) CANNOT carry such a proof — the short
  lattice vectors put decomposed coordinates inside the prefix ranges, so
  adversarial scalars reach acc = ±T mid-ladder — and therefore use
  `jac_add(..., complete=True)`: a select-routed complete addition whose
  doubling/infinity routes are driven by exact in-graph zero tests
  (fq.is_zero).  For share combination the added points are distinct
  verified shares whose discrete logs were fixed before the (public)
  Lagrange coefficients were known, so an accidental ±collision has
  cryptographically negligible probability; signature combines are
  additionally re-verified against the master public key by the backend
  (defense in depth with CPU fallback).
* **GLV/GLS endomorphism decomposition** (default; ``HBBFT_TPU_NO_GLV=1``
  reverts).  G1: k = k1 + λ·k2 with |k1|,|k2| ≤ 2^127 via exact-fraction
  Babai rounding on the basis (−λ, 1), (1, λ+1) (det −r; λ² + λ + 1 = r
  exactly for BLS12-381), φ(x, y) = (β·x, y) one lane-constant multiply.
  G2: 4-way GLS k = Σ k_j·u^j with |k_j| < 2^63 over the ψ (twist
  Frobenius) eigenvalue u, ψ applied as conjugate + two lane-constant
  Fq2 multiplies.  Both run a 16-entry per-lane joint table ({Σ w_j·P_j}
  over 2-bit/1-bit windows of every half/quarter) through a 64-step
  gather-based Shamir ladder: 2368 ladder field-muls per G1 ladder vs
  3810 on the w2 path (~1.6×), 1920 Fq2-muls per G2 ladder vs 3810 (~2×).
  Outputs are bit-identical to the w2/binary ladders either way.

Reference analogue: group ops inside `threshold_crypto`'s `pairing` crate
(SURVEY.md §2.2) — serial Rust there, batched limb vectors here; the
endomorphism playbook follows Gallant–Lambert–Vanstone (CRYPTO 2001) and
Galbraith–Lindell–Scott (J. Cryptology 2011) as deployed in blst.
"""

from __future__ import annotations

import os
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto import bls381 as _gold
from hbbft_tpu.crypto.field import R
from hbbft_tpu.ops import fq, tower

SCALAR_BITS = 254  # scalars are screened to < 2^254 (see safe_scalar)
GLV_HALF_BITS = 128  # |k1|,|k2| ≤ 2^127 (Babai bound, property-tested)
GLS_QUARTER_BITS = 64  # |k_j| < 2^63 for the 4-way G2 split


# ---------------------------------------------------------------------------
# Field-ops namespaces
# ---------------------------------------------------------------------------


class _F1:
    """Fq ops (G1 coordinate field)."""

    add = staticmethod(fq.add)
    sub = staticmethod(fq.sub)
    neg = staticmethod(fq.neg)
    mul = staticmethod(fq.mul)
    sqr = staticmethod(fq.sqr)
    mul_many = staticmethod(fq.mul_n)
    select = staticmethod(fq.select)
    is_zero = staticmethod(fq.is_zero)

    @staticmethod
    def is_zero_pair(a, b):
        """Both zero tests as ONE stacked probe (XLA compile time scales
        with dot_general count — same motivation as fq.mul_n)."""
        z = fq.is_zero(jnp.stack([a, b]))
        return z[0], z[1]

    @staticmethod
    def endo(P):
        """φ(x, y) = (β·x, y) — multiplication by λ on the r-subgroup."""
        return _phi_g1(P)

    @staticmethod
    def zeros_like(x):
        return jnp.zeros_like(jnp.asarray(x))

    @staticmethod
    def one_like(x):
        x = jnp.asarray(x)
        return jnp.broadcast_to(jnp.asarray(fq.ONE), x.shape)


class _F2:
    """Fq2 ops (G2 coordinate field)."""

    add = staticmethod(tower.fq2_add)
    sub = staticmethod(tower.fq2_sub)
    neg = staticmethod(tower.fq2_neg)
    mul = staticmethod(tower.fq2_mul)
    sqr = staticmethod(tower.fq2_sqr)
    mul_many = staticmethod(tower.fq2_mul_many)
    select = staticmethod(tower.fq2_select)
    is_zero = staticmethod(tower.fq2_is_zero)

    @staticmethod
    def is_zero_pair(a, b):
        """All four component zero-probes as ONE stacked fq.is_zero."""
        z = fq.is_zero(jnp.stack([a[0], a[1], b[0], b[1]]))
        return z[0] & z[1], z[2] & z[3]

    @staticmethod
    def endo(P):
        """ψ = twist∘Frobenius∘untwist — multiplication by u on G2."""
        return _psi_g2(P)

    @staticmethod
    def zeros_like(x):
        return tuple(jnp.zeros_like(jnp.asarray(c)) for c in x)

    @staticmethod
    def one_like(x):
        return tuple(
            jnp.broadcast_to(jnp.asarray(c), jnp.asarray(x[0]).shape)
            for c in tower.FQ2_ONE
        )


# ---------------------------------------------------------------------------
# Jacobian point ops.  A point is (X, Y, Z, inf) with inf a bool array over
# the batch shape.  (X : Y : Z) is valid only where ~inf.
# ---------------------------------------------------------------------------


def jac_double(F, P):
    # Staged so every stage's independent products share one stacked multiply
    # (compile-time: 3 dots instead of 7 — see fq.mul_n).
    X, Y, Z, inf = P
    A, B, YZ = F.mul_many([(X, X), (Y, Y), (Y, Z)])
    E = F.add(F.add(A, A), A)  # 3A
    C, t, Fv = F.mul_many([(B, B), (F.add(X, B), F.add(X, B)), (E, E)])
    D = F.add(F.sub(F.sub(t, A), C), F.sub(F.sub(t, A), C))  # 2((X+B)²−A−C)
    X3 = F.sub(Fv, F.add(D, D))
    C4 = F.add(F.add(C, C), F.add(C, C))
    C8 = F.add(C4, C4)
    (EDX3,) = F.mul_many([(E, F.sub(D, X3))])
    Y3 = F.sub(EDX3, C8)
    Z3 = F.add(YZ, YZ)
    return (X3, Y3, Z3, inf)


def jac_add(F, P, Qp, complete=False):
    """Jacobian add; infinity handled by select.

    ``complete=False`` (default): unequal add — requires P ≠ ±Q where both
    are finite; the classic-ladder call sites carry structural proofs of
    that precondition (see `safe_scalar` / `_scalar_mul_w2`).

    ``complete=True``: select-routed COMPLETE addition, used on every
    joint-table path (table build and table-ladder accumulator adds),
    where adversarial scalars can reach the degenerate cases.  Exhaustive
    case split — with U1 = X1·Z2², U2 = X2·Z1², S1 = Y1·Z2³, S2 = Y2·Z1³
    and the exact in-graph zero tests H = U2−U1 ≡ 0, Rr = S2−S1 ≡ 0
    (fq.is_zero; sound and complete within the documented lazy-value
    domain, which every operand here satisfies as a difference of fresh
    mul outputs):

    1. inf1           → result Q (the inf selects below, either mode).
    2. inf2           → result P (ditto).
    3. finite, H ≠ 0             → P ≠ ±Q: the unequal formula is valid.
    4. finite, H = 0, Rr = 0     → U1=U2, S1=S2 ⟺ P = Q (Jacobian
       equality is exactly the cross-multiplied coordinate equality):
       route to jac_double(P), which is total (no excluded inputs; the
       y = 0 self-inverse case would need a 2-torsion point, and the
       order-r subgroup of BLS12-381 has none since r is odd).
    5. finite, H = 0, Rr ≠ 0     → x-coords equal, y-coords differ ⟺
       Q = −P: route to the canonical infinity lanes (0, 1, 0, inf=True).
       (y1 = −y2 AND y1 = y2 would again need 2-torsion — cases 4/5 are
       mutually exclusive for order-r inputs.)

    Degenerate lanes under ``complete=False`` produce finite-residue
    garbage (never NaN/Inf — the formulas are polynomial), which callers
    must discard by select; under ``complete=True`` every case returns
    the correct point."""
    if complete:
        return _jac_add_complete(F, P, Qp)
    X1, Y1, Z1, inf1 = P
    X2, Y2, Z2, inf2 = Qp
    Z1Z1, Z2Z2, Y1Z2, Y2Z1, Z1Z2 = F.mul_many(
        [(Z1, Z1), (Z2, Z2), (Y1, Z2), (Y2, Z1), (Z1, Z2)]
    )
    U1, U2, S1, S2 = F.mul_many(
        [(X1, Z2Z2), (X2, Z1Z1), (Y1Z2, Z2Z2), (Y2Z1, Z1Z1)]
    )
    H = F.sub(U2, U1)
    Rr = F.sub(S2, S1)
    H2, Z3 = F.mul_many([(H, H), (Z1Z2, H)])
    H3, U1H2, R2 = F.mul_many([(H, H2), (U1, H2), (Rr, Rr)])
    X3 = F.sub(F.sub(R2, H3), F.add(U1H2, U1H2))
    RY, S1H3 = F.mul_many([(Rr, F.sub(U1H2, X3)), (S1, H3)])
    Y3 = F.sub(RY, S1H3)

    # inf1 → Q ; inf2 → P ; both → inf
    X3 = F.select(inf1, X2, F.select(inf2, X1, X3))
    Y3 = F.select(inf1, Y2, F.select(inf2, Y1, Y3))
    Z3 = F.select(inf1, Z2, F.select(inf2, Z1, Z3))
    return (X3, Y3, Z3, inf1 & inf2)


def _jac_add_complete(F, P, Qp):
    """jac_add's ``complete=True`` body (see its docstring for the
    exhaustive case split).  The doubling route's products are
    interleaved into the unequal-add's stacked multiply levels — the two
    routes' formulas are level-parallel, so completeness costs the same
    5 mul_many dispatch sites as the plain add (XLA compile time scales
    with dot_general count; fq.mul_n note) instead of 5 + jac_double's 3.
    Field-mul totals are unchanged (23 per lane)."""
    X1, Y1, Z1, inf1 = P
    X2, Y2, Z2, inf2 = Qp
    # L1: add inputs + doubling stage 1 (A, B, YZ of jac_double on P)
    Z1Z1, Z2Z2, Y1Z2, Y2Z1, Z1Z2, dA, dB, dYZ = F.mul_many(
        [
            (Z1, Z1), (Z2, Z2), (Y1, Z2), (Y2, Z1), (Z1, Z2),
            (X1, X1), (Y1, Y1), (Y1, Z1),
        ]
    )
    dE = F.add(F.add(dA, dA), dA)  # 3A
    # L2: U/S cross terms + doubling stage 2 (C, t, F of jac_double)
    U1, U2, S1, S2, dC, dt, dFv = F.mul_many(
        [
            (X1, Z2Z2), (X2, Z1Z1), (Y1Z2, Z2Z2), (Y2Z1, Z1Z1),
            (dB, dB), (F.add(X1, dB), F.add(X1, dB)), (dE, dE),
        ]
    )
    H = F.sub(U2, U1)
    Rr = F.sub(S2, S1)
    dD = F.add(F.sub(F.sub(dt, dA), dC), F.sub(F.sub(dt, dA), dC))
    dX3 = F.sub(dFv, F.add(dD, dD))
    # L3: H²/Z3 + doubling stage 3 (E·(D−X3))
    H2, Z3, dEDX = F.mul_many([(H, H), (Z1Z2, H), (dE, F.sub(dD, dX3))])
    dC4 = F.add(F.add(dC, dC), F.add(dC, dC))
    dY3 = F.sub(dEDX, F.add(dC4, dC4))
    dZ3 = F.add(dYZ, dYZ)
    # L4/L5: the unequal-add tail
    H3, U1H2, R2 = F.mul_many([(H, H2), (U1, H2), (Rr, Rr)])
    X3 = F.sub(F.sub(R2, H3), F.add(U1H2, U1H2))
    RY, S1H3 = F.mul_many([(Rr, F.sub(U1H2, X3)), (S1, H3)])
    Y3 = F.sub(RY, S1H3)

    eq_x, eq_y = F.is_zero_pair(H, Rr)
    fin = ~inf1 & ~inf2
    use_dbl = fin & eq_x & eq_y
    to_inf = fin & eq_x & ~eq_y
    X3 = F.select(use_dbl, dX3, X3)
    Y3 = F.select(use_dbl, dY3, Y3)
    Z3 = F.select(use_dbl, dZ3, Z3)

    # inf1 → Q ; inf2 → P ; both → inf
    X3 = F.select(inf1, X2, F.select(inf2, X1, X3))
    Y3 = F.select(inf1, Y2, F.select(inf2, Y1, Y3))
    Z3 = F.select(inf1, Z2, F.select(inf2, Z1, Z3))
    # canonical infinity lanes: bounded coordinates keep later zero-test
    # operands inside their documented value domain
    X3 = F.select(to_inf, F.zeros_like(X3), X3)
    Y3 = F.select(to_inf, F.one_like(Y3), Y3)
    Z3 = F.select(to_inf, F.zeros_like(Z3), Z3)
    return (X3, Y3, Z3, (inf1 & inf2) | to_inf)


def jac_neg(F, P):
    X, Y, Z, inf = P
    return (X, F.neg(Y), Z, inf)


def infinity_like(F, P):
    X, Y, Z, inf = P
    return (
        F.zeros_like(X),
        F.one_like(Y),
        F.zeros_like(Z),
        jnp.ones_like(inf),
    )


def jac_select(F, cond, P, Qp):
    return (
        F.select(cond, P[0], Qp[0]),
        F.select(cond, P[1], Qp[1]),
        F.select(cond, P[2], Qp[2]),
        jnp.where(cond, P[3], Qp[3]),
    )


def scalar_mul(F, bits: jnp.ndarray, P):
    """MSB-first ladder: bits shape (..., SCALAR_BITS) over batch shape.

    Scalars must be pre-screened by `safe_scalar` (< 2^254, no ±1 prefix).

    (The round-2 fused whole-ladder Pallas kernel was deleted after its
    on-chip A/B loss — PERF.md "Round-2 sixth pass": this scan form won
    every RLC metric, g2_sign 7,001/s vs the fused path trailing.)
    """
    if jnp.shape(bits)[-1] % 2 == 0 and not os.environ.get(
        "HBBFT_TPU_LADDER_BINARY"
    ):
        return _scalar_mul_w2(F, bits, P)

    acc = infinity_like(F, P)

    def step(acc, bit):
        acc = jac_double(F, acc)
        cand = jac_add(F, acc, P)
        cond = bit.astype(bool)
        return jac_select(F, cond, cand, acc), None

    # scan over the bit axis: move it to the front.
    xs = jnp.moveaxis(bits, -1, 0)
    acc, _ = jax.lax.scan(step, acc, xs)
    return acc


def _scalar_mul_w2(F, bits: jnp.ndarray, P):
    """2-bit windowed MSB-first ladder: acc ← 4·acc + w·P per window,
    w = 2·b_hi + b_lo ∈ {0..3} selected from precomputed {P, 2P, 3P}.

    Halves the sequential scan length and replaces 2 conditional adds
    with 1 per 2 bits: ~25% fewer point-ops than the binary ladder AND
    half the per-step scan overhead (the dominant cost at RLC widths).

    Unequal-add safety (same style as safe_scalar's argument; COVERS THIS
    w2 LADDER ONLY — the GLV/GLS joint-table ladder gets no such proof
    and uses complete adds instead, see `_scalar_mul_joint`): before a
    window the accumulator is 4m·P with prefix m < 2^252 (a safe_scalar
    input has < 2^254 bits, so the prefix before the last window is at
    most 2^252−1).  A degenerate add needs 4m ≡ ±w (mod r) for the
    selected w ∈ {1,2,3}: 4m = w is impossible (4 ∤ w, and m = 0 is the
    explicit-infinity lane jac_add handles), and 4m = r−w needs
    m ≥ (r−3)/4 > 2^252.8 — out of range.  The w = 0 lane executes a
    dummy add of P whose (possibly degenerate) result is discarded by
    the select; degenerate lanes are finite residues, never NaN/Inf.
    Precompute: 3P = 2P + P is safe since 2 ≢ ±1 (mod r).
    """
    P2 = jac_double(F, P)
    P3 = jac_add(F, P2, P)
    acc = infinity_like(F, P)

    def step(acc, bw):
        hi, lo = bw
        acc = jac_double(F, jac_double(F, acc))
        T = jac_select(
            F,
            hi.astype(bool),
            jac_select(F, lo.astype(bool), P3, P2),
            P,
        )
        cand = jac_add(F, acc, T)
        nz = (hi | lo).astype(bool)
        return jac_select(F, nz, cand, acc), None

    hi = jnp.moveaxis(bits[..., 0::2], -1, 0)
    lo = jnp.moveaxis(bits[..., 1::2], -1, 0)
    acc, _ = jax.lax.scan(step, acc, (hi, lo))
    return acc


# ---------------------------------------------------------------------------
# GLV/GLS endomorphism ladders: device side.
#
# The endomorphism constants self-validated in crypto/bls381.py at import
# (φ(G1) == λ·G1, ψ(G2) == u·G2); if either resolution failed the GLV
# path is disabled wholesale (glv_enabled) and the w2 ladders carry on.
# ---------------------------------------------------------------------------

_BETA_ROW = (
    fq.from_int(_gold._BETA) if _gold._BETA is not None else None
)
_PSI_CX = (
    tower.fq2_from_ints(_gold._PSI_CONSTS[0])
    if _gold._PSI_CONSTS is not None
    else None
)
_PSI_CY = (
    tower.fq2_from_ints(_gold._PSI_CONSTS[1])
    if _gold._PSI_CONSTS is not None
    else None
)


def _phi_g1(P):
    """G1 endomorphism φ in Jacobian coordinates: (β·X, Y, Z).

    Affine check: x = X/Z² ↦ β·X/Z² = β·x, y unchanged — exactly
    φ(x, y) = (β·x, y).  One lane-constant field multiply."""
    X, Y, Z, inf = P
    (bX,) = fq.mul_n([(jnp.asarray(_BETA_ROW), X)])
    return (bX, Y, Z, inf)


def _psi_g2(P):
    """G2 endomorphism ψ in Jacobian coordinates:
    (c_x·X̄, c_y·Ȳ, Z̄) with σ the Fq2 conjugation (Frobenius).

    Affine check: x = X/Z² ↦ c_x·X̄/Z̄² = c_x·σ(x) (σ is a field
    automorphism), matching bls381._psi.  Conjugation is a sign flip;
    the two constant Fq2 multiplies are 6 stacked Fq muls."""
    X, Y, Z, inf = P
    cx = tuple(jnp.asarray(c) for c in _PSI_CX)
    cy = tuple(jnp.asarray(c) for c in _PSI_CY)
    Xp, Yp = tower.fq2_mul_many(
        [(cx, tower.fq2_conj(X)), (cy, tower.fq2_conj(Y))]
    )
    return (Xp, Yp, tower.fq2_conj(Z), inf)


def _stack_points(pts):
    """Stack identical-structure points along a new leading batch axis
    (the field ops are batch-agnostic, so one point-op over the stack
    replaces len(pts) separate ops — XLA compile time scales with the
    dot_general count, the fq.mul_n motivation)."""
    return jax.tree_util.tree_map(lambda *cs: jnp.stack(cs), *pts)


def _index_point(P, i: int):
    return jax.tree_util.tree_map(lambda c: c[i], P)


def _joint_table(F, parts, digit_base: int):
    """The 16-entry per-lane joint table T[idx] = Σ_j digit_j(idx)·parts[j]
    with idx = Σ_j digit_j·digit_base^j, digit_base^len(parts) == 16.

    Construction iterates digits/indices over ``range(...)`` only — a
    FIXED order (the ``glv-table-order`` lint rule pins this: a
    nondeterministic build order would compile a different gather layout
    per process and break replay/A-B bit-identity).  Per-part multiple
    chains d·parts[j] come first (one stacked doubling + one stacked
    complete add across all parts), then each further part folds into the
    running table with ONE stacked complete add covering every (d, prev)
    combination — 2–3 stacked point-ops total instead of 11+ sequential
    ones, with identical per-lane arithmetic (the stack axis is just
    batch).

    Complete adds throughout: entry collisions (w·P_i = ±w'·P_j) are
    excluded only by curve-specific eigenvalue-magnitude arguments
    (λ ≫ 3 in G1; no vanishing ±u^j subset sum in G2), not by the
    ladder-structural proof the classic paths carry — and this module's
    safety contract is that every table path is complete rather than
    argued case-by-case.
    """
    m = len(parts)
    if digit_base == 4:
        S = _stack_points(parts)
        D2 = jac_double(F, S)  # 2·parts[j], total — no degenerate case
        D3 = jac_add(F, D2, S, complete=True)  # 3·parts[j]
        chains = [
            [None, parts[j], _index_point(D2, j), _index_point(D3, j)]
            for j in range(m)
        ]
    else:
        chains = [[None, parts[j]] for j in range(m)]
    entries = [infinity_like(F, parts[0])] + chains[0][1:]
    for j in range(1, m):
        base = digit_base**j
        prev = list(entries)  # covers idx ∈ [0, base)
        for d in range(1, digit_base):
            entries.append(chains[j][d])  # idx = d·base (∞ + d·P_j)
            entries.extend([None] * (base - 1))  # filled from C below
        A = _stack_points(
            [prev[i] for d in range(1, digit_base) for i in range(1, base)]
        )
        B = _stack_points(
            [chains[j][d] for d in range(1, digit_base) for i in range(1, base)]
        )
        C = jac_add(F, A, B, complete=True)
        lane = 0
        for d in range(1, digit_base):
            for i in range(1, base):
                entries[d * base + i] = _index_point(C, lane)
                lane += 1
    return entries


def _gather_entry(F, entries, onehot, base_inf, zero_window):
    """Select per-lane table rows: one-hot (…, 16) × the stacked
    coordinate planes, contracted with ONE exact (HIGHEST-precision)
    matmul over all planes — the MXU form of a lane-varying gather
    (SURVEY.md §7: no dynamic indexing in the batched graphs).

    The infinity flag needs no gather: for an order-r base point every
    nonzero-window table entry is finite (its multiplier is a nonzero
    small combination — |s·w1 + λ·s'·w2| ≤ 3 + 3λ ≪ r in G1, a
    non-vanishing ±u^j subset sum in G2), so the selected entry is ∞
    exactly when the window is zero or the base point itself is ∞."""
    hp = jax.lax.Precision.HIGHEST
    planes = []
    for k in range(3):
        if isinstance(entries[0][k], tuple):  # Fq2 component pair
            for i in range(len(entries[0][k])):
                planes.append(jnp.stack([e[k][i] for e in entries], axis=-2))
        else:
            planes.append(jnp.stack([e[k] for e in entries], axis=-2))
    T = jnp.stack(planes, axis=-3)  # (..., planes, 16, NLIMBS)
    # match the representation dtype: an f32 one-hot against int32 limb
    # planes (the legacy HBBFT_TPU_FQ_BITS=11 arm) would promote the
    # gathered coordinates to f32 and break the scan carry's dtype;
    # integer one-hot contraction is exact in either dtype
    sel = jnp.einsum(
        "...w,...cwl->...cl", onehot.astype(T.dtype), T, precision=hp
    )
    comps = [sel[..., c, :] for c in range(len(planes))]
    if isinstance(entries[0][0], tuple):
        coords = [tuple(comps[2 * k : 2 * k + 2]) for k in range(3)]
    else:
        coords = comps
    inf = zero_window | base_inf
    return (coords[0], coords[1], coords[2], inf)


def _scalar_mul_joint(F, bits, negs, P):
    """GLV/GLS joint-table Shamir ladder.

    ``bits``: (..., m, W) MSB-first windows of the m decomposed parts
    (m=2, W=128 for G1 GLV with 2-bit windows; m=4, W=64 for G2 GLS with
    1-bit windows); ``negs``: (..., m) per-part sign flags; ``P``:
    batched Jacobian base points of order r.

    Per step: window-many doublings, then ONE complete add of the
    gathered table entry — 64 steps either way, 16-entry table either
    way.  The w=0 entry is the explicit infinity lane, so the add is
    unconditional (no discard-select): zero windows pass through via the
    inf2 route of jac_add.  Every accumulator add is complete=True — the
    decomposed prefixes are λ/u-sized, so acc = ±T collisions are
    adversarially reachable (the module docstring's safety note; the
    degenerate-case tests drive them on purpose)."""
    m = int(bits.shape[-2])
    digit_base, wbits = (4, 2) if m == 2 else (2, 1)
    parts = []
    Pj = P
    for j in range(m):
        if j:
            Pj = F.endo(Pj)
        parts.append(jac_select(F, negs[..., j], jac_neg(F, Pj), Pj))
    entries = _joint_table(F, parts, digit_base)

    if wbits == 2:
        w = 2 * bits[..., 0::2] + bits[..., 1::2]  # (..., m, W/2)
    else:
        w = bits
    nent = digit_base**m
    idx = jnp.zeros(w.shape[:-2] + w.shape[-1:], dtype=w.dtype)
    for j in range(m):
        idx = idx + w[..., j, :] * (digit_base**j)
    xs = jnp.moveaxis(idx, -1, 0)  # (steps, ...)

    acc = infinity_like(F, P)

    base_inf = P[3]

    def step(acc, ix):
        for _ in range(wbits):
            acc = jac_double(F, acc)
        onehot = (
            ix[..., None] == jnp.arange(nent, dtype=ix.dtype)
        ).astype(jnp.float32)
        T = _gather_entry(F, entries, onehot, base_inf, ix == 0)
        return jac_add(F, acc, T, complete=True), None

    acc, _ = jax.lax.scan(step, acc, xs)
    return acc


# ---------------------------------------------------------------------------
# Host-side scalar preparation
# ---------------------------------------------------------------------------


def safe_scalar(s: int) -> Tuple[int, bool]:
    """Return (s', negate) with s ≡ ±s' (mod r) and s' < 2^254.

    SCOPE OF THE PROOF BELOW: it covers exactly the two CLASSIC ladder
    variants — the binary ladder (`scalar_mul`'s scan form) and the 2-bit
    windowed ladder (`_scalar_mul_w2`) — both of which use the UNEQUAL
    Jacobian add.  It does NOT cover the GLV/GLS joint-table ladders
    (`_scalar_mul_joint`): those take decomposed scalars that never pass
    through safe_scalar, their prefix bound is λ/u-sized rather than
    2^254, and their accumulator adds are select-routed COMPLETE adds
    precisely because no analogous structural exclusion exists (see
    jac_add's exhaustive case split).

    Why the bound makes the classic ladders safe: a selected add step
    computes acc + P with acc = 2m·P, where the pre-step prefix m has
    ≤ 253 bits.  The unequal-add degenerate case needs 2m ≡ ±1 (mod r);
    but 2m < 2^254 < r − 1, so 2m can be neither 1 (it's even and > 0
    when it matters) nor r − 1.  Since r > 2^254.8, at least one of s,
    r − s is always < 2^254.
    """
    s %= R
    if not (s >> SCALAR_BITS):
        return (s, False)
    return (R - s, True)


def scalars_to_bits(scalars: Sequence[int], width: int = SCALAR_BITS) -> np.ndarray:
    """(B, width) MSB-first bit matrix (host; vectorized via unpackbits).

    A narrower width (e.g. 64 for random-linear-combination coefficients)
    shortens the device ladder proportionally; any scalar < 2^width < 2^254
    is automatically ladder-safe (see safe_scalar).
    """
    if not scalars:
        return np.zeros((0, width), dtype=np.int32)
    nbytes = (width + 7) // 8
    rows = []
    for s in scalars:
        if s >> width:
            raise ValueError("scalar too large for bit width")
        rows.append(int(s).to_bytes(nbytes, "big"))
    buf = np.frombuffer(b"".join(rows), dtype=np.uint8).reshape(
        len(scalars), nbytes
    )
    bits = np.unpackbits(buf, axis=1)[:, 8 * nbytes - width :]
    return bits.astype(np.int32)


# ---------------------------------------------------------------------------
# GLV/GLS endomorphism ladders: host-side decomposition (exact-fraction
# Babai rounding; all Python ints, vectorization-free by necessity).
# ---------------------------------------------------------------------------

_G1_LAM = _gold._G1_LAMBDA  # λ = x²−1; λ² + λ + 1 == r EXACTLY for BLS12
assert _G1_LAM * _G1_LAM + _G1_LAM + 1 == R, "GLV basis determinant is not -r"
_G2_U = _gold._U  # the signed BLS parameter u (ψ eigenvalue on G2)
assert _G2_U**4 - _G2_U**2 + 1 == R, "GLS basis relation r(u) broken"

#: GLS lattice basis (rows): integer vectors v with Σ v_j·u^j ≡ 0 (mod r).
_G2_BASIS = (
    (_G2_U, -1, 0, 0),
    (0, _G2_U, -1, 0),
    (0, 0, _G2_U, -1),
    (1, 0, -1, _G2_U),
)


def _minor3(m, i, j) -> int:
    mm = [[m[r][c] for c in range(4) if c != j] for r in range(4) if r != i]
    a, b, c = mm[0]
    d, e, f = mm[1]
    g, h, k = mm[2]
    return a * (e * k - f * h) - b * (d * k - f * g) + c * (d * h - e * g)


def _det4(m) -> int:
    return sum(
        (-1) ** j * m[0][j] * _minor3(m, 0, j) for j in range(4)
    )


_G2_DET = _det4(_G2_BASIS)
#: first adjugate row: the Babai coefficients for target (k, 0, 0, 0) are
#: c_j = k·adj[0][j] / det (row-vector convention c·B = t).
_G2_ADJ0 = tuple((-1) ** j * _minor3(_G2_BASIS, j, 0) for j in range(4))
if _G2_DET < 0:
    _G2_DET = -_G2_DET
    _G2_ADJ0 = tuple(-a for a in _G2_ADJ0)
assert _G2_DET == R, "GLS basis determinant is not ±r"


def _divround(n: int, d: int) -> int:
    """round(n/d) to nearest for d > 0 (exact-fraction Babai rounding)."""
    return (2 * n + d) // (2 * d)


def glv_enabled() -> bool:
    """GLV/GLS decomposition active?  Read per batch (not at import) so
    ``HBBFT_TPU_NO_GLV=1`` flips in-process A/Bs immediately; the legacy
    ``HBBFT_TPU_LADDER_BINARY`` knob also forces the classic path (it
    selects the binary ladder, which GLV would bypass entirely)."""
    if os.environ.get("HBBFT_TPU_NO_GLV") or os.environ.get(
        "HBBFT_TPU_LADDER_BINARY"
    ):
        return False
    return _BETA_ROW is not None and _PSI_CX is not None


def glv_decompose_g1(k: int) -> List[Tuple[int, bool]]:
    """k ≡ ±k1 ± λ·k2 (mod r) with |k1|,|k2| ≤ 2^127.

    Exact-fraction Babai on the basis (−λ, 1), (1, λ+1): the rational
    coordinates of (k, 0) are c1 = −(λ+1)k/r, c2 = k/r (det = −r), each
    rounded to the nearest integer with pure-int arithmetic.  Returns
    [(|k1|, k1<0), (|k2|, k2<0)].  The 2^127 bound is property-tested
    over ≥50k scalars (tests/test_curve_jax.py)."""
    k %= R
    b1 = _divround(-(_G1_LAM + 1) * k, R)
    b2 = _divround(k, R)
    k1 = k + b1 * _G1_LAM - b2
    k2 = -b1 - b2 * (_G1_LAM + 1)
    return [(abs(k1), k1 < 0), (abs(k2), k2 < 0)]


def gls_decompose_g2(k: int) -> List[Tuple[int, bool]]:
    """k ≡ Σ_j ±k_j·u^j (mod r) with |k_j| < 2^63 (4-way GLS split).

    Exact-fraction Babai on `_G2_BASIS` via the precomputed adjugate
    row / determinant (det = r)."""
    k %= R
    bs = [_divround(k * a, _G2_DET) for a in _G2_ADJ0]
    ks = [
        (k if j == 0 else 0)
        - sum(bs[i] * _G2_BASIS[i][j] for i in range(4))
        for j in range(4)
    ]
    return [(abs(x), x < 0) for x in ks]


def prep_g1_scalars(scalars: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
    """Full-width G1 ladder prep → (bits, negs) for g1_scalar_mul_signed.

    GLV on (default): bits (B, 2, GLV_HALF_BITS) MSB-first windows of the
    decomposed halves, negs (B, 2) per-half signs.  GLV off: the classic
    safe_scalar form — bits (B, SCALAR_BITS), negs (B,).  The device
    dispatches on the extra axis, so the two forms can never alias."""
    if not glv_enabled():
        safe = [safe_scalar(s) for s in scalars]
        return (
            scalars_to_bits([s for s, _ in safe]),
            np.array([n for _, n in safe], dtype=bool),
        )
    parts = [glv_decompose_g1(s) for s in scalars]
    flat = [p for pair in parts for p, _ in pair]
    bits = scalars_to_bits(flat, GLV_HALF_BITS).reshape(
        len(scalars), 2, GLV_HALF_BITS
    )
    negs = np.array(
        [n for pair in parts for _, n in pair], dtype=bool
    ).reshape(len(scalars), 2)
    return bits, negs


def prep_g2_scalars(scalars: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
    """Full-width G2 ladder prep: 4-way GLS form (B, 4, GLS_QUARTER_BITS)
    when enabled, else the classic safe_scalar form (prep_g1_scalars
    note)."""
    if not glv_enabled():
        safe = [safe_scalar(s) for s in scalars]
        return (
            scalars_to_bits([s for s, _ in safe]),
            np.array([n for _, n in safe], dtype=bool),
        )
    parts = [gls_decompose_g2(s) for s in scalars]
    flat = [p for quad in parts for p, _ in quad]
    bits = scalars_to_bits(flat, GLS_QUARTER_BITS).reshape(
        len(scalars), 4, GLS_QUARTER_BITS
    )
    negs = np.array(
        [n for quad in parts for _, n in quad], dtype=bool
    ).reshape(len(scalars), 4)
    return bits, negs


# Analytic field-mul accounting for the `ladder_field_muls` counter
# (stacked-mul counts of the formulas above; selects/zero-tests excluded).
_DBL_MULS = 7  # jac_double: 3 + 3 + 1 stacked products
_ADD_MULS = 16  # jac_add unequal core: 5 + 4 + 2 + 3 + 2
_CADD_MULS = _ADD_MULS + _DBL_MULS  # complete add evaluates both routes


def ladder_scan_field_muls(bits: np.ndarray, glv: bool) -> int:
    """Per-lane field-mul count of the MAIN LADDER SCAN a prepared bit
    matrix drives (table build excluded — see glv_table_field_muls).
    Fq muls for G1 shapes, Fq2 muls for G2 shapes.

    GLV G1 (m=2, 2-bit windows): 64·(2·7 + 23) = 2368 — vs the w2
    baseline 127·(2·7 + 16) = 3810, the ~1.6× PERF.md predicted."""
    width = int(bits.shape[-1])
    if glv:
        m = int(bits.shape[-2])
        wbits = 2 if m == 2 else 1
        return (width // wbits) * (wbits * _DBL_MULS + _CADD_MULS)
    if width % 2 == 0 and not os.environ.get("HBBFT_TPU_LADDER_BINARY"):
        return (width // 2) * (2 * _DBL_MULS + _ADD_MULS)
    return width * (_DBL_MULS + _ADD_MULS)


def glv_table_field_muls(bits: np.ndarray) -> int:
    """Per-lane field-mul count of the joint-table build (endomorphism
    applications + 2 doublings + 11 complete adds for m=2; ψ chains +
    11 complete adds for m=4)."""
    m = int(bits.shape[-2])
    if m == 2:
        return 1 + 2 * _DBL_MULS + 11 * _CADD_MULS  # φ is one constant mul
    # ψ chained three times, 2 constant Fq2 muls per application
    return 3 * 2 + 11 * _CADD_MULS


# ---------------------------------------------------------------------------
# Host <-> device point conversion
# ---------------------------------------------------------------------------


def g1_to_device(
    points: Sequence[Optional[Tuple[int, int]]], cache=None, gather=None
):
    """Affine G1 points (golden-ref (x, y) ints or None) → batched Jacobian.

    ``cache`` (an ops/staging.StagingCache) serves repeated coordinate
    values from the cross-call limb-row cache instead of re-running the
    bigint conversion per dispatch.  ``gather`` (numpy int indices)
    expands converted DISTINCT rows to full lane width host-side (see
    pairing.g1_affine_to_device)."""
    conv = cache.rows if cache is not None else fq.from_ints
    g = (lambda a: a[gather]) if gather is not None else (lambda a: a)
    xs = g(conv([(p[0] if p else 0) for p in points]))
    ys = g(conv([(p[1] if p else 1) for p in points]))
    inf = g(np.array([p is None for p in points]))
    zs = np.where(
        inf[:, None], np.asarray(fq.ZERO), np.asarray(fq.ONE)
    ).astype(np.asarray(fq.ONE).dtype)
    return (jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs), jnp.asarray(inf))


def g2_to_device(points, cache=None, gather=None):
    """Affine G2 points (((x0,x1),(y0,y1)) or None) → batched Jacobian."""
    conv = cache.rows if cache is not None else fq.from_ints
    g = (lambda a: a[gather]) if gather is not None else (lambda a: a)
    X = (
        g(conv([(p[0][0] if p else 0) for p in points])),
        g(conv([(p[0][1] if p else 0) for p in points])),
    )
    Y = (
        g(conv([(p[1][0] if p else 1) for p in points])),
        g(conv([(p[1][1] if p else 0) for p in points])),
    )
    Z = (
        g(conv([(1 if p is not None else 0) for p in points])),
        g(conv([0 for _ in points])),
    )
    inf = g(np.array([p is None for p in points]))
    return (
        tuple(jnp.asarray(c) for c in X),
        tuple(jnp.asarray(c) for c in Y),
        tuple(jnp.asarray(c) for c in Z),
        jnp.asarray(inf),
    )


def _batch_modinv(vals: List[int], q: int) -> List[int]:
    """Montgomery batch inversion: one pow(·, -1, q) + 3 bigint muls per
    element instead of one pow per element.  Zero entries pass through as
    zero (callers treat them as infinity)."""
    prefix: List[int] = []
    acc = 1
    for v in vals:
        prefix.append(acc)
        if v:
            acc = acc * v % q
    inv = pow(acc, -1, q)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        if vals[i]:
            out[i] = prefix[i] * inv % q
            inv = inv * vals[i] % q
    return out


def g1_from_device(P) -> List[Optional[Tuple[int, int]]]:
    """Batched Jacobian G1 → affine int tuples (host; exact).

    Round-5 vectorization: ONE batched residue readback per coordinate
    plane (fq.to_ints) and ONE batch inversion for every lane's Z — the
    per-lane to_int/pow loop was the dominant host cost of macro runs
    (PERF.md round-5 north-star attribution)."""
    X, Y, Z, inf = P
    X, Y, Z = np.asarray(X), np.asarray(Y), np.asarray(Z)
    inf = np.asarray(inf)
    from hbbft_tpu.crypto.field import Q

    xs = fq.to_ints(X)
    ys = fq.to_ints(Y)
    zs = fq.to_ints(Z)
    zis = _batch_modinv(zs, Q)
    out: List[Optional[Tuple[int, int]]] = []
    for i in range(X.shape[0]):
        if inf[i] or zs[i] == 0:
            out.append(None)
            continue
        zi = zis[i]
        zi2 = zi * zi % Q
        out.append((xs[i] * zi2 % Q, ys[i] * zi2 * zi % Q))
    return out


def g2_from_device(P):
    """Batched Jacobian G2 → affine ((x0,x1),(y0,y1)) tuples (host; exact).

    Same vectorization as g1_from_device; the Fq2 inversion uses the
    conjugate/norm identity so the batch inversion runs over Fq norms."""
    from hbbft_tpu.crypto.field import Q

    X, Y, Z, inf = P
    inf = np.asarray(inf)
    n = np.asarray(X[0]).shape[0]
    coords = {}
    for name, pair in (("x", X), ("y", Y), ("z", Z)):
        coords[name + "0"] = fq.to_ints(np.asarray(pair[0]))
        coords[name + "1"] = fq.to_ints(np.asarray(pair[1]))
    # 1/(z0 + z1·u) = (z0 - z1·u) / (z0² + z1²): batch-invert the norms
    norms = [
        (coords["z0"][i] * coords["z0"][i] + coords["z1"][i] * coords["z1"][i]) % Q
        for i in range(n)
    ]
    ninvs = _batch_modinv(norms, Q)
    out = []
    for i in range(n):
        z0, z1 = coords["z0"][i], coords["z1"][i]
        if inf[i] or (z0 == 0 and z1 == 0):
            out.append(None)
            continue
        ni = ninvs[i]
        zi = (z0 * ni % Q, (-z1 * ni) % Q)
        zi2 = ((zi[0] * zi[0] - zi[1] * zi[1]) % Q, 2 * zi[0] * zi[1] % Q)
        zi3 = (
            (zi2[0] * zi[0] - zi2[1] * zi[1]) % Q,
            (zi2[0] * zi[1] + zi2[1] * zi[0]) % Q,
        )
        x0, x1 = coords["x0"][i], coords["x1"][i]
        y0, y1 = coords["y0"][i], coords["y1"][i]
        out.append(
            (
                (
                    (x0 * zi2[0] - x1 * zi2[1]) % Q,
                    (x0 * zi2[1] + x1 * zi2[0]) % Q,
                ),
                (
                    (y0 * zi3[0] - y1 * zi3[1]) % Q,
                    (y0 * zi3[1] + y1 * zi3[0]) % Q,
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# Batched scalar-mul + linear combination (the share-combine kernel)
# ---------------------------------------------------------------------------


def g1_scalar_mul_batch(points, bits):
    """points: batched Jacobian G1 (B, ...), bits (B, 254) → batched m·P."""
    return scalar_mul(_F1, jnp.asarray(bits), points)


def g2_scalar_mul_batch(points, bits):
    return scalar_mul(_F2, jnp.asarray(bits), points)


def _tree_sum(F, P, axis_len: int):
    """Reduce a batch of points (leading axis) to their sum by halving."""
    n = axis_len
    while n > 1:
        half = n // 2
        a = tuple(
            jax.tree_util.tree_map(lambda c: c[:half], P[k]) for k in range(3)
        ) + (P[3][:half],)
        b = tuple(
            jax.tree_util.tree_map(lambda c: c[half : 2 * half], P[k])
            for k in range(3)
        ) + (P[3][half : 2 * half],)
        summed = jac_add(F, a, b)
        if n % 2:
            tail = tuple(
                jax.tree_util.tree_map(lambda c: c[-1:], P[k]) for k in range(3)
            ) + (P[3][-1:],)
            summed = (
                tuple(
                    jax.tree_util.tree_map(
                        lambda s, t: jnp.concatenate([s, t], axis=0), summed[k], tail[k]
                    )
                    for k in range(3)
                )
                + (jnp.concatenate([summed[3], tail[3]], axis=0),)
            )
            n = half + 1
        else:
            n = half
        P = summed
    return P


def jac_to_affine_g1(P):
    """Batched Jacobian → affine (x, y, inf) — one Fermat inverse total.

    Infinity lanes get garbage coordinates masked to (0, 1) with inf=True;
    the Miller loop neutralizes them by flag, never by value.
    """
    X, Y, Z, inf = P
    # Avoid 0-division garbage polluting the batch product: substitute 1.
    Zsafe = fq.select(inf, _F1.one_like(Z), Z)
    zinv = fq.batch_inv(Zsafe)
    zinv2, zinv3 = fq.mul_n([(zinv, zinv), (fq.mul(zinv, zinv), zinv)])
    x, y = fq.mul_n([(X, zinv2), (Y, zinv3)])
    x = fq.select(inf, _F1.zeros_like(x), x)
    y = fq.select(inf, _F1.one_like(y), y)
    return (x, y, inf)


def jac_to_affine_g2(P):
    X, Y, Z, inf = P
    Zsafe = tower.fq2_select(inf, _F2.one_like(Z), Z)
    zinv = tower.batch_inv_fq2(Zsafe)
    (zinv2,) = tower.fq2_mul_many([(zinv, zinv)])
    (zinv3,) = tower.fq2_mul_many([(zinv2, zinv)])
    x, y = tower.fq2_mul_many([(X, zinv2), (Y, zinv3)])
    x = tower.fq2_select(inf, _F2.zeros_like(x), x)
    y = tower.fq2_select(inf, _F2.one_like(y), y)
    return (x, y, inf)


def _scalar_mul_signed(F, points, bits, negs):
    """Shared signed-ladder dispatch: a bit matrix with a decomposition
    axis (ndim == point-batch ndim + 2, the prep_g*_scalars GLV/GLS form)
    routes to the joint-table ladder with per-part signs; the classic
    form applies the single safe_scalar negation after the w2/binary
    ladder.  The shapes cannot alias, so the jit cache keys the path."""
    bits = jnp.asarray(bits)
    negs = jnp.asarray(negs)
    if bits.ndim == jnp.ndim(points[3]) + 2:
        return _scalar_mul_joint(F, bits, negs, points)
    prods = scalar_mul(F, bits, points)
    return jac_select(F, negs, jac_neg(F, prods), prods)


def g1_scalar_mul_signed(points, bits, negs):
    """Batched ±(bits_i · P_i) ladders: the shared signed-ladder prologue
    (`negs` is the (B,) safe_scalar negation mask in classic form, or the
    (B, 2) per-half sign mask in GLV form)."""
    return _scalar_mul_signed(_F1, points, bits, negs)


def g2_scalar_mul_signed(points, bits, negs):
    return _scalar_mul_signed(_F2, points, bits, negs)


def linear_combine_g1(points, bits, negs):
    """Σ ±(bits_i · P_i) over the leading axis → single Jacobian point.

    `bits`/`negs` take either prepared form (see prep_g1_scalars): the
    classic (B, SCALAR_BITS) rows with a (B,) safe_scalar negation mask,
    or the GLV (B, 2, GLV_HALF_BITS) windows with (B, 2) per-half signs
    (per-quarter (B, 4, ·) for the G2 twin).
    """
    prods = g1_scalar_mul_signed(points, bits, negs)
    return _tree_sum(_F1, prods, jnp.shape(bits)[0])


def linear_combine_g2(points, bits, negs):
    prods = g2_scalar_mul_signed(points, bits, negs)
    return _tree_sum(_F2, prods, jnp.shape(bits)[0])
