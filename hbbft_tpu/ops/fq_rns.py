"""Batched BLS12-381 Fq arithmetic in a Residue Number System (RNS) —
the MXU-matmul reformulation of the field layer.

The limb path (ops/fq.py) multiplies via a 50-limb convolution: ~2.5k
multiply-adds plus carry/fold passes per Fq product, all VPU work with
per-item shifted operands the MXU cannot help with (measured ceiling
~217M muls/s on a v5e — PERF.md round-2 kernel A/B; the round-2 verdict's
task 1).  This module removes the convolution entirely:

* An element is its residues modulo 78 fixed 11-bit primes (two RNS
  bases B1, B2 of 39 primes each) plus one redundant power-of-two
  modulus m_r = 256 — layout ``(..., 79)`` float32, all values exact
  integers < 2^24 (the f32 exact envelope).
* Multiplication mod each prime is ONE pointwise product lane per prime
  — 79 multiplies instead of 2500.
* The only inter-lane operations are the two Montgomery **base
  extensions**, each a CONSTANT-matrix product ``(..., 39) @ (39, 40)``
  — weight-stationary matmuls batched over every lane the caller holds,
  exactly the shape XLA tiles onto the MXU.  (Constant matrices are
  entry-split ``e = e_lo + 64·e_hi`` so both partial matmuls accumulate
  below 2^24 and stay exact in f32; see _SPLIT_SHIFT.)

Algorithm: full-RNS Montgomery reduction (the standard hardware
construction — Bajard et al. / Kawamura et al.; the first extension is
the uncorrected CRT sum whose +δ·M1 slack is absorbed by the lazy value
bound, the second is the Shenoy–Kumaresan EXACT extension through the
redundant modulus).  ``mul(a, b)`` returns ``a·b·M1⁻¹ (mod Q)`` — the
Montgomery product — so elements are stored in Montgomery form
(``from_int`` multiplies by M1 mod Q, ``to_int`` strips it); since every
public entry point converts through from_int/to_int, the form is
invisible to callers and the public surface is drop-in compatible with
ops/fq.py (the facade at the bottom of fq.py re-exports this module when
``HBBFT_TPU_FQ_IMPL=rns``).

Value discipline (mirrors fq.py's lazy residues): a represented VALUE may
be any integer with |v| < 2^16·Q; ``add``/``sub``/``neg`` are pointwise
and lazy (residues drift above p and below 0), ``mul`` renormalizes its
own inputs.  Closure: with M1 > 2^34·Q, a Montgomery product of two
in-domain values is < 41·Q, so hundreds of chained adds stay in-domain
— wider than the dozen-add discipline the tower relies on
(ops/tower.py).  mul_small renormalizes too, so small-constant scalings
compose safely.

Reference analogue: the `ff`/`pairing` crates' 64-bit Montgomery limbs
under threshold_crypto (SURVEY.md §2.2) — redesigned a second time, now
for the MXU's constant-matrix contraction instead of add-with-carry.
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.field import Q

DTYPE = jnp.float32
NP_DTYPE = np.float32

# -- base construction (Python ints, import time) ----------------------------


def _primes_11bit() -> List[int]:
    sieve = np.ones(2048, dtype=bool)
    sieve[:2] = False
    for i in range(2, 46):
        if sieve[i]:
            sieve[i * i :: i] = False
    return [int(p) for p in np.nonzero(sieve)[0] if p > 1024][::-1]


_ALL = _primes_11bit()  # descending from 2039
N_B = 39  # primes per base
B1 = _ALL[:N_B]
B2 = _ALL[N_B : 2 * N_B]
M_R = 256  # redundant Shenoy–Kumaresan modulus (coprime: primes are odd)
M1 = 1
for _p in B1:
    M1 *= _p
M2 = 1
for _p in B2:
    M2 *= _p
# Closure bounds (see mul): inputs |A|,|B| < 2^16·Q, the sign offset makes
# the Montgomery numerator x' = A·B + 2^33·Q² ∈ [0, 2^34·Q²], and with
# M1 > 2^34·Q the reduced output is < 41·Q — back in-domain.
assert M1 > (Q << 34), "M1 must exceed 2^34*Q for the lazy-value closure"
assert M2 > 80 * Q, "M2 must exceed the Montgomery-output bound"
assert M_R > N_B + 2, "S-K correction digit must fit the redundant modulus"
# The TIGHTEST f32-exactness bound any mul stage relies on (the fused r2r
# reduction below): |x2r|·M1⁻¹ + q̂·(Q·M1⁻¹) with x2r ∈ (−p, 3p), q̂ ∈
# [0, p), both constants < p → sum < 3p² + p² = 4p² ≈ 2^23.99 — only
# ~0.9% under the 2^24 f32-exact envelope.  A larger prime base or a
# wider sign offset could pass the looser bounds yet break this one, so
# it is asserted at import time, not just in tests.
_P_MAX = _ALL[0]  # descending sieve → largest base prime (2039)
assert 4 * _P_MAX * _P_MAX < (1 << 24), "fused r2r reduction exceeds f32-exact"

#: lane layout: [B1 | B2 | m_r]
NLIMBS = 2 * N_B + 1
_S1 = slice(0, N_B)
_S2 = slice(N_B, 2 * N_B)
_SR = slice(2 * N_B, 2 * N_B + 1)

_P_ALL = np.array(B1 + B2 + [M_R], dtype=np.int64)
P_VEC = _P_ALL.astype(NP_DTYPE)
_INV_P = (1.0 / _P_ALL).astype(NP_DTYPE)

# Montgomery per-lane constants.
_M1INV_B2R = np.array(
    [pow(M1, -1, p) for p in B2] + [pow(M1, -1, M_R)], dtype=NP_DTYPE
)  # M1⁻¹ mod p_j,  j ∈ B2∪{m_r}
# fused: σ_i = x_i·(−Q⁻¹·(M1/p_i)⁻¹ mod p_i) — one product+mod, not two
_SIGMA_C_B1 = np.array(
    [((-pow(Q, -1, p)) % p) * pow(M1 // p, -1, p) % p for p in B1],
    dtype=NP_DTYPE,
)
# fused: r_j = x_j·M1⁻¹ + q̂_j·(Q·M1⁻¹ mod p_j) — both products < 2^22,
# sum < 2^23, ONE reduction instead of three
_QM1INV_B2R = np.array(
    [Q * pow(M1, -1, p) % p for p in B2] + [Q * pow(M1, -1, M_R) % M_R],
    dtype=NP_DTYPE,
)
_W2INV_B2 = np.array(
    [pow(M2 // p, -1, p) for p in B2], dtype=NP_DTYPE
)  # (M2/p_j)⁻¹ mod p_j
_M2INV_R = float(pow(M2, -1, M_R))
_M2_B1 = np.array([M2 % p for p in B1], dtype=NP_DTYPE)  # M2 mod p_i

# Sign offset: a fixed multiple of Q added to every Montgomery numerator
# so the integer being reduced is provably non-negative (lazy values may
# be negative; the S-K extension reconstructs the representative in
# [0, M2), so a negative r would silently gain +M2 and leave the domain).
_X_OFFSET_INT = (1 << 33) * Q * Q
assert _X_OFFSET_INT % Q == 0

# Extension matrices (constant weights — the MXU operands).  Entries are
# split e = e_lo + 64·e_hi so each partial matmul's f32 accumulation stays
# below 2^24: terms ≤ 2047·63, 39 of them → < 2^22.3.
_SPLIT_SHIFT = 64.0


def _split_matrix(e: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    hi = np.floor(e / _SPLIT_SHIFT)
    return (e - hi * _SPLIT_SHIFT).astype(NP_DTYPE), hi.astype(NP_DTYPE)


# E1[i, j] = (M1/p_i) mod (B2∪{m_r})_j   — extension 1 (B1 → B2∪m_r)
_E1 = np.array(
    [[(M1 // p) % pj for pj in B2 + [M_R]] for p in B1], dtype=np.float64
)
# E2[j, i] = (M2/p_j) mod (B1∪{m_r})_i   — extension 2 (B2 → B1∪m_r)
_E2 = np.array(
    [[(M2 // p) % pi for pi in B1 + [M_R]] for p in B2], dtype=np.float64
)
_E1_LO, _E1_HI = _split_matrix(_E1)
_E2_LO, _E2_HI = _split_matrix(_E2)

ZERO = np.zeros(NLIMBS, dtype=NP_DTYPE)


# -- host <-> device conversion ----------------------------------------------


def from_int(x: int) -> np.ndarray:
    """Residue vector of the MONTGOMERY form x·M1 mod Q."""
    v = (x % Q) * M1 % Q
    return np.array(
        [v % p for p in B1] + [v % p for p in B2] + [v % M_R], dtype=NP_DTYPE
    )


ONE = from_int(1)


# 30-bit limb decomposition machinery for VECTORIZED host<->device
# conversion (round-5: per-value Python loops were the dominant host cost
# at macro scale — 79 `% p` per from_int, 39 CRT terms per to_int).
# Exactness: residues/limbs < 2^11/2^30, so the int64 matmuls below peak
# at 13·2^30·2^11 < 2^45 (from) and 39·2^11·2^30 < 2^47 (to) — no wrap.
_N_IN_LIMBS = 13  # ceil(381 / 30): Montgomery values < Q < 2^381
_POW30 = np.array(
    [[pow(1 << 30, j, int(p)) for p in _P_ALL] for j in range(_N_IN_LIMBS)],
    dtype=np.int64,
)  # (13, 79)
_P_VEC_I64 = np.asarray(P_VEC, dtype=np.int64)
_MASK30 = (1 << 30) - 1


def from_ints(xs) -> np.ndarray:
    """Stack of residue vectors, value-deduplicated (fq.from_ints note).

    Vectorized: unique values decompose into 30-bit limbs (Python shifts)
    and one (u, 13) @ (13, 79) int64 matmul + lane mod produces every
    residue — replacing 79 Python `% p` per value."""
    xs = [int(x) for x in xs]
    uniq: dict = {}
    vals: List[int] = []
    idx = np.empty(len(xs), dtype=np.int64)
    for j, x in enumerate(xs):
        pos = uniq.get(x)
        if pos is None:
            pos = uniq[x] = len(vals)
            vals.append(x)
        idx[j] = pos
    if not vals:
        return np.zeros((0, NLIMBS), dtype=NP_DTYPE)
    limbs = np.empty((len(vals), _N_IN_LIMBS), dtype=np.int64)
    for i, x in enumerate(vals):
        v = (x % Q) * M1 % Q
        for j in range(_N_IN_LIMBS):
            limbs[i, j] = v & _MASK30
            v >>= 30
    res = np.mod(limbs @ _POW30, _P_VEC_I64)  # (u, 79)
    return res.astype(NP_DTYPE)[idx]


# Garner/CRT weights over B1 for host readback.
_CRT_W_B1 = [(M1 // p) * pow(M1 // p, -1, p) % M1 for p in B1]


def to_int(res) -> int:
    """Exact represented value mod Q (strips the Montgomery factor).

    Residues may be lazy (negative / above p).  The value is recovered
    from base B1 alone: CRT gives v mod M1, and |v| < 2^16·Q ≪ M1/2 maps
    the high half to negatives unambiguously."""
    arr = np.asarray(res)
    v = 0
    for k, p in enumerate(B1):
        r = int(round(float(arr[..., k]))) % p
        v = (v + r * _CRT_W_B1[k]) % M1
    if v > M1 // 2:
        v -= M1
    return v * _M1_INV_Q % Q


#: cached CRT weight limbs for vectorized readback: _CRT_W_B1 decomposed
#: into 30-bit limbs, (39, ceil(429/30)=15) int64.
_W_LIMBS = np.array(
    [[(w >> (30 * j)) & _MASK30 for j in range(15)] for w in _CRT_W_B1],
    dtype=np.int64,
)
_B1_I64 = np.asarray(B1, dtype=np.int64)
_M1_INV_Q = pow(M1, -1, Q)


def to_ints(batch) -> list:
    """Vectorized batch readback: one rint+mod over (n, 39) lanes and one
    (n, 39) @ (39, 15) int64 matmul collapse the per-value CRT loop; the
    remaining per-value work is 15 shift-adds + two bigint mod-muls."""
    arr = np.asarray(batch)
    if arr.ndim == 1:
        return [to_int(arr)]
    n = arr.shape[0]
    if n == 0:
        return []
    r = np.mod(np.rint(arr[..., : len(B1)]).astype(np.int64), _B1_I64)
    S = r @ _W_LIMBS  # (n, 15), exact: 39·2^11·2^30 < 2^47
    out = []
    half = M1 // 2
    for i in range(n):
        v = 0
        row = S[i]
        for j in range(14, -1, -1):
            v = (v << 30) + int(row[j])
        v %= M1
        if v > half:
            v -= M1
        out.append(v * _M1_INV_Q % Q)
    return out


# -- lane-wise modular reduction ---------------------------------------------

_P_J = jnp.asarray(P_VEC)
_INVP_J = jnp.asarray(_INV_P)


def _mod_lanes(x: jnp.ndarray, p, invp) -> jnp.ndarray:
    """Exact per-lane reduction to [0, p) for integer-valued f32 inputs
    with |x| < 2^24: one estimated-quotient pass (floor may be off by
    one either way near multiples) followed by two branchless clamps."""
    x = x - jnp.floor(x * invp) * p
    x = x - p * (x >= p)
    x = x + p * (x < 0)
    return x


def _mod_loose(x: jnp.ndarray, p, invp) -> jnp.ndarray:
    """One-pass reduction to (−p, 2p) — same congruence class, no clamps.

    Sufficient wherever only the f32 exactness budget matters (products
    with an 11-bit operand stay < 3p·p < 2^24); the full clamped form is
    reserved for values whose INTEGER range matters: the CRT digits σ/ξ
    (a negative digit would make the reconstructed q̂/r negative and wrap
    the S-K extension) and the S-K correction δ."""
    return x - jnp.floor(x * invp) * p


def carry3(x: jnp.ndarray) -> jnp.ndarray:
    """Representation-normalization hook (fq.carry3 analogue): reduce
    every lane into (−p, 2p) — enough that lane products stay f32-exact
    ((2·2047)² < 2^24).  NOTE: lane reduction only — the represented
    VALUE is unchanged (RNS lanes cannot shrink a value; see
    reduce_small for that)."""
    x = jnp.asarray(x, DTYPE)
    return _mod_loose(x, _P_J, _INVP_J)


def reduce_small(x: jnp.ndarray) -> jnp.ndarray:
    """VALUE renormalization (the limb path's carry+fold analogue).

    Chained linear terms (e.g. the ±2·input in fq12_cyclo_sqr, iterated
    64× by the x-power chain) double the represented value per step; the
    limb path caps it with a fold, RNS needs one Montgomery pass: a full
    mul by ONE renormalizes the value to < 41·Q while representing the
    same element."""
    return mul(x, _ONE_J)


# -- core ops ----------------------------------------------------------------


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy add — pointwise, no reduction (mul renormalizes)."""
    return a + b


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return a - b


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return -a


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(cond[..., None], a, b)


#: Extension-matmul strategy (HBBFT_TPU_RNS_EXT): ``highest`` —
#: precision=HIGHEST f32 einsums (exact; TPU lowers each to 6 bf16 MXU
#: passes); ``bf16`` — explicit 6/5-bit plane split so operands ARE
#: bf16-exact, 4 native passes; ``int8`` — same split on the int8 MXU
#: path (int32 accumulation).  All three are bit-identical (every
#: partial bound derived below); the window A/B picks the on-chip
#: default.  Read at import (kernels cache jitted closures).
_EXT_MODE = os.environ.get("HBBFT_TPU_RNS_EXT", "highest")
assert _EXT_MODE in ("highest", "bf16", "int8"), _EXT_MODE


def _ext_matmul(sigma: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray,
                p_out, invp_out) -> jnp.ndarray:
    """Σ_i sigma_i · E[i, j] mod p_j via the entry-split constant matmuls.

    sigma lanes are reduced (< 2^11); each partial contraction stays
    below 2^22.3 so f32 accumulation is exact.  The hi partial is reduced
    before recombination so the weighted sum also stays exact.

    ``highest`` mode: precision=HIGHEST is load-bearing — TPU f32 dots
    default to single bf16 passes, and 11-bit sigma lanes are NOT
    bf16-exact; HIGHEST selects the exact-f32 algorithm.

    ``bf16``/``int8`` modes additionally split sigma into a 6-bit lo /
    5-bit hi plane (mirroring fq_rns_pallas._split_dot): all four
    operands are then ≤ 6-bit integers — exactly representable in bf16
    AND int8 — so the dots run as NATIVE MXU passes (4 instead of
    HIGHEST's 6 per einsum pair) with exact f32/int32 accumulation:

        ll ≤ 39·63·63 < 2^17.3      lh, hl ≤ 39·31·63 < 2^16.3
        hh ≤ 39·31·31 < 2^15.3
        ll + 64·mod(lh+hl) + 4096·mod(hh) ≤ 155k + 131k + 8.39M < 2^24
    """
    if _EXT_MODE == "highest":
        hp = jax.lax.Precision.HIGHEST
        s_lo = jnp.einsum(
            "...i,ij->...j", sigma, lo, precision=hp, preferred_element_type=DTYPE
        )
        s_hi = jnp.einsum(
            "...i,ij->...j", sigma, hi, precision=hp, preferred_element_type=DTYPE
        )
        s_hi = _mod_lanes(s_hi, p_out, invp_out)
        return _mod_lanes(s_lo + _SPLIT_SHIFT * s_hi, p_out, invp_out)

    v_hi = jnp.floor(sigma * (1.0 / _SPLIT_SHIFT))
    v_lo = sigma - _SPLIT_SHIFT * v_hi
    if _EXT_MODE == "int8":
        op, acc = jnp.int8, jnp.int32
    else:
        op, acc = jnp.bfloat16, DTYPE

    def dot(v, m):
        return jnp.einsum(
            "...i,ij->...j",
            v.astype(op),
            m.astype(op),
            preferred_element_type=acc,
        ).astype(DTYPE)

    ll = dot(v_lo, lo)
    mid = _mod_lanes(dot(v_hi, lo) + dot(v_lo, hi), p_out, invp_out)
    hh = _mod_lanes(dot(v_hi, hi), p_out, invp_out)
    return _mod_lanes(
        ll + _SPLIT_SHIFT * mid + (_SPLIT_SHIFT * _SPLIT_SHIFT) * hh,
        p_out,
        invp_out,
    )


_E1_LO_J = jnp.asarray(_E1_LO)
_E1_HI_J = jnp.asarray(_E1_HI)
_E2_LO_J = jnp.asarray(_E2_LO)
_E2_HI_J = jnp.asarray(_E2_HI)
_P_B2R = jnp.asarray(np.concatenate([P_VEC[_S2], P_VEC[_SR]]))
_INVP_B2R = jnp.asarray(np.concatenate([_INV_P[_S2], _INV_P[_SR]]))
_P_B1R = jnp.asarray(np.concatenate([P_VEC[_S1], P_VEC[_SR]]))
_INVP_B1R = jnp.asarray(np.concatenate([_INV_P[_S1], _INV_P[_SR]]))
_X_OFF_J = jnp.asarray(
    np.array([_X_OFFSET_INT % int(p) for p in _P_ALL], dtype=NP_DTYPE)
)
_SIGMA_C_B1_J = jnp.asarray(_SIGMA_C_B1)
_QM1INV_B2R_J = jnp.asarray(_QM1INV_B2R)
_M1INV_B2R_J = jnp.asarray(_M1INV_B2R)
_W2INV_B2_J = jnp.asarray(_W2INV_B2)
_M2_B1_J = jnp.asarray(_M2_B1)
_M2INV_R_J = jnp.asarray(_M2INV_R, DTYPE)
_MR_P_J = jnp.asarray(float(M_R), DTYPE)
_MR_INVP_J = jnp.asarray(1.0 / M_R, DTYPE)
_ONE_J = jnp.asarray(ONE)


def _use_fused(which: str) -> bool:
    """Route through the fused whole-mul Pallas kernel on TPU
    (fq_rns_pallas; trace-time check, mirrors fq._use_pallas).

    HBBFT_TPU_RNS_FUSED selects how much routes: ``pow`` (default) only
    the fixed-exponent chains — the shape the round-2 on-chip record
    shows fused kernels winning (one launch vs ~760 sequential
    dispatches for the Fermat inverse); ``all`` additionally every mul
    (per-mul fusion LOST the limb A/B on full verification graphs, so
    this stays an A/B flag until tools/tpu_window.sh re-judges it for
    RNS); ``0`` disables.  HBBFT_TPU_NO_PALLAS force-disables (bench.py's
    compile-failure fallback ladder)."""
    if os.environ.get("HBBFT_TPU_NO_PALLAS"):
        return False
    mode = os.environ.get("HBBFT_TPU_RNS_FUSED", "pow")
    if mode == "0" or (mode != "all" and mode != which):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a·b·M1⁻¹ (mod Q) — 77 pointwise lanes plus two
    constant-matrix base extensions; no convolution, no carries."""
    if _use_fused("mul"):
        from hbbft_tpu.ops import fq_rns_pallas

        return fq_rns_pallas.mul(a, b)
    return _mul_body(carry3(a), carry3(b))


def _mul_body(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """mul minus the input renormalization: requires BOTH operands'
    lanes already in (−p, 2p) — true for carry3 output and for any
    output of this function itself (so chains may skip the re-carry:
    |a·b| ≤ 4p² < 2^24 is the import-asserted envelope; the same
    steady-state argument as fq_rns_pallas._mul_core(reduced=True))."""
    # sign offset (multiple of Q) keeps the reduced integer non-negative;
    # x lanes stay UNREDUCED in (−p, 3p): both downstream products still
    # fit the exact envelope (3p·p ≈ 2^23.6 < 2^24, ~25% headroom — any
    # widening of the offset or the primes must re-derive this), saving a
    # full-width reduction stage.
    x = _mod_loose(a * b, _P_J, _INVP_J) + _X_OFF_J  # lanes in (−p, 3p)

    # σ_i = (−x·Q⁻¹ mod M1)·(M1/p_i)⁻¹ mod p_i, constants fused.
    p1, ip1 = _P_J[_S1], _INVP_J[_S1]
    sigma = _mod_lanes(x[..., _S1] * _SIGMA_C_B1_J, p1, ip1)

    # Extension 1 (uncorrected CRT sum): q̂ = q + δ·M1, δ ≤ 38 — the
    # slack lands in the lazy value bound, not in correctness.
    qhat = _ext_matmul(sigma, _E1_LO_J, _E1_HI_J, _P_B2R, _INVP_B2R)

    # r = (x + q̂·Q)/M1 over B2 ∪ {m_r}: expanded as x·M1⁻¹ + q̂·(Q·M1⁻¹)
    # — both products < 2^22, so ONE reduction covers the sum.
    x2r = jnp.concatenate([x[..., _S2], x[..., _SR]], axis=-1)
    # |x2r|·M1⁻¹ < 3p·p ≈ 2^23.6 and qhat is clamped [0,p) → sum < 2^24
    r2r = _mod_loose(
        x2r * _M1INV_B2R_J + qhat * _QM1INV_B2R_J, _P_B2R, _INVP_B2R
    )
    r2 = r2r[..., :N_B]
    r_mr = r2r[..., N_B:]

    # Extension 2 (Shenoy–Kumaresan, EXACT through m_r): B2 → B1.
    p2, ip2 = _P_J[_S2], _INVP_J[_S2]
    xi = _mod_lanes(r2 * _W2INV_B2_J, p2, ip2)
    raw = _ext_matmul(xi, _E2_LO_J, _E2_HI_J, _P_B1R, _INVP_B1R)
    raw1 = raw[..., :N_B]
    raw_mr = raw[..., N_B:]
    delta = _mod_lanes(
        (raw_mr - r_mr) * _M2INV_R_J, _MR_P_J, _MR_INVP_J
    )  # δ ≤ 39 < m_r — exact
    r1 = _mod_loose(raw1 - delta * _M2_B1_J, p1, ip1)
    return jnp.concatenate([r1, r2, r_mr], axis=-1)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_n(pairs) -> list:
    """Stacked independent products (fq.mul_n contract)."""
    if len(pairs) == 1:
        return [mul(pairs[0][0], pairs[0][1])]
    common = ()
    for a, b in pairs:
        common = jnp.broadcast_shapes(common, jnp.shape(a), jnp.shape(b))
    A = jnp.stack([jnp.broadcast_to(jnp.asarray(a), common) for a, _ in pairs])
    B = jnp.stack([jnp.broadcast_to(jnp.asarray(b), common) for _, b in pairs])
    C = mul(A, B)
    return [C[i] for i in range(len(pairs))]


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small int, |k| < 2^15 (fq.mul_small contract).

    Always routes through a full Montgomery product with the residues of
    k·M1, so the value renormalizes to < 41·Q.  (A lazy pointwise scale
    would be cheaper per call but lets CHAINS of small scalings escape
    the 2^16·Q value domain silently — the renormalizing form makes
    mul_small composition-safe like mul itself.)"""
    if not -(1 << 15) < k < (1 << 15):
        raise ValueError("|k| must be < 2^15")
    return mul(a, jnp.asarray(from_int(k)))


def pow_fixed(x: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """x^exponent (Montgomery chain; exponent baked into the graph).

    The base is carried ONCE outside the scan and the body chains
    :func:`_mul_body` directly — every operand inside the loop is a mul
    output (lanes in (−p, 2p)), so the per-iteration re-carry the naive
    form pays (4 of ~15 reduction stages per mul) is skipped."""
    if exponent >= 1 and _use_fused("pow"):
        from hbbft_tpu.ops import fq_rns_pallas

        return fq_rns_pallas.pow_fixed(x, exponent)
    bits = [int(b) for b in bin(exponent)[2:]]
    bits_arr = jnp.asarray(bits, dtype=jnp.int32)
    x_c = carry3(x)

    def step(acc, bit):
        acc = _mul_body(acc, acc)
        cond = jnp.broadcast_to(bit.astype(bool), acc.shape[:-1])
        acc = select(cond, _mul_body(acc, x_c), acc)
        return acc, None

    ones = jnp.broadcast_to(jnp.asarray(ONE), x.shape)
    acc, _ = jax.lax.scan(step, ones, bits_arr)
    return acc


def inv(x: jnp.ndarray) -> jnp.ndarray:
    return pow_fixed(x, Q - 2)


def batch_inv(x: jnp.ndarray) -> jnp.ndarray:
    # carry the whole stack ONCE: associative_scan passes the endpoint
    # elements through RAW (prefix[0] = x[0]), so scanning over lazy
    # lanes would violate _mul_body's (−p, 2p) operand contract at the
    # wings.  Post-carry, every scan leaf/combination and the wing
    # products below are in-contract, so all re-carries are skipped.
    xc = carry3(x)
    prefix = jax.lax.associative_scan(_mul_body, xc, axis=0)
    suffix = jax.lax.associative_scan(_mul_body, xc, axis=0, reverse=True)
    tinv = inv(prefix[-1])
    one = jnp.broadcast_to(jnp.asarray(ONE), x[:1].shape)
    pre = jnp.concatenate([one, prefix[:-1]], axis=0)
    suf = jnp.concatenate([suffix[1:], one], axis=0)
    return _mul_body(_mul_body(pre, suf), jnp.broadcast_to(tinv, x.shape))


def is_zero_host(res) -> bool:
    return to_int(res) == 0


# -- in-graph zero test (complete-add route selector; ops/curve.py) ----------
#
# An RNS vector determines its integer value v exactly mod M1 (base B1
# alone), and every value the curve layer feeds here is a short linear
# combination of Montgomery-mul outputs — each in [0, 41·Q) — so
# |v| < _Z_BOUND·Q ≪ M1/2.  Testing Q | v then needs no CRT readback:
# let v' = v + _Z_BOUND·Q (non-negative) and w ≡ v'·Q⁻¹ (mod p_i) per
# lane.  If Q | v then v' = (m + _Z_BOUND)·Q with 0 ≤ m + _Z_BOUND <
# 2·_Z_BOUND, so w equals that SAME small integer on every lane (it is
# < every base prime).  Conversely, if all 39 lanes agree on a value
# c < 2·_Z_BOUND, then w ≡ c mod M1, hence v' ≡ c·Q (mod M1); both
# sides lie in [0, 2·_Z_BOUND·Q) ⊂ [0, M1), so v' = c·Q exactly and
# Q | v.  Cost: two pointwise mul+mod passes over 39 lanes.

#: |value| bound the zero test accepts: covers any ± combination of a few
#: fq2 Karatsuba recombinations of mul outputs (each component of an fq2
#: product is within (−2·41·Q, 41·Q); a difference of two stays well
#: inside 256·Q).
_Z_BOUND = 256
assert 2 * _Z_BOUND * Q < M1, "zero-test bound must stay CRT-unambiguous"
assert 2 * _Z_BOUND < min(B1), "zero-test digit must fit every base prime"
_Z_OFF_B1 = np.array([(_Z_BOUND * Q) % int(p) for p in B1], dtype=NP_DTYPE)
_Z_QINV_B1 = np.array([pow(Q, -1, int(p)) for p in B1], dtype=NP_DTYPE)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """Exact in-graph test: does the residue vector represent 0 mod Q?

    Returns a bool array over the batch shape.  Contract: the represented
    INTEGER value (not just the class mod Q) must satisfy |v| <
    ``_Z_BOUND``·Q — true for any difference/sum of a few mul outputs;
    raw canonical inputs ([0, Q)) trivially qualify.  Lanes may be lazy.
    """
    p1 = _P_J[_S1]
    ip1 = _INVP_J[_S1]
    v = _mod_lanes(jnp.asarray(x, DTYPE)[..., _S1], p1, ip1)
    v = _mod_lanes(v + jnp.asarray(_Z_OFF_B1), p1, ip1)
    w = _mod_lanes(v * jnp.asarray(_Z_QINV_B1), p1, ip1)
    same = jnp.all(w == w[..., :1], axis=-1)
    return same & (w[..., 0] < 2 * _Z_BOUND)
