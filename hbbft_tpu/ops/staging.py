"""Device-staging cache: limb rows for repeated field values.

Host assembly converts Python bigint coordinates into device limb rows
(``fq.from_ints`` — 30-bit decomposition + residue/limb matmul) on every
dispatch.  The per-era key material is tiny and wildly repetitive: an
N=100 epoch re-stages the same ≤100 public key shares ~990k times across
share verifies, every RLC group row re-stages the generator and the same
H2(doc) points, and the engine's per-receiver workload repeats each
share N−1 times.  ``from_ints`` already deduplicates *within* one call;
this cache makes the deduplication *cross-call*: each distinct field
value is limb-converted once per era and thereafter gathered by index
(`np.stack` over cached rows), so steady-state dispatches skip the
bigint decomposition entirely.

Keying is by **value** (the field integer), which is self-invalidating —
a stale entry cannot be wrong, only dead weight — with an LRU bound
(``HBBFT_TPU_STAGE_CAP``, default 32768 rows ≈ 25 MB at RNS width; 0
disables) so churned eras age out.  Era turnover additionally clears the
cache outright via ``CryptoBackend.new_era`` (the engine calls it after
every DKG) so dead key material is dropped promptly rather than evicted
lane by lane.

One cache serves every staging form: G1/G2, affine and Jacobian, because
they all decompose into per-coordinate ``fq`` rows.  The cache yields
host numpy — placement (``jnp.asarray`` or MeshBackend's sharded
``device_put``) happens downstream, so the mesh placement hook composes
unchanged.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from hbbft_tpu.ops import fq


def _default_capacity() -> int:
    try:
        return int(os.environ.get("HBBFT_TPU_STAGE_CAP", "32768"))
    except ValueError:
        return 32768


class StagingCache:
    """LRU of ``value → limb row`` with batched miss conversion.

    ``counters`` (a ``utils.metrics.Counters`` or None) receives
    ``stage_cache_hits``/``stage_cache_misses`` tallies, counted per
    *distinct* value per call (the within-call fan-out was already free
    via ``from_ints`` dedup; hits measure conversions actually skipped).
    """

    def __init__(self, capacity: Optional[int] = None, counters=None) -> None:
        self.capacity = _default_capacity() if capacity is None else capacity
        self.counters = counters
        self._rows: "OrderedDict[int, np.ndarray]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._rows)

    def clear(self) -> None:
        self._rows.clear()

    def rows(self, vals: Sequence[int]) -> np.ndarray:
        """(len(vals), NLIMBS) canonical limb rows — drop-in for
        ``fq.from_ints`` (same values, dtype and shape)."""
        if self.capacity <= 0:
            return fq.from_ints(vals)
        rowmap = self._rows
        idx = np.empty(len(vals), dtype=np.int64)
        uniq: dict = {}
        order: list = []
        for j, v in enumerate(vals):
            v = int(v)
            p = uniq.get(v)
            if p is None:
                p = uniq[v] = len(order)
                order.append(v)
            idx[j] = p
        if not order:
            return np.zeros((0, fq.NLIMBS), dtype=fq.NP_DTYPE)
        missing = [v for v in order if v not in rowmap]
        if missing:
            conv = fq.from_ints(missing)
            for i, v in enumerate(missing):
                # copy: a view would pin the whole batch array in memory
                # for as long as any one row survives in the cache
                rowmap[v] = np.array(conv[i])
        c = self.counters
        if c is not None:
            c.stage_cache_misses += len(missing)
            c.stage_cache_hits += len(order) - len(missing)
        urows = []
        for v in order:
            rowmap.move_to_end(v)
            urows.append(rowmap[v])
        while len(rowmap) > self.capacity:
            rowmap.popitem(last=False)
        return np.stack(urows)[idx]
