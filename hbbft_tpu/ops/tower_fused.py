"""VMEM-resident fused tower kernels: fq2/fq6/fq12 ops on the RNS core.

Mid-granularity fusion — the level between the two approaches that
already failed on-chip (per-step fused LIMB kernels lost 1.4-2.6x to
Mosaic scheduling in round 2; FUSE2 whole-loop kernels never compiled):
each kernel here fuses ONE tower-level operation (an Fq12 multiply, a
Miller double step, the whole final-exp hard part) so the ~54 Fq muls
inside one Fq12 multiply never leave VMEM, while the loop structure
around the kernels stays ordinary XLA (`lax.scan` at the pairing layer).

Building blocks:

* The multiply body is `fq_rns_pallas._mul_core` — the already-golden
  full-RNS Montgomery pipeline — reused VERBATIM on (80, T) row tiles.
* A tower operation's n independent Fq products run as ONE core pass by
  CONCATENATING the operands along the lane axis (scatter-free — the
  FUSE2 lesson: `_kmul`); the recombination arithmetic is the exact
  pointwise code from ops/tower.py (`fq2_from_products`,
  `fq6_from_products`, ...), which only uses lazy adds/subs/negs and so
  runs unchanged on row-layout tiles.  Because the recombination is the
  SAME code and the core is stage-identical to `fq_rns.mul`, the fused
  kernels compute the same represented values as the stacked
  composition — the golden tests assert that equality bit-for-bit on
  canonical readback.
* `reduce_small` (a full Montgomery multiply by ONE in the RNS
  representation — value renormalization, see fq_rns.reduce_small) is
  mirrored in-kernel as a core pass against a broadcast ONE column, so
  the cyclotomic-squaring chain has the identical value flow to
  tower.fq12_cyclo_sqr.

Layout: a tower element with C Fq coefficients is ONE (C·80, T) f32
array — coefficient c occupies rows [80c, 80c+80) in the padded kernel
row layout of fq_rns_pallas ([B1(39) | pad | B2(39) | m_r]); 80 rows =
10 sublanes, so every coefficient slice is sublane-aligned.  Leaf order
matches tower.fq12_to_ints_batch: for fq6-half s, fq2-coeff t, component
c — index 4s + 2t + c... i.e. ``[c for x6 in a for x2 in x6 for c in x2]``.

Tiling: TILE lanes per grid step (HBBFT_TPU_TOWER_TILE, default 128 —
the f32 lane minimum).  The widest internal concatenation is 54·TILE
lanes (an Fq12 multiply); at TILE=128 the peak live set of a core pass
is ~12-15 MB of VMEM, inside the ~16 MB/core budget but with little
slack — raising TILE trades grid overhead against Mosaic spilling, which
is exactly what the `fused_chain_ab` window step measures on-chip.

Routing (`fused_tower_mode`): the fallback ladder is
fused → HBBFT_TPU_NO_FUSED → HBBFT_TPU_NO_PALLAS, with the per-call kill
switch HBBFT_TPU_NO_FUSED_TOWER disabling ONLY these tower kernels
(leaving the round-2 pow kernel routing untouched).
HBBFT_TPU_FUSED_TOWER=interpret forces interpret-mode routing (the CPU
A/B arm used by the tests); =auto (default) routes natively on TPU only.
Requires the RNS field implementation (fq.IMPL == "rns"); the limb
facade never routes here.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hbbft_tpu.crypto.bls381 import BLS_X, BLS_X_IS_NEG
from hbbft_tpu.ops import fq
from hbbft_tpu.ops import fq_rns as R
from hbbft_tpu.ops import fq_rns_pallas as K
from hbbft_tpu.ops import tower

#: lanes per grid step.  Module attribute (not captured) so tests can
#: shrink it for cheap interpret-mode runs; every cached pallas_call is
#: keyed on the tile it was built with.
TILE = int(os.environ.get("HBBFT_TPU_TOWER_TILE", "128"))
assert TILE > 0, f"HBBFT_TPU_TOWER_TILE={TILE} must be positive"

NROWS = K.NROWS  # 80
_NB = R.N_B  # 39
DTYPE = K.DTYPE

#: final-exp x-chain bit schedule (MSB implicit — acc starts at the base,
#: mirroring tower.fq12_cyclo_pow_segmented's bin(x)[3:]).
_X_CHAIN_BITS = np.array([int(b) for b in bin(BLS_X)[3:]], dtype=np.int32)


def fused_tower_mode():
    """None (off) | "native" | "interpret" — read per call, never cached.

    Ladder position: fused → HBBFT_TPU_NO_FUSED → HBBFT_TPU_NO_PALLAS
    (either generic switch disables this layer too), plus the dedicated
    per-call kill switch HBBFT_TPU_NO_FUSED_TOWER."""
    if fq.IMPL != "rns":
        return None
    if os.environ.get("HBBFT_TPU_NO_PALLAS"):
        return None
    if os.environ.get("HBBFT_TPU_NO_FUSED"):
        return None
    if os.environ.get("HBBFT_TPU_NO_FUSED_TOWER"):
        return None
    v = os.environ.get("HBBFT_TPU_FUSED_TOWER", "auto")
    if v in ("0", "off"):
        return None
    if v == "interpret":
        return "interpret"
    if v in ("1", "native"):
        return "native"
    return "native" if jax.default_backend() == "tpu" else None


# ---------------------------------------------------------------------------
# Constants in kernel layout
# ---------------------------------------------------------------------------

#: packed tower constants (80, 40): col 0 = ONE (the reduce_small
#: multiplier), cols 1+12(n−1)..12n = the Frobenius^n fq2 coefficient
#: sets for n = 1, 2, 3 (component c of K^{(n)}[j][i] at column
#: 1 + 12(n−1) + 2(3j+i) + c).  K^{(n)} = conj(K^{(n−1)})·K^{(1)} —
#: frob^n(a)_ji = conj^n(a_ji)·K^{(n)}_ji, so each frob^n application is
#: ONE 6-fq2 constant round instead of n chained applications.
_NTC = 40


def _const_col(res79) -> np.ndarray:
    """(79,) RNS residues → padded (80,) kernel row column."""
    v = np.array(res79, dtype=np.float32).reshape(-1)
    return np.concatenate([v[:_NB], np.zeros(1, np.float32), v[_NB:]])


@functools.lru_cache(maxsize=None)
def _tower_consts() -> np.ndarray:
    # host-golden Frobenius fq2 constants, converted on the RNS path
    # explicitly (the tower module's copies follow the fq facade, which
    # may be bound to the limb impl)
    from hbbft_tpu.crypto import bls381 as gold

    c = np.zeros((NROWS, _NTC), dtype=np.float32)
    c[:, 0] = _const_col(R.ONE)
    k1 = [
        [
            gold.fq2_mul(
                tower._gold_fq2_pow(tower._C3_INT, i),
                tower._gold_fq2_pow(tower._C6_INT, j),
            )
            for i in range(3)
        ]
        for j in range(2)
    ]
    kn = k1
    for n in (1, 2, 3):
        for j in range(2):
            for i in range(3):
                col = 1 + 12 * (n - 1) + 2 * (3 * j + i)
                c[:, col] = _const_col(R.from_int(kn[j][i][0]))
                c[:, col + 1] = _const_col(R.from_int(kn[j][i][1]))
        kn = [
            [
                gold.fq2_mul(gold.fq2_conj(kn[j][i]), k1[j][i])
                for i in range(3)
            ]
            for j in range(2)
        ]
    return c


# ---------------------------------------------------------------------------
# Layout: lanes (..., 79) <-> kernel rows (80, T); element pack/unpack
# ---------------------------------------------------------------------------


def _n_tiles(lanes: int, tile: int) -> int:
    return max(1, -(-lanes // tile))


def _to_rows(x: jnp.ndarray, lanes: int, width: int) -> jnp.ndarray:
    """(..., 79) → padded kernel layout (80, width)."""
    flat = jnp.asarray(x, DTYPE).reshape(lanes, R.NLIMBS).T
    z = jnp.zeros((1, lanes), dtype=DTYPE)
    rows = jnp.concatenate([flat[:_NB], z, flat[_NB:]], axis=0)
    pad = width - lanes
    return jnp.pad(rows, ((0, 0), (0, pad))) if pad else rows


def _from_rows(r: jnp.ndarray, lanes: int) -> jnp.ndarray:
    """(80, T) kernel rows → (lanes, 79)."""
    body = jnp.concatenate([r[:_NB, :lanes], r[40:, :lanes]], axis=0)
    return body.T


def _leaves(el) -> list:
    """Flatten an fq2/fq6/fq12 pytree into its Fq coefficient list, in
    the canonical order (matches tower.fq12_to_ints_batch for fq12)."""
    out = []

    def walk(x):
        if isinstance(x, tuple):
            for y in x:
                walk(y)
        else:
            out.append(x)

    walk(el)
    return out


def _fq2_of(rows):
    return (rows[0], rows[1])


def _fq6_of(rows):
    return ((rows[0], rows[1]), (rows[2], rows[3]), (rows[4], rows[5]))


def _fq12_of(rows):
    return (_fq6_of(rows[0:6]), _fq6_of(rows[6:12]))


_OF = {2: _fq2_of, 6: _fq6_of, 12: _fq12_of}


def _unpack_rows(r: jnp.ndarray, n: int) -> list:
    return [r[NROWS * i : NROWS * (i + 1)] for i in range(n)]


def _pack_element(el, lanes: int, width: int) -> jnp.ndarray:
    return jnp.concatenate(
        [_to_rows(c, lanes, width) for c in _leaves(el)], axis=0
    )


def _unpack_element(r: jnp.ndarray, n: int, lanes: int, shape) -> tuple:
    rows = _unpack_rows(r, n)
    return _OF[n]([_from_rows(c, lanes).reshape(shape) for c in rows])


# ---------------------------------------------------------------------------
# In-kernel building blocks
# ---------------------------------------------------------------------------


def _kmul(pairs, em, cm) -> list:
    """n independent Fq products in ONE `_mul_core` pass.

    Operands are CONCATENATED along the lane axis (one contiguous core
    call, no scatters — the FUSE2 lesson), multiplied, and sliced back.
    `reduced=False`: the core renormalizes its own input lanes, exactly
    as fq_rns.mul does for the stacked path — identical value flow."""
    a = jnp.concatenate([p[0] for p in pairs], axis=1)
    b = jnp.concatenate([p[1] for p in pairs], axis=1)
    out = K._mul_core(a, b, em, cm, reduced=False)
    t = pairs[0][0].shape[1]
    return [out[:, i * t : (i + 1) * t] for i in range(len(pairs))]


def _kmul2(pairs2, em, cm) -> list:
    """n independent fq2 products (Karatsuba, 3 Fq lanes each) in one
    core pass — the in-kernel tower.fq2_mul_many."""
    flat = []
    for a, b in pairs2:
        flat.extend(tower.fq2_mul_pairs(a, b))
    res = _kmul(flat, em, cm)
    return [
        tower.fq2_from_products(res[3 * i : 3 * i + 3])
        for i in range(len(pairs2))
    ]


def _reduce12(coeffs, tc, em, cm) -> list:
    """In-kernel fq.reduce_small over 6 fq2 coefficients: one Montgomery
    pass against the broadcast ONE column (value renormalization — same
    represented values as the stacked reduce_small, which is mul by ONE)."""
    arrs = [c for pair in coeffs for c in pair]
    cat = jnp.concatenate(arrs, axis=1)
    one = jnp.broadcast_to(tc[:, 0:1], cat.shape)
    red = K._mul_core(cat, one, em, cm, reduced=False)
    t = arrs[0].shape[1]
    out = [red[:, i * t : (i + 1) * t] for i in range(12)]
    return [(out[2 * i], out[2 * i + 1]) for i in range(6)]


def _fq12_mul_r(a, b, em, cm):
    """In-kernel tower.fq12_mul — 18 fq2 (54 Fq) products, one core pass."""
    return _fq12_mul_many_r([(a, b)], em, cm)[0]


def _fq12_sqr_r(a, em, cm):
    return tower.fq12_sqr_from_products(
        _kmul2(tower.fq12_sqr_pairs(a), em, cm)
    )


def _cyclo_sqr_r(a, tc, em, cm):
    """In-kernel tower.fq12_cyclo_sqr (Granger–Scott), 18 squaring lanes
    + the 12-lane value renormalization, two core passes — the chain
    step that keeps the whole x-power state in VMEM."""
    (a0, a1, a2), (b0, b1, b2) = a
    flat = []
    for x, y in ((a0, b1), (a1, b2), (a2, b0)):
        flat.extend(tower.fq2_sqr_pairs(x))
        flat.extend(tower.fq2_sqr_pairs(y))
        flat.extend(tower.fq2_sqr_pairs(tower.fq2_add(x, y)))
    res = _kmul(flat, em, cm)
    sq = [tower.fq2_sqr_from_products(res[2 * i : 2 * i + 2]) for i in range(9)]
    (x0s, y0s, s0s), (x1s, y1s, s1s), (x2s, y2s, s2s) = (
        sq[0:3],
        sq[3:6],
        sq[6:9],
    )

    def three(t):
        return tower.fq2_add(tower.fq2_add(t, t), t)

    def two(t):
        return tower.fq2_add(t, t)

    xy0 = tower.fq2_sub(tower.fq2_sub(s0s, x0s), y0s)
    xy1 = tower.fq2_sub(tower.fq2_sub(s1s, x1s), y1s)
    xy2 = tower.fq2_sub(tower.fq2_sub(s2s, x2s), y2s)

    s_a0 = tower.fq2_sub(three(tower.fq2_add(x0s, tower.fq2_mul_xi(y0s))), two(a0))
    s_b1 = tower.fq2_add(three(xy0), two(b1))
    s_a2 = tower.fq2_sub(three(tower.fq2_add(x1s, tower.fq2_mul_xi(y1s))), two(a2))
    s_b0 = tower.fq2_add(tower.fq2_mul_xi(three(xy1)), two(b0))
    s_a1 = tower.fq2_sub(three(tower.fq2_add(tower.fq2_mul_xi(x2s), y2s)), two(a1))
    s_b2 = tower.fq2_add(three(xy2), two(b2))

    out = _reduce12([s_a0, s_a1, s_a2, s_b0, s_b1, s_b2], tc, em, cm)
    return ((out[0], out[1], out[2]), (out[3], out[4], out[5]))


def _fq12_mul_many_r(ab_list, em, cm) -> list:
    """k independent fq12 products (18 fq2 pairs each) in ONE core pass."""
    flat = []
    for a, b in ab_list:
        a0, a1 = a
        b0, b1 = b
        flat += (
            tower.fq6_mul_fq2_pairs(a0, b0)
            + tower.fq6_mul_fq2_pairs(a1, b1)
            + tower.fq6_mul_fq2_pairs(
                tower.fq6_add(a0, a1), tower.fq6_add(b0, b1)
            )
        )
    res = _kmul2(flat, em, cm)
    outs = []
    for idx in range(len(ab_list)):
        r = res[18 * idx : 18 * idx + 18]
        t0 = tower.fq6_from_products(r[0:6])
        t1 = tower.fq6_from_products(r[6:12])
        mid = tower.fq6_from_products(r[12:18])
        c0 = tower.fq6_add(t0, tower.fq6_mul_by_v(t1))
        c1 = tower.fq6_sub(mid, tower.fq6_add(t0, t1))
        outs.append((c0, c1))
    return outs


def _frob3_r(y1, y2, y3, tc, em, cm):
    """frob(y1), frob²(y2), frob³(y3) in ONE 18-fq2 core round.

    Uses the host-precomputed K^{(n)} constant sets (frob^n(a)_ji =
    conj^n(a_ji)·K^{(n)}_ji), so a power-n Frobenius costs the same one
    round as a single application instead of n chained ones."""
    t = y1[0][0][0].shape[1]
    pairs = []
    for n, a in ((1, y1), (2, y2), (3, y3)):
        off = 1 + 12 * (n - 1)
        for j in range(2):
            for i in range(3):
                col = off + 2 * (3 * j + i)
                kc = (
                    jnp.broadcast_to(tc[:, col : col + 1], (NROWS, t)),
                    jnp.broadcast_to(tc[:, col + 1 : col + 2], (NROWS, t)),
                )
                aji = tower.fq2_conj(a[j][i]) if n % 2 else a[j][i]
                pairs.append((aji, kc))
    res = _kmul2(pairs, em, cm)

    def f12(r):
        return ((r[0], r[1], r[2]), (r[3], r[4], r[5]))

    return f12(res[0:6]), f12(res[6:12]), f12(res[12:18])


# ---------------------------------------------------------------------------
# Kernel: single tower operation (fq2/fq6/fq12 multiply and square)
# ---------------------------------------------------------------------------

#: kind → (coefficient count, body builder on row pytrees)
_OP_BODY = {
    "fq2_mul": (2, lambda a, b, tc, em, cm: _kmul2([(a, b)], em, cm)[0]),
    "fq2_sqr": (
        2,
        lambda a, b, tc, em, cm: tower.fq2_sqr_from_products(
            _kmul(tower.fq2_sqr_pairs(a), em, cm)
        ),
    ),
    "fq6_mul": (
        6,
        lambda a, b, tc, em, cm: tower.fq6_from_products(
            _kmul2(tower.fq6_mul_fq2_pairs(a, b), em, cm)
        ),
    ),
    "fq6_sqr": (
        6,
        # tower.fq6_sqr IS fq6_mul(a, a) — mirror it exactly
        lambda a, b, tc, em, cm: tower.fq6_from_products(
            _kmul2(tower.fq6_mul_fq2_pairs(a, a), em, cm)
        ),
    ),
    "fq12_mul": (12, lambda a, b, tc, em, cm: _fq12_mul_r(a, b, em, cm)),
    "fq12_sqr": (12, lambda a, b, tc, em, cm: _fq12_sqr_r(a, em, cm)),
    "fq12_cyclo_sqr": (
        12,
        lambda a, b, tc, em, cm: _cyclo_sqr_r(a, tc, em, cm),
    ),
}


def _op_kernel(a_ref, b_ref, em_ref, cm_ref, tc_ref, out_ref, *, kind: str):
    n, body = _OP_BODY[kind]
    em, cm, tc = em_ref[:], cm_ref[:], tc_ref[:]
    a = _OF[n](_unpack_rows(a_ref[:], n))
    b = _OF[n](_unpack_rows(b_ref[:], n))
    out = body(a, b, tc, em, cm)
    out_ref[:] = jnp.concatenate(_leaves(out), axis=0)


@functools.lru_cache(maxsize=None)
def _op_call(kind: str, n_tiles: int, tile: int, interpret: bool):
    n, _ = _OP_BODY[kind]
    rows = n * NROWS
    return pl.pallas_call(
        functools.partial(_op_kernel, kind=kind),
        out_shape=jax.ShapeDtypeStruct((rows, n_tiles * tile), DTYPE),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((rows, tile), lambda i: (0, i)),
            pl.BlockSpec((rows, tile), lambda i: (0, i)),
            pl.BlockSpec((NROWS, NROWS), lambda i: (0, 0)),
            pl.BlockSpec((NROWS, K._NCONST), lambda i: (0, 0)),
            pl.BlockSpec((NROWS, _NTC), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rows, tile), lambda i: (0, i)),
        interpret=interpret,
    )


def _tower_op(kind: str, a, b, interpret: bool):
    n, _ = _OP_BODY[kind]
    leaves = _leaves(a)
    shape = jnp.broadcast_shapes(*(jnp.shape(c) for c in _leaves((a, b))))
    batch = shape[:-1]
    lanes = int(np.prod(batch)) if batch else 1
    nt = _n_tiles(lanes, TILE)
    width = nt * TILE

    def pack(el):
        return jnp.concatenate(
            [
                _to_rows(jnp.broadcast_to(jnp.asarray(c, DTYPE), shape), lanes, width)
                for c in _leaves(el)
            ],
            axis=0,
        )

    out = _op_call(kind, nt, TILE, interpret)(
        pack(a),
        pack(b),
        jnp.asarray(K._EMAT),
        jnp.asarray(K._CONSTS),
        jnp.asarray(_tower_consts()),
    )
    del leaves
    return _unpack_element(out, n, lanes, shape)


def fq2_mul(a, b, interpret: bool = False):
    """Fused tower.fq2_mul — one kernel, 3 Fq lanes resident in VMEM."""
    return _tower_op("fq2_mul", a, b, interpret)


def fq2_sqr(a, interpret: bool = False):
    return _tower_op("fq2_sqr", a, a, interpret)


def fq6_mul(a, b, interpret: bool = False):
    """Fused tower.fq6_mul — 18 Fq lanes in one VMEM-resident pass."""
    return _tower_op("fq6_mul", a, b, interpret)


def fq6_sqr(a, interpret: bool = False):
    return _tower_op("fq6_sqr", a, a, interpret)


def fq12_mul(a, b, interpret: bool = False):
    """Fused tower.fq12_mul — the ~54 Fq muls never leave VMEM."""
    return _tower_op("fq12_mul", a, b, interpret)


def fq12_sqr(a, interpret: bool = False):
    return _tower_op("fq12_sqr", a, a, interpret)


def fq12_cyclo_sqr(a, interpret: bool = False):
    """Fused Granger–Scott cyclotomic squaring (incl. the reduce pass)."""
    return _tower_op("fq12_cyclo_sqr", a, a, interpret)


# ---------------------------------------------------------------------------
# Kernel: Miller double step (scatter-free concatenate form)
# ---------------------------------------------------------------------------


def _dbl_kernel(f_ref, r_ref, p_ref, em_ref, cm_ref, fout_ref, rout_ref):
    """One Miller doubling — f ← f²·l(R), R ← 2R — with all four stacked
    rounds of pairing._miller_double_step (48 + 18 + 7 + 45 Fq lanes)
    fused into one VMEM-resident kernel.  The recombination between the
    rounds is the exact code from pairing.py, run on row tiles."""
    em, cm = em_ref[:], cm_ref[:]
    f = _fq12_of(_unpack_rows(f_ref[:], 12))
    rr = _unpack_rows(r_ref[:], 6)
    X, Y, Z = (rr[0], rr[1]), (rr[2], rr[3]), (rr[4], rr[5])
    pp = _unpack_rows(p_ref[:], 2)
    xP, yP = pp[0], pp[1]

    res = _kmul2(
        tower.fq12_sqr_pairs(f) + [(X, X), (Y, Y), (Z, Z), (Y, Z)], em, cm
    )
    f2 = tower.fq12_sqr_from_products(res[:12])
    XX, YY, ZZ, YZ = res[12:]
    E = tower.fq2_add(tower.fq2_add(XX, XX), XX)
    XpYY = tower.fq2_add(X, YY)
    XXX, XXZZ, YZ3, C, T, Fv = _kmul2(
        [(XX, X), (XX, ZZ), (YZ, ZZ), (YY, YY), (XpYY, XpYY), (E, E)], em, cm
    )
    D = tower.fq2_sub(tower.fq2_sub(T, XX), C)
    D = tower.fq2_add(D, D)
    X3 = tower.fq2_sub(Fv, tower.fq2_add(D, D))
    C4 = tower.fq2_add(tower.fq2_add(C, C), tower.fq2_add(C, C))
    C8 = tower.fq2_add(C4, C4)

    c1a1 = tower.fq2_sub(
        tower.fq2_add(tower.fq2_add(XXX, XXX), XXX), tower.fq2_add(YY, YY)
    )
    u = tower.fq2_mul_xi(tower.fq2_add(YZ3, YZ3))
    v = tower.fq2_add(tower.fq2_add(XXZZ, XXZZ), XXZZ)

    DmX3 = tower.fq2_sub(D, X3)
    prods = _kmul(
        tower.fq2_mul_pairs(E, DmX3)
        + [(u[0], yP), (u[1], yP), (v[0], xP), (v[1], xP)],
        em,
        cm,
    )
    EDX3 = tower.fq2_from_products(prods[:3])
    c0a0 = (prods[3], prods[4])
    c1a2 = (fq.neg(prods[5]), fq.neg(prods[6]))

    Y3 = tower.fq2_sub(EDX3, C8)
    Z3p = tower.fq2_add(YZ, YZ)

    res4 = _kmul2(tower.fq12_mul_line_pairs(f2, (c0a0, c1a1, c1a2)), em, cm)
    f_new = tower.fq12_mul_line_from_products(res4)

    fout_ref[:] = jnp.concatenate(_leaves(f_new), axis=0)
    rout_ref[:] = jnp.concatenate(
        [X3[0], X3[1], Y3[0], Y3[1], Z3p[0], Z3p[1]], axis=0
    )


@functools.lru_cache(maxsize=None)
def _dbl_call(n_tiles: int, tile: int, interpret: bool):
    return pl.pallas_call(
        _dbl_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((12 * NROWS, n_tiles * tile), DTYPE),
            jax.ShapeDtypeStruct((6 * NROWS, n_tiles * tile), DTYPE),
        ),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((12 * NROWS, tile), lambda i: (0, i)),
            pl.BlockSpec((6 * NROWS, tile), lambda i: (0, i)),
            pl.BlockSpec((2 * NROWS, tile), lambda i: (0, i)),
            pl.BlockSpec((NROWS, NROWS), lambda i: (0, 0)),
            pl.BlockSpec((NROWS, K._NCONST), lambda i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((12 * NROWS, tile), lambda i: (0, i)),
            pl.BlockSpec((6 * NROWS, tile), lambda i: (0, i)),
        ),
        interpret=interpret,
    )


def miller_double_step_rows(f_rows, r_rows, p_rows, interpret: bool = False):
    """Row-layout Miller double step — ONE launch per scan iteration.

    f_rows (960, T), r_rows (480, T) = [X0 X1 Y0 Y1 Z0 Z1],
    p_rows (160, T) = [xP yP]; T must be a multiple of the build tile."""
    width = f_rows.shape[1]
    nt = width // TILE
    assert nt * TILE == width, (width, TILE)
    return _dbl_call(nt, TILE, interpret)(
        f_rows, r_rows, p_rows, jnp.asarray(K._EMAT), jnp.asarray(K._CONSTS)
    )


# ---------------------------------------------------------------------------
# Kernel: the final-exponentiation hard part (the x-chain)
# ---------------------------------------------------------------------------


def _sel12(cond, x, y):
    """Scalar-predicated fq12 register select (pointwise where)."""
    return jax.tree_util.tree_map(lambda u, v: jnp.where(cond, u, v), x, y)


def _hard_kernel(bits_ref, m_ref, em_ref, cm_ref, tc_ref, out_ref):
    """The ENTIRE final-exp hard part in one kernel, as ONE fori_loop.

    The five x-power chains of pairing.final_exponentiation_fast run as a
    single 5·nbits-step loop over a VMEM-resident register file
    (acc, base, b, y3, y2, y1) — the round-15 scan discipline: one
    compiled ~4-core-pass body, no unrolled chains.  Step s executes bit
    s % nbits of chain s // nbits (squaring + where-blended multiply, the
    proven _pow_kernel shape); at each chain boundary a lax.cond branch
    performs the glue multiply (conj(chain)·operand — the operand is ONE
    for the pure-power chains, so the register update is uniform) and
    rotates the register file.  Every value renormalization is the same
    Montgomery pass-by-ONE as the stacked path, so represented values are
    identical; the trailing Frobenius glue uses the precomputed K^{(n)}
    sets to finish in one round."""
    em, cm, tc = em_ref[:], cm_ref[:], tc_ref[:]
    m = _fq12_of(_unpack_rows(m_ref[:], 12))
    nbits = bits_ref.shape[0]
    t = m_ref.shape[1]
    zero = jnp.zeros((NROWS, t), DTYPE)
    one2 = (jnp.broadcast_to(tc[:, 0:1], (NROWS, t)), zero)
    z2 = (zero, zero)
    one12 = ((one2, z2, z2), (z2, z2, z2))

    def body(s, regs):
        acc, base, b, y3, y2, y1 = regs
        i = s % nbits
        k = s // nbits
        sq = _cyclo_sqr_r(acc, tc, em, cm)
        wm = _fq12_mul_r(sq, base, em, cm)
        acc = _sel12(bits_ref[i] > 0, wm, sq)

        def boundary(r):
            acc2, _, b2, y32, y22, y12 = r
            # chain result (BLS x is negative → conjugate), then the glue
            # multiply: ·conj(m) after chain 0 (→b), ·conj(b) after
            # chain 1 (→y3), ·conj(y3) after chain 3 (→y1); chains 2 and
            # 4 are pure powers (→y2, →y0'), i.e. a multiply by ONE.
            ca = tower.fq12_conj(acc2) if BLS_X_IS_NEG else acc2
            op = _sel12(
                k == 0,
                tower.fq12_conj(m),
                _sel12(
                    k == 1,
                    tower.fq12_conj(b2),
                    _sel12(k == 3, tower.fq12_conj(y32), one12),
                ),
            )
            val = _fq12_mul_r(ca, op, em, cm)
            return (
                val,
                val,
                _sel12(k == 0, val, b2),
                _sel12(k == 1, val, y32),
                _sel12(k == 2, val, y22),
                _sel12(k == 3, val, y12),
            )

        return jax.lax.cond(
            i == nbits - 1, boundary, lambda r: r, (acc, base, b, y3, y2, y1)
        )

    regs = jax.lax.fori_loop(0, 5 * nbits, body, (m, m, m, m, m, m))
    y0p, _, _, y3, y2, y1 = regs
    m3 = _fq12_mul_r(_cyclo_sqr_r(m, tc, em, cm), m, em, cm)
    y0 = _fq12_mul_r(y0p, m3, em, cm)
    f1, f2, f3 = _frob3_r(y1, y2, y3, tc, em, cm)
    # ((y0·F1)·F2)·F3 regrouped as (y0·F1)·(F2·F3) — same field value,
    # one fewer sequential round
    u, v = _fq12_mul_many_r([(y0, f1), (f2, f3)], em, cm)
    out = _fq12_mul_r(u, v, em, cm)
    out_ref[:] = jnp.concatenate(_leaves(out), axis=0)


@functools.lru_cache(maxsize=None)
def _hard_call(n_tiles: int, tile: int, nbits: int, interpret: bool):
    rows = 12 * NROWS
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((rows, tile), lambda i, *_: (0, i)),
            pl.BlockSpec((NROWS, NROWS), lambda i, *_: (0, 0)),
            pl.BlockSpec((NROWS, K._NCONST), lambda i, *_: (0, 0)),
            pl.BlockSpec((NROWS, _NTC), lambda i, *_: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rows, tile), lambda i, *_: (0, i)),
    )
    return pl.pallas_call(
        _hard_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, n_tiles * tile), DTYPE),
        grid_spec=grid_spec,
        interpret=interpret,
    )


def hard_exp(m, interpret: bool = False):
    """Final-exp hard part for a CYCLOTOMIC fq12 element m — one launch.

    Drop-in for the hard half of pairing.final_exponentiation_fast (the
    five `_cyclo_pow_x` chains + glue); the easy part (which needs the
    Fermat inverse) stays on the existing paths."""
    shape = jnp.shape(_leaves(m)[0])
    batch = shape[:-1]
    lanes = int(np.prod(batch)) if batch else 1
    nt = _n_tiles(lanes, TILE)
    packed = _pack_element(m, lanes, nt * TILE)
    out = _hard_call(nt, TILE, len(_X_CHAIN_BITS), interpret)(
        jnp.asarray(_X_CHAIN_BITS),
        packed,
        jnp.asarray(K._EMAT),
        jnp.asarray(K._CONSTS),
        jnp.asarray(_tower_consts()),
    )
    return _unpack_element(out, 12, lanes, shape)
