"""GF(2⁸) Reed–Solomon on TPU — erasure coding as an int8 MXU matmul.

Replaces the hot path of the reference's `reed-solomon-erasure` crate
(SURVEY.md §2.2) with a formulation that maps directly onto the TPU's MXU:

GF(2⁸) is an 8-dimensional vector space over F₂, and multiplication by a
*constant* c is an F₂-linear map — an 8×8 bit matrix M_c whose column j is
the bit decomposition of c·α^j (α = 2, the primitive element).  A full
GF(2⁸) matrix product ``out[r,l] = ⊕_i gf_mul(M[r,i], x[i,l])`` therefore
becomes a plain binary matrix product over F₂:

    out_bits[(r,b), l] = ( Σ_(i,j) Mbits[(r,b),(i,j)] · xbits[(i,j), l] ) mod 2

i.e. an ordinary (8r × 8k) @ (8k × L) **int8 matmul with int32 accumulation**
— exactly what the MXU executes natively — followed by a parity mask (& 1).
XOR-accumulation over the k dimension is free: it *is* the mod-2 of the
integer accumulation.

Both the encode matrix (parity rows) and every decode matrix (Lagrange
interpolation rows for a given erasure pattern) are constants per call site,
so the bit expansion happens once on host and the per-shard work is a single
fused unpack → matmul → parity → pack kernel under ``jit``.

Golden-tested against the numpy host codec in hbbft_tpu/crypto/erasure.py.
"""

from __future__ import annotations

import os
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from hbbft_tpu.crypto.erasure import RSCodec, gf256


def expand_gf_matrix(m: np.ndarray) -> np.ndarray:
    """Expand a GF(2⁸) matrix (r×k uint8) into its F₂ bit-matrix (8r×8k int8).

    Row-major bit layout: output bit-row ``8*r + b`` is bit ``b`` of output
    byte ``r``; input bit-column ``8*i + j`` is bit ``j`` of input byte ``i``.
    """
    gf = gf256()
    # lint: allow[deferred-fetch] host-constant prep: the input is a host
    # numpy GF matrix (encode/Lagrange rows), never a device value
    m = np.asarray(m, dtype=np.uint8)
    r, k = m.shape
    out = np.zeros((8 * r, 8 * k), dtype=np.int8)
    for j in range(8):
        # column block j: bits of m[r,i] * 2^j
        prod = gf.mul(m, np.uint8(1 << j))  # (r, k)
        for b in range(8):
            out[b::8, j::8] = (prod >> b) & 1
    return out


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """(k, L) uint8 -> (8k, L) int8 bit planes, row-major (byte, bit)."""
    k, L = x.shape
    bits = (x[:, None, :] >> jnp.arange(8, dtype=jnp.uint8)[None, :, None]) & 1
    return bits.reshape(8 * k, L).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8r, L) int -> (r, L) uint8."""
    r8, L = bits.shape
    b = bits.reshape(r8 // 8, 8, L).astype(jnp.uint8)
    return jnp.sum(b << jnp.arange(8, dtype=jnp.uint8)[None, :, None], axis=1).astype(
        jnp.uint8
    )


@jax.jit
def gf256_matmul(mbits: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """GF(2⁸) matrix product via the F₂ bit-matmul.

    mbits: (8r, 8k) int8 — ``expand_gf_matrix`` of the GF coefficient matrix.
    x:     (k, L) uint8 — shard matrix (byte columns).
    Returns (r, L) uint8.

    Two dot strategies (HBBFT_TPU_GF_DOT, read at trace time — A/B in
    separate processes like the kernel conv modes):

    * ``int8`` (default): int8×int8→int32 dot_general; parity = & 1.
    * ``bf16``: bits are trivially bf16-exact and the 8k-term counts stay
      far below 2^24, so the same contraction runs as a NATIVE bf16 MXU
      matmul with exact f32 accumulation; parity = x − 2·⌊x/2⌋ in f32.
      Candidate fix for the measured 102 MB/s on-chip int8 rate (~50×
      under the MXU roofline — suspected emulated int8 lowering; round-2
      verdict Weak #6).
    """
    xbits = _unpack_bits(x)
    if os.environ.get("HBBFT_TPU_GF_DOT", "int8") == "bf16":
        acc = jax.lax.dot_general(
            mbits.astype(jnp.bfloat16),
            xbits.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        par = acc - 2.0 * jnp.floor(acc * 0.5)
        return _pack_bits(par.astype(jnp.uint8))
    acc = jax.lax.dot_general(
        mbits,
        xbits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _pack_bits(acc & 1)


class DecodeMatrixCache:
    """Bounded LRU of bit-expanded GF(2⁸) decode matrices, keyed by
    erasure pattern.

    A decode matrix depends only on ``(xs, missing)`` — the first-k
    present shard indices and the missing indices — so every epoch that
    sees the same erasure pattern (the common case: a stable crashed-set
    repeats for many epochs) reuses one device constant.  Distinct
    patterns are combinatorially many, hence the bound: at ``capacity``
    entries the least-recently-used pattern is evicted (pinned in
    tests/test_device_rs.py).  Used by both the per-codec JaxRSCodec
    hot path and the backend-global batched plane (ops/backend.py).
    """

    def __init__(self, capacity: int = 64) -> None:
        self.capacity = capacity
        self._cache: OrderedDict = OrderedDict()

    def __len__(self) -> int:
        return len(self._cache)

    def keys(self):
        return self._cache.keys()

    def get(self, xs, missing) -> jnp.ndarray:
        """The (8·|missing| × 8·k) F₂ bit matrix mapping values at ``xs``
        to values at ``missing`` (device constant; built on miss)."""
        key = (tuple(xs), tuple(missing))
        hit = self._cache.get(key)
        if hit is not None:
            self._cache.move_to_end(key)
            return hit
        if len(self._cache) >= self.capacity:
            self._cache.popitem(last=False)
        mat = gf256().lagrange_matrix(list(xs), list(missing))
        bits = jnp.asarray(expand_gf_matrix(mat))
        self._cache[key] = bits
        return bits


class JaxRSCodec(RSCodec):
    """Systematic (k data, m parity) RS codec with a TPU matmul hot path.

    Same field, evaluation points, and API as the host
    :class:`~hbbft_tpu.crypto.erasure.RSCodec` (shards interoperate); only
    the GF(2⁸) matrix products are overridden to run as device bit-matmuls.
    Decode matrices (one per erasure pattern) are bit-expanded lazily and
    kept in a small LRU cache (:class:`DecodeMatrixCache`).
    """

    _DECODE_CACHE_MAX = 64

    def __init__(self, data_shards: int, parity_shards: int) -> None:
        super().__init__(data_shards, parity_shards)
        self._encode_bits = jnp.asarray(expand_gf_matrix(self.encode_matrix))
        self._decode_cache = DecodeMatrixCache(self._DECODE_CACHE_MAX)

    def encode_matrix_fn(self):
        """The jitted parity kernel: (k, L) uint8 → (m, L) uint8."""
        bits = self._encode_bits
        return lambda mat: gf256_matmul(bits, mat)

    # -- overridden matrix products ------------------------------------------

    def _parity(self, mat: np.ndarray) -> np.ndarray:
        # lint: allow[deferred-fetch] synchronous golden/bench entry point —
        # the engine's hot path routes through ops/backend.py, which fetches
        # via the DispatchPipeline seam
        return np.asarray(gf256_matmul(self._encode_bits, jnp.asarray(mat)))

    def _interpolate(self, xs, missing, stack: np.ndarray) -> np.ndarray:
        bits = self._decode_cache.get(xs, missing)
        # lint: allow[deferred-fetch] synchronous golden/bench entry point —
        # the engine's hot path routes through ops/backend.py, which fetches
        # via the DispatchPipeline seam
        return np.asarray(gf256_matmul(bits, jnp.asarray(stack)))
