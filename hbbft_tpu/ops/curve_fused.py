"""Fused Pallas kernel for Jacobian scalar-multiplication ladders.

curve.scalar_mul lowers to a lax.scan whose every iteration dispatches
~8 stacked multiplies (3 for the doubling, 5 for the add) — ~2,200
Pallas calls for a 254-bit G2 ladder, ~1,100 for the 128-bit
random-linear-combination coefficients the backend's grouped
verification uses.  At the measured ~100 µs fixed cost per call
(PERF.md) the ladder is >95% launch overhead at protocol batch sizes.

This kernel runs the WHOLE double-and-add-always ladder in one launch:
an in-kernel ``fori_loop`` over the bit rows, per-lane bit masks read
from a VMEM (nbits, TILE) block, Jacobian state held limbs-first in
VMEM throughout.  One implementation serves both groups — the
coordinate field is a tuple of 1 (Fq, G1) or 2 (Fq2, G2) limb planes,
mirroring curve.py's field-namespace parameterization.

Infinity handling matches curve.py exactly: an explicit mask lane
(carried as a broadcast row) with total formulas and selects — the
accumulator starts at infinity and the add's select chain handles the
first set bit.

Golden-tested against curve.scalar_mul in interpret mode
(tests/test_curve_fused.py).  Reference analogue: scalar multiplication
inside `threshold_crypto`'s `pairing` crate (SURVEY.md §2.2).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hbbft_tpu.ops import fq
from hbbft_tpu.ops import pairing_fused as _pf
from hbbft_tpu.ops.fq_pallas import _FOLD_T
from hbbft_tpu.ops.pairing_fused import _algebra, _scratch


def _use() -> bool:
    # Opt-in; precedence rule lives in fq._use_fused.  The on-chip A/B
    # (PERF.md "Round-2 sixth pass") measured the scan-form ladder faster
    # than this fused kernel (g2_sign 7,001/s unfused; the fused default
    # path trailed on every RLC metric).
    return fq._use_fused()


# ---------------------------------------------------------------------------
# Generic degree-k coordinate algebra: a coordinate is a k-tuple of
# (NLIMBS, T) limb planes; k = 1 for Fq (G1), k = 2 for Fq2 (G2).
# ---------------------------------------------------------------------------


def _field(k: int, m, m2):
    if k == 1:
        mul = lambda a, b: (m(a[0], b[0]),)  # noqa: E731
    else:
        mul = m2  # Karatsuba fq2 (tuple in, tuple out)

    add = lambda a, b: tuple(x + y for x, y in zip(a, b))  # noqa: E731
    sub = lambda a, b: tuple(x - y for x, y in zip(a, b))  # noqa: E731

    def sel(mask, a, b):  # mask: (1, T) 0/1 floats
        return tuple(jnp.where(mask > 0, x, y) for x, y in zip(a, b))

    return mul, add, sub, sel


def _jac_double(F, P):
    """curve.jac_double formulas on tuple coordinates."""
    mul, add, sub, _ = F
    X, Y, Z, inf = P
    A = mul(X, X)
    B = mul(Y, Y)
    YZ = mul(Y, Z)
    E = add(add(A, A), A)
    C = mul(B, B)
    t = mul(add(X, B), add(X, B))
    Fv = mul(E, E)
    D2 = sub(sub(t, A), C)
    D = add(D2, D2)
    X3 = sub(Fv, add(D, D))
    C4 = add(add(C, C), add(C, C))
    C8 = add(C4, C4)
    EDX3 = mul(E, sub(D, X3))
    Y3 = sub(EDX3, C8)
    Z3 = add(YZ, YZ)
    return (X3, Y3, Z3, inf)


def _jac_add(F, P, Q):
    """curve.jac_add (unequal add; infinity via mask selects)."""
    mul, add, sub, sel = F
    X1, Y1, Z1, inf1 = P
    X2, Y2, Z2, inf2 = Q
    Z1Z1 = mul(Z1, Z1)
    Z2Z2 = mul(Z2, Z2)
    Y1Z2 = mul(Y1, Z2)
    Y2Z1 = mul(Y2, Z1)
    Z1Z2 = mul(Z1, Z2)
    U1 = mul(X1, Z2Z2)
    U2 = mul(X2, Z1Z1)
    S1 = mul(Y1Z2, Z2Z2)
    S2 = mul(Y2Z1, Z1Z1)
    H = sub(U2, U1)
    Rr = sub(S2, S1)
    H2 = mul(H, H)
    Z3 = mul(Z1Z2, H)
    H3 = mul(H, H2)
    U1H2 = mul(U1, H2)
    R2 = mul(Rr, Rr)
    X3 = sub(sub(R2, H3), add(U1H2, U1H2))
    RY = mul(Rr, sub(U1H2, X3))
    S1H3 = mul(S1, H3)
    Y3 = sub(RY, S1H3)

    X3 = sel(inf1, X2, sel(inf2, X1, X3))
    Y3 = sel(inf1, Y2, sel(inf2, Y1, Y3))
    Z3 = sel(inf1, Z2, sel(inf2, Z1, Z3))
    return (X3, Y3, Z3, inf1 * inf2)


def _ladder_kernel(k: int, p_ref, bits_ref, fold_ref, out_ref, acc_ref=None):
    m, m2, _sq2 = _algebra(fold_ref[:], acc_ref)
    F = _field(k, m, m2)
    t = p_ref.shape[-1]

    def coord(ref, base):
        return tuple(ref[base + j] for j in range(k))

    P = (
        coord(p_ref, 0),
        coord(p_ref, k),
        coord(p_ref, 2 * k),
        p_ref[3 * k][0:1, :],  # inf mask row (1, T)
    )

    zero = jnp.zeros((fq.NLIMBS, t), dtype=fq.DTYPE)
    # ONE = [1, 0, 0, ...] built in-kernel (captured array constants are
    # rejected by pallas_call; an iota row mask is free).
    row = jax.lax.broadcasted_iota(jnp.int32, (fq.NLIMBS, t), 0)
    onev = jnp.where(row == 0, 1.0, 0.0).astype(fq.DTYPE)
    acc0 = (
        (zero,) * k,
        (onev,) + (zero,) * (k - 1),
        (zero,) * k,
        jnp.ones((1, t), dtype=fq.DTYPE),  # starts at infinity
    )

    nbits = bits_ref.shape[0]

    def body(i, acc):
        acc = _jac_double(F, acc)
        cand = _jac_add(F, acc, P)
        b = bits_ref[pl.ds(i, 1), :]  # (1, T) per-lane bit mask
        sel = F[3]
        return (
            sel(b, cand[0], acc[0]),
            sel(b, cand[1], acc[1]),
            sel(b, cand[2], acc[2]),
            jnp.where(b > 0, cand[3], acc[3]),
        )

    acc = jax.lax.fori_loop(0, nbits, body, acc0)

    for j in range(k):
        out_ref[j] = acc[0][j]
        out_ref[k + j] = acc[1][j]
        out_ref[2 * k + j] = acc[2][j]
    out_ref[3 * k] = jnp.broadcast_to(acc[3], (fq.NLIMBS, t))


@functools.lru_cache(maxsize=None)
def _ladder_call(k: int, nbits: int, n_tiles: int, interpret: bool, tile: int):
    rows = 3 * k + 1
    return pl.pallas_call(
        functools.partial(_ladder_kernel, k),
        out_shape=jax.ShapeDtypeStruct(
            (rows, fq.NLIMBS, n_tiles * tile), fq.DTYPE
        ),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((rows, fq.NLIMBS, tile), lambda i: (0, 0, i)),
            pl.BlockSpec((nbits, tile), lambda i: (0, i)),
            pl.BlockSpec(
                (fq.NLIMBS, fq.CONV - fq.FOLD_FROM), lambda i: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (rows, fq.NLIMBS, tile), lambda i: (0, 0, i)
        ),
        scratch_shapes=_scratch(),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# XLA-side wrapper: curve.py point pytrees in/out.
# ---------------------------------------------------------------------------


def _leaves(coordinate, k):
    return list(coordinate) if k == 2 else [coordinate]


def scalar_mul(k: int, bits: jnp.ndarray, P, interpret: bool | None = None):
    """Fused drop-in for curve.scalar_mul.

    ``k`` is the coordinate-field degree (1 = G1, 2 = G2); ``P`` the
    curve.py Jacobian point pytree; ``bits`` (B, nbits) MSB-first.
    """
    X, Y, Z, inf = P
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lanes = jnp.shape(bits)[0]
    n_tiles = max(1, -(-lanes // _pf.TILE))
    pad = n_tiles * _pf.TILE - lanes

    leaves = _leaves(X, k) + _leaves(Y, k) + _leaves(Z, k)
    inf_plane = jnp.broadcast_to(
        jnp.asarray(inf, fq.DTYPE)[:, None], (lanes, fq.NLIMBS)
    )
    stacked = _pf.pack_rows(leaves + [inf_plane], lanes)
    bits_cols = jnp.asarray(bits, fq.DTYPE).T  # (nbits, lanes)
    if pad:
        bits_cols = jnp.pad(bits_cols, ((0, 0), (0, pad)))

    nbits = int(jnp.shape(bits)[1])
    out = _ladder_call(k, nbits, n_tiles, interpret, _pf.TILE)(
        stacked, bits_cols, jnp.asarray(_FOLD_T)
    )

    g = lambda r: out[r, :, :lanes].T  # noqa: E731

    def coord(base):
        if k == 1:
            return g(base)
        return (g(base), g(base + 1))

    inf_out = out[3 * k, 0, :lanes] > 0.5
    return (coord(0), coord(k), coord(2 * k), inf_out)
