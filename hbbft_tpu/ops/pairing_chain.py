"""Fused pairing chain: the verification graph on VMEM-resident kernels.

This is the orchestration layer over ops/tower_fused.py — the Miller
loop, product merge and final exponentiation of ops/pairing.py rebuilt so
that the heavy tower arithmetic runs inside the fused kernels:

* `miller_loop_fused`: ONE `lax.scan` over the 63 bits of |x| whose body
  launches the fused double-step kernel (all four stacked rounds of
  `pairing._miller_double_step` in one VMEM-resident pass) on a ROW-layout
  carry (f (960, W), R (480, W)).  The rare addition step (5 of 63 bits)
  stays the existing stacked-XLA path behind a `lax.cond` — it converts
  rows→lanes, runs `pairing._miller_add_step`, and converts back, so it
  only *executes* on set bits.
* `final_exponentiation_fast_fused`: the easy part stays on the stacked
  path (it needs the Fermat inverse), the whole hard part is ONE
  `tower_fused.hard_exp` launch (the 5·63-step x-chain register loop).
* `miller_product_fused` / `product2_fast_fused`: same merge policy and
  A/B switches as the unfused `miller_product` (HBBFT_TPU_NO_MERGE,
  rank/batch fallbacks); cross-pair merge multiplies ride the fused
  fq12_mul kernel.

Every kernel reuses the exact recombination code of ops/tower.py and the
`fq_rns_pallas` Montgomery core, so represented values are identical to
the unfused graph (the tests assert bit-for-bit equality on canonical
readback) — the kill switch HBBFT_TPU_NO_FUSED_TOWER restores the
unfused graphs exactly.

Analytic dispatch model (counter-asserted in tests): per merged 2-pair
verification graph the stacked composition launches one Pallas multiply
per stacked round —

    63 doubles × 4 rounds + 5 adds × 11 rounds     = 307   (Miller)
    1 cross-pair merge                             = 1
    ~12 rounds easy part                           = 12
    5 chains × (63×2 rounds/sqr + ~6 set-bit muls) = 660   (hard part)

while the fused chain launches 63 double-step kernels + the same 55
add rounds + 1 merge + the same ~12 easy rounds + ONE hard-part kernel —
a ≥3× drop in per-verification device dispatches (measured ≈7×).
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.bls381 import BLS_X_IS_NEG
from hbbft_tpu.ops import pairing, tower
from hbbft_tpu.ops import tower_fused as tf
from hbbft_tpu.ops.tower_fused import fused_tower_mode  # noqa: F401  (re-export)


def resolve_mode(fused=None):
    """Normalize a per-call routing override to None|"native"|"interpret".

    ``None`` consults the env ladder (`tower_fused.fused_tower_mode`);
    ``False`` forces the unfused graph; an explicit mode string wins."""
    if fused is False:
        return None
    if isinstance(fused, str):
        return fused
    return fused_tower_mode()


# ---------------------------------------------------------------------------
# Analytic dispatch/throughput model (see module docstring for derivation)
# ---------------------------------------------------------------------------

_N_BITS = len(pairing._X_BITS)  # 63
_N_ADDS = sum(pairing._X_BITS)  # 5 set bits of |x| below the MSB
_DBL_ROUNDS = 4  # pairing._miller_double_step stacked multiplies
_ADD_ROUNDS = 11  # _line_add 5 + jac_add 5 + fq12_mul_line 1
_EASY_ROUNDS = 12  # conj-free: inv (~10 stacked rounds) + frob² + mul
_CHAIN_ROUNDS = 5 * (2 * _N_BITS + 6)  # 5 chains; cyclo sqr = mul+reduce
_HARD_GLUE_ROUNDS = 10  # b/y3/y1/y0 muls, m3, 3 frobenius, 2 final muls

#: Fq multiplies inside one fused double-step launch (48+18+7+45 lanes).
DBL_FIELD_MULS = 118
#: Fq multiplies inside the fused hard-part kernel: 5·63 loop steps of
#: cyclo-sqr (18+12 reduce) + branch-free blend multiply (54), 5 boundary
#: glue multiplies, and the m3/y0/frobenius/final tail.
HARD_FIELD_MULS = 5 * _N_BITS * (30 + 54) + 5 * 54 + 84 + 54 + 54 + 108 + 54


def analytic_pallas_calls(n_pairs: int = 2, fused: bool = False) -> int:
    """Device kernel launches per verification graph (merged Miller)."""
    shared = _N_ADDS * _ADD_ROUNDS + (n_pairs - 1) + _EASY_ROUNDS
    if fused:
        return _N_BITS + shared + 1  # dbl launches + add/easy/merge + hard
    return _N_BITS * _DBL_ROUNDS + shared + _CHAIN_ROUNDS + _HARD_GLUE_ROUNDS


def analytic_chain_field_muls(n_items: int, n_pairs: int = 2) -> int:
    """Fq multiplies executed INSIDE the fused kernels for ``n_items``
    verifications — the numerator of the fused-chain muls/s metric."""
    per_item = n_pairs * _N_BITS * DBL_FIELD_MULS + (n_pairs - 1) * 54
    return n_items * (per_item + HARD_FIELD_MULS)


# ---------------------------------------------------------------------------
# Fused Miller loop
# ---------------------------------------------------------------------------


def miller_loop_fused(P, Qa, mode: str = "native"):
    """`pairing.miller_loop` with the doubling step on the fused kernel.

    The scan carry lives in kernel ROW layout (f (960, W), R (480, W)) so
    the dominant path — 63 doubling steps — is one kernel launch per bit
    with no relayout; only the 5 set-bit addition steps convert to lane
    layout for the existing stacked `_miller_add_step` and back."""
    interpret = mode == "interpret"
    xP, yP, infP = P
    xQ, yQ, infQ = Qa
    shape = jnp.asarray(xP).shape
    batch_shape = shape[:-1]
    lanes = int(np.prod(batch_shape)) if batch_shape else 1
    width = tf._n_tiles(lanes, tf.TILE) * tf.TILE

    one2 = tower.fq2_broadcast(tower.FQ2_ONE, batch_shape)
    inf0 = jnp.zeros(batch_shape, dtype=bool)
    Qj = (xQ, yQ, one2, inf0)

    def pack(el):
        return jnp.concatenate(
            [tf._to_rows(c, lanes, width) for c in tf._leaves(el)], axis=0
        )

    def unpack_f(fr):
        return tf._unpack_element(fr, 12, lanes, shape)

    def unpack_R(rr):
        c = [
            tf._from_rows(x, lanes).reshape(shape)
            for x in tf._unpack_rows(rr, 6)
        ]
        return ((c[0], c[1]), (c[2], c[3]), (c[4], c[5]), inf0)

    p_rows = pack((jnp.asarray(xP), jnp.asarray(yP)))
    f_rows = pack(tower.fq12_broadcast_one(batch_shape))
    r_rows = pack(((xQ, yQ), one2))
    bits = jnp.asarray(pairing._X_BITS, dtype=jnp.bool_)

    def body(carry, bit):
        fr, rr = carry
        fr, rr = tf.miller_double_step_rows(fr, rr, p_rows, interpret)

        def add(c):
            f, Rj = unpack_f(c[0]), unpack_R(c[1])
            f2, R2 = pairing._miller_add_step(f, Rj, Qa, Qj, xP, yP)
            return pack(f2), pack((R2[0], R2[1], R2[2]))

        fr, rr = jax.lax.cond(bit, add, lambda c: c, (fr, rr))
        return (fr, rr), None

    (f_rows, _), _ = jax.lax.scan(body, (f_rows, r_rows), bits)
    f = unpack_f(f_rows)
    if BLS_X_IS_NEG:
        f = tower.fq12_conj(f)
    neutral = infP | infQ
    return tower.fq12_select(
        neutral, tower.fq12_broadcast_one(batch_shape), f
    )


def miller_product_fused(pairs, mode: str = "native"):
    """`pairing.miller_product` on the fused loop — same merge policy
    (stack along the leading axis when every pair is batched with one
    common batch size; HBBFT_TPU_NO_MERGE and rank mismatches fall back
    to sequential loops), cross-pair merges on the fused fq12_mul."""
    interpret = mode == "interpret"
    if len(pairs) == 1:
        return miller_loop_fused(*pairs[0], mode=mode)

    ranks = {jnp.ndim(p[0][0]) for p in pairs}
    batches = {jnp.shape(p[0][0])[0] for p in pairs}
    if (
        ranks != {2}
        or len(batches) != 1
        or os.environ.get("HBBFT_TPU_NO_MERGE")
    ):
        f = None
        for P, Qa in pairs:
            fk = miller_loop_fused(P, Qa, mode=mode)
            f = fk if f is None else tf.fq12_mul(f, fk, interpret=interpret)
        return f

    def cat(leaves):
        return jnp.concatenate([jnp.asarray(c) for c in leaves], axis=0)

    P = jax.tree_util.tree_map(lambda *cs: cat(cs), *[p for p, _ in pairs])
    Qa = jax.tree_util.tree_map(lambda *cs: cat(cs), *[q for _, q in pairs])
    f_all = miller_loop_fused(P, Qa, mode=mode)
    batch = jnp.shape(pairs[0][0][0])[0]
    parts = [
        jax.tree_util.tree_map(lambda c: c[i * batch : (i + 1) * batch], f_all)
        for i in range(len(pairs))
    ]
    f = parts[0]
    for fk in parts[1:]:
        f = tf.fq12_mul(f, fk, interpret=interpret)
    return f


def final_exponentiation_fast_fused(f, mode: str = "native"):
    """`pairing.final_exponentiation_fast` with the hard part as ONE
    kernel launch.  The easy part stays stacked (it needs the Fermat
    inverse, which already rides the round-2 fused pow kernel)."""
    m = tower.fq12_mul(tower.fq12_conj(f), tower.fq12_inv(f))
    m = tower.fq12_mul(tower.fq12_frobenius_n(m, 2), m)
    return tf.hard_exp(m, interpret=(mode == "interpret"))


def product2_fast_fused(P1, Q1, P2, Q2, mode: str = "native"):
    """Fused-chain `pairing.product2_fast` — same represented values."""
    return final_exponentiation_fast_fused(
        miller_product_fused([(P1, Q1), (P2, Q2)], mode=mode), mode=mode
    )
