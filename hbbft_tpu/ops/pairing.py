"""Batched BLS12-381 optimal-ate pairing for TPU.

The verification workhorse (SURVEY.md §3.2 "PAIRING CHECK — HOTTEST LOOP").
Everything is batched over a leading axis and built from the golden-tested
limb/tower/curve kernels — there are **no inversions and no data-dependent
branches** in the Miller loop:

* The G2 ladder point R runs in Jacobian coordinates via the already-tested
  `curve.jac_double` / `curve.jac_add`.
* Line functions are derived from R's Jacobian coordinates directly, each
  scaled by a per-step Fq2 factor (2YZ³ for doubling, (x_Q·Z² − X)·Z for
  addition).  Fq2 factors lie in a proper subfield killed by the final
  exponentiation, so the pairing value is unchanged — the standard trick
  that removes every division.
* Untwist convention matches crypto/bls381.py: ψ(x', y') = (x'/w², y'/w³),
  ξ = 1 + u, w⁶ = ξ.  A line through R' with twist-slope λ' evaluated at
  P = (x_P, y_P) ∈ G1 becomes (after scaling by ξ):

      l = ξ·y_P  +  (λ'·x' − y')·w³  −  λ'·x_P·w⁵

  whose Fq12 coordinates are c0 = ((ξ·y_P), 0, 0), c1 = (0, λ'x'−y',
  −λ'x_P) in the (v^i·w^j) basis — i.e. a sparse element.
* The final exponentiation does the easy part structurally (conjugate,
  one inverse, Frobenius²) and the hard part as a fixed-exponent scan
  ((Q⁴−Q²+1)/R); a cyclotomic x-chain is a later optimization — the plain
  chain is golden-testable directly against bls381.pairing.

The product form `miller_product` multiplies several pairings' Miller
values per item before one shared final exponentiation — this is what
makes batched share verification cheap (e(a,b)==e(c,d) becomes
FE(ML(a,b)·ML(−c,d)) == 1, two Miller loops and ONE final exp).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.bls381 import BLS_X, BLS_X_IS_NEG
from hbbft_tpu.crypto.field import Q, R as SUBGROUP_R
from hbbft_tpu.ops import curve, fq, tower


# Exponents for the final exponentiation.
_EASY_DONE_HARD = (Q**4 - Q**2 + 1) // SUBGROUP_R

# Miller bit schedule: MSB of |x| is implicit; iterate remaining bits.
_X_BITS = [int(b) for b in bin(BLS_X)[3:]]


# ---------------------------------------------------------------------------
# Host <-> device affine points
# ---------------------------------------------------------------------------


def g1_affine_to_device(
    points: Sequence[Optional[Tuple[int, int]]], cache=None, gather=None
):
    """Affine G1 ints (or None) → (x, y, inf) limb batch.

    ``cache`` (an ops/staging.StagingCache) replaces the per-call limb
    conversion with a cross-call value-keyed row lookup — repeated key
    material (public key shares, generators, H2 points) is converted
    once per era instead of once per dispatch.

    ``gather`` (a numpy int index array) expands the converted DISTINCT
    rows to the full lane width host-side — numpy fancy indexing before
    ``jnp.asarray``, so replicated lanes never pay per-lane conversion
    NOR an unjitted device gather (whose per-shape compiles would dwarf
    the saving)."""
    conv = cache.rows if cache is not None else fq.from_ints
    g = (lambda a: a[gather]) if gather is not None else (lambda a: a)
    xs = g(conv([(p[0] if p else 0) for p in points]))
    ys = g(conv([(p[1] if p else 1) for p in points]))
    inf = g(np.array([p is None for p in points]))
    return (jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(inf))


def g2_affine_to_device(points, cache=None, gather=None):
    """Affine G2 tuples (or None) → (x fq2, y fq2, inf) batch."""
    conv = cache.rows if cache is not None else fq.from_ints
    g = (lambda a: a[gather]) if gather is not None else (lambda a: a)
    X = (
        g(conv([(p[0][0] if p else 0) for p in points])),
        g(conv([(p[0][1] if p else 0) for p in points])),
    )
    Y = (
        g(conv([(p[1][0] if p else 1) for p in points])),
        g(conv([(p[1][1] if p else 0) for p in points])),
    )
    inf = g(np.array([p is None for p in points]))
    return (
        tuple(jnp.asarray(c) for c in X),
        tuple(jnp.asarray(c) for c in Y),
        jnp.asarray(inf),
    )


def g1_neg_device(P):
    x, y, inf = P
    return (x, fq.neg(y), inf)


# ---------------------------------------------------------------------------
# Line evaluations from Jacobian R
# ---------------------------------------------------------------------------


def _line_double(Rj, xP, yP):
    """Line for the doubling step, scaled by 2YZ³ (Fq2 factor).

    l = 2YZ³·ξ·y_P + (3X³ − 2Y²)·w³ − 3X²Z²·x_P·w⁵
    Returns fq2 coefficients (c0a0, c1a1, c1a2).
    """
    X, Y, Z, _ = Rj
    XX, YY, ZZ = tower.fq2_mul_many([(X, X), (Y, Y), (Z, Z)])
    Z3, XXX, XXZZ = tower.fq2_mul_many([(ZZ, Z), (XX, X), (XX, ZZ)])
    (YZ3,) = tower.fq2_mul_many([(Y, Z3)])
    c1a1 = tower.fq2_sub(
        tower.fq2_add(tower.fq2_add(XXX, XXX), XXX),
        tower.fq2_add(YY, YY),
    )
    u = tower.fq2_mul_xi(tower.fq2_add(YZ3, YZ3))
    v = tower.fq2_add(tower.fq2_add(XXZZ, XXZZ), XXZZ)
    # The two Fq-scalar coefficient muls share one stacked multiply.
    p = fq.mul_n([(u[0], yP), (u[1], yP), (v[0], xP), (v[1], xP)])
    c0a0 = (p[0], p[1])
    c1a2 = (fq.neg(p[2]), fq.neg(p[3]))
    return (c0a0, c1a1, c1a2)


def _line_add(Rj, Qa, xP, yP):
    """Line for the mixed-addition step R + Q, scaled by D = (x_Q·Z² − X)·Z.

    With N = y_Q·Z³ − Y (so twist slope λ' = N/D):
      l = ξ·y_P·D + (N·x_Q − y_Q·D)·w³ − N·x_P·w⁵
    """
    X, Y, Z, _ = Rj
    xQ, yQ, _ = Qa
    (ZZ,) = tower.fq2_mul_many([(Z, Z)])
    Z3, xQZZ = tower.fq2_mul_many([(ZZ, Z), (xQ, ZZ)])
    yQZ3, D = tower.fq2_mul_many([(yQ, Z3), (tower.fq2_sub(xQZZ, X), Z)])
    N = tower.fq2_sub(yQZ3, Y)
    NxQ, yQD = tower.fq2_mul_many([(N, xQ), (yQ, D)])
    c1a1 = tower.fq2_sub(NxQ, yQD)
    u = tower.fq2_mul_xi(D)
    p = fq.mul_n([(u[0], yP), (u[1], yP), (N[0], xP), (N[1], xP)])
    c0a0 = (p[0], p[1])
    c1a2 = (fq.neg(p[2]), fq.neg(p[3]))
    return (c0a0, c1a1, c1a2)


def _line_to_fq12(coeffs):
    """(c0a0, c1a1, c1a2) sparse line → full fq12 element."""
    c0a0, c1a1, c1a2 = coeffs
    zero = tuple(jnp.zeros_like(jnp.asarray(c)) for c in c0a0)
    c0 = (c0a0, zero, zero)
    c1 = (zero, c1a1, c1a2)
    return (c0, c1)


# ---------------------------------------------------------------------------
# Miller loop (batched over leading axis)
# ---------------------------------------------------------------------------


def _miller_double_step(f, Rj, xP, yP):
    """One Miller doubling — f ← f²·l(R), R ← 2R — in FOUR stacked
    multiplies (~121 Fq lanes), sharing every intermediate between the
    line evaluation and the Jacobian doubling (they both need X², Y²,
    Z², Y·Z, X³, X²Z², Y·Z³):

      round 1: the 12 fq2 products of f² + X², Y², Z², Y·Z
      round 2: Z³, X³, X²Z², YZ³, Y⁴, (X+Y²)², E² (E = 3X²)
      round 3: E·(D−X₃) + the four Fq line-coefficient scalings
      round 4: the 15 fq2 products of the sparse line multiply

    Replaces the unfused path (separate fq12_sqr / _line_double /
    jac_double / full fq12_mul ≈ 9 calls, 136 lanes).
    """
    X, Y, Z, inf = Rj
    res = tower.fq2_mul_many(
        tower.fq12_sqr_pairs(f) + [(X, X), (Y, Y), (Z, Z), (Y, Z)]
    )
    f2 = tower.fq12_sqr_from_products(res[:12])
    XX, YY, ZZ, YZ = res[12:]
    E = tower.fq2_add(tower.fq2_add(XX, XX), XX)  # 3X²
    XpYY = tower.fq2_add(X, YY)
    XXX, XXZZ, YZ3, C, T, Fv = tower.fq2_mul_many(
        [
            (XX, X),
            (XX, ZZ),
            (YZ, ZZ),  # Y·Z³ as (YZ)·Z² — Z³ itself is never needed
            (YY, YY),
            (XpYY, XpYY),
            (E, E),
        ]
    )
    D = tower.fq2_sub(tower.fq2_sub(T, XX), C)
    D = tower.fq2_add(D, D)  # 2((X+Y²)² − X² − Y⁴)
    X3 = tower.fq2_sub(Fv, tower.fq2_add(D, D))
    C4 = tower.fq2_add(tower.fq2_add(C, C), tower.fq2_add(C, C))
    C8 = tower.fq2_add(C4, C4)

    # Line l = 2YZ³·ξ·y_P + (3X³ − 2Y²)·w³ − 3X²Z²·x_P·w⁵ (see _line_double)
    c1a1 = tower.fq2_sub(
        tower.fq2_add(tower.fq2_add(XXX, XXX), XXX), tower.fq2_add(YY, YY)
    )
    u = tower.fq2_mul_xi(tower.fq2_add(YZ3, YZ3))
    v = tower.fq2_add(tower.fq2_add(XXZZ, XXZZ), XXZZ)

    DmX3 = tower.fq2_sub(D, X3)
    prods = fq.mul_n(
        tower.fq2_mul_pairs(E, DmX3)
        + [(u[0], yP), (u[1], yP), (v[0], xP), (v[1], xP)]
    )
    EDX3 = tower.fq2_from_products(prods[:3])
    c0a0 = (prods[3], prods[4])
    c1a2 = (fq.neg(prods[5]), fq.neg(prods[6]))

    Y3 = tower.fq2_sub(EDX3, C8)
    Z3p = tower.fq2_add(YZ, YZ)
    Rj2 = (X3, Y3, Z3p, inf)

    f_new = tower.fq12_mul_line(f2, (c0a0, c1a1, c1a2))
    return f_new, Rj2


def _miller_add_step(f, Rj, Qa, Qj, xP, yP):
    """One Miller mixed addition — f ← f·l(R, Q), R ← R + Q.  Only runs
    at the set bits of |x| (5 of 63 for BLS12-381), so it reuses the
    generic line/add helpers plus the sparse line multiply."""
    line = _line_add(Rj, Qa, xP, yP)
    R2 = curve.jac_add(curve._F2, Rj, Qj)
    return tower.fq12_mul_line(f, line), R2


def miller_loop(P, Qa):
    """f_{|x|,Q}(P), conjugated for x < 0 — batched.

    P = (xP, yP, infP) limb batch; Qa = (xQ fq2, yQ fq2, infQ).
    Items with an infinite P or Q yield f = 1.

    ONE scan over the 63 bits of |x|; the body always runs the fused
    doubling step, and the addition path sits behind a ``lax.cond`` so it
    only *executes* at the 5 set bits — the previous body computed the
    addition unconditionally and selected it away, wasting roughly the
    doubling path's cost again on 58 of 63 iterations.  (A host-side
    segmented unrolling achieved the same arithmetic but blew the XLA
    CPU compiler up on larger composed graphs.)
    """
    xP, yP, infP = P
    xQ, yQ, infQ = Qa
    batch_shape = jnp.asarray(xP).shape[:-1]

    one2 = tower.fq2_broadcast(tower.FQ2_ONE, batch_shape)
    Rj0 = (xQ, yQ, one2, jnp.zeros(batch_shape, dtype=bool))
    Qj = (xQ, yQ, one2, jnp.zeros(batch_shape, dtype=bool))

    bits = jnp.asarray(_X_BITS, dtype=jnp.bool_)

    def body(carry, bit):
        fc, Rc = carry
        fc, Rc = _miller_double_step(fc, Rc, xP, yP)
        fc, Rc = jax.lax.cond(
            bit,
            lambda c: _miller_add_step(c[0], c[1], Qa, Qj, xP, yP),
            lambda c: c,
            (fc, Rc),
        )
        return (fc, Rc), None

    carry = (tower.fq12_broadcast_one(batch_shape), Rj0)
    (f, _), _ = jax.lax.scan(body, carry, bits)

    if BLS_X_IS_NEG:
        f = tower.fq12_conj(f)

    # Neutralize infinite inputs.
    neutral = infP | infQ
    return tower.fq12_select(neutral, tower.fq12_broadcast_one(batch_shape), f)


def miller_product(pairs):
    """Π_k ML(P_k, Q_k) per item — pairs is a list of (P, Qa) batches.

    The k loops are fused into ONE batched scan by stacking the pairs
    along the leading axis: same arithmetic, but every stacked limb
    multiply carries k× the lanes and the scan runs once instead of k
    times.  The kernel's throughput rises steeply with lane count in
    this regime (measured 33→89 M muls/s from 4k→16k lanes on a v5e),
    so for the k=2 verification shape this is close to a 2× win over
    sequential loops.
    """
    if len(pairs) == 1:
        return miller_loop(*pairs[0])

    # The stacked scan needs every pair batched (rank ≥ 2 leaves) with one
    # common batch size; anything else falls back to sequential loops
    # rather than silently concatenating along the wrong axis.  The merge
    # is graph-level (a concatenate, no Pallas compile risk), so it has
    # its own A/B switch (HBBFT_TPU_NO_MERGE=1) independent of the fused-
    # kernel opt-in — lane-count scaling makes it a win on every path.
    ranks = {jnp.ndim(p[0][0]) for p in pairs}
    batches = {jnp.shape(p[0][0])[0] for p in pairs}
    if (
        ranks != {2}
        or len(batches) != 1
        or os.environ.get("HBBFT_TPU_NO_MERGE")
    ):
        f = None
        for P, Qa in pairs:
            fk = miller_loop(P, Qa)
            f = fk if f is None else tower.fq12_mul(f, fk)
        return f

    def cat(leaves):
        return jnp.concatenate([jnp.asarray(c) for c in leaves], axis=0)

    P = jax.tree_util.tree_map(lambda *cs: cat(cs), *[p for p, _ in pairs])
    Qa = jax.tree_util.tree_map(lambda *cs: cat(cs), *[q for _, q in pairs])
    f_all = miller_loop(P, Qa)
    batch = jnp.shape(pairs[0][0][0])[0]
    parts = [
        jax.tree_util.tree_map(lambda c: c[i * batch : (i + 1) * batch], f_all)
        for i in range(len(pairs))
    ]
    f = parts[0]
    for fk in parts[1:]:
        f = tower.fq12_mul(f, fk)
    return f


# ---------------------------------------------------------------------------
# Final exponentiation
# ---------------------------------------------------------------------------


def final_exponentiation(f):
    """f^((Q¹²−1)/R): structural easy part + fixed-scan hard part."""
    # easy: f^((Q⁶−1)(Q²+1))
    t0 = tower.fq12_conj(f)  # f^(Q⁶)
    t1 = tower.fq12_inv(f)
    t2 = tower.fq12_mul(t0, t1)  # f^(Q⁶−1)
    t3 = tower.fq12_frobenius_n(t2, 2)
    eased = tower.fq12_mul(t3, t2)  # ^(Q²+1)
    # hard: ^((Q⁴−Q²+1)/R)
    return tower.fq12_pow_fixed(eased, _EASY_DONE_HARD)


def _cyclo_pow_x(m):
    """m^x for the BLS parameter x (negative) — cyclotomic elements only,
    where inverse = conjugate.  Uses the segmented Granger–Scott chain:
    63 compressed squarings (18 Fq lanes each) + 5 multiplies, instead
    of 63×(full squaring + select-multiply) = 63×90 lanes."""
    p = tower.fq12_cyclo_pow_segmented(m, BLS_X)
    return tower.fq12_conj(p) if BLS_X_IS_NEG else p


def final_exponentiation_fast(f):
    """f^{3·(Q¹²−1)/R} — the x-power addition chain for the hard part.

    Computes the THIRD POWER of the exact final exponentiation: the classic
    BLS12 decomposition (verified exactly in tests against the integer
    identity) is 3·(Q⁴−Q²+1)/R = c0 + c1·Q + c2·Q² + c3·Q³ with
    c3 = (x−1)², c2 = c3·x, c1 = c2·x − c3, c0 = c1·x + 3.  Since
    gcd(3, R) = 1 and f^H lies in the order-R subgroup, f^{3H} == 1 iff
    f^H == 1 — so every verification check can use this chain (4 short
    64-bit x-powers ≈ 5× cheaper than the plain 1270-bit scan).  Use
    `final_exponentiation` when the exact pairing VALUE matters.
    """
    # easy part: f^((Q⁶−1)(Q²+1)) → cyclotomic subgroup
    m = tower.fq12_mul(tower.fq12_conj(f), tower.fq12_inv(f))
    m = tower.fq12_mul(tower.fq12_frobenius_n(m, 2), m)
    # hard part ×3
    a = _cyclo_pow_x(m)  # m^x
    b = tower.fq12_mul(a, tower.fq12_conj(m))  # m^(x−1)
    c = _cyclo_pow_x(b)  # m^(x²−x)
    y3 = tower.fq12_mul(c, tower.fq12_conj(b))  # m^((x−1)²)
    y2 = _cyclo_pow_x(y3)  # m^(c3·x)
    y1 = tower.fq12_mul(_cyclo_pow_x(y2), tower.fq12_conj(y3))  # m^(c2·x−c3)
    m3 = tower.fq12_mul(tower.fq12_cyclo_sqr(m), m)
    y0 = tower.fq12_mul(_cyclo_pow_x(y1), m3)  # m^(c1·x+3)
    out = tower.fq12_mul(y0, tower.fq12_frobenius(y1))
    out = tower.fq12_mul(out, tower.fq12_frobenius_n(y2, 2))
    out = tower.fq12_mul(out, tower.fq12_frobenius_n(y3, 3))
    return out


def pairing(P, Qa):
    """Full batched pairing e(P, Q) as fq12 limb elements."""
    return final_exponentiation(miller_loop(P, Qa))


def product2_fast(P1, Q1, P2, Q2, fused=None):
    """THE verification kernel: FE_fast(ML(P1,Q1)·ML(P2,Q2)) as fq12 limbs.

    Single definition shared by the backend, the bench, the graft entry and
    the mesh-sharded path, so they always measure/compile the same graph.
    Host-compare each item against 1 (`is_one_host`) to decide
    e(P1,Q1)·e(P2,Q2) == 1.

    ``fused`` routes the graph onto the VMEM-resident fused tower kernels
    (ops/pairing_chain.py) — ``None`` consults the env ladder at TRACE
    time (jit callers that must react to env flips key their caches on
    the resolved mode, see TpuBackend), ``False`` forces the stacked
    graph, an explicit "native"/"interpret" wins.  Both graphs compute
    identical represented values.
    """
    from hbbft_tpu.ops import pairing_chain

    mode = pairing_chain.resolve_mode(fused)
    if mode:
        return pairing_chain.product2_fast_fused(P1, Q1, P2, Q2, mode=mode)
    return final_exponentiation_fast(miller_product([(P1, Q1), (P2, Q2)]))


def example_verify_batch(n_items: int, seed: int = 0, distinct: int = 8):
    """Host-built batch of valid checks e(−G1, a·G2)·e(a·G1, G2) == 1.

    Shared by bench.py and __graft_entry__ so the benchmark and the
    driver's compile check exercise identical shapes.  `distinct` bounds
    how many expensive golden scalar-muls are computed.
    """
    import random

    from hbbft_tpu.crypto import bls381 as gold
    from hbbft_tpu.crypto.field import R as SUBR

    rng = random.Random(seed)
    scalars = [rng.randrange(1, SUBR) for _ in range(max(1, distinct))]
    pts = [
        (
            gold.ec_neg(gold.FQ, gold.G1_GEN),
            gold.ec_mul(gold.FQ2, a, gold.G2_GEN),
            gold.ec_mul(gold.FQ, a, gold.G1_GEN),
            gold.G2_GEN,
        )
        for a in scalars
    ]
    quads = [pts[i % len(pts)] for i in range(n_items)]
    return (
        g1_affine_to_device([q[0] for q in quads]),
        g2_affine_to_device([q[1] for q in quads]),
        g1_affine_to_device([q[2] for q in quads]),
        g2_affine_to_device([q[3] for q in quads]),
    )


# ---------------------------------------------------------------------------
# Host-side comparison (the only canonical reduction, at the seam)
# ---------------------------------------------------------------------------


def is_one_host(f, idx=None) -> bool:
    """Exact check f == 1 in Fq12 (host ints)."""
    from hbbft_tpu.crypto.bls381 import FQ12_ONE

    return tower.fq12_to_ints(f, idx) == FQ12_ONE


def is_one_host_batch(f, n: int) -> list:
    """Exact f == 1 for the first ``n`` lanes in one vectorized readback
    (tower.fq12_to_ints_batch) — same booleans as ``is_one_host(f, i)``
    per lane at a fraction of the per-item CRT cost."""
    from hbbft_tpu.crypto.bls381 import FQ12_ONE

    return [v == FQ12_ONE for v in tower.fq12_to_ints_batch(f, n)]


def product_check(pairs) -> np.ndarray:
    """Per-item boolean: Π_k e(P_k, Q_k) == 1 (ONE shared final exp).

    The canonical equality test runs host-side on the returned limbs —
    the device graph stays scan/select-only.  Uses the fast (cubed)
    final exponentiation: the == 1 outcome is identical (gcd(3, R) = 1).
    """
    f = final_exponentiation_fast(miller_product(pairs))
    n = np.asarray(f[0][0][0]).shape[0]
    return np.array(is_one_host_batch(f, n))
