"""Pallas TPU kernel: fused Fq limb multiply (conv + carry + fold in VMEM).

The XLA path in fq.mul materializes a (lanes, NLIMBS, CONV) banded matrix
in HBM per stacked multiply — measured HBM-bound on a v5e (batch 1024 is
*slower* than 256).  This kernel keeps the whole pipeline — input
renormalization, shifted convolution, carries, both fold rounds — in VMEM;
HBM traffic drops to the operands and result.

Layout inside the kernel is **limbs-on-sublanes, lanes-on-batch**
((NLIMBS, T) tiles): every step is then a full-width VPU op or a
static-offset slice update; nothing touches the lane (=batch) axis, so a
tile of T lanes runs T field multiplications in lockstep.

The kernel is generic over fq's limb representation (8-bit/float32 —
default, full-rate VPU FMAs — or 11-bit/int32).  The fold step is a small
matmul (jnp.dot) so it can ride the MXU in the float32 configuration.

The public wrapper keeps fq.py's (..., NLIMBS) layout and transposes at
the kernel boundary (one read+write per operand — still far less traffic
than the banded matrix).  Falls back to interpret mode off-TPU, which is
how the CPU test suite golden-checks it.

Reference analogue: this is the "Pallas pairing kernel" hot path named by
BASELINE.json / SURVEY.md §7 hard part 1 — the field layer all curve and
pairing arithmetic bottoms out in.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The limb binding, NOT the facade: this kernel is limb-layout-only, and the
# facade's module-level names are rebound to fq_rns when HBBFT_TPU_FQ_IMPL=rns
# (the default).  fq.limb is captured before that rebinding (ADVICE r4 high).
from hbbft_tpu.ops import fq_limb as fq

TILE = 512  # lanes per grid step: 4 × (8, 128) VPU tiles

# Convolution strategy inside the kernel (selectable for A/B timing via
# HBBFT_TPU_CONV_MODE; module-level so tests can exercise every mode):
#   "concat"  — each shifted partial product via zero-pad concatenations
#               (functional, many VMEM copies)
#   "scratch" — accumulate into a VMEM scratch ref with static-slice
#               read-modify-writes (one pass of traffic, but the slice
#               offsets i are sublane-misaligned for 7 of 8 steps)
#   "grouped" — decompose the shift i = 8q + r: accumulate per-residue
#               partials at ALIGNED offsets 8q into an (8, CONV_PAD, T)
#               scratch, then apply only 8 misaligned shifts (one per r)
#               at the end instead of NLIMBS of them
# Measured on a v5e (tools/kernel_bench.py, 2026-07-30), M muls/s at
# 4k/16k/64k/256k lanes: grouped 33/89/177/217, scratch 22/108/141/208,
# concat 21/70/172/209.  Grouped is the default for its clear win in the
# small-lane regime the Miller loop actually runs in (and at 64k+);
# scratch holds a lead in the mid (16k) regime — revisit if a workload
# lives there.
_CONV_MODE = os.environ.get("HBBFT_TPU_CONV_MODE", "grouped")

_SUB = 8  # sublane granularity the "grouped" mode aligns to
_NLIMBS_PAD = -(-fq.NLIMBS // _SUB) * _SUB  # 56 for the 8-bit config
_CONV_PAD = _SUB * ((fq.NLIMBS - 1) // _SUB) + _NLIMBS_PAD

# FOLD columns: FOLD_T[:, j] = canonical limbs of 2^(BITS·(FOLD_FROM+j)) mod Q.
_FOLD_T = np.ascontiguousarray(fq._FOLD_ROWS.T)  # (NLIMBS, CONV - FOLD_FROM)


def _carry_cols(x: jnp.ndarray, passes: int = 3) -> jnp.ndarray:
    """fq.carry3 in limbs-first layout: split all rows but the last."""
    n = x.shape[0]
    for _ in range(passes):
        if fq.DTYPE == jnp.int32:
            hi = x >> fq.BITS
            lo = x & fq.MASK
        else:
            hi = jnp.floor(x * fq._INV_BASE)
            lo = x - hi * fq.BASE
        lo = jnp.concatenate([lo[: n - 1], x[n - 1 :]], axis=0)
        shifted = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[: n - 1]], axis=0)
        x = lo + shifted
    return x


def _conv_concat(a, b):
    """Shifted multiply-accumulate via zero-pad concatenations."""
    t = a.shape[1]

    def zero_rows(n):
        return jnp.zeros((n, t), dtype=fq.DTYPE)

    acc = zero_rows(fq.CONV)
    for i in range(fq.NLIMBS):
        prod = a[i : i + 1, :] * b  # (NLIMBS, T)
        parts = []
        if i:
            parts.append(zero_rows(i))
        parts.append(prod)
        if fq.CONV - fq.NLIMBS - i:
            parts.append(zero_rows(fq.CONV - fq.NLIMBS - i))
        acc = acc + jnp.concatenate(parts, axis=0)
    return acc


def _conv_grouped(a, b, acc8_ref):
    """Aligned-offset accumulation: P_r[8q:8q+PAD] += a[8q+r]·b_pad, then
    c = Σ_r shift_r(P_r).  Only 8 misaligned row-shifts total."""
    t = a.shape[1]
    b_pad = jnp.concatenate(
        [b, jnp.zeros((_NLIMBS_PAD - fq.NLIMBS, t), dtype=fq.DTYPE)], axis=0
    )
    acc8_ref[...] = jnp.zeros_like(acc8_ref)
    for r in range(_SUB):
        for q in range((fq.NLIMBS - 1 - r) // _SUB + 1):
            i = _SUB * q + r
            if i >= fq.NLIMBS:
                break
            acc8_ref[r, _SUB * q : _SUB * q + _NLIMBS_PAD, :] += (
                a[i : i + 1, :] * b_pad
            )
    # Assemble c[CONV] = Σ_r shift_r(P_r).  P_r rows beyond CONV−r hold only
    # zero-padding products (i+j ≤ CONV−1 always), so truncation is safe;
    # conversely pad up when _CONV_PAD < CONV (the 11-bit configuration).
    c = None
    for r in range(_SUB):
        rows = min(_CONV_PAD, fq.CONV - r)
        part = acc8_ref[r, :rows, :]
        pieces = []
        if r:
            pieces.append(jnp.zeros((r, t), dtype=fq.DTYPE))
        pieces.append(part)
        tail = fq.CONV - r - rows
        if tail:
            pieces.append(jnp.zeros((tail, t), dtype=fq.DTYPE))
        shifted = jnp.concatenate(pieces, axis=0) if len(pieces) > 1 else part
        c = shifted if c is None else c + shifted
    return c


def _mul_core(a, b, fold_t, acc_ref):
    """CARRIED operands (NLIMBS, T) → carried product.  The shared
    conv+carry+fold pipeline used by every kernel in this module; the
    conv strategy is chosen by the scratch ref's presence/shape."""
    ff = fq.FOLD_FROM
    t = a.shape[1]

    if acc_ref is None:
        c = _conv_concat(a, b)
    elif len(acc_ref.shape) == 3:
        c = _conv_grouped(a, b, acc_ref)
    else:
        # One-pass accumulation into VMEM scratch: each step is a 50-row
        # static-slice read-modify-write instead of a 99-row concat+add.
        acc_ref[:] = jnp.zeros_like(acc_ref)
        for i in range(fq.NLIMBS):
            acc_ref[i : i + fq.NLIMBS, :] += a[i : i + 1, :] * b
        c = acc_ref[:]
    c = _carry_cols(c)

    # Fold 1: replace limbs ≥ FOLD_FROM via the mod-Q rows — one small
    # matmul (NLIMBS, CONV-FOLD_FROM) @ (CONV-FOLD_FROM, T).
    out = jnp.concatenate(
        [c[:ff], jnp.zeros((fq.NLIMBS - ff, t), dtype=fq.DTYPE)], axis=0
    ) + jnp.dot(fold_t, c[ff:], preferred_element_type=fq.DTYPE)

    out = _carry_cols(out)

    # Fold 2: tidy limbs ≥ FOLD_FROM (NLIMBS - FOLD_FROM of them).
    nhi = fq.NLIMBS - ff
    out2 = jnp.concatenate(
        [out[:ff], jnp.zeros((nhi, t), dtype=fq.DTYPE)], axis=0
    ) + jnp.dot(fold_t[:, :nhi], out[ff:], preferred_element_type=fq.DTYPE)

    return _carry_cols(out2)


def _mul_kernel(a_ref, b_ref, fold_ref, out_ref, acc_ref=None):
    a = _carry_cols(a_ref[:])  # (NLIMBS, T), limbs ≤ BASE+1
    b = _carry_cols(b_ref[:])
    out_ref[:] = _mul_core(a, b, fold_ref[:], acc_ref)


@functools.lru_cache(maxsize=None)
def _mul_call(n_tiles: int, interpret: bool, mode: str):
    scratch = []
    if mode == "scratch":
        scratch = [pltpu.VMEM((fq.CONV, TILE), fq.DTYPE)]
    elif mode == "grouped":
        scratch = [pltpu.VMEM((_SUB, _CONV_PAD, TILE), fq.DTYPE)]
    return pl.pallas_call(
        _mul_kernel,
        out_shape=jax.ShapeDtypeStruct((fq.NLIMBS, n_tiles * TILE), fq.DTYPE),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((fq.NLIMBS, TILE), lambda i: (0, i)),
            pl.BlockSpec((fq.NLIMBS, TILE), lambda i: (0, i)),
            pl.BlockSpec((fq.NLIMBS, fq.CONV - fq.FOLD_FROM), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((fq.NLIMBS, TILE), lambda i: (0, i)),
        scratch_shapes=scratch,
        interpret=interpret,
    )


def _to_cols(x: jnp.ndarray, lanes: int, n_tiles: int) -> jnp.ndarray:
    """(..., NLIMBS) → limbs-first padded (NLIMBS, n_tiles·TILE)."""
    flat = x.reshape(lanes, fq.NLIMBS).T
    pad = n_tiles * TILE - lanes
    return jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat


def _from_cols(out: jnp.ndarray, lanes: int, shape) -> jnp.ndarray:
    return out[:, :lanes].T.reshape(shape)


def _lane_count(shape) -> tuple:
    lanes = 1
    for d in shape[:-1]:
        lanes *= d
    return lanes, max(1, -(-lanes // TILE))


def mul(a: jnp.ndarray, b: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Drop-in for fq.mul on TPU: (..., NLIMBS) lazy residues in, same out."""
    shape = jnp.broadcast_shapes(jnp.shape(a), jnp.shape(b))
    a = jnp.broadcast_to(jnp.asarray(a, fq.DTYPE), shape)
    b = jnp.broadcast_to(jnp.asarray(b, fq.DTYPE), shape)
    lanes, n_tiles = _lane_count(shape)
    out = _mul_call(n_tiles, interpret, _CONV_MODE)(
        _to_cols(a, lanes, n_tiles),
        _to_cols(b, lanes, n_tiles),
        jnp.asarray(_FOLD_T),
    )
    return _from_cols(out, lanes, shape)


# ---------------------------------------------------------------------------
# Fixed-exponent power: the whole square-and-multiply chain in ONE kernel.
#
# The XLA path (fq.pow_fixed) lowers to a lax.scan whose every iteration
# dispatches 2 stacked multiplies — for the 381-bit Fermat inverse that is
# ~760 sequential Pallas calls at ~100 µs fixed overhead each (~80 ms per
# verification graph, the dominant cost of final_exponentiation's easy
# part at protocol batch sizes).  Here the bit loop runs INSIDE the kernel
# (jax.lax.fori_loop over a scalar-prefetched bit schedule in SMEM), so
# the chain costs one kernel launch and never leaves VMEM.
# ---------------------------------------------------------------------------


def _pow_kernel(bits_ref, x_ref, fold_ref, out_ref, acc_ref=None):
    x = _carry_cols(x_ref[:])
    fold_t = fold_ref[:]
    nbits = bits_ref.shape[0]

    def body(i, acc):
        sq = _mul_core(acc, acc, fold_t, acc_ref)
        withx = _mul_core(sq, x, fold_t, acc_ref)
        # SMEM scalar read with a traced index; blend keeps the body
        # branch-free (both products always run — the set-bit density of
        # the Fermat exponent is ~60%, so a cond would save little).
        return jnp.where(bits_ref[i] > 0, withx, sq)

    # MSB is implicit: acc starts at x, loop covers bits [1, nbits).
    out_ref[:] = jax.lax.fori_loop(1, nbits, body, x)


@functools.lru_cache(maxsize=None)
def _pow_call(n_tiles: int, nbits: int, interpret: bool, mode: str):
    scratch = []
    if mode == "scratch":
        scratch = [pltpu.VMEM((fq.CONV, TILE), fq.DTYPE)]
    elif mode == "grouped":
        scratch = [pltpu.VMEM((_SUB, _CONV_PAD, TILE), fq.DTYPE)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((fq.NLIMBS, TILE), lambda i, *_: (0, i)),
            pl.BlockSpec(
                (fq.NLIMBS, fq.CONV - fq.FOLD_FROM), lambda i, *_: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec((fq.NLIMBS, TILE), lambda i, *_: (0, i)),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        _pow_kernel,
        out_shape=jax.ShapeDtypeStruct((fq.NLIMBS, n_tiles * TILE), fq.DTYPE),
        grid_spec=grid_spec,
        interpret=interpret,
    )


def pow_fixed(x: jnp.ndarray, exponent: int, interpret: bool = False) -> jnp.ndarray:
    """x^exponent for a Python-int exponent ≥ 1 — one kernel launch.

    Drop-in for fq.pow_fixed on TPU; (..., NLIMBS) lazy residues in/out.
    """
    if exponent < 1:
        raise ValueError("pow_fixed kernel requires exponent >= 1")
    bits = np.asarray([int(b) for b in bin(exponent)[2:]], dtype=np.int32)
    shape = jnp.shape(x)
    x = jnp.asarray(x, fq.DTYPE)
    lanes, n_tiles = _lane_count(shape)
    out = _pow_call(n_tiles, len(bits), interpret, _CONV_MODE)(
        jnp.asarray(bits), _to_cols(x, lanes, n_tiles), jnp.asarray(_FOLD_T)
    )
    return _from_cols(out, lanes, shape)
