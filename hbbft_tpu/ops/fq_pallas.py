"""Pallas TPU kernel: fused Fq limb multiply (conv + carry + fold in VMEM).

The XLA path in fq.mul materializes a (lanes, 37, 73) banded matrix in HBM
per stacked multiply (~11 KB/lane) — measured HBM-bound on a v5e (batch
1024 is *slower* than 256).  This kernel keeps the whole pipeline —
input renormalization, 37-step shifted convolution, carries, both fold
rounds — in VMEM; HBM traffic drops to the 0.3 KB/lane of the operands
and result.

Layout inside the kernel is **limbs-on-sublanes, lanes-on-batch**
((37, T) int32 tiles): every step is then a full-width VPU op or a
static-offset slice update; nothing touches the lane (=batch) axis, so a
tile of T lanes runs T field multiplications in lockstep.

The public wrapper keeps fq.py's (..., NLIMBS) layout and transposes at
the kernel boundary (one read+write per operand — still ~15× less traffic
than the banded matrix).  Falls back to interpret mode off-TPU, which is
how the CPU test suite golden-checks it.

Reference analogue: this is the "Pallas pairing kernel" hot path named by
BASELINE.json / SURVEY.md §7 hard part 1 — the field layer all curve and
pairing arithmetic bottoms out in.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hbbft_tpu.ops import fq

TILE = 512  # lanes per grid step: 4 × (8, 128) int32 VPU tiles

# FOLD columns: FOLD_T[:, j] = canonical limbs of 2^(11·(35+j)) mod Q.
_FOLD_T = np.ascontiguousarray(fq._FOLD_ROWS.T)  # (37, 38)


def _carry_cols(x: jnp.ndarray, passes: int = 3) -> jnp.ndarray:
    """fq.carry3 in limbs-first layout: split all rows but the last."""
    n = x.shape[0]
    for _ in range(passes):
        hi = x >> fq.BITS
        lo = x & fq.MASK
        lo = jnp.concatenate([lo[: n - 1], x[n - 1 :]], axis=0)
        shifted = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[: n - 1]], axis=0)
        x = lo + shifted
    return x


def _mul_kernel(a_ref, b_ref, fold_ref, out_ref):
    a = _carry_cols(a_ref[:])  # (37, T), limbs ≤ 2^11+1
    b = _carry_cols(b_ref[:])
    fold_t = fold_ref[:]

    # Schoolbook convolution as 37 shifted multiply-accumulates.  Mosaic has
    # no scatter-add; shift via static zero-pad concatenation instead.
    t = a.shape[1]

    def zero_rows(n):
        return jnp.zeros((n, t), dtype=jnp.int32)

    acc = zero_rows(fq.CONV)
    for i in range(fq.NLIMBS):
        prod = a[i : i + 1, :] * b  # (37, T)
        parts = []
        if i:
            parts.append(zero_rows(i))
        parts.append(prod)
        if fq.CONV - fq.NLIMBS - i:
            parts.append(zero_rows(fq.CONV - fq.NLIMBS - i))
        acc = acc + jnp.concatenate(parts, axis=0)

    c = _carry_cols(acc)

    # Fold 1: replace limbs ≥ 35 via 2^(11·(35+j)) mod Q rows (38 of them).
    hi = c[35:]
    out = jnp.concatenate(
        [c[:35], jnp.zeros((fq.NLIMBS - 35, t), dtype=jnp.int32)], axis=0
    )
    for j in range(fq.CONV - 35):
        out = out + fold_t[:, j : j + 1] * hi[j : j + 1, :]

    out = _carry_cols(out)

    # Fold 2: tidy limbs 35, 36.
    hi2 = out[35:37]
    out2 = jnp.concatenate(
        [out[:35], jnp.zeros((2, t), dtype=jnp.int32)], axis=0
    )
    for j in range(2):
        out2 = out2 + fold_t[:, j : j + 1] * hi2[j : j + 1, :]

    out_ref[:] = _carry_cols(out2)


@functools.lru_cache(maxsize=None)
def _mul_call(n_tiles: int, interpret: bool):
    return pl.pallas_call(
        _mul_kernel,
        out_shape=jax.ShapeDtypeStruct((fq.NLIMBS, n_tiles * TILE), jnp.int32),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((fq.NLIMBS, TILE), lambda i: (0, i)),
            pl.BlockSpec((fq.NLIMBS, TILE), lambda i: (0, i)),
            pl.BlockSpec((fq.NLIMBS, fq.CONV - 35), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((fq.NLIMBS, TILE), lambda i: (0, i)),
        interpret=interpret,
    )


def mul(a: jnp.ndarray, b: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Drop-in for fq.mul on TPU: (..., 37) lazy residues in, same out."""
    shape = jnp.broadcast_shapes(jnp.shape(a), jnp.shape(b))
    a = jnp.broadcast_to(jnp.asarray(a, jnp.int32), shape)
    b = jnp.broadcast_to(jnp.asarray(b, jnp.int32), shape)
    lanes = 1
    for d in shape[:-1]:
        lanes *= d
    flat_a = a.reshape(lanes, fq.NLIMBS).T
    flat_b = b.reshape(lanes, fq.NLIMBS).T
    n_tiles = max(1, -(-lanes // TILE))
    pad = n_tiles * TILE - lanes
    if pad:
        flat_a = jnp.pad(flat_a, ((0, 0), (0, pad)))
        flat_b = jnp.pad(flat_b, ((0, 0), (0, pad)))
    out = _mul_call(n_tiles, interpret)(flat_a, flat_b, jnp.asarray(_FOLD_T))
    return out[:, :lanes].T.reshape(shape)
