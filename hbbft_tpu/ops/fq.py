"""Fq — the BLS12-381 base field: implementation facade.

``HBBFT_TPU_FQ_IMPL`` selects the field implementation at import time:

* ``rns`` (default) — the RNS / MXU-matmul implementation
  (``ops/fq_rns.py``).  Promoted to default in round 4 on the measured
  on-chip A/B: rlc_dec_verify 16.8k shares/s under RNS vs 2.8k under
  limb (6.0x, ``tpu_window_r04/``).
* ``limb`` — the float32 limb-vector implementation
  (``ops/fq_limb.py``), kept as an independent golden cross-check and
  legacy A/B arm.

Same API, same semantics (values mod Q through ``from_int``/``to_int``),
different device layout: ``(..., 79)`` residue lanes vs ``(..., 50)``
limbs.  Everything above the Fq API (tower, curve, pairing, backend) is
representation-agnostic and picks the binding up at import.

``fq.limb`` is ALWAYS the limb module itself, whatever the facade
selects — the limb Pallas kernels (``ops/fq_pallas.py``) and the
limb-specific tests bind to it directly, so the cross-check arm cannot
be corrupted by the facade default (ADVICE r4 high finding: mixing
rebound RNS constants with limb-only internals produced NaN).

Mirrors SURVEY.md §2.2's threshold_crypto field layer.
"""

from __future__ import annotations

import os

from hbbft_tpu.ops import fq_limb as limb

_FQ_IMPL = os.environ.get("HBBFT_TPU_FQ_IMPL", "rns")
if _FQ_IMPL == "rns":
    from hbbft_tpu.ops import fq_rns as _impl
elif _FQ_IMPL == "limb":
    _impl = limb
else:  # pragma: no cover - configuration error
    raise ValueError(f"HBBFT_TPU_FQ_IMPL must be 'limb' or 'rns', got {_FQ_IMPL}")

#: active implementation name ("rns" or "limb") — for bench/row labels.
IMPL = _FQ_IMPL

NLIMBS = _impl.NLIMBS
DTYPE = _impl.DTYPE
NP_DTYPE = _impl.NP_DTYPE
ZERO = _impl.ZERO
ONE = _impl.ONE
from_int = _impl.from_int
from_ints = _impl.from_ints
to_int = _impl.to_int
to_ints = _impl.to_ints
carry3 = _impl.carry3
add = _impl.add
sub = _impl.sub
neg = _impl.neg
mul = _impl.mul
sqr = _impl.sqr
mul_n = _impl.mul_n
mul_small = _impl.mul_small
reduce_small = _impl.reduce_small
select = _impl.select
pow_fixed = _impl.pow_fixed
inv = _impl.inv
batch_inv = _impl.batch_inv
is_zero_host = _impl.is_zero_host
is_zero = _impl.is_zero

# Limb-only width diagnostics some tools print (tools/kernel_bench.py):
# stable limb values regardless of the facade choice, as before the split.
BITS = limb.BITS
BASE = limb.BASE
