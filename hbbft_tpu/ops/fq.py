"""Batched BLS12-381 base-field (Fq) limb arithmetic for TPU.

This is the foundation of the device crypto stack (SURVEY.md §7 "hard parts"
item 1): 381-bit field elements as vectors of **37 limbs × 11 bits** held in
``int32`` — the widest limb for which a full 37-term schoolbook convolution
plus reduction fits signed-int32 accumulators with headroom:

    products  ≤ (2^11+ε)^2            ≈ 2^22
    conv sum  ≤ 37 · 2^22             ≈ 2^27.3   < 2^31  ✓
    fold sum  ≤ 38 · 2^11 · 2^11.7    ≈ 2^28     < 2^31  ✓

Representation ("lazy residue"):

* An element is any int32 vector ``l[0..36]`` whose value Σ l_i·2^(11i) is
  congruent to the represented element mod Q.  Limbs may be negative
  (subtraction never borrows; signs ride along) and the value may exceed Q —
  reduction keeps |value| < 2^394 ≈ 2^13·Q, and every op tolerates inputs
  with |value| up to ~2^398 (a dozen chained lazy adds); vectors outside
  that envelope (e.g. all 37 limbs at MASK ⇒ 2^407) are out of domain.
* ``carry3`` renormalizes limbs to [-1, 2^11+1) in three data-independent
  vector passes (no sequential scan — carries shrink geometrically from the
  2^28 bound).  The TOP limb is never split, so no carry is ever dropped.
* There is deliberately **no canonical reduction on device**: protocols need
  booleans and byte-strings only at the host seam, where ``to_int`` does an
  exact Python-int mod-Q.  This removes every sequential carry chain from
  the jitted graph (SURVEY.md §7 hard part 6: fixed reduction orders).

Multiplication is convolution expressed as one gather + one small matmul:
``Bmat[i,k] = b[k-i]`` (37×73, built with a precomputed index/mask pair),
then ``c = a @ Bmat`` — XLA turns the batch of these into large int32
dot-generals, the MXU/VPU-friendly shape the whole design targets.

Reduction mod Q folds limbs ≥ 35 through precomputed rows
``FOLD[j] = limbs(2^(11·(35+j)) mod Q)`` — again a matmul.  Two fold rounds
bring any 73-limb convolution back to the 37-limb lazy invariant.

Reference analogue: the `ff`/`pairing` crates' 64-bit limb arithmetic under
`threshold_crypto` (SURVEY.md §2.2) — redesigned for a carry-less SIMD ISA
instead of scalar add-with-carry.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.field import Q

BITS = 11
BASE = 1 << BITS
MASK = BASE - 1
NLIMBS = 37  # 37·11 = 407 bits capacity; values stay below 2^394.
CONV = 2 * NLIMBS - 1  # 73


def _int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    """Canonical little-endian limb decomposition of a non-negative int."""
    if x < 0:
        raise ValueError("canonical limbs are non-negative")
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    if x:
        raise ValueError("value does not fit limb vector")
    return out


# -- precomputed constants ---------------------------------------------------

# Gather/mask pair turning b (37 limbs) into the banded matrix Bmat[i, k] =
# b[k-i], so that (a @ Bmat)[k] = Σ_i a_i·b_{k-i} — the full product.
_K = np.arange(CONV)[None, :]  # (1, 73)
_I = np.arange(NLIMBS)[:, None]  # (37, 1)
_GATHER_IDX = np.clip(_K - _I, 0, NLIMBS - 1).astype(np.int32)  # (37, 73)
_GATHER_MASK = ((_K - _I >= 0) & (_K - _I < NLIMBS)).astype(np.int32)

# FOLD[j] = canonical limbs of (2^(11·(35+j)) mod Q), j = 0..37: replaces
# limb positions ≥ 35 by their mod-Q equivalents.  Position 35 (2^385) is
# already > Q, so folding from 35 keeps the value bound tight (< 2^394).
_FOLD_ROWS = np.stack(
    [_int_to_limbs(pow(1 << BITS, 35 + j, Q)) for j in range(NLIMBS + 1)]
)  # (38, 37)

Q_LIMBS = _int_to_limbs(Q)

ZERO = np.zeros(NLIMBS, dtype=np.int32)
ONE = _int_to_limbs(1)


# -- host <-> device conversion ---------------------------------------------


def from_int(x: int) -> np.ndarray:
    """Canonical limb vector for x (reduced mod Q first)."""
    return _int_to_limbs(x % Q)


def from_ints(xs) -> np.ndarray:
    """Stack of canonical limb vectors, shape (len(xs), NLIMBS)."""
    return np.stack([from_int(int(x)) for x in xs])


def to_int(limbs) -> int:
    """Exact value of a (possibly lazy/negative) limb vector, mod Q."""
    arr = np.asarray(limbs)
    val = 0
    for i in range(arr.shape[-1] - 1, -1, -1):
        val = (val << BITS) + int(arr[..., i])
    return val % Q


def to_ints(batch) -> list:
    arr = np.asarray(batch)
    return [to_int(arr[i]) for i in range(arr.shape[0])]


# -- core ops (all jnp, batch-agnostic over leading dims) --------------------


def carry3(x: jnp.ndarray) -> jnp.ndarray:
    """Three vectorized carry passes: limbs land in [-1, BASE+1].

    Works for any |limb| ≤ 2^30.  The top limb accumulates incoming carries
    without being split (its magnitude stays tiny because values are
    < 2^394 ≪ 2^(11·36)), so nothing is ever truncated.
    """
    x = jnp.asarray(x)
    for _ in range(3):
        hi = x >> BITS  # arithmetic shift: correct floor for negatives
        lo = x & MASK
        # Keep the top limb whole.
        lo = lo.at[..., -1].set(x[..., -1])
        shifted = jnp.concatenate(
            [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1
        )
        x = lo + shifted
    return x


def _fold(c: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    """Replace limbs ≥ 35 via precomputed (2^(11·(35+j)) mod Q) rows."""
    lo = c[..., :35]
    hi = c[..., 35:]
    lo = jnp.concatenate(
        [lo, jnp.zeros(lo.shape[:-1] + (NLIMBS - 35,), dtype=lo.dtype)], axis=-1
    )
    return lo + jnp.einsum(
        "...j,jk->...k", hi, rows[: hi.shape[-1]], preferred_element_type=jnp.int32
    )


_FOLD_J = jnp.asarray(_FOLD_ROWS)


def reduce_conv(c: jnp.ndarray) -> jnp.ndarray:
    """73-limb convolution output → 37-limb lazy residue."""
    c = carry3(c)  # limbs ≤ BASE+1
    c = _fold(c, _FOLD_J)  # 73 → 37 limbs, |value| < 2^398
    c = carry3(c)
    c = _fold(c, _FOLD_J)  # tidy limbs 35,36 → |value| < 2^394
    return carry3(c)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy add — no carry (mul/carry3 downstream absorbs growth)."""
    return a + b


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy subtract — limbs may go negative; that's fine."""
    return a - b


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return -a


def _use_pallas() -> bool:
    """Route muls through the fused Pallas kernel on TPU (trace-time check).

    The XLA path materializes the banded matrix in HBM; on TPU the Pallas
    kernel keeps conv+carry+fold in VMEM (~1.3× today, and the tuning
    surface for the round-2 kernel work — see PERF.md).  Disable with
    HBBFT_TPU_NO_PALLAS=1.
    """
    import os

    if os.environ.get("HBBFT_TPU_NO_PALLAS"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full product + reduction.  Inputs may be lazy (|limb| ≤ 2^14ish from
    a few chained adds); they are renormalized before the convolution."""
    if _use_pallas():
        from hbbft_tpu.ops import fq_pallas

        return fq_pallas.mul(a, b)
    a = carry3(a)
    b = carry3(b)
    bmat = b[..., _GATHER_IDX] * _GATHER_MASK  # (..., 37, 73)
    c = jnp.einsum(
        "...i,...ik->...k", a, bmat, preferred_element_type=jnp.int32
    )
    return reduce_conv(c)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_n(pairs) -> list:
    """Many independent Fq products as ONE stacked convolution.

    XLA compile time scales with the number of dot_generals in a graph
    (≈0.3 s each for this shape on CPU); a Miller-loop body written with
    per-product `mul` calls takes minutes to compile.  Stacking n products
    along a new leading axis costs one concat/slice pair and compiles —
    and runs — as a single large batch multiply.  Operands must share a
    broadcastable batch shape.
    """
    if len(pairs) == 1:
        return [mul(pairs[0][0], pairs[0][1])]
    common = ()
    for a, b in pairs:
        common = jnp.broadcast_shapes(common, jnp.shape(a), jnp.shape(b))
    A = jnp.stack([jnp.broadcast_to(jnp.asarray(a), common) for a, _ in pairs])
    B = jnp.stack([jnp.broadcast_to(jnp.asarray(b), common) for _, b in pairs])
    C = mul(A, B)
    return [C[i] for i in range(len(pairs))]


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small non-negative int (|k| < 2^15)."""
    return reduce_small(a * jnp.int32(k))


def reduce_small(x: jnp.ndarray) -> jnp.ndarray:
    """Renormalize a 37-limb vector whose limbs grew (adds, small scalars)."""
    x = carry3(x)
    x = _fold(x, _FOLD_J)
    return carry3(x)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branchless per-item select; cond shape broadcasts against (..., 37)."""
    return jnp.where(cond[..., None], a, b)


def pow_fixed(x: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """x^exponent for a Python-int exponent baked into the graph.

    Uses a lax.scan over the fixed bit schedule (MSB first) so the graph
    stays O(1) in exponent length: per step one square + one select-mul.
    """
    bits = [int(b) for b in bin(exponent)[2:]]
    bits_arr = jnp.asarray(bits, dtype=jnp.int32)

    def step(acc, bit):
        acc = sqr(acc)
        cond = jnp.broadcast_to(bit.astype(bool), acc.shape[:-1])
        acc = select(cond, mul(acc, x), acc)
        return acc, None

    # Seed with 1 so the first iteration (MSB, always 1) sets acc = x.
    ones = jnp.broadcast_to(jnp.asarray(ONE), x.shape)
    acc, _ = jax.lax.scan(step, ones, bits_arr)
    return acc


def inv(x: jnp.ndarray) -> jnp.ndarray:
    """Fermat inverse x^(Q-2).  ~760 muls — amortize with batch_inv."""
    return pow_fixed(x, Q - 2)


def batch_inv(x: jnp.ndarray) -> jnp.ndarray:
    """Invert a batch (leading axis) of nonzero elements with ONE Fermat
    inverse: parallel prefix/suffix product scans + the Montgomery trick."""
    prefix = jax.lax.associative_scan(mul, x, axis=0)
    suffix = jax.lax.associative_scan(mul, x, axis=0, reverse=True)
    tinv = inv(prefix[-1])
    one = jnp.broadcast_to(jnp.asarray(ONE), x[:1].shape)
    pre = jnp.concatenate([one, prefix[:-1]], axis=0)  # prefix_{i-1}
    suf = jnp.concatenate([suffix[1:], one], axis=0)  # suffix_{i+1}
    return mul(mul(pre, suf), jnp.broadcast_to(tinv, x.shape))


def is_zero_host(limbs) -> bool:
    """Host-side exact zero test (the only canonical compare we ever need)."""
    return to_int(limbs) == 0
