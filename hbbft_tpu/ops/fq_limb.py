"""Batched BLS12-381 base-field (Fq) limb arithmetic for TPU.

This is the foundation of the device crypto stack (SURVEY.md §7 "hard parts"
item 1): 381-bit field elements as limb vectors in one of two switchable
representations (env ``HBBFT_TPU_FQ_BITS``):

* **8-bit limbs × 50 in float32** (default) — the MXU/VPU-rate path.  All
  intermediate integers stay below 2^24, so float32 arithmetic is *exact*:

      products  ≤ 257²                  ≈ 2^16.01
      conv sum  ≤ 50 · 257²             ≈ 2^21.7   < 2^24  ✓
      fold sum  ≤ 51 · 257 · 255        ≈ 2^21.7   < 2^24  ✓

  Float32 multiply-adds run at full VPU rate (int32 multiplies are
  emulated multi-op on TPU) and the convolution/fold matmuls are eligible
  for the MXU — this representation exists purely because of that.

* **11-bit limbs × 37 in int32** — the original conservative path, kept as
  a second independent implementation for golden cross-checking:

      products  ≤ (2^11+ε)^2            ≈ 2^22
      conv sum  ≤ 37 · 2^22             ≈ 2^27.3   < 2^31  ✓
      fold sum  ≤ 38 · 2^11 · 2^11.7    ≈ 2^28     < 2^31  ✓

Representation ("lazy residue"), identical in both widths:

* An element is any limb vector ``l[0..NLIMBS-1]`` whose value
  Σ l_i·2^(BITS·i) is congruent to the represented element mod Q.  Limbs
  may be negative (subtraction never borrows; signs ride along) and the
  value may exceed Q — reduction keeps |value| < 2^(BITS·(FOLD_FROM+2))ish,
  and every op tolerates inputs with a dozen chained lazy adds; vectors at
  the full 2^(BITS·NLIMBS) capacity are out of domain.
* ``carry3`` renormalizes limbs to [-1, BASE+1) in three data-independent
  vector passes (no sequential scan — carries shrink geometrically).  The
  TOP limb is never split, so no carry is ever dropped.
* There is deliberately **no canonical reduction on device**: protocols need
  booleans and byte-strings only at the host seam, where ``to_int`` does an
  exact Python-int mod-Q.  This removes every sequential carry chain from
  the jitted graph (SURVEY.md §7 hard part 6: fixed reduction orders).

Multiplication is convolution expressed as one gather + one small matmul:
``Bmat[i,k] = b[k-i]`` (NLIMBS×CONV, built with a precomputed index/mask
pair), then ``c = a @ Bmat`` — XLA turns the batch of these into large
dot-generals, the MXU/VPU-friendly shape the whole design targets.

Reduction mod Q folds limbs ≥ FOLD_FROM through precomputed rows
``FOLD[j] = limbs(2^(BITS·(FOLD_FROM+j)) mod Q)`` — again a matmul.  Two
fold rounds bring any CONV-limb convolution back to the lazy invariant.

Reference analogue: the `ff`/`pairing` crates' 64-bit limb arithmetic under
`threshold_crypto` (SURVEY.md §2.2) — redesigned for a carry-less SIMD ISA
instead of scalar add-with-carry.
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.field import Q

BITS = int(os.environ.get("HBBFT_TPU_FQ_BITS", "8"))
if BITS == 8:
    NLIMBS = 50  # 50·8 = 400 bits capacity; values stay below 2^396.
    FOLD_FROM = 48  # 2^(8·48) = 2^384 > Q ≈ 2^381.4
    DTYPE = jnp.float32
    NP_DTYPE = np.float32
elif BITS == 11:
    NLIMBS = 37  # 37·11 = 407 bits capacity; values stay below 2^394.
    FOLD_FROM = 35  # 2^(11·35) = 2^385 > Q
    DTYPE = jnp.int32
    NP_DTYPE = np.int32
else:  # pragma: no cover - configuration error
    raise ValueError(f"HBBFT_TPU_FQ_BITS must be 8 or 11, got {BITS}")

BASE = 1 << BITS
MASK = BASE - 1
CONV = 2 * NLIMBS - 1
_INV_BASE = 1.0 / BASE  # exact power of two


def _int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    """Canonical little-endian limb decomposition of a non-negative int."""
    if x < 0:
        raise ValueError("canonical limbs are non-negative")
    out = np.zeros(n, dtype=np.int64)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    if x:
        raise ValueError("value does not fit limb vector")
    return out.astype(NP_DTYPE)


# -- precomputed constants ---------------------------------------------------

# Gather/mask pair turning b (NLIMBS limbs) into the banded matrix
# Bmat[i, k] = b[k-i], so that (a @ Bmat)[k] = Σ_i a_i·b_{k-i}.
_K = np.arange(CONV)[None, :]  # (1, CONV)
_I = np.arange(NLIMBS)[:, None]  # (NLIMBS, 1)
_GATHER_IDX = np.clip(_K - _I, 0, NLIMBS - 1).astype(np.int32)
_GATHER_MASK = ((_K - _I >= 0) & (_K - _I < NLIMBS)).astype(NP_DTYPE)

# FOLD[j] = canonical limbs of (2^(BITS·(FOLD_FROM+j)) mod Q): replaces limb
# positions ≥ FOLD_FROM by their mod-Q equivalents.
_FOLD_ROWS = np.stack(
    [
        _int_to_limbs(pow(1 << BITS, FOLD_FROM + j, Q))
        for j in range(CONV - FOLD_FROM)
    ]
)  # (CONV - FOLD_FROM, NLIMBS)

Q_LIMBS = _int_to_limbs(Q)

ZERO = np.zeros(NLIMBS, dtype=NP_DTYPE)
ONE = _int_to_limbs(1)


# -- host <-> device conversion ---------------------------------------------


def from_int(x: int) -> np.ndarray:
    """Canonical limb vector for x (reduced mod Q first)."""
    return _int_to_limbs(x % Q)


def from_ints(xs) -> np.ndarray:
    """Stack of canonical limb vectors, shape (len(xs), NLIMBS).

    Value-deduplicated: whole-network batches replicate the same point
    coordinates across many lanes (one per receiver), so each distinct
    value is limb-converted once and fanned out with a numpy take —
    at N=100 this is the difference between ~10⁴ and ~10⁶ conversions
    per epoch."""
    xs = [int(x) for x in xs]
    uniq: dict = {}
    rows: List[np.ndarray] = []
    idx = np.empty(len(xs), dtype=np.int64)
    for j, x in enumerate(xs):
        pos = uniq.get(x)
        if pos is None:
            pos = uniq[x] = len(rows)
            rows.append(from_int(x))
        idx[j] = pos
    if not rows:
        return np.zeros((0, NLIMBS), dtype=np.asarray(ZERO).dtype)
    return np.stack(rows)[idx]


def to_int(limbs) -> int:
    """Exact value of a (possibly lazy/negative) limb vector, mod Q."""
    arr = np.asarray(limbs)
    val = 0
    for i in range(arr.shape[-1] - 1, -1, -1):
        val = (val << BITS) + int(round(float(arr[..., i])))
    return val % Q


def to_ints(batch) -> list:
    arr = np.asarray(batch)
    return [to_int(arr[i]) for i in range(arr.shape[0])]


# -- core ops (all jnp, batch-agnostic over leading dims) --------------------


def _split(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(hi, lo) with x = hi·BASE + lo, lo ∈ [0, BASE) — exact both dtypes.

    int32 uses shift/mask (arithmetic shift floors negatives correctly);
    float32 uses an exact power-of-two scale + floor.  Float inputs must be
    integer-valued with |x| < 2^24 (all callers guarantee this).
    """
    if DTYPE == jnp.int32:
        return x >> BITS, x & MASK
    hi = jnp.floor(x * _INV_BASE)
    return hi, x - hi * BASE


def carry3(x: jnp.ndarray) -> jnp.ndarray:
    """Three vectorized carry passes: limbs land in [-1, BASE+1].

    Works for any limb magnitude up to the dtype's exact-integer envelope
    (2^30 int32 / 2^24 float32).  The top limb accumulates incoming carries
    without being split (its magnitude stays tiny because reduced values
    are far below 2^(BITS·(NLIMBS-1))), so nothing is ever truncated.
    """
    x = jnp.asarray(x, DTYPE)
    for _ in range(3):
        hi, lo = _split(x)
        # Keep the top limb whole.
        lo = lo.at[..., -1].set(x[..., -1])
        shifted = jnp.concatenate(
            [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1
        )
        x = lo + shifted
    return x


def _fold(c: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    """Replace limbs ≥ FOLD_FROM via the precomputed mod-Q rows."""
    lo = c[..., :FOLD_FROM]
    hi = c[..., FOLD_FROM:]
    lo = jnp.concatenate(
        [lo, jnp.zeros(lo.shape[:-1] + (NLIMBS - FOLD_FROM,), dtype=lo.dtype)],
        axis=-1,
    )
    return lo + jnp.einsum(
        "...j,jk->...k", hi, rows[: hi.shape[-1]], preferred_element_type=DTYPE
    )


_FOLD_J = jnp.asarray(_FOLD_ROWS)


def reduce_conv(c: jnp.ndarray) -> jnp.ndarray:
    """CONV-limb convolution output → NLIMBS-limb lazy residue."""
    c = carry3(c)  # limbs ≤ BASE+1
    c = _fold(c, _FOLD_J)  # CONV → NLIMBS limbs
    c = carry3(c)
    c = _fold(c, _FOLD_J)  # tidy limbs ≥ FOLD_FROM
    return carry3(c)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy add — no carry (mul/carry3 downstream absorbs growth)."""
    return a + b


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lazy subtract — limbs may go negative; that's fine."""
    return a - b


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return -a


def _use_pallas() -> bool:
    """Route muls through the fused Pallas kernel on TPU (trace-time check).

    The XLA path materializes the banded matrix in HBM; on TPU the Pallas
    kernel keeps conv+carry+fold in VMEM.  Disable with
    HBBFT_TPU_NO_PALLAS=1.
    """
    if os.environ.get("HBBFT_TPU_NO_PALLAS"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full product + reduction.  Inputs may be lazy (limbs grown by a few
    chained adds); they are renormalized before the convolution."""
    if _use_pallas():
        from hbbft_tpu.ops import fq_pallas

        return fq_pallas.mul(a, b)
    a = carry3(a)
    b = carry3(b)
    bmat = b[..., _GATHER_IDX] * jnp.asarray(_GATHER_MASK)
    if DTYPE == jnp.float32:
        # Post-carry3 limbs lie in [-1, BASE+1] ⊂ bf16-exact integers, so the
        # banded contraction is a native bf16×bf16→f32 MXU dot: products are
        # exact (8-bit × 8-bit mantissas) and the 50-term sums stay < 2^24.
        c = jnp.einsum(
            "...i,...ik->...k",
            a.astype(jnp.bfloat16),
            bmat.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    else:
        c = jnp.einsum("...i,...ik->...k", a, bmat, preferred_element_type=DTYPE)
    return reduce_conv(c)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_n(pairs) -> list:
    """Many independent Fq products as ONE stacked convolution.

    XLA compile time scales with the number of dot_generals in a graph
    (≈0.3 s each for this shape on CPU); a Miller-loop body written with
    per-product `mul` calls takes minutes to compile.  Stacking n products
    along a new leading axis costs one concat/slice pair and compiles —
    and runs — as a single large batch multiply.  Operands must share a
    broadcastable batch shape.
    """
    if len(pairs) == 1:
        return [mul(pairs[0][0], pairs[0][1])]
    common = ()
    for a, b in pairs:
        common = jnp.broadcast_shapes(common, jnp.shape(a), jnp.shape(b))
    A = jnp.stack([jnp.broadcast_to(jnp.asarray(a), common) for a, _ in pairs])
    B = jnp.stack([jnp.broadcast_to(jnp.asarray(b), common) for _, b in pairs])
    C = mul(A, B)
    return [C[i] for i in range(len(pairs))]


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small int k, |k| < 2^15 (k may be negative).

    The input is renormalized first so the scaled limbs stay inside the
    float32 exact-integer envelope (257 · 2^15 < 2^24).
    """
    if not -(1 << 15) < k < (1 << 15):
        raise ValueError("|k| must be < 2^15")
    return reduce_small(carry3(a) * jnp.asarray(k, DTYPE))


def reduce_small(x: jnp.ndarray) -> jnp.ndarray:
    """Renormalize a NLIMBS-limb vector whose limbs grew (adds, scalars)."""
    x = carry3(x)
    x = _fold(x, _FOLD_J)
    return carry3(x)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branchless per-item select; cond shape broadcasts against (..., NLIMBS)."""
    return jnp.where(cond[..., None], a, b)


def pow_fixed(x: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """x^exponent for a Python-int exponent baked into the graph.

    On TPU the whole square-and-multiply chain runs inside ONE Pallas
    kernel (fq_pallas.pow_fixed) — the scan form below dispatches 2
    kernel calls per exponent bit, which at ~100 µs fixed cost per call
    dominates everything for the 381-bit Fermat inverse.
    """
    if (
        exponent >= 1
        and _use_pallas()
        and not os.environ.get("HBBFT_TPU_NO_FUSED")
    ):
        from hbbft_tpu.ops import fq_pallas

        return fq_pallas.pow_fixed(x, exponent)
    bits = [int(b) for b in bin(exponent)[2:]]
    bits_arr = jnp.asarray(bits, dtype=jnp.int32)

    def step(acc, bit):
        acc = sqr(acc)
        cond = jnp.broadcast_to(bit.astype(bool), acc.shape[:-1])
        acc = select(cond, mul(acc, x), acc)
        return acc, None

    # Seed with 1 so the first iteration (MSB, always 1) sets acc = x.
    ones = jnp.broadcast_to(jnp.asarray(ONE), x.shape)
    acc, _ = jax.lax.scan(step, ones, bits_arr)
    return acc


def inv(x: jnp.ndarray) -> jnp.ndarray:
    """Fermat inverse x^(Q-2).  ~760 muls — amortize with batch_inv."""
    return pow_fixed(x, Q - 2)


def batch_inv(x: jnp.ndarray) -> jnp.ndarray:
    """Invert a batch (leading axis) of nonzero elements with ONE Fermat
    inverse: parallel prefix/suffix product scans + the Montgomery trick."""
    prefix = jax.lax.associative_scan(mul, x, axis=0)
    suffix = jax.lax.associative_scan(mul, x, axis=0, reverse=True)
    tinv = inv(prefix[-1])
    one = jnp.broadcast_to(jnp.asarray(ONE), x[:1].shape)
    pre = jnp.concatenate([one, prefix[:-1]], axis=0)  # prefix_{i-1}
    suf = jnp.concatenate([suffix[1:], one], axis=0)  # suffix_{i+1}
    return mul(mul(pre, suf), jnp.broadcast_to(tinv, x.shape))


def is_zero_host(limbs) -> bool:
    """Host-side exact zero test (the only canonical compare we ever need)."""
    return to_int(limbs) == 0


# -- in-graph zero test (complete-add route selector; ops/curve.py) ----------
#
# The lazy representation has no canonical form, so v ≡ 0 (mod Q) cannot be
# a limb compare.  The test reconstructs exactness from two ingredients:
#
# 1. A float32 estimate of the quotient m ≈ v/Q (weights 2^(BITS·i)/Q).
#    After ``reduce_small`` the value satisfies |v| < 2·(BASE+2)·Q (the
#    fold rows are < Q and post-carry3 only the two limbs ≥ FOLD_FROM,
#    each ≤ BASE+1, contribute a fold row; the un-folded part is
#    < 2^(BITS·FOLD_FROM) < 8·Q), so the candidate quotient lies in
#    m_est + c, c ∈ {−2..2}: the f32 estimate error on exact multiples is
#    ≪ 1 (post-reduce limbs are ≤ BASE+1, so every term is ≤ (BASE+1)·w_i
#    and the partial sums stay ≤ |m| + 1) — ±2 is generous margin.
# 2. Exact residues of v modulo ``_NZ_NPRIMES`` probe primes < 1300
#    (product ≈ 2^407): y_j = Σ_i l_i·(2^(BITS·i) mod p_j) as one
#    constant matmul whose accumulation provably stays exact (bound
#    asserted below).  Then v ≡ 0 (mod Q) iff v = m·Q for some candidate
#    m, iff y_j ≡ m·(Q mod p_j) (mod p_j) for EVERY probe prime — sound
#    because |v − m·Q| < 2·(BASE+2+2)·Q < 2^394 < Π p_j, so all-residues
#    -zero forces v − m·Q = 0 exactly.
#
# Soundness does not depend on the estimate accuracy (a wrong m simply
# fails the residue check); completeness (never missing a true zero) is
# the ±2 candidate window, exercised by the adversarial degenerate-case
# tests (tests/test_glv_degenerate.py).


def _probe_primes(limit: int, count: int) -> List[int]:
    out: List[int] = []
    x = limit
    while len(out) < count and x > 2:
        x -= 1
        if all(x % d for d in range(2, int(x**0.5) + 1)):
            out.append(x)
    return out


_NZ_NPRIMES = 40
_NZ_P = np.array(_probe_primes(1300, _NZ_NPRIMES), dtype=np.int64)
# residue weight matrix (NLIMBS, 40): w[i, j] = 2^(BITS·i) mod p_j
_NZ_W = np.array(
    [[pow(1 << BITS, i, int(p)) for p in _NZ_P] for i in range(NLIMBS)],
    dtype=np.float64,
)
_NZ_QMOD = np.array([Q % int(p) for p in _NZ_P], dtype=np.float64)
# quotient-estimate weights 2^(BITS·i)/Q (≤ ~2^11 for the top limb)
_NZ_EST = np.array(
    [float(1 << (BITS * i)) / float(Q) for i in range(NLIMBS)], dtype=np.float64
)
# accumulation-exactness envelope for the residue matmul: post-carry3
# limbs are ≤ BASE+1 and weights < max probe prime.  The 8-bit/f32 arm
# accumulates in f32 (50·257·1296 < 2^24, only just); the 11-bit arm's
# sums exceed 2^24 and MUST accumulate in int32 (< 2^31 with margin) —
# an f32 accumulation there silently rounds residues and turns the zero
# test into a coin flip (caught by the int32 degenerate-route test arm).
if DTYPE == jnp.float32:
    assert NLIMBS * (BASE + 1) * (int(_NZ_P[0]) - 1) < (1 << 24), (
        "residue-probe matmul would exceed the f32-exact envelope"
    )
else:
    assert NLIMBS * (BASE + 1) * (int(_NZ_P[0]) - 1) < (1 << 31), (
        "residue-probe matmul would exceed int32"
    )


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """Exact in-graph test: does the limb vector represent 0 mod Q?

    Returns a bool array over the batch shape.  Accepts any lazy input
    within the representation's domain (a difference/sum of a few mul
    outputs included) — ``reduce_small`` renormalizes first.  Cost: two
    constant matmuls + a handful of vector passes, ≪ one field mul.
    """
    x = reduce_small(jnp.asarray(x, DTYPE))
    hp = jax.lax.Precision.HIGHEST
    # quotient estimate: post-reduce limbs are ≤ BASE+1 ≤ 2049 — exact in
    # f32 — and the weighted sum is ~|v|/Q ≲ 2^12, so f32 is plenty
    t = jnp.einsum(
        "...i,i->...",
        x.astype(jnp.float32),
        jnp.asarray(_NZ_EST, jnp.float32),
        precision=hp,
    )
    m0 = jnp.round(t)
    hit = jnp.zeros(x.shape[:-1], dtype=bool)
    if DTYPE == jnp.int32:
        y = jnp.mod(
            jnp.einsum("...i,ij->...j", x, jnp.asarray(_NZ_W, jnp.int32)),
            jnp.asarray(_NZ_P, jnp.int32),
        )
        qmod = jnp.asarray(_NZ_QMOD, jnp.int32)
        p_i = jnp.asarray(_NZ_P, jnp.int32)
        m0_i = m0.astype(jnp.int32)
        for c in (-2, -1, 0, 1, 2):
            # (m0+c)·qmod ≤ ~2^13·1300 < 2^24 — int32-exact
            r = y - jnp.mod((m0_i + c)[..., None] * qmod, p_i)
            hit = hit | jnp.all(jnp.mod(r, p_i) == 0, axis=-1)
        return hit
    y = jnp.einsum(
        "...i,ij->...j",
        x,
        jnp.asarray(_NZ_W, jnp.float32),
        precision=hp,
    )
    p = jnp.asarray(_NZ_P, jnp.float32)
    invp = jnp.asarray(1.0 / _NZ_P, jnp.float32)
    qmod = jnp.asarray(_NZ_QMOD, jnp.float32)

    def modp(v):
        # exact for integer-valued f32 |v| < 2^24: one estimated-quotient
        # pass, then two branchless clamps (floor may be off by one)
        v = v - jnp.floor(v * invp) * p
        v = v - p * (v >= p)
        return v + p * (v < 0)

    y = modp(y)
    for c in (-2, -1, 0, 1, 2):
        # (m0+c)·qmod ≤ ~600·1300 < 2^20 — f32-exact before the mod
        r = y - modp((m0 + c)[..., None] * qmod)
        hit = hit | jnp.all(r == 0, axis=-1)
    return hit
