"""Batched SHA-256 on the accelerator: the hash half of the device
erasure/hash plane (PR 19).

The RBC plane is O(N²) hashes per epoch — N proposers × N Merkle proofs
over RS shards — and `crypto/merkle.py` frames all of them with two
fixed-shape message forms:

* leaf hash:  ``sha256(b"\\x00" + data)`` — uniform ``leaf_len`` across a
  proposer batch (RS shards of one encode share a length);
* node hash:  ``sha256(b"\\x01" + left32 + right32)`` — always 65 bytes.

Fixed shapes mean SHA-256's padding is STATIC per trace, so the whole
plane vectorizes as plain ``uint32`` array ops batched over a leading
axis: message schedule + 64-round compression with no per-item control
flow, rounds and blocks both folded with ``lax.scan`` so the graph stays
O(1) in rounds and leaf length (see ``_compress`` for the compile-budget
rationale).  No Pallas kernel is needed — the compression is
element-wise u32 arithmetic the XLA fusion already handles; the win is
batching, not a hand-tiled loop.

Entry points (all ``jax.jit``, retraced per shape):

* :func:`leaf_hashes` / :func:`node_hashes` — tagged hashing primitives.
* :func:`tree_levels` — all T proposers' full Merkle levels in one
  dispatch (leaves padded to the power-of-two with ``_h_leaf(b"")``,
  matching ``MerkleTree.__init__`` bit for bit).
* :func:`verify_proofs` — the batched proof walk over
  ``crypto/merkle.PackedProofs``-shaped arrays (leaves, paths, indices,
  roots → per-proof booleans), the device twin of
  ``native.merkle_validate_batch``.

Everything here returns device arrays; fetching is the caller's job
(``ops/backend.py`` routes results through the DispatchPipeline seam).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# FIPS 180-4 constants.
_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)
_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x, n: int):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress(state, w):
    """One compression round over a batch: ``state`` (B, 8) u32 +
    message-block words ``w`` (B, 16) u32 → new (B, 8) state.

    Both the 48-word schedule expansion and the 64 rounds run as
    ``lax.scan``s, NOT unrolled python loops: the first cut unrolled
    ≈650 element-wise ops per entry point and XLA:CPU spent ~10 s
    compiling EACH (entry point × shape) — across the engine's tree /
    proof shapes that blew straight through the 870 s tier-1 window
    (the same compile-budget lesson as the PR-4 GLV table build).  The
    scan body is ~15 ops, so per-shape compiles drop to well under a
    second; multi-block messages scan over this whole function."""
    n_batch = w.shape[0]

    def sched(win, _):
        # win: (B, 16) rolling window of words t-16 .. t-1
        w15 = win[:, 1]
        w2 = win[:, 14]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> jnp.uint32(3))
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> jnp.uint32(10))
        nw = win[:, 0] + s0 + win[:, 9] + s1
        return jnp.concatenate([win[:, 1:], nw[:, None]], axis=1), nw

    _, extra = jax.lax.scan(sched, w, None, length=48)  # (48, B)
    ws = jnp.concatenate([jnp.swapaxes(w, 0, 1), extra], axis=0)  # (64, B)

    def rnd(vars8, wk):
        a, b, c, d, e, f, g, h = vars8
        wt, kt = wk
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + s0 + maj, a, b, c, d + t1, e, f, g), None

    init = tuple(state[:, i] for i in range(8))
    ks = jnp.broadcast_to(jnp.asarray(_K)[:, None], (64, n_batch))
    out, _ = jax.lax.scan(rnd, init, (ws, ks))
    return jnp.stack(out, axis=1) + state


def _pad_tagged(msgs, tag: int):
    """FIPS padding for ``tag_byte + msgs[i]``, batched: (B, L) u8 →
    (B, nblocks, 16) big-endian u32 words.  ``L`` is static per trace,
    so the pad layout is a compile-time constant."""
    n_msgs, msg_len = msgs.shape
    total = msg_len + 1  # tag byte
    nblocks = (total + 9 + 63) // 64
    fill = nblocks * 64 - total - 8
    bitlen = total * 8
    tag_col = jnp.full((n_msgs, 1), tag, dtype=jnp.uint8)
    mid = jnp.zeros((n_msgs, fill), dtype=jnp.uint8).at[:, 0].set(0x80)
    lenbytes = jnp.asarray(
        [(bitlen >> (8 * (7 - i))) & 0xFF for i in range(8)], dtype=jnp.uint8
    )
    buf = jnp.concatenate(
        [tag_col, msgs, mid, jnp.broadcast_to(lenbytes, (n_msgs, 8))], axis=1
    )
    quads = buf.reshape(n_msgs, nblocks, 16, 4).astype(jnp.uint32)
    return (
        (quads[..., 0] << jnp.uint32(24))
        | (quads[..., 1] << jnp.uint32(16))
        | (quads[..., 2] << jnp.uint32(8))
        | quads[..., 3]
    )


def _digest_bytes(state):
    """(B, 8) u32 state → (B, 32) u8 big-endian digests."""
    shifts = jnp.asarray([24, 16, 8, 0], dtype=jnp.uint32)
    parts = (state[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    return parts.reshape(state.shape[0], 32).astype(jnp.uint8)


def _sha256_tagged(msgs, tag: int):
    """sha256(bytes([tag]) + m) for every row of ``msgs`` ((B, L) u8 →
    (B, 32) u8).  Blocks fold under ``lax.scan`` so long leaves don't
    inflate the graph."""
    words = _pad_tagged(msgs, tag)
    state0 = jnp.broadcast_to(jnp.asarray(_H0), (msgs.shape[0], 8))

    def body(state, block):
        return _compress(state, block), None

    state, _ = jax.lax.scan(body, state0, jnp.swapaxes(words, 0, 1))
    return _digest_bytes(state)


@jax.jit
def leaf_hashes(leaves):
    """Batched ``merkle._h_leaf``: (B, L) u8 → (B, 32) u8."""
    return _sha256_tagged(leaves, 0)


@jax.jit
def node_hashes(left, right):
    """Batched ``merkle._h_node``: two (B, 32) u8 → (B, 32) u8."""
    return _sha256_tagged(jnp.concatenate([left, right], axis=1), 1)


@jax.jit
def tree_levels(leaves):
    """All T proposers' Merkle levels in one dispatch.

    ``leaves``: (T, n, leaf_len) u8 real leaves.  Returns the tuple of
    levels ((T, size, 32), (T, size/2, 32), …, (T, 1, 32)) with ``size``
    the next power of two ≥ n and missing leaves padded with
    ``_h_leaf(b"")`` — the exact construction of ``MerkleTree.__init__``,
    so a host tree built from these levels is bit-identical to one that
    hashed on the host."""
    n_trees, n_leaves, leaf_len = leaves.shape
    size = 1
    while size < n_leaves:
        size *= 2
    level = _sha256_tagged(leaves.reshape(n_trees * n_leaves, leaf_len), 0)
    level = level.reshape(n_trees, n_leaves, 32)
    if size > n_leaves:
        pad = _sha256_tagged(jnp.zeros((1, 0), dtype=jnp.uint8), 0)
        level = jnp.concatenate(
            [level, jnp.broadcast_to(pad[None], (n_trees, size - n_leaves, 32))],
            axis=1,
        )
    levels = [level]
    while level.shape[1] > 1:
        half = level.shape[1] // 2
        left = level[:, 0::2].reshape(n_trees * half, 32)
        right = level[:, 1::2].reshape(n_trees * half, 32)
        level = _sha256_tagged(
            jnp.concatenate([left, right], axis=1), 1
        ).reshape(n_trees, half, 32)
        levels.append(level)
    return tuple(levels)


@jax.jit
def verify_proofs(leaves, paths, indices, roots):
    """Batched Merkle proof walk over PackedProofs-shaped arrays.

    ``leaves`` (P, leaf_len) u8, ``paths`` (P, depth, 32) u8 sibling
    chains, ``indices`` (P,) int leaf positions, ``roots`` (P, 32) u8 —
    returns (P,) bool, one verdict per proof.  Same walk as
    ``merkle.Proof.validate``: at depth d the accumulator is the left
    operand iff bit d of the index is 0."""
    acc = _sha256_tagged(leaves, 0)
    idx = indices.astype(jnp.int32)
    for d in range(paths.shape[1]):
        sib = paths[:, d]
        is_left = ((idx >> d) & 1) == 0
        left = jnp.where(is_left[:, None], acc, sib)
        right = jnp.where(is_left[:, None], sib, acc)
        acc = _sha256_tagged(jnp.concatenate([left, right], axis=1), 1)
    return jnp.all(acc == roots, axis=1)
