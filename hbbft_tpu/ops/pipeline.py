"""Pipelined device dispatch — the deferred-fetch seam.

The measured N=100 real-crypto epoch (PERF.md round-2 eighth pass) is a
strictly serial loop: host assembly (limb packing, ``scalars_to_bits``,
affine staging) runs between every one of the ~42 device dispatches
because the backend fetched each jitted call's result synchronously.
JAX's dispatch model is asynchronous — a jitted call returns immediately
with the computation enqueued on the device stream; only materializing
the output (``np.asarray``) blocks.  This module exploits that: a
dispatch is *submitted* (launched on the device) and its fetch is
*deferred* behind a bounded in-flight queue, so the host assembles chunk
k+1 while chunk k executes on device.

Contract (what the backend and the tests rely on):

* **Bit-identical outputs.**  Pipelining changes only *when* a result is
  materialized, never what was computed: every submitted dispatch runs
  the same jitted graph on the same staged inputs as the synchronous
  path, and each delivery callback writes to slots no other callback
  touches.  ``HBBFT_TPU_NO_PIPELINE=1`` forces depth 0 (fetch before
  ``submit`` returns) — the literal pre-pipeline behavior.
* **Bounded in-flight buffers.**  At most ``depth`` (default 2,
  ``HBBFT_TPU_PIPELINE_DEPTH``) unfetched dispatches are held — pending
  output buffers scale HBM with the queue, and the lane caps that size
  each dispatch (ops/backend.py ``device_lane_cap``) assume only a
  couple of chunks are live at once.  Submitting when full first
  resolves the oldest entry (FIFO), momentarily holding depth+1 while
  the new launch overlaps the old fetch's host-side delivery work.
* **Attribution is unchanged in shape.**  Each dispatch bills its full
  dispatch→fetch wall interval [t0, t1] to ``counters.device_seconds``
  (+ the per-kind bucket) and emits the *identical* interval as a
  ``device=True`` tracer span — exactly the synchronous seam's contract,
  so traced device time and counter attribution still agree by
  construction (tools/trace_report.py).  Pipelined intervals *overlap*
  in wall time; each in-flight slot therefore gets its own tracer track
  (``device/<slot>``) so B/E pairs still nest per track, and
  ``counters.overlap_seconds`` accumulates the host time spent between
  issuing a dispatch and requesting its fetch — the measure of how much
  assembly actually hid under device execution.

The *only* host sync point is :func:`fetch_to_host` below — the
``deferred-fetch`` lint rule (analysis/rules_tracer.py) flags any
``np.asarray``/``jax.device_get`` reappearing in the dispatch layer
outside this module, so the pipeline stays the single fetch seam.

Import-light on purpose: no numpy/jax at module scope, so
crypto/backend.py's MockBackend can reuse the queue machinery (simulated
async completion order in tier-1) without pulling in JAX.
"""

from __future__ import annotations

import heapq
import os
import time
from collections import deque
from typing import Any, Callable, List, Optional


def hostpipe_enabled() -> bool:
    """Kill switch for the HOST half of the epoch (PR 5): the array
    engine's vectorized assembly/scatter fast paths and its cross-round
    deferred-verify overlap.  ``HBBFT_TPU_NO_HOSTPIPE=1`` restores the
    legacy per-item loops and strictly ordered verification — outputs
    are bit-identical and ``device_dispatches`` unchanged either way
    (asserted in tests/test_host_buckets.py).  Re-read per epoch so
    in-process A/Bs take effect immediately."""
    return not os.environ.get("HBBFT_TPU_NO_HOSTPIPE")


def device_rs_enabled() -> bool:
    """Kill switch for the device erasure/hash plane (PR 19): batched RS
    encode/reconstruct as GF(2⁸) bit-matmuls and device SHA-256 Merkle
    build/verify routed through the dispatch seam.
    ``HBBFT_TPU_NO_DEVICE_RS=1`` restores the host codec/hashlib path
    bit-for-bit (asserted in tests/test_device_rs.py).  Re-read per call
    so in-process A/Bs take effect immediately."""
    return not os.environ.get("HBBFT_TPU_NO_DEVICE_RS")


def pipeline_depth() -> int:
    """Max in-flight dispatches.  Re-read per submit so in-process A/Bs
    (``HBBFT_TPU_NO_PIPELINE=1`` vs. default) take effect immediately."""
    if os.environ.get("HBBFT_TPU_NO_PIPELINE"):
        return 0
    try:
        d = int(os.environ.get("HBBFT_TPU_PIPELINE_DEPTH", "2"))
    except ValueError:
        d = 2
    return max(0, d)


def fetch_to_host(out):
    """THE deferred-fetch seam: materialize a jitted call's output tree
    on host.  Blocks until the device computation completes."""
    import jax
    import numpy as np

    return jax.tree_util.tree_map(np.asarray, out)


class PendingDispatch:
    """One launched-but-unfetched dispatch.

    ``value`` is populated by :meth:`resolve` (idempotent); ``slot`` is
    the in-flight tracer-track index, or None for a synchronous entry
    (sync entries span the classic ``device`` track)."""

    __slots__ = (
        "t0", "t_issued", "kind", "items", "slot", "_blocked_at_issue",
        "_raw", "_fetch", "_on_result", "_pipe", "done", "value",
    )

    def __init__(self, pipe, raw, fetch, kind, items, slot, on_result, t0, t_issued):
        self._pipe = pipe
        self._raw = raw
        self._fetch = fetch
        self._on_result = on_result
        self.kind = kind
        self.items = items
        self.slot = slot
        self.t0 = t0
        self.t_issued = t_issued
        self._blocked_at_issue = pipe._fetch_blocked
        self.done = False
        self.value: Any = None

    def resolve(self):
        """Fetch + bill + deliver (no-op after the first call)."""
        return self._pipe._resolve(self)


class DispatchPipeline:
    """Bounded FIFO of in-flight dispatches with deferred fetches.

    ``counters`` (a ``utils.metrics.Counters`` or None) receives the
    device-time / overlap attribution; ``tracer_ref`` is a zero-arg
    callable returning the live tracer (the backend's tracer is attached
    *after* construction, so it must be read at resolve time).
    ``depth_fn`` overrides the env-driven depth (tests, MockBackend).
    """

    def __init__(
        self,
        counters=None,
        tracer_ref: Optional[Callable[[], Any]] = None,
        depth_fn: Callable[[], int] = pipeline_depth,
    ) -> None:
        self._counters = counters
        self._tracer_ref = tracer_ref
        self._depth_fn = depth_fn
        #: opt-in happens-before probe (analysis/schedules.RaceTracker):
        #: when attached, every submit/resolve reports an event with a
        #: vector clock, so a schedule divergence is reported as a
        #: concrete racing access pair.  None costs one attribute check.
        self.probe = None
        self._q: deque = deque()
        self._free_slots: List[int] = []
        self._slots_created = 0
        #: cumulative host seconds spent BLOCKED inside fetches.  Each
        #: entry snapshots this at launch so its overlap window can
        #: exclude time the host spent waiting on OTHER entries' fetches
        #: — otherwise overlap_seconds would count fetch-block wall as
        #: "hidden assembly" and overstate the pipeline's win.
        self._fetch_blocked = 0.0

    def __len__(self) -> int:
        return len(self._q)

    def _alloc_slot(self) -> int:
        # lint: allow[seam-race] slot ids are recycled only after their
        # dispatch resolved; reuse order affects tracer track NAMES only,
        # never delivered values (callbacks write disjoint slots)
        if self._free_slots:
            return heapq.heappop(self._free_slots)
        s = self._slots_created
        self._slots_created += 1
        return s

    def submit(
        self,
        launch: Callable[[], Any],
        fetch: Optional[Callable[[Any], Any]] = fetch_to_host,
        kind: str = "",
        items: int = 0,
        on_result: Optional[Callable[[Any], None]] = None,
        sync: bool = False,
    ) -> PendingDispatch:
        """Launch one dispatch; defer its fetch up to the queue depth.

        ``launch()`` issues the (async) device call and returns the raw
        output handle; ``fetch(raw)`` materializes it on host (None =
        identity, for host-computed mock entries).  ``on_result(value)``
        delivers the fetched value — it must write only slots owned by
        this dispatch, so completion order never matters.

        ``sync=True`` (or depth 0 via the kill switch) resolves every
        older entry and then this one before returning — the exact
        pre-pipeline synchronous behavior, used where control flow needs
        the result immediately (RLC bisection rounds, single combines).
        """
        depth = 0 if sync else self._depth_fn()
        t0 = time.perf_counter()
        raw = launch()
        t_issued = time.perf_counter()
        slot = None if depth <= 0 else self._alloc_slot()
        p = PendingDispatch(
            self, raw, fetch, kind, items, slot, on_result, t0, t_issued
        )
        if self.probe is not None:
            self.probe.pipe_submit(p)
        if depth <= 0:
            # Drain FIFO first so delivery order degenerates to program
            # order — byte-compatible with the pre-pipeline seam.
            # lint: allow[seam-race] _q IS the pipeline API: the bounded
            # FIFO handoff itself; entries are opaque PendingDispatch
            # objects and every delivery writes only slots it owns
            while self._q:
                self._q.popleft().resolve()
            self._resolve(p)
            return p
        self._q.append(p)
        # Launch-then-trim: the new dispatch is already on the device
        # stream while the oldest entry's fetch (and its host-side
        # delivery work, e.g. Jacobian→affine conversion) runs.
        while len(self._q) > depth:
            self._q.popleft().resolve()
        return p

    def flush(self, order: Optional[List[int]] = None) -> None:
        """Resolve every pending dispatch (FIFO, or by explicit ``order``
        — a permutation of indices into the current pending list, used by
        MockBackend to exercise out-of-order completion deterministically)."""
        pending = list(self._q)
        self._q.clear()
        if order is not None:
            pending = [pending[i] for i in order]
        for p in pending:
            p.resolve()

    def _track_for(self, p: PendingDispatch) -> str:
        """Tracer track a resolved dispatch's span lands on.  The shard
        pipeline (parallel/shardpipe.py) overrides this so sharded
        entries span their DEVICE's ``device/<n>`` track instead of the
        in-flight slot's."""
        return "device" if p.slot is None else f"device/{p.slot}"

    def _bill_device(self, p: PendingDispatch, dt: float) -> None:
        """Per-device attribution hook (no-op on the single-queue
        pipeline): called once per resolve with the same [t0, t1]
        interval the counters and tracer bill."""

    def _resolve(self, p: PendingDispatch):
        if p.done:
            return p.value
        p.done = True
        if self.probe is not None:
            self.probe.pipe_resolve(p)
        t_req = time.perf_counter()
        # fetch-block seconds other entries accrued inside THIS entry's
        # [t_issued, t_req] window — sampled before our own fetch adds in
        blocked_in_window = self._fetch_blocked - p._blocked_at_issue
        value = p._fetch(p._raw) if p._fetch is not None else p._raw
        t1 = time.perf_counter()
        self._fetch_blocked += t1 - t_req
        p._raw = None  # release the device buffer reference
        dt = t1 - p.t0
        self._bill_device(p, dt)
        c = self._counters
        if c is not None:
            # host-bucket attribution (obs/hostbuckets.py): the fetch
            # itself is device WAIT, not host work — regions subtract it
            c.fetch_blocked_seconds += t1 - t_req
            c.device_seconds += dt
            if p.kind:
                name = "device_seconds_" + p.kind
                setattr(c, name, getattr(c, name) + dt)
            if p.slot is not None:
                # Host time spent doing USEFUL work while this dispatch
                # was in flight: launch return → fetch request, minus
                # the stretches spent blocked in other entries' fetches.
                # This is the assembly (and delivery) work that actually
                # hid under device execution.
                c.overlap_seconds += max(
                    0.0, (t_req - p.t_issued) - blocked_in_window
                )
                c.pipelined_dispatches += 1
        tr = self._tracer_ref() if self._tracer_ref is not None else None
        if tr is not None:
            track = self._track_for(p)
            tr.complete(
                f"dispatch:{p.kind or 'unkinded'}", p.t0, t1,
                cat=p.kind or "unkinded", track=track, items=p.items,
                device=True,
            )
            if p.items:
                tr.hist("dispatch_batch_items").record(p.items)
        if p.slot is not None:
            heapq.heappush(self._free_slots, p.slot)
        p.value = value
        if p._on_result is not None:
            p._on_result(value)
        return value
