"""Pallas TPU kernel: the ENTIRE full-RNS Montgomery multiply fused in VMEM.

The XLA path (ops/fq_rns.mul) is ~15 elementwise stages around four small
constant matmuls; between fused groups XLA materializes (lanes, 79) f32
intermediates in HBM, and at ~3k VPU ops per mul against ~10 buffer
round-trips the pipeline is HBM-bound (the round-3 roofline, PERF.md).
This kernel keeps every stage — input renormalization, pointwise product,
both Montgomery base extensions, the Shenoy–Kumaresan correction — in
VMEM; HBM traffic drops to the two operands and the result.

Layout inside the kernel is **residues-on-sublanes, lanes-on-minor**
((80, T) tiles): stage k is then a full-width VPU op over all T lanes.
The 79 residue rows are PADDED to 80 with one dead row between the bases:

    rows  0:39  base B1          rows 40:79  base B2
    row     39  pad (zeros)      row     79  m_r (S-K redundant modulus)

so every slice the algorithm takes — B1∪pad = [0:40), B2∪m_r = [40:80) —
starts on a sublane-aligned offset and has width 40.  (B2∪{m_r} is
exactly the 40-wide unit the Montgomery pipeline works in: x2r, r2r and
their constants; the pad row rides along with all-zero constants and
stays identically zero.)

The base-extension matmuls run on the MXU as EXPLICIT bf16 bit-planes:
both the constant matrices (entry-split e = e_lo + 64·e_hi at module
load, entries ≤ 63) and the 11-bit digit vectors (split in-kernel into a
6-bit lo / 5-bit hi plane) are bf16-representable integers, products
accumulate in f32 (exact: 40 terms of ≤ 63·63 < 2^18), and the weighted
recombination reduces the hi partials before scaling so every sum stays
inside the 2^24 f32-exact envelope (round-5 form: the mid partial is
LOOSE-reduced to (−p, 2p), see _split_dot's bound derivation):

    ll + 64·loose(lh + hl) + 4096·mod(hh)
        ∈ (−262k, 159k + 262k + 8.39M) ⊂ (−2^24, 2^24)

This sidesteps any reliance on Mosaic's f32-dot precision lowering — the
operands ARE bf16, exactly (the fq_rns.py:293 "bit-plane split" lever).

Routing (see fq_rns._use_fused): HBBFT_TPU_RNS_FUSED=pow (default on TPU)
routes only pow_fixed — the 380-iteration Fermat-inverse chain where the
round-2 record shows fused kernels WIN (one launch vs ~760 dispatched
stacked muls); =all also routes every mul (the per-mul A/B lost 1.4-2.6×
for the LIMB kernels on-chip round 2 — the RNS re-match is a
tools/tpu_window.sh item); =0 disables.  HBBFT_TPU_NO_PALLAS disables
everything (bench.py's compile-failure fallback ladder relies on this).

Falls back to interpret mode off-TPU, which is how the CPU suite
golden-checks it (tests/test_fq_rns_pallas.py).

Reference analogue: the `ff` crate's Montgomery multiply under
threshold_crypto (SURVEY.md §2.2) — here as one resident-VMEM TPU kernel.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os

from hbbft_tpu.ops import fq_rns as R

#: lanes per grid step (4 × (8, 128) VPU tiles by default).  Env knob for
#: the on-chip tuning A/B (tools/tpu_window.sh): larger tiles amortize
#: grid overhead, smaller ones overlap better with the extension matmuls.
TILE = int(os.environ.get("HBBFT_TPU_RNS_TILE", "512"))
# fail fast at import: 0 would divide-by-zero in _lane_count, and a
# non-multiple of 128 dies deep in Mosaic lane tiling mid-window
assert TILE > 0 and TILE % 128 == 0, f"HBBFT_TPU_RNS_TILE={TILE} not a multiple of 128"
NROWS = 80  # 39 B1 + pad + 39 B2 + m_r
_NB = R.N_B  # 39
_PAD_P = 1031.0  # pad-row modulus: any positive value keeps 0 → 0 exact

DTYPE = jnp.float32

# -- constants in kernel layout (module load; Python ints → f32 columns) -----


def _col(vals) -> np.ndarray:
    return np.asarray(vals, dtype=np.float32).reshape(-1, 1)


def _pad40(v39, pad=0.0) -> list:
    return list(v39) + [pad]


# full-width (80, 1) columns: [B1 | pad | B2 | m_r]
_P80 = _col(R.B1 + [_PAD_P] + R.B2 + [R.M_R])
_INVP80 = 1.0 / _P80
_XOFF80 = _col(
    [R._X_OFFSET_INT % p for p in R.B1]
    + [0]
    + [R._X_OFFSET_INT % p for p in R.B2]
    + [R._X_OFFSET_INT % R.M_R]
)

# (40, 1) columns for the B1∪pad half (pad row constant 0 → stays zero)
_SIGMA_C40 = _col(_pad40([float(c) for c in R._SIGMA_C_B1]))
_P1_40 = _P80[:40]
_INVP1_40 = _INVP80[:40]

# (40, 1) columns for the B2∪m_r half (order [B2..., m_r] matches rows 40:80)
_P2R_40 = _P80[40:]
_INVP2R_40 = _INVP80[40:]
_M1INV40 = _col([float(c) for c in R._M1INV_B2R])
_QM1INV40 = _col([float(c) for c in R._QM1INV_B2R])
_W2INV40 = _col(_pad40([float(c) for c in R._W2INV_B2]))  # 0 at the m_r row

# ext-2 OUTPUT rows are B1∪{m_r}: [B1..., m_r] — row 39 is m_r here, so its
# modulus column differs from _P1_40 at that row only.
_PB1R40 = _col(_pad40(R.B1, pad=R.M_R))
_INVPB1R40 = 1.0 / _PB1R40
_M2B1R40 = _col(_pad40([float(c) for c in R._M2_B1]))  # 0 at the m_r row
_M2INV_R = float(R._M2INV_R)
_MR = float(R.M_R)

# Extension matrices, transposed to (out=40, in=40) with a zero column for
# the pad/dead input row, entry-split into bf16 planes (entries ≤ 63).
def _ext_T_pad(e_lo: np.ndarray, e_hi: np.ndarray):
    """(39, 40) split matrices → two (40, 40) bf16 operands E^T."""
    def one(e):
        t = np.zeros((40, 40), dtype=np.float32)
        t[:, :_NB] = e.T  # out-rows × in-cols; input col 39 stays zero
        return t
    return one(e_lo), one(e_hi)


_E1T_LO, _E1T_HI = _ext_T_pad(R._E1_LO, R._E1_HI)
_E2T_LO, _E2T_HI = _ext_T_pad(R._E2_LO, R._E2_HI)
# one packed (80, 80) input: [[E1T_LO, E1T_HI], [E2T_LO, E2T_HI]]
_EMAT = np.block([[_E1T_LO, _E1T_HI], [_E2T_LO, _E2T_HI]])

# Per-row constant vectors packed into ONE (80, 16) input (Pallas requires
# array constants as inputs, not captures).  Columns 0-2 are full-width;
# 40-row constants sit in the half of the column their consumer slices.
_NCONST = 16


def _pack_consts() -> np.ndarray:
    c = np.zeros((NROWS, _NCONST), dtype=np.float32)
    c[:, 0:1] = _P80
    c[:, 1:2] = _INVP80
    c[:, 2:3] = _XOFF80
    c[:40, 3:4] = _SIGMA_C40
    c[40:, 4:5] = _M1INV40
    c[40:, 5:6] = _QM1INV40
    c[40:, 6:7] = _W2INV40
    c[:40, 7:8] = _PB1R40
    c[:40, 8:9] = _INVPB1R40
    c[:40, 9:10] = _M2B1R40
    return c


_CONSTS = _pack_consts()


# -- kernel-internal stages ---------------------------------------------------


def _mod_loose(x, p, invp):
    """One-pass reduction to (−p, 2p) — fq_rns._mod_loose, column consts."""
    return x - jnp.floor(x * invp) * p


def _mod_lanes(x, p, invp):
    """Exact reduction to [0, p) — fq_rns._mod_lanes, column consts."""
    x = x - jnp.floor(x * invp) * p
    x = x - p * (x >= p).astype(DTYPE)
    x = x + p * (x < 0).astype(DTYPE)
    return x


def _split_dot(elo, ehi, v, p, invp, exact: bool = True):
    """mod-p rows of Eᵀ·v via four exact bf16 MXU passes.

    v is an 11-bit digit block (40, T) in [0, p): split into a 6-bit lo
    and 5-bit hi plane, multiply against the pre-split matrix planes, and
    recombine with the hi partials reduced first (bounds in the module
    docstring).

    Round-5 op-count trims (every _mod_lanes is ~4 more VPU lane-ops
    per row than _mod_loose):
    * ``mid`` needs only the LOOSE reduction: |64·mid_loose| < 64·2p =
      262,016, and ll + 64·mid + 4096·hh then spans
      (−262,016, 158,760 + 262,016 + 8,384,512) ⊂ (−2^24, 2^24) — still
      f32-exact.  (``hh`` must stay exact: 4096·2p would already be
      16.77M ≈ 2^24.)
    * ``exact=False`` callers (the SECOND extension) take a loose
      result in (−p, 2p): its m_r digit row is re-reduced exactly by
      the S-K delta step, and its r1 consumer is a _mod_loose over
      |raw − 39·p| — f32-safe at 41p ≪ 2^24.  The FIRST extension's
      q̂ must stay exact: its consumer bound 3p² + q̂·p ≤ 4p² is tight
      (the import-time assert in fq_rns.py)."""
    v_hi = jnp.floor(v * (1.0 / 64.0))
    v_lo = v - 64.0 * v_hi
    f32 = DTYPE

    def dot(m, x):
        return jax.lax.dot_general(
            m.astype(jnp.bfloat16),
            x.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=f32,
        )

    ll = dot(elo, v_lo)
    mid = _mod_loose(dot(elo, v_hi) + dot(ehi, v_lo), p, invp)
    hh = _mod_lanes(dot(ehi, v_hi), p, invp)
    out = ll + 64.0 * mid + 4096.0 * hh
    return _mod_lanes(out, p, invp) if exact else _mod_loose(out, p, invp)


def _mul_core(a, b, em, cm, reduced: bool):
    """(80, T) CARRIED-or-lazy operands → (80, T) Montgomery product.

    Mirrors fq_rns.mul stage for stage (same bounds, same comments there);
    ``cm`` is the packed (80, 16) constant matrix (_pack_consts);
    ``reduced=True`` skips the input renormalization — valid whenever both
    operands are outputs of this core (lanes already in (−p, 2p), so
    |a·b| ≤ 4p² < 2^24 holds without the extra pass — the chain/pow
    kernels' steady state)."""
    p80, ip80 = cm[:, 0:1], cm[:, 1:2]
    if not reduced:
        a = _mod_loose(a, p80, ip80)
        b = _mod_loose(b, p80, ip80)
    x = _mod_loose(a * b, p80, ip80) + cm[:, 2:3]  # (−p, 3p)

    p1, ip1 = cm[:40, 0:1], cm[:40, 1:2]
    p2r, ip2r = cm[40:, 0:1], cm[40:, 1:2]
    sigma = _mod_lanes(x[:40] * cm[:40, 3:4], p1, ip1)

    qhat = _split_dot(em[:40, :40], em[:40, 40:], sigma, p2r, ip2r)

    x2r = x[40:]
    r2r = _mod_loose(x2r * cm[40:, 4:5] + qhat * cm[40:, 5:6], p2r, ip2r)

    xi = _mod_lanes(r2r * cm[40:, 6:7], p2r, ip2r)
    raw = _split_dot(
        em[40:, :40], em[40:, 40:], xi, cm[:40, 7:8], cm[:40, 8:9],
        exact=False,
    )

    delta = _mod_lanes(
        (raw[39:40] - r2r[39:40]) * _M2INV_R, _MR, 1.0 / _MR
    )  # δ ≤ 39 < m_r — exact
    r1 = _mod_loose(raw - delta * cm[:40, 9:10], p1, ip1)
    return jnp.concatenate([r1, r2r], axis=0)


# -- kernels ------------------------------------------------------------------


def _mul_kernel(a_ref, b_ref, em_ref, cm_ref, out_ref):
    out_ref[:] = _mul_core(a_ref[:], b_ref[:], em_ref[:], cm_ref[:], reduced=False)


def _chain_kernel(a_ref, b_ref, em_ref, cm_ref, out_ref, *, n: int):
    """x ← x·b, n times, never leaving VMEM (kernel-bench ceiling probe)."""
    em, cm = em_ref[:], cm_ref[:]
    p80, ip80 = cm[:, 0:1], cm[:, 1:2]
    b = _mod_loose(b_ref[:], p80, ip80)
    x = _mod_loose(a_ref[:], p80, ip80)

    def body(_, x):
        return _mul_core(x, b, em, cm, reduced=True)

    out_ref[:] = jax.lax.fori_loop(0, n, body, x)


def _pow_kernel(bits_ref, x_ref, em_ref, cm_ref, out_ref):
    """Square-and-multiply chain in ONE kernel (fq_pallas._pow_kernel's
    shape: SMEM bit schedule, branch-free blend body)."""
    em, cm = em_ref[:], cm_ref[:]
    p80, ip80 = cm[:, 0:1], cm[:, 1:2]
    x = _mod_loose(x_ref[:], p80, ip80)
    nbits = bits_ref.shape[0]

    def body(i, acc):
        sq = _mul_core(acc, acc, em, cm, reduced=True)
        withx = _mul_core(sq, x, em, cm, reduced=True)
        return jnp.where(bits_ref[i] > 0, withx, sq)

    # MSB is implicit: acc starts at x, loop covers bits [1, nbits).
    out_ref[:] = jax.lax.fori_loop(1, nbits, body, x)


@functools.lru_cache(maxsize=None)
def _mul_call(n_tiles: int, interpret: bool):
    return pl.pallas_call(
        _mul_kernel,
        out_shape=jax.ShapeDtypeStruct((NROWS, n_tiles * TILE), DTYPE),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((NROWS, TILE), lambda i: (0, i)),
            pl.BlockSpec((NROWS, TILE), lambda i: (0, i)),
            pl.BlockSpec((NROWS, NROWS), lambda i: (0, 0)),
            pl.BlockSpec((NROWS, _NCONST), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((NROWS, TILE), lambda i: (0, i)),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _chain_call(n_tiles: int, n: int, interpret: bool):
    return pl.pallas_call(
        functools.partial(_chain_kernel, n=n),
        out_shape=jax.ShapeDtypeStruct((NROWS, n_tiles * TILE), DTYPE),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((NROWS, TILE), lambda i: (0, i)),
            pl.BlockSpec((NROWS, TILE), lambda i: (0, i)),
            pl.BlockSpec((NROWS, NROWS), lambda i: (0, 0)),
            pl.BlockSpec((NROWS, _NCONST), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((NROWS, TILE), lambda i: (0, i)),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _pow_call(n_tiles: int, nbits: int, interpret: bool):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((NROWS, TILE), lambda i, *_: (0, i)),
            pl.BlockSpec((NROWS, NROWS), lambda i, *_: (0, 0)),
            pl.BlockSpec((NROWS, _NCONST), lambda i, *_: (0, 0)),
        ],
        out_specs=pl.BlockSpec((NROWS, TILE), lambda i, *_: (0, i)),
    )
    return pl.pallas_call(
        _pow_kernel,
        out_shape=jax.ShapeDtypeStruct((NROWS, n_tiles * TILE), DTYPE),
        grid_spec=grid_spec,
        interpret=interpret,
    )


# -- layout conversion + public wrappers -------------------------------------


def _lane_count(shape) -> tuple:
    lanes = 1
    for d in shape[:-1]:
        lanes *= d
    return lanes, max(1, -(-lanes // TILE))


def _to_rows(x: jnp.ndarray, lanes: int, n_tiles: int) -> jnp.ndarray:
    """(..., 79) → padded kernel layout (80, n_tiles·TILE)."""
    flat = x.reshape(lanes, R.NLIMBS).T
    z = jnp.zeros((1, lanes), dtype=DTYPE)
    rows = jnp.concatenate([flat[:_NB], z, flat[_NB:]], axis=0)
    pad = n_tiles * TILE - lanes
    return jnp.pad(rows, ((0, 0), (0, pad))) if pad else rows


def _from_rows(out: jnp.ndarray, lanes: int, shape) -> jnp.ndarray:
    body = jnp.concatenate([out[:_NB, :lanes], out[40:, :lanes]], axis=0)
    return body.T.reshape(shape)


def _prep(a, b):
    shape = jnp.broadcast_shapes(jnp.shape(a), jnp.shape(b))
    a = jnp.broadcast_to(jnp.asarray(a, DTYPE), shape)
    b = jnp.broadcast_to(jnp.asarray(b, DTYPE), shape)
    lanes, n_tiles = _lane_count(shape)
    return shape, a, b, lanes, n_tiles


def mul(a: jnp.ndarray, b: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Drop-in for fq_rns.mul: (..., 79) lazy residues in, same out."""
    shape, a, b, lanes, n_tiles = _prep(a, b)
    out = _mul_call(n_tiles, interpret)(
        _to_rows(a, lanes, n_tiles),
        _to_rows(b, lanes, n_tiles),
        jnp.asarray(_EMAT),
        jnp.asarray(_CONSTS),
    )
    return _from_rows(out, lanes, shape)


def mul_chain(
    a: jnp.ndarray, b: jnp.ndarray, n: int, interpret: bool = False
) -> jnp.ndarray:
    """n chained Montgomery products x ← x·b in one kernel launch."""
    shape, a, b, lanes, n_tiles = _prep(a, b)
    out = _chain_call(n_tiles, n, interpret)(
        _to_rows(a, lanes, n_tiles),
        _to_rows(b, lanes, n_tiles),
        jnp.asarray(_EMAT),
        jnp.asarray(_CONSTS),
    )
    return _from_rows(out, lanes, shape)


def pow_fixed(x: jnp.ndarray, exponent: int, interpret: bool = False) -> jnp.ndarray:
    """x^exponent (Python-int exponent ≥ 1) — one kernel launch.

    Drop-in for fq_rns.pow_fixed; the Fermat-inverse chain (exponent
    Q−2, 380 bits) is the shape this kernel exists for."""
    if exponent < 1:
        raise ValueError("pow_fixed kernel requires exponent >= 1")
    bits = np.asarray([int(c) for c in bin(exponent)[2:]], dtype=np.int32)
    shape = jnp.shape(x)
    x = jnp.asarray(x, DTYPE)
    lanes, n_tiles = _lane_count(shape)
    out = _pow_call(n_tiles, len(bits), interpret)(
        jnp.asarray(bits),
        _to_rows(x, lanes, n_tiles),
        jnp.asarray(_EMAT),
        jnp.asarray(_CONSTS),
    )
    return _from_rows(out, lanes, shape)
