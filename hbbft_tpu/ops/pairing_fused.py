"""Fused Pallas kernels for the pairing verification graph.

The measured cost model on a v5e (PERF.md "per-call overhead"): every
Pallas dispatch carries ~100 µs of fixed overhead (launch + (50, lanes)
relayouts at the kernel boundary), while the arithmetic inside runs at
>200 M Fq-muls/s.  The unfused verification graph makes ~550 sequential
stacked-multiply dispatches (4 per Miller doubling × 63, ~2 per final-exp
x-chain bit × 4 chains, plus glue), so at protocol batch sizes (256–1024
lanes) the graph is ~90% launch overhead — and flat in batch size.

This module collapses whole formula blocks into single kernels, keeping
every intermediate in VMEM and the loop state in **limbs-first packed
layout** ((rows, NLIMBS, lanes)) across the entire scan, so the per-call
boundary transposes disappear too:

* ``_step_call``      — one Miller double-step (f ← f²·l(R), R ← 2R):
                        ~121 Fq products that previously took 4 dispatches
                        plus XLA recombination glue between them.
* ``_cyclo_run_call`` — k consecutive Granger–Scott cyclotomic squarings
                        via an in-kernel fori_loop: one dispatch per
                        zero-run of the x-chain instead of one (or two)
                        per bit.
* ``_mul12_call``     — a full fq12 multiply (54 products, one dispatch);
                        used at the set bits of the x-chain and for the
                        final-exp recombination products.

The kernel bodies re-derive the tower formulas (Karatsuba fq2, Toom-ish
fq6, complex fq12 squaring, sparse line multiply) from the same algebra
as ops/tower.py; equivalence is enforced by golden tests against the
unfused path (tests/test_pairing_fused.py) over random points.

Reference analogue: the `pairing` crate's Miller loop / final
exponentiation under `threshold_crypto` (SURVEY.md §2.2) — restructured
so one TPU kernel launch does the work its CPU code spreads over a
function call tree.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hbbft_tpu.ops import fq
from hbbft_tpu.ops.fq_pallas import (
    _CONV_MODE,
    _CONV_PAD,
    _FOLD_T,
    _SUB,
    _carry_cols,
    _mul_core,
)

TILE = int(os.environ.get("HBBFT_TPU_FUSED_TILE", "512"))

# Packed-state row order for an fq12 element: f[j][i][k] — Fq6 coeff j,
# fq2 coeff i, Fq component k.
F12_ROWS = 12
# Miller scan state: fq12 f (12 rows) + Jacobian G2 R = X, Y, Z (6 rows).
STEP_ROWS = F12_ROWS + 6


def _scratch():
    if _CONV_MODE == "scratch":
        return [pltpu.VMEM((fq.CONV, TILE), fq.DTYPE)]
    if _CONV_MODE == "grouped":
        return [pltpu.VMEM((_SUB, _CONV_PAD, TILE), fq.DTYPE)]
    return []


# ---------------------------------------------------------------------------
# Kernel-local tower algebra on (NLIMBS, T) limb columns.
#
# Every helper takes/returns possibly-lazy values; ``m`` (the only place
# magnitudes are consumed multiplicatively) renormalizes its operands, so
# linear combinations with small coefficients (≤ 8 here) are always safe
# inside the float32 exact envelope (see ops/fq.py domain note).
# ---------------------------------------------------------------------------


def _algebra(fold_t, acc_ref):
    def m(a, b):  # Fq product, carried output
        return _mul_core(_carry_cols(a), _carry_cols(b), fold_t, acc_ref)

    def m2(a, b):  # fq2 Karatsuba: 3 Fq products
        t0 = m(a[0], b[0])
        t1 = m(a[1], b[1])
        t2 = m(a[0] + a[1], b[0] + b[1])
        return (t0 - t1, t2 - t0 - t1)

    def sq2(a):  # fq2 squaring: (a0+a1)(a0−a1), 2·a0a1
        t0 = m(a[0] + a[1], a[0] - a[1])
        t1 = m(a[0], a[1])
        return (t0, t1 + t1)

    return m, m2, sq2


def _add2(a, b):
    return (a[0] + b[0], a[1] + b[1])


def _sub2(a, b):
    return (a[0] - b[0], a[1] - b[1])


def _xi2(a):  # × ξ = 1 + u
    return (a[0] - a[1], a[0] + a[1])


def _add6(a, b):
    return tuple(_add2(x, y) for x, y in zip(a, b))


def _sub6(a, b):
    return tuple(_sub2(x, y) for x, y in zip(a, b))


def _mul_by_v(a):  # fq6 × v
    return (_xi2(a[2]), a[0], a[1])


def _m6(m2, a, b):
    """fq6 product: 6 fq2 products + ξ recombination (tower.fq6_mul)."""
    t0, t1, t2 = m2(a[0], b[0]), m2(a[1], b[1]), m2(a[2], b[2])
    m12 = m2(_add2(a[1], a[2]), _add2(b[1], b[2]))
    m01 = m2(_add2(a[0], a[1]), _add2(b[0], b[1]))
    m02 = m2(_add2(a[0], a[2]), _add2(b[0], b[2]))
    c0 = _add2(t0, _xi2(_sub2(m12, _add2(t1, t2))))
    c1 = _add2(_sub2(m01, _add2(t0, t1)), _xi2(t2))
    c2 = _add2(_sub2(m02, _add2(t0, t2)), t1)
    return (c0, c1, c2)


def _sqr12(m2, f):
    """Complex fq12 squaring (tower.fq12_sqr): c1 = 2·a0a1,
    c0 = (a0+a1)(a0+v·a1) − t − v·t with t = a0·a1."""
    a0, a1 = f
    t = _m6(m2, a0, a1)
    u = _m6(m2, _add6(a0, a1), _add6(a0, _mul_by_v(a1)))
    c0 = _sub6(u, _add6(t, _mul_by_v(t)))
    c1 = _add6(t, t)
    return (c0, c1)


def _mul12(m2, a, b):
    """Full fq12 product: Karatsuba over fq6 (tower.fq12_mul)."""
    a0, a1 = a
    b0, b1 = b
    t0 = _m6(m2, a0, b0)
    t1 = _m6(m2, a1, b1)
    mid = _m6(m2, _add6(a0, a1), _add6(b0, b1))
    c0 = _add6(t0, _mul_by_v(t1))
    c1 = _sub6(mid, _add6(t0, t1))
    return (c0, c1)


def _mul_line(m2, f, line):
    """f × sparse line (l0, l4, l5) — tower.fq12_mul_line."""
    l0, l4, l5 = line
    f0, f1 = f
    a0, a1, a2 = f0
    b0, b1, b2 = f1
    t0 = (m2(a0, l0), m2(a1, l0), m2(a2, l0))
    t1 = (
        _xi2(_add2(m2(b1, l5), m2(b2, l4))),
        _add2(m2(b0, l4), _xi2(m2(b2, l5))),
        _add2(m2(b0, l5), m2(b1, l4)),
    )
    mid = _m6(m2, _add6(f0, f1), (l0, l4, l5))
    c0 = _add6(t0, _mul_by_v(t1))
    c1 = _sub6(mid, _add6(t0, t1))
    return (c0, c1)


# -- packed-state <-> tower-tuple conversion (kernel side) -------------------


def _read_f12(ref_or_arr, base=0):
    g = lambda k: ref_or_arr[base + k]  # noqa: E731
    return (
        ((g(0), g(1)), (g(2), g(3)), (g(4), g(5))),
        ((g(6), g(7)), (g(8), g(9)), (g(10), g(11))),
    )


def _write_f12(ref, f, base=0):
    vals = [c for six in f for two in six for c in two]
    for k, v in enumerate(vals):
        ref[base + k] = v


# ---------------------------------------------------------------------------
# Kernel 1: Miller double-step — f ← f²·l(R), R ← 2R in ONE launch.
# ---------------------------------------------------------------------------


def _double_step_math(m, m2, sq2, X, Y, Z, xP, yP):
    """The fused doubling formulas (pairing._miller_double_step algebra)."""
    XX = sq2(X)
    YY = sq2(Y)
    ZZ = sq2(Z)
    YZ = m2(Y, Z)
    E = (XX[0] + XX[0] + XX[0], XX[1] + XX[1] + XX[1])  # 3X²
    XpYY = _add2(X, YY)
    XXX = m2(XX, X)
    XXZZ = m2(XX, ZZ)
    YZ3 = m2(YZ, ZZ)  # Y·Z³
    C = sq2(YY)  # Y⁴
    T = sq2(XpYY)
    Fv = sq2(E)
    D = _sub2(_sub2(T, XX), C)
    D = _add2(D, D)  # 2((X+Y²)² − X² − Y⁴)
    X3 = _sub2(Fv, _add2(D, D))
    C4 = _add2(_add2(C, C), _add2(C, C))
    C8 = _add2(C4, C4)

    # Line l = 2YZ³·ξ·y_P + (3X³ − 2Y²)·w³ − 3X²Z²·x_P·w⁵
    c1a1 = _sub2(
        (XXX[0] + XXX[0] + XXX[0], XXX[1] + XXX[1] + XXX[1]),
        _add2(YY, YY),
    )
    u = _xi2(_add2(YZ3, YZ3))
    v = (XXZZ[0] + XXZZ[0] + XXZZ[0], XXZZ[1] + XXZZ[1] + XXZZ[1])
    c0a0 = (m(u[0], yP), m(u[1], yP))
    c1a2 = (-m(v[0], xP), -m(v[1], xP))

    EDX3 = m2(E, _sub2(D, X3))
    Y3 = _sub2(EDX3, C8)
    Z3 = _add2(YZ, YZ)

    return (c0a0, c1a1, c1a2), X3, Y3, Z3


def _step_kernel(state_ref, pq_ref, fold_ref, out_ref, acc_ref=None):
    m, m2, sq2 = _algebra(fold_ref[:], acc_ref)
    f = _read_f12(state_ref)
    X = (state_ref[12], state_ref[13])
    Y = (state_ref[14], state_ref[15])
    Z = (state_ref[16], state_ref[17])
    xP, yP = pq_ref[0], pq_ref[1]

    f2 = _sqr12(m2, f)
    line, X3, Y3, Z3 = _double_step_math(m, m2, sq2, X, Y, Z, xP, yP)
    f_new = _mul_line(m2, f2, line)

    _write_f12(out_ref, f_new)
    out_ref[12], out_ref[13] = X3[0], X3[1]
    out_ref[14], out_ref[15] = Y3[0], Y3[1]
    out_ref[16], out_ref[17] = Z3[0], Z3[1]


@functools.lru_cache(maxsize=None)
def _step_call(n_tiles: int, interpret: bool):
    return pl.pallas_call(
        _step_kernel,
        out_shape=jax.ShapeDtypeStruct(
            (STEP_ROWS, fq.NLIMBS, n_tiles * TILE), fq.DTYPE
        ),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((STEP_ROWS, fq.NLIMBS, TILE), lambda i: (0, 0, i)),
            pl.BlockSpec((2, fq.NLIMBS, TILE), lambda i: (0, 0, i)),
            pl.BlockSpec(
                (fq.NLIMBS, fq.CONV - fq.FOLD_FROM), lambda i: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (STEP_ROWS, fq.NLIMBS, TILE), lambda i: (0, 0, i)
        ),
        scratch_shapes=_scratch(),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Kernel 1b (opt-in, HBBFT_TPU_FUSE2=1): the ENTIRE Miller loop in one
# launch.  The 63-bit schedule of |x| is STATIC, so the kernel unrolls it
# as zero-run fori_loops over the double-step with explicit mixed-addition
# steps at the ~6 set bits — one dispatch replaces 63 step dispatches plus
# the XLA add regions.  Untested on real Mosaic until the chip is back:
# kept off the default path (PERF.md round-2 fourth pass).
# ---------------------------------------------------------------------------


def _fuse2() -> bool:
    # Respect the fallback ladder: NO_FUSED / NO_PALLAS must win over the
    # FUSE2 opt-in, or bench/driver retries on "a more conservative path"
    # would recompile the exact kernel that just failed.
    if os.environ.get("HBBFT_TPU_NO_FUSED") or os.environ.get("HBBFT_TPU_NO_PALLAS"):
        return False
    return bool(os.environ.get("HBBFT_TPU_FUSE2"))


def _x_segments():
    from hbbft_tpu.ops import pairing

    plan = []
    run = 0
    for b in pairing._X_BITS:
        run += 1
        if b:
            plan.append((run, True))
            run = 0
    if run:
        plan.append((run, False))
    return tuple(plan)


def _add_step_math(m, m2, sq2, f, X, Y, Z, xQ, yQ, xP, yP):
    """Mixed addition R ← R + Q (Q affine) fused with its line and the
    sparse f·line multiply (pairing._line_add + curve.jac_add algebra,
    Z2 = 1 so U1 = X, S1 = Y, H = x_Q·Z² − X, Rr = y_Q·Z³ − Y; the line's
    D = H·Z and N = Rr are shared with the addition)."""
    ZZ = sq2(Z)
    ZZZ = m2(ZZ, Z)
    U2 = m2(xQ, ZZ)
    S2 = m2(yQ, ZZZ)
    H = _sub2(U2, X)
    Rr = _sub2(S2, Y)

    # Line: l = ξ·D·y_P + (Rr·x_Q − y_Q·D)·w³ − Rr·x_P·w⁵,  D = H·Z.
    D = m2(H, Z)
    c1a1 = _sub2(m2(Rr, xQ), m2(yQ, D))
    u = _xi2(D)
    c0a0 = (m(u[0], yP), m(u[1], yP))
    c1a2 = (-m(Rr[0], xP), -m(Rr[1], xP))
    f_new = _mul_line(m2, f, (c0a0, c1a1, c1a2))

    # Addition: X3 = Rr² − H³ − 2XH², Y3 = Rr(XH² − X3) − Y·H³, Z3 = Z·H.
    H2 = sq2(H)
    H3 = m2(H, H2)
    XH2 = m2(X, H2)
    R2 = sq2(Rr)
    X3 = _sub2(_sub2(R2, H3), _add2(XH2, XH2))
    Y3 = _sub2(m2(Rr, _sub2(XH2, X3)), m2(Y, H3))
    Z3 = m2(Z, H)
    return f_new, X3, Y3, Z3


def _miller_full_kernel(segments, q_ref, pq_ref, fold_ref, out_ref, acc_ref=None):
    fold_t = fold_ref[:]
    m, m2, sq2 = _algebra(fold_t, acc_ref)
    xP, yP = pq_ref[0], pq_ref[1]
    xQ = (q_ref[0], q_ref[1])
    yQ = (q_ref[2], q_ref[3])

    t = xP.shape[-1]
    # Build the constant 1 without .at[].set — basic-index updates lower
    # to the scatter primitive, which Mosaic's TPU lowering rejects.
    one = jnp.concatenate(
        [
            jnp.ones((1, t), dtype=fq.DTYPE),
            jnp.zeros((fq.NLIMBS - 1, t), dtype=fq.DTYPE),
        ],
        axis=0,
    )
    zero = jnp.zeros((fq.NLIMBS, t), dtype=fq.DTYPE)
    f = tuple(
        tuple((one if (i, j, k) == (0, 0, 0) else zero) for k in (0, 1))
        for i in (0, 1)
        for j in (0, 1, 2)
    )
    # regroup to ((3×fq2), (3×fq2))
    f = (f[0:3], f[3:6])
    X, Y, Z = xQ, yQ, (one, zero)

    # No per-iteration renormalization needed: every carry component is a
    # product (m/m2 outputs, already carried) or a small linear combination
    # of them, and `m` renormalizes its operands — the same dataflow the
    # per-step scan path has across kernel boundaries.
    def double_body(_, carry):
        f, X, Y, Z = carry
        f2 = _sqr12(m2, f)
        line, X3, Y3, Z3 = _double_step_math(m, m2, sq2, X, Y, Z, xP, yP)
        return _mul_line(m2, f2, line), X3, Y3, Z3

    carry = (f, X, Y, Z)
    for run, add_after in segments:
        carry = jax.lax.fori_loop(0, run, double_body, carry)
        if add_after:
            f, X, Y, Z = carry
            f, X3, Y3, Z3 = _add_step_math(
                m, m2, sq2, f, X, Y, Z, xQ, yQ, xP, yP
            )
            carry = (f, X3, Y3, Z3)

    f, _, _, _ = carry
    _write_f12(out_ref, f)


@functools.lru_cache(maxsize=None)
def _miller_full_call(segments, n_tiles: int, interpret: bool):
    return pl.pallas_call(
        functools.partial(_miller_full_kernel, segments),
        out_shape=jax.ShapeDtypeStruct(
            (F12_ROWS, fq.NLIMBS, n_tiles * TILE), fq.DTYPE
        ),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((4, fq.NLIMBS, TILE), lambda i: (0, 0, i)),
            pl.BlockSpec((2, fq.NLIMBS, TILE), lambda i: (0, 0, i)),
            pl.BlockSpec(
                (fq.NLIMBS, fq.CONV - fq.FOLD_FROM), lambda i: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (F12_ROWS, fq.NLIMBS, TILE), lambda i: (0, 0, i)
        ),
        scratch_shapes=_scratch(),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Kernel 2: k cyclotomic squarings in one launch (fori_loop inside).
# ---------------------------------------------------------------------------


def _cyclo_sqr_math(m2, sq2, f):
    """Granger–Scott compressed squaring (tower.fq12_cyclo_sqr algebra).

    Fq4 pairs (x, y) ∈ {(a0, b1), (a1, b2), (a2, b0)}; per pair the three
    squares x², y², (x+y)² give 2xy; recombine with ξ mixing.
    """
    (a0, a1, a2), (b0, b1, b2) = f

    def pair(x, y):
        xs = sq2(x)
        ys = sq2(y)
        ss = sq2(_add2(x, y))
        xy = _sub2(_sub2(ss, xs), ys)
        return xs, ys, xy

    x0s, y0s, xy0 = pair(a0, b1)
    x1s, y1s, xy1 = pair(a1, b2)
    x2s, y2s, xy2 = pair(a2, b0)

    def three(t):
        return (t[0] + t[0] + t[0], t[1] + t[1] + t[1])

    def two(t):
        return (t[0] + t[0], t[1] + t[1])

    s_a0 = _sub2(three(_add2(x0s, _xi2(y0s))), two(a0))
    s_b1 = _add2(three(xy0), two(b1))
    s_a2 = _sub2(three(_add2(x1s, _xi2(y1s))), two(a2))
    s_b0 = _add2(_xi2(three(xy1)), two(b0))
    s_a1 = _sub2(three(_add2(_xi2(x2s), y2s)), two(a1))
    s_b2 = _add2(three(xy2), two(b2))
    return ((s_a0, s_a1, s_a2), (s_b0, s_b1, s_b2))


def _reduce_cols(x, fold_t):
    """fq.reduce_small in limbs-first layout: carry → fold → carry.

    The fold is NOT optional here: limbs ≥ FOLD_FROM (including the top
    limb, which the carry passes deliberately never split) must be
    redistributed mod Q, otherwise linear terms that pass an input limb
    straight to an output (the ±2·aᵢ terms of the cyclotomic squaring)
    double the top limb every iteration — exponential growth that
    overflows float32 after ~25 chained squarings."""
    x = _carry_cols(x)
    ff = fq.FOLD_FROM
    nhi = fq.NLIMBS - ff
    t = x.shape[1]
    x = jnp.concatenate(
        [x[:ff], jnp.zeros((nhi, t), dtype=fq.DTYPE)], axis=0
    ) + jnp.dot(fold_t[:, :nhi], x[ff:], preferred_element_type=fq.DTYPE)
    return _carry_cols(x)


def _flat_to_f12(flat):
    return (
        ((flat[0], flat[1]), (flat[2], flat[3]), (flat[4], flat[5])),
        ((flat[6], flat[7]), (flat[8], flat[9]), (flat[10], flat[11])),
    )


def _cyclo_sqr_body(m2, sq2, fold_t):
    """fori_loop body: one reduced Granger–Scott squaring on flat f12
    state (shared by the k-run kernel and the FUSE2 whole-chain kernel)."""

    def body(_, flat):
        out = _cyclo_sqr_math(m2, sq2, _flat_to_f12(flat))
        return [
            _reduce_cols(c, fold_t) for six in out for two in six for c in two
        ]

    return body


def _cyclo_run_kernel(k: int, state_ref, fold_ref, out_ref, acc_ref=None):
    fold_t = fold_ref[:]
    m, m2, sq2 = _algebra(fold_t, acc_ref)
    f0 = _read_f12(state_ref)
    flat0 = [c for six in f0 for two in six for c in two]
    flat = jax.lax.fori_loop(0, k, _cyclo_sqr_body(m2, sq2, fold_t), flat0)
    _write_f12(out_ref, _flat_to_f12(flat))


@functools.lru_cache(maxsize=None)
def _cyclo_run_call(k: int, n_tiles: int, interpret: bool):
    return pl.pallas_call(
        functools.partial(_cyclo_run_kernel, k),
        out_shape=jax.ShapeDtypeStruct(
            (F12_ROWS, fq.NLIMBS, n_tiles * TILE), fq.DTYPE
        ),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((F12_ROWS, fq.NLIMBS, TILE), lambda i: (0, 0, i)),
            pl.BlockSpec(
                (fq.NLIMBS, fq.CONV - fq.FOLD_FROM), lambda i: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (F12_ROWS, fq.NLIMBS, TILE), lambda i: (0, 0, i)
        ),
        scratch_shapes=_scratch(),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Kernel 2b (opt-in, HBBFT_TPU_FUSE2=1): a whole x-chain m^BLS_X in one
# launch — the segment plan is static, so squaring runs and the ~6 set-bit
# multiplies unroll in-kernel (one dispatch per chain instead of ~16).
# ---------------------------------------------------------------------------


def _pow_chain_kernel(exponent: int, m_ref, fold_ref, out_ref, acc_ref=None):
    fold_t = fold_ref[:]
    m, m2, sq2 = _algebra(fold_t, acc_ref)
    base = _read_f12(m_ref)
    base_flat = [c for six in base for two in six for c in two]
    body = _cyclo_sqr_body(m2, sq2, fold_t)

    flat = list(base_flat)
    for run, mult in _segments(exponent):
        flat = jax.lax.fori_loop(0, run, body, flat)
        if mult:
            prod = _mul12(m2, _flat_to_f12(flat), base)
            flat = [c for six in prod for two in six for c in two]
    _write_f12(out_ref, _flat_to_f12(flat))


@functools.lru_cache(maxsize=None)
def _pow_chain_call(exponent: int, n_tiles: int, interpret: bool):
    return pl.pallas_call(
        functools.partial(_pow_chain_kernel, exponent),
        out_shape=jax.ShapeDtypeStruct(
            (F12_ROWS, fq.NLIMBS, n_tiles * TILE), fq.DTYPE
        ),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((F12_ROWS, fq.NLIMBS, TILE), lambda i: (0, 0, i)),
            pl.BlockSpec(
                (fq.NLIMBS, fq.CONV - fq.FOLD_FROM), lambda i: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (F12_ROWS, fq.NLIMBS, TILE), lambda i: (0, 0, i)
        ),
        scratch_shapes=_scratch(),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Kernel 3: full fq12 multiply.
# ---------------------------------------------------------------------------


def _mul12_kernel(a_ref, b_ref, fold_ref, out_ref, acc_ref=None):
    m, m2, sq2 = _algebra(fold_ref[:], acc_ref)
    out = _mul12(m2, _read_f12(a_ref), _read_f12(b_ref))
    _write_f12(out_ref, out)


@functools.lru_cache(maxsize=None)
def _mul12_call(n_tiles: int, interpret: bool):
    spec = pl.BlockSpec((F12_ROWS, fq.NLIMBS, TILE), lambda i: (0, 0, i))
    return pl.pallas_call(
        _mul12_kernel,
        out_shape=jax.ShapeDtypeStruct(
            (F12_ROWS, fq.NLIMBS, n_tiles * TILE), fq.DTYPE
        ),
        grid=(n_tiles,),
        in_specs=[
            spec,
            spec,
            pl.BlockSpec(
                (fq.NLIMBS, fq.CONV - fq.FOLD_FROM), lambda i: (0, 0)
            ),
        ],
        out_specs=spec,
        scratch_shapes=_scratch(),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Host-side packing: tower pytrees ((..., NLIMBS) leaves) <-> packed
# (rows, NLIMBS, lanes) arrays.  Done ONCE per scan, not per call.
# ---------------------------------------------------------------------------


def _leaves_f12(f):
    return [c for six in f for two in six for c in two]


def pack_rows(leaves, lanes):
    """[(..., NLIMBS) leaves] → (rows, NLIMBS, lanes_padded)."""
    n_tiles = max(1, -(-lanes // TILE))
    pad = n_tiles * TILE - lanes
    stacked = jnp.stack(
        [
            jnp.asarray(leaf, fq.DTYPE).reshape(lanes, fq.NLIMBS).T
            for leaf in leaves
        ]
    )  # (rows, NLIMBS, lanes)
    if pad:
        stacked = jnp.pad(stacked, ((0, 0), (0, 0), (0, pad)))
    return stacked


def unpack_f12(packed, lanes):
    """(≥12, NLIMBS, lanes_padded) → fq12 tuple of (lanes, NLIMBS)."""
    g = lambda k: packed[k, :, :lanes].T  # noqa: E731
    return (
        ((g(0), g(1)), (g(2), g(3)), (g(4), g(5))),
        ((g(6), g(7)), (g(8), g(9)), (g(10), g(11))),
    )


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Fused Miller loop and final exponentiation (drop-in for pairing.py).
# ---------------------------------------------------------------------------


def miller_loop(P, Qa):
    """Batched f_{|x|,Q}(P) with one kernel launch per doubling step.

    Same contract as pairing.miller_loop; the addition step (5 of 63
    iterations) runs on the unfused XLA path behind a lax.cond with
    pack/unpack at the branch boundary.
    """
    from hbbft_tpu.crypto.bls381 import BLS_X_IS_NEG
    from hbbft_tpu.ops import pairing, tower

    xP, yP, infP = P
    xQ, yQ, infQ = Qa
    out_shape = jnp.asarray(xP).shape[:-1]
    lanes = int(np.prod(out_shape)) if out_shape else 1
    n_tiles = max(1, -(-lanes // TILE))
    interpret = _interpret()

    # Work on a FLAT batch throughout (pack/unpack and the add-step
    # branch all assume rank 1); restore the caller's shape at the end.
    def flat_fq(c):
        return jnp.asarray(c).reshape(lanes, fq.NLIMBS)

    xP, yP = flat_fq(xP), flat_fq(yP)
    xQ = (flat_fq(xQ[0]), flat_fq(xQ[1]))
    yQ = (flat_fq(yQ[0]), flat_fq(yQ[1]))
    infP = jnp.asarray(infP).reshape(lanes)
    infQ = jnp.asarray(infQ).reshape(lanes)
    Qa = (xQ, yQ, infQ)
    batch_shape = (lanes,)

    fold = jnp.asarray(_FOLD_T)

    if _fuse2():
        # Whole loop in ONE launch (bit schedule unrolled in-kernel).
        q = pack_rows([xQ[0], xQ[1], yQ[0], yQ[1]], lanes)
        pqf = pack_rows([xP, yP], lanes)
        out = _miller_full_call(_x_segments(), n_tiles, interpret)(
            q, pqf, fold
        )
        f = unpack_f12(out, lanes)
        if BLS_X_IS_NEG:
            f = tower.fq12_conj(f)
        neutral = infP | infQ
        f = tower.fq12_select(
            neutral, tower.fq12_broadcast_one(batch_shape), f
        )
        return jax.tree_util.tree_map(
            lambda c: c.reshape(tuple(out_shape) + (fq.NLIMBS,)), f
        )

    one2 = tower.fq2_broadcast(tower.FQ2_ONE, batch_shape)
    f1 = tower.fq12_broadcast_one(batch_shape)
    state = pack_rows(
        _leaves_f12(f1) + [xQ[0], xQ[1], yQ[0], yQ[1], one2[0], one2[1]],
        lanes,
    )
    pq = pack_rows([xP, yP], lanes)
    Qj = (xQ, yQ, one2, jnp.zeros(batch_shape, dtype=bool))

    step = _step_call(n_tiles, interpret)

    def unpack_state(s):
        f = unpack_f12(s, lanes)
        g = lambda k: s[k, :, :lanes].T  # noqa: E731
        R = (
            (g(12), g(13)),
            (g(14), g(15)),
            (g(16), g(17)),
            jnp.zeros(batch_shape, dtype=bool),
        )
        return f, R

    def repack_state(f, R):
        X, Y, Z, _ = R
        return pack_rows(
            _leaves_f12(f) + [X[0], X[1], Y[0], Y[1], Z[0], Z[1]], lanes
        )

    def add_branch(s):
        f, R = unpack_state(s)
        f, R = pairing._miller_add_step(f, R, Qa, Qj, xP, yP)
        return repack_state(f, R)

    bits = jnp.asarray(pairing._X_BITS, dtype=jnp.bool_)

    def body(s, bit):
        s = step(s, pq, fold)
        s = jax.lax.cond(bit, add_branch, lambda t: t, s)
        return s, None

    state, _ = jax.lax.scan(body, state, bits)
    f, _ = unpack_state(state)

    if BLS_X_IS_NEG:
        f = tower.fq12_conj(f)

    neutral = infP | infQ
    f = tower.fq12_select(neutral, tower.fq12_broadcast_one(batch_shape), f)
    # Restore the caller's batch shape (flattened on entry).
    return jax.tree_util.tree_map(
        lambda c: c.reshape(tuple(out_shape) + (fq.NLIMBS,)), f
    )


def _segments(exponent: int):
    """x-chain plan: [(n_squarings, multiply_after?)] covering the bits
    of ``exponent`` after the implicit MSB."""
    bits = bin(exponent)[3:]
    plan = []
    run = 0
    for b in bits:
        run += 1
        if b == "1":
            plan.append((run, True))
            run = 0
    if run:
        plan.append((run, False))
    return plan


def cyclo_pow(packed_m, exponent: int, n_tiles: int):
    """m^exponent for cyclotomic packed m — one launch per zero-run plus
    one fq12-multiply launch per set bit (drop-in for the scan in
    tower.fq12_cyclo_pow_segmented, minus ~10× the dispatches).  With
    HBBFT_TPU_FUSE2=1 the whole chain runs in a single launch."""
    interpret = _interpret()
    fold = jnp.asarray(_FOLD_T)
    if _fuse2():
        return _pow_chain_call(exponent, n_tiles, interpret)(packed_m, fold)
    acc = packed_m
    for run, mult in _segments(exponent):
        acc = _cyclo_run_call(run, n_tiles, interpret)(acc, fold)
        if mult:
            acc = _mul12_call(n_tiles, interpret)(acc, packed_m, fold)
    return acc


def fused_mul12(a_packed, b_packed, n_tiles: int):
    return _mul12_call(n_tiles, _interpret())(
        a_packed, b_packed, jnp.asarray(_FOLD_T)
    )


def _conj_packed(p):
    """Packed fq12 conjugate: negate the c1 rows (6..11)."""
    mask = np.ones((F12_ROWS, 1, 1), dtype=fq.NP_DTYPE)
    mask[6:] = -1
    return p * jnp.asarray(mask)


def final_exp_fast(f):
    """f^{3·(Q¹²−1)/R} — pairing.final_exponentiation_fast with the hard
    part running on the fused kernels.

    Easy part stays on the tower path (its Fermat inverse is already one
    kernel via fq_pallas.pow_fixed); the four x-power chains then run as
    packed cyclo-run + fq12-mul launches (~10 dispatches per chain
    instead of ~130), and the final Frobenius recombination returns to
    the tower path (3 small constant multiplies).
    """
    from hbbft_tpu.crypto.bls381 import BLS_X, BLS_X_IS_NEG
    from hbbft_tpu.ops import tower

    out_shape = jnp.asarray(f[0][0][0]).shape[:-1]
    lanes = int(np.prod(out_shape)) if out_shape else 1
    n_tiles = max(1, -(-lanes // TILE))
    interpret = _interpret()

    # Easy part: m = f^((Q⁶−1)(Q²+1)) — cyclotomic afterwards.
    m = tower.fq12_mul(tower.fq12_conj(f), tower.fq12_inv(f))
    m = tower.fq12_mul(tower.fq12_frobenius_n(m, 2), m)

    pm = pack_rows(_leaves_f12(m), lanes)

    def pow_x(p):
        out = cyclo_pow(p, BLS_X, n_tiles)
        return _conj_packed(out) if BLS_X_IS_NEG else out

    def mul(a, b):
        return fused_mul12(a, b, n_tiles)

    a = pow_x(pm)  # m^x
    b = mul(a, _conj_packed(pm))  # m^(x−1)
    c = pow_x(b)  # m^(x²−x)
    y3 = mul(c, _conj_packed(b))  # m^((x−1)²)
    y2 = pow_x(y3)
    y1 = mul(pow_x(y2), _conj_packed(y3))
    m3 = mul(
        _cyclo_run_call(1, n_tiles, interpret)(pm, jnp.asarray(_FOLD_T)), pm
    )  # m³
    y0 = mul(pow_x(y1), m3)

    # Frobenius recombination on the tower path (3 constant multiplies).
    u0 = unpack_f12(y0, lanes)
    u1 = unpack_f12(y1, lanes)
    u2 = unpack_f12(y2, lanes)
    u3 = unpack_f12(y3, lanes)
    out = tower.fq12_mul(u0, tower.fq12_frobenius(u1))
    out = tower.fq12_mul(out, tower.fq12_frobenius_n(u2, 2))
    out = tower.fq12_mul(out, tower.fq12_frobenius_n(u3, 3))
    # Restore the caller's batch shape (pack/unpack flattens it).
    return jax.tree_util.tree_map(
        lambda c: jnp.asarray(c).reshape(tuple(out_shape) + (fq.NLIMBS,)), out
    )
