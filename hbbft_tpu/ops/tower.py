"""Batched BLS12-381 tower fields Fq2 / Fq6 / Fq12 on the limb kernel.

Structure mirrors the pure-Python golden reference (crypto/bls381.py) —
Fq2 = Fq[u]/(u²+1), Fq6 = Fq2[v]/(v³−ξ) with ξ = 1+u, Fq12 = Fq6[w]/(w²−v)
— but every element coefficient is a (…, 37) int32 limb vector, so the same
formulas run batched under jit/vmap/shard_map.

Elements are pytrees of limb arrays:

* fq2:  (c0, c1)
* fq6:  (a0, a1, a2)      — fq2 coefficients
* fq12: (b0, b1)          — fq6 coefficients

Lazy-add discipline: adds/subs don't carry; `fq.mul` renormalizes its own
inputs, so any formula with ≤ a few chained adds per mul operand is exact
(see fq.py domain note).  Inversions go down the tower to a single Fq
Fermat inverse; `batch_inv*` amortizes even that across a batch axis with
the Montgomery product trick using parallel prefix/suffix scans.

Frobenius constants are computed host-side with the golden-reference Fq2
arithmetic at import time.

Reference analogue: the `pairing` crate's Fq2/Fq6/Fq12 towers under
`threshold_crypto` (SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import fq

# ---------------------------------------------------------------------------
# Fq2
# ---------------------------------------------------------------------------

FQ2_ZERO = (np.asarray(fq.ZERO), np.asarray(fq.ZERO))
FQ2_ONE = (np.asarray(fq.ONE), np.asarray(fq.ZERO))


def fq2_add(a, b):
    return (fq.add(a[0], b[0]), fq.add(a[1], b[1]))


def fq2_sub(a, b):
    return (fq.sub(a[0], b[0]), fq.sub(a[1], b[1]))


def fq2_neg(a):
    return (fq.neg(a[0]), fq.neg(a[1]))


def fq2_conj(a):
    return (a[0], fq.neg(a[1]))


def fq2_is_zero(a):
    """In-graph exact zero test (bool over the batch shape).  Inherits
    fq.is_zero's value-domain contract on BOTH components — satisfied by
    any ± combination of a few fq2 products (the Karatsuba recombination
    keeps each component within a handful of mul outputs)."""
    return fq.is_zero(a[0]) & fq.is_zero(a[1])


def fq2_mul_pairs(a, b) -> list:
    """The 3 Karatsuba Fq operand pairs of an fq2 product (for stacking)."""
    return [
        (a[0], b[0]),
        (a[1], b[1]),
        (fq.add(a[0], a[1]), fq.add(b[0], b[1])),
    ]


def fq2_from_products(t) -> tuple:
    """Recombine the 3 Karatsuba products into (c0, c1)."""
    return (fq.sub(t[0], t[1]), fq.sub(t[2], fq.add(t[0], t[1])))


def fq2_mul(a, b):
    return fq2_from_products(fq.mul_n(fq2_mul_pairs(a, b)))


def fq2_mul_many(pairs) -> list:
    """n independent fq2 products via ONE stacked Fq multiply (3n lanes)."""
    flat = []
    for a, b in pairs:
        flat.extend(fq2_mul_pairs(a, b))
    res = fq.mul_n(flat)
    return [fq2_from_products(res[3 * i : 3 * i + 3]) for i in range(len(pairs))]


def fq2_sqr(a):
    # (a0+a1u)² = (a0+a1)(a0−a1) + 2a0a1·u — 2 Fq muls.
    t0, t1 = fq.mul_n(
        [(fq.add(a[0], a[1]), fq.sub(a[0], a[1])), (a[0], a[1])]
    )
    return (t0, fq.add(t1, t1))


def fq2_mul_fq(a, k):
    """Multiply by an Fq limb vector."""
    return (fq.mul(a[0], k), fq.mul(a[1], k))


def fq2_mul_small(a, k: int):
    return (fq.mul_small(a[0], k), fq.mul_small(a[1], k))


def fq2_mul_xi(a):
    """Multiply by ξ = 1 + u:  (a0 − a1) + (a0 + a1)·u."""
    return (fq.sub(a[0], a[1]), fq.add(a[0], a[1]))


def fq2_inv(a):
    n0, n1 = fq.mul_n([(a[0], a[0]), (a[1], a[1])])
    ninv = fq.inv(fq.add(n0, n1))
    m0, m1 = fq.mul_n([(a[0], ninv), (a[1], ninv)])
    return (m0, fq.neg(m1))


def fq2_select(cond, a, b):
    return (fq.select(cond, a[0], b[0]), fq.select(cond, a[1], b[1]))


def fq2_from_ints(pair) -> Tuple[np.ndarray, np.ndarray]:
    return (fq.from_int(pair[0]), fq.from_int(pair[1]))


def fq2_stack(pairs):
    """Stack Python (c0, c1) int pairs into a batched fq2 element."""
    return (
        fq.from_ints([p[0] for p in pairs]),
        fq.from_ints([p[1] for p in pairs]),
    )


def fq2_to_ints(a, idx=None) -> Tuple[int, int]:
    c0, c1 = np.asarray(a[0]), np.asarray(a[1])
    if idx is not None:
        c0, c1 = c0[idx], c1[idx]
    return (fq.to_int(c0), fq.to_int(c1))


def fq2_broadcast(a, batch_shape):
    return tuple(
        jnp.broadcast_to(jnp.asarray(c), tuple(batch_shape) + (fq.NLIMBS,))
        for c in a
    )


# ---------------------------------------------------------------------------
# Fq6
# ---------------------------------------------------------------------------

FQ6_ZERO = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


def fq6_add(a, b):
    return tuple(fq2_add(x, y) for x, y in zip(a, b))


def fq6_sub(a, b):
    return tuple(fq2_sub(x, y) for x, y in zip(a, b))


def fq6_neg(a):
    return tuple(fq2_neg(x) for x in a)


def fq6_mul_fq2_pairs(a, b) -> list:
    """The 6 fq2 operand pairs of a Toom/Karatsuba fq6 product."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    return [
        (a0, b0),  # t0
        (a1, b1),  # t1
        (a2, b2),  # t2
        (fq2_add(a1, a2), fq2_add(b1, b2)),  # m12
        (fq2_add(a0, a1), fq2_add(b0, b1)),  # m01
        (fq2_add(a0, a2), fq2_add(b0, b2)),  # m02
    ]


def fq6_from_products(res) -> tuple:
    """Recombine [t0, t1, t2, m12, m01, m02] into (c0, c1, c2)."""
    t0, t1, t2, m12, m01, m02 = res
    c0 = fq2_add(t0, fq2_mul_xi(fq2_sub(m12, fq2_add(t1, t2))))
    c1 = fq2_add(fq2_sub(m01, fq2_add(t0, t1)), fq2_mul_xi(t2))
    c2 = fq2_add(fq2_sub(m02, fq2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fq6_mul(a, b):
    return fq6_from_products(fq2_mul_many(fq6_mul_fq2_pairs(a, b)))


def fq6_sqr(a):
    return fq6_mul(a, a)


def fq6_mul_by_v(a):
    return (fq2_mul_xi(a[2]), a[0], a[1])


def fq6_mul_fq2(a, k):
    return tuple(fq2_mul(x, k) for x in a)


def fq6_inv(a):
    a0, a1, a2 = a
    s = fq2_mul_many(
        [(a0, a0), (a1, a2), (a2, a2), (a0, a1), (a1, a1), (a0, a2)]
    )
    c0 = fq2_sub(s[0], fq2_mul_xi(s[1]))
    c1 = fq2_sub(fq2_mul_xi(s[2]), s[3])
    c2 = fq2_sub(s[4], s[5])
    u = fq2_mul_many([(a2, c1), (a1, c2), (a0, c0)])
    t = fq2_add(fq2_mul_xi(fq2_add(u[0], u[1])), u[2])
    t_inv = fq2_inv(t)
    out = fq2_mul_many([(c0, t_inv), (c1, t_inv), (c2, t_inv)])
    return (out[0], out[1], out[2])


def fq6_select(cond, a, b):
    return tuple(fq2_select(cond, x, y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# Fq12
# ---------------------------------------------------------------------------

FQ12_ZERO = (FQ6_ZERO, FQ6_ZERO)
FQ12_ONE = (FQ6_ONE, FQ6_ZERO)


def fq12_add(a, b):
    return (fq6_add(a[0], b[0]), fq6_add(a[1], b[1]))


def fq12_sub(a, b):
    return (fq6_sub(a[0], b[0]), fq6_sub(a[1], b[1]))


def fq12_mul(a, b):
    # Karatsuba over Fq6; all 3 fq6 products (18 fq2, 54 Fq lanes) ride ONE
    # stacked multiply.
    a0, a1 = a
    b0, b1 = b
    flat = (
        fq6_mul_fq2_pairs(a0, b0)
        + fq6_mul_fq2_pairs(a1, b1)
        + fq6_mul_fq2_pairs(fq6_add(a0, a1), fq6_add(b0, b1))
    )
    res = fq2_mul_many(flat)
    t0 = fq6_from_products(res[0:6])
    t1 = fq6_from_products(res[6:12])
    mid = fq6_from_products(res[12:18])
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(mid, fq6_add(t0, t1))
    return (c0, c1)


def fq12_mul_line_pairs(f, line) -> list:
    """Fq2 operand pairs for f × sparse line (15 products; see
    fq12_mul_line_from_products).  The line element is
    (l0, l4, l5) ≡ ((l0, 0, 0), (0, l4, l5)) in fq6 coordinates — the
    shape every Miller-loop line evaluation produces (pairing.py)."""
    l0, l4, l5 = line
    f0, f1 = f
    a0, a1, a2 = f0
    b0, b1, b2 = f1
    pairs = []
    # t0 = f0·(l0,0,0): fq2-scalar product, 3 muls
    pairs.extend([(a0, l0), (a1, l0), (a2, l0)])
    # t1 = f1·(0,l4,l5): sparse fq6 product, 6 muls
    pairs.extend(
        [(b1, l5), (b2, l4), (b0, l4), (b2, l5), (b0, l5), (b1, l4)]
    )
    # mid = (f0+f1)·(l0,l4,l5): full Karatsuba fq6 product, 6 muls
    pairs.extend(fq6_mul_fq2_pairs(fq6_add(f0, f1), (l0, l4, l5)))
    return pairs


def fq12_mul_line_from_products(res) -> tuple:
    """Recombine the 15 products of fq12_mul_line_pairs.

    Layout of `res` (fq2 values): [t0a0, t0a1, t0a2, b1l5, b2l4, b0l4,
    b2l5, b0l5, b1l4, m0..m5(mid Karatsuba)].
    """
    t0 = (res[0], res[1], res[2])
    # t1 = f1·(0,l4,l5): r0 = ξ(b1l5 + b2l4), r1 = b0l4 + ξ(b2l5),
    #                    r2 = b0l5 + b1l4
    t1 = (
        fq2_mul_xi(fq2_add(res[3], res[4])),
        fq2_add(res[5], fq2_mul_xi(res[6])),
        fq2_add(res[7], res[8]),
    )
    mid = fq6_from_products(res[9:15])
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(mid, fq6_add(t0, t1))
    return (c0, c1)


def fq12_mul_line(f, line):
    """f × sparse line element in ONE stacked multiply (45 Fq lanes
    instead of fq12_mul's 54)."""
    vals = fq2_mul_many(fq12_mul_line_pairs(f, line))
    return fq12_mul_line_from_products(vals)


def fq12_sqr_pairs(a) -> list:
    """The 12 fq2 operand pairs of a complex fq12 squaring (for stacking
    into a larger fused multiply — see pairing's Miller double step)."""
    a0, a1 = a
    return fq6_mul_fq2_pairs(a0, a1) + fq6_mul_fq2_pairs(
        fq6_add(a0, a1), fq6_add(a0, fq6_mul_by_v(a1))
    )


def fq12_sqr_from_products(res) -> tuple:
    t = fq6_from_products(res[0:6])
    u = fq6_from_products(res[6:12])
    c0 = fq6_sub(u, fq6_add(t, fq6_mul_by_v(t)))
    c1 = fq6_add(t, t)
    return (c0, c1)


def fq12_sqr(a):
    # Complex squaring over Fq6: both fq6 products in ONE stacked multiply.
    return fq12_sqr_from_products(fq2_mul_many(fq12_sqr_pairs(a)))


def fq12_conj(a):
    return (a[0], fq6_neg(a[1]))


def fq2_sqr_pairs(a) -> list:
    """The 2 Fq operand pairs of an fq2 square (for stacking):
    (a0+a1)(a0−a1) and a0·a1 → recombine via fq2_sqr_from_products."""
    return [(fq.add(a[0], a[1]), fq.sub(a[0], a[1])), (a[0], a[1])]


def fq2_sqr_from_products(t) -> tuple:
    return (t[0], fq.add(t[1], t[1]))


def fq12_cyclo_sqr(a):
    """Granger–Scott squaring for CYCLOTOMIC elements (|a| divides
    Φ₁₂(q) = q⁴−q²+1, i.e. anything after the easy final-exp part).

    Decompose Fq12 = Fq4[v]/(v³−ξ) with Fq4 = Fq2[y]/(y²−ξ), y = v·w:
    the Fq4 triples are g0 = (a0, b1), g1 = (a1, b2), g2 = (a2, b0·ξ⁻¹),
    and for cyclotomic elements the square needs only the three Fq4
    squarings (verified empirically against the golden fq12_sqr):

        h0 = 3·g0² − 2·conj(g0)
        h1 = 3·ξ·g2² − 2·conj(g1)
        h2 = 3·g1² − 2·conj(g2)

    Written out in tower coordinates the ξ⁻¹ cancels.  Cost: 9 fq2
    squarings = 18 Fq lanes in ONE stacked multiply — 2× fewer lanes
    than fq12_sqr and, because the x-power chain using it needs no
    per-bit full multiply, ~5× fewer lanes per exponent bit.
    """
    (a0, a1, a2), (b0, b1, b2) = a
    # 9 fq2 squarings: x², y², (x+y)² for the three (x, y) Fq4 pairs.
    flat = []
    for x, y in ((a0, b1), (a1, b2), (a2, b0)):
        flat.extend(fq2_sqr_pairs(x))
        flat.extend(fq2_sqr_pairs(y))
        flat.extend(fq2_sqr_pairs(fq2_add(x, y)))
    res = fq.mul_n(flat)
    sq = [fq2_sqr_from_products(res[2 * i : 2 * i + 2]) for i in range(9)]
    (x0s, y0s, s0s), (x1s, y1s, s1s), (x2s, y2s, s2s) = (
        sq[0:3],
        sq[3:6],
        sq[6:9],
    )

    def three(t):
        return fq2_add(fq2_add(t, t), t)

    def two(t):
        return fq2_add(t, t)

    # 2·x·y = (x+y)² − x² − y²  (per Fq4 pair)
    xy0 = fq2_sub(fq2_sub(s0s, x0s), y0s)
    xy1 = fq2_sub(fq2_sub(s1s, x1s), y1s)
    xy2 = fq2_sub(fq2_sub(s2s, x2s), y2s)

    # h0 = 3(a0² + ξb1²) − 2a0  ;  y-part 3·2a0b1 + 2b1
    s_a0 = fq2_sub(three(fq2_add(x0s, fq2_mul_xi(y0s))), two(a0))
    s_b1 = fq2_add(three(xy0), two(b1))
    # h2 = 3(a1² + ξb2²) − 2a2  ;  s_b0 = ξ·(3·2a1b2) + 2b0
    s_a2 = fq2_sub(three(fq2_add(x1s, fq2_mul_xi(y1s))), two(a2))
    s_b0 = fq2_add(fq2_mul_xi(three(xy1)), two(b0))
    # h1 = 3(ξa2² + b0²) − 2a1  ;  s_b2 = 3·2a2b0 + 2b2
    s_a1 = fq2_sub(three(fq2_add(fq2_mul_xi(x2s), y2s)), two(a1))
    s_b2 = fq2_add(three(xy2), two(b2))

    # Renormalize: the ±2·(input) linear terms would otherwise double the
    # limb magnitude every chained squaring (the x-power chain does 64 in a
    # row), blowing the exact-float32 envelope after ~13.  One stacked
    # carry+fold pass (no multiplies) caps limbs at [-1, BASE+1].
    coeffs = [s_a0, s_a1, s_a2, s_b0, s_b1, s_b2]
    arrs = [c for pair in coeffs for c in pair]
    red = fq.reduce_small(jnp.stack(arrs))
    out = [(red[2 * i], red[2 * i + 1]) for i in range(6)]
    return ((out[0], out[1], out[2]), (out[3], out[4], out[5]))


def fq12_cyclo_pow_segmented(a, exponent: int):
    """a^exponent for cyclotomic a, fixed Python-int exponent > 0.

    ONE compact lax.scan whose body does a Granger–Scott squaring plus a
    ``lax.cond``-guarded multiply: the multiply branch only *executes* on
    the set bits (Hamming weight 6 for the BLS parameter x), so the cost
    is 63 compressed squarings + ~6 multiplies — while the compiled graph
    stays a single small scan body.  (A host-side segmented unrolling of
    the schedule achieved the same arithmetic but inflated the graph to
    the point of crashing the XLA CPU compiler on larger programs.)
    """
    bits = jnp.asarray(
        [int(b) for b in bin(exponent)[3:]], dtype=jnp.bool_
    )  # MSB implicit: acc starts at a

    def step(acc, bit):
        acc = fq12_cyclo_sqr(acc)
        acc = jax.lax.cond(bit, lambda t: fq12_mul(t, a), lambda t: t, acc)
        return acc, None

    if bits.shape[0] == 0:
        return a
    acc, _ = jax.lax.scan(step, a, bits)
    return acc


def fq12_inv(a):
    a0, a1 = a
    res = fq2_mul_many(
        fq6_mul_fq2_pairs(a0, a0) + fq6_mul_fq2_pairs(a1, a1)
    )
    t = fq6_sub(
        fq6_from_products(res[0:6]), fq6_mul_by_v(fq6_from_products(res[6:12]))
    )
    t_inv = fq6_inv(t)
    res = fq2_mul_many(
        fq6_mul_fq2_pairs(a0, t_inv) + fq6_mul_fq2_pairs(a1, t_inv)
    )
    return (
        fq6_from_products(res[0:6]),
        fq6_neg(fq6_from_products(res[6:12])),
    )


def fq12_select(cond, a, b):
    return (fq6_select(cond, a[0], b[0]), fq6_select(cond, a[1], b[1]))


def fq12_broadcast_one(batch_shape):
    """Batched multiplicative identity."""
    return jax.tree_util.tree_map(
        lambda c: jnp.broadcast_to(
            jnp.asarray(c), tuple(batch_shape) + (fq.NLIMBS,)
        ),
        FQ12_ONE,
    )


def fq12_pow_fixed(a, exponent: int):
    """a^exponent for a fixed Python-int exponent, via lax.scan."""
    if exponent == 0:
        return fq12_broadcast_one(jnp.asarray(a[0][0][0]).shape[:-1])
    bits = jnp.asarray([int(b) for b in bin(exponent)[2:]], dtype=jnp.int32)
    batch_shape = jnp.asarray(a[0][0][0]).shape[:-1]

    def step(acc, bit):
        acc = fq12_sqr(acc)
        cond = jnp.broadcast_to(bit.astype(bool), batch_shape)
        acc = fq12_select(cond, fq12_mul(acc, a), acc)
        return acc, None

    acc, _ = jax.lax.scan(step, fq12_broadcast_one(batch_shape), bits)
    return acc


# ---------------------------------------------------------------------------
# Frobenius x ↦ x^Q on Fq12.
#
# With w² = v, v³ = ξ:  frob(v) = v·ξ^((Q−1)/3),  frob(w) = w·ξ^((Q−1)/6),
# and Fq2 coefficients are conjugated.  Writing a = Σ_{i<3,j<2} a_ij v^i w^j:
#   frob(a) = Σ conj(a_ij) · C3^i · C6^j · v^i w^j
# where C3 = ξ^((Q−1)/3), C6 = ξ^((Q−1)/6) ∈ Fq2 (host-precomputed below).
# ---------------------------------------------------------------------------


def _gold_fq2_pow(base, e: int):
    acc = gold.FQ2_ONE
    while e:
        if e & 1:
            acc = gold.fq2_mul(acc, base)
        base = gold.fq2_sqr(base)
        e >>= 1
    return acc


_XI = (1, 1)
_C3_INT = _gold_fq2_pow(_XI, (Q - 1) // 3)
_C6_INT = _gold_fq2_pow(_XI, (Q - 1) // 6)

# FROB_COEFF[j][i] = C3^i · C6^j as canonical limb fq2 constants.
_FROB_COEFF = [
    [
        fq2_from_ints(
            gold.fq2_mul(_gold_fq2_pow(_C3_INT, i), _gold_fq2_pow(_C6_INT, j))
        )
        for i in range(3)
    ]
    for j in range(2)
]


def fq12_frobenius(a):
    """x ↦ x^Q (one application) — 6 constant muls in one stack."""
    pairs = [
        (fq2_conj(a[j][i]), _FROB_COEFF[j][i])
        for j in range(2)
        for i in range(3)
    ]
    res = fq2_mul_many(pairs)
    return ((res[0], res[1], res[2]), (res[3], res[4], res[5]))


def fq12_frobenius_n(a, n: int):
    for _ in range(n % 12):
        a = fq12_frobenius(a)
    return a


# ---------------------------------------------------------------------------
# Batch inversion (Montgomery trick) along a leading batch axis.
# ---------------------------------------------------------------------------


def _scan_products(x, mul_fn):
    """Inclusive prefix and suffix products along axis 0."""
    prefix = jax.lax.associative_scan(mul_fn, x, axis=0)
    suffix = jax.lax.associative_scan(mul_fn, x, axis=0, reverse=True)
    return prefix, suffix


def batch_inv_fq2(x):
    """Invert a batch of fq2 elements (leading axis) with ONE Fq inversion.

    inv_i = prefix_{i−1} · suffix_{i+1} · (Π x)⁻¹ — prefix/suffix via
    parallel scans, so the only sequential cost is the Fermat inverse of
    the total product.  All elements must be nonzero.
    """
    prefix, suffix = _scan_products(x, fq2_mul)
    total = jax.tree_util.tree_map(lambda c: c[-1], prefix)
    tinv = fq2_inv(total)

    p0, p1 = prefix
    s0, s1 = suffix
    one0 = jnp.broadcast_to(jnp.asarray(FQ2_ONE[0]), p0[:1].shape)
    one1 = jnp.broadcast_to(jnp.asarray(FQ2_ONE[1]), p1[:1].shape)
    pre = (  # prefix_{i-1}, with 1 at i = 0
        jnp.concatenate([one0, p0[:-1]], axis=0),
        jnp.concatenate([one1, p1[:-1]], axis=0),
    )
    suf = (  # suffix_{i+1}, with 1 at i = n-1
        jnp.concatenate([s0[1:], one0], axis=0),
        jnp.concatenate([s1[1:], one1], axis=0),
    )
    wing = fq2_mul(pre, suf)
    return fq2_mul(wing, fq2_broadcast_like(tinv, x))


def fq2_broadcast_like(a, ref):
    shape = jnp.asarray(ref[0]).shape
    return tuple(jnp.broadcast_to(jnp.asarray(c), shape) for c in a)


# ---------------------------------------------------------------------------
# Host conversion fq6 / fq12 <-> golden tuples
# ---------------------------------------------------------------------------


def fq6_from_ints(t):
    return tuple(fq2_from_ints(x) for x in t)


def fq12_from_ints(t):
    return tuple(fq6_from_ints(x) for x in t)


def fq6_stack(ts):
    return tuple(
        fq2_stack([t[i] for t in ts]) for i in range(3)
    )


def fq12_stack(ts):
    return tuple(
        fq6_stack([t[i] for t in ts]) for i in range(2)
    )


def fq6_to_ints(a, idx=None):
    return tuple(fq2_to_ints(x, idx) for x in a)


def fq12_to_ints(a, idx=None):
    return tuple(fq6_to_ints(x, idx) for x in a)


def fq12_to_ints_batch(a, n=None):
    """Canonical Fq12 values of the first ``n`` lanes at once.

    Twelve batched coefficient readbacks (``fq.to_ints`` — one
    rint+mod+matmul per coefficient array) replace the 12·n per-lane
    CRT loops of ``fq12_to_ints(a, i)`` — the per-item host conversion
    was the largest single slice of the array engine's ``dispatch``
    bucket (verdict-delivery of N² pairing checks).  Returns a list of
    ``n`` nested tuples identical to the per-lane form."""
    leaves = [np.asarray(c) for x6 in a for x2 in x6 for c in x2]
    if n is None:
        n = leaves[0].shape[0]
    ints = [fq.to_ints(lv[:n]) for lv in leaves]
    return [
        tuple(
            tuple(
                (ints[s * 6 + t * 2][i], ints[s * 6 + t * 2 + 1][i])
                for t in range(3)
            )
            for s in range(2)
        )
        for i in range(n)
    ]
