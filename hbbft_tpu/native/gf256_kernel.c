/* GF(2^8) Reed-Solomon matmul kernel (host side).
 *
 * Native equivalent of the reference's `reed-solomon-erasure` Rust crate
 * (SURVEY.md 2.2): the host-path hot op behind broadcast encode/decode.
 * The TPU path (hbbft_tpu/ops/gf256.py) handles device batches; this file
 * serves the VirtualNet runtime's host-side shard work.
 *
 * Strategy: the classic SIMD nibble-split.  For multiplier constant c the
 * product c*x factors through x's nibbles:  c*x = LO_c[x & 15] ^ HI_c[x >> 4]
 * (GF addition is XOR and the nibble decomposition is linear).  With AVX2 the
 * two 16-entry tables live in a 256-bit register and PSHUFB resolves 32 bytes
 * per shuffle.  Scalar fallback uses a 64KB full product table.
 *
 * Field: poly 0x11D, generator 2 - matching crypto/erasure.py.
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

#define POLY 0x11D

static uint8_t MUL[256][256];
static uint8_t NIB_LO[256][16]; /* NIB_LO[c][n] = c * n        */
static uint8_t NIB_HI[256][16]; /* NIB_HI[c][n] = c * (n << 4) */
static int READY = 0;

static uint8_t gf_mul_slow(uint32_t a, uint32_t b) {
    uint32_t r = 0;
    while (b) {
        if (b & 1) r ^= a;
        a <<= 1;
        if (a & 0x100) a ^= POLY;
        b >>= 1;
    }
    return (uint8_t)r;
}

#if defined(__GFNI__) && defined(__AVX2__)
/* GFNI: c*x is GF(2)-linear in x, so each constant c is an 8x8 bit
 * matrix and VGF2P8AFFINEQB resolves 32 products per instruction.  The
 * qword packing convention is DERIVED at init (four candidate packings
 * probed against the MUL table) and the whole path self-disables on any
 * mismatch — correctness never rests on reading the SDM right. */
static uint64_t AFF[256];
static int GFNI_OK = 0;

static uint64_t aff_pack(uint8_t c, int variant) {
    /* row i bit j = bit i of (c * 2^j); the qword packing convention
     * (column bit order x row byte order) is probed as 4 variants —
     * on this ISA the working one is bit j unreversed, row i at qword
     * byte 7-i, but the self-test decides, not the comment. */
    int bo = variant & 1, ro = variant >> 1;
    uint64_t q = 0;
    for (int i = 0; i < 8; i++) {
        uint8_t row = 0;
        for (int j = 0; j < 8; j++)
            if ((MUL[c][1u << j] >> i) & 1)
                row |= (uint8_t)(1u << (bo ? (7 - j) : j));
        int byte_pos = ro ? (7 - i) : i;
        q |= (uint64_t)row << (8 * byte_pos);
    }
    return q;
}

static int gfni_selftest(int variant) {
    for (int c = 1; c < 256; c += 37) {
        __m128i A = _mm_set1_epi64x((long long)aff_pack((uint8_t)c, variant));
        uint8_t xs[16], got[16];
        for (int t = 0; t < 16; t++) xs[t] = (uint8_t)(t * 17 + 3);
        __m128i x = _mm_loadu_si128((const __m128i *)xs);
        __m128i r = _mm_gf2p8affine_epi64_epi8(x, A, 0);
        _mm_storeu_si128((__m128i *)got, r);
        for (int t = 0; t < 16; t++)
            if (got[t] != MUL[c][xs[t]]) return 0;
    }
    return 1;
}
#endif

void gf256_init(void) {
    if (READY) return;
    for (int a = 0; a < 256; a++)
        for (int b = 0; b < 256; b++)
            MUL[a][b] = gf_mul_slow((uint32_t)a, (uint32_t)b);
    for (int c = 0; c < 256; c++)
        for (int n = 0; n < 16; n++) {
            NIB_LO[c][n] = MUL[c][n];
            NIB_HI[c][n] = MUL[c][n << 4];
        }
#if defined(__GFNI__) && defined(__AVX2__)
    for (int variant = 0; variant < 4 && !GFNI_OK; variant++) {
        if (gfni_selftest(variant)) {
            for (int c = 0; c < 256; c++)
                AFF[c] = aff_pack((uint8_t)c, variant);
            GFNI_OK = 1;
        }
    }
#endif
    READY = 1;
}

/* dst[0..len) ^= c * src[0..len) */
static void mul_acc_row(uint8_t *dst, const uint8_t *src, uint8_t c, size_t len) {
    size_t t = 0;
    if (c == 0) return;
#if defined(__AVX2__)
    if (len >= 32) {
        const __m128i lo128 = _mm_loadu_si128((const __m128i *)NIB_LO[c]);
        const __m128i hi128 = _mm_loadu_si128((const __m128i *)NIB_HI[c]);
        const __m256i lo_tbl = _mm256_broadcastsi128_si256(lo128);
        const __m256i hi_tbl = _mm256_broadcastsi128_si256(hi128);
        const __m256i mask = _mm256_set1_epi8(0x0F);
        for (; t + 32 <= len; t += 32) {
            __m256i x = _mm256_loadu_si256((const __m256i *)(src + t));
            __m256i xl = _mm256_and_si256(x, mask);
            __m256i xh = _mm256_and_si256(_mm256_srli_epi16(x, 4), mask);
            __m256i p = _mm256_xor_si256(
                _mm256_shuffle_epi8(lo_tbl, xl),
                _mm256_shuffle_epi8(hi_tbl, xh));
            __m256i d = _mm256_loadu_si256((const __m256i *)(dst + t));
            _mm256_storeu_si256((__m256i *)(dst + t), _mm256_xor_si256(d, p));
        }
    }
#endif
    {
        const uint8_t *row = MUL[c];
        for (; t < len; t++) dst[t] ^= row[src[t]];
    }
}

#if defined(__AVX2__)
/* dst ^= c0*s0 ^ c1*s1 ^ c2*s2 ^ c3*s3: four coefficient rows combined
 * per dst read-modify-write — the inner loop is L1-bandwidth bound and
 * this cuts the dst stream 4x vs four mul_acc_row passes. */
static void mul4_acc_row(uint8_t *dst, const uint8_t *const s[4],
                         const uint8_t c[4], size_t len) {
    size_t t = 0;
#if defined(__GFNI__)
    if (GFNI_OK) {
        __m256i A[4];
        for (int q = 0; q < 4; q++)
            A[q] = _mm256_set1_epi64x((long long)AFF[c[q]]);
        for (; t + 32 <= len; t += 32) {
            __m256i acc = _mm256_loadu_si256((const __m256i *)(dst + t));
            for (int q = 0; q < 4; q++) {
                __m256i x = _mm256_loadu_si256((const __m256i *)(s[q] + t));
                acc = _mm256_xor_si256(
                    acc, _mm256_gf2p8affine_epi64_epi8(x, A[q], 0));
            }
            _mm256_storeu_si256((__m256i *)(dst + t), acc);
        }
    }
#endif
    if (t + 32 <= len) {  /* non-GFNI main loop (tables built lazily) */
        const __m256i mask = _mm256_set1_epi8(0x0F);
        __m256i lo[4], hi[4];
        for (int q = 0; q < 4; q++) {
            lo[q] = _mm256_broadcastsi128_si256(
                _mm_loadu_si128((const __m128i *)NIB_LO[c[q]]));
            hi[q] = _mm256_broadcastsi128_si256(
                _mm_loadu_si128((const __m128i *)NIB_HI[c[q]]));
        }
        for (; t + 32 <= len; t += 32) {
            __m256i acc = _mm256_loadu_si256((const __m256i *)(dst + t));
            for (int q = 0; q < 4; q++) {
                __m256i x = _mm256_loadu_si256((const __m256i *)(s[q] + t));
                __m256i xl = _mm256_and_si256(x, mask);
                __m256i xh = _mm256_and_si256(_mm256_srli_epi16(x, 4), mask);
                acc = _mm256_xor_si256(
                    acc,
                    _mm256_xor_si256(_mm256_shuffle_epi8(lo[q], xl),
                                     _mm256_shuffle_epi8(hi[q], xh)));
            }
            _mm256_storeu_si256((__m256i *)(dst + t), acc);
        }
    }
    for (; t < len; t++) {
        uint8_t v = dst[t];
        for (int q = 0; q < 4; q++) v ^= MUL[c[q]][s[q][t]];
        dst[t] = v;
    }
}
#endif

/* out(r x L) = m(r x k) * x(k x L) over GF(2^8).
 *
 * Cache-blocked over the shard axis: at the N=100 broadcast shape
 * (66 x 34 over 16 KB shards) the full working set is ~1.6 MB and the
 * naive row-major loop re-misses every out row per j; 4 KB blocks keep
 * the touched out+x stripes (~400 KB) L2-resident across the whole
 * (i, j) sweep.  Within a block, coefficients are consumed four at a
 * time (mul4_acc_row). */
#define GF_BLOCK 4096

void gf256_matmul(const uint8_t *m, const uint8_t *x, uint8_t *out,
                  long rows, long cols, long len) {
    if (!READY) gf256_init();
    memset(out, 0, (size_t)rows * (size_t)len);
    for (long b = 0; b < len; b += GF_BLOCK) {
        size_t blen = (size_t)((len - b < GF_BLOCK) ? (len - b) : GF_BLOCK);
        for (long i = 0; i < rows; i++) {
            uint8_t *drow = out + (size_t)i * len + b;
            long j = 0;
#if defined(__AVX2__)
            for (; j + 4 <= cols; j += 4) {
                const uint8_t *s[4];
                uint8_t c[4];
                int live = 0;
                for (int q = 0; q < 4; q++) {
                    c[q] = m[(size_t)i * cols + j + q];
                    s[q] = x + (size_t)(j + q) * len + b;
                    live |= c[q];
                }
                if (live) mul4_acc_row(drow, s, c, blen);
            }
#endif
            for (; j < cols; j++)
                mul_acc_row(drow, x + (size_t)j * len + b,
                            m[(size_t)i * cols + j], blen);
        }
    }
}

/* Elementwise c = a * b over GF(2^8). */
void gf256_mul_elem(const uint8_t *a, const uint8_t *b, uint8_t *c, long n) {
    if (!READY) gf256_init();
    for (long i = 0; i < n; i++) c[i] = MUL[a[i]][b[i]];
}
