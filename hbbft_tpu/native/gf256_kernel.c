/* GF(2^8) Reed-Solomon matmul kernel (host side).
 *
 * Native equivalent of the reference's `reed-solomon-erasure` Rust crate
 * (SURVEY.md 2.2): the host-path hot op behind broadcast encode/decode.
 * The TPU path (hbbft_tpu/ops/gf256.py) handles device batches; this file
 * serves the VirtualNet runtime's host-side shard work.
 *
 * Strategy: the classic SIMD nibble-split.  For multiplier constant c the
 * product c*x factors through x's nibbles:  c*x = LO_c[x & 15] ^ HI_c[x >> 4]
 * (GF addition is XOR and the nibble decomposition is linear).  With AVX2 the
 * two 16-entry tables live in a 256-bit register and PSHUFB resolves 32 bytes
 * per shuffle.  Scalar fallback uses a 64KB full product table.
 *
 * Field: poly 0x11D, generator 2 - matching crypto/erasure.py.
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

#define POLY 0x11D

static uint8_t MUL[256][256];
static uint8_t NIB_LO[256][16]; /* NIB_LO[c][n] = c * n        */
static uint8_t NIB_HI[256][16]; /* NIB_HI[c][n] = c * (n << 4) */
static int READY = 0;

static uint8_t gf_mul_slow(uint32_t a, uint32_t b) {
    uint32_t r = 0;
    while (b) {
        if (b & 1) r ^= a;
        a <<= 1;
        if (a & 0x100) a ^= POLY;
        b >>= 1;
    }
    return (uint8_t)r;
}

void gf256_init(void) {
    if (READY) return;
    for (int a = 0; a < 256; a++)
        for (int b = 0; b < 256; b++)
            MUL[a][b] = gf_mul_slow((uint32_t)a, (uint32_t)b);
    for (int c = 0; c < 256; c++)
        for (int n = 0; n < 16; n++) {
            NIB_LO[c][n] = MUL[c][n];
            NIB_HI[c][n] = MUL[c][n << 4];
        }
    READY = 1;
}

/* dst[0..len) ^= c * src[0..len) */
static void mul_acc_row(uint8_t *dst, const uint8_t *src, uint8_t c, size_t len) {
    size_t t = 0;
    if (c == 0) return;
#if defined(__AVX2__)
    if (len >= 32) {
        const __m128i lo128 = _mm_loadu_si128((const __m128i *)NIB_LO[c]);
        const __m128i hi128 = _mm_loadu_si128((const __m128i *)NIB_HI[c]);
        const __m256i lo_tbl = _mm256_broadcastsi128_si256(lo128);
        const __m256i hi_tbl = _mm256_broadcastsi128_si256(hi128);
        const __m256i mask = _mm256_set1_epi8(0x0F);
        for (; t + 32 <= len; t += 32) {
            __m256i x = _mm256_loadu_si256((const __m256i *)(src + t));
            __m256i xl = _mm256_and_si256(x, mask);
            __m256i xh = _mm256_and_si256(_mm256_srli_epi16(x, 4), mask);
            __m256i p = _mm256_xor_si256(
                _mm256_shuffle_epi8(lo_tbl, xl),
                _mm256_shuffle_epi8(hi_tbl, xh));
            __m256i d = _mm256_loadu_si256((const __m256i *)(dst + t));
            _mm256_storeu_si256((__m256i *)(dst + t), _mm256_xor_si256(d, p));
        }
    }
#endif
    {
        const uint8_t *row = MUL[c];
        for (; t < len; t++) dst[t] ^= row[src[t]];
    }
}

/* out(r x L) = m(r x k) * x(k x L) over GF(2^8). */
void gf256_matmul(const uint8_t *m, const uint8_t *x, uint8_t *out,
                  long rows, long cols, long len) {
    if (!READY) gf256_init();
    memset(out, 0, (size_t)rows * (size_t)len);
    for (long i = 0; i < rows; i++)
        for (long j = 0; j < cols; j++)
            mul_acc_row(out + (size_t)i * len, x + (size_t)j * len,
                        m[(size_t)i * cols + j], (size_t)len);
}

/* Elementwise c = a * b over GF(2^8). */
void gf256_mul_elem(const uint8_t *a, const uint8_t *b, uint8_t *c, long n) {
    if (!READY) gf256_init();
    for (long i = 0; i < n; i++) c[i] = MUL[a[i]][b[i]];
}
