"""Native (C) host kernels, built on first use with graceful fallback.

The reference leans on native Rust crates for its host-side hot math
(`reed-solomon-erasure`, SURVEY.md §2.2).  Here the equivalent host kernels
live in a small C file compiled at first import — `cc -O3 -march=native`
into a cached shared object next to the source — and bound via ctypes (no
pybind11 in this image).  If no toolchain is available the callers fall
back to the numpy implementations transparently.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import tempfile
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "gf256_kernel.c")


def _host_tag() -> str:
    """ISA fingerprint for the .so cache name: a -march=native object built
    on one machine must not be loaded on another (SIGILL on a checkout
    shared over NFS or baked into a reused container image)."""
    feat = ""
    try:
        with open("/proc/cpuinfo") as f:
            feat = next((ln for ln in f if ln.startswith(("flags", "Features"))), "")
    except OSError:
        pass
    digest = hashlib.sha256((platform.machine() + feat).encode()).hexdigest()[:12]
    return f"{platform.machine()}-{digest}"


_SO = os.path.join(_DIR, f"_gf256_kernel.{_host_tag()}.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_so(src: str, so: str) -> bool:
    """(Re)build a shared object if missing or stale.  Returns success."""
    try:
        if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
            return True
        # Unique temp output per process so concurrent builders can't
        # publish each other's half-written object; os.replace is atomic.
        tmp = f"{so}.{os.getpid()}.tmp"
        for flags in (["-march=native"], []):  # fall back if -march trips
            cmd = ["cc", "-O3", "-shared", "-fPIC"] + flags + ["-o", tmp, src]
            try:
                subprocess.run(
                    cmd, check=True, capture_output=True, timeout=120
                )
                os.replace(tmp, so)
                return True
            except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
                continue
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
        return False
    except OSError:
        return False


def _build() -> bool:
    return _build_so(_SRC, _SO)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.gf256_init()
    u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    lib.gf256_matmul.argtypes = [
        u8p,
        u8p,
        u8p,
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_long,
    ]
    lib.gf256_matmul.restype = None
    lib.gf256_mul_elem.argtypes = [u8p, u8p, u8p, ctypes.c_long]
    lib.gf256_mul_elem.restype = None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def gf256_matmul(m: np.ndarray, x: np.ndarray) -> Optional[np.ndarray]:
    """(r×k)·(k×L) GF(2⁸) product via the C kernel, or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    m = np.ascontiguousarray(m, dtype=np.uint8)
    x = np.ascontiguousarray(x, dtype=np.uint8)
    r, k = m.shape
    k2, L = x.shape
    if k != k2:
        raise ValueError("shape mismatch")
    out = np.empty((r, L), dtype=np.uint8)
    lib.gf256_matmul(m, x, out, r, k, L)
    return out


# ---------------------------------------------------------------------------
# SHA-256 / Merkle batch kernel (sha256_kernel.c)
# ---------------------------------------------------------------------------

_SHA_SRC = os.path.join(_DIR, "sha256_kernel.c")
_SHA_SO = os.path.join(_DIR, f"_sha256_kernel.{_host_tag()}.so")
_sha_lib: Optional[ctypes.CDLL] = None
_sha_tried = False


def _load_sha() -> Optional[ctypes.CDLL]:
    global _sha_lib, _sha_tried
    if _sha_lib is not None or _sha_tried:
        return _sha_lib
    _sha_tried = True
    if not _build_so(_SHA_SRC, _SHA_SO):
        return None
    try:
        lib = ctypes.CDLL(_SHA_SO)
    except OSError:
        return None
    u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
    lib.sha256_batch.argtypes = [u8p, ctypes.c_long, ctypes.c_long, u8p]
    lib.sha256_batch.restype = None
    lib.merkle_validate_batch.argtypes = [
        u8p, ctypes.c_long, u8p, i32p, u8p,
        ctypes.c_long, ctypes.c_long, ctypes.c_long, u8p,
    ]
    lib.merkle_validate_batch.restype = None
    lib.merkle_root_batch.argtypes = [
        u8p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        ctypes.c_long, ctypes.c_long, u8p,
    ]
    lib.merkle_root_batch.restype = None

    # Self-test against hashlib — guards the SHA-NI block schedules (and
    # falls back to the scalar path, then to hashlib, on any mismatch).
    # Two items with distinct contents and a >64-byte length: covers the
    # dual-stream (x2) path, the single path, and both padding branches.
    probe = np.frombuffer(
        b"abc" + bytes(62) + b"defg" + bytes(61), dtype=np.uint8
    ).reshape(2, 65)
    want = b"".join(
        hashlib.sha256(probe[i].tobytes()).digest() for i in range(2)
    )
    out = np.empty((2, 32), dtype=np.uint8)
    lib.sha256_batch(np.ascontiguousarray(probe), 2, 65, out)
    if out.tobytes() != want:
        try:
            lib.sha256_disable_ni()
            lib.sha256_batch(np.ascontiguousarray(probe), 2, 65, out)
            if out.tobytes() != want:
                return None
        except Exception:
            return None
    _sha_lib = lib
    return _sha_lib


def sha256_available() -> bool:
    return _load_sha() is not None


def sha256_batch(data: np.ndarray) -> Optional[np.ndarray]:
    """Hash each row of a (n, item_len) uint8 array; None if no C kernel."""
    lib = _load_sha()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, dtype=np.uint8)
    n, item_len = data.shape
    out = np.empty((n, 32), dtype=np.uint8)
    lib.sha256_batch(data, n, item_len, out)
    return out


def merkle_validate_batch(
    leaf_vals: np.ndarray,
    paths: np.ndarray,
    indices: np.ndarray,
    roots: np.ndarray,
    reps: int,
) -> Optional[np.ndarray]:
    """Validate n proofs (each reps times).  Shapes: leaf_vals (n, L),
    paths (n, depth, 32), indices (n,), roots (n, 32).  Returns (n,) bool
    or None if the C kernel is unavailable or L is out of contract."""
    lib = _load_sha()
    if lib is None:
        return None
    leaf_vals = np.ascontiguousarray(leaf_vals, dtype=np.uint8)
    n, leaf_len = leaf_vals.shape
    if leaf_len + 1 > 4096:
        return None  # h_leaf buffer contract in sha256_kernel.c
    paths = np.ascontiguousarray(paths, dtype=np.uint8)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    roots = np.ascontiguousarray(roots, dtype=np.uint8)
    depth = paths.shape[1] if paths.ndim == 3 else 0
    ok = np.empty(n, dtype=np.uint8)
    lib.merkle_validate_batch(
        leaf_vals, leaf_len, paths, indices, roots, n, depth, int(reps), ok
    )
    return ok.astype(bool)


def merkle_root_batch(
    leaves: np.ndarray, size: int, reps: int
) -> Optional[np.ndarray]:
    """Roots of t trees: leaves (t, n_leaves, leaf_len), padded to `size`
    (pow2 ≤ 256) with empty leaves; each built reps times.  (t, 32) out."""
    lib = _load_sha()
    if lib is None:
        return None
    leaves = np.ascontiguousarray(leaves, dtype=np.uint8)
    t, n_leaves, leaf_len = leaves.shape
    if size > 256 or leaf_len + 1 > 4096:
        return None
    out = np.empty((t, 32), dtype=np.uint8)
    lib.merkle_root_batch(leaves, t, n_leaves, leaf_len, size, int(reps), out)
    return out


# ---------------------------------------------------------------------------
# hash-to-G2 kernel (hashg2_kernel.c) — the DKG/coin host-hash wall
# ---------------------------------------------------------------------------

_HG2_SRC = os.path.join(_DIR, "hashg2_kernel.c")
_HG2_SO = os.path.join(_DIR, f"_hashg2_kernel.{_host_tag()}.so")
_hg2_lib = None
_hg2_tried = False
_hg2_checked = False


def _load_hashg2():
    global _hg2_lib, _hg2_tried
    if _hg2_lib is not None or _hg2_tried:
        return _hg2_lib
    _hg2_tried = True
    if os.environ.get("HBBFT_TPU_NO_NATIVE_HASHG2"):
        return None
    if not _build_so(_HG2_SRC, _HG2_SO):
        return None
    try:
        lib = ctypes.CDLL(_HG2_SO)
    except OSError:
        return None
    u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    u64p = np.ctypeslib.ndpointer(dtype=np.uint64, flags="C_CONTIGUOUS")
    lib.hashg2_one.argtypes = [u8p, ctypes.c_long, u64p]
    lib.hashg2_one.restype = ctypes.c_int
    _hg2_lib = lib
    return _hg2_lib


def _limbs_to_int(limbs) -> int:
    out = 0
    for i, v in enumerate(limbs):
        out |= int(v) << (64 * i)
    return out


def hashg2(data: bytes, pure_fn=None):
    """Native hash-to-G2: ((x0, x1), (y0, y1)) ints, or None if the kernel
    is unavailable.  ``pure_fn`` (the golden Python hash) is required on
    the FIRST call: the kernel is golden-checked against it on a few docs
    and permanently disabled on any mismatch — the native path must be
    point-for-point interchangeable with the pure one."""
    global _hg2_lib, _hg2_checked
    lib = _load_hashg2()
    if lib is None:
        return None
    out = np.empty(24, dtype=np.uint64)

    def one(doc: bytes):
        buf = np.frombuffer(doc, dtype=np.uint8) if doc else np.empty(0, np.uint8)
        rc = lib.hashg2_one(np.ascontiguousarray(buf), len(doc), out)
        if rc != 0:
            return None
        return (
            (_limbs_to_int(out[0:6]), _limbs_to_int(out[6:12])),
            (_limbs_to_int(out[12:18]), _limbs_to_int(out[18:24])),
        )

    if not _hg2_checked:
        if pure_fn is None:
            return None  # cannot self-test yet; caller retries with pure_fn
        for probe in (b"", b"g2-golden-0", b"g2-golden-1", bytes(range(97))):
            got = one(probe)
            if got is None or got != pure_fn(probe):
                _hg2_lib = None  # mismatch: disable permanently
                return None
        _hg2_checked = True
    return one(data)

