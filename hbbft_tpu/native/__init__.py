"""Native (C) host kernels, built on first use with graceful fallback.

The reference leans on native Rust crates for its host-side hot math
(`reed-solomon-erasure`, SURVEY.md §2.2).  Here the equivalent host kernels
live in a small C file compiled at first import — `cc -O3 -march=native`
into a cached shared object next to the source — and bound via ctypes (no
pybind11 in this image).  If no toolchain is available the callers fall
back to the numpy implementations transparently.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import tempfile
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "gf256_kernel.c")


def _host_tag() -> str:
    """ISA fingerprint for the .so cache name: a -march=native object built
    on one machine must not be loaded on another (SIGILL on a checkout
    shared over NFS or baked into a reused container image)."""
    feat = ""
    try:
        with open("/proc/cpuinfo") as f:
            feat = next((ln for ln in f if ln.startswith(("flags", "Features"))), "")
    except OSError:
        pass
    digest = hashlib.sha256((platform.machine() + feat).encode()).hexdigest()[:12]
    return f"{platform.machine()}-{digest}"


_SO = os.path.join(_DIR, f"_gf256_kernel.{_host_tag()}.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    """(Re)build the shared object if missing or stale.  Returns success."""
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return True
        # Unique temp output per process so concurrent builders can't
        # publish each other's half-written object; os.replace is atomic.
        tmp = f"{_SO}.{os.getpid()}.tmp"
        for flags in (["-march=native"], []):  # fall back if -march trips
            cmd = (
                ["cc", "-O3", "-shared", "-fPIC"] + flags + ["-o", tmp, _SRC]
            )
            try:
                subprocess.run(
                    cmd, check=True, capture_output=True, timeout=120
                )
                os.replace(tmp, _SO)
                return True
            except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
                continue
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
        return False
    except OSError:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.gf256_init()
    u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    lib.gf256_matmul.argtypes = [
        u8p,
        u8p,
        u8p,
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_long,
    ]
    lib.gf256_matmul.restype = None
    lib.gf256_mul_elem.argtypes = [u8p, u8p, u8p, ctypes.c_long]
    lib.gf256_mul_elem.restype = None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def gf256_matmul(m: np.ndarray, x: np.ndarray) -> Optional[np.ndarray]:
    """(r×k)·(k×L) GF(2⁸) product via the C kernel, or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    m = np.ascontiguousarray(m, dtype=np.uint8)
    x = np.ascontiguousarray(x, dtype=np.uint8)
    r, k = m.shape
    k2, L = x.shape
    if k != k2:
        raise ValueError("shape mismatch")
    out = np.empty((r, L), dtype=np.uint8)
    lib.gf256_matmul(m, x, out, r, k, L)
    return out
