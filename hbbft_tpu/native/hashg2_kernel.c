/* hash-to-G2 host kernel: the DKG/coin hash wall made native.
 *
 * Mirrors hbbft_tpu/crypto/bls381.py's hash_to_g2 EXACTLY — same
 * try-and-increment schedule (sha256(tag + ctr_be4 + plane + data) x2
 * per Fq coordinate), same complex-method Fq2 square root with the same
 * deterministic root choice (lexicographic tuple min of y and -y over
 * CANONICAL integers), same Budroni-Pintore cofactor clearing
 * [u^2-u-1]P + [u-1]psi(P) + psi^2(2P) — so native and pure paths are
 * interchangeable point-for-point (the Python loader golden-checks this
 * at first use and falls back on any mismatch).
 *
 * Why: the pure path costs 13.65 ms/doc (measured round 5; ~87% in the
 * affine-with-inversion cofactor clearing).  The era-change DKG hashes
 * 2(N^2 + N^3) docs, which walls the N=100 churn row at ~7.7 h
 * (PERF.md round-5 itemization).  Here: Montgomery 6x64 Fq (schoolbook
 * mul12 + REDC), jacobian a=0 EC over Fq2 (no per-op inversions), one
 * Fq2 inversion per point at the end.
 *
 * Reference analogue: threshold_crypto's hash_to_g2 under the pairing
 * crate (SURVEY.md §2.2) — natively implemented there too.
 */

#include <stdint.h>
#include <string.h>

typedef uint64_t u64;
typedef __uint128_t u128;

/* ---------------------------------------------------------------- SHA-256 */

static const uint32_t SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

#define ROR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256(const unsigned char *data, long len, unsigned char out[32]) {
    uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                     0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    unsigned char block[64];
    long full = len / 64, rem = len % 64, b;
    for (b = 0; b <= full; b++) {
        long n = (b < full) ? 64 : rem;
        const unsigned char *src = data + b * 64;
        uint32_t w[64];
        int i;
        int last = 0;
        if (b == full) {
            memcpy(block, src, (size_t)n);
            block[n] = 0x80;
            if (n + 9 <= 64) {
                memset(block + n + 1, 0, (size_t)(64 - n - 9));
                u64 bits = (u64)len * 8;
                for (i = 0; i < 8; i++)
                    block[56 + i] = (unsigned char)(bits >> (56 - 8 * i));
                last = 1;
            } else {
                memset(block + n + 1, 0, (size_t)(64 - n - 1));
            }
            src = block;
        }
        for (;;) {
            for (i = 0; i < 16; i++)
                w[i] = ((uint32_t)src[4 * i] << 24) | ((uint32_t)src[4 * i + 1] << 16) |
                       ((uint32_t)src[4 * i + 2] << 8) | src[4 * i + 3];
            for (i = 16; i < 64; i++) {
                uint32_t s0 = ROR(w[i - 15], 7) ^ ROR(w[i - 15], 18) ^ (w[i - 15] >> 3);
                uint32_t s1 = ROR(w[i - 2], 17) ^ ROR(w[i - 2], 19) ^ (w[i - 2] >> 10);
                w[i] = w[i - 16] + s0 + w[i - 7] + s1;
            }
            uint32_t a = h[0], bb = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
                     g = h[6], hh = h[7];
            for (i = 0; i < 64; i++) {
                uint32_t S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
                uint32_t ch = (e & f) ^ (~e & g);
                uint32_t t1 = hh + S1 + ch + SHA_K[i] + w[i];
                uint32_t S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
                uint32_t mj = (a & bb) ^ (a & c) ^ (bb & c);
                uint32_t t2 = S0 + mj;
                hh = g; g = f; f = e; e = d + t1;
                d = c; c = bb; bb = a; a = t1 + t2;
            }
            h[0] += a; h[1] += bb; h[2] += c; h[3] += d;
            h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
            if (b < full || last) break;
            /* padding spilled into an extra block */
            memset(block, 0, 56);
            u64 bits = (u64)len * 8;
            for (i = 0; i < 8; i++)
                block[56 + i] = (unsigned char)(bits >> (56 - 8 * i));
            src = block;
            last = 1;
        }
    }
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (unsigned char)(h[i] >> 24);
        out[4 * i + 1] = (unsigned char)(h[i] >> 16);
        out[4 * i + 2] = (unsigned char)(h[i] >> 8);
        out[4 * i + 3] = (unsigned char)h[i];
    }
}

/* ------------------------------------------------- Fq (Montgomery, 6x64) */

static const u64 QL[6] = {0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL, 0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const u64 R2[6] = {0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL, 0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};
static const u64 R1[6] = {0x760900000002fffdULL, 0xebf4000bc40c0002ULL, 0x5f48985753c758baULL, 0x77ce585370525745ULL, 0x5c071a97a256ec6dULL, 0x15f65ec3fa80e493ULL};
static const u64 NPRIME = 0x89f3fffcfffcfffdULL;
static const u64 EXP_SQRT[6] = {0xee7fbfffffffeaabULL, 0x07aaffffac54ffffULL, 0xd9cc34a83dac3d89ULL, 0xd91dd2e13ce144afULL, 0x92c6e9ed90d2eb35ULL, 0x0680447a8e5ff9a6ULL};
static const u64 EXP_INV[6] = {0xb9feffffffffaaa9ULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL, 0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const u64 PSI_CX_1[6] = {0x8bfd00000000aaadULL, 0x409427eb4f49fffdULL, 0x897d29650fb85f9bULL, 0xaa0d857d89759ad4ULL, 0xec02408663d4de85ULL, 0x1a0111ea397fe699ULL};
static const u64 PSI_CY_0[6] = {0xf1ee7b04121bdea2ULL, 0x304466cf3e67fa0aULL, 0xef396489f61eb45eULL, 0x1c3dedd930b1cf60ULL, 0xe2e9c448d77a2cd9ULL, 0x135203e60180a68eULL};
static const u64 PSI_CY_1[6] = {0xc81084fbede3cc09ULL, 0xee67992f72ec05f4ULL, 0x77f76e17009241c5ULL, 0x48395dabc2d3435eULL, 0x6831e36d6bd17ffeULL, 0x06af0e0437ff400bULL};
static const u64 U_ABS = 0xd201000000010000ULL; /* u = -U_ABS for BLS12-381 */

typedef struct { u64 v[6]; } fq;   /* Montgomery domain */

static int fq_cmp_raw(const u64 *a, const u64 *b) {
    for (int i = 5; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static void fq_sub_raw(u64 *r, const u64 *a, const u64 *b) {
    u64 borrow = 0;
    for (int i = 0; i < 6; i++) {
        u64 t = a[i] - b[i];
        u64 br2 = (a[i] < b[i]);
        u64 t2 = t - borrow;
        borrow = br2 | (t < borrow);
        r[i] = t2;
    }
}

static void fq_add(fq *r, const fq *a, const fq *b) {
    u64 carry = 0;
    for (int i = 0; i < 6; i++) {
        u128 t = (u128)a->v[i] + b->v[i] + carry;
        r->v[i] = (u64)t;
        carry = (u64)(t >> 64);
    }
    if (carry || fq_cmp_raw(r->v, QL) >= 0) fq_sub_raw(r->v, r->v, QL);
}

static void fq_sub(fq *r, const fq *a, const fq *b) {
    if (fq_cmp_raw(a->v, b->v) >= 0) {
        fq_sub_raw(r->v, a->v, b->v);
    } else {
        u64 t[6];
        fq_sub_raw(t, b->v, a->v);
        fq_sub_raw(r->v, QL, t);
    }
}

static int fq_is_zero(const fq *a) {
    u64 o = 0;
    for (int i = 0; i < 6; i++) o |= a->v[i];
    return o == 0;
}

static void fq_neg(fq *r, const fq *a) {
    if (fq_is_zero(a)) { *r = *a; return; }
    fq_sub_raw(r->v, QL, a->v);
}

/* T[12] <- a*b; then REDC in place */
static void fq_mul(fq *r, const fq *a, const fq *b) {
    u64 T[13];
    memset(T, 0, sizeof T);
    for (int i = 0; i < 6; i++) {
        u64 carry = 0;
        for (int j = 0; j < 6; j++) {
            u128 t = (u128)a->v[i] * b->v[j] + T[i + j] + carry;
            T[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        T[i + 6] += carry;
    }
    /* REDC: 6 rounds */
    for (int i = 0; i < 6; i++) {
        u64 m = T[i] * NPRIME;
        u64 carry = 0;
        for (int j = 0; j < 6; j++) {
            u128 t = (u128)m * QL[j] + T[i + j] + carry;
            T[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        for (int k = i + 6; carry; k++) {
            u128 t = (u128)T[k] + carry;
            T[k] = (u64)t;
            carry = (u64)(t >> 64);
        }
    }
    for (int i = 0; i < 6; i++) r->v[i] = T[i + 6];
    if (T[12] || fq_cmp_raw(r->v, QL) >= 0) fq_sub_raw(r->v, r->v, QL);
}

static void fq_sqr(fq *r, const fq *a) { fq_mul(r, a, a); }

static void fq_set_one(fq *r) { memcpy(r->v, R1, sizeof R1); }
static void fq_set_zero(fq *r) { memset(r->v, 0, sizeof r->v); }

/* canonical u64[6] -> Montgomery */
static void fq_from_canon(fq *r, const u64 *c) {
    fq t, r2;
    memcpy(t.v, c, 6 * sizeof(u64));
    memcpy(r2.v, R2, sizeof R2);
    fq_mul(r, &t, &r2);
}

/* Montgomery -> canonical u64[6] (REDC with 1) */
static void fq_to_canon(u64 *c, const fq *a) {
    fq one_raw, t;
    memset(one_raw.v, 0, sizeof one_raw.v);
    one_raw.v[0] = 1;
    fq_mul(&t, a, &one_raw);
    memcpy(c, t.v, 6 * sizeof(u64));
}

/* a^e for a 6-limb exponent (MSB-first square-and-multiply) */
static void fq_pow(fq *r, const fq *a, const u64 *e) {
    fq acc;
    fq_set_one(&acc);
    int started = 0;
    for (int limb = 5; limb >= 0; limb--) {
        for (int bit = 63; bit >= 0; bit--) {
            if (started) fq_sqr(&acc, &acc);
            if ((e[limb] >> bit) & 1) {
                if (started) fq_mul(&acc, &acc, a);
                else { acc = *a; started = 1; }
            }
        }
    }
    if (!started) fq_set_one(&acc);
    *r = acc;
}

static int fq_equal(const fq *a, const fq *b) {
    return fq_cmp_raw(a->v, b->v) == 0;
}

/* sqrt in Fq (q = 3 mod 4): s = a^((q+1)/4), verified.  1 on success. */
static int fq_sqrt(fq *r, const fq *a) {
    fq s, s2;
    fq_pow(&s, a, EXP_SQRT);
    fq_sqr(&s2, &s);
    if (!fq_equal(&s2, a)) return 0;
    *r = s;
    return 1;
}

static void fq_inv(fq *r, const fq *a) { fq_pow(r, a, EXP_INV); }

/* ------------------------------------------------------------------- Fq2 */

typedef struct { fq c0, c1; } fq2;

static void fq2_add(fq2 *r, const fq2 *a, const fq2 *b) {
    fq_add(&r->c0, &a->c0, &b->c0);
    fq_add(&r->c1, &a->c1, &b->c1);
}
static void fq2_sub(fq2 *r, const fq2 *a, const fq2 *b) {
    fq_sub(&r->c0, &a->c0, &b->c0);
    fq_sub(&r->c1, &a->c1, &b->c1);
}
static void fq2_neg(fq2 *r, const fq2 *a) {
    fq_neg(&r->c0, &a->c0);
    fq_neg(&r->c1, &a->c1);
}
static void fq2_conj(fq2 *r, const fq2 *a) {
    r->c0 = a->c0;
    fq_neg(&r->c1, &a->c1);
}
static void fq2_mul(fq2 *r, const fq2 *a, const fq2 *b) {
    /* Karatsuba: 3 Fq muls.  (a0b0 - a1b1, (a0+a1)(b0+b1) - a0b0 - a1b1) */
    fq m0, m1, sa, sb, cross, o0, o1;
    fq_mul(&m0, &a->c0, &b->c0);
    fq_mul(&m1, &a->c1, &b->c1);
    fq_add(&sa, &a->c0, &a->c1);
    fq_add(&sb, &b->c0, &b->c1);
    fq_mul(&cross, &sa, &sb);
    fq_sub(&o0, &m0, &m1);
    fq_sub(&cross, &cross, &m0);
    fq_sub(&o1, &cross, &m1);
    r->c0 = o0;
    r->c1 = o1;
}
static void fq2_sqr(fq2 *r, const fq2 *a) {
    /* complex squaring: 2 Fq muls.  ((a0+a1)(a0-a1), 2 a0 a1) */
    fq s, d, m, o1;
    fq_add(&s, &a->c0, &a->c1);
    fq_sub(&d, &a->c0, &a->c1);
    fq_mul(&m, &a->c1, &a->c0);
    fq_add(&o1, &m, &m);
    fq_mul(&r->c0, &s, &d);
    r->c1 = o1;
}

static int fq2_is_zero(const fq2 *a) {
    return fq_is_zero(&a->c0) && fq_is_zero(&a->c1);
}
static int fq2_equal(const fq2 *a, const fq2 *b) {
    return fq_equal(&a->c0, &b->c0) && fq_equal(&a->c1, &b->c1);
}
static void fq2_inv(fq2 *r, const fq2 *a) {
    fq n0, n1, norm, ni;
    fq_sqr(&n0, &a->c0);
    fq_sqr(&n1, &a->c1);
    fq_add(&norm, &n0, &n1);
    fq_inv(&ni, &norm);
    fq_mul(&r->c0, &a->c0, &ni);
    fq_mul(&n0, &a->c1, &ni);
    fq_neg(&r->c1, &n0);
}

/* lexicographic canonical compare of (c0, c1) tuples — mirrors Python's
 * tuple min() over canonical ints */
static int fq2_canon_cmp(const fq2 *a, const fq2 *b) {
    u64 ca[6], cb[6];
    fq_to_canon(ca, &a->c0);
    fq_to_canon(cb, &b->c0);
    int c = fq_cmp_raw(ca, cb);
    if (c) return c;
    fq_to_canon(ca, &a->c1);
    fq_to_canon(cb, &b->c1);
    return fq_cmp_raw(ca, cb);
}

/* sqrt in Fq2, complex method — EXACT mirror of bls381.fq2_sqrt */
static int fq2_sqrt(fq2 *r, const fq2 *a) {
    if (fq2_is_zero(a)) { fq_set_zero(&r->c0); fq_set_zero(&r->c1); return 1; }
    if (fq_is_zero(&a->c1)) {
        fq s;
        if (fq_sqrt(&s, &a->c0)) {
            r->c0 = s; fq_set_zero(&r->c1);
        } else {
            fq na0;
            fq_neg(&na0, &a->c0);
            if (!fq_sqrt(&s, &na0)) return 0;
            fq_set_zero(&r->c0); r->c1 = s;
        }
        /* verified below like the Python path's implicit exactness */
        fq2 chk; fq2_sqr(&chk, r);
        return fq2_equal(&chk, a);
    }
    fq n0, n1, norm, alpha, inv2, delta, x0, twox0, ix, x1;
    fq_sqr(&n0, &a->c0);
    fq_sqr(&n1, &a->c1);
    fq_add(&norm, &n0, &n1);
    if (!fq_sqrt(&alpha, &norm)) return 0;
    /* inv2 = 2^{-1}: (Q+1)/2 canonical — computed once */
    {
        fq two;
        fq_set_one(&two);
        fq_add(&two, &two, &two);
        fq_inv(&inv2, &two);
    }
    fq_add(&delta, &a->c0, &alpha);
    fq_mul(&delta, &delta, &inv2);
    if (!fq_sqrt(&x0, &delta)) {
        fq_sub(&delta, &a->c0, &alpha);
        fq_mul(&delta, &delta, &inv2);
        if (!fq_sqrt(&x0, &delta)) return 0;
    }
    fq_add(&twox0, &x0, &x0);
    fq_inv(&ix, &twox0);
    fq_mul(&x1, &a->c1, &ix);
    r->c0 = x0;
    r->c1 = x1;
    fq2 chk;
    fq2_sqr(&chk, r);
    return fq2_equal(&chk, a);
}

/* ------------------------------------------ E'(Fq2), jacobian, a = 0 ----- */

typedef struct { fq2 X, Y, Z; int inf; } g2j;

static void g2_set_inf(g2j *p) { p->inf = 1; }

static void g2_from_affine(g2j *p, const fq2 *x, const fq2 *y) {
    p->X = *x;
    p->Y = *y;
    fq_set_one(&p->Z.c0);
    fq_set_zero(&p->Z.c1);
    p->inf = 0;
}

static void g2_dbl(g2j *r, const g2j *p) {
    if (p->inf || fq2_is_zero(&p->Y)) { g2_set_inf(r); return; }
    fq2 A, B, C, D, E, F, t, X3, Y3, Z3;
    fq2_sqr(&A, &p->X);
    fq2_sqr(&B, &p->Y);
    fq2_sqr(&C, &B);
    fq2_add(&t, &p->X, &B);
    fq2_sqr(&t, &t);
    fq2_sub(&t, &t, &A);
    fq2_sub(&t, &t, &C);
    fq2_add(&D, &t, &t);
    fq2_add(&E, &A, &A);
    fq2_add(&E, &E, &A);
    fq2_sqr(&F, &E);
    fq2_sub(&X3, &F, &D);
    fq2_sub(&X3, &X3, &D);
    fq2_sub(&t, &D, &X3);
    fq2_mul(&Y3, &E, &t);
    fq2_add(&t, &C, &C);   /* 2C */
    fq2_add(&t, &t, &t);   /* 4C */
    fq2_add(&t, &t, &t);   /* 8C */
    fq2_sub(&Y3, &Y3, &t);
    fq2_mul(&Z3, &p->Y, &p->Z);
    fq2_add(&Z3, &Z3, &Z3);
    r->X = X3; r->Y = Y3; r->Z = Z3; r->inf = 0;
}

static void g2_add(g2j *r, const g2j *p, const g2j *q) {
    if (p->inf) { *r = *q; return; }
    if (q->inf) { *r = *p; return; }
    fq2 Z1Z1, Z2Z2, U1, U2, S1, S2, t, H, R_, H2, H3, X3, Y3, Z3;
    fq2_sqr(&Z1Z1, &p->Z);
    fq2_sqr(&Z2Z2, &q->Z);
    fq2_mul(&U1, &p->X, &Z2Z2);
    fq2_mul(&U2, &q->X, &Z1Z1);
    fq2_mul(&t, &q->Z, &Z2Z2);
    fq2_mul(&S1, &p->Y, &t);
    fq2_mul(&t, &p->Z, &Z1Z1);
    fq2_mul(&S2, &q->Y, &t);
    if (fq2_equal(&U1, &U2)) {
        if (fq2_equal(&S1, &S2)) { g2_dbl(r, p); return; }
        g2_set_inf(r);
        return;
    }
    fq2_sub(&H, &U2, &U1);
    fq2_sub(&R_, &S2, &S1);
    fq2_sqr(&H2, &H);
    fq2_mul(&H3, &H, &H2);
    fq2_sqr(&X3, &R_);
    fq2_sub(&X3, &X3, &H3);
    fq2_mul(&t, &U1, &H2);
    fq2_sub(&X3, &X3, &t);
    fq2_sub(&X3, &X3, &t);
    fq2_sub(&t, &t, &X3);      /* U1*H2 - X3 */
    fq2_mul(&Y3, &R_, &t);
    fq2_mul(&t, &S1, &H3);
    fq2_sub(&Y3, &Y3, &t);
    fq2_mul(&Z3, &p->Z, &q->Z);
    fq2_mul(&Z3, &Z3, &H);
    r->X = X3; r->Y = Y3; r->Z = Z3; r->inf = 0;
}

static void g2_neg(g2j *r, const g2j *p) {
    *r = *p;
    if (!p->inf) fq2_neg(&r->Y, &p->Y);
}

/* k*P for u64 k (MSB-first) */
static void g2_mul_u64(g2j *r, u64 k, const g2j *p) {
    g2j acc;
    g2_set_inf(&acc);
    int started = 0;
    for (int bit = 63; bit >= 0; bit--) {
        if (started) g2_dbl(&acc, &acc);
        if ((k >> bit) & 1) {
            if (started) g2_add(&acc, &acc, p);
            else { acc = *p; started = 1; }
        }
    }
    if (!started) g2_set_inf(&acc);
    *r = acc;
}

/* [u]P with the NEGATIVE BLS parameter (u = -U_ABS) */
static void g2_mul_u_signed(g2j *r, const g2j *p) {
    g2j t;
    g2_mul_u64(&t, U_ABS, p);
    g2_neg(r, &t);
}

/* psi(x, y) = (cx * conj(x), cy * conj(y)); jacobian: conj(Z) rides along */
static fq2 PSI_CX_M, PSI_CY_M;

/* dlopen-time init: no lazy flag, no data race — ctypes releases the GIL
 * during calls, so concurrent hashers must never observe a torn constant */
__attribute__((constructor)) static void psi_init(void) {
    fq_set_zero(&PSI_CX_M.c0);
    fq_from_canon(&PSI_CX_M.c1, PSI_CX_1);
    fq_from_canon(&PSI_CY_M.c0, PSI_CY_0);
    fq_from_canon(&PSI_CY_M.c1, PSI_CY_1);
}

static void g2_psi(g2j *r, const g2j *p) {
    if (p->inf) { g2_set_inf(r); return; }
    fq2 t;
    fq2_conj(&t, &p->X);
    fq2_mul(&r->X, &PSI_CX_M, &t);
    fq2_conj(&t, &p->Y);
    fq2_mul(&r->Y, &PSI_CY_M, &t);
    fq2_conj(&r->Z, &p->Z);
    r->inf = 0;
}

/* Budroni-Pintore: [u^2-u-1]P + [u-1]psi(P) + psi^2(2P) — mirrors
 * bls381.clear_cofactor_g2's exact composition */
static void g2_clear_cofactor(g2j *r, const g2j *p) {
    g2j uP, u1P, t, tmp, psiP, two_p, psi2;
    g2_mul_u_signed(&uP, p);
    g2_neg(&tmp, p);
    g2_add(&u1P, &uP, &tmp);          /* [u-1]P */
    g2_mul_u_signed(&t, &u1P);
    g2_add(&t, &t, &tmp);             /* [u^2-u-1]P */
    g2_psi(&psiP, p);
    g2_mul_u_signed(&tmp, &psiP);
    g2_add(&t, &t, &tmp);
    g2_neg(&tmp, &psiP);
    g2_add(&t, &t, &tmp);             /* + [u-1]psi(P) */
    g2_dbl(&two_p, p);
    g2_psi(&psi2, &two_p);
    g2_psi(&psi2, &psi2);
    g2_add(r, &t, &psi2);
}

/* ------------------------------------------------------- hash-to-G2 ------ */

/* _hash_fq: int(sha256(tag+ctr+0+data) || sha256(tag+ctr+1+data)) mod Q,
 * returned in Montgomery form.  64-byte big-endian digest -> 8 LE limbs ->
 * canonical via REDC12 + two Montgomery muls. */
static void redc12(fq *r, const u64 *T12) {
    u64 T[13];
    memcpy(T, T12, 12 * sizeof(u64));
    T[12] = 0;
    for (int i = 0; i < 6; i++) {
        u64 m = T[i] * NPRIME;
        u64 carry = 0;
        for (int j = 0; j < 6; j++) {
            u128 t = (u128)m * QL[j] + T[i + j] + carry;
            T[i + j] = (u64)t;
            carry = (u64)(t >> 64);
        }
        for (int k = i + 6; carry; k++) {
            u128 t = (u128)T[k] + carry;
            T[k] = (u64)t;
            carry = (u64)(t >> 64);
        }
    }
    for (int i = 0; i < 6; i++) r->v[i] = T[i + 6];
    if (T[12] || fq_cmp_raw(r->v, QL) >= 0) fq_sub_raw(r->v, r->v, QL);
}

static void hash_fq(fq *out_mont, const char *tag, long taglen, uint32_t ctr,
                    const unsigned char *data, long len) {
    /* message = tag + ctr_be4 + plane_byte + data */
    unsigned char buf[4200];
    unsigned char digest[64];
    long off = 0;
    memcpy(buf + off, tag, (size_t)taglen);
    off += taglen;
    buf[off++] = (unsigned char)(ctr >> 24);
    buf[off++] = (unsigned char)(ctr >> 16);
    buf[off++] = (unsigned char)(ctr >> 8);
    buf[off++] = (unsigned char)ctr;
    long plane_off = off;
    off += 1;
    memcpy(buf + off, data, (size_t)len);
    off += len;
    for (int plane = 0; plane < 2; plane++) {
        buf[plane_off] = (unsigned char)plane;
        sha256(buf, off, digest + 32 * plane);
    }
    /* big-endian 64 bytes -> little-endian limbs v[8] */
    u64 v[12];
    memset(v, 0, sizeof v);
    for (int i = 0; i < 8; i++) {
        u64 limb = 0;
        for (int b = 0; b < 8; b++)
            limb = (limb << 8) | digest[(7 - i) * 8 + b];
        v[i] = limb;
    }
    fq t, r2m, canon;
    redc12(&t, v);                 /* d * R^-1 */
    memcpy(r2m.v, R2, sizeof R2);
    fq_mul(&canon, &t, &r2m);      /* d mod Q, canonical-as-raw */
    fq_mul(out_mont, &canon, &r2m); /* d * R: Montgomery form */
}

/* Returns 0 on success.  out: x.c0, x.c1, y.c0, y.c1 canonical LE limbs. */
int hashg2_one(const unsigned char *data, long len, u64 *out) {
    if (len < 0 || len > 4096) return -1;
    fq2 x, b2, y2, x3, y;
    fq four;
    for (uint32_t ctr = 0;; ctr++) {
        if (ctr > 1000) return -2; /* unreachable for honest SHA */
        hash_fq(&x.c0, "bls381-g2c0", 11, ctr, data, len);
        hash_fq(&x.c1, "bls381-g2c1", 11, ctr, data, len);
        /* y2 = x^3 + (4, 4) */
        fq_set_one(&four);
        fq_add(&four, &four, &four);
        fq_add(&four, &four, &four);
        b2.c0 = four;
        b2.c1 = four;
        fq2_sqr(&x3, &x);
        fq2_mul(&x3, &x3, &x);
        fq2_add(&y2, &x3, &b2);
        if (!fq2_sqrt(&y, &y2)) continue;
        /* deterministic sign: lexicographic min(y, -y) over canonical */
        fq2 ny;
        fq2_neg(&ny, &y);
        if (fq2_canon_cmp(&ny, &y) < 0) y = ny;
        g2j P, C;
        g2_from_affine(&P, &x, &y);
        g2_clear_cofactor(&C, &P);
        if (C.inf) continue;
        /* to affine + canonical output */
        fq2 zi, zi2, zi3, ax, ay;
        fq2_inv(&zi, &C.Z);
        fq2_sqr(&zi2, &zi);
        fq2_mul(&zi3, &zi2, &zi);
        fq2_mul(&ax, &C.X, &zi2);
        fq2_mul(&ay, &C.Y, &zi3);
        fq_to_canon(out + 0, &ax.c0);
        fq_to_canon(out + 6, &ax.c1);
        fq_to_canon(out + 12, &ay.c0);
        fq_to_canon(out + 18, &ay.c1);
        return 0;
    }
}
