/* Batched SHA-256 host kernel for the Merkle hot loop.
 *
 * The array engine validates O(N^3) Merkle proofs per epoch (SURVEY.md
 * par.3.2 marks the Echo verifies HOT at N=100); via hashlib each digest
 * costs ~1us of Python overhead regardless of openssl speed.  This kernel
 * runs whole proof batches per call: leaf hash -> path fold -> root
 * compare, entirely in C.  FIPS 180-4 SHA-256, written out from the spec;
 * a SHA-NI block function is used when the toolchain/CPU support it
 * (guarded by a loader self-test, scalar otherwise).
 *
 * Domain separation matches crypto/merkle.py: leaf = H(0x00||data),
 * node = H(0x01||left||right).
 */

#include <stdint.h>
#include <string.h>

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

#define ROR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256_block_scalar(uint32_t st[8], const uint8_t *p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
               ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ROR(w[i - 15], 7) ^ ROR(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = ROR(w[i - 2], 17) ^ ROR(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K[i] + w[i];
        uint32_t S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

#if defined(__SHA__) && defined(__x86_64__)
#include <immintrin.h>
static int g_use_ni = 1;

/* Standard SHA-NI block schedule (Intel's published instruction flow). */
static void sha256_block_ni(uint32_t st[8], const uint8_t *data) {
    const __m128i SHUF = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                        0x0405060700010203ULL);
    __m128i T = _mm_loadu_si128((const __m128i *)&st[0]); /* DCBA */
    __m128i S1 = _mm_loadu_si128((const __m128i *)&st[4]); /* HGFE */
    T = _mm_shuffle_epi32(T, 0xB1);        /* CDAB */
    S1 = _mm_shuffle_epi32(S1, 0x1B);      /* EFGH */
    __m128i S0 = _mm_alignr_epi8(T, S1, 8); /* ABEF */
    S1 = _mm_blend_epi16(S1, T, 0xF0);      /* CDGH */
    const __m128i AS = S0, CS = S1;

    __m128i M0 = _mm_shuffle_epi8(
        _mm_loadu_si128((const __m128i *)(data + 0)), SHUF);
    __m128i M1 = _mm_shuffle_epi8(
        _mm_loadu_si128((const __m128i *)(data + 16)), SHUF);
    __m128i M2 = _mm_shuffle_epi8(
        _mm_loadu_si128((const __m128i *)(data + 32)), SHUF);
    __m128i M3 = _mm_shuffle_epi8(
        _mm_loadu_si128((const __m128i *)(data + 48)), SHUF);
    __m128i MSG, TMP;

#define RND2(Mcur, kidx)                                                     \
    MSG = _mm_add_epi32(Mcur, _mm_loadu_si128((const __m128i *)&K[kidx]));   \
    S1 = _mm_sha256rnds2_epu32(S1, S0, MSG);                                 \
    MSG = _mm_shuffle_epi32(MSG, 0x0E);                                      \
    S0 = _mm_sha256rnds2_epu32(S0, S1, MSG);

#define SCHED(Mm1, Mcur, Mnext, Mprev)                                       \
    TMP = _mm_alignr_epi8(Mcur, Mm1, 4);                                     \
    Mnext = _mm_add_epi32(Mnext, TMP);                                       \
    Mnext = _mm_sha256msg2_epu32(Mnext, Mcur);                               \
    Mprev = _mm_sha256msg1_epu32(Mprev, Mcur);

    /* rounds 0-15 feed the schedule for 16-63 */
    RND2(M0, 0);
    RND2(M1, 4);  M0 = _mm_sha256msg1_epu32(M0, M1);
    RND2(M2, 8);  M1 = _mm_sha256msg1_epu32(M1, M2);
    RND2(M3, 12);
    SCHED(M2, M3, M0, M2);
    RND2(M0, 16);
    SCHED(M3, M0, M1, M3);
    RND2(M1, 20);
    SCHED(M0, M1, M2, M0);
    RND2(M2, 24);
    SCHED(M1, M2, M3, M1);
    RND2(M3, 28);
    SCHED(M2, M3, M0, M2);
    RND2(M0, 32);
    SCHED(M3, M0, M1, M3);
    RND2(M1, 36);
    SCHED(M0, M1, M2, M0);
    RND2(M2, 40);
    SCHED(M1, M2, M3, M1);
    RND2(M3, 44);
    SCHED(M2, M3, M0, M2);
    RND2(M0, 48);
    SCHED(M3, M0, M1, M3);
    RND2(M1, 52);
    SCHED(M0, M1, M2, M0);
    RND2(M2, 56);
    TMP = _mm_alignr_epi8(M2, M1, 4); /* final schedule: w60..63 */
    M3 = _mm_add_epi32(M3, TMP);
    M3 = _mm_sha256msg2_epu32(M3, M2);
    RND2(M3, 60);
#undef RND2
#undef SCHED

    S0 = _mm_add_epi32(S0, AS);
    S1 = _mm_add_epi32(S1, CS);
    T = _mm_shuffle_epi32(S0, 0x1B);       /* FEBA */
    S1 = _mm_shuffle_epi32(S1, 0xB1);      /* DCHG */
    S0 = _mm_blend_epi16(T, S1, 0xF0);     /* DCBA */
    S1 = _mm_alignr_epi8(S1, T, 8);        /* HGFE */
    _mm_storeu_si128((__m128i *)&st[0], S0);
    _mm_storeu_si128((__m128i *)&st[4], S1);
}

/* Two independent blocks interleaved: sha256rnds2 has multi-cycle
 * latency on a serial 32-deep dependency chain, so one stream leaves the
 * SHA unit half idle; two streams nearly double throughput. */
static void sha256_block_ni_x2(uint32_t sa[8], const uint8_t *da,
                               uint32_t sb[8], const uint8_t *db) {
    const __m128i SHUF = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                        0x0405060700010203ULL);
#define PREP(T, S1, S0, st)                                                  \
    T = _mm_loadu_si128((const __m128i *)&st[0]);                            \
    S1 = _mm_loadu_si128((const __m128i *)&st[4]);                           \
    T = _mm_shuffle_epi32(T, 0xB1);                                          \
    S1 = _mm_shuffle_epi32(S1, 0x1B);                                        \
    S0 = _mm_alignr_epi8(T, S1, 8);                                          \
    S1 = _mm_blend_epi16(S1, T, 0xF0);
    __m128i Ta, S1a, S0a, Tb, S1b, S0b;
    PREP(Ta, S1a, S0a, sa);
    PREP(Tb, S1b, S0b, sb);
#undef PREP
    const __m128i ASa = S0a, CSa = S1a, ASb = S0b, CSb = S1b;
#define LOAD(M, d, off)                                                      \
    M = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)((d) + (off))), SHUF);
    __m128i M0a, M1a, M2a, M3a, M0b, M1b, M2b, M3b, MSGa, MSGb, TMPa, TMPb;
    LOAD(M0a, da, 0)  LOAD(M1a, da, 16) LOAD(M2a, da, 32) LOAD(M3a, da, 48)
    LOAD(M0b, db, 0)  LOAD(M1b, db, 16) LOAD(M2b, db, 32) LOAD(M3b, db, 48)
#undef LOAD

#define RND2X(Ma, Mb, kidx)                                                  \
    MSGa = _mm_add_epi32(Ma, _mm_loadu_si128((const __m128i *)&K[kidx]));    \
    MSGb = _mm_add_epi32(Mb, _mm_loadu_si128((const __m128i *)&K[kidx]));    \
    S1a = _mm_sha256rnds2_epu32(S1a, S0a, MSGa);                             \
    S1b = _mm_sha256rnds2_epu32(S1b, S0b, MSGb);                             \
    MSGa = _mm_shuffle_epi32(MSGa, 0x0E);                                    \
    MSGb = _mm_shuffle_epi32(MSGb, 0x0E);                                    \
    S0a = _mm_sha256rnds2_epu32(S0a, S1a, MSGa);                             \
    S0b = _mm_sha256rnds2_epu32(S0b, S1b, MSGb);

#define SCHEDX(m1a, ca, na, pa, m1b, cb, nb, pb)                             \
    TMPa = _mm_alignr_epi8(ca, m1a, 4);                                      \
    TMPb = _mm_alignr_epi8(cb, m1b, 4);                                      \
    na = _mm_add_epi32(na, TMPa);                                            \
    nb = _mm_add_epi32(nb, TMPb);                                            \
    na = _mm_sha256msg2_epu32(na, ca);                                       \
    nb = _mm_sha256msg2_epu32(nb, cb);                                       \
    pa = _mm_sha256msg1_epu32(pa, ca);                                       \
    pb = _mm_sha256msg1_epu32(pb, cb);

    RND2X(M0a, M0b, 0);
    RND2X(M1a, M1b, 4);
    M0a = _mm_sha256msg1_epu32(M0a, M1a);
    M0b = _mm_sha256msg1_epu32(M0b, M1b);
    RND2X(M2a, M2b, 8);
    M1a = _mm_sha256msg1_epu32(M1a, M2a);
    M1b = _mm_sha256msg1_epu32(M1b, M2b);
    RND2X(M3a, M3b, 12);
    SCHEDX(M2a, M3a, M0a, M2a, M2b, M3b, M0b, M2b);
    RND2X(M0a, M0b, 16);
    SCHEDX(M3a, M0a, M1a, M3a, M3b, M0b, M1b, M3b);
    RND2X(M1a, M1b, 20);
    SCHEDX(M0a, M1a, M2a, M0a, M0b, M1b, M2b, M0b);
    RND2X(M2a, M2b, 24);
    SCHEDX(M1a, M2a, M3a, M1a, M1b, M2b, M3b, M1b);
    RND2X(M3a, M3b, 28);
    SCHEDX(M2a, M3a, M0a, M2a, M2b, M3b, M0b, M2b);
    RND2X(M0a, M0b, 32);
    SCHEDX(M3a, M0a, M1a, M3a, M3b, M0b, M1b, M3b);
    RND2X(M1a, M1b, 36);
    SCHEDX(M0a, M1a, M2a, M0a, M0b, M1b, M2b, M0b);
    RND2X(M2a, M2b, 40);
    SCHEDX(M1a, M2a, M3a, M1a, M1b, M2b, M3b, M1b);
    RND2X(M3a, M3b, 44);
    SCHEDX(M2a, M3a, M0a, M2a, M2b, M3b, M0b, M2b);
    RND2X(M0a, M0b, 48);
    SCHEDX(M3a, M0a, M1a, M3a, M3b, M0b, M1b, M3b);
    RND2X(M1a, M1b, 52);
    SCHEDX(M0a, M1a, M2a, M0a, M0b, M1b, M2b, M0b);
    RND2X(M2a, M2b, 56);
    TMPa = _mm_alignr_epi8(M2a, M1a, 4);
    TMPb = _mm_alignr_epi8(M2b, M1b, 4);
    M3a = _mm_add_epi32(M3a, TMPa);
    M3b = _mm_add_epi32(M3b, TMPb);
    M3a = _mm_sha256msg2_epu32(M3a, M2a);
    M3b = _mm_sha256msg2_epu32(M3b, M2b);
    RND2X(M3a, M3b, 60);
#undef RND2X
#undef SCHEDX

#define FIN(S0, S1, T, AS, CS, st)                                           \
    S0 = _mm_add_epi32(S0, AS);                                              \
    S1 = _mm_add_epi32(S1, CS);                                              \
    T = _mm_shuffle_epi32(S0, 0x1B);                                         \
    S1 = _mm_shuffle_epi32(S1, 0xB1);                                        \
    S0 = _mm_blend_epi16(T, S1, 0xF0);                                       \
    S1 = _mm_alignr_epi8(S1, T, 8);                                          \
    _mm_storeu_si128((__m128i *)&st[0], S0);                                 \
    _mm_storeu_si128((__m128i *)&st[4], S1);
    FIN(S0a, S1a, Ta, ASa, CSa, sa);
    FIN(S0b, S1b, Tb, ASb, CSb, sb);
#undef FIN
}

static void sha256_block(uint32_t st[8], const uint8_t *p) {
    if (g_use_ni)
        sha256_block_ni(st, p);
    else
        sha256_block_scalar(st, p);
}
static void sha256_block_x2(uint32_t sa[8], const uint8_t *pa, uint32_t sb[8],
                            const uint8_t *pb) {
    if (g_use_ni) {
        sha256_block_ni_x2(sa, pa, sb, pb);
    } else {
        sha256_block_scalar(sa, pa);
        sha256_block_scalar(sb, pb);
    }
}
void sha256_disable_ni(void) { g_use_ni = 0; }
#else
static void sha256_block(uint32_t st[8], const uint8_t *p) {
    sha256_block_scalar(st, p);
}
static void sha256_block_x2(uint32_t sa[8], const uint8_t *pa, uint32_t sb[8],
                            const uint8_t *pb) {
    sha256_block_scalar(sa, pa);
    sha256_block_scalar(sb, pb);
}
void sha256_disable_ni(void) {}
#endif

static void sha256(const uint8_t *msg, long len, uint8_t out[32]) {
    uint32_t st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                      0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    long i = 0;
    for (; i + 64 <= len; i += 64)
        sha256_block(st, msg + i);
    uint8_t tail[128];
    long rem = len - i;
    memcpy(tail, msg + i, rem);
    tail[rem] = 0x80;
    long tl = (rem + 9 <= 64) ? 64 : 128;
    memset(tail + rem + 1, 0, tl - rem - 9);
    uint64_t bits = (uint64_t)len * 8;
    for (int b = 0; b < 8; b++)
        tail[tl - 1 - b] = (uint8_t)(bits >> (8 * b));
    for (long o = 0; o < tl; o += 64)
        sha256_block(st, tail + o);
    for (int w = 0; w < 8; w++) {
        out[4 * w] = (uint8_t)(st[w] >> 24);
        out[4 * w + 1] = (uint8_t)(st[w] >> 16);
        out[4 * w + 2] = (uint8_t)(st[w] >> 8);
        out[4 * w + 3] = (uint8_t)st[w];
    }
}

/* Two equal-length messages hashed in lockstep (dual NI streams). */
static void sha256_x2(const uint8_t *ma, const uint8_t *mb, long len,
                      uint8_t oa[32], uint8_t ob[32]) {
    uint32_t sa[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                      0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    uint32_t sb[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                      0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    long i = 0;
    for (; i + 64 <= len; i += 64)
        sha256_block_x2(sa, ma + i, sb, mb + i);
    uint8_t ta[128], tb[128];
    long rem = len - i;
    memcpy(ta, ma + i, rem);
    memcpy(tb, mb + i, rem);
    ta[rem] = 0x80;
    tb[rem] = 0x80;
    long tl = (rem + 9 <= 64) ? 64 : 128;
    memset(ta + rem + 1, 0, tl - rem - 9);
    memset(tb + rem + 1, 0, tl - rem - 9);
    uint64_t bits = (uint64_t)len * 8;
    for (int b = 0; b < 8; b++) {
        ta[tl - 1 - b] = (uint8_t)(bits >> (8 * b));
        tb[tl - 1 - b] = (uint8_t)(bits >> (8 * b));
    }
    for (long o = 0; o < tl; o += 64)
        sha256_block_x2(sa, ta + o, sb, tb + o);
    for (int w = 0; w < 8; w++) {
        oa[4 * w] = (uint8_t)(sa[w] >> 24);
        oa[4 * w + 1] = (uint8_t)(sa[w] >> 16);
        oa[4 * w + 2] = (uint8_t)(sa[w] >> 8);
        oa[4 * w + 3] = (uint8_t)sa[w];
        ob[4 * w] = (uint8_t)(sb[w] >> 24);
        ob[4 * w + 1] = (uint8_t)(sb[w] >> 16);
        ob[4 * w + 2] = (uint8_t)(sb[w] >> 8);
        ob[4 * w + 3] = (uint8_t)sb[w];
    }
}

/* Batched plain hashing: n fixed-length items -> 32-byte digests. */
void sha256_batch(const uint8_t *data, long n, long item_len, uint8_t *out) {
    long i = 0;
    for (; i + 2 <= n; i += 2)
        sha256_x2(data + i * item_len, data + (i + 1) * item_len, item_len,
                  out + 32 * i, out + 32 * (i + 1));
    if (i < n)
        sha256(data + i * item_len, item_len, out + 32 * i);
}

static void h_leaf(const uint8_t *val, long len, uint8_t out[32]) {
    uint8_t buf[4096];
    if (len + 1 <= (long)sizeof(buf)) {
        buf[0] = 0x00;
        memcpy(buf + 1, val, len);
        sha256(buf, len + 1, out);
    } else {
        /* oversized leaf: hash in two passes is NOT equivalent; callers
         * keep shards < 4095 bytes (enforced Python-side). */
        sha256(val, len, out); /* unreachable by contract */
    }
}

static void h_node(const uint8_t l[32], const uint8_t r[32], uint8_t out[32]) {
    uint8_t buf[65];
    buf[0] = 0x01;
    memcpy(buf + 1, l, 32);
    memcpy(buf + 33, r, 32);
    sha256(buf, 65, out);
}

static void h_leaf_x2(const uint8_t *va, const uint8_t *vb, long len,
                      uint8_t oa[32], uint8_t ob[32]) {
    uint8_t ba[4096], bb[4096];
    if (len + 1 > 4096)
        return; /* out of contract (enforced Python-side) */
    ba[0] = 0x00;
    bb[0] = 0x00;
    memcpy(ba + 1, va, len);
    memcpy(bb + 1, vb, len);
    sha256_x2(ba, bb, len + 1, oa, ob);
}

static void h_node_x2(const uint8_t la[32], const uint8_t ra[32],
                      const uint8_t lb[32], const uint8_t rb[32],
                      uint8_t oa[32], uint8_t ob[32]) {
    uint8_t ba[65], bb[65];
    ba[0] = 0x01;
    bb[0] = 0x01;
    memcpy(ba + 1, la, 32);
    memcpy(ba + 33, ra, 32);
    memcpy(bb + 1, lb, 32);
    memcpy(bb + 33, rb, 32);
    sha256_x2(ba, bb, 65, oa, ob);
}

/* Validate n proofs, each `reps` times (N receivers re-check the same
 * echo; repetition keeps measured work honest).  Layout:
 *   leaf_vals: (n, leaf_len)   paths: (n, depth, 32)
 *   indices:   (n,) int32      roots: (n, 32)      ok_out: (n,) uint8  */
void merkle_validate_batch(const uint8_t *leaf_vals, long leaf_len,
                           const uint8_t *paths, const int32_t *indices,
                           const uint8_t *roots, long n, long depth,
                           long reps, uint8_t *ok_out) {
    uint8_t acc[32], acc2[32];
    /* adjacent items run as dual NI streams; the reps loop (N receivers
     * re-checking the same proof) stays outermost so the work is honest */
    long i = 0;
    for (; i + 2 <= n; i += 2) {
        uint8_t ok = 0, ok2 = 0;
        for (long r = 0; r < reps; r++) {
            h_leaf_x2(leaf_vals + i * leaf_len,
                      leaf_vals + (i + 1) * leaf_len, leaf_len, acc, acc2);
            int32_t idx = indices[i], idx2 = indices[i + 1];
            for (long d = 0; d < depth; d++) {
                const uint8_t *sib = paths + (i * depth + d) * 32;
                const uint8_t *sib2 = paths + ((i + 1) * depth + d) * 32;
                h_node_x2((idx & 1) ? sib : acc, (idx & 1) ? acc : sib,
                          (idx2 & 1) ? sib2 : acc2, (idx2 & 1) ? acc2 : sib2,
                          acc, acc2);
                idx >>= 1;
                idx2 >>= 1;
            }
            ok = memcmp(acc, roots + 32 * i, 32) == 0;
            ok2 = memcmp(acc2, roots + 32 * (i + 1), 32) == 0;
        }
        ok_out[i] = ok;
        ok_out[i + 1] = ok2;
    }
    for (; i < n; i++) {
        uint8_t ok = 0;
        for (long r = 0; r < reps; r++) {
            h_leaf(leaf_vals + i * leaf_len, leaf_len, acc);
            int32_t idx = indices[i];
            for (long d = 0; d < depth; d++) {
                const uint8_t *sib = paths + (i * depth + d) * 32;
                if (idx & 1)
                    h_node(sib, acc, acc);
                else
                    h_node(acc, sib, acc);
                idx >>= 1;
            }
            ok = memcmp(acc, roots + 32 * i, 32) == 0;
        }
        ok_out[i] = ok;
    }
}

/* Batched tree roots: t trees of n_leaves fixed-length leaves, padded to
 * size (a power of two) with H(0x00) empty leaves; each built `reps`
 * times.  leaves: (t, n_leaves, leaf_len)  roots_out: (t, 32). */
void merkle_root_batch(const uint8_t *leaves, long t, long n_leaves,
                       long leaf_len, long size, long reps,
                       uint8_t *roots_out) {
    uint8_t level[256 * 32]; /* size <= 256 leaves per tree */
    uint8_t empty[32];
    uint8_t zero = 0x00;
    sha256(&zero, 1, empty);
    if (size > 256)
        return;
    for (long ti = 0; ti < t; ti++) {
        for (long r = 0; r < reps; r++) {
            const uint8_t *base = leaves + ti * n_leaves * leaf_len;
            long i = 0;
            for (; i + 2 <= n_leaves; i += 2)
                h_leaf_x2(base + i * leaf_len, base + (i + 1) * leaf_len,
                          leaf_len, level + 32 * i, level + 32 * (i + 1));
            for (; i < n_leaves; i++)
                h_leaf(base + i * leaf_len, leaf_len, level + 32 * i);
            for (i = n_leaves; i < size; i++)
                memcpy(level + 32 * i, empty, 32);
            for (long w = size; w > 1; w /= 2) {
                long half = w / 2;
                for (i = 0; i + 2 <= half; i += 2)
                    h_node_x2(level + 64 * i, level + 64 * i + 32,
                              level + 64 * (i + 1), level + 64 * (i + 1) + 32,
                              level + 32 * i, level + 32 * (i + 1));
                for (; i < half; i++)
                    h_node(level + 64 * i, level + 64 * i + 32,
                           level + 32 * i);
            }
        }
        memcpy(roots_out + 32 * ti, level, 32);
    }
}
