"""Checkpoint/resume: serialize a live protocol node, restore it elsewhere.

The reference keeps every protocol state type serde-serializable so an
embedder can persist a node and resume it (SURVEY.md §5 checkpoint row:
"all message/state types are serde-serializable, so embedders can
persist"; `JoinPlan` § is the built-in era snapshot).  This module is that
capability for the whole stack: :func:`save_node` walks a protocol
instance's object graph down to primitives and crypto elements and emits
canonical bytes (utils/canonical.py — the same no-code-exec discipline as
the wire layer, NOT pickle); :func:`load_node` rebuilds an equivalent
instance that continues the protocol deterministically.

Scope and semantics:

* **Quiescent points only.** Deferred :class:`~hbbft_tpu.core.types.
  CryptoWork` items carry result callbacks (closures) and live in Steps,
  never in protocol instance state — so a node is snapshotable whenever no
  Step of its own is outstanding, i.e. between cranks once the round's
  crypto barrier has resolved.  :func:`save_node` refuses objects holding
  callables anywhere in their state, turning a violated assumption into an
  immediate error instead of a silently-wrong checkpoint.
* **The crypto backend is environment, not state.** Backends (device
  handles, compiled-kernel caches) and their stateless ``Group`` objects
  are encoded as placeholders; :func:`load_node` re-attaches the backend
  the caller provides.  Everything consensus-visible — key material,
  counters, RNG state, buffered messages, per-instance sub-protocol
  state — rides in the snapshot.
* **Shared mutable state stays shared.** The encoder memoizes every
  non-primitive node: the single ``random.Random`` the builders thread
  through all layers (SURVEY.md §4 determinism requirement) is serialized
  once and re-shared on restore, so a restored node's future coin flips and
  transaction samples match the original's exactly.  Cycles are handled
  the same way.

Security note: decoding instantiates only classes from the fixed registry
below (framework state types), sets attributes by name, and never executes
embedded code — malformed input raises :class:`SnapshotError`.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from hbbft_tpu.utils import canonical


class SnapshotError(ValueError):
    """Malformed snapshot bytes or unsnapshotable state."""


# ---------------------------------------------------------------------------
# Class registry: every type allowed to appear in a snapshot.  Collected by
# module so new state dataclasses register automatically; decode rejects
# anything else.
# ---------------------------------------------------------------------------

_STATE_MODULES = (
    "hbbft_tpu.core.types",
    "hbbft_tpu.core.fault_log",
    "hbbft_tpu.core.network_info",
    "hbbft_tpu.crypto.keys",
    "hbbft_tpu.crypto.poly",
    "hbbft_tpu.crypto.merkle",
    "hbbft_tpu.crypto.erasure",
    "hbbft_tpu.protocols.bool_set",
    "hbbft_tpu.protocols.broadcast",
    "hbbft_tpu.protocols.sbv_broadcast",
    "hbbft_tpu.protocols.binary_agreement",
    "hbbft_tpu.protocols.threshold_sign",
    "hbbft_tpu.protocols.threshold_decrypt",
    "hbbft_tpu.protocols.subset",
    "hbbft_tpu.protocols.honey_badger",
    "hbbft_tpu.protocols.change",
    "hbbft_tpu.protocols.votes",
    "hbbft_tpu.protocols.sync_key_gen",
    "hbbft_tpu.protocols.dynamic_honey_badger",
    "hbbft_tpu.protocols.transaction_queue",
    "hbbft_tpu.engine.array_engine",
    "hbbft_tpu.protocols.queueing_honey_badger",
    "hbbft_tpu.protocols.sender_queue",
    "hbbft_tpu.utils.metrics",
    # Whole-network checkpoint: VirtualNet + Node + NetMessage + adversaries,
    # so an entire simulation (nodes, in-flight queue, shared RNG) resumes
    # deterministically from bytes.
    "hbbft_tpu.net.virtual_net",
    "hbbft_tpu.net.adversary",
    # crash axis: schedules, per-node tracks (checkpoint blobs, WALs,
    # parked traffic) — a whole-net snapshot taken mid-outage resumes
    # with the outage intact
    "hbbft_tpu.net.crash",
    # control plane: the SLO spec, the adaptive batch controller (its B
    # trace, hysteresis counters, and rng are replay state), and load
    # traces — a soak resumed mid-run continues the same control law
    "hbbft_tpu.control.slo",
    "hbbft_tpu.control.controller",
    "hbbft_tpu.control.trace",
)

_registry_cache: Optional[Dict[str, type]] = None


def _registry() -> Dict[str, type]:
    global _registry_cache
    if _registry_cache is None:
        import importlib
        import inspect

        reg: Dict[str, type] = {}
        for modname in _STATE_MODULES:
            mod = importlib.import_module(modname)
            for name, cls in inspect.getmembers(mod, inspect.isclass):
                if cls.__module__ != modname:
                    continue  # re-export, owned elsewhere
                reg[f"{modname}:{name}"] = cls
        _registry_cache = reg
    return _registry_cache


def _class_tag(cls: type) -> str:
    return f"{cls.__module__}:{cls.__qualname__}"


def _state_attrs(obj: Any) -> List[Tuple[str, Any]]:
    """All instance attributes, whether slot- or dict-backed (or both).

    Walks the full ``__slots__`` chain (bases like ``typing.Generic``
    contribute none — that must not force the dict path) and merges any
    instance ``__dict__`` on top, so hybrid classes (slotted dataclass over
    a dict-backed base) serialize completely.  Sorted for determinism.

    Attributes named in the class's ``_SNAPSHOT_ENV_ATTRS`` are dropped:
    they hold environment (live observer callables, attached drivers —
    e.g. QueueingHoneyBadger.sample_listener, VirtualNet.traffic), not
    consensus state, and restore falls back to the class default exactly
    like the backend/tracer contract.
    """
    env = getattr(type(obj), "_SNAPSHOT_ENV_ATTRS", ())
    attrs: Dict[str, Any] = {}
    for c in reversed(type(obj).__mro__):
        s = c.__dict__.get("__slots__")
        if not s:
            continue
        for name in [s] if isinstance(s, str) else s:
            if name in ("__dict__", "__weakref__"):
                continue
            if hasattr(obj, name):
                attrs[name] = getattr(obj, name)
    attrs.update(getattr(obj, "__dict__", None) or {})
    return sorted((n, v) for n, v in attrs.items() if n not in env)


# ---------------------------------------------------------------------------
# Encoding.  Every tree node is a (tag, ...) tuple over canonical.py's
# primitive types.  Mutable/shareable nodes get a memo index on first
# encounter; later encounters encode as ("r", idx).
# ---------------------------------------------------------------------------

_PRIMITIVES = (bool, int, bytes, str, type(None))


class _Encoder:
    def __init__(self) -> None:
        self.memo: Dict[int, int] = {}
        self.next_idx = 0

    def _memoize(self, obj: Any) -> int:
        idx = self.next_idx
        self.next_idx += 1
        self.memo[id(obj)] = idx
        return idx

    def encode(self, obj: Any) -> Any:
        if isinstance(obj, bool) or obj is None:
            return ("p", obj)
        if isinstance(obj, int) or isinstance(obj, (bytes, str)):
            return ("p", obj)
        if isinstance(obj, bytearray):
            return ("ba", bytes(obj))
        if isinstance(obj, float):
            # Exact round-trip via IEEE bits (canonical has no float type).
            import struct

            return ("f", struct.pack(">d", obj))

        prior = self.memo.get(id(obj))
        if prior is not None:
            return ("r", prior)

        # -- environment leaves ------------------------------------------
        from hbbft_tpu.crypto.backend import CryptoBackend
        from hbbft_tpu.crypto.group import Group

        if isinstance(obj, CryptoBackend):
            return ("backend", self._memoize(obj))
        if isinstance(obj, Group):
            return ("group", self._memoize(obj))
        if isinstance(obj, random.Random):
            idx = self._memoize(obj)
            version, state, gauss = obj.getstate()
            return ("rng", idx, version, list(state), self.encode(gauss))
        if isinstance(obj, np.ndarray):
            idx = self._memoize(obj)
            if obj.dtype.hasobject:
                raise SnapshotError("object-dtype ndarray in state")
            return ("nd", idx, obj.dtype.str, list(obj.shape), obj.tobytes())

        # -- containers ---------------------------------------------------
        if isinstance(obj, list):
            idx = self._memoize(obj)
            return ("l", idx, [self.encode(x) for x in obj])
        if isinstance(obj, dict):
            idx = self._memoize(obj)
            return (
                "d",
                idx,
                [(self.encode(k), self.encode(v)) for k, v in obj.items()],
            )
        if isinstance(obj, tuple):
            # Immutable: no memo (cycles through tuples are impossible to
            # build in protocol code; sharing need not be preserved).
            return ("t", [self.encode(x) for x in obj])
        if isinstance(obj, (set, frozenset)):
            idx = self._memoize(obj)
            # Sort members BEFORE real encoding, each with a throwaway
            # encoder: sorting real encodings would reorder memo
            # definitions after the ("r", idx) references to them, making
            # the snapshot undecodable (dangling refs on restore).
            members = sorted(
                obj, key=lambda x: canonical.encode(_Encoder().encode(x))
            )
            items = [self.encode(x) for x in members]
            return ("s" if isinstance(obj, set) else "fs", idx, items)

        # -- module-level functions from registered modules ----------------
        # (e.g. SenderQueue's msg_epoch_fn default): encoded BY NAME and
        # re-looked-up on decode — never deserialized code.  Closures and
        # lambdas have no stable name and are rejected below.
        import types as _types

        if isinstance(obj, _types.FunctionType):
            mod = getattr(obj, "__module__", None)
            qn = getattr(obj, "__qualname__", "")
            if mod in _STATE_MODULES and "<" not in qn and "." not in qn:
                import importlib

                if getattr(importlib.import_module(mod), qn, None) is obj:
                    return ("fn", mod, qn)

        # -- registered framework objects ---------------------------------
        tag = _class_tag(type(obj))
        if tag not in _registry():
            if callable(obj):
                raise SnapshotError(
                    f"callable in state ({obj!r}): snapshot only at "
                    "quiescent points (no outstanding CryptoWork)"
                )
            raise SnapshotError(f"unregistered state class {tag}")
        idx = self._memoize(obj)
        return (
            "o",
            idx,
            tag,
            [(name, self.encode(val)) for name, val in _state_attrs(obj)],
        )


class _Decoder:
    def __init__(self, backend) -> None:
        self.backend = backend
        self.objects: Dict[int, Any] = {}

    def decode(self, node: Any) -> Any:
        if not isinstance(node, tuple) or not node:
            raise SnapshotError(f"bad node {node!r}")
        tag = node[0]
        if tag == "p":
            return node[1]
        if tag == "ba":
            return bytearray(node[1])
        if tag == "f":
            import struct

            return struct.unpack(">d", node[1])[0]
        if tag == "r":
            try:
                return self.objects[node[1]]
            except KeyError:
                raise SnapshotError(f"dangling ref {node[1]}")
        if tag == "backend":
            self.objects[node[1]] = self.backend
            return self.backend
        if tag == "group":
            self.objects[node[1]] = self.backend.group
            return self.backend.group
        if tag == "rng":
            _, idx, version, state, gauss = node
            # lint: allow[replay-purity] not an entropy draw: the fresh
            # Random is a shell whose state is overwritten on the next
            # line by the checkpoint-logged (version, state, gauss) tuple
            r = random.Random()
            r.setstate((version, tuple(state), self.decode(gauss)))
            self.objects[idx] = r
            return r
        if tag == "nd":
            _, idx, dtype, shape, raw = node
            arr = np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape).copy()
            self.objects[idx] = arr
            return arr
        if tag == "l":
            _, idx, items = node
            out: List[Any] = []
            self.objects[idx] = out
            out.extend(self.decode(x) for x in items)
            return out
        if tag == "d":
            _, idx, items = node
            d: Dict[Any, Any] = {}
            self.objects[idx] = d
            for k, v in items:
                d[self.decode(k)] = self.decode(v)
            return d
        if tag == "t":
            return tuple(self.decode(x) for x in node[1])
        if tag in ("s", "fs"):
            _, idx, items = node
            if tag == "s":
                s: Any = set()
                self.objects[idx] = s
                s.update(self.decode(x) for x in items)
                return s
            fs = frozenset(self.decode(x) for x in items)
            self.objects[idx] = fs
            return fs
        if tag == "fn":
            _, mod, qn = node
            if mod not in _STATE_MODULES or "." in qn or "<" in qn:
                raise SnapshotError(f"function outside registry: {mod}:{qn}")
            import importlib
            import types as _types

            fn = getattr(importlib.import_module(mod), qn, None)
            if not isinstance(fn, _types.FunctionType):
                raise SnapshotError(f"unknown function {mod}:{qn}")
            return fn
        if tag == "o":
            _, idx, clstag, attrs = node
            cls = _registry().get(clstag)
            if cls is None:
                raise SnapshotError(f"unknown class {clstag!r}")
            obj = cls.__new__(cls)
            self.objects[idx] = obj
            for name, val in attrs:
                if not isinstance(name, str):
                    raise SnapshotError("non-str attribute name")
                # object.__setattr__: works for frozen dataclasses too.
                object.__setattr__(obj, name, self.decode(val))
            return obj
        raise SnapshotError(f"unknown tag {tag!r}")


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

_MAGIC = b"HBTPUSNAP1"


def save_node(algo: Any) -> bytes:
    """Serialize a protocol instance (any layer: RBC/BA/…/QHB or a
    SenderQueue-wrapped stack) to canonical snapshot bytes."""
    tree = _Encoder().encode(algo)
    return _MAGIC + canonical.encode(tree)


def load_node(data: bytes, backend) -> Any:
    """Rebuild a protocol instance from :func:`save_node` bytes.

    ``backend`` supplies the crypto environment (device handles are not
    state); it must be protocol-compatible with the one used at save time
    (same group semantics — e.g. both BLS12-381, or both mock).
    """
    if not data.startswith(_MAGIC):
        raise SnapshotError("bad magic")
    # Every decode failure surfaces as SnapshotError (the module contract):
    # truncated/corrupted bytes otherwise raise TypeError/ValueError/
    # struct.error from canonical parsing, rng setstate, ndarray reshape…
    try:
        tree = canonical.decode(data[len(_MAGIC) :])
        return _Decoder(backend).decode(tree)
    except SnapshotError:
        raise
    except Exception as e:
        raise SnapshotError(f"malformed snapshot: {e!r}") from e
