"""Shared JAX configuration helpers."""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point JAX's persistent compilation cache at the repo-local dir.

    The pairing graphs take tens of seconds (CPU: minutes pre-stacking) to
    compile; the cache makes every subsequent process — tests, bench, the
    driver's graft checks — reuse compiled modules.  Safe to call multiple
    times or before/after other jax.config updates.
    """
    import jax

    if cache_dir is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        cache_dir = os.path.join(repo, ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs — cache is an optimization only
