"""Shared JAX configuration helpers."""

from __future__ import annotations

import hashlib
import os
import platform


def _host_tag() -> str:
    """CPU-feature fingerprint for the cache directory name.

    XLA:CPU persists AOT executables whose cache key does NOT include the
    host's CPU features; loading an entry compiled on a machine with a
    different feature set SIGILLs/SIGSEGVs inside
    ``compilation_cache.get_executable_and_time`` (observed when this
    sandbox migrated hosts mid-session).  Same defense as
    hbbft_tpu/native's .so cache naming.
    """
    feat = ""
    try:
        with open("/proc/cpuinfo") as f:
            feat = next(
                (ln for ln in f if ln.startswith(("flags", "Features"))), ""
            )
    except OSError:
        pass
    digest = hashlib.sha256((platform.machine() + feat).encode()).hexdigest()
    return f"{platform.machine()}-{digest[:12]}"


def raise_stack_limit(soft_bytes: int = 512 << 20) -> None:
    """Raise RLIMIT_STACK's soft limit for XLA:CPU compilation.

    XLA:CPU runs deeply recursive LLVM passes on the CALLING thread; on
    the ~8 MB default main-thread stack the RLC verification graphs
    (vmapped ladders + two Miller loops + final exp in one jit) segfault
    nondeterministically inside backend_compile_and_load — observed five
    times on 2026-07-30, always in an RLC-graph compile, including a
    fully solo pytest run.  The Linux main-thread stack grows on demand
    up to the soft limit, so raising it in-process (before the compile)
    is sufficient; spawned threads are unaffected (their stacks are
    fixed at creation), matching the observed main-thread crash site.
    """
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        want = soft_bytes if hard == resource.RLIM_INFINITY else min(
            soft_bytes, hard
        )
        if soft != resource.RLIM_INFINITY and soft < want:
            resource.setrlimit(resource.RLIMIT_STACK, (want, hard))
    except Exception:
        pass  # best effort — platform without resource or denied


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point JAX's persistent compilation cache at a repo-local,
    host-fingerprinted dir.

    The pairing graphs take tens of seconds (CPU: minutes pre-stacking) to
    compile; the cache makes every subsequent process — tests, bench, the
    driver's graft checks — reuse compiled modules.  Safe to call multiple
    times or before/after other jax.config updates.
    """
    import jax

    # XLA:CPU persists AOT executables whose recorded target features
    # include tuning pseudo-features (+prefer-no-scatter/-gather) that
    # fail the loader's host-compatibility check EVEN ON THE SAME HOST —
    # observed as warn-then-SIGILL or a hard abort inside
    # compilation_cache.get_executable_and_time (two pytest runs died
    # there 2026-07-30).  The cache is therefore TPU-only; CPU runs
    # (tests, the dryrun child) always compile fresh.  Override with
    # HBBFT_TPU_FORCE_CPU_CACHE=1 for local experiments.
    # Key off the PRIMARY platform: the ambient TPU session registers
    # "axon,cpu" (cpu as fallback) and must keep the cache; a forced-CPU
    # child ("cpu") must not.  An EMPTY string (auto-detection) keeps the
    # cache: every CPU-forced context in this project sets the platform
    # explicitly (conftest, dryrun child, bench re-exec), and probing
    # jax.default_backend() here would initialize — and possibly hang on a
    # dead tunnel — the backend at import time.
    plats = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    primary = plats.split(",")[0].strip().lower()
    if primary not in ("tpu", "axon", "") and not os.environ.get(
        "HBBFT_TPU_FORCE_CPU_CACHE"
    ):
        return

    if cache_dir is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        cache_dir = os.path.join(repo, f".jax_cache.{_host_tag()}")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs — cache is an optimization only
