"""Shared JAX configuration helpers."""

from __future__ import annotations

import hashlib
import os
import platform


def _host_tag() -> str:
    """CPU-feature fingerprint for the cache directory name.

    XLA:CPU persists AOT executables whose cache key does NOT include the
    host's CPU features; loading an entry compiled on a machine with a
    different feature set SIGILLs/SIGSEGVs inside
    ``compilation_cache.get_executable_and_time`` (observed when this
    sandbox migrated hosts mid-session).  Same defense as
    hbbft_tpu/native's .so cache naming.
    """
    feat = ""
    try:
        with open("/proc/cpuinfo") as f:
            feat = next(
                (ln for ln in f if ln.startswith(("flags", "Features"))), ""
            )
    except OSError:
        pass
    digest = hashlib.sha256((platform.machine() + feat).encode()).hexdigest()
    return f"{platform.machine()}-{digest[:12]}"


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point JAX's persistent compilation cache at a repo-local,
    host-fingerprinted dir.

    The pairing graphs take tens of seconds (CPU: minutes pre-stacking) to
    compile; the cache makes every subsequent process — tests, bench, the
    driver's graft checks — reuse compiled modules.  Safe to call multiple
    times or before/after other jax.config updates.
    """
    import jax

    if cache_dir is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        cache_dir = os.path.join(repo, f".jax_cache.{_host_tag()}")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs — cache is an optimization only
