"""Canonical deterministic serialization (the `bincode` analogue).

The reference serializes every signed/encrypted structure with
`bincode`+serde (SURVEY.md §2.2), which is canonical and deterministic —
a requirement for signatures to verify across nodes.  Python's pickle is
neither, so this module defines a tiny self-describing tag-length-value
encoding over the primitive tree types protocols actually sign/encrypt:
``None, bool, int, bytes, str, list, tuple, dict``.

Dicts are serialized with keys sorted by their own encoding, making the
output independent of insertion order.  Ints are arbitrary-precision,
zig-zag-free (sign byte + magnitude).
"""

from __future__ import annotations

from typing import Any

_T_NONE = b"\x00"
_T_FALSE = b"\x01"
_T_TRUE = b"\x02"
_T_INT = b"\x03"
_T_BYTES = b"\x04"
_T_STR = b"\x05"
_T_LIST = b"\x06"
_T_TUPLE = b"\x07"
_T_DICT = b"\x08"


def _len_prefix(n: int) -> bytes:
    return n.to_bytes(4, "big")


def encode(obj: Any) -> bytes:
    """Canonically encode a primitive tree."""
    if obj is None:
        return _T_NONE
    if obj is False:
        return _T_FALSE
    if obj is True:
        return _T_TRUE
    if isinstance(obj, int):
        neg = obj < 0
        mag = (-obj if neg else obj).to_bytes((abs(obj).bit_length() + 7) // 8 or 1, "big")
        return _T_INT + (b"\x01" if neg else b"\x00") + _len_prefix(len(mag)) + mag
    if isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        return _T_BYTES + _len_prefix(len(b)) + b
    if isinstance(obj, str):
        b = obj.encode("utf-8")
        return _T_STR + _len_prefix(len(b)) + b
    if isinstance(obj, list):
        parts = [encode(x) for x in obj]
        return _T_LIST + _len_prefix(len(parts)) + b"".join(parts)
    if isinstance(obj, tuple):
        parts = [encode(x) for x in obj]
        return _T_TUPLE + _len_prefix(len(parts)) + b"".join(parts)
    if isinstance(obj, dict):
        items = sorted((encode(k), encode(v)) for k, v in obj.items())
        return _T_DICT + _len_prefix(len(items)) + b"".join(k + v for k, v in items)
    raise TypeError(f"cannot canonically encode {type(obj).__name__}")


def encode_batch(objs) -> list:
    """Canonical encodings of many trees at once.

    Byte-identical to ``[encode(o) for o in objs]``; the dominant leaf
    shape (a plain ``bytes`` payload — every contribution the array
    engine frames, N per epoch) is inlined so the batch pays one frame
    per item instead of the full recursive dispatch."""
    out = []
    append = out.append
    for obj in objs:
        if type(obj) is bytes:
            append(_T_BYTES + _len_prefix(len(obj)) + obj)
        else:
            append(encode(obj))
    return out


def decode(data: bytes) -> Any:
    obj, off = _decode(data, 0)
    if off != len(data):
        raise ValueError("trailing bytes")
    return obj


def decode_batch(blobs) -> list:
    """Canonical decodes of many blobs at once (inverse of
    :func:`encode_batch`): the bare-``bytes`` payload fast path slices
    the value straight out of the frame; anything else takes the full
    recursive decode.  Equals ``[decode(b) for b in blobs]``."""
    out = []
    append = out.append
    for data in blobs:
        if data[:1] == _T_BYTES:
            n = int.from_bytes(data[1:5], "big")
            if len(data) == 5 + n:
                append(data[5:])
                continue
        append(decode(data))
    return out


def _decode(data: bytes, off: int):
    tag = data[off : off + 1]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_FALSE:
        return False, off
    if tag == _T_TRUE:
        return True, off
    if tag == _T_INT:
        neg = data[off] == 1
        n = int.from_bytes(data[off + 1 : off + 5], "big")
        mag = int.from_bytes(data[off + 5 : off + 5 + n], "big")
        return (-mag if neg else mag), off + 5 + n
    if tag == _T_BYTES:
        n = int.from_bytes(data[off : off + 4], "big")
        return data[off + 4 : off + 4 + n], off + 4 + n
    if tag == _T_STR:
        n = int.from_bytes(data[off : off + 4], "big")
        return data[off + 4 : off + 4 + n].decode("utf-8"), off + 4 + n
    if tag in (_T_LIST, _T_TUPLE):
        n = int.from_bytes(data[off : off + 4], "big")
        off += 4
        out = []
        for _ in range(n):
            x, off = _decode(data, off)
            out.append(x)
        return (out if tag == _T_LIST else tuple(out)), off
    if tag == _T_DICT:
        n = int.from_bytes(data[off : off + 4], "big")
        off += 4
        out = {}
        for _ in range(n):
            k, off = _decode(data, off)
            v, off = _decode(data, off)
            out[k] = v
        return out, off
    raise ValueError(f"bad tag {tag!r} at {off - 1}")
