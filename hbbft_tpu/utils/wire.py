"""Wire serialization for every protocol message type.

The reference serializes all wire messages with `bincode`+serde
(SURVEY.md §2.2) — canonical bytes on the network, no code execution on
decode.  This module is that discipline for the whole message hierarchy:

    QHB/DHB msg ⊃ HB msg ⊃ Subset msg ⊃ {Broadcast | BA msg ⊃ Coin msg}

``encode_message`` lowers a message object to a tagged canonical tree
(utils/canonical.py) and returns bytes; ``decode_message`` parses bytes
back into message objects, validating shapes as it goes — malformed input
raises :class:`WireError`, never executes code (unlike pickle, which
examples/node.py previously used on network input).

Crypto payloads (signature/decryption shares, Merkle proofs) travel as
their own fixed to_bytes forms; decoding needs the ambient crypto
``group`` to reconstruct curve elements.
"""

from __future__ import annotations

from typing import Any

from hbbft_tpu.crypto.keys import DecryptionShare, SignatureShare
from hbbft_tpu.crypto.merkle import Proof
from hbbft_tpu.protocols.binary_agreement import BaMessage
from hbbft_tpu.protocols.bool_set import BoolSet
from hbbft_tpu.protocols.broadcast import BroadcastMessage
from hbbft_tpu.protocols.dynamic_honey_badger import DhbMessage
from hbbft_tpu.protocols.honey_badger import HbMessage
from hbbft_tpu.protocols.sbv_broadcast import SbvMessage
from hbbft_tpu.protocols.sender_queue import SqMessage
from hbbft_tpu.protocols.subset import SubsetMessage
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecryptMessage
from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage
from hbbft_tpu.utils import canonical


class WireError(ValueError):
    """Malformed or unknown wire bytes."""


#: The wire-variant registry: every message class the codec speaks, mapped to
#: its (wire tag, kind-variant tuple).  This is the single enumeration that
#: (a) the handler-exhaustiveness lint rule cross-references against each
#: protocol's ``handle_message`` dispatch, (b) tests/test_wire_properties.py
#: walks to prove canonical encode/decode round-trips, and (c) the codec
#: below is drift-checked against by the lint rule (every registered kind
#: must appear as a literal in this module).  Adding a message variant
#: without updating all three breaks the tier-1 suite — by design.
WIRE_VARIANTS = {
    "SbvMessage": ("sbv", ("bval", "aux")),
    "ThresholdSignMessage": ("tsig", ()),
    "ThresholdDecryptMessage": ("tdec", ()),
    "BroadcastMessage": ("bc", ("value", "echo", "ready")),
    "BaMessage": ("ba", ("sbv", "conf", "coin", "term")),
    "SubsetMessage": ("ss", ("broadcast", "agreement")),
    "HbMessage": ("hb", ("subset", "dec_share")),
    "DhbMessage": ("dhb", ()),
    "SqMessage": ("sq", ("epoch_started", "algo")),
}


def _to_tree(msg: Any) -> Any:
    if isinstance(msg, SbvMessage):
        if msg.kind not in ("bval", "aux"):
            raise WireError(f"bad sbv kind {msg.kind!r}")
        return ("sbv", msg.kind, bool(msg.value))
    if isinstance(msg, ThresholdSignMessage):
        return ("tsig", msg.share.to_bytes())
    if isinstance(msg, ThresholdDecryptMessage):
        return ("tdec", msg.share.to_bytes())
    if isinstance(msg, BroadcastMessage):
        if msg.kind in ("value", "echo"):
            return ("bc", msg.kind, msg.payload.to_bytes())
        if msg.kind == "ready":
            return ("bc", "ready", bytes(msg.payload))
        raise WireError(f"bad broadcast kind {msg.kind!r}")
    if isinstance(msg, BaMessage):
        if msg.kind == "sbv":
            inner: Any = _to_tree(msg.payload)
        elif msg.kind == "conf":
            inner = msg.payload.bits
        elif msg.kind == "coin":
            inner = _to_tree(msg.payload)
        elif msg.kind == "term":
            inner = bool(msg.payload)
        else:
            raise WireError(f"bad ba kind {msg.kind!r}")
        return ("ba", msg.round, msg.kind, inner)
    if isinstance(msg, SubsetMessage):
        return ("ss", msg.proposer, msg.kind, _to_tree(msg.payload))
    if isinstance(msg, HbMessage):
        return ("hb", msg.epoch, msg.kind, msg.proposer, _to_tree(msg.payload))
    if isinstance(msg, DhbMessage):
        return ("dhb", msg.era, _to_tree(msg.payload))
    if isinstance(msg, SqMessage):
        if msg.kind == "epoch_started":
            era, epoch = msg.payload
            return ("sq", "epoch_started", (int(era), int(epoch)))
        if msg.kind == "algo":
            return ("sq", "algo", _to_tree(msg.payload))
        raise WireError(f"bad sender-queue kind {msg.kind!r}")
    raise WireError(f"unencodable message type {type(msg).__name__}")


def encode_message(msg: Any) -> bytes:
    """Message object → canonical wire bytes."""
    return canonical.encode(_to_tree(msg))


def _need(cond: bool, what: str) -> None:
    if not cond:
        raise WireError(f"malformed {what}")


def _valid_id(x: Any) -> bool:
    """Node ids on the wire must be hashable canonical scalars (or tuples
    of them) — anything else is rejected before it can reach protocol
    dict lookups."""
    if x is None or isinstance(x, (bool, int, bytes, str)):
        return True
    return isinstance(x, tuple) and all(_valid_id(e) for e in x)


def _from_tree(t: Any, group) -> Any:
    _need(isinstance(t, tuple) and len(t) >= 2 and isinstance(t[0], str), "message")
    tag = t[0]
    if tag == "sbv":
        _need(len(t) == 3 and t[1] in ("bval", "aux") and isinstance(t[2], bool), "sbv")
        return SbvMessage(t[1], t[2])
    if tag == "tsig":
        _need(len(t) == 2 and isinstance(t[1], bytes), "tsig")
        return ThresholdSignMessage(SignatureShare.from_bytes(group, t[1]))
    if tag == "tdec":
        _need(len(t) == 2 and isinstance(t[1], bytes), "tdec")
        return ThresholdDecryptMessage(DecryptionShare.from_bytes(group, t[1]))
    if tag == "bc":
        _need(len(t) == 3 and isinstance(t[2], bytes), "broadcast")
        if t[1] in ("value", "echo"):
            try:
                proof = Proof.from_bytes(t[2])
            except Exception as e:
                raise WireError(f"bad proof bytes: {e}") from e
            return BroadcastMessage(t[1], proof)
        _need(t[1] == "ready" and len(t[2]) == 32, "ready")
        return BroadcastMessage("ready", t[2])
    if tag == "ba":
        _need(len(t) == 4 and isinstance(t[1], int) and t[1] >= 0, "ba")
        kind, inner = t[2], t[3]
        if kind == "sbv":
            payload: Any = _from_tree(inner, group)
            _need(isinstance(payload, SbvMessage), "ba sbv payload")
        elif kind == "conf":
            _need(isinstance(inner, int) and 0 <= inner <= 3, "ba conf")
            payload = BoolSet(inner)
        elif kind == "coin":
            payload = _from_tree(inner, group)
            _need(isinstance(payload, ThresholdSignMessage), "ba coin payload")
        elif kind == "term":
            _need(isinstance(inner, bool), "ba term")
            payload = inner
        else:
            raise WireError(f"bad ba kind {kind!r}")
        return BaMessage(t[1], kind, payload)
    if tag == "ss":
        _need(len(t) == 4 and t[2] in ("broadcast", "agreement"), "subset")
        _need(_valid_id(t[1]), "subset proposer")
        payload = _from_tree(t[3], group)
        if t[2] == "broadcast":
            _need(isinstance(payload, BroadcastMessage), "subset payload")
        else:
            _need(isinstance(payload, BaMessage), "subset payload")
        return SubsetMessage(t[1], t[2], payload)
    if tag == "hb":
        _need(len(t) == 5 and isinstance(t[1], int) and t[1] >= 0, "hb")
        _need(t[2] in ("subset", "dec_share"), "hb kind")
        _need(_valid_id(t[3]), "hb proposer")
        payload = _from_tree(t[4], group)
        if t[2] == "subset":
            _need(isinstance(payload, SubsetMessage), "hb payload")
        else:
            _need(isinstance(payload, ThresholdDecryptMessage), "hb payload")
        return HbMessage(t[1], t[2], t[3], payload)
    if tag == "dhb":
        _need(len(t) == 3 and isinstance(t[1], int) and t[1] >= 0, "dhb")
        payload = _from_tree(t[2], group)
        _need(isinstance(payload, HbMessage), "dhb payload")
        return DhbMessage(t[1], payload)
    if tag == "sq":
        _need(len(t) == 3, "sq")
        if t[1] == "epoch_started":
            _need(
                isinstance(t[2], tuple)
                and len(t[2]) == 2
                and all(isinstance(x, int) and x >= 0 for x in t[2]),
                "sq epoch_started",
            )
            return SqMessage("epoch_started", (t[2][0], t[2][1]))
        _need(t[1] == "algo", "sq kind")
        return SqMessage("algo", _from_tree(t[2], group))
    raise WireError(f"unknown wire tag {tag!r}")


def decode_message(data: bytes, group) -> Any:
    """Canonical wire bytes → message object (never executes code)."""
    try:
        tree = canonical.decode(data)
    except Exception as e:
        raise WireError(f"bad canonical bytes: {e}") from e
    return _from_tree(tree, group)
