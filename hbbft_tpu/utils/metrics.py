"""Observability: counters + opt-in structured event log.

SURVEY.md §5 (port note): the reference's only observability is `log` macros
and the simulation's epoch table; the port is required to surface *counters*
— messages, pairings verified, shares combined, epochs/sec — because they
are literally the BASELINE metrics, plus a structured per-crank event log
in the driver.

:class:`Counters` is attached to every :class:`~hbbft_tpu.crypto.backend
.CryptoBackend` (crypto-side tallies) and to :class:`~hbbft_tpu.net
.virtual_net.VirtualNet` (net-side tallies).  :class:`EventLog` is opt-in
(``NetBuilder.trace(...)``): when absent, the runtime pays one ``is None``
check per crank.
"""

from __future__ import annotations

import json
from collections import deque
from dataclasses import asdict, dataclass, field, fields
from typing import Any, Dict, List, Optional


@dataclass(slots=True)
class Counters:
    """Monotonic tallies of the framework's operative metrics."""

    # net-side
    messages_delivered: int = 0
    cranks: int = 0
    faults_recorded: int = 0
    # net-schedule layer (net/virtual_net.NetSchedule): messages dropped
    # by loss/partition policy vs merely future-dated by latency/jitter
    schedule_dropped: int = 0
    schedule_delayed: int = 0
    # crash/restart axis (net/crash.py): node deaths, completed restarts,
    # traffic parked for down nodes, WAL events re-handled during recovery,
    # replayed emissions suppressed as already-delivered, and checkpoints
    # taken (baseline + periodic)
    node_crashes: int = 0
    node_restarts: int = 0
    crash_parked_messages: int = 0
    crash_replayed_events: int = 0
    crash_suppressed_sends: int = 0
    crash_checkpoints: int = 0
    # crypto-side: items verified per kind
    sig_shares_verified: int = 0
    dec_shares_verified: int = 0
    signatures_verified: int = 0
    ciphertexts_verified: int = 0
    # crypto-side: how the work was done
    pairing_checks: int = 0  # pairing-equation evaluations dispatched
    rlc_groups: int = 0  # grouped (random-linear-combination) checks
    rlc_adaptive_splits: int = 0  # batches re-partitioned by observed contamination
    sig_shares_combined: int = 0  # shares consumed by signature combines
    dec_shares_combined: int = 0  # shares consumed by decryption combines
    device_dispatches: int = 0  # jitted device calls issued
    # host/device wall-clock attribution (round-3 verdict task 8: the
    # first on-chip N=100 epoch must show where time goes).  Timed at the
    # hot seams only: device_seconds wraps dispatch+fetch of the dominant
    # jitted calls; hash_g2_seconds is the host EC hash (the named
    # >10%-risk item at 29 ms/doc).
    device_seconds: float = 0.0
    hash_g2_seconds: float = 0.0
    # pipelined-dispatch attribution (PR 3): host_assembly_seconds is the
    # host-side staging cost (limb packing, scalars_to_bits, point
    # conversion) the deferred-fetch pipeline overlaps with device
    # execution; overlap_seconds is the host time actually spent doing
    # useful work between issuing a dispatch and requesting its fetch
    # (the hidden-under-device window, EXCLUDING stretches blocked in
    # other entries' fetches — counting those would overstate the win);
    # pipelined_dispatches counts dispatches whose fetch was deferred.  NOTE under pipelining the per-dispatch [dispatch, fetch]
    # intervals overlap in wall time, so device_seconds may legitimately
    # exceed wall clock — it remains the sum of per-dispatch intervals
    # and still equals the traced device-span total by construction.
    host_assembly_seconds: float = 0.0
    overlap_seconds: float = 0.0
    pipelined_dispatches: int = 0
    # host-side attribution (PR 5: the north-star epoch's 55% "host:
    # everything else" bucket, itemized).  host_seconds is the total host
    # wall inside engine epochs/era changes EXCLUDING time blocked in
    # device fetches (fetch_blocked_seconds, billed by the pipeline's
    # resolve seam) — i.e. the time the host thread actually spent doing
    # host work.  The host_bucket_* fields are an EXCLUSIVE partition of
    # host_seconds (obs/hostbuckets.py region stack: each region bills
    # its own wall minus child regions minus fetch-blocked stretches), so
    # they sum to host_seconds by construction:
    #   encode    — canonical encode/decode + ciphertext (de)serialization
    #   rs_merkle — RS encode/reconstruct, Merkle commits, proof hashing
    #   assemble  — batched-call item-list construction (rounds 7-8 etc.)
    #   scatter   — flat dispatch results → per-(proposer, sender) state
    #   staging   — limb packing / scalars_to_bits / point conversion
    #               (the _host_assembly blocks; == host_assembly_seconds
    #               minus its own fetch-blocked stretches)
    #   dispatch  — backend batch-call host glue outside staging (group
    #               bookkeeping, delivery callbacks, host golden paths)
    #   other     — everything not under a named region (the residual the
    #               <10%-unattributed acceptance bar tracks)
    host_seconds: float = 0.0
    fetch_blocked_seconds: float = 0.0
    host_bucket_encode: float = 0.0
    host_bucket_rs_merkle: float = 0.0
    host_bucket_assemble: float = 0.0
    host_bucket_scatter: float = 0.0
    host_bucket_staging: float = 0.0
    host_bucket_dispatch: float = 0.0
    host_bucket_other: float = 0.0
    # device-staging cache (ops/staging.py): distinct field values served
    # from / inserted into the limb-row cache per staging call
    stage_cache_hits: int = 0
    stage_cache_misses: int = 0
    # GLV/GLS endomorphism-ladder accounting (ops/curve.py): analytic
    # field-mul counts of the dispatched scalar-ladder scans (per-lane
    # formula cost × lanes; Fq muls on G1 shapes, Fq2 muls on G2 shapes —
    # the unit mirrors what the graph actually executes per lane), the
    # separate joint-table build cost, the number of host Babai
    # decompositions, and the host wall spent decomposing + packing
    # windows (the host share of the table path; the in-graph table
    # build itself is device time).  ladder_field_muls is the
    # glv_ladder_ab bench row's numerator: the ≥1.5× per-G1-ladder
    # reduction (2368 vs 3810) reads directly off it.
    ladder_field_muls: int = 0
    glv_table_field_muls: int = 0
    glv_decompositions: int = 0
    glv_table_build_seconds: float = 0.0
    # device_seconds split by dispatch kind (round-4 verdict task 7: the
    # n16 on-chip epoch was 90% unattributed).  Sums to device_seconds up
    # to the rare unkinded dispatch; zero-valued kinds are elided from
    # bench rows.
    device_seconds_pairing: float = 0.0  # exact pairing checks (+fallback)
    device_seconds_rlc_sig: float = 0.0  # grouped RLC sig-share verifies
    device_seconds_rlc_dec: float = 0.0  # grouped RLC dec-share verifies
    device_seconds_combine: float = 0.0  # Lagrange combines (sig + dec)
    device_seconds_sign: float = 0.0  # batched G2 sign ladders
    device_seconds_decrypt: float = 0.0  # batched G1 decrypt-share ladders
    device_seconds_dkg: float = 0.0  # batched era-change DKG ladders/MSMs
    device_seconds_encrypt: float = 0.0  # batched threshold-encrypt ladders
    device_seconds_glv_ab: float = 0.0  # glv_ladder_ab bench-row dispatches
    # device erasure/hash plane (PR 19): RS encode / reconstruct bit-matmuls
    # and Merkle tree-build + proof-verify SHA-256 dispatches
    device_seconds_rs_enc: float = 0.0  # batched GF(2⁸) parity matmuls
    device_seconds_rs_dec: float = 0.0  # batched GF(2⁸) decode matmuls
    device_seconds_merkle: float = 0.0  # batched device SHA-256 (build+verify)
    # VMEM-resident fused tower chain (PR 20): dispatches whose pairing
    # graph rode the fused kernels (ops/pairing_chain.py) bill their
    # device wall here instead of the per-kind buckets above, so the
    # fused/unfused A/B reads directly off the kind split.
    device_seconds_fused_chain: float = 0.0
    # fused-chain accounting: calls that routed onto the fused kernels,
    # the ANALYTIC Fq-mul count executed inside them (pairing_chain.
    # analytic_chain_field_muls — the muls/s numerator of the
    # fused_chain_ab bench row), and the analytic per-verification device
    # kernel-launch counts of both compositions (pairing_chain.
    # analytic_pallas_calls — the ≥3× dispatch-drop criterion reads off
    # fused vs stacked directly).
    fused_tower_calls: int = 0
    fused_chain_field_muls: int = 0
    fused_chain_pallas_calls: int = 0
    stacked_chain_pallas_calls: int = 0

    def snapshot(self) -> Dict[str, float]:
        return asdict(self)

    def reset(self) -> None:
        """Zero every tally (fresh measurement window on a shared backend)."""
        for f in fields(self):
            setattr(self, f.name, f.default)

    def diff(self, prev: Dict[str, float]) -> Dict[str, float]:
        """Delta since a previous :meth:`snapshot` (only nonzero keys)."""
        cur = self.snapshot()
        return {k: cur[k] - prev.get(k, 0) for k in cur if cur[k] != prev.get(k, 0)}

    def delta(self, since: Dict[str, float]) -> Dict[str, float]:
        """Snapshot-diff measurement window: every field's change since
        ``since`` (a previous :meth:`snapshot`), INCLUDING zero-valued
        fields.  The per-epoch series (obs/timeseries.py) and any other
        windowed reader use this instead of a mid-run :meth:`reset` —
        resetting a live, shared Counters skews every run-end aggregate
        read after it."""
        cur = self.snapshot()
        return {k: cur[k] - since.get(k, 0) for k in cur}

    def merged_with(self, other: "Counters") -> Dict[str, float]:
        a, b = self.snapshot(), other.snapshot()
        return {k: a[k] + b[k] for k in a}


class EventLog:
    """Opt-in structured per-crank event log (SURVEY.md §5 port note).

    Events are plain dicts; ``emit`` is cheap append.  ``to_jsonl`` dumps
    the log for offline analysis.  A ``capacity`` bound (default 1M) guards
    against unbounded growth on soak runs — the backing store is a
    ``deque(maxlen=capacity)`` ring buffer, so eviction of the oldest
    event is O(1) (the earlier list-based store paid an O(n) front
    deletion per eviction batch) and ``dropped`` accounting is exact.
    """

    def __init__(self, capacity: int = 1_000_000) -> None:
        self.capacity = capacity
        self.events: deque = deque(maxlen=capacity)
        self._emitted = 0

    def emit(self, **fields: Any) -> None:
        self.events.append(fields)
        self._emitted += 1

    @property
    def dropped(self) -> int:
        return self._emitted - len(self.events)

    def __len__(self) -> int:
        return len(self.events)

    def of_type(self, event: str) -> List[Dict[str, Any]]:
        return [e for e in self.events if e.get("event") == event]

    def to_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for e in self.events:
                f.write(json.dumps(e, default=repr) + "\n")
