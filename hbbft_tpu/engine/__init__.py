"""Vectorized simulation engine (the "array engine").

See :mod:`hbbft_tpu.engine.array_engine` — the whole-network lockstep
executor that replaces per-message Python dispatch with per-round batched
array/crypto operations.
"""

from hbbft_tpu.engine.array_engine import (
    ArrayHoneyBadgerNet,
    EngineInvariantError,
)

__all__ = ["ArrayHoneyBadgerNet", "EngineInvariantError"]
