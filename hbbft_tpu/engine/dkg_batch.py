"""Batched era-change DKG for the lockstep array engine.

The per-node path (protocols/sync_key_gen.py, kept untouched as the
protocol runtime and golden cross-check) costs O(N³) *sequential* host
crypto at era-change time: every ack value is an individually encrypted,
pairing-verified, commitment-cross-checked ciphertext.  Measured live in
round 5 at N=100 (BASELINE config 3): ~2.5 s per handle_part × 10k parts
plus ~1M handle_ack calls each containing a pure-Python pairing — a
multi-DAY single-core job.  The reference's Rust would take minutes; a
TPU-first design must do better, not merely match.

This module runs the SAME protocol math in array form:

* every full-width scalar multiplication (bivariate commitment
  coefficients, ciphertext U/shared/W components, row/value decryption
  ladders) goes through the backend's batched ladder dispatches
  (``g1_mul_batch``/``g2_mul_batch`` — thousands of independent 255-bit
  ladders per device call);
* every ciphertext validity check (e(G1, W) == e(U, H2(U‖V))) goes
  through ``backend.verify_ciphertexts`` — one batched pairing dispatch
  per phase instead of N³ sequential host pairings;
* the commitment cross-checks (row checks f_p(k+1,·)·G == C_p.row(k+1),
  ack checks f_p(a+1,k+1)·G == C_p(a+1,k+1)) collapse under a random
  linear combination: Σ w·(value·G − commit-eval) == O.  The weights
  fold into PURE Fr arithmetic on the host (the commitment points enter
  one aggregated multi-scalar combination, ``backend.g1_lincomb``), so
  N³ G1 Horner evaluations become one MSM + O(N³) cheap int mults.
  Soundness: a forged value survives with probability 2⁻⁶⁴ per weight —
  the framework's standard grouped-RLC argument (ops/backend.py); on
  aggregate mismatch the caller falls back to the exact per-node path
  for attribution.

What stays honestly host-side: hash-to-G2 of each ciphertext (the
try-and-increment + cofactor clearing in crypto/bls381.py), pad/XOR
symmetric encryption, and Fr polynomial arithmetic.  Hash-to-G2
dominates at large N (itemized in PERF.md round 5) — it is the next
native-kernel candidate, not a reason to skip the batch design.

Protocol-semantics parity: same Part/Ack counts, same deterministic
key-set derivation (first t+1 complete proposers, Σ row(0) commitments,
Lagrange-interpolated share values) as SyncKeyGen.generate().  Keys are
NOT byte-identical across paths (the rng is consumed in a different
order); tests/test_dkg_batch.py asserts semantic equivalence — matching
workload counts, self-consistent key sets, working consensus under the
new keys — plus rejection of corrupted rows/values by each RLC check.

Reference analogue: hbbft's sync_key_gen used by DynamicHoneyBadger for
validator churn (SURVEY.md §3.4); the batching is the TPU-first redesign.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Sequence, Tuple

from hbbft_tpu.crypto.field import interpolate_at_zero
from hbbft_tpu.crypto.keys import Ciphertext, PublicKeySet, SecretKeyShare
from hbbft_tpu.crypto.poly import BivarPoly, Commitment
from hbbft_tpu.utils import canonical


class DkgStats:
    """Work accounting mirroring the per-node path's report fields."""

    __slots__ = (
        "parts_handled", "acks_handled", "ciphertexts_verified",
        "hashes_g2", "ladder_muls", "msm_terms",
    )

    def __init__(self) -> None:
        self.parts_handled = 0
        self.acks_handled = 0
        self.ciphertexts_verified = 0
        self.hashes_g2 = 0
        self.ladder_muls = 0
        self.msm_terms = 0


def batched_encrypt(
    backend, pk_els, msgs, rng, stats=None, kind: str = "dkg"
) -> List[Ciphertext]:
    """Threshold-encrypt msgs[i] to pk_els[i], ladders batched — the
    public batched counterpart of crypto/keys.Ciphertext.encrypt (same
    stages: U = s·G1, pad = H(s·PK), V = msg ⊕ pad, W = s·H2(U‖V)).
    Used by the DKG phases here and by the array engine's contribution
    encryption.  ``stats`` (a DkgStats) is optional work accounting.

    The returned ciphertexts carry the ENCRYPTOR's cached hash point;
    callers whose receivers must honestly pay their own hash-to-G2
    delete ``_hash_point`` first (as _batched_decrypt and the array
    engine both do)."""
    if stats is None:
        stats = DkgStats()
    g = backend.group
    n = len(msgs)
    ss = [rng.randrange(1, g.r) for _ in range(n)]
    base = [g.g1()] * n
    us = backend.g1_mul_batch(ss, base, kind)
    shareds = backend.g1_mul_batch(ss, list(pk_els), kind)
    stats.ladder_muls += 2 * n
    vs = []
    hs = []
    t0 = time.perf_counter()
    for i in range(n):
        pad = g.hash_bytes(g.g1_to_bytes(shareds[i]), len(msgs[i]))
        v = bytes(a ^ b for a, b in zip(msgs[i], pad))
        vs.append(v)
        hs.append(g.hash_to_g2(g.g1_to_bytes(us[i]) + v))
    # billed directly (not via TpuBackend._hash_g2): these docs must NOT
    # enter the h2 cache, or the receiver's honest re-hash inside
    # verify_ciphertexts would become a free cache hit
    backend.counters.hash_g2_seconds += time.perf_counter() - t0
    stats.hashes_g2 += n
    ws = backend.g2_mul_batch(ss, hs, kind)
    stats.ladder_muls += n
    out = []
    for i in range(n):
        ct = Ciphertext(g, us[i], vs[i], ws[i])
        ct._hash_point = hs[i]  # encryptor-side cache (receiver recomputes)
        out.append(ct)
    return out


def _batched_decrypt(backend, cts, sk_xs, stats) -> List[bytes]:
    """Decrypt cts[i] under secret scalar sk_xs[i], ladders + pairings
    batched.  Mirrors SecretKey.decrypt: validity pairing first (receiver
    recomputes H2(U‖V) — the honest per-receiver workload), then
    pad = H(x·U), plaintext = V ⊕ pad."""
    g = backend.group
    n = len(cts)
    for ct in cts:
        # drop the encryptor's cached hash point: the receiving role must
        # pay (and count) its own hash-to-G2
        if hasattr(ct, "_hash_point"):
            del ct._hash_point
    ok = backend.verify_ciphertexts(cts)
    stats.ciphertexts_verified += n
    stats.hashes_g2 += n
    if not all(ok):
        bad = ok.index(False)
        raise ValueError(f"batched DKG: invalid ciphertext at index {bad}")
    shareds = backend.g1_mul_batch(list(sk_xs), [ct.u for ct in cts])
    stats.ladder_muls += n
    out = []
    for i in range(n):
        pad = g.hash_bytes(g.g1_to_bytes(shareds[i]), len(cts[i].v))
        out.append(bytes(a ^ b for a, b in zip(cts[i].v, pad)))
    return out


def _rlc_weight(rng) -> int:
    """64-bit nonzero random weight — the grouped-RLC standard width
    (ops/backend.py rlc discussion; soundness 2⁻⁶⁴ per forged item)."""
    return rng.randrange(1, 1 << 64)


def batched_era_dkg(
    backend,
    ids: Sequence[Any],
    sk_xs: Dict[Any, int],
    pk_els: Dict[Any, Any],
    threshold: int,
    rng,
) -> Tuple[PublicKeySet, Dict[Any, SecretKeyShare], DkgStats]:
    """Full-workload SyncKeyGen among all N nodes, device-batched.

    ``sk_xs``/``pk_els`` are each node's long-term secret scalar / public
    G1 element (the encryption keys the per-node path uses).  Returns the
    master PublicKeySet, per-node SecretKeyShares, and work stats; raises
    on any check failure (the lockstep engine is all-honest — a failure
    is a bug, and callers may re-run the per-node path for attribution).
    """
    g = backend.group
    n = len(ids)
    t = threshold
    stats = DkgStats()

    # -- proposal phase: bivariate polys + commitments (batched muls) -------
    polys: List[BivarPoly] = [BivarPoly.random(g, t, rng) for _ in range(n)]
    flat_scalars: List[int] = []
    for poly in polys:
        for row in poly.coeffs:
            flat_scalars.extend(row)
    m = t + 1
    base = g.g1()
    commit_pts = backend.g1_mul_batch(flat_scalars, [base] * len(flat_scalars))
    stats.ladder_muls += len(flat_scalars)
    # commit_grid[p][i][j] = coeffs[p][i][j]·G1
    commit_grid = [
        [
            commit_pts[p * m * m + i * m : p * m * m + i * m + m]
            for i in range(m)
        ]
        for p in range(n)
    ]

    # -- row distribution: encrypt row^p_k coeffs to node k -----------------
    # row^p_k = f^p(k+1, ·) — what Part.rows carries in the per-node path.
    row_coeffs: List[List[List[int]]] = []  # [p][k][j]
    enc_pk: List[Any] = []
    enc_msgs: List[bytes] = []
    for p in range(n):
        per_k = []
        for k, nid in enumerate(ids):
            coeffs = polys[p].row(k + 1).coeffs
            per_k.append(coeffs)
            enc_pk.append(pk_els[nid])
            enc_msgs.append(canonical.encode(list(coeffs)))
        row_coeffs.append(per_k)
    row_cts = batched_encrypt(backend, enc_pk, enc_msgs, rng, stats)

    # -- part handling: each node decrypts + checks its row -----------------
    dec_xs = [sk_xs[ids[k]] for _ in range(n) for k in range(n)]
    row_plain = _batched_decrypt(backend, row_cts, dec_xs, stats)
    got_rows: List[List[List[int]]] = [[None] * n for _ in range(n)]
    for p in range(n):
        for k in range(n):
            coeffs = canonical.decode(row_plain[p * n + k])
            if not isinstance(coeffs, list) or len(coeffs) != m:
                raise ValueError("batched DKG: malformed row plaintext")
            got_rows[p][k] = [c % g.r for c in coeffs]
    stats.parts_handled += n * n

    # Row commitment RLC check, all (p, k, j) at once:
    #   Σ w_{pkj}·row^p_k[j]·G  ==  Σ_{pij} C^p_ij · (Σ_k w_{pkj}(k+1)^i)
    xpow = [[pow(k + 1, i, g.r) for i in range(m)] for k in range(n)]
    w_row = [
        [[_rlc_weight(rng) for _ in range(m)] for _ in range(n)]
        for _ in range(n)
    ]
    lhs_scalar = 0
    for p in range(n):
        for k in range(n):
            row = got_rows[p][k]
            wk = w_row[p][k]
            for j in range(m):
                lhs_scalar = (lhs_scalar + wk[j] * row[j]) % g.r
    agg_scalars: List[int] = []
    agg_points: List[Any] = []
    for p in range(n):
        for i in range(m):
            for j in range(m):
                s = 0
                for k in range(n):
                    s += w_row[p][k][j] * xpow[k][i]
                agg_scalars.append(s % g.r)
                agg_points.append(commit_grid[p][i][j])
    stats.msm_terms += len(agg_points)
    rhs = backend.g1_lincomb(agg_scalars, agg_points)
    lhs = g.g1_mul(lhs_scalar, base)
    if lhs != rhs:
        raise ValueError("batched DKG: aggregated row-commitment check failed")

    # -- ack phase: every node acks every part to every node ----------------
    # value v^p_{a,k} = row^p_a(k+1); acker a encrypts it to node k.
    ack_vals: List[List[List[int]]] = []  # [p][a][k]
    enc_pk2: List[Any] = []
    enc_msgs2: List[bytes] = []
    for p in range(n):
        per_a = []
        for a in range(n):
            rowpoly = got_rows[p][a]
            per_k = []
            for k, nid in enumerate(ids):
                acc = 0
                for c in reversed(rowpoly):
                    acc = (acc * (k + 1) + c) % g.r
                per_k.append(acc)
                enc_pk2.append(pk_els[nid])
                enc_msgs2.append(canonical.encode(acc))
            per_a.append(per_k)
        ack_vals.append(per_a)
    ack_cts = batched_encrypt(backend, enc_pk2, enc_msgs2, rng, stats)

    dec_xs2 = [
        sk_xs[ids[k]]
        for p in range(n)
        for a in range(n)
        for k in range(n)
    ]
    ack_plain = _batched_decrypt(backend, ack_cts, dec_xs2, stats)
    got_vals: List[List[List[int]]] = [
        [[None] * n for _ in range(n)] for _ in range(n)
    ]
    idx = 0
    for p in range(n):
        for a in range(n):
            for k in range(n):
                v = canonical.decode(ack_plain[idx])
                idx += 1
                if not isinstance(v, int):
                    raise ValueError("batched DKG: malformed ack plaintext")
                got_vals[p][a][k] = v % g.r
    stats.acks_handled += n * n * n

    # Ack RLC check, all (p, a, k) at once:
    #   Σ w·v^p_{a,k}·G == Σ_{pij} C^p_ij · (Σ_a (a+1)^i Σ_k w_{pak}(k+1)^j)
    # (f symmetric: f(a+1, k+1) = Σ_ij c_ij (a+1)^i (k+1)^j.)
    w_ack = [
        [[_rlc_weight(rng) for _ in range(n)] for _ in range(n)]
        for _ in range(n)
    ]
    lhs_scalar2 = 0
    for p in range(n):
        for a in range(n):
            wa = w_ack[p][a]
            va = got_vals[p][a]
            for k in range(n):
                lhs_scalar2 = (lhs_scalar2 + wa[k] * va[k]) % g.r
    agg_scalars2: List[int] = []
    agg_points2: List[Any] = []
    for p in range(n):
        # inner[a][j] = Σ_k w_{pak}(k+1)^j  (separable double sum)
        inner = [
            [sum(w_ack[p][a][k] * xpow[k][j] for k in range(n)) % g.r
             for j in range(m)]
            for a in range(n)
        ]
        for i in range(m):
            for j in range(m):
                s = 0
                for a in range(n):
                    s += xpow[a][i] * inner[a][j]
                agg_scalars2.append(s % g.r)
                agg_points2.append(commit_grid[p][i][j])
    stats.msm_terms += len(agg_points2)
    rhs2 = backend.g1_lincomb(agg_scalars2, agg_points2)
    lhs2 = g.g1_mul(lhs_scalar2, base)
    if lhs2 != rhs2:
        raise ValueError("batched DKG: aggregated ack-value check failed")

    # -- key derivation (mirrors SyncKeyGen.generate exactly) ---------------
    # All parts complete in the honest lockstep run; the deterministic
    # choice is the first t+1 proposer indices.
    complete = list(range(t + 1))
    master: List[Any] = None
    for p in complete:
        row0 = commit_grid[p][0]  # BivarCommitment.row(0) = C^p_{0j}
        if master is None:
            master = list(row0)
        else:
            master = [g.g1_add(x, y) for x, y in zip(master, row0)]
    pk_set = PublicKeySet(Commitment(g, master))

    shares: Dict[Any, SecretKeyShare] = {}
    for k, nid in enumerate(ids):
        share_val = 0
        for p in complete:
            pts = [(a + 1, got_vals[p][a][k]) for a in range(t + 1)]
            share_val = (share_val + interpolate_at_zero(pts, g.r)) % g.r
        shares[nid] = SecretKeyShare(g, share_val)

    # consistency: every share must match the master commitment (batched)
    share_pts = backend.g1_mul_batch(
        [shares[nid].x for nid in ids], [base] * n
    )
    stats.ladder_muls += n
    for k, nid in enumerate(ids):
        if share_pts[k] != pk_set.public_key_share(k).el:
            raise ValueError(f"batched DKG: share {k} disagrees with master")
    return pk_set, shares, stats
