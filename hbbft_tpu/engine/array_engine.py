"""ArrayHoneyBadgerNet — the whole network as data (lockstep array engine).

The object runtime (`hbbft_tpu/net/virtual_net.py`) faithfully mirrors the
reference harness: one Python ``handle_message`` per delivered message.  At
N=100 an epoch is ~7N³ ≈ 6.9M messages; even at ~70µs each the host layer
alone takes ~8 minutes per epoch — three orders of magnitude above the
BASELINE north star, and the same wall the reference's per-message Rust
design hits (there it is the per-share pairing cost instead).

This module is the TPU-first answer for the *simulation* workload
(BASELINE.json configs 1/3/5, `examples/simulation.rs` §): run all N nodes
in **lockstep rounds** — every message sent in round r is delivered in
round r+1 (a zero-latency full-mesh network, the same schedule the
round-barrier ``defer_mode="round"`` runtime produces) — and execute each
round as a handful of *batched* operations over the whole network instead
of per-message dispatch:

* merkle proof checks:   one batched hash call per round (N³ items)
* pairing verifications: one batched backend call per round (N³ items)
* RS encode/decode:      per-instance numpy/GF(2⁸) matmul
* threshold counting:    plain arithmetic (symmetric under lockstep)

**Workload fidelity.** Per-receiver work is NOT deduplicated: every
(receiver, sender) pair contributes its own hash validation and its own
share-verification item, exactly as N independent nodes (and the object
engine, and the reference) would perform.  Message counts are tallied from
the same Target expansion rules VirtualNet applies.  The only asymmetry
the lockstep schedule removes is adversarial interleaving — which the
object engine retains for correctness testing (differential tests compare
the two).

**Protocol equivalence.** Under the lockstep schedule with honest nodes the
per-receiver state machines of broadcast.py / sbv_broadcast.py /
binary_agreement.py / subset.py / honey_badger.py are symmetric: every
threshold (N−f Echo, f+1/2f+1 BVal, 2f+1 Ready, N−f Aux/Conf) crosses for
all receivers in the same round, every RBC decodes in the same round, every
BA instance receives input ``true`` in the same round and decides ``true``
in its first round on the fixed coin (binary_agreement.py `_fixed_coin`:
round 0 → true).  The engine executes exactly those transitions, checking
the thresholds it relies on with explicit raises, and produces the same
`Batch` values the object engine emits under this schedule.

**Host-side execution (PR 5).** Epoch host time is itemized into the
``host_bucket_*`` counters (obs/hostbuckets.py regions: encode,
rs_merkle, assemble, scatter, staging, dispatch, other) and the hot host
paths are vectorized: item lists and result scatter use index arithmetic
over the flat backend batches, the N² Merkle proofs pack into arrays
(crypto/merkle.PackedProofs), the round-3 per-receiver RS reconstructs
run once with accounting-only replication, and canonical encode/decode
is batched.  Verification overlaps the NEXT round's assembly through the
backends' deferred entry points (the ``verify_*_deferred`` seam riding
ops/pipeline.py's bounded queue): combines are dispatched speculatively
while the share checks execute, and a failed check still raises before
any Batch is emitted.  ``HBBFT_TPU_NO_HOSTPIPE=1`` restores the legacy
per-item loops and strictly ordered verification — Batches are
bit-identical and ``device_dispatches`` unchanged either way (asserted
in tests/test_host_buckets.py).

Faulty/adversarial behaviour and latency models stay the object engine's
job; the array engine targets the honest-path throughput configs.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.crypto.backend import CryptoBackend, MockBackend
from hbbft_tpu.crypto.erasure import rs_codec
from hbbft_tpu.crypto.merkle import MerkleTree, PackedProofs, _depth, validate_proofs
from hbbft_tpu.obs import critpath as _critpath
from hbbft_tpu.ops.pipeline import device_rs_enabled, hostpipe_enabled
from hbbft_tpu.protocols.honey_badger import Batch
from hbbft_tpu.utils import canonical
from hbbft_tpu.utils.metrics import Counters


class EngineInvariantError(RuntimeError):
    """A lockstep invariant the engine relies on failed (honest-path
    precondition violated, or a Byzantine input slipped into a
    simulation run).  Raised explicitly — these checks used to be
    ``assert`` statements, which silently vanish under ``python -O`` and
    would turn the Byzantine-detection paths into no-ops."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise EngineInvariantError(msg)


@dataclass
class EpochReport:
    """Work accounting for one lockstep epoch (all-network totals)."""

    epoch: int
    rounds: int = 0
    messages_delivered: int = 0
    proofs_validated: int = 0
    hashes: int = 0
    ciphertexts_verified: int = 0
    dec_shares_verified: int = 0
    combines: int = 0
    rs_encodes: int = 0
    rs_reconstructs: int = 0
    coin_rounds: int = 0
    coin_signs: int = 0
    sig_shares_verified: int = 0
    sig_combines: int = 0
    votes_verified: int = 0
    kg_parts_handled: int = 0
    kg_acks_handled: int = 0
    # wall seconds per engine phase (rbc / coin / ba / decrypt) — the
    # lockstep engine's critical-path attribution input
    # (obs/critpath.path_from_phase_seconds)
    phase_seconds: Optional[Dict[str, float]] = None


class ArrayHoneyBadgerNet:
    """N-node HoneyBadger network executed in lockstep rounds.

    API shape::

        net = ArrayHoneyBadgerNet(range(100), backend=MockBackend(), seed=7)
        batches = net.run_epoch({i: contrib_bytes(i) for i in net.ids})
        # batches[node_id] — identical Batch for every node

    ``dedup_verifies=True`` collapses the N identical copies of each
    share-verification (each receiver checks the same share against the
    same public key) to one representative — a *memoizing simulation*
    mode; the default keeps the full per-receiver workload so measured
    epochs/sec reflect N independent nodes.
    """

    # class-level fallbacks for the environment attributes (not state —
    # save_node drops everything in _SNAPSHOT_ENV_ATTRS and restore
    # lands back on these defaults).  batch_listeners receive the
    # per-node Batch map after every epoch (the traffic subsystem's
    # delivery fan-out); contribution_source, when set, supplies
    # run_epochs' contributions (epoch -> {node: bytes}) instead of the
    # synthetic random payloads; batch_size_provider (zero-arg -> int)
    # publishes the control plane's live batch size B — the adaptive
    # controller (hbbft_tpu/control/) installs it and the traffic
    # driver's contribution sampling consults it per epoch.  All are
    # checkpoint-detached: a restored engine falls back to these
    # defaults and the embedder re-attaches its environment.
    tracer = None
    batch_listeners: Sequence = ()
    contribution_source = None
    batch_size_provider = None
    #: per-epoch series (obs/timeseries.MetricsLog): when attached, every
    #: run_epoch appends a row (merged counter deltas, histogram windows,
    #: live B, the epoch's phase-attributed gate) — environment, not state
    metrics_log = None
    _SNAPSHOT_ENV_ATTRS = (
        "tracer",
        "batch_listeners",
        "contribution_source",
        "batch_size_provider",
        "metrics_log",
    )

    def __init__(
        self,
        node_ids: Sequence[Any],
        backend: Optional[CryptoBackend] = None,
        seed: int = 0,
        dedup_verifies: bool = False,
        verify_chunk: int = 1 << 17,
        dynamic: bool = False,
        coin_rounds: int = 0,
        tracer=None,
    ) -> None:
        self.ids = sorted(node_ids)
        self.n = len(self.ids)
        self.f = (self.n - 1) // 3
        self.backend = backend or MockBackend()
        self.rng = random.Random(seed)
        self.netinfos: Dict[Any, NetworkInfo] = NetworkInfo.generate_map(
            self.ids, self.rng, self.backend
        )
        self.dedup_verifies = dedup_verifies
        self.verify_chunk = verify_chunk
        #: DynamicHoneyBadger flavor (BASELINE config 3): contributions are
        #: wrapped in DHB's internal ("icontrib", user, votes, keygen)
        #: envelope.  With no membership churn the vote/key-gen lists are
        #: empty, so the batched per-batch signature verification the DHB
        #: layer performs (dynamic_honey_badger.py _on_hb_batch) has zero
        #: items — the honest cost of DHB's steady state over HB.
        self.dynamic = dynamic
        #: Real ThresholdSign coin rounds per BA instance per epoch.  The
        #: honest lockstep schedule with unanimous inputs decides every BA
        #: on the round-0 fixed coin (binary_agreement.py _fixed_coin), so
        #: the default epoch carries no threshold-sign traffic.  With
        #: coin_rounds=R > 0 the engine models the split-input schedule —
        #: conf_values = {true, false} for R rounds, so each BA round
        #: invokes the REAL coin (threshold_sign.py): every node signs the
        #: round nonce, broadcasts its share, verifies every peer's share,
        #: and Lagrange-combines f+1 — before the definite round decides.
        #: This is BASELINE config 2's workload (SURVEY.md §3.2 hottest
        #: loop) riding the macro epoch.
        self.coin_rounds = coin_rounds
        self.epoch = 0
        self.era = 0
        #: opt-in :class:`~hbbft_tpu.obs.tracer.Tracer`: run_epoch emits the
        #: span hierarchy epoch → subset → rbc/ba phases → per-proposer
        #: RBC/BA instance spans → coin rounds, on top of whatever device
        #: dispatch spans the backend adds.  Environment, not state —
        #: checkpoint() detaches it (utils/snapshot.py contract).
        self.tracer = tracer
        self.batch_listeners: List = []
        self.counters = Counters()
        self.reports: List[EpochReport] = []
        self.churn_reports: List[EpochReport] = []
        any_info = self.netinfos[self.ids[0]]
        self.pk_set = any_info.public_key_set
        self.pk_master = self.pk_set.public_key()
        self.threshold = self.pk_set.threshold()
        # polynomial-commitment evaluations are per-era constants; the
        # round-8 loop would otherwise re-evaluate them N² times per epoch
        # (0.8 s/epoch at N=100, measured)
        self.pk_shares = [
            self.pk_set.public_key_share(i) for i in range(self.n)
        ]
        self.codec = rs_codec(self.n - 2 * self.f, 2 * self.f)

    # -- helpers -------------------------------------------------------------

    def _count_msgs(self, rep: EpochReport, n_messages: int) -> None:
        rep.messages_delivered += n_messages
        self.counters.messages_delivered += n_messages

    def _verify_batch(self, kind: str, items: list) -> List[bool]:
        """Batched backend verification with chunking (device-batch sized)."""
        out: List[bool] = []
        fn = {
            "sig": self.backend.verify_sig_shares,
            "dec": self.backend.verify_dec_shares,
            "ct": self.backend.verify_ciphertexts,
        }[kind]
        for i in range(0, len(items), self.verify_chunk):
            out.extend(fn(items[i : i + self.verify_chunk]))
        return out

    def _verify_deferred(self, kind: str, items: list):
        """Deferred twin of :meth:`_verify_batch` — submits the chunks now
        (behind the backend's bounded in-flight queue) and returns a
        zero-arg resolver, so the NEXT round's item lists assemble while
        this round's checks execute on device (cross-round pipelining;
        kill switch ``HBBFT_TPU_NO_HOSTPIPE=1`` routes around it)."""
        fn = {
            "sig": self.backend.verify_sig_shares_deferred,
            "dec": self.backend.verify_dec_shares_deferred,
            "ct": self.backend.verify_ciphertexts_deferred,
        }[kind]
        resolvers = [
            fn(items[i : i + self.verify_chunk])
            for i in range(0, len(items), self.verify_chunk)
        ]

        def resolve() -> List[bool]:
            out: List[bool] = []
            for r in resolvers:
                out.extend(r())
            return out

        return resolve

    # -- the epoch -----------------------------------------------------------

    def run_epoch(self, contributions: Dict[Any, bytes]) -> Dict[Any, Batch]:
        """Execute one full HoneyBadger epoch; returns per-node Batches.

        ``contributions[node] -> bytes`` is each node's proposed payload
        (what QueueingHoneyBadger would sample from its transaction queue).
        """
        # host-bucket attribution (obs/hostbuckets.py): the epoch region
        # bills counters.host_seconds (wall minus device-fetch-blocked)
        # and every phase below bills its named exclusive slice
        with self.backend.buckets.epoch():
            out = self._run_epoch(contributions)
        # delivery fan-out (traffic subsystem et al.): listeners observe
        # the same per-node Batch map the caller receives.  Deliberately
        # OUTSIDE the epoch region — listener work (commit bookkeeping,
        # mempool drains) is not engine time and must not bill the
        # attributed host_seconds total or its unattributed-share gate.
        for cb in self.batch_listeners:
            cb(out)
        # per-epoch series row (obs/timeseries.py): after the listener
        # fan-out so mempool/controller updates for this epoch are visible
        if self.metrics_log is not None:
            rep = self.reports[-1]
            gate = _critpath.path_from_phase_seconds(
                rep.epoch, rep.phase_seconds or {}, cranks=rep.rounds
            )
            self.metrics_log.snap(
                rep.epoch,
                counters=self.counters.merged_with(self.backend.counters),
                tracer=self.tracer,
                controller_b=(
                    self.batch_size_provider()
                    if self.batch_size_provider is not None
                    else None
                ),
                gate=gate,
            )
        return out

    def _run_epoch(self, contributions: Dict[Any, bytes]) -> Dict[Any, Batch]:
        n, f = self.n, self.f
        rep = EpochReport(epoch=self.epoch)
        tr = self.tracer
        bk = self.backend.buckets
        fast = hostpipe_enabled()
        # phase wall clocks run unconditionally (~4 reads per epoch): the
        # per-phase splits feed EpochReport.phase_seconds, the lockstep
        # critical-path attribution input.  Tracer clock when attached
        # (keeps spans and splits on one timebase), perf_counter otherwise.
        clock = tr.clock if tr is not None else time.perf_counter
        phase_s: Dict[str, float] = {}
        if tr is not None:
            tr.begin(
                f"epoch:{self.epoch}", cat="epoch",
                epoch=self.epoch, n=n, era=self.era,
            )
            tr.begin("subset", cat="subset", epoch=self.epoch)
            tr.begin("rbc", cat="rbc")
        t_phase = clock()

        # ------ round 0: encrypt + RS-encode + Merkle-commit + Value -------
        # honey_badger.py propose(): canonical-encode the contribution
        # (wrapped in DHB's internal envelope in dynamic mode), then
        # threshold-encrypt.
        with bk.region("encode"):
            inners: List[Any] = [
                ("icontrib", bytes(contributions[nid]), [], [])  # match DHB propose()
                if self.dynamic
                else bytes(contributions[nid])
                for nid in self.ids
            ]
            msgs = (
                canonical.encode_batch(inners)
                if fast
                else [canonical.encode(x) for x in inners]
            )
        # all N threshold-encryptions through the backend's batched
        # ladders (same math as pk_master.encrypt per node — ~0.85
        # s/epoch of sequential host EC at N=16, ~5 s at N=100,
        # measured round-5 profile)
        from hbbft_tpu.engine.dkg_batch import batched_encrypt

        master_el = self.pk_master.el
        with bk.region("dispatch"):
            ct_list = batched_encrypt(
                self.backend, [master_el] * n, msgs, self.rng, kind="encrypt"
            )
        for ct in ct_list:
            # receivers must pay their own hash-to-G2 in rounds 7-8
            # (the encryptor-side cache would make them free cache hits)
            if hasattr(ct, "_hash_point"):
                del ct._hash_point
        cts: Dict[Any, Any] = dict(zip(self.ids, ct_list))
        with bk.region("encode"):
            ct_bytes = {nid: cts[nid].to_bytes() for nid in self.ids}

        # broadcast.py broadcast(): frame, shard, commit.
        trees: Dict[Any, MerkleTree] = {}
        shards: Dict[Any, List[bytes]] = {}
        with bk.region("rs_merkle"):
            framed_list = [
                len(ct_bytes[nid]).to_bytes(4, "big") + ct_bytes[nid]
                for nid in self.ids
            ]
            if fast:
                # erasure/hash plane behind the backend seam (PR 19): on
                # TpuBackend the N encodes collapse into one batched
                # GF(2⁸) bit-matmul and the N tree builds into one
                # batched device SHA-256 dispatch; host backends run the
                # identical per-item loops behind the batch entry points
                sh_lists = self.backend.rs_encode_batch(self.codec, framed_list)
                tree_list = self.backend.merkle_build_batch(sh_lists)
                for nid, sh, t in zip(self.ids, sh_lists, tree_list):
                    shards[nid] = sh
                    trees[nid] = t
                    rep.rs_encodes += 1
            else:
                for nid, framed in zip(self.ids, framed_list):
                    sh = self.codec.encode(framed)
                    shards[nid] = sh
                    trees[nid] = MerkleTree(sh)
                    rep.rs_encodes += 1
        tree_size = 1 << _depth(n)  # trees pad to a power of two
        rep.hashes += n * (2 * tree_size - 1)
        self._count_msgs(rep, n * (n - 1))  # Value: point-to-point
        rep.rounds += 1

        # The N² distinct (instance, shard-index) proofs; each is validated
        # many times across receivers/phases — the repetition count is
        # passed down so the batched hasher repeats the WORK without
        # materializing millions of identical Python objects.  Fast path:
        # the proofs never exist as objects at all — array slices of the
        # tree levels feed the C kernel directly (PackedProofs).
        proofs: Optional[List] = None
        packed: Optional[PackedProofs] = None
        with bk.region("rs_merkle"):
            if fast:
                # device=True lifts the native-SHA gate when the packed
                # batch is headed for the device proof walk instead of
                # the C kernel — the kill-switch arm keeps today's choice
                packed = PackedProofs.from_trees(
                    [trees[p] for p in self.ids], n,
                    device=self.backend.device_rs_plane and device_rs_enabled(),
                )
            if packed is None:
                proofs = [trees[p].proof(s) for p in self.ids for s in range(n)]
        n_proofs = n * n

        def _validate_all(reps: int) -> List[bool]:
            if packed is not None:
                return self.backend.merkle_verify_batch(packed, reps=reps)
            return validate_proofs(proofs, n, reps=reps)

        # ------ round 1: validate own Value proof, send Echo ---------------
        # broadcast.py _handle_value → _validate_proof(own index): each
        # receiver checks the one proof addressed to it (N² total).
        with bk.region("rs_merkle"):
            ok = _validate_all(1)
        _require(all(ok), "array engine: proposer produced an invalid proof")
        rep.proofs_validated += n_proofs
        rep.hashes += n_proofs * (_depth(n) + 1)
        self._count_msgs(rep, n * n * (n - 1))  # Echo: Target.all per node
        rep.rounds += 1

        # ------ round 2: validate N echoes each, N−f quorum → Ready --------
        # broadcast.py _handle_echo: every receiver checks every sender's
        # shard proof (the O(N³) hash hot loop, batched here: N² distinct
        # proofs × N receivers each).
        reps = 1 if self.dedup_verifies else n
        with bk.region("rs_merkle"):
            ok = _validate_all(reps)
        _require(all(ok), "array engine: honest echo failed validation")
        rep.proofs_validated += n_proofs * reps
        rep.hashes += n_proofs * reps * (_depth(n) + 1)
        # Echo count n ≥ N−f for every (instance, receiver): send Ready.
        _require(n >= n - f, "array engine: Echo quorum short")
        self._count_msgs(rep, n * n * (n - 1))  # Ready: Target.all
        rep.rounds += 1

        # ------ round 3: Ready quorum (2f+1) → reconstruct + re-commit -----
        # broadcast.py _try_decode: all N shards present at every receiver;
        # reconstruct and re-verify the Merkle commitment.
        values: Dict[Any, bytes] = {}
        reps = 1 if self.dedup_verifies else n
        full_shards: Dict[Any, List[bytes]] = {}
        with bk.region("rs_merkle"):
            if fast:
                # every receiver performs this identical all-present
                # reconstruction — ONE batched pass through the backend
                # plane (the all-present case is zero GF math on every
                # backend), replicated in ACCOUNTING only
                full_list = self.backend.rs_reconstruct_batch(
                    self.codec, [list(shards[p]) for p in self.ids]
                )
            else:
                full_list = []
                for p in self.ids:
                    for _ in range(reps):
                        full = self.codec.reconstruct(list(shards[p]))
                    full_list.append(full)
            for p, full in zip(self.ids, full_list):
                full_shards[p] = full
                framed = b"".join(full[: self.codec.k])
                length = int.from_bytes(framed[:4], "big")
                values[p] = framed[4 : 4 + length]
                rep.rs_reconstructs += reps
                rep.hashes += reps * (2 * tree_size - 1)
            # ... and the Merkle re-commit of the reconstructed shard
            # vector, batched across instances through the C hash kernel.
            roots = _roots_batch(
                [full_shards[p] for p in self.ids], reps
            )
        for p, root in zip(self.ids, roots):
            _require(
                root == trees[p].root_hash,
                "array engine: reconstructed root mismatch",
            )
        for p in self.ids:
            _require(values[p] == ct_bytes[p], "RBC value mismatch")
        t_now = clock()
        phase_s["rbc"] = t_now - t_phase
        if tr is not None:
            # per-proposer RBC instance spans: in the lockstep schedule all
            # N instances cover the same wall interval, one per track
            for idx, nid in enumerate(self.ids):
                tr.complete(
                    f"rbc:{idx}", t_phase, t_now, cat="rbc",
                    track=f"rbc/{idx}", proposer=repr(nid),
                )
            tr.end()  # rbc
            tr.begin("ba", cat="ba")
        t_phase = t_now
        # subset.py _on_broadcast_output: input true to BA_p. BA round 0:
        # sbv_broadcast.py send_bval → BVal(true) to all.
        self._count_msgs(rep, n * n * (n - 1))  # BVal
        rep.rounds += 1

        # ------ round 4: BVal threshold (2f+1) → bin_values, Aux -----------
        _require(n >= 2 * f + 1, "array engine: BVal threshold short")
        self._count_msgs(rep, n * n * (n - 1))  # Aux
        rep.rounds += 1

        # ------ round 5: Aux quorum (N−f) → SBV output {true}, Conf --------
        self._count_msgs(rep, n * n * (n - 1))  # Conf
        rep.rounds += 1

        # ------ round 6: Conf quorum → coin ---------------------------------
        # binary_agreement.py: with unanimous inputs conf_values = {true}
        # is definite and equals the round-0 fixed coin → decide(true)
        # immediately, no threshold-sign traffic (coin_rounds == 0).  With
        # coin_rounds=R the engine executes R REAL coin rounds first (the
        # split-input schedule where conf_values stays {true, false}).
        t_coin = clock()
        for r in range(self.coin_rounds):
            self._coin_round(rep, round_no=r)
        if self.coin_rounds:
            phase_s["coin"] = clock() - t_coin
        if tr is not None:
            # the deciding round consults the FIXED coin (zero-duration
            # span: no threshold-sign traffic, but the consult is a real
            # protocol event every BA instance performs)
            tr.begin(f"coin_round:{self.coin_rounds}", cat="coin", fixed=True)
            tr.end()
        self._count_msgs(rep, n * n * (n - 1))  # Term
        rep.rounds += 1
        t_now = clock()
        phase_s["ba"] = (t_now - t_phase) - phase_s.get("coin", 0.0)
        if tr is not None:
            for idx, nid in enumerate(self.ids):
                tr.complete(
                    f"ba:{idx}", t_phase, t_now, cat="ba",
                    track=f"ba/{idx}", proposer=repr(nid),
                )
            tr.end()  # ba
            tr.end()  # subset
            tr.begin("decrypt", cat="decrypt", epoch=self.epoch)
        t_phase = t_now

        # ------ round 7: ciphertext validation + decryption shares ---------
        # honey_badger.py: SubsetOutput::Contribution(p, ct) → spawn
        # ThresholdDecrypt(p); set_ciphertext defers a verify_ciphertext
        # item per (receiver, proposer).
        reps = 1 if self.dedup_verifies else n
        with bk.region("assemble"):
            ct_items = [cts[p] for p in self.ids for _ in range(reps)]
        ct_resolve = None
        if fast:
            # deferred: the ciphertext pairings execute behind the queue
            # while the decrypt-share round assembles below
            with bk.region("dispatch"):
                ct_resolve = self._verify_deferred("ct", ct_items)
        else:
            with bk.region("dispatch"):
                ok = self._verify_batch("ct", ct_items)
            _require(
                all(ok), "array engine: honest ciphertext failed validation"
            )
        rep.ciphertexts_verified += len(ct_items)
        # threshold_decrypt.py start_decryption: every node multicasts its
        # decryption share for every accepted proposer — all N² scalar
        # mults through the backend's batched ladder (one device dispatch
        # on TpuBackend).
        with bk.region("assemble"):
            sk_shares = [self.netinfos[s].secret_key_share for s in self.ids]
            gen_items = [(sk, cts[p]) for p in self.ids for sk in sk_shares]
        with bk.region("dispatch"):
            gen_out = self.backend.decrypt_shares_batch(gen_items)
        if ct_resolve is not None:
            # resolved AFTER the decrypt dispatches that overlapped it; a
            # bad ciphertext still raises before any Batch is emitted
            with bk.region("dispatch"):
                ok = ct_resolve()
            _require(
                all(ok), "array engine: honest ciphertext failed validation"
            )
        dec_shares: Optional[Dict[Any, Dict[int, Any]]] = None
        if not fast:
            # legacy scatter: flat ladder output → per-(proposer, sender)
            # dicts via a pos cursor.  The fast path never materializes
            # them — round 8 indexes gen_out[p_idx*n + s_idx] directly.
            with bk.region("scatter"):
                dec_shares = {}
                pos = 0
                for p in self.ids:
                    per_sender: Dict[int, Any] = {}
                    for s_idx in range(n):
                        per_sender[s_idx] = gen_out[pos]
                        pos += 1
                    dec_shares[p] = per_sender
        self._count_msgs(rep, n * n * (n - 1))  # dec shares: Target.all
        rep.rounds += 1

        # ------ round 8: verify all shares, combine, emit batches ----------
        # threshold_decrypt.py handle_message: every receiver verifies every
        # other sender's share (own share is trusted) — the O(N³) pairing
        # hot loop, one batched backend dispatch.
        reps = 1 if self.dedup_verifies else n - 1
        with bk.region("assemble"):
            if fast:
                distinct = [
                    (self.pk_shares[s_idx], cts[p], gen_out[p_idx * n + s_idx])
                    for p_idx, p in enumerate(self.ids)
                    for s_idx in range(n)
                ]
                items = [it for it in distinct for _ in range(reps)]
            else:
                items = []
                for p in self.ids:
                    for s_idx in range(n):
                        pk_share = self.pk_shares[s_idx]
                        item = (pk_share, cts[p], dec_shares[p][s_idx])
                        items.extend([item] * reps)
        dec_resolve = None
        if fast:
            with bk.region("dispatch"):
                dec_resolve = self._verify_deferred("dec", items)
        else:
            with bk.region("dispatch"):
                ok = self._verify_batch("dec", items)
            _require(
                all(ok), "array engine: honest decryption share rejected"
            )
        rep.dec_shares_verified += len(items)

        # _try_combine: threshold+1 lowest-indexed verified shares.  Every
        # receiver combines independently — all N² combines go through the
        # backend's batched API (one device dispatch on TpuBackend).  Fast
        # path: combines are dispatched while the share verification above
        # is still in flight (speculative under the honest schedule — a
        # rejected share raises below, before batch emission).
        reps = 1 if self.dedup_verifies else n
        k = self.threshold + 1
        with bk.region("assemble"):
            combine_items = []
            for p_idx, p in enumerate(self.ids):
                if fast:
                    chosen = {
                        i: gen_out[p_idx * n + i] for i in range(k)
                    }
                else:
                    chosen = {i: dec_shares[p][i] for i in range(k)}
                combine_items.extend([(chosen, cts[p])] * reps)
        plains: List[bytes] = []
        with bk.region("dispatch"):
            for i in range(0, len(combine_items), self.verify_chunk):
                plains.extend(
                    self.backend.combine_dec_shares_batch(
                        self.pk_set, combine_items[i : i + self.verify_chunk]
                    )
                )
        rep.combines += len(combine_items)
        if dec_resolve is not None:
            with bk.region("dispatch"):
                ok = dec_resolve()
            _require(
                all(ok), "array engine: honest decryption share rejected"
            )
        plain: Dict[Any, bytes] = {}
        with bk.region("scatter"):
            for j, p in enumerate(self.ids):
                pt = plains[j * reps]
                _require(pt is not None, "array engine: combine failed")
                plain[p] = pt
        # honey_badger.py batch emission: canonical-decode each plaintext;
        # in dynamic mode additionally unwrap DHB's internal envelope
        # (dynamic_honey_badger.py _on_hb_batch — its batched per-batch
        # signature verification runs over the votes/key-gen lists, which
        # are empty in the no-churn steady state).
        decoded: Dict[Any, bytes] = {}
        with bk.region("encode"):
            plain_list = [plain[p] for p in self.ids]
            trees_out = (
                canonical.decode_batch(plain_list)
                if fast
                else [canonical.decode(b) for b in plain_list]
            )
            for p, tree in zip(self.ids, trees_out):
                if self.dynamic:
                    tag, user, votes, kg = tree
                    _require(
                        tag == "icontrib" and votes == [] and kg == [],
                        "array engine: DHB envelope mismatch",
                    )
                    tree = user
                _require(tree == bytes(contributions[p]), "decrypt mismatch")
                decoded[p] = tree
        rep.rounds += 1
        phase_s["decrypt"] = clock() - t_phase
        rep.phase_seconds = phase_s
        if tr is not None:
            tr.end()  # decrypt
            tr.end()  # epoch

        batch = Batch(epoch=self.epoch, contributions=decoded)
        self.epoch += 1
        self.reports.append(rep)
        self.counters.cranks += rep.rounds
        return {nid: batch for nid in self.ids}

    def _coin_round(self, rep: EpochReport, round_no: int) -> None:
        """One real common-coin round across all N BA instances
        (threshold_sign.py sign → verify → combine → parity, batched
        network-wide; SURVEY.md §3.2 marks the share-verify as the
        HOTTEST loop).

        Per BA round the full SBV exchange repeats (BVal, Aux, Conf) and
        then every node broadcasts its coin share — 4×N²(N−1) messages.
        Crypto, batched through the backend seam:

        * sign:    N shares per instance (one x_s·H2(doc_p) G2 ladder each)
        * verify:  every receiver checks every OTHER sender's share
                   (N·(N−1) per instance; dedup mode: one representative)
        * combine: every receiver Lagrange-combines f+1 verified shares
                   (N per instance; dedup: 1) and takes sig.parity()

        All receivers must derive the SAME bit — checked per instance.
        Fast path (``hostpipe_enabled``): flat index arithmetic replaces
        the per-instance share dicts, and the combine assembly overlaps
        the deferred share verification.
        """
        tr = self.tracer
        bk = self.backend.buckets
        fast = hostpipe_enabled()
        if tr is not None:
            tr.begin(f"coin_round:{round_no}", cat="coin", round=round_no)
        n = self.n
        with bk.region("encode"):
            docs = [
                canonical.encode(("coin", self.epoch, p_idx, round_no))
                for p_idx in range(n)
            ]
        # SBV re-exchange for this BA round, then the share broadcast.
        self._count_msgs(rep, 4 * n * n * (n - 1))  # BVal, Aux, Conf, share
        with bk.region("assemble"):
            sk_shares = [self.netinfos[s].secret_key_share for s in self.ids]
            sign_items = [
                (sk, docs[p_idx]) for p_idx in range(n) for sk in sk_shares
            ]
        with bk.region("dispatch"):
            shares_flat = self.backend.sign_shares_batch(sign_items)
        rep.coin_signs += len(sign_items)
        shares: Optional[List[Dict[int, Any]]] = None
        if not fast:
            with bk.region("scatter"):
                shares = []
                pos = 0
                for p_idx in range(n):
                    shares.append(
                        {s_idx: shares_flat[pos + s_idx] for s_idx in range(n)}
                    )
                    pos += n
        # per-receiver share verification (own share trusted).
        reps = 1 if self.dedup_verifies else n - 1
        with bk.region("assemble"):
            if fast:
                distinct = [
                    (self.pk_shares[s_idx], docs[p_idx],
                     shares_flat[p_idx * n + s_idx])
                    for p_idx in range(n)
                    for s_idx in range(n)
                ]
                items = [it for it in distinct for _ in range(reps)]
            else:
                items = []
                for p_idx in range(n):
                    for s_idx in range(n):
                        item = (
                            self.pk_shares[s_idx],
                            docs[p_idx],
                            shares[p_idx][s_idx],
                        )
                        items.extend([item] * reps)
        sig_resolve = None
        if fast:
            with bk.region("dispatch"):
                sig_resolve = self._verify_deferred("sig", items)
        else:
            with bk.region("dispatch"):
                ok = self._verify_batch("sig", items)
            _require(all(ok), "array engine: honest coin share rejected")
        rep.sig_shares_verified += len(items)
        # per-receiver combine: receiver i uses the f+1 verified shares
        # with the lowest indices starting at its own (subsets differ by
        # receiver; the combined signature must not).
        k = self.threshold + 1
        with bk.region("assemble"):
            combine_items = []
            per_instance_slots: List[List[int]] = []
            for p_idx in range(n):
                slots = []
                for recv in range(1 if self.dedup_verifies else n):
                    if fast:
                        chosen = {
                            (recv + j) % n: shares_flat[
                                p_idx * n + (recv + j) % n
                            ]
                            for j in range(k)
                        }
                    else:
                        chosen = {
                            (recv + j) % n: shares[p_idx][(recv + j) % n]
                            for j in range(k)
                        }
                    slots.append(len(combine_items))
                    combine_items.append((chosen, None))
                per_instance_slots.append(slots)
        sigs = []
        with bk.region("dispatch"):
            for i in range(0, len(combine_items), self.verify_chunk):
                sigs.extend(
                    self.backend.combine_sig_shares_batch(
                        self.pk_set, combine_items[i : i + self.verify_chunk]
                    )
                )
        rep.sig_combines += len(combine_items)
        if sig_resolve is not None:
            with bk.region("dispatch"):
                ok = sig_resolve()
            _require(all(ok), "array engine: honest coin share rejected")
        with bk.region("scatter"):
            for p_idx in range(n):
                bits = {
                    sigs[slot].parity()
                    for slot in per_instance_slots[p_idx]
                }
                _require(
                    len(bits) == 1, "array engine: coin bit disagreement"
                )
        rep.coin_rounds += 1
        rep.rounds += 1
        if tr is not None:
            tr.end()  # coin_round

    def era_change(self) -> EpochReport:
        """Mid-run validator turnover: vote → DKG → new era (SURVEY.md
        §3.4), executed lockstep between epochs.

        Models DynamicHoneyBadger's churn machinery at array-engine scale:

        1. **Vote**: every node signs a Change vote with its per-node key
           (votes.py); every receiver verifies every vote — one batched
           ``verify_signatures`` call (N·(N−1) checks; dedup: N).
        2. **DKG**: all N nodes run SyncKeyGen — every Part handled by
           every node (N² handle_part, each decrypting + checking a
           committed row), every Ack by every node (N³ value checks; this
           O(N³) host cost is the real price of an era change and is what
           the churn bench row measures).
        3. **Era turnover**: each node's generate() must agree on the new
           PublicKeySet; NetworkInfo is rebuilt with the new key shares,
           era += 1.  The NEXT run_epoch's decrypt-equality checks prove
           consensus still holds under the new keys.

        Returns the work report (also appended to ``churn_reports``).
        """
        with self.backend.buckets.epoch():
            return self._era_change()

    def _era_change(self) -> EpochReport:
        n, f = self.n, self.f
        rep = EpochReport(epoch=self.epoch)
        bk = self.backend.buckets
        g = self.backend.group

        # 1) signed votes, batch-verified per receiver (ride inside one
        # epoch's contributions, so no extra message rounds).
        with bk.region("encode"):
            vote_doc = canonical.encode(("vote", self.era, "rotate-keys"))
        vote_sigs = {
            nid: self.netinfos[nid].secret_key.sign(vote_doc)
            for nid in self.ids
        }
        reps = 1 if self.dedup_verifies else n - 1
        pub_keys = self.netinfos[self.ids[0]].public_key_map()
        with bk.region("assemble"):
            vote_items = [
                (pub_keys[nid], vote_doc, vote_sigs[nid])
                for nid in self.ids
                for _ in range(reps)
            ]
        with bk.region("dispatch"):
            ok = self.backend.verify_signatures(vote_items)
        _require(all(ok), "array engine: honest vote rejected")
        rep.votes_verified += len(vote_items)

        # 2) full SyncKeyGen among all N (lockstep Part then Ack phases).
        #
        # Two equivalent engines for the same protocol math:
        #   batched (default) — engine/dkg_batch.py: device-batched
        #     ladders + batched pairing checks + RLC-aggregated commitment
        #     checks.  The per-node path is O(N³) SEQUENTIAL host crypto
        #     (measured round 5: a multi-day job at N=100 — each ack is an
        #     individually pairing-verified ciphertext in pure Python).
        #   pernode — the original lockstep SyncKeyGen objects, kept as
        #     the golden cross-check (HBBFT_TPU_DKG=pernode; equivalence
        #     asserted in tests/test_dkg_batch.py).
        dkg_mode = os.environ.get("HBBFT_TPU_DKG", "batched")
        if dkg_mode == "batched":
            from hbbft_tpu.engine.dkg_batch import batched_era_dkg

            self._count_msgs(rep, n * (n - 1))  # Part: Target.All
            self._count_msgs(rep, n * n * (n - 1))  # Ack: Target.All
            with bk.region("dispatch"):
                first, shares, kstats = batched_era_dkg(
                    self.backend,
                    self.ids,
                    {nid: self.netinfos[nid].secret_key.x for nid in self.ids},
                    {nid: pub_keys[nid].el for nid in self.ids},
                    f,
                    self.rng,
                )
            rep.kg_parts_handled += kstats.parts_handled
            rep.kg_acks_handled += kstats.acks_handled
            rep.ciphertexts_verified += kstats.ciphertexts_verified
            rep.hashes += kstats.hashes_g2
            rep.rounds += 2
            results = {nid: (first, shares[nid]) for nid in self.ids}
        else:
            from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen

            kgs: Dict[Any, SyncKeyGen] = {}
            parts = {}
            for nid in self.ids:
                kg, part = SyncKeyGen.new(
                    nid, self.netinfos[nid].secret_key, pub_keys, f, self.rng, g
                )
                kgs[nid] = kg
                parts[nid] = part
            self._count_msgs(rep, n * (n - 1))  # Part: Target.All
            acks = []
            for proposer in self.ids:
                for nid in self.ids:
                    out = kgs[nid].handle_part(
                        proposer, parts[proposer], self.rng
                    )
                    _require(out.fault is None, str(out.fault))
                    if out.ack is not None:
                        acks.append((nid, out.ack))
                    rep.kg_parts_handled += 1
            self._count_msgs(rep, n * n * (n - 1))  # Ack: Target.All per part
            for acker, ack in acks:
                for nid in self.ids:
                    out = kgs[nid].handle_ack(acker, ack)
                    _require(out.fault is None, str(out.fault))
                    rep.kg_acks_handled += 1
            rep.rounds += 2
            results = {nid: kgs[nid].generate() for nid in self.ids}

        # 3) era turnover: everyone must derive the same key set.
        first = results[self.ids[0]][0]
        _require(
            all(results[nid][0] == first for nid in self.ids),
            "array engine: DKG public key set disagreement",
        )
        secret_keys = {nid: self.netinfos[nid].secret_key for nid in self.ids}
        self.netinfos = {
            nid: NetworkInfo(
                our_id=nid,
                secret_key_share=results[nid][1],
                public_key_set=first,
                secret_key=secret_keys[nid],
                public_keys=pub_keys,
            )
            for nid in self.ids
        }
        self.pk_set = first
        self.pk_master = first.public_key()
        self.threshold = first.threshold()
        self.pk_shares = [first.public_key_share(i) for i in range(n)]
        self.era += 1
        # era-keyed staging invalidation: device backends drop the limb
        # rows staged for the dead era's key material (ops/staging.py)
        self.backend.new_era(self.era)
        self.churn_reports.append(rep)
        return rep

    def checkpoint(self) -> bytes:
        """Whole-engine state (keys, era, epoch, RNG, reports) to canonical
        snapshot bytes — the soak configs (BASELINE 3/5 at 1k epochs) are
        resumable mid-run.  The crypto backend is environment, not state
        (utils/snapshot.py contract) — and so are the tracer and the
        traffic hooks (batch listeners / contribution source hold live
        callables), dropped by save_node via ``_SNAPSHOT_ENV_ATTRS``."""
        from hbbft_tpu.utils.snapshot import save_node

        return save_node(self)

    @classmethod
    def restore(cls, data: bytes, backend: CryptoBackend) -> "ArrayHoneyBadgerNet":
        """Rebuild from :meth:`checkpoint` bytes; resumes byte-identically
        (the RNG state round-trips, so epoch E+1 after restore equals
        epoch E+1 of the uninterrupted run)."""
        from hbbft_tpu.utils.snapshot import SnapshotError, load_node

        net = load_node(data, backend)
        if not isinstance(net, cls):
            raise SnapshotError(
                f"snapshot holds {type(net).__name__}, not {cls.__name__} "
                "(object-engine snapshots resume via Simulation.from_checkpoint)"
            )
        return net

    def run_epochs(
        self,
        k: int,
        payload_size: int = 128,
        churn_at: Optional[Sequence[int]] = None,
    ) -> List[Dict[Any, Batch]]:
        """Run k epochs with synthetic per-node contributions (or, when a
        ``contribution_source`` is installed — the traffic subsystem's
        sourcing hook — with whatever it supplies per epoch); an
        ``era_change()`` fires before each epoch index in ``churn_at``."""
        churn = set(churn_at or ())
        out = []
        for i in range(k):
            if i in churn:
                self.era_change()
            if self.contribution_source is not None:
                contribs = self.contribution_source(self.epoch)
            else:
                contribs = {
                    nid: self.rng.getrandbits(8 * payload_size).to_bytes(
                        payload_size, "big"
                    )
                    for nid in self.ids
                }
            out.append(self.run_epoch(contribs))
        return out


def _roots_batch(shard_lists: List[List[bytes]], reps: int) -> List[bytes]:
    """Merkle roots of many shard vectors, built ``reps`` times each —
    C batch kernel when available, python MerkleTree otherwise."""
    import numpy as np

    from hbbft_tpu import native

    n_leaves = len(shard_lists[0])
    leaf_len = len(shard_lists[0][0])
    uniform = all(
        len(sl) == n_leaves and all(len(s) == leaf_len for s in sl)
        for sl in shard_lists
    )
    size = 1 << _depth(n_leaves)
    if uniform and size <= 256 and leaf_len + 1 <= 4096:
        leaves = np.frombuffer(
            b"".join(b"".join(sl) for sl in shard_lists), dtype=np.uint8
        ).reshape(len(shard_lists), n_leaves, leaf_len)
        roots = native.merkle_root_batch(leaves, size, reps)
        if roots is not None:
            return [roots[i].tobytes() for i in range(len(shard_lists))]
    out = []
    for sl in shard_lists:
        for _ in range(reps):
            tree = MerkleTree(sl)
        out.append(tree.root_hash)
    return out
