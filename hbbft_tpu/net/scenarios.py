"""Scenario harness: the attack × network-schedule matrix over VirtualNet.

ROADMAP item 4 ("as many scenarios as you can imagine"): tier-1 proves
honest-path bit-identity; the CCS 2016 headline claim — liveness under a
fully asynchronous adversary controlling f nodes *and* the network — needs
the cross product of misbehaviour (net/adversary.py attack library) and
network conditions (net/virtual_net.NetSchedule).  This module is the
registry + runner for that matrix:

* :data:`ATTACKS` — named attack factories with the fault kinds each one
  provably plants (drawn from ``core.fault_log.FAULT_KINDS``; an
  unregistered expectation breaks lint and tests together).
* :data:`SCHEDULES` — named network-condition factories (uniform / LAN /
  WAN / partition-and-heal, plus a model-violating lossy shape kept out
  of the liveness matrix by its ``lossy`` flag).
* :func:`run_scenario` — one cell: N nodes, f=⌊(N−1)/3⌋ faulty, a full
  HoneyBadger epoch loop; returns a :class:`ScenarioResult` with the
  per-cell verdicts the matrix asserts: every honest node committed
  identical Batches, every injected misbehaviour landed in the fault log
  with the expected kind against a faulty node, no fault was ever
  attributed to an honest node, and a stalled cell carries the
  why-stalled report naming the attack.

Determinism: a cell is a pure function of (attack, schedule, n, seed) —
all entropy flows through the net's single seeded rng, so replaying a
seed reproduces the fault log and the batch digest bit-for-bit
(tests/test_scenarios.py pins this).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from hbbft_tpu.core.fault_log import all_fault_kinds
from hbbft_tpu.net.adversary import (
    Adversary,
    CraftedShareAdversary,
    EquivocatingAdversary,
    LaggardAdversary,
    NullAdversary,
    ReplayAdversary,
    WithholdingAdversary,
)
from hbbft_tpu.net.virtual_net import (
    CrankError,
    NetBuilder,
    NetSchedule,
    Partition,
    VirtualNet,
)
from hbbft_tpu.obs import critpath as _critpath
from hbbft_tpu.obs.flight import FlightRecorder
from hbbft_tpu.obs.timeseries import MetricsLog, snap_net


@dataclass(frozen=True)
class Attack:
    """One named attack: a per-run adversary factory plus the fault kinds
    the attack provably plants (must be registered in FAULT_KINDS)."""

    name: str
    make: Callable[[int], Adversary]  # n -> fresh adversary
    expected_faults: Tuple[str, ...] = ()
    description: str = ""


@dataclass(frozen=True)
class ScheduleSpec:
    """One named network condition; ``make(n)`` returns a fresh
    NetSchedule (or None for instant delivery).  ``lossy`` marks
    schedules that violate the eventual-delivery model — they exercise
    the drop machinery and the stall reporter, not the liveness matrix."""

    name: str
    make: Callable[[int], Optional[NetSchedule]]
    lossy: bool = False
    description: str = ""


# ---------------------------------------------------------------------------
# Registries
# ---------------------------------------------------------------------------

_ATTACK_LIST: Tuple[Attack, ...] = (
    Attack(
        "passive",
        lambda n: NullAdversary(),
        description="control row: no tampering",
    ),
    Attack(
        "equivocate",
        lambda n: EquivocatingAdversary(),
        expected_faults=("broadcast:conflicting_values",),
        description="conflicting RBC Values per recipient",
    ),
    Attack(
        "withhold_echo",
        lambda n: WithholdingAdversary(kinds=("echo",)),
        description="faulty nodes send no Echo (quorum at exactly N-f)",
    ),
    Attack(
        "withhold_ready",
        lambda n: WithholdingAdversary(kinds=("ready",)),
        description="faulty nodes send no Ready",
    ),
    Attack(
        "withhold_shares",
        lambda n: WithholdingAdversary(kinds=("sig_share", "dec_share")),
        description="faulty nodes withhold threshold shares",
    ),
    Attack(
        "crafted_shares",
        lambda n: CraftedShareAdversary(rate=0.5),
        expected_faults=("threshold_decrypt:invalid_share",),
        description="well-typed invalid threshold shares at 50% rate",
    ),
    Attack(
        "replay_flood",
        lambda n: ReplayAdversary(copies=3),
        expected_faults=(
            "broadcast:multiple_echos",
            "broadcast:multiple_readys",
        ),
        description="3x duplicate flood of all faulty traffic",
    ),
    Attack(
        "laggard",
        lambda n: LaggardAdversary(lag_cranks=60 * n * n),
        description="one honest node lags, then catches up",
    ),
)

ATTACKS: Dict[str, Attack] = {a.name: a for a in _ATTACK_LIST}


def _wan_latency(sender: Any, to: Any) -> int:
    """Deterministic heterogeneous per-link base latency (1..8 cranks):
    a fixed function of the directed link, not of arrival order."""
    s = sender if isinstance(sender, int) else len(repr(sender))
    t = to if isinstance(to, int) else len(repr(to))
    return 1 + (3 * s + 5 * t) % 8


_SCHEDULE_LIST: Tuple[ScheduleSpec, ...] = (
    ScheduleSpec(
        "uniform",
        lambda n: None,
        description="instant delivery (legacy behavior)",
    ),
    ScheduleSpec(
        "lan",
        lambda n: NetSchedule(name="lan", latency=1, jitter=2),
        description="small uniform latency + jitter",
    ),
    ScheduleSpec(
        "wan",
        lambda n: NetSchedule(name="wan", link_latency=_wan_latency, jitter=3),
        description="heterogeneous per-link latency + jitter",
    ),
    ScheduleSpec(
        "partition_heal",
        lambda n: NetSchedule(
            name="partition_heal",
            partitions=(
                Partition(
                    start=20,
                    end=20 + 30 * n * n,
                    groups=(frozenset(range(n // 2)),),
                ),
            ),
        ),
        description="halves isolated early, healed after 30·N² cranks",
    ),
    ScheduleSpec(
        "lossy",
        lambda n: NetSchedule(name="lossy", drop=0.05, latency=1, jitter=1),
        lossy=True,
        description="5% i.i.d. message loss (violates eventual delivery; "
        "exercises drop accounting and the stall reporter)",
    ),
)

SCHEDULES: Dict[str, ScheduleSpec] = {s.name: s for s in _SCHEDULE_LIST}

#: the liveness matrix: every attack × every eventual-delivery schedule
MATRIX_ATTACKS: Tuple[str, ...] = tuple(
    a.name for a in _ATTACK_LIST if a.name != "passive"
)
MATRIX_SCHEDULES: Tuple[str, ...] = tuple(
    s.name for s in _SCHEDULE_LIST if not s.lossy
)
#: the full verdict matrix: lossy schedules ride too, gated on the
#: bounded-degradation contract (see run_scenario) instead of liveness
MATRIX_SCHEDULES_ALL: Tuple[str, ...] = tuple(s.name for s in _SCHEDULE_LIST)


def _check_registry() -> None:
    known = all_fault_kinds()
    for a in _ATTACK_LIST:
        unknown = [k for k in a.expected_faults if k not in known]
        if unknown:
            raise ValueError(
                f"attack {a.name!r} expects unregistered fault kinds {unknown}"
            )


_check_registry()


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


@dataclass
class ScenarioResult:
    """Verdicts + evidence for one matrix cell."""

    attack: str
    schedule: str
    n: int
    f: int
    seed: int
    ok: bool = False
    #: all honest nodes committed identical batch sequences
    batches_identical: bool = False
    #: identical on the COMMON committed prefix (a stalled cell's honest
    #: nodes may have unequal lengths; safety is about what committed)
    prefix_identical: bool = False
    epochs_committed: int = 0
    #: expected fault kinds that never landed against a faulty node
    missing_expected: List[str] = field(default_factory=list)
    #: (observer, accused, kind) for faults attributed to HONEST nodes —
    #: must be empty: correct nodes never accuse each other
    misattributed: List[Tuple[str, str, str]] = field(default_factory=list)
    #: observed fault kind -> count (honest observers only)
    fault_kinds: Dict[str, int] = field(default_factory=dict)
    #: sorted (observer, accused, kind) triples — the replay-determinism
    #: fingerprint next to batch_digest
    fault_log: List[Tuple[str, str, str]] = field(default_factory=list)
    #: sha256 over the canonical repr of every honest node's batches
    batch_digest: str = ""
    cranks: int = 0
    messages_delivered: int = 0
    schedule_dropped: int = 0
    schedule_delayed: int = 0
    error: Optional[str] = None
    #: why-stalled report when the cell starved (CrankError.report)
    why: Optional[Dict[str, Any]] = None
    #: the cell was judged under the bounded-degradation contract (lossy
    #: schedules violate eventual delivery, so liveness isn't gated; the
    #: cell passes iff whatever committed is identical, nothing was
    #: misattributed, and a stall names its cause)
    bounded: bool = False
    #: flight-recorder forensics bundle (obs/flight.py), present when the
    #: cell failed and obs was on — tools/scenario_matrix.py --fail-dir
    #: writes it next to the row dump
    forensics: Optional[Dict[str, Any]] = None

    def row(self) -> Dict[str, Any]:
        """Flat JSON-friendly form for tools/scenario_matrix.py."""
        return {
            "attack": self.attack,
            "schedule": self.schedule,
            "n": self.n,
            "f": self.f,
            "seed": self.seed,
            "ok": self.ok,
            "bounded": self.bounded,
            "epochs": self.epochs_committed,
            "fault_kinds": dict(sorted(self.fault_kinds.items())),
            "missing_expected": self.missing_expected,
            "misattributed": self.misattributed,
            "batch_digest": self.batch_digest,
            "cranks": self.cranks,
            "messages_delivered": self.messages_delivered,
            "schedule_dropped": self.schedule_dropped,
            "schedule_delayed": self.schedule_delayed,
            "error": self.error,
        }


def build_scenario_net(
    attack: Attack,
    schedule: ScheduleSpec,
    n: int,
    f: Optional[int] = None,
    seed: int = 0,
    backend=None,
    scheduler: str = "random",
    crank_limit: int = 5_000_000,
) -> VirtualNet:
    """One cell's VirtualNet: HoneyBadger at N nodes / f faulty under the
    attack's adversary and the schedule's network conditions."""
    from hbbft_tpu.protocols.honey_badger import HoneyBadger

    if f is None:
        f = (n - 1) // 3
    builder = (
        NetBuilder(range(n))
        .num_faulty(f)
        .adversary(attack.make(n))
        .schedule(schedule.make(n))
        .scenario(f"{attack.name}x{schedule.name}@n{n}")
        .scheduler(scheduler)
        .crank_limit(crank_limit)
        .using(
            lambda ni, be: HoneyBadger(ni, be, session_id=b"scenario-matrix")
        )
    )
    if backend is not None:
        builder = builder.backend(backend)
    return builder.build(seed=seed)


def _collect(result: ScenarioResult, net: VirtualNet, epochs: int) -> None:
    """Fill the result's evidence fields from a (possibly partial) run."""
    correct = net.correct_nodes()
    faulty_ids = {node.id for node in net.faulty_nodes()}
    triples = sorted(
        (repr(node.id), repr(fa.node_id), fa.kind)
        for node in correct
        for fa in node.faults_observed
    )
    result.fault_log = triples
    kinds: Dict[str, int] = {}
    for _, _, kind in triples:
        kinds[kind] = kinds.get(kind, 0) + 1
    result.fault_kinds = kinds
    result.misattributed = [
        t
        for node in correct
        for fa in node.faults_observed
        if fa.node_id not in faulty_ids
        for t in ((repr(node.id), repr(fa.node_id), fa.kind),)
    ]
    common = min((len(node.outputs) for node in correct), default=0)
    result.epochs_committed = common
    seqs = [node.outputs[:epochs] for node in correct]
    result.batches_identical = bool(seqs) and all(s == seqs[0] for s in seqs)
    prefix = [node.outputs[:common] for node in correct]
    result.prefix_identical = bool(prefix) and all(
        s == prefix[0] for s in prefix
    )
    h = hashlib.sha256()
    for b in seqs[0] if seqs else ():
        h.update(repr((b.epoch, sorted(b.contributions.items(), key=repr))).encode())
    result.batch_digest = h.hexdigest()
    result.cranks = net.cranks
    result.messages_delivered = net.messages_delivered
    result.schedule_dropped = net.counters.schedule_dropped
    result.schedule_delayed = net.counters.schedule_delayed


def run_scenario(
    attack_name: str,
    schedule_name: str,
    n: int,
    f: Optional[int] = None,
    seed: int = 0,
    epochs: int = 1,
    backend=None,
    scheduler: str = "random",
    crank_limit: int = 5_000_000,
    obs: bool = True,
) -> ScenarioResult:
    """Run one matrix cell; never raises — a starved cell comes back with
    ``ok=False`` and the why-stalled report naming the attack.  With
    ``obs=True`` a flight recorder rides along and a failed cell carries
    its forensics bundle on ``result.forensics``."""
    attack = ATTACKS[attack_name]
    schedule = SCHEDULES[schedule_name]
    if f is None:
        f = (n - 1) // 3
    result = ScenarioResult(
        attack=attack_name, schedule=schedule_name, n=n, f=f, seed=seed
    )
    net = build_scenario_net(
        attack, schedule, n, f=f, seed=seed, backend=backend,
        scheduler=scheduler, crank_limit=crank_limit,
    )
    rec = _critpath.CritPathRecorder() if obs else None
    flight = None
    if rec is not None:
        _critpath.activate(rec)
        net.critpath = rec
        flight = FlightRecorder(
            context={
                "cell": {
                    "attack": attack_name, "schedule": schedule_name,
                    "n": n, "f": f, "seed": seed, "epochs": epochs,
                }
            }
        )

    def _frame(e: int) -> None:
        if rec is None:
            return
        events = rec.take()
        paths = _critpath.paths_from_events(events)
        if paths:
            rec.last_path = paths[-1]
        flight.record(e, events=events)

    def _dump(reason: str) -> None:
        if rec is None:
            return
        if rec.events:
            _frame(epochs)  # trailing mid-epoch window
        summary = (result.why or {}).get("summary") or []
        result.forensics = flight.bundle(
            reason,
            why=result.why,
            faults=result.fault_log,
            gate_hint=summary[0] if summary else None,
        )

    try:
        try:
            for e in range(epochs):
                for i in sorted(net.nodes):
                    net.send_input(i, {"from": i, "epoch": e})
                net.crank_until(
                    lambda nt, e=e: all(
                        len(node.outputs) >= e + 1
                        for node in nt.correct_nodes()
                    ),
                    max_cranks=crank_limit,
                )
                _frame(e)
        except CrankError as err:
            result.error = str(err).splitlines()[0]
            result.why = err.report
            _collect(result, net, epochs)
            if schedule.lossy:
                result.ok = _bounded_degradation_ok(result)
                result.bounded = result.ok
            _dump("crank_error")
            return result
        _collect(result, net, epochs)
        missing = []
        faulty_ids = {repr(node.id) for node in net.faulty_nodes()}
        for kind in attack.expected_faults:
            landed = any(
                k == kind and accused in faulty_ids
                for _, accused, k in result.fault_log
            )
            if not landed:
                missing.append(kind)
        result.missing_expected = missing
        result.ok = (
            result.batches_identical
            and result.epochs_committed >= epochs
            and not missing
            and not result.misattributed
        )
        if schedule.lossy and not result.ok:
            result.ok = _bounded_degradation_ok(result)
            result.bounded = result.ok
        if not result.ok:
            _dump("verdict_failure")
        return result
    finally:
        if rec is not None:
            _critpath.deactivate()


def _bounded_degradation_ok(result: ScenarioResult) -> bool:
    """The lossy-schedule verdict (a dropped message may legitimately
    starve a quorum or swallow an attack's evidence): whatever prefix
    committed is identical on every honest node, no fault was ever
    attributed to an honest node, and — when the cell stalled — the
    why-stalled report names a cause instead of a bare limit."""
    if result.misattributed or not result.prefix_identical:
        return False
    if result.error is None:
        return True  # completed; only the expected-fault evidence is waived
    return bool((result.why or {}).get("summary"))


def run_matrix(
    ns: Sequence[int] = (4, 7, 16),
    attacks: Sequence[str] = MATRIX_ATTACKS,
    schedules: Sequence[str] = MATRIX_SCHEDULES,
    seed: int = 0,
    epochs: int = 1,
    backend_factory: Optional[Callable[[], Any]] = None,
    scheduler: str = "random",
) -> List[ScenarioResult]:
    """Sweep the attack × schedule × N matrix (one fresh backend per cell
    when ``backend_factory`` is given; default MockBackend per cell)."""
    out: List[ScenarioResult] = []
    for n in ns:
        for attack_name in attacks:
            for schedule_name in schedules:
                backend = backend_factory() if backend_factory else None
                out.append(
                    run_scenario(
                        attack_name, schedule_name, n,
                        seed=seed, epochs=epochs, backend=backend,
                        scheduler=scheduler,
                    )
                )
    return out


# ---------------------------------------------------------------------------
# The composed gauntlet (ROADMAP item 4, closed): a cell is the full
# product attack × net-schedule × churn-schedule × crash-schedule ×
# traffic-source, run as a multi-epoch soak over the object runtime —
# SenderQueue-wrapped QueueingHoneyBadger on VirtualNet — so every axis
# composes with the real wire protocol: era changes ride committed votes,
# crashed nodes restore from utils/snapshot checkpoints and catch up
# through the sender-queue window, and client load flows through the
# traffic subsystem's mempools and lifecycle tracker.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ChurnSpec:
    """Era-change schedule: ``make(n, epochs)`` returns the epochs at
    which every correct node votes the encryption-schedule flip (a
    schedule change wins by strict majority and turns the era over
    without a DKG — the cheapest real era change; the DKG-bearing
    remove/add path is covered by tests/test_dynamic_honey_badger.py)."""

    name: str
    make: Callable[[int, int], Tuple[int, ...]]
    description: str = ""


@dataclass(frozen=True)
class CrashSpec:
    """Crash axis: ``make(n, epochs)`` returns a fresh CrashSchedule (or
    None for the crash-free runtime)."""

    name: str
    make: Callable[[int, int], Optional["CrashSchedule"]]
    description: str = ""


@dataclass(frozen=True)
class TrafficSpec:
    """Traffic axis: open-loop Poisson client load at ``rate_frac`` of
    the nominal per-epoch capacity (validators × batch_size); None runs
    the soak load-free (QHB commits empty batches).  ``adaptive`` puts
    the SLO-driven batch controller (hbbft_tpu/control/) in the loop —
    B updates ride ("batch_size", B) inputs so they are WAL-logged and
    crash-restart replay stays bit-identical; ``trace`` names a
    registered load trace (control/trace.py) modulating the rate."""

    name: str
    rate_frac: Optional[float] = None
    description: str = ""
    adaptive: bool = False
    trace: Optional[str] = None


_CHURN_LIST: Tuple[ChurnSpec, ...] = (
    ChurnSpec("none", lambda n, epochs: (), description="single era"),
    ChurnSpec(
        "era_flip",
        lambda n, epochs: tuple(
            e for e in (epochs // 3, (2 * epochs) // 3) if 0 < e < epochs
        ),
        description="two era changes (encryption-schedule flip votes)",
    ),
)

CHURNS: Dict[str, ChurnSpec] = {c.name: c for c in _CHURN_LIST}


def _one_restart(n: int, epochs: int) -> "CrashSchedule":
    from hbbft_tpu.net.crash import CrashEvent, CrashSchedule

    return CrashSchedule(
        (
            CrashEvent(
                at_epoch=max(1, epochs // 3),
                down_epochs=max(2, min(4, epochs // 6)),
            ),
        ),
        recommit_epochs=3,
    )


def _two_restarts(n: int, epochs: int) -> "CrashSchedule":
    from hbbft_tpu.net.crash import CrashEvent, CrashSchedule

    down = max(2, min(4, epochs // 6))
    return CrashSchedule(
        (
            CrashEvent(at_epoch=max(1, epochs // 4), down_epochs=down),
            CrashEvent(at_epoch=max(2, (3 * epochs) // 5), down_epochs=down),
        ),
        recommit_epochs=3,
    )


_CRASH_LIST: Tuple[CrashSpec, ...] = (
    CrashSpec("none", lambda n, epochs: None, description="no crashes"),
    CrashSpec(
        "one_restart",
        _one_restart,
        description="highest-id honest node dies at epochs/3, restarts "
        "after the net advances a few epochs",
    ),
    CrashSpec(
        "two_restarts",
        _two_restarts,
        description="the same node dies and recovers twice",
    ),
)

CRASHES: Dict[str, CrashSpec] = {c.name: c for c in _CRASH_LIST}

_TRAFFIC_LIST: Tuple[TrafficSpec, ...] = (
    TrafficSpec("none", None, description="load-free soak"),
    TrafficSpec("half_x", 0.5, description="0.5x nominal open-loop load"),
    TrafficSpec("one_x", 1.0, description="1x nominal open-loop load"),
    TrafficSpec("two_x", 2.0, description="2x nominal (overload) load"),
    TrafficSpec(
        "one_x_adaptive",
        1.0,
        description="1x nominal load with the SLO-driven adaptive batch "
        "controller in the loop (input-borne B updates)",
        adaptive=True,
    ),
    TrafficSpec(
        "swing_adaptive",
        0.4,
        description="0.4x base rate under the 10x-swing trace with the "
        "adaptive controller in the loop",
        adaptive=True,
        trace="swing10x",
    ),
)

TRAFFICS: Dict[str, TrafficSpec] = {t.name: t for t in _TRAFFIC_LIST}


@dataclass(frozen=True)
class Cell:
    """One composed-gauntlet cell: the five axes plus shape and seed.
    A cell is a pure function of its fields — same cell, same
    fingerprint, bit for bit (tools/soak.py pins this)."""

    attack: str = "passive"
    schedule: str = "uniform"
    churn: str = "none"
    crash: str = "none"
    traffic: str = "none"
    n: int = 4
    epochs: int = 12
    seed: int = 0
    batch_size: int = 3
    f: Optional[int] = None

    def cell_id(self) -> str:
        return (
            f"{self.attack}x{self.schedule}x{self.churn}x{self.crash}"
            f"x{self.traffic}@n{self.n}e{self.epochs}s{self.seed}"
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "attack": self.attack,
            "schedule": self.schedule,
            "churn": self.churn,
            "crash": self.crash,
            "traffic": self.traffic,
            "n": self.n,
            "epochs": self.epochs,
            "seed": self.seed,
            "batch_size": self.batch_size,
            "f": self.f,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Cell":
        return Cell(**{k: d[k] for k in Cell.__dataclass_fields__ if k in d})


@dataclass
class SoakResult:
    """Verdicts + evidence + replay fingerprint for one gauntlet cell."""

    cell: Cell
    ok: bool = False
    batches_identical: bool = False
    epochs_committed: int = 0
    eras: List[int] = field(default_factory=list)
    missing_expected: List[str] = field(default_factory=list)
    misattributed: List[Tuple[str, str, str]] = field(default_factory=list)
    fault_kinds: Dict[str, int] = field(default_factory=dict)
    fault_log: List[Tuple[str, str, str]] = field(default_factory=list)
    batch_digest: str = ""
    cranks: int = 0
    messages_delivered: int = 0
    #: crash-axis evidence: counts + per-recovery records, and the gate —
    #: every restarted node within recommit_epochs of the honest maximum
    crashes: int = 0
    restarts: int = 0
    recoveries: List[Dict[str, Any]] = field(default_factory=list)
    recovered_in_time: bool = True
    #: traffic-axis evidence (empty without a traffic source)
    tx_committed: int = 0
    tx_per_epoch: float = 0.0
    commit_p50: float = 0.0
    commit_p99: float = 0.0
    traffic_fingerprint: str = ""
    traffic_state: str = ""
    error: Optional[str] = None
    why: Optional[Dict[str, Any]] = None
    stall_named: bool = False
    bounded: bool = False
    #: observability planes (run_cell obs=True): the per-epoch series
    #: rows, the run's gating histogram, and — on failure — the flight
    #: recorder's forensics bundle.  Evidence, NOT state: none of these
    #: enter fingerprint(), so obs on/off cannot flip a replay verdict.
    series: List[Dict[str, Any]] = field(default_factory=list)
    gating: Dict[str, float] = field(default_factory=dict)
    forensics: Optional[Dict[str, Any]] = None

    def fingerprint(self) -> str:
        """Seeded-replay fingerprint: batch sha256 + sorted fault log +
        tx-tracker fingerprint + the crash/restart trace."""
        h = hashlib.sha256()
        h.update(self.batch_digest.encode())
        for t in self.fault_log:
            h.update(repr(t).encode())
        h.update(self.traffic_fingerprint.encode())
        h.update(
            repr(
                (
                    self.crashes,
                    self.restarts,
                    [
                        (r.get("node"), r.get("restart_crank"), r.get("replayed_events"))
                        for r in self.recoveries
                    ],
                    self.cranks,
                    self.epochs_committed,
                )
            ).encode()
        )
        return h.hexdigest()

    def row(self) -> Dict[str, Any]:
        return {
            "cell": self.cell.cell_id(),
            **self.cell.to_dict(),
            "ok": self.ok,
            "bounded": self.bounded,
            "epochs_committed": self.epochs_committed,
            "eras": self.eras,
            "batch_digest": self.batch_digest,
            "fingerprint": self.fingerprint(),
            "fault_kinds": dict(sorted(self.fault_kinds.items())),
            "missing_expected": self.missing_expected,
            "misattributed": self.misattributed[:8],
            "cranks": self.cranks,
            "messages_delivered": self.messages_delivered,
            "crashes": self.crashes,
            "restarts": self.restarts,
            "recoveries": self.recoveries,
            "recovered_in_time": self.recovered_in_time,
            "tx_committed": self.tx_committed,
            "tx_per_epoch": self.tx_per_epoch,
            "commit_p50": self.commit_p50,
            "commit_p99": self.commit_p99,
            "traffic_state": self.traffic_state,
            "stall_named": self.stall_named,
            "error": self.error,
            "gating": self.gating,
        }


def build_cell_net(cell: Cell, backend=None, crank_limit: int = 5_000_000):
    """The composed cell's VirtualNet: SenderQueue-wrapped QHB at N/f
    under the cell's adversary, network schedule, and crash schedule."""
    from hbbft_tpu.protocols.queueing_honey_badger import (
        QueueingHoneyBadgerBuilder,
    )
    from hbbft_tpu.protocols.sender_queue import SenderQueue

    attack = ATTACKS[cell.attack]
    sched = SCHEDULES[cell.schedule]
    crash = CRASHES[cell.crash]
    f = cell.f if cell.f is not None else (cell.n - 1) // 3

    def make(ni, be, rng):
        qhb = (
            QueueingHoneyBadgerBuilder(ni, be, rng)
            .batch_size(cell.batch_size)
            .session_id(b"gauntlet")
            .build()
        )
        return SenderQueue(qhb)

    builder = (
        NetBuilder(range(cell.n))
        .num_faulty(f)
        .adversary(attack.make(cell.n))
        .schedule(sched.make(cell.n))
        .crashes(crash.make(cell.n, cell.epochs))
        .scenario(cell.cell_id())
        .crank_limit(crank_limit)
        .using(make)
    )
    if backend is not None:
        builder = builder.backend(backend)
    return builder.build(seed=cell.seed)


def _soak_collect(result: SoakResult, net, driver) -> None:
    """Evidence fields from a (possibly partial) composed run."""
    correct = net.correct_nodes()
    faulty_ids = {node.id for node in net.faulty_nodes()}
    triples = sorted(
        (repr(node.id), repr(fa.node_id), fa.kind)
        for node in correct
        for fa in node.faults_observed
    )
    result.fault_log = triples
    kinds: Dict[str, int] = {}
    for _, _, kind in triples:
        kinds[kind] = kinds.get(kind, 0) + 1
    result.fault_kinds = kinds
    result.misattributed = [
        t
        for node in correct
        for fa in node.faults_observed
        if fa.node_id not in faulty_ids
        for t in ((repr(node.id), repr(fa.node_id), fa.kind),)
    ]
    common = min((len(node.outputs) for node in correct), default=0)
    result.epochs_committed = common
    seqs = [node.outputs[:common] for node in correct]
    result.batches_identical = bool(seqs) and all(s == seqs[0] for s in seqs)
    h = hashlib.sha256()
    for b in seqs[0] if seqs else ():
        h.update(
            repr(
                (
                    getattr(b, "era", 0),
                    b.epoch,
                    sorted(b.contributions.items(), key=repr),
                    getattr(b, "change", None),
                )
            ).encode()
        )
    result.batch_digest = h.hexdigest()
    result.eras = sorted(
        {getattr(b, "era", 0) for b in (seqs[0] if seqs else ())}
    )
    result.cranks = net.cranks
    result.messages_delivered = net.messages_delivered
    if net.crash is not None:
        st = net.crash.stats()
        result.crashes = st["crashes"]
        result.restarts = st["restarts"]
        result.recoveries = st["recoveries"]
        gate = net.crash.schedule.recommit_epochs
        down = net.down_node_ids()
        ref = max(
            (len(n.outputs) for n in correct if n.id not in down), default=0
        )
        for nid, t in sorted(net.crash.tracks.items(), key=lambda kv: repr(kv[0])):
            if t.crashes and t.state != "up":
                result.recovered_in_time = False  # still (or terminally) down
            elif t.restarts and len(net.nodes[nid].outputs) < ref - gate:
                result.recovered_in_time = False
    if driver is not None:
        rep = driver.report()
        result.tx_committed = rep["committed"]
        result.tx_per_epoch = rep["tx_per_epoch"]
        lat = driver.tracker.hist("tx_commit_latency")
        result.commit_p50 = round(lat.percentile(50), 3)
        result.commit_p99 = round(lat.percentile(99), 3)
        # tracker.fingerprint() is a nested dict; hash a sorted repr so
        # the soak fingerprint stays one hex string.  The controller's B
        # trace (when the adaptive axis is on) is part of the replay
        # contract: a divergent control decision must flip the cell
        # fingerprint even if throughput happens to match.
        fp = repr(sorted(driver.tracker.fingerprint().items()))
        if driver.controller is not None:
            fp += repr(driver.controller.b_trace())
        result.traffic_fingerprint = hashlib.sha256(fp.encode()).hexdigest()
        result.traffic_state = rep["status"]["state"]


def run_cell(
    cell: Cell,
    backend=None,
    crank_limit: int = 5_000_000,
    obs: bool = True,
    net_sink: Optional[List] = None,
) -> SoakResult:
    """Run one composed-gauntlet cell; never raises — a starved cell
    comes back ok=False with the why-stalled report naming the dominant
    cause (attack, partition, down node, or starved/saturated source).

    ``obs=True`` (default) wires the three observability planes: a
    :class:`~hbbft_tpu.obs.critpath.CritPathRecorder` on the module stamp
    hook (gating-chain reconstruction per epoch), a per-epoch
    :class:`~hbbft_tpu.obs.timeseries.MetricsLog` (``result.series``),
    and a :class:`~hbbft_tpu.obs.flight.FlightRecorder` whose forensics
    bundle lands on ``result.forensics`` when the cell dies (CrankError,
    verdict failure, or a ``crash:*`` fault).  None of it enters the
    replay fingerprint.  Cells run sequentially, so the single
    process-wide stamp hook is activated around this run only.

    ``net_sink`` (a caller-supplied list) receives the live VirtualNet
    before the first crank — the post-run inspection hook the dynamic
    snapshot-coverage twin test uses to diff restored instances against
    live ones."""
    rec = _critpath.CritPathRecorder() if obs else None
    if rec is not None:
        _critpath.activate(rec)
    try:
        return _run_cell(cell, backend, crank_limit, rec, net_sink)
    finally:
        if rec is not None:
            _critpath.deactivate()


def _run_cell(
    cell: Cell,
    backend,
    crank_limit: int,
    rec: Optional[_critpath.CritPathRecorder],
    net_sink: Optional[List] = None,
) -> SoakResult:
    from hbbft_tpu.protocols.change import Change
    from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
    from hbbft_tpu.traffic.driver import ObjectTrafficDriver
    from hbbft_tpu.traffic.workload import OpenLoopSource, ZipfPopulation

    attack = ATTACKS[cell.attack]
    sched = SCHEDULES[cell.schedule]
    churn = CHURNS[cell.churn]
    traffic = TRAFFICS[cell.traffic]
    result = SoakResult(cell=cell)
    net = build_cell_net(cell, backend=backend, crank_limit=crank_limit)
    if net_sink is not None:
        net_sink.append(net)
    f = cell.f if cell.f is not None else (cell.n - 1) // 3

    driver = None
    if traffic.rate_frac is not None:
        rate = traffic.rate_frac * (cell.n - f) * cell.batch_size
        trace = None
        if traffic.trace is not None:
            from hbbft_tpu.control.trace import make_trace

            trace = make_trace(traffic.trace)
        controller = None
        if traffic.adaptive:
            from hbbft_tpu.control import SLO, AdaptiveBatchController

            # small-N soak ladder bracketing the cell's batch size; a
            # generous p99 target — soak cells compose partitions and
            # outages, and the controller reacting (not the SLO holding)
            # is what the gauntlet exercises
            controller = AdaptiveBatchController(
                SLO(p99_epochs=8.0),
                initial_b=4,
                ladder=(2, 4, 8, 16),
                window=3,
                hold_epochs=2,
            )
        source = OpenLoopSource(
            rate=rate, population=ZipfPopulation(1024), trace=trace
        )
        driver = ObjectTrafficDriver(
            net,
            source,
            rng=net.rng,
            batch_size=cell.batch_size,
            mempool_capacity=1 << 12,
            controller=controller,
        )

    series = flight = None
    all_paths: List[_critpath.EpochCritPath] = []
    next_frame = [0]
    if rec is not None:
        series = MetricsLog()
        flight = FlightRecorder(context={"cell": cell.to_dict()})
        net.critpath = rec  # crank/virtual-clock ticks + health gate line
        net.metrics_log = series

    def _epoch_obs(k: Optional[int] = None) -> None:
        """Epoch boundary: drain the stamp ring, reconstruct the window's
        gating chains, snap a series row, and push a flight frame."""
        if rec is None:
            return
        if k is None:
            k = next_frame[0]
        next_frame[0] = k + 1
        events = rec.take()
        paths = _critpath.paths_from_events(events)
        if paths:
            rec.last_path = paths[-1]
            all_paths.extend(paths)
        row = snap_net(
            series,
            net,
            k,
            gate=paths[-1] if paths else None,
            controller_b=(
                driver.controller.current_b
                if driver is not None and driver.controller is not None
                else None
            ),
            mempool_depth=driver.max_depth if driver is not None else None,
        )
        flight.record(k, series_row=row, events=events)

    def _obs_finish(reason: Optional[str]) -> None:
        """Attach the evidence planes to the result; ``reason`` non-None
        dumps the flight ring as a forensics bundle."""
        if rec is None:
            return
        if rec.events:
            _epoch_obs()  # trailing window (recovery grace / mid-epoch death)
        result.series = series.rows_list()
        result.gating = _critpath.gating_histogram(all_paths)
        if reason is not None:
            summary = (result.why or {}).get("summary") or []
            result.forensics = flight.bundle(
                reason,
                why=result.why,
                faults=result.fault_log,
                gate_hint=summary[0] if summary else None,
            )

    churn_epochs = set(churn.make(cell.n, cell.epochs))
    # alternating schedule flips so consecutive churn votes name distinct
    # winning changes (tick_tock(1, 0) encrypts every epoch — semantics
    # identical to always, so the flip costs an era change and nothing else)
    flips = (
        Change.set_schedule(EncryptionSchedule("tick_tock", 1, 0)),
        Change.set_schedule(EncryptionSchedule.always()),
    )
    nflip = 0

    def live_done(nt, k: int) -> bool:
        down = nt.down_node_ids()
        return all(
            len(node.outputs) >= k + 1
            for node in nt.correct_nodes()
            if node.id not in down
        )

    try:
        for k in range(cell.epochs):
            if k in churn_epochs:
                ch = flips[nflip % 2]
                nflip += 1
                # down nodes included: send_input parks the vote and the
                # restarted node casts it at recovery (client-retry model)
                for node in net.correct_nodes():
                    net.send_input(node.id, ("change", ch))
            if driver is not None:
                driver._wave(k)
            else:
                if k == 0:
                    for node in net.correct_nodes():
                        net.send_input(
                            node.id, ("user", ("boot", repr(node.id)))
                        )
                net.crank_until(
                    lambda nt, k=k: live_done(nt, k), max_cranks=crank_limit
                )
            _epoch_obs(k)
        if net.crash is not None:
            # recovery grace: give the last restart room to catch up to
            # the honest maximum before the verdict reads the gate.
            # Bounded by a few epochs' worth of cranks — a permanently
            # failed recovery must not spin to the crank limit (QHB
            # self-perpetuates, so the net never quiesces on its own)
            gate = net.crash.schedule.recommit_epochs
            per_epoch = max(1_000, net.cranks // max(1, cell.epochs))
            grace = min(crank_limit, per_epoch * (gate + 3) * 4)

            def recovered(nt) -> bool:
                correct = nt.correct_nodes()
                ref = max(
                    (
                        len(n.outputs)
                        for n in correct
                        if not nt.crash.is_down(n.id)
                    ),
                    default=0,
                )
                for nid, t in sorted(
                    nt.crash.tracks.items(), key=lambda kv: repr(kv[0])
                ):
                    if t.state == "failed":
                        continue  # terminally down: the verdict fails it
                    if (t.state == "down" and t.restart_pending) or (
                        t.state == "up" and t.restarts
                    ):
                        if (
                            nt.crash.is_down(nid)
                            or len(nt.nodes[nid].outputs) < ref - gate
                        ):
                            return False
                return True

            try:
                net.crank_until(recovered, max_cranks=grace)
            except CrankError:
                pass  # verdict reads the gate from the final state
    except CrankError as err:
        result.error = str(err).splitlines()[0]
        result.why = err.report
        result.stall_named = bool((err.report or {}).get("summary"))
        _soak_collect(result, net, driver)
        _obs_finish("crank_error")
        if sched.lossy:
            # bounded-degradation contract: a lossy stall passes iff the
            # committed prefix is identical, nothing was misattributed,
            # restarts met their gate, and the stall names its cause
            result.ok = (
                result.batches_identical
                and not result.misattributed
                and result.recovered_in_time
                and result.stall_named
            )
            result.bounded = result.ok
        return result
    _soak_collect(result, net, driver)
    faulty_ids = {repr(node.id) for node in net.faulty_nodes()}
    missing = []
    for kind in attack.expected_faults:
        landed = any(
            k == kind and accused in faulty_ids
            for _, accused, k in result.fault_log
        )
        if not landed:
            missing.append(kind)
    result.missing_expected = missing
    result.ok = (
        result.batches_identical
        and result.epochs_committed >= cell.epochs
        and not missing
        and not result.misattributed
        and result.recovered_in_time
    )
    if sched.lossy and not result.ok:
        # bounded-degradation contract, same as the 2-axis matrix
        result.ok = (
            result.batches_identical
            and not result.misattributed
            and result.recovered_in_time
        )
        result.bounded = result.ok
    reason = None
    if not result.ok:
        reason = "verdict_failure"
    else:
        # a crash:* fault with a passing verdict (e.g. checkpoint_failed)
        # still merits the evidence dump — the next session debugs from it
        crash_kinds = sorted(k for k in result.fault_kinds if k.startswith("crash:"))
        if crash_kinds:
            reason = crash_kinds[0]
    _obs_finish(reason)
    return result
