"""Scenario harness: the attack × network-schedule matrix over VirtualNet.

ROADMAP item 4 ("as many scenarios as you can imagine"): tier-1 proves
honest-path bit-identity; the CCS 2016 headline claim — liveness under a
fully asynchronous adversary controlling f nodes *and* the network — needs
the cross product of misbehaviour (net/adversary.py attack library) and
network conditions (net/virtual_net.NetSchedule).  This module is the
registry + runner for that matrix:

* :data:`ATTACKS` — named attack factories with the fault kinds each one
  provably plants (drawn from ``core.fault_log.FAULT_KINDS``; an
  unregistered expectation breaks lint and tests together).
* :data:`SCHEDULES` — named network-condition factories (uniform / LAN /
  WAN / partition-and-heal, plus a model-violating lossy shape kept out
  of the liveness matrix by its ``lossy`` flag).
* :func:`run_scenario` — one cell: N nodes, f=⌊(N−1)/3⌋ faulty, a full
  HoneyBadger epoch loop; returns a :class:`ScenarioResult` with the
  per-cell verdicts the matrix asserts: every honest node committed
  identical Batches, every injected misbehaviour landed in the fault log
  with the expected kind against a faulty node, no fault was ever
  attributed to an honest node, and a stalled cell carries the
  why-stalled report naming the attack.

Determinism: a cell is a pure function of (attack, schedule, n, seed) —
all entropy flows through the net's single seeded rng, so replaying a
seed reproduces the fault log and the batch digest bit-for-bit
(tests/test_scenarios.py pins this).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from hbbft_tpu.core.fault_log import all_fault_kinds
from hbbft_tpu.net.adversary import (
    Adversary,
    CraftedShareAdversary,
    EquivocatingAdversary,
    LaggardAdversary,
    NullAdversary,
    ReplayAdversary,
    WithholdingAdversary,
)
from hbbft_tpu.net.virtual_net import (
    CrankError,
    NetBuilder,
    NetSchedule,
    Partition,
    VirtualNet,
)


@dataclass(frozen=True)
class Attack:
    """One named attack: a per-run adversary factory plus the fault kinds
    the attack provably plants (must be registered in FAULT_KINDS)."""

    name: str
    make: Callable[[int], Adversary]  # n -> fresh adversary
    expected_faults: Tuple[str, ...] = ()
    description: str = ""


@dataclass(frozen=True)
class ScheduleSpec:
    """One named network condition; ``make(n)`` returns a fresh
    NetSchedule (or None for instant delivery).  ``lossy`` marks
    schedules that violate the eventual-delivery model — they exercise
    the drop machinery and the stall reporter, not the liveness matrix."""

    name: str
    make: Callable[[int], Optional[NetSchedule]]
    lossy: bool = False
    description: str = ""


# ---------------------------------------------------------------------------
# Registries
# ---------------------------------------------------------------------------

_ATTACK_LIST: Tuple[Attack, ...] = (
    Attack(
        "passive",
        lambda n: NullAdversary(),
        description="control row: no tampering",
    ),
    Attack(
        "equivocate",
        lambda n: EquivocatingAdversary(),
        expected_faults=("broadcast:conflicting_values",),
        description="conflicting RBC Values per recipient",
    ),
    Attack(
        "withhold_echo",
        lambda n: WithholdingAdversary(kinds=("echo",)),
        description="faulty nodes send no Echo (quorum at exactly N-f)",
    ),
    Attack(
        "withhold_ready",
        lambda n: WithholdingAdversary(kinds=("ready",)),
        description="faulty nodes send no Ready",
    ),
    Attack(
        "withhold_shares",
        lambda n: WithholdingAdversary(kinds=("sig_share", "dec_share")),
        description="faulty nodes withhold threshold shares",
    ),
    Attack(
        "crafted_shares",
        lambda n: CraftedShareAdversary(rate=0.5),
        expected_faults=("threshold_decrypt:invalid_share",),
        description="well-typed invalid threshold shares at 50% rate",
    ),
    Attack(
        "replay_flood",
        lambda n: ReplayAdversary(copies=3),
        expected_faults=(
            "broadcast:multiple_echos",
            "broadcast:multiple_readys",
        ),
        description="3x duplicate flood of all faulty traffic",
    ),
    Attack(
        "laggard",
        lambda n: LaggardAdversary(lag_cranks=60 * n * n),
        description="one honest node lags, then catches up",
    ),
)

ATTACKS: Dict[str, Attack] = {a.name: a for a in _ATTACK_LIST}


def _wan_latency(sender: Any, to: Any) -> int:
    """Deterministic heterogeneous per-link base latency (1..8 cranks):
    a fixed function of the directed link, not of arrival order."""
    s = sender if isinstance(sender, int) else len(repr(sender))
    t = to if isinstance(to, int) else len(repr(to))
    return 1 + (3 * s + 5 * t) % 8


_SCHEDULE_LIST: Tuple[ScheduleSpec, ...] = (
    ScheduleSpec(
        "uniform",
        lambda n: None,
        description="instant delivery (legacy behavior)",
    ),
    ScheduleSpec(
        "lan",
        lambda n: NetSchedule(name="lan", latency=1, jitter=2),
        description="small uniform latency + jitter",
    ),
    ScheduleSpec(
        "wan",
        lambda n: NetSchedule(name="wan", link_latency=_wan_latency, jitter=3),
        description="heterogeneous per-link latency + jitter",
    ),
    ScheduleSpec(
        "partition_heal",
        lambda n: NetSchedule(
            name="partition_heal",
            partitions=(
                Partition(
                    start=20,
                    end=20 + 30 * n * n,
                    groups=(frozenset(range(n // 2)),),
                ),
            ),
        ),
        description="halves isolated early, healed after 30·N² cranks",
    ),
    ScheduleSpec(
        "lossy",
        lambda n: NetSchedule(name="lossy", drop=0.05, latency=1, jitter=1),
        lossy=True,
        description="5% i.i.d. message loss (violates eventual delivery; "
        "exercises drop accounting and the stall reporter)",
    ),
)

SCHEDULES: Dict[str, ScheduleSpec] = {s.name: s for s in _SCHEDULE_LIST}

#: the liveness matrix: every attack × every eventual-delivery schedule
MATRIX_ATTACKS: Tuple[str, ...] = tuple(
    a.name for a in _ATTACK_LIST if a.name != "passive"
)
MATRIX_SCHEDULES: Tuple[str, ...] = tuple(
    s.name for s in _SCHEDULE_LIST if not s.lossy
)


def _check_registry() -> None:
    known = all_fault_kinds()
    for a in _ATTACK_LIST:
        unknown = [k for k in a.expected_faults if k not in known]
        if unknown:
            raise ValueError(
                f"attack {a.name!r} expects unregistered fault kinds {unknown}"
            )


_check_registry()


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


@dataclass
class ScenarioResult:
    """Verdicts + evidence for one matrix cell."""

    attack: str
    schedule: str
    n: int
    f: int
    seed: int
    ok: bool = False
    #: all honest nodes committed identical batch sequences
    batches_identical: bool = False
    epochs_committed: int = 0
    #: expected fault kinds that never landed against a faulty node
    missing_expected: List[str] = field(default_factory=list)
    #: (observer, accused, kind) for faults attributed to HONEST nodes —
    #: must be empty: correct nodes never accuse each other
    misattributed: List[Tuple[str, str, str]] = field(default_factory=list)
    #: observed fault kind -> count (honest observers only)
    fault_kinds: Dict[str, int] = field(default_factory=dict)
    #: sorted (observer, accused, kind) triples — the replay-determinism
    #: fingerprint next to batch_digest
    fault_log: List[Tuple[str, str, str]] = field(default_factory=list)
    #: sha256 over the canonical repr of every honest node's batches
    batch_digest: str = ""
    cranks: int = 0
    messages_delivered: int = 0
    schedule_dropped: int = 0
    schedule_delayed: int = 0
    error: Optional[str] = None
    #: why-stalled report when the cell starved (CrankError.report)
    why: Optional[Dict[str, Any]] = None

    def row(self) -> Dict[str, Any]:
        """Flat JSON-friendly form for tools/scenario_matrix.py."""
        return {
            "attack": self.attack,
            "schedule": self.schedule,
            "n": self.n,
            "f": self.f,
            "seed": self.seed,
            "ok": self.ok,
            "epochs": self.epochs_committed,
            "fault_kinds": dict(sorted(self.fault_kinds.items())),
            "missing_expected": self.missing_expected,
            "misattributed": self.misattributed,
            "batch_digest": self.batch_digest,
            "cranks": self.cranks,
            "messages_delivered": self.messages_delivered,
            "schedule_dropped": self.schedule_dropped,
            "schedule_delayed": self.schedule_delayed,
            "error": self.error,
        }


def build_scenario_net(
    attack: Attack,
    schedule: ScheduleSpec,
    n: int,
    f: Optional[int] = None,
    seed: int = 0,
    backend=None,
    scheduler: str = "random",
    crank_limit: int = 5_000_000,
) -> VirtualNet:
    """One cell's VirtualNet: HoneyBadger at N nodes / f faulty under the
    attack's adversary and the schedule's network conditions."""
    from hbbft_tpu.protocols.honey_badger import HoneyBadger

    if f is None:
        f = (n - 1) // 3
    builder = (
        NetBuilder(range(n))
        .num_faulty(f)
        .adversary(attack.make(n))
        .schedule(schedule.make(n))
        .scenario(f"{attack.name}x{schedule.name}@n{n}")
        .scheduler(scheduler)
        .crank_limit(crank_limit)
        .using(
            lambda ni, be: HoneyBadger(ni, be, session_id=b"scenario-matrix")
        )
    )
    if backend is not None:
        builder = builder.backend(backend)
    return builder.build(seed=seed)


def _collect(result: ScenarioResult, net: VirtualNet, epochs: int) -> None:
    """Fill the result's evidence fields from a (possibly partial) run."""
    correct = net.correct_nodes()
    faulty_ids = {node.id for node in net.faulty_nodes()}
    triples = sorted(
        (repr(node.id), repr(fa.node_id), fa.kind)
        for node in correct
        for fa in node.faults_observed
    )
    result.fault_log = triples
    kinds: Dict[str, int] = {}
    for _, _, kind in triples:
        kinds[kind] = kinds.get(kind, 0) + 1
    result.fault_kinds = kinds
    result.misattributed = [
        t
        for node in correct
        for fa in node.faults_observed
        if fa.node_id not in faulty_ids
        for t in ((repr(node.id), repr(fa.node_id), fa.kind),)
    ]
    result.epochs_committed = min(
        (len(node.outputs) for node in correct), default=0
    )
    seqs = [node.outputs[:epochs] for node in correct]
    result.batches_identical = bool(seqs) and all(s == seqs[0] for s in seqs)
    h = hashlib.sha256()
    for b in seqs[0] if seqs else ():
        h.update(repr((b.epoch, sorted(b.contributions.items(), key=repr))).encode())
    result.batch_digest = h.hexdigest()
    result.cranks = net.cranks
    result.messages_delivered = net.messages_delivered
    result.schedule_dropped = net.counters.schedule_dropped
    result.schedule_delayed = net.counters.schedule_delayed


def run_scenario(
    attack_name: str,
    schedule_name: str,
    n: int,
    f: Optional[int] = None,
    seed: int = 0,
    epochs: int = 1,
    backend=None,
    scheduler: str = "random",
    crank_limit: int = 5_000_000,
) -> ScenarioResult:
    """Run one matrix cell; never raises — a starved cell comes back with
    ``ok=False`` and the why-stalled report naming the attack."""
    attack = ATTACKS[attack_name]
    schedule = SCHEDULES[schedule_name]
    if f is None:
        f = (n - 1) // 3
    result = ScenarioResult(
        attack=attack_name, schedule=schedule_name, n=n, f=f, seed=seed
    )
    net = build_scenario_net(
        attack, schedule, n, f=f, seed=seed, backend=backend,
        scheduler=scheduler, crank_limit=crank_limit,
    )
    try:
        for e in range(epochs):
            for i in sorted(net.nodes):
                net.send_input(i, {"from": i, "epoch": e})
            net.crank_until(
                lambda nt, e=e: all(
                    len(node.outputs) >= e + 1 for node in nt.correct_nodes()
                ),
                max_cranks=crank_limit,
            )
    except CrankError as err:
        result.error = str(err).splitlines()[0]
        result.why = err.report
        _collect(result, net, epochs)
        return result
    _collect(result, net, epochs)
    missing = []
    faulty_ids = {repr(node.id) for node in net.faulty_nodes()}
    for kind in attack.expected_faults:
        landed = any(
            k == kind and accused in faulty_ids
            for _, accused, k in result.fault_log
        )
        if not landed:
            missing.append(kind)
    result.missing_expected = missing
    result.ok = (
        result.batches_identical
        and result.epochs_committed >= epochs
        and not missing
        and not result.misattributed
    )
    return result


def run_matrix(
    ns: Sequence[int] = (4, 7, 16),
    attacks: Sequence[str] = MATRIX_ATTACKS,
    schedules: Sequence[str] = MATRIX_SCHEDULES,
    seed: int = 0,
    epochs: int = 1,
    backend_factory: Optional[Callable[[], Any]] = None,
    scheduler: str = "random",
) -> List[ScenarioResult]:
    """Sweep the attack × schedule × N matrix (one fresh backend per cell
    when ``backend_factory`` is given; default MockBackend per cell)."""
    out: List[ScenarioResult] = []
    for n in ns:
        for attack_name in attacks:
            for schedule_name in schedules:
                backend = backend_factory() if backend_factory else None
                out.append(
                    run_scenario(
                        attack_name, schedule_name, n,
                        seed=seed, epochs=epochs, backend=backend,
                        scheduler=scheduler,
                    )
                )
    return out
