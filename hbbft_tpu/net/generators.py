"""Random well-typed message generators for :class:`RandomAdversary`.

Rebuild of the reference `RandomAdversary`'s message generation (SURVEY.md
§4: "tampers faulty nodes' traffic with random *well-typed* messages" via
proptest strategies).  Each generator produces a syntactically valid wire
message with adversarial content — valid types, garbage semantics — so the
receiving protocol exercises its validation / fault-attribution paths
rather than its `isinstance` guard.

All randomness comes from the net's seeded RNG: runs stay reproducible.
"""

from __future__ import annotations

import random
from typing import Any, Callable, List

from hbbft_tpu.protocols.binary_agreement import BaMessage
from hbbft_tpu.protocols.bool_set import BoolSet
from hbbft_tpu.protocols.broadcast import BroadcastMessage
from hbbft_tpu.protocols.honey_badger import HbMessage
from hbbft_tpu.protocols.sbv_broadcast import SbvMessage
from hbbft_tpu.protocols.subset import SubsetMessage
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecryptMessage
from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage


def _rand_bytes(rng: random.Random, n: int = 32) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def random_proof(rng: random.Random, n_leaves: int):
    """A structurally valid Merkle proof with random content."""
    from hbbft_tpu.crypto.merkle import Proof

    depth = max(1, (n_leaves - 1).bit_length())
    return Proof(
        value=_rand_bytes(rng, rng.randrange(1, 64)),
        index=rng.randrange(n_leaves),
        path=tuple(_rand_bytes(rng) for _ in range(depth)),
        root_hash=_rand_bytes(rng),
        n_leaves=n_leaves,
    )


def random_broadcast_message(rng: random.Random, n_nodes: int) -> BroadcastMessage:
    kind = rng.choice(["value", "echo", "ready"])
    if kind == "ready":
        return BroadcastMessage.ready(_rand_bytes(rng))
    proof = random_proof(rng, n_leaves=n_nodes)
    return BroadcastMessage(kind, proof)


def random_sig_share_message(rng: random.Random, group) -> ThresholdSignMessage:
    """A well-typed signature share whose element is random (won't verify)."""
    from hbbft_tpu.crypto.keys import SignatureShare

    el = group.g2_mul(rng.randrange(1, 1 << 64), group.g2())
    return ThresholdSignMessage(SignatureShare(group, el))


def random_dec_share_message(rng: random.Random, group) -> ThresholdDecryptMessage:
    from hbbft_tpu.crypto.keys import DecryptionShare

    el = group.g1_mul(rng.randrange(1, 1 << 64), group.g1())
    return ThresholdDecryptMessage(DecryptionShare(group, el))


def random_ba_message(rng: random.Random, group) -> BaMessage:
    rnd = rng.randrange(0, 4)
    kind = rng.choice(["sbv", "conf", "coin", "term"])
    if kind == "sbv":
        payload: Any = SbvMessage(rng.choice(["bval", "aux"]), rng.random() < 0.5)
    elif kind == "conf":
        payload = BoolSet(rng.randrange(4))
    elif kind == "coin":
        payload = random_sig_share_message(rng, group)
    else:
        payload = rng.random() < 0.5
    return BaMessage(rnd, kind, payload)


def random_subset_message(
    rng: random.Random, proposers: List[Any], n_nodes: int, group
) -> SubsetMessage:
    proposer = rng.choice(proposers)
    if rng.random() < 0.5:
        return SubsetMessage(
            proposer, "broadcast", random_broadcast_message(rng, n_nodes)
        )
    return SubsetMessage(proposer, "agreement", random_ba_message(rng, group))


def random_hb_message(
    rng: random.Random, proposers: List[Any], n_nodes: int, group
) -> HbMessage:
    epoch = rng.randrange(0, 3)
    if rng.random() < 0.5:
        return HbMessage.subset(
            epoch, random_subset_message(rng, proposers, n_nodes, group)
        )
    return HbMessage(
        epoch,
        "dec_share",
        rng.choice(proposers),
        random_dec_share_message(rng, group),
    )


def generator_for(protocol: str) -> Callable:
    """``RandomAdversary`` generator for a protocol name.

    The returned callable has the adversary's ``(net, msg) -> payload``
    shape; node ids and the group come from the live net.
    """

    def gen(net, msg):
        rng = net.rng
        ids = sorted(net.nodes)
        group = net.backend.group
        if protocol == "broadcast":
            return random_broadcast_message(rng, len(ids))
        if protocol == "binary_agreement":
            return random_ba_message(rng, group)
        if protocol == "subset":
            return random_subset_message(rng, ids, len(ids), group)
        if protocol == "honey_badger":
            return random_hb_message(rng, ids, len(ids), group)
        raise ValueError(f"no generator for {protocol!r}")

    return gen
