"""Legacy ``TestNetwork`` harness — the pre-VirtualNet test API.

Rebuild of the reference's OLD integration harness (`tests/network/mod.rs`
§, SURVEY.md §2.1 "Legacy test harness": ``TestNetwork``, ``Adversary``,
``MessageScheduler::{Random, First}``), which predates the `tests/net/`
VirtualNet and survived in the vintage as a second, simpler driver.  Here
it is a THIN COMPAT LAYER over :mod:`hbbft_tpu.net.virtual_net` — same
semantics, one implementation: the scheduler enum maps onto VirtualNet's
scheduler modes, the legacy crash-silence adversary is VirtualNet's
``SilentAdversary``, and the legacy bool-flip adversary is provided here
(it predates the generator-based ``RandomAdversary``).

Use VirtualNet/NetBuilder for new code — this module exists so a user of
the reference's legacy tests finds the surface they expect.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.core.types import Step
from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.net.adversary import Adversary, NullAdversary, SilentAdversary
from hbbft_tpu.net.virtual_net import NetBuilder, NetMessage, VirtualNet

__all__ = [
    "MessageScheduler",
    "TestNetwork",
    "SilentAdversary",
    "FlipBoolAdversary",
]


class MessageScheduler(enum.Enum):
    """Legacy delivery-order policy (reference ``MessageScheduler`` §)."""

    #: deliver a uniformly random pending message each step
    RANDOM = "random"
    #: always deliver the oldest pending message (FIFO)
    FIRST = "first"


class FlipBoolAdversary(Adversary):
    """Legacy bool-flip fault: faulty senders' boolean message fields are
    inverted (the classic BinaryAgreement equivocation-style corruption).

    Flips every ``bool``-typed dataclass field of the payload, recursing
    through nested dataclasses (the protocol message wrappers);
    non-dataclass payloads pass through unchanged.  A custom ``flip``
    callable overrides the behavior entirely."""

    def __init__(self, flip: Optional[Callable[[Any], Any]] = None) -> None:
        self._flip = flip

    def _flip_payload(self, payload: Any) -> Any:
        if self._flip is not None:
            return self._flip(payload)
        if not dataclasses.is_dataclass(payload):
            return payload
        changes: Dict[str, Any] = {}
        for f in dataclasses.fields(payload):
            v = getattr(payload, f.name)
            if isinstance(v, bool):
                changes[f.name] = not v
            elif dataclasses.is_dataclass(v) and not isinstance(v, type):
                inner = self._flip_payload(v)
                if inner is not v:
                    changes[f.name] = inner
        return dataclasses.replace(payload, **changes) if changes else payload

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        flipped = self._flip_payload(msg.payload)
        if flipped is msg.payload:
            return [msg]
        return [NetMessage(msg.sender, msg.to, flipped)]


class TestNetwork:
    """N-node deterministic harness with the legacy step-wise API.

    ``good_num`` correct + ``adv_num`` faulty nodes (ids ``0..N-1``;
    which ids are faulty is drawn by the seeded RNG — the VirtualNet
    convention; inspect ``nodes[i].faulty``);
    ``new_algorithm(netinfo, backend)`` constructs each node's protocol
    instance (same signature as ``NetBuilder.using``)."""

    __test__ = False  # "Test"-prefixed API name; not a pytest class

    def __init__(
        self,
        good_num: int,
        adv_num: int,
        new_algorithm: Callable[..., Any],
        *,
        backend: Optional[CryptoBackend] = None,
        scheduler: MessageScheduler = MessageScheduler.RANDOM,
        adversary: Optional[Adversary] = None,
        seed: int = 0,
    ) -> None:
        n = good_num + adv_num
        builder = (
            NetBuilder(range(n))
            .num_faulty(adv_num)
            .scheduler(scheduler.value)
            .adversary(adversary or NullAdversary())
        )
        if backend is not None:
            builder = builder.backend(backend)
        self.net: VirtualNet = builder.using(new_algorithm).build(seed=seed)
        self.scheduler = scheduler

    # -- legacy surface ------------------------------------------------------

    @property
    def nodes(self):
        return self.net.nodes

    def input(self, node_id: Any, value: Any) -> Step:
        """Feed one node's input (legacy ``input``)."""
        return self.net.send_input(node_id, value)

    def input_all(self, value: Any) -> None:
        """Same input to every node (legacy ``input_all``)."""
        self.net.broadcast_input(value)

    def step(self) -> Optional[Tuple[Any, Step]]:
        """Deliver ONE message per the scheduler; returns (node_id, step)
        or None when the network is quiescent."""
        return self.net.crank()

    def run(self, max_steps: int = 1_000_000) -> Dict[Any, List[Any]]:
        """Crank to quiescence; returns {node_id: outputs} for CORRECT
        nodes (the legacy harness asserted agreement over these)."""
        self.net.crank_to_quiescence(max_cranks=max_steps)
        return {node.id: list(node.outputs) for node in self.net.correct_nodes()}

    def outputs(self, node_id: Any) -> List[Any]:
        return list(self.net.nodes[node_id].outputs)
