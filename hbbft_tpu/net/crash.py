"""Crash/restart fault injection: kill a node mid-epoch, restore it from
its last checkpoint, and replay it back into the current epoch.

ROADMAP item 4's missing axis: ``SilentAdversary`` can make a node mute,
but it can never make one *die and come back* — lose every byte of state
since its last checkpoint, miss epochs while the rest of the network
advances, and rejoin committing the same Batches.  This module is that
axis, built on two existing pieces:

* **Checkpoints** are :func:`hbbft_tpu.utils.snapshot.save_node` blobs of
  the node's whole algorithm stack (SenderQueue ⊃ QHB ⊃ DHB ⊃ HB ⊃ …),
  taken at quiescent crank boundaries every ``checkpoint_every``
  committed batches (knob: ``HBBFT_TPU_CHECKPOINT_EVERY``).
* **Catch-up** rides the sender-queue/replay machinery.  Between
  checkpoints the manager keeps a write-ahead log of every event the
  node consumed (delivered message or injected input, each with the
  shared rng's state *before* handling) plus the ordered log of every
  message the node emitted.  Restart = ``load_node(checkpoint)`` + replay
  the WAL with the logged rng states — the restored node re-derives its
  crash-time state **bit-identically**, so each re-emitted message
  matches the sent log and is suppressed instead of double-delivered
  (peers never see an honest node equivocate because it restarted).
  From there the normal SenderQueue window protocol carries it to the
  current epoch: traffic addressed to the node while it was down is
  parked by the manager (the link-layer-retransmission model) and
  re-enqueued at restart; peers' SenderQueues release their buffered
  future-epoch traffic as the node announces progress.

Failure policy: a recovery that cannot complete — unreadable checkpoint,
replay raising, or replayed emissions/outputs diverging from the
pre-crash record — is an **attributed fault** (``crash:recovery_failed``
/ ``crash:replay_divergence``, recorded against the crashed node in its
own fault log), never a harness exception: the soak cell fails its
verdict with evidence instead of killing the run.

Determinism: the manager draws no entropy of its own — replay rng states
come from the WAL, node choice falls back to the highest-id honest node
(the LaggardAdversary convention), and all bookkeeping iterates sorted
ids — so a seeded soak replays its crash/restart trace bit-for-bit.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Tuple

from hbbft_tpu.core.fault_log import Fault
from hbbft_tpu.obs import critpath as _critpath
from hbbft_tpu.utils.snapshot import SnapshotError, load_node, save_node

if TYPE_CHECKING:  # pragma: no cover
    from hbbft_tpu.net.virtual_net import NetMessage, VirtualNet


def _default_checkpoint_every() -> int:
    return max(1, int(os.environ.get("HBBFT_TPU_CHECKPOINT_EVERY", "4")))


@dataclass(frozen=True)
class CrashEvent:
    """One crash (and optional restart) of one node.

    ``node_id=None`` resolves to the highest-id honest node when the
    schedule arms (deterministic for a given seed — same convention as
    :class:`~hbbft_tpu.net.adversary.LaggardAdversary`).  The crash
    fires when the node has committed ``at_epoch`` batches (or at
    virtual-clock time ``at``, whichever is set); the restart fires once
    the rest of the honest network has advanced ``down_epochs`` further
    batches (or after ``down_ticks`` virtual-clock ticks).  A down node
    whose restart is epoch-gated restarts immediately if the network
    starves without it — the starvation-release convention that keeps a
    misconfigured cell diagnosable instead of silently dead."""

    node_id: Any = None
    at_epoch: Optional[int] = 1
    at: Optional[int] = None
    down_epochs: Optional[int] = 2
    down_ticks: Optional[int] = None
    restart: bool = True


class CrashSchedule:
    """The crash axis of a scenario cell: which nodes die when, how long
    they stay down, and how often their state is checkpointed."""

    def __init__(
        self,
        events: Tuple[CrashEvent, ...] = (),
        checkpoint_every: Optional[int] = None,
        recommit_epochs: int = 3,
    ) -> None:
        self.events = tuple(events)
        self.checkpoint_every = (
            checkpoint_every
            if checkpoint_every is not None
            else _default_checkpoint_every()
        )
        #: the recovery gate: a restarted node must be within this many
        #: committed batches of the honest maximum by the end of a soak
        self.recommit_epochs = recommit_epochs

    def describe(self) -> Dict[str, Any]:
        return {
            "events": len(self.events),
            "checkpoint_every": self.checkpoint_every,
            "recommit_epochs": self.recommit_epochs,
        }


class _NodeTrack:
    """Per-node crash-axis state: pending events, checkpoint, WAL."""

    def __init__(self, events: List[CrashEvent]) -> None:
        self.pending: List[CrashEvent] = list(events)
        self.state = "up"  # "up" | "down" | "restoring" | "failed"
        self.down_since_now = 0
        self.down_since_crank = 0
        self.outputs_at_crash = 0
        self.restart_at_now: Optional[int] = None
        self.restart_epoch_mark: Optional[int] = None
        self.restart_pending = False
        # checkpoint: algorithm blob + the harness-side marks that pair it
        self.ckpt_blob: Optional[bytes] = None
        self.ckpt_outputs = 0
        self.ckpt_faults = 0
        self.ckpt_epoch: Tuple[int, int] = (0, 0)
        # write-ahead log since the checkpoint: ("m", rng_state, sender,
        # payload) for deliveries, ("i", rng_state, input, None) for inputs
        self.wal: List[Tuple[str, Any, Any, Any]] = []
        # ordered (to, payload) emissions since the checkpoint, and the
        # replay cursor consumed against it during restart
        self.sent: List[Tuple[Any, Any]] = []
        self.sent_cursor = 0
        self.diverged = False
        # traffic/input parked while down (re-enqueued at restart)
        self.parked: List["NetMessage"] = []
        self.parked_inputs: List[Any] = []
        # evidence for the soak verdicts
        self.crashes = 0
        self.restarts = 0
        self.recoveries: List[Dict[str, Any]] = []

    @property
    def active(self) -> bool:
        """Still worth logging for: a crash is pending or in progress."""
        return bool(self.pending) or self.state != "up"


#: wrapper-chain depth bound (SenderQueue ⊃ QHB ⊃ DHB ⊃ HB is 4; the
#: bound replaces an id()-based cycle guard, which the determinism lint
#: rightly bans)
_MAX_WRAP_DEPTH = 8


def _era_epoch(algo: Any) -> Tuple[int, int]:
    """(era, epoch) of an algorithm stack, for checkpoint reporting.
    Duck-typed walk through SenderQueue/QHB wrappers (obs/health.py's
    unwrap convention); totals to (0, 0) on unknown shapes."""
    for _ in range(_MAX_WRAP_DEPTH):
        for attr in ("algo", "dhb"):
            inner = getattr(algo, attr, None)
            if inner is not None and hasattr(inner, "handle_message"):
                algo = inner
                break
        else:
            break
    hb = getattr(algo, "hb", None)
    if hb is not None:
        return (getattr(algo, "era", 0), getattr(hb, "epoch", 0))
    return (0, getattr(algo, "epoch", 0))


def _find_rng(algo: Any) -> Optional[Any]:
    """The rng object the algorithm stack draws from internally (QHB/DHB
    store the builder rng; plain HB takes it per call)."""
    for _ in range(_MAX_WRAP_DEPTH):
        rng = getattr(algo, "rng", None)
        if rng is not None and hasattr(rng, "getstate"):
            return rng
        for attr in ("algo", "dhb", "hb"):
            inner = getattr(algo, attr, None)
            if inner is not None and hasattr(inner, "handle_message"):
                algo = inner
                break
        else:
            return None
    return None


def _rebind_rng(algo: Any, rng: Any) -> None:
    """Point every wrapper layer's stored rng back at the net's shared
    stream (post-replay: the restored clone's job is done, and future
    deliveries log the shared rng's state for any *second* crash)."""
    for _ in range(_MAX_WRAP_DEPTH):
        if algo is None:
            return
        if hasattr(getattr(algo, "rng", None), "getstate"):
            algo.rng = rng
        nxt = None
        for attr in ("algo", "dhb", "hb"):
            inner = getattr(algo, attr, None)
            if inner is not None and hasattr(inner, "handle_message"):
                nxt = inner
                break
        algo = nxt


class CrashManager:
    """VirtualNet's crash axis driver.  All hooks are total: a failure
    inside recovery becomes an attributed ``crash:*`` fault, never an
    exception out of the crank loop."""

    #: environment, not state: live callables installed by drivers (e.g.
    #: ObjectTrafficDriver re-installing its sample_listener on the
    #: restored algorithm).  Whole-net snapshots drop them.
    restart_listeners = ()
    _SNAPSHOT_ENV_ATTRS = ("restart_listeners",)

    def __init__(self, schedule: CrashSchedule) -> None:
        self.schedule = schedule
        self.tracks: Dict[Any, _NodeTrack] = {}
        self._order: List[Any] = []
        self._armed = False
        self._replaying: Any = None
        self.restart_listeners: List[Any] = []

    def add_restart_listener(self, fn) -> None:
        """Register a restart hook.  Rebinds instead of appending so it
        also works on a restored manager, whose env-attr fallback is the
        immutable class-level ``()``."""
        self.restart_listeners = list(self.restart_listeners) + [fn]

    # -- introspection -------------------------------------------------------

    def down_ids(self) -> frozenset:
        return frozenset(
            nid for nid in self._order if self.tracks[nid].state != "up"
        )

    def is_down(self, node_id) -> bool:
        t = self.tracks.get(node_id)
        return t is not None and t.state != "up"

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"crashes": 0, "restarts": 0, "recoveries": []}
        for nid in self._order:
            t = self.tracks[nid]
            out["crashes"] += t.crashes
            out["restarts"] += t.restarts
            out["recoveries"].extend(t.recoveries)
        return out

    def describe(self, now: int) -> Dict[str, Any]:
        """State snapshot for the why-stalled crash context."""
        nodes: Dict[str, Any] = {}
        for nid in self._order:
            t = self.tracks[nid]
            d: Dict[str, Any] = {"state": t.state}
            if t.state != "up":
                d["down_since_crank"] = t.down_since_crank
                d["checkpoint_epoch"] = list(t.ckpt_epoch)
                d["restart_pending"] = t.restart_pending
                if t.restart_at_now is not None:
                    d["restart_at"] = t.restart_at_now
                if t.restart_epoch_mark is not None:
                    d["restart_epoch_mark"] = t.restart_epoch_mark
            elif t.restarts:
                d["restarts"] = t.restarts
            if t.parked:
                d["parked_messages"] = len(t.parked)
            nodes[repr(nid)] = d
        return {"schedule": self.schedule.describe(), "nodes": nodes}

    # -- arming --------------------------------------------------------------

    def _arm(self, net: "VirtualNet") -> None:
        self._armed = True
        by_node: Dict[Any, List[CrashEvent]] = {}
        honest = [n.id for n in net.correct_nodes()]
        fallback = max(honest, key=net.node_order_key) if honest else None
        for ev in self.schedule.events:
            nid = ev.node_id if ev.node_id is not None else fallback
            if nid is None or nid not in net.nodes:
                continue
            by_node.setdefault(nid, []).append(ev)
        self._order = sorted(by_node, key=net.node_order_key)
        for nid in self._order:
            self.tracks[nid] = _NodeTrack(by_node[nid])
            # epoch-0 baseline: a node that dies before its first periodic
            # checkpoint still has a recovery point
            self._checkpoint(net, nid)

    # -- crank hooks (called by VirtualNet; must never raise) ----------------

    def on_crank(self, net: "VirtualNet") -> None:
        """Fire due crashes and due restarts (start-of-crank)."""
        if not self._armed:
            self._arm(net)
        for nid in self._order:
            t = self.tracks[nid]
            if t.state == "up" and t.pending:
                ev = t.pending[0]
                due = (ev.at is not None and net.now >= ev.at) or (
                    ev.at_epoch is not None
                    and len(net.nodes[nid].outputs) >= ev.at_epoch
                )
                if due:
                    self._crash(net, nid, ev)
            elif t.state == "down" and t.restart_pending:
                if self._restart_due(net, t):
                    self._restart(net, nid)

    def on_idle(self, net: "VirtualNet") -> bool:
        """Quiescence handling: fast-forward the virtual clock to the
        next tick-gated event, and starvation-release any epoch-gated
        restart (the net drained without the node, so nothing will ever
        advance the epoch mark).  Returns True when an event fired."""
        if not self._armed and self.schedule.events:
            self._arm(net)
        fired = False
        ticks = [
            t.restart_at_now
            for nid in self._order
            for t in (self.tracks[nid],)
            if t.state == "down" and t.restart_pending
            and t.restart_at_now is not None
        ] + [
            t.pending[0].at
            for nid in self._order
            for t in (self.tracks[nid],)
            if t.state == "up" and t.pending and t.pending[0].at is not None
        ]
        if ticks:
            net.now = max(net.now, min(ticks))
            self.on_crank(net)
            fired = True
        for nid in self._order:
            t = self.tracks[nid]
            # starvation release is for EPOCH-gated restarts only (their
            # mark can never advance on a drained net); a tick-gated
            # restart keeps its configured outage — the fast-forward
            # branch above reaches it when its time comes
            if (
                t.state == "down"
                and t.restart_pending
                and t.restart_at_now is None
            ):
                self._restart(net, nid)
                fired = True
        return fired

    def after_crank(self, net: "VirtualNet") -> None:
        """Periodic checkpointing at the quiescent crank boundary."""
        if not self._armed or net._pending_work:
            return
        for nid in self._order:
            t = self.tracks[nid]
            if (
                t.state == "up"
                and t.pending
                and len(net.nodes[nid].outputs) - t.ckpt_outputs
                >= self.schedule.checkpoint_every
            ):
                self._checkpoint(net, nid)

    def on_deliver(self, net: "VirtualNet", msg: "NetMessage") -> None:
        """WAL a delivery to a crash-tracked node (pre-handling, with the
        shared rng's pre-handling state)."""
        # lint: allow[seam-race] live WAL append vs replay read is the axis's
        # one seam: _restart only runs between cranks, never concurrently
        t = self.tracks.get(msg.to)
        if t is not None and t.state == "up" and t.pending:
            t.wal.append(("m", net.rng.getstate(), msg.sender, msg.payload))

    def on_input(self, net: "VirtualNet", node_id, input: Any) -> bool:
        """Park inputs to a down node (True = consumed); WAL inputs to a
        crash-tracked live node."""
        t = self.tracks.get(node_id)
        if t is None:
            return False
        if t.state != "up":
            t.parked_inputs.append(input)
            return True
        if t.pending:
            t.wal.append(("i", net.rng.getstate(), input, None))
        return False

    def on_send(self, net: "VirtualNet", node: Any, msg: "NetMessage") -> bool:
        """Sent-log bookkeeping.  During a replay, emissions matching the
        pre-crash record are suppressed (True) — they were already
        delivered; a mismatch marks the recovery diverged and lets the
        message through (peers will fault the double-send, which is the
        point: divergence must be visible evidence)."""
        # lint: allow[seam-race] _restart sets _replaying around a synchronous
        # replay loop; the crank loop is single-threaded so no interleaving
        if self._replaying == node.id:
            t = self.tracks[node.id]
            if t.sent_cursor < len(t.sent):
                to, payload = t.sent[t.sent_cursor]
                if to == msg.to and payload == msg.payload:
                    t.sent_cursor += 1
                    net.counters.crash_suppressed_sends += 1
                    return True
            t.diverged = True
            return False
        t = self.tracks.get(node.id)
        if t is not None and t.state == "up" and t.pending:
            t.sent.append((msg.to, msg.payload))
        return False

    def on_enqueue(self, net: "VirtualNet", msg: "NetMessage") -> bool:
        """Park traffic addressed to a down node (True = consumed): the
        simulator's stand-in for link-layer retransmission."""
        t = self.tracks.get(msg.to)
        if t is not None and t.state != "up":
            t.parked.append(msg)
            net.counters.crash_parked_messages += 1
            return True
        return False

    # -- the axis itself -----------------------------------------------------

    def _fault(self, net: "VirtualNet", nid, kind: str) -> None:
        net.nodes[nid].faults_observed.append(Fault(nid, kind))
        net.counters.faults_recorded += 1

    def _checkpoint(self, net: "VirtualNet", nid) -> None:
        node = net.nodes[nid]
        t = self.tracks[nid]
        try:
            blob = save_node(node.algorithm)
        except SnapshotError:
            # a stale recovery point, visibly attributed — not a crash of
            # the harness and not a silently-skipped checkpoint
            self._fault(net, nid, "crash:checkpoint_failed")
            return
        t.ckpt_blob = blob
        t.ckpt_outputs = len(node.outputs)
        t.ckpt_faults = len(node.faults_observed)
        t.ckpt_epoch = _era_epoch(node.algorithm)
        t.wal = []
        t.sent = []
        net.counters.crash_checkpoints += 1

    def _max_honest_outputs(self, net: "VirtualNet") -> int:
        best = 0
        for node in net.correct_nodes():
            if not self.is_down(node.id):
                best = max(best, len(node.outputs))
        return best

    def _crash(self, net: "VirtualNet", nid, ev: CrashEvent) -> None:
        import heapq

        t = self.tracks[nid]
        t.pending.pop(0)
        t.state = "down"
        t.crashes += 1
        t.down_since_now = net.now
        t.down_since_crank = net.cranks
        t.outputs_at_crash = len(net.nodes[nid].outputs)
        t.restart_pending = ev.restart
        t.restart_at_now = (
            net.now + ev.down_ticks if ev.down_ticks is not None else None
        )
        t.restart_epoch_mark = (
            self._max_honest_outputs(net) + ev.down_epochs
            if ev.down_epochs is not None
            else None
        )
        net.counters.node_crashes += 1
        # sweep in-flight traffic addressed to the dead node into the
        # parked store: live queue + the schedule layer's future heap
        # (entries are (not_before, seq, msg) with unique seq, so sorting
        # never compares messages — the LaggardAdversary convention)
        held = [m for m in net.queue if m.to == nid]
        if held:
            net.queue[:] = [m for m in net.queue if m.to != nid]
            t.parked.extend(held)
            net.counters.crash_parked_messages += len(held)
        fut = net._future
        if fut and any(e[2].to == nid for e in fut):
            fut_held = sorted(e for e in fut if e[2].to == nid)
            fut[:] = [e for e in fut if e[2].to != nid]
            heapq.heapify(fut)
            t.parked.extend(e[2] for e in fut_held)
            net.counters.crash_parked_messages += len(fut_held)

    def _restart_due(self, net: "VirtualNet", t: _NodeTrack) -> bool:
        if t.restart_at_now is not None and net.now >= t.restart_at_now:
            return True
        return (
            t.restart_epoch_mark is not None
            and self._max_honest_outputs(net) >= t.restart_epoch_mark
        )

    def _restart(self, net: "VirtualNet", nid) -> None:
        t = self.tracks[nid]
        node = net.nodes[nid]
        t.restart_pending = False
        t.state = "restoring"
        if t.ckpt_blob is None:
            self._fault(net, nid, "crash:recovery_failed")
            t.state = "failed"
            return
        pre_tail = list(node.outputs[t.ckpt_outputs :])
        try:
            algo = load_node(t.ckpt_blob, net.backend)
        except SnapshotError:
            self._fault(net, nid, "crash:recovery_failed")
            t.state = "failed"
            return
        node.algorithm = algo
        del node.outputs[t.ckpt_outputs :]
        # protocol faults in the truncated tail re-emerge from the replay;
        # manager-attributed crash:* evidence does not — preserve it
        preserved = [
            f
            for f in node.faults_observed[t.ckpt_faults :]
            if f.kind.startswith("crash:")
        ]
        del node.faults_observed[t.ckpt_faults :]
        node.faults_observed.extend(preserved)
        replay_rng = _find_rng(algo)
        if replay_rng is None:
            replay_rng = net.rng.__class__()
        t.sent_cursor = 0
        t.diverged = False
        self._replaying = nid
        # WAL-replay window: protocol completion stamps fired while the
        # node catches up attribute to the crash:recovery pseudo-phase —
        # a restart-gated epoch must name the recovering node, not the
        # phase the replay happened to re-run.
        rec = _critpath.active()
        if rec is not None:
            rec.begin_recovery(nid)
        try:
            for kind, state, a, b in t.wal:
                replay_rng.setstate(state)
                if kind == "m":
                    step = node.algorithm.handle_message(a, b, rng=replay_rng)
                else:
                    step = node.algorithm.handle_input(a, rng=replay_rng)
                net._process_step(node, step)
                net.counters.crash_replayed_events += 1
        except Exception:
            self._fault(net, nid, "crash:recovery_failed")
            t.state = "failed"
            return
        finally:
            self._replaying = None
            if rec is not None:
                rec.end_recovery()
        if (
            t.diverged
            or t.sent_cursor != len(t.sent)
            or node.outputs[t.ckpt_outputs :] != pre_tail
        ):
            self._fault(net, nid, "crash:replay_divergence")
        _rebind_rng(node.algorithm, net.rng)
        t.state = "up"
        t.restarts += 1
        net.counters.node_restarts += 1
        t.recoveries.append(
            {
                "node": repr(nid),
                "down_cranks": net.cranks - t.down_since_crank,
                "checkpoint_epoch": list(t.ckpt_epoch),
                "replayed_events": len(t.wal),
                "recommitted": len(node.outputs) - t.ckpt_outputs,
                "behind_after_replay": max(
                    0, self._max_honest_outputs(net) - len(node.outputs)
                ),
                "restart_crank": net.cranks,
            }
        )
        parked, t.parked = t.parked, []
        for msg in parked:
            net._enqueue(msg)
        parked_inputs, t.parked_inputs = t.parked_inputs, []
        for inp in parked_inputs:
            net.send_input(nid, inp)
        # lint: allow[replay-purity] post-replay env reattachment: the WAL
        # loop above has already sealed replayed state; listeners exist
        # precisely so the environment (traffic driver, controller) can
        # re-install its checkpoint-detached hooks on the fresh instance
        for fn in self.restart_listeners:
            try:
                fn(net, nid, node.algorithm)
            except Exception:
                self._fault(net, nid, "crash:recovery_failed")
