"""Adversary framework + attack library for the VirtualNet simulator.

Rebuild of `tests/net/adversary.rs` § (SURVEY.md §2.1): an adversary gets two
hooks — ``pre_crank`` (observe/reorder/inject before each delivery) and
``tamper`` (rewrite traffic originating from faulty nodes).  Used by every
protocol integration test to exercise Byzantine scheduling and corruption.

The attack library (ROADMAP item 4) covers the concrete misbehaviors the
CCS 2016 liveness claim must survive:

* :class:`EquivocatingAdversary` — conflicting RBC ``Value``\\ s per
  recipient (provable ``broadcast:conflicting_values``),
* :class:`WithholdingAdversary` — withheld echoes/readies/threshold
  shares up to the f-boundary (crash-style liveness pressure, no
  provable evidence),
* :class:`CraftedShareAdversary` — well-typed-but-invalid threshold
  shares at a configurable contamination rate (the RLC bisection's
  worst case; ``threshold_sign:invalid_sig_share`` /
  ``threshold_decrypt:invalid_share``),
* :class:`ReplayAdversary` — duplicate floods (``broadcast:
  multiple_echos`` / ``multiple_readys`` under exactly-once delivery),
* :class:`LaggardAdversary` — one honest node lags behind, then catches
  up (the state-transfer-free catch-up path).

All entropy comes from ``net.rng`` — the run's single seeded stream — so
every attack replays bit-identically for a given seed.  Tamper hooks
never raise on unrecognized payloads: a message the attack doesn't
understand passes through untouched (the same no-crash discipline the
byzantine-input lint family enforces on protocol handlers).
"""

from __future__ import annotations

import heapq

from dataclasses import replace
from typing import TYPE_CHECKING, Any, Callable, Dict, List, Optional, Tuple

if TYPE_CHECKING:  # pragma: no cover
    from hbbft_tpu.net.virtual_net import NetMessage, VirtualNet


class Adversary:
    """Default: passive (deliver in scheduler order, no tampering).

    ``scheduler_override``: adversaries whose power *is* delivery order set
    this to ``"first"`` so VirtualNet pops the queue head they arranged in
    ``pre_crank`` (the default random scheduler would erase the ordering).
    """

    scheduler_override: Optional[str] = None

    def pre_crank(self, net: "VirtualNet") -> None:
        """Called before each crank; may reorder/inject into ``net.queue``."""

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        """Rewrite a message sent by a *faulty* node.  Return the (possibly
        empty, possibly longer) list of messages to enqueue instead."""
        return [msg]

    def describe(self) -> Dict[str, Any]:
        """Attack identity for the why-stalled report (name + knobs)."""
        return {"name": type(self).__name__}


class NullAdversary(Adversary):
    pass


class NodeOrderAdversary(Adversary):
    """Delivers messages grouped by recipient id order — a scheduling game
    that starves late nodes (reference `NodeOrderAdversary` §)."""

    scheduler_override = "first"

    def pre_crank(self, net: "VirtualNet") -> None:
        if net.queue:
            net.queue.sort(key=lambda m: net.node_order_key(m.to))


class ReorderingAdversary(Adversary):
    """Randomly shuffles the pending queue every crank (seeded)."""

    scheduler_override = "first"

    def pre_crank(self, net: "VirtualNet") -> None:
        net.rng.shuffle(net.queue)


class SilentAdversary(Adversary):
    """Faulty nodes never send anything (crash-style faults)."""

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        return []


class RandomAdversary(Adversary):
    """Replaces faulty nodes' traffic with random well-typed messages.

    ``generator(net, msg)`` produces a replacement payload; with probability
    ``p_replace`` the original message is swapped, otherwise passed through.
    (Reference `RandomAdversary` § generates random well-typed messages via
    proptest strategies; here the per-protocol test supplies the generator.)
    """

    def __init__(
        self,
        generator: Callable[["VirtualNet", "NetMessage"], object],
        p_replace: float = 0.5,
        p_drop: float = 0.0,
    ) -> None:
        # lint: allow[hook-detachment] the generator is adversary structure,
        # not environment: snapshot-bearing runs supply module-level
        # generator functions (serialized by name), and env-dropping it
        # would turn a restored RandomAdversary into a silent pass-through
        self.generator = generator
        self.p_replace = p_replace
        self.p_drop = p_drop

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        from hbbft_tpu.net.virtual_net import NetMessage

        roll = net.rng.random()
        if roll < self.p_drop:
            return []
        if roll < self.p_drop + self.p_replace:
            payload = self.generator(net, msg)
            if payload is None:
                return []
            return [NetMessage(msg.sender, msg.to, payload)]
        return [msg]

    def describe(self) -> Dict[str, Any]:
        return {
            "name": type(self).__name__,
            "p_replace": self.p_replace,
            "p_drop": self.p_drop,
        }


# ---------------------------------------------------------------------------
# Message surgery: the protocol stack wraps Broadcast/BA/share messages in
# Subset/HB/DHB envelopes; an attack on the innermost message rebuilds the
# envelope chain around the tampered payload.
# ---------------------------------------------------------------------------


def locate_inner(
    payload: Any, match: Callable[[Any], bool]
) -> Tuple[Any, Optional[Callable[[Any], Any]]]:
    """Find the innermost sub-message satisfying ``match`` inside the
    envelope chain DHB ⊃ HB ⊃ Subset ⊃ {Broadcast | BA ⊃ Coin}.

    Returns ``(inner, rebuild)`` where ``rebuild(new_inner)`` produces the
    whole payload with only the matched message replaced, or
    ``(None, None)`` when nothing matches — the caller passes the message
    through untouched (tamper hooks never raise on unknown shapes)."""
    from hbbft_tpu.protocols.binary_agreement import BaMessage
    from hbbft_tpu.protocols.dynamic_honey_badger import DhbMessage
    from hbbft_tpu.protocols.honey_badger import HbMessage
    from hbbft_tpu.protocols.sender_queue import SqMessage
    from hbbft_tpu.protocols.subset import SubsetMessage

    if match(payload):
        return payload, lambda m: m
    descend = (
        isinstance(payload, (DhbMessage, SubsetMessage))
        or (isinstance(payload, HbMessage) and payload.kind in ("subset", "dec_share"))
        or (isinstance(payload, BaMessage) and payload.kind == "coin")
        or (isinstance(payload, SqMessage) and payload.kind == "algo")
    )
    if descend:
        inner, rebuild = locate_inner(payload.payload, match)
        if inner is not None:
            return inner, lambda m, rb=rebuild: replace(payload, payload=rb(m))
    return None, None


def classify_inner(payload: Any) -> Optional[str]:
    """Traffic class of the innermost protocol message: one of
    ``{"value", "echo", "ready", "sig_share", "dec_share"}`` or None."""
    from hbbft_tpu.protocols.broadcast import BroadcastMessage
    from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecryptMessage
    from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage

    def interesting(m: Any) -> bool:
        return isinstance(
            m, (BroadcastMessage, ThresholdSignMessage, ThresholdDecryptMessage)
        )

    inner, _ = locate_inner(payload, interesting)
    if inner is None:
        return None
    if isinstance(inner, BroadcastMessage):
        return inner.kind if inner.kind in ("value", "echo", "ready") else None
    if isinstance(inner, ThresholdSignMessage):
        return "sig_share"
    return "dec_share"


# ---------------------------------------------------------------------------
# Attack library
# ---------------------------------------------------------------------------


class EquivocatingAdversary(Adversary):
    """Faulty proposers equivocate: every recipient of an RBC ``Value``
    additionally receives a *conflicting* ``Value`` for its own shard
    index, committed to an alternative Merkle root.  Under exactly-once
    delivery the second Value is provable proposer misbehaviour —
    ``broadcast:conflicting_values`` — and the honest majority still
    terminates (the losing root never reaches an Echo quorum that the
    winning root's totality argument doesn't subsume)."""

    def __init__(self, alt_value: bytes = b"equivocated contribution") -> None:
        self.alt_value = alt_value
        self._alt_trees: Dict[Tuple[Any, int], Any] = {}

    def _alt_tree(self, sender: Any, n: int):
        from hbbft_tpu.crypto.erasure import rs_codec
        from hbbft_tpu.crypto.merkle import MerkleTree

        key = (sender, n)
        tree = self._alt_trees.get(key)
        if tree is None:
            f = (n - 1) // 3
            value = self.alt_value + b"/" + repr(sender).encode()
            framed = len(value).to_bytes(4, "big") + value
            shards = rs_codec(n - 2 * f, 2 * f).encode(framed)
            tree = MerkleTree(shards)
            self._alt_trees[key] = tree
        return tree

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        from hbbft_tpu.net.virtual_net import NetMessage
        from hbbft_tpu.protocols.broadcast import BroadcastMessage

        inner, rebuild = locate_inner(
            msg.payload,
            lambda m: isinstance(m, BroadcastMessage) and m.kind == "value",
        )
        if inner is None:
            return [msg]
        n = len(net.nodes)
        idx = net.node_order_key(msg.to)
        if idx >= n:  # recipient outside the modelled id set
            return [msg]
        alt_proof = self._alt_tree(msg.sender, n).proof(idx)
        alt = rebuild(BroadcastMessage.value(alt_proof))
        return [msg, NetMessage(msg.sender, msg.to, alt)]

    def describe(self) -> Dict[str, Any]:
        return {"name": type(self).__name__, "alt_value": repr(self.alt_value)}


class WithholdingAdversary(Adversary):
    """Faulty nodes withhold selected traffic classes — echoes, readys,
    threshold shares — in full or at a seeded ``fraction``.  Withholding
    is not provable misbehaviour (no fault expected); the honest N−f must
    carry every quorum, which sizes the attack exactly to the f-boundary
    (the tamper hook only ever fires for faulty senders)."""

    def __init__(
        self,
        kinds: Tuple[str, ...] = ("echo", "ready", "sig_share", "dec_share"),
        fraction: float = 1.0,
    ) -> None:
        self.kinds = tuple(kinds)
        self.fraction = fraction

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        if classify_inner(msg.payload) not in self.kinds:
            return [msg]
        if self.fraction < 1.0 and net.rng.random() >= self.fraction:
            return [msg]
        return []

    def describe(self) -> Dict[str, Any]:
        return {
            "name": type(self).__name__,
            "kinds": list(self.kinds),
            "fraction": self.fraction,
        }


class CraftedShareAdversary(Adversary):
    """Faulty nodes replace outgoing threshold shares with *well-typed*
    garbage group elements at a configurable contamination ``rate``
    (drawn per message copy from ``net.rng``).  This is the RLC
    bisection's adversarial shape: every crafted share must be rejected,
    attributed (``threshold_sign:invalid_sig_share`` /
    ``threshold_decrypt:invalid_share``), and must never reach a
    combine."""

    def __init__(
        self,
        rate: float = 1.0,
        kinds: Tuple[str, ...] = ("sig_share", "dec_share"),
    ) -> None:
        self.rate = rate
        self.kinds = tuple(kinds)

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        from hbbft_tpu.crypto.keys import DecryptionShare, SignatureShare
        from hbbft_tpu.net.virtual_net import NetMessage
        from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecryptMessage
        from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage

        wanted = []
        if "sig_share" in self.kinds:
            wanted.append(ThresholdSignMessage)
        if "dec_share" in self.kinds:
            wanted.append(ThresholdDecryptMessage)
        inner, rebuild = locate_inner(
            msg.payload, lambda m: isinstance(m, tuple(wanted))
        )
        if inner is None:
            return [msg]
        if self.rate < 1.0 and net.rng.random() >= self.rate:
            return [msg]
        group = net.backend.group
        r = net.rng.randrange(1, 1 << 64)
        if isinstance(inner, ThresholdSignMessage):
            crafted: Any = ThresholdSignMessage(
                SignatureShare(group, group.g2_mul(r, group.g2()))
            )
        else:
            crafted = ThresholdDecryptMessage(
                DecryptionShare(group, group.g1_mul(r, group.g1()))
            )
        return [NetMessage(msg.sender, msg.to, rebuild(crafted))]

    def describe(self) -> Dict[str, Any]:
        return {
            "name": type(self).__name__,
            "rate": self.rate,
            "kinds": list(self.kinds),
        }


class ReplayAdversary(Adversary):
    """Duplicate flood: every message a faulty node sends is enqueued
    ``copies`` times.  Under the simulator's exactly-once delivery a
    re-sent Echo/Ready is provable (``broadcast:multiple_echos`` /
    ``multiple_readys``); share re-sends are legal and must be absorbed
    silently — the flood tests both paths plus queue pressure."""

    def __init__(self, copies: int = 3) -> None:
        if copies < 2:
            raise ValueError("ReplayAdversary needs copies >= 2")
        self.copies = copies

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        from hbbft_tpu.net.virtual_net import NetMessage

        return [msg] + [
            NetMessage(msg.sender, msg.to, msg.payload)
            for _ in range(self.copies - 1)
        ]

    def describe(self) -> Dict[str, Any]:
        return {"name": type(self).__name__, "copies": self.copies}


class LaggardAdversary(Adversary):
    """One *honest* node lags behind: traffic addressed to it is held in
    a side buffer until ``lag_cranks`` deliveries elapsed (or the rest of
    the network would starve without it), then released all at once — the
    lag-behind-then-catch-up path that exercises future-epoch buffering
    and quorum progress at N−1 live nodes.  The laggard defaults to the
    highest-id honest node (deterministic for a given seed)."""

    def __init__(self, lag_cranks: int = 400, node_id: Any = None) -> None:
        self.lag_cranks = lag_cranks
        self.node_id = node_id
        self._held: List["NetMessage"] = []
        self._released = False

    def laggard(self, net: "VirtualNet") -> Any:
        if self.node_id is None:
            honest = [n.id for n in net.correct_nodes()]
            if honest:
                self.node_id = max(honest, key=net.node_order_key)
        return self.node_id

    def pre_crank(self, net: "VirtualNet") -> None:
        lid = self.laggard(net)
        if lid is None or self._released:
            return
        held = [m for m in net.queue if m.to == lid]
        if held:
            net.queue[:] = [m for m in net.queue if m.to != lid]
            self._held.extend(held)
        # The hold must also sweep the schedule layer's future-dated
        # heap: crank() fast-forwards the virtual clock AFTER pre_crank
        # when the live queue drains, so a laggard-bound message left on
        # the heap could be released and delivered mid-lag.  Entries are
        # (not_before, seq, msg) with unique seq, so sorting never
        # compares messages.
        fut = getattr(net, "_future", None)
        if fut and any(e[2].to == lid for e in fut):
            fut_held = sorted(e for e in fut if e[2].to == lid)
            fut[:] = [e for e in fut if e[2].to != lid]
            heapq.heapify(fut)
            self._held.extend(e[2] for e in fut_held)
        # Starvation check covers the future heap too: with the laggard's
        # traffic held, remaining future messages mean the net
        # fast-forwards rather than starving, so the lag must hold —
        # releasing on a momentarily empty live queue would degenerate
        # the attack under any latency schedule.
        starved = not net.queue and not getattr(net, "_future", None)
        if self._held and (net.cranks >= self.lag_cranks or starved):
            net.queue.extend(self._held)
            self._held.clear()
            self._released = True

    def describe(self) -> Dict[str, Any]:
        return {
            "name": type(self).__name__,
            "laggard": repr(self.node_id),
            "lag_cranks": self.lag_cranks,
            "released": self._released,
            "held": len(self._held),
        }
