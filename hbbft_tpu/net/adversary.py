"""Adversary framework for the VirtualNet simulator.

Rebuild of `tests/net/adversary.rs` § (SURVEY.md §2.1): an adversary gets two
hooks — ``pre_crank`` (observe/reorder/inject before each delivery) and
``tamper`` (rewrite traffic originating from faulty nodes).  Used by every
protocol integration test to exercise Byzantine scheduling and corruption.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, List, Optional

if TYPE_CHECKING:  # pragma: no cover
    from hbbft_tpu.net.virtual_net import NetMessage, VirtualNet


class Adversary:
    """Default: passive (deliver in scheduler order, no tampering).

    ``scheduler_override``: adversaries whose power *is* delivery order set
    this to ``"first"`` so VirtualNet pops the queue head they arranged in
    ``pre_crank`` (the default random scheduler would erase the ordering).
    """

    scheduler_override: Optional[str] = None

    def pre_crank(self, net: "VirtualNet") -> None:
        """Called before each crank; may reorder/inject into ``net.queue``."""

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        """Rewrite a message sent by a *faulty* node.  Return the (possibly
        empty, possibly longer) list of messages to enqueue instead."""
        return [msg]


class NullAdversary(Adversary):
    pass


class NodeOrderAdversary(Adversary):
    """Delivers messages grouped by recipient id order — a scheduling game
    that starves late nodes (reference `NodeOrderAdversary` §)."""

    scheduler_override = "first"

    def pre_crank(self, net: "VirtualNet") -> None:
        if net.queue:
            net.queue.sort(key=lambda m: net.node_order_key(m.to))


class ReorderingAdversary(Adversary):
    """Randomly shuffles the pending queue every crank (seeded)."""

    scheduler_override = "first"

    def pre_crank(self, net: "VirtualNet") -> None:
        net.rng.shuffle(net.queue)


class SilentAdversary(Adversary):
    """Faulty nodes never send anything (crash-style faults)."""

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        return []


class RandomAdversary(Adversary):
    """Replaces faulty nodes' traffic with random well-typed messages.

    ``generator(net, msg)`` produces a replacement payload; with probability
    ``p_replace`` the original message is swapped, otherwise passed through.
    (Reference `RandomAdversary` § generates random well-typed messages via
    proptest strategies; here the per-protocol test supplies the generator.)
    """

    def __init__(
        self,
        generator: Callable[["VirtualNet", "NetMessage"], object],
        p_replace: float = 0.5,
        p_drop: float = 0.0,
    ) -> None:
        self.generator = generator
        self.p_replace = p_replace
        self.p_drop = p_drop

    def tamper(self, net: "VirtualNet", msg: "NetMessage") -> List["NetMessage"]:
        from hbbft_tpu.net.virtual_net import NetMessage

        roll = net.rng.random()
        if roll < self.p_drop:
            return []
        if roll < self.p_drop + self.p_replace:
            payload = self.generator(net, msg)
            if payload is None:
                return []
            return [NetMessage(msg.sender, msg.to, payload)]
        return [msg]
