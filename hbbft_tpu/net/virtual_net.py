"""VirtualNet — deterministic single-process network simulator and runtime.

Rebuild of the reference's `tests/net/` harness (`VirtualNet`, `NetBuilder`,
`CrankError` §, SURVEY.md §2.1/§3.3), promoted from test utility to **the**
framework runtime: on TPU, this driver is also the crypto-batch accumulator —
deferred :class:`~hbbft_tpu.core.types.CryptoWork` items emitted by protocol
steps are resolved either immediately (``defer_mode="eager"``, reference
semantics) or accumulated across a whole crank round and resolved in one
batched device call (``defer_mode="round"`` — the SURVEY.md §7 round-barrier
design that makes the N=100 pairing load a single dispatch).

Everything is seeded and deterministic: one `random.Random` threaded through
scheduling, adversaries, and protocol RNG needs.
"""

from __future__ import annotations

import heapq
import random
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.types import CryptoWork, Step, TargetedMessage
from hbbft_tpu.crypto.backend import CryptoBackend, MockBackend
from hbbft_tpu.net.adversary import Adversary, NullAdversary
from hbbft_tpu.net.crash import CrashEvent, CrashManager, CrashSchedule
from hbbft_tpu.obs.tracer import Tracer
from hbbft_tpu.utils.metrics import Counters, EventLog

__all__ = [
    "CrankError",
    "CrashEvent",
    "CrashSchedule",
    "NetBuilder",
    "NetMessage",
    "NetSchedule",
    "Node",
    "Partition",
    "VirtualNet",
]


class CrankError(Exception):
    """Limit exceeded or invariant broken while cranking.

    ``report`` (when raised by :class:`VirtualNet`) carries the
    :func:`hbbft_tpu.obs.health.why_stalled` diagnosis taken at the
    moment of the trip — the starved protocol instances, the active
    adversary/scenario, and the schedule state — so a tripped limit is
    never a bare number without a culprit."""

    def __init__(self, message: str, report: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.report = report


@dataclass
class NetMessage:
    """An in-flight message (reference `NetMessage` §).

    ``not_before`` is the earliest virtual-clock time (``VirtualNet.now``)
    the schedule layer allows this message to be delivered; 0 means
    immediately eligible (the default when no schedule is attached)."""

    sender: Any
    to: Any
    payload: Any
    not_before: int = 0


@dataclass(frozen=True)
class Partition:
    """One partition episode: while ``start <= now < end`` messages
    crossing between different ``groups`` are held (healed at ``end``)
    or dropped.  Nodes absent from every group share an implicit
    "mainland" side."""

    start: int
    end: int
    groups: Tuple[frozenset, ...]

    def side(self, node) -> int:
        for i, g in enumerate(self.groups):
            if node in g:
                return i
        return -1

    def crosses(self, sender, to) -> bool:
        return self.side(sender) != self.side(to)

    def isolated_sets(self) -> List[set]:
        return [set(g) for g in self.groups]


class NetSchedule:
    """Crank-time network conditions, composable with any adversary.

    Applied at SEND time (when a message enters the queue): each message
    gets a delivery delay in cranks — per-link base latency plus seeded
    jitter — may be dropped i.i.d., and, while a partition episode is
    active, traffic crossing the partition boundary is held until the
    heal time (``partition_mode="heal"``) or dropped
    (``partition_mode="drop"``).  All randomness comes from ``net.rng``
    (the run's single seeded stream), so a schedule never breaks replay
    determinism.

    ``link_latency(sender, to) -> int`` overrides the uniform base
    ``latency`` per directed link (a WAN shape: heterogeneous RTTs).
    """

    def __init__(
        self,
        name: str = "custom",
        latency: int = 0,
        jitter: int = 0,
        drop: float = 0.0,
        link_latency: Optional[Callable[[Any, Any], int]] = None,
        partitions: Sequence[Partition] = (),
        partition_mode: str = "heal",
    ) -> None:
        if partition_mode not in ("heal", "drop"):
            raise ValueError(f"bad partition_mode {partition_mode!r}")
        self.name = name
        self.latency = latency
        self.jitter = jitter
        self.drop = drop
        # lint: allow[hook-detachment] shape callables in snapshotted runs
        # are module-level functions (the encoder serializes them by name);
        # env-dropping link_latency would silently flatten WAN latencies on
        # restore, breaking replay determinism the other way
        self.link_latency = link_latency
        self.partitions = tuple(partitions)
        self.partition_mode = partition_mode

    def active_partition(self, now: int) -> Optional[Partition]:
        for p in self.partitions:
            if p.start <= now < p.end:
                return p
        return None

    def on_send(self, net: "VirtualNet", msg: NetMessage) -> Optional[int]:
        """Delay (in cranks) for ``msg`` entering the queue now, or None
        to drop it.  Must not raise on any message shape: a tampered or
        malformed payload still gets a plain link delay."""
        rng = net.rng
        if self.drop and rng.random() < self.drop:
            return None
        delay = (
            self.link_latency(msg.sender, msg.to)
            if self.link_latency is not None
            else self.latency
        )
        if self.jitter:
            delay += rng.randrange(self.jitter + 1)
        part = self.active_partition(net.now)
        if part is not None and part.crosses(msg.sender, msg.to):
            if self.partition_mode == "drop":
                return None
            delay = max(delay, part.end - net.now)
        return delay

    def describe(self, now: int) -> Dict[str, Any]:
        """State snapshot for the why-stalled report."""
        out: Dict[str, Any] = {"name": self.name}
        if self.latency or self.link_latency is not None:
            out["latency"] = "per-link" if self.link_latency else self.latency
        if self.jitter:
            out["jitter"] = self.jitter
        if self.drop:
            out["drop"] = self.drop
        part = self.active_partition(now)
        if part is not None:
            out["partition"] = {
                "isolates": [sorted(g, key=repr) for g in part.isolated_sets()],
                "heals_at": part.end,
                "mode": self.partition_mode,
            }
        return out


@dataclass
class Node:
    """One simulated node: its algorithm instance and captured outputs."""

    id: Any
    algorithm: Any
    faulty: bool = False
    outputs: List[Any] = field(default_factory=list)
    faults_observed: List[Any] = field(default_factory=list)


class VirtualNet:
    """N protocol instances + a message queue + a crank loop."""

    #: attached traffic driver (hbbft_tpu/traffic/driver.py registers
    #: itself here so why_stalled can name a starved/saturated source).
    #: Environment, not state: whole-net snapshots drop it (the driver
    #: holds live callables) and restore falls back to None.
    traffic = None
    #: schedule-explorer hooks (analysis/schedules.py) — environment, not
    #: state.  With ``scheduler="controlled"``, ``crank_chooser(net)``
    #: picks the queue index to deliver next; ``race_probe`` (a
    #: RaceTracker) records crank events with causal enqueue edges.
    crank_chooser = None
    race_probe = None
    #: critical-path recorder (obs/critpath.CritPathRecorder) and
    #: per-epoch series (obs/timeseries.MetricsLog) — environment, not
    #: state: both hold open-ended evidence rings the harness owns, so
    #: whole-net snapshots drop them and restore falls back to None.
    critpath = None
    metrics_log = None
    #: structured per-crank event sink and span tracer (hbbft_tpu/obs) —
    #: environment, not state: both are observer planes holding open-ended
    #: buffers (and the tracer holds live hook callables), so a whole-net
    #: snapshot taken with either attached must drop them rather than die
    #: in the encoder; restore falls back to None (headless net).
    event_log = None
    tracer = None
    _SNAPSHOT_ENV_ATTRS = (
        "traffic",
        "crank_chooser",
        "race_probe",
        "critpath",
        "metrics_log",
        "event_log",
        "tracer",
    )
    #: class fallback so pre-crash-axis whole-net snapshots restore
    #: (decode sets only serialized attrs); instances always assign it
    crash = None

    def __init__(
        self,
        nodes: Dict[Any, Node],
        backend: CryptoBackend,
        adversary: Adversary,
        rng: random.Random,
        message_limit: Optional[int] = None,
        crank_limit: Optional[int] = None,
        defer_mode: str = "eager",
        scheduler: str = "random",
        event_log: Optional["EventLog"] = None,
        tracer: Optional[Tracer] = None,
        schedule: Optional[NetSchedule] = None,
        scenario_name: Optional[str] = None,
        crash_schedule: Optional[CrashSchedule] = None,
    ) -> None:
        self.nodes = nodes
        self.backend = backend
        self.adversary = adversary
        self.rng = rng
        self.queue: List[NetMessage] = []
        self.message_limit = message_limit
        self.crank_limit = crank_limit
        self.defer_mode = defer_mode
        self.scheduler = scheduler
        #: optional network-condition layer (latency/jitter/drop/partition);
        #: None keeps the legacy instant-delivery behavior byte-identical
        self.schedule = schedule
        #: scenario label (net/scenarios.py) surfaced by why_stalled
        self.scenario_name = scenario_name
        #: optional crash/restart axis (net/crash.py); None keeps every
        #: code path byte-identical to the crash-free runtime
        if crash_schedule is not None and defer_mode != "eager":
            # the WAL replay model re-derives the crash-time state by
            # re-handling logged events with eager crypto resolution; the
            # round barrier resolves work BETWEEN cranks against shared
            # net state the WAL cannot capture, so a restart under
            # defer_mode="round" would always read as replay divergence
            raise ValueError(
                "crash schedules require defer_mode='eager' (the WAL "
                "replay cannot reproduce the round-barrier resolution)"
            )
        self.crash = (
            CrashManager(crash_schedule) if crash_schedule is not None else None
        )
        #: virtual clock in cranks; advances 1 per delivery and
        #: fast-forwards when every pending message is future-dated
        self.now = 0
        #: future-dated messages as a (not_before, seq, msg) min-heap;
        #: ``queue`` only ever holds deliverable-now messages, so the
        #: scheduler pick stays O(1) with or without a schedule
        self._future: List[Tuple[int, int, NetMessage]] = []
        self._future_seq = 0
        self.messages_delivered = 0
        self.dropped_messages = 0
        self.cranks = 0
        self._sorted_ids = sorted(nodes)
        self._node_order = {n: i for i, n in enumerate(self._sorted_ids)}
        self._pending_work: List[CryptoWork] = []
        #: net-side operative metrics; crypto-side live on backend.counters
        self.counters = Counters()
        #: opt-in structured per-crank trace (SURVEY.md §5 port note)
        self.event_log = event_log
        #: opt-in span tracer + histogram registry (hbbft_tpu/obs).  When
        #: attached, every crank records its handle_message latency and
        #: the pre-pop queue depth; per-crank SPANS additionally require
        #: ``tracer.crank_spans`` (a span per delivered message is only
        #: worth it on small runs).  Same zero-cost-when-None discipline
        #: as the event log.
        self.tracer = tracer

    def metrics(self) -> Dict[str, int]:
        """Combined net + crypto counters (one dict, SURVEY.md §5).

        cranks/messages_delivered mirror the authoritative limit-check
        attributes (single source of truth; the Counters copies are synced
        here, not incremented separately)."""
        self.counters.cranks = self.cranks
        self.counters.messages_delivered = self.messages_delivered
        return self.counters.merged_with(self.backend.counters)

    # -- introspection -------------------------------------------------------

    def node_order_key(self, node_id) -> int:
        return self._node_order.get(node_id, len(self._node_order))

    def correct_nodes(self) -> List[Node]:
        return [n for n in self.nodes.values() if not n.faulty]

    def faulty_nodes(self) -> List[Node]:
        return [n for n in self.nodes.values() if n.faulty]

    def node(self, node_id) -> Node:
        return self.nodes[node_id]

    def down_node_ids(self) -> frozenset:
        """Nodes currently dead under the crash axis (empty without one)."""
        return self.crash.down_ids() if self.crash is not None else frozenset()

    # -- input ---------------------------------------------------------------

    def send_input(self, node_id, input: Any) -> Step:
        if self.crash is not None and self.crash.on_input(self, node_id, input):
            return Step()  # node is down: input parked until restart
        node = self.nodes[node_id]
        step = node.algorithm.handle_input(input, rng=self.rng)
        self._process_step(node, step)
        return step

    def broadcast_input(self, input: Any) -> None:
        for node_id in sorted(self.nodes):
            self.send_input(node_id, input)

    # -- cranking ------------------------------------------------------------

    def _crank_error(self, message: str) -> CrankError:
        """A CrankError carrying the why-stalled diagnosis: the starved
        instances plus the active adversary/scenario and schedule state,
        so a tripped limit names its culprit instead of a bare number."""
        from hbbft_tpu.obs.health import render_why_stalled, why_stalled

        try:
            report = why_stalled(self)
            rendered = render_why_stalled(report)
        except Exception as e:  # diagnosis must never mask the trip
            report = {"error": repr(e)}
            rendered = f"why-stalled report unavailable: {e!r}"
        return CrankError(f"{message}\n{rendered}", report=report)

    def _release_due(self) -> None:
        """Move future-dated messages whose time has come into the live
        queue (time-then-insertion order: deterministic)."""
        fut = self._future
        while fut and fut[0][0] <= self.now:
            self.queue.append(heapq.heappop(fut)[2])

    def crank(self) -> Optional[Tuple[Any, Step]]:
        """Deliver one message.  Returns (recipient, step) or None if idle."""
        self._release_due()
        if self.crash is not None:
            self.crash.on_crank(self)
        self.adversary.pre_crank(self)
        if not self.queue:
            self._flush_work()
            self._release_due()
            if not self.queue and self._future:
                # everything pending is future-dated: fast-forward the
                # virtual clock to the earliest delivery time (latency
                # never burns cranks; real time IS the crank count)
                self.now = self._future[0][0]
                self._release_due()
            if not self.queue and self.crash is not None:
                # pending crash-axis events (a tick-gated crash/restart,
                # or an epoch-gated restart the drained net starved out)
                # are fast-forwarded like future-dated messages
                if self.crash.on_idle(self):
                    self._release_due()
            if not self.queue:
                return None
        self.cranks += 1
        self.now += 1
        if self.critpath is not None:
            self.critpath.tick(self.cranks, self.now)
        if self.crank_limit is not None and self.cranks > self.crank_limit:
            raise self._crank_error(f"crank limit {self.crank_limit} exceeded")

        scheduler = self.adversary.scheduler_override or self.scheduler
        if scheduler == "controlled" and self.crank_chooser is not None:
            idx = self.crank_chooser(self)
        elif scheduler == "random":
            idx = self.rng.randrange(len(self.queue))
        else:
            idx = 0
        msg = self.queue.pop(idx)
        node = self.nodes.get(msg.to)
        if node is None:
            # Addressed to a node this net doesn't model (e.g. departed
            # validator): count it so lost traffic is diagnosable.
            self.dropped_messages += 1
            return msg.to, Step()
        self.messages_delivered += 1
        if self.message_limit is not None and self.messages_delivered > self.message_limit:
            raise self._crank_error(
                f"message limit {self.message_limit} exceeded"
            )
        if self.crash is not None:
            self.crash.on_deliver(self, msg)
        probe = self.race_probe
        if probe is not None:
            probe.begin_crank(msg)
        tr = self.tracer
        if tr is None:
            step = node.algorithm.handle_message(msg.sender, msg.payload, rng=self.rng)
        else:
            tr.hist("net_queue_depth").record(len(self.queue) + 1)
            t0 = time.perf_counter()
            step = node.algorithm.handle_message(msg.sender, msg.payload, rng=self.rng)
            t1 = time.perf_counter()
            tr.hist("crank_latency_us").record((t1 - t0) * 1e6)
            if tr.crank_spans:
                tr.complete(
                    f"crank:{type(msg.payload).__name__}", t0, t1,
                    cat="crank", track="crank", to=repr(msg.to),
                )
        if self.event_log is not None:
            self.event_log.emit(
                event="crank",
                crank=self.cranks,
                sender=msg.sender,
                to=msg.to,
                msg_type=type(msg.payload).__name__,
                outputs=len(step.output),
                messages_out=len(step.messages),
                faults=len(step.fault_log),
                deferred=len(step.work),
            )
        self._process_step(node, step)
        if probe is not None:
            probe.end_crank()
        if self.crash is not None:
            self.crash.after_crank(self)
        return msg.to, step

    def crank_round(self) -> int:
        """Deliver every currently-queued message, then resolve all deferred
        crypto in one batched backend call (the TPU round barrier).  Returns
        number of messages delivered."""
        n = len(self.queue)
        delivered = 0
        for _ in range(n):
            if not self.queue:
                break
            if self.crank() is not None:
                delivered += 1
        self._flush_work()
        return delivered

    def crank_until(
        self,
        pred: Callable[["VirtualNet"], bool],
        max_cranks: int = 100_000,
    ) -> None:
        """Crank until ``pred(net)`` or quiescence; CrankError on starvation."""
        for _ in range(max_cranks):
            if pred(self):
                return
            if self.crank() is None:
                self._flush_work()
                if not self.queue and not self._future:
                    if pred(self):
                        return
                    raise self._crank_error(
                        "network quiesced before predicate held"
                    )
        raise self._crank_error(f"predicate not reached in {max_cranks} cranks")

    def crank_to_quiescence(self, max_cranks: int = 1_000_000) -> None:
        for _ in range(max_cranks):
            if self.crank() is None:
                self._flush_work()
                if not self.queue and not self._future:
                    return
        raise self._crank_error(f"not quiescent after {max_cranks} cranks")

    # -- step processing -----------------------------------------------------

    def _process_step(self, node: Node, step: Step) -> None:
        node.outputs.extend(step.output)
        node.faults_observed.extend(step.fault_log)
        if step.fault_log.entries:
            self.counters.faults_recorded += len(step.fault_log.entries)
            if self.event_log is not None:
                for f in step.fault_log.entries:
                    # lint: allow[replay-purity] observer plane: emit is
                    # guarded and records evidence only — events never
                    # feed protocol state, and a restored net replays
                    # headless (event_log falls back to None)
                    self.event_log.emit(
                        event="fault", observer=node.id, node=f.node_id, kind=f.kind
                    )
        for work in step.work:
            if work.owner is None:
                work.owner = node.id
            if self.defer_mode == "eager":
                self._resolve_work([work])
            else:
                self._pending_work.append(work)
        for tm in step.messages:
            self._route(node, tm)

    def _route(self, node: Node, tm: TargetedMessage) -> None:
        recipients = tm.target.recipients(self._sorted_ids, our_id=node.id)
        for to in recipients:
            msg = NetMessage(node.id, to, tm.message)
            if self.crash is not None and self.crash.on_send(self, node, msg):
                continue  # replayed emission already delivered pre-crash
            if node.faulty:
                for m in self.adversary.tamper(self, msg):
                    self._enqueue(m)
            else:
                self._enqueue(msg)

    def _enqueue(self, msg: NetMessage) -> None:
        """Queue one message through the schedule layer (latency/jitter/
        drop/partition); adversary and schedule compose — tampered
        traffic is scheduled exactly like honest traffic.  Future-dated
        messages park on the time-ordered heap and enter ``queue`` only
        once deliverable."""
        if self.crash is not None and self.crash.on_enqueue(self, msg):
            return  # recipient is down: parked until its restart
        if self.race_probe is not None:
            # stable content key + causal edge to the enqueuing crank
            # lint: allow[replay-purity] explorer probe: tags carry
            # observer-only metadata (never read by protocol code), and a
            # restored net replays unprobed (race_probe falls back to None)
            self.race_probe.tag_message(msg)
        if self.schedule is not None:
            delay = self.schedule.on_send(self, msg)
            if delay is None:
                self.counters.schedule_dropped += 1
                return
            if delay > 0:
                msg.not_before = self.now + delay
                self.counters.schedule_delayed += 1
                self._future_seq += 1
                heapq.heappush(
                    self._future, (msg.not_before, self._future_seq, msg)
                )
                return
        self.queue.append(msg)

    # -- deferred crypto -----------------------------------------------------

    def _flush_work(self) -> None:
        while self._pending_work:
            batch, self._pending_work = self._pending_work, []
            self._resolve_work(batch)

    def _resolve_work(self, batch: Sequence[CryptoWork]) -> None:
        """Group work items by kind, hit the backend once per kind, re-enter
        the protocol callbacks, and process any follow-up steps."""
        by_kind: Dict[str, List[CryptoWork]] = defaultdict(list)
        for w in batch:
            by_kind[w.kind].append(w)
        follow_ups: List[Tuple[CryptoWork, Any]] = []
        for kind, items in by_kind.items():
            if kind == "verify_sig_share":
                results = self.backend.verify_sig_shares([w.payload for w in items])
            elif kind == "verify_dec_share":
                results = self.backend.verify_dec_shares([w.payload for w in items])
            elif kind == "verify_signature":
                results = self.backend.verify_signatures([w.payload for w in items])
            elif kind == "verify_ciphertext":
                results = self.backend.verify_ciphertexts([w.payload for w in items])
            else:
                raise CrankError(f"unknown crypto work kind {kind!r}")
            follow_ups.extend(zip(items, results))
        for work, result in follow_ups:
            follow_step = work.on_result(result)
            if follow_step:
                owner = self.nodes.get(work.owner)
                if owner is None:
                    raise CrankError("crypto work item has no owner node")
                self._process_step(owner, follow_step)


class NetBuilder:
    """Fluent builder mirroring the reference `NetBuilder` §.

    Example::

        net = (NetBuilder(range(4))
               .num_faulty(1)
               .backend(MockBackend())
               .using(lambda netinfo, b: ThresholdSign(netinfo, b, doc=b"x"))
               .build(seed=7))
    """

    def __init__(self, node_ids: Sequence[Any]) -> None:
        self._ids = sorted(node_ids)
        self._num_faulty = 0
        self._adversary: Adversary = NullAdversary()
        self._backend: Optional[CryptoBackend] = None
        self._message_limit: Optional[int] = None
        self._crank_limit: Optional[int] = None
        self._defer_mode = "eager"
        self._scheduler = "random"
        self._schedule: Optional[NetSchedule] = None
        self._crash_schedule: Optional[CrashSchedule] = None
        self._scenario_name: Optional[str] = None
        self._event_log: Optional[EventLog] = None
        self._tracer: Optional[Tracer] = None
        self._constructor: Optional[Callable[[NetworkInfo, CryptoBackend], Any]] = None

    def num_faulty(self, f: int) -> "NetBuilder":
        if len(self._ids) <= 3 * f and f > 0:
            raise ValueError(f"N={len(self._ids)} cannot tolerate f={f} (need N>3f)")
        self._num_faulty = f
        return self

    def adversary(self, adv: Adversary) -> "NetBuilder":
        self._adversary = adv
        return self

    def backend(self, backend: CryptoBackend) -> "NetBuilder":
        self._backend = backend
        return self

    def message_limit(self, limit: int) -> "NetBuilder":
        self._message_limit = limit
        return self

    def crank_limit(self, limit: int) -> "NetBuilder":
        self._crank_limit = limit
        return self

    def defer_mode(self, mode: str) -> "NetBuilder":
        assert mode in ("eager", "round")
        self._defer_mode = mode
        return self

    def scheduler(self, mode: str) -> "NetBuilder":
        assert mode in ("random", "first", "controlled")
        self._scheduler = mode
        return self

    def schedule(self, sched: Optional[NetSchedule]) -> "NetBuilder":
        """Attach a network-condition schedule (latency/jitter/drop/
        partition-and-heal); None keeps instant delivery."""
        self._schedule = sched
        return self

    def crashes(self, sched: Optional[CrashSchedule]) -> "NetBuilder":
        """Attach a crash/restart schedule (net/crash.py); None keeps the
        crash-free runtime byte-identical."""
        self._crash_schedule = sched
        return self

    def scenario(self, name: str) -> "NetBuilder":
        """Label the run for fault diagnosis: why_stalled and CrankError
        reports name this scenario."""
        self._scenario_name = name
        return self

    def trace(self, sink: Union[EventLog, Tracer]) -> "NetBuilder":
        """Attach an opt-in observability sink: an :class:`EventLog`
        (structured per-crank events) or an :class:`~hbbft_tpu.obs.tracer
        .Tracer` (spans + histograms; also attached to the backend so
        dispatch spans land on the same timeline).  Call twice to attach
        both."""
        if isinstance(sink, Tracer):
            self._tracer = sink
        else:
            self._event_log = sink
        return self

    def using(
        self, constructor: Callable[[NetworkInfo, CryptoBackend], Any]
    ) -> "NetBuilder":
        """``constructor(netinfo, backend[, rng]) -> protocol instance`` per
        node.  Constructors that accept a third argument receive the net's
        seeded rng (needed by protocols that generate key material, e.g.
        DynamicHoneyBadger's in-band DKG)."""
        # lint: allow[hook-detachment] the builder is pre-run configuration:
        # no live net references it, so it can never appear in a snapshot
        self._constructor = constructor
        return self

    def build(self, seed: int = 0) -> VirtualNet:
        if self._constructor is None:
            raise ValueError("NetBuilder.using(...) not set")
        rng = random.Random(seed)
        backend = self._backend or MockBackend()
        if self._tracer is not None:
            backend.tracer = self._tracer
        netinfos = NetworkInfo.generate_map(self._ids, rng, backend)
        faulty_ids = set(rng.sample(self._ids, self._num_faulty))

        import inspect

        try:
            n_params = len(inspect.signature(self._constructor).parameters)
        except (TypeError, ValueError):
            n_params = 2

        def construct(nid):
            if n_params >= 3:
                return self._constructor(netinfos[nid], backend, rng)
            return self._constructor(netinfos[nid], backend)

        nodes = {
            nid: Node(
                id=nid,
                algorithm=construct(nid),
                faulty=nid in faulty_ids,
            )
            for nid in self._ids
        }
        return VirtualNet(
            nodes=nodes,
            backend=backend,
            adversary=self._adversary,
            rng=rng,
            message_limit=self._message_limit,
            crank_limit=self._crank_limit,
            defer_mode=self._defer_mode,
            scheduler=self._scheduler,
            event_log=self._event_log,
            tracer=self._tracer,
            schedule=self._schedule,
            scenario_name=self._scenario_name,
            crash_schedule=self._crash_schedule,
        )
