"""Log-bucketed histograms with percentile summaries.

HDR-style base-2 buckets with ``SUBBUCKETS`` linear sub-buckets per
octave: relative quantile error is bounded by ``1/SUBBUCKETS`` (~6% at
16), while ``record`` stays O(1) with a small dict — soak runs record
millions of per-crank latencies without keeping raw samples.  Exact
count/sum/min/max ride alongside, so means and extremes are not subject
to bucketing error.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

#: linear sub-buckets per power-of-two octave (quantile error ≤ 1/16).
SUBBUCKETS = 16


class Histogram:
    """Distribution of nonnegative values (latencies, batch sizes, depths)."""

    __slots__ = ("name", "counts", "count", "total", "min", "max")

    def __init__(self, name: str = "") -> None:
        self.name = name
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    # -- recording -----------------------------------------------------------

    @staticmethod
    def _bucket(value: float) -> int:
        if value < 1.0:
            # sub-unit values share one linear octave [0, 1)
            return int(value * SUBBUCKETS) - SUBBUCKETS
        e = int(math.log2(value))
        # float log2 can land one octave off at exact powers; clamp.
        if value < (1 << e):
            e -= 1
        elif value >= (1 << (e + 1)):
            e += 1
        sub = int((value / (1 << e) - 1.0) * SUBBUCKETS)
        return e * SUBBUCKETS + min(sub, SUBBUCKETS - 1)

    @staticmethod
    def _bucket_value(bucket: int) -> float:
        """Representative (geometric-midpoint) value of a bucket."""
        if bucket < 0:
            return (bucket + SUBBUCKETS + 0.5) / SUBBUCKETS
        e, sub = divmod(bucket, SUBBUCKETS)
        lo = (1 << e) * (1.0 + sub / SUBBUCKETS)
        return lo * (1.0 + 0.5 / SUBBUCKETS)

    def record(self, value: float) -> None:
        if value < 0:
            value = 0.0
        b = self._bucket(value)
        self.counts[b] = self.counts.get(b, 0) + 1
        self.count += 1
        self.total += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value

    # -- summaries -----------------------------------------------------------

    def percentile(self, p: float) -> float:
        """Approximate p-th percentile (p in [0, 100])."""
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(self.count * p / 100.0))
        seen = 0
        for b in sorted(self.counts):
            seen += self.counts[b]
            if seen >= rank:
                # clamp to the exact extremes so p0/p100 are honest
                v = self._bucket_value(b)
                return min(max(v, self.min), self.max)
        return self.max  # pragma: no cover — rank <= count always hits

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def summary(self, round_to: int = 3) -> Dict[str, float]:
        """Compact summary for bench rows / heartbeats (empty → count 0)."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "mean": round(self.mean, round_to),
            "min": round(self.min, round_to),
            "max": round(self.max, round_to),
            "p50": round(self.percentile(50), round_to),
            "p90": round(self.percentile(90), round_to),
            "p99": round(self.percentile(99), round_to),
        }

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"Histogram({self.name!r}, {self.summary()})"
