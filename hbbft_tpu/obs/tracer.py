"""Tracer — hierarchical protocol/device spans with Perfetto export.

A span marks one interval of work (an epoch, one ACS, one BA instance,
one coin round, one batched device dispatch) on a named **track**.
Tracks map to Chrome-trace ``tid``\\ s, so spans on one track must nest
(begin/end as a stack) while spans on different tracks overlap freely —
which is exactly the lockstep engine's shape: all N BA-instance spans run
concurrently, each on its own ``ba/<idx>`` track, under one ``subset``
span on the main track.  The pipelined dispatch seam (ops/pipeline.py)
uses the same mechanism for overlapping device intervals: synchronous
dispatches span the ``device`` track, while each in-flight slot of the
deferred-fetch queue spans its own ``device/<slot>`` track — a slot is
reused only after its previous span's fetch completed, so per-track
nesting holds even though dispatch+fetch intervals overlap in wall time.

Export targets:

* :meth:`write_chrome` — Chrome trace-event JSON (``{"traceEvents":
  [...]}``), loadable in Perfetto (https://ui.perfetto.dev) or
  ``chrome://tracing``.  Spans are matched ``B``/``E`` pairs with
  microsecond ``ts``; span categories reuse the ``device_seconds_*``
  kind labels (pairing, rlc_sig, combine, sign, decrypt, ...).
* :meth:`write_jsonl` — one raw event per line for offline tooling
  (``tools/trace_report.py``).

The tracer also owns a registry of log-bucketed
:class:`~hbbft_tpu.obs.histogram.Histogram`\\ s (per-crank latency,
dispatch batch sizes, RLC group sizes, queue depths) so one object
threads through runtime, engine, and backend.

Zero-cost when absent: every instrumentation site guards with
``if tracer is not None`` (the EventLog discipline).  ``Tracer(spans=
False)`` keeps the histogram registry live but makes span emission a
no-op — bench rows collect distributions without paying event-list
growth on million-dispatch runs.
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from hbbft_tpu.obs.histogram import Histogram


class Tracer:
    """Collects span events + histograms; exports Chrome trace / JSONL."""

    def __init__(
        self,
        spans: bool = True,
        capacity: int = 2_000_000,
        clock=time.perf_counter,
    ) -> None:
        self.spans_enabled = spans
        self.capacity = capacity
        self.clock = clock
        self._t0 = clock()
        self.events: List[Dict[str, Any]] = []
        self.dropped = 0
        self.histograms: Dict[str, Histogram] = {}
        self.pid = os.getpid()
        self._tids: Dict[str, int] = {}
        self._stacks: Dict[int, List[str]] = {}
        #: opt-in per-crank spans in VirtualNet (histograms are always on;
        #: a span per delivered message is only worth it on small runs)
        self.crank_spans = False

    # -- clock/track plumbing ------------------------------------------------

    def _now_us(self) -> float:
        return (self.clock() - self._t0) * 1e6

    def _tid(self, track: str) -> int:
        tid = self._tids.get(track)
        if tid is None:
            tid = len(self._tids)
            self._tids[track] = tid
        return tid

    # -- spans ---------------------------------------------------------------
    #
    # Capacity is enforced in WHOLE-SPAN units: a B whose E could not be
    # recorded would leave an unclosed span that fails the trace-event
    # validator, so begin() drops the B at capacity (remembering that on
    # the stack) and end() closes only what was actually opened —
    # overshooting capacity by at most the spans already open when the
    # limit was hit.  complete() emits its B/E pair atomically or drops
    # both.

    def begin(self, name: str, cat: str = "", track: str = "main", **args: Any) -> None:
        """Open a span on ``track`` (close with :meth:`end` on the same track)."""
        if not self.spans_enabled:
            return
        tid = self._tid(track)
        emitted = len(self.events) < self.capacity
        self._stacks.setdefault(tid, []).append((name, emitted))
        if not emitted:
            self.dropped += 1
            return
        ev: Dict[str, Any] = {
            "name": name,
            "cat": cat or "span",
            "ph": "B",
            "ts": self._now_us(),
            "pid": self.pid,
            "tid": tid,
        }
        if args:
            ev["args"] = args
        self.events.append(ev)

    def end(self, track: str = "main", **args: Any) -> None:
        """Close the innermost open span on ``track``."""
        if not self.spans_enabled:
            return
        tid = self._tid(track)
        stack = self._stacks.get(tid)
        if not stack:
            raise ValueError(f"Tracer.end on track {track!r} with no open span")
        name, emitted = stack.pop()
        if not emitted:  # its B was dropped at capacity: drop the E too
            self.dropped += 1
            return
        ev: Dict[str, Any] = {
            "name": name,
            "cat": "span",
            "ph": "E",
            "ts": self._now_us(),
            "pid": self.pid,
            "tid": tid,
        }
        if args:
            ev["args"] = args
        self.events.append(ev)

    @contextmanager
    def span(self, name: str, cat: str = "", track: str = "main", **args: Any):
        self.begin(name, cat=cat, track=track, **args)
        try:
            yield self
        finally:
            self.end(track=track)

    def complete(
        self,
        name: str,
        t0: float,
        t1: float,
        cat: str = "",
        track: str = "main",
        **args: Any,
    ) -> None:
        """Record a finished span retroactively from two ``clock()`` stamps.

        Used where the caller already timed the interval (the backend's
        dispatch+fetch seam bills the identical ``t1 - t0`` to
        ``counters.device_seconds``, so traced device time and counter
        attribution agree exactly)."""
        if not self.spans_enabled:
            return
        tid = self._tid(track)
        if self._stacks.get(tid):
            raise ValueError(
                f"Tracer.complete on track {track!r} inside an open span"
            )
        if len(self.events) + 2 > self.capacity:  # whole pair or nothing
            self.dropped += 2
            return
        base = {"name": name, "cat": cat or "span", "pid": self.pid, "tid": tid}
        b = dict(base, ph="B", ts=(t0 - self._t0) * 1e6)
        if args:
            b["args"] = args
        self.events.append(b)
        self.events.append(dict(base, ph="E", ts=(t1 - self._t0) * 1e6))

    def open_spans(self) -> int:
        return sum(len(s) for s in self._stacks.values())

    # -- histograms ----------------------------------------------------------

    def hist(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(name)
        return h

    def hist_summary(self) -> Dict[str, Dict[str, float]]:
        """All non-empty histogram summaries (bench-row / heartbeat shape)."""
        return {
            name: h.summary()
            for name, h in sorted(self.histograms.items())
            if h.count
        }

    # -- export --------------------------------------------------------------

    def chrome_events(self) -> List[Dict[str, Any]]:
        """Events in Chrome trace-event form: ts-sorted B/E spans plus
        thread-name metadata so Perfetto labels the tracks."""
        meta: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": "hbbft_tpu"},
            }
        ]
        for track, tid in self._tids.items():
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self.pid,
                    "tid": tid,
                    "ts": 0,
                    "args": {"name": track},
                }
            )
        # Stable sort: retroactive `complete` spans interleave with live
        # begin/end stamps; Perfetto requires neither order nor nesting
        # across tids, but monotonic ts makes the file diffable/validatable.
        body = sorted(self.events, key=lambda e: e["ts"])
        return meta + body

    @staticmethod
    def _ensure_parent(path: str) -> None:
        # a missing artifacts/ dir must not discard a multi-hour run's
        # trace at the very last write
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)

    def write_chrome(self, path: str) -> None:
        if self.open_spans():
            raise ValueError(
                f"{self.open_spans()} span(s) still open — end them before export"
            )
        self._ensure_parent(path)
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "dropped_events": self.dropped,
                "histograms": self.hist_summary(),
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f)

    def write_jsonl(self, path: str) -> None:
        """One event per line, ts-sorted (retroactive ``complete`` spans
        interleave with live stamps in emission order; sorting gives the
        same monotonic-ts guarantee the Chrome export has)."""
        self._ensure_parent(path)
        with open(path, "w") as f:
            for ev in sorted(self.events, key=lambda e: e["ts"]):
                f.write(json.dumps(ev, default=repr) + "\n")

    def write(self, path: str) -> None:
        """Chrome trace for ``*.json``, raw JSONL for ``*.jsonl``."""
        if path.endswith(".jsonl"):
            self.write_jsonl(path)
        else:
            self.write_chrome(path)

    def __len__(self) -> int:
        return len(self.events)
